"""Regression fixture: the PR 14 journal/WAL hazard, committed so the
interprocedural GFL004 pass can never silently lose the shape that
motivated it (tests/test_gofrlint.py asserts this file IS flagged).

The hazard: ``Journal.record`` holds the per-journal lock while calling
``self._wal.append_tokens`` — a method on a DIFFERENT object whose body
reaches ``os.fsync`` two hops down. No single function both holds the
lock and blocks, so the per-file rule is structurally blind to it; the
whole-program pass resolves ``self._wal`` to :class:`WalWriter` from
the ``__init__`` assignment and carries may-block through the chain.

(The fsync inside :class:`WalWriter` under WalWriter's OWN lock is the
resource-guard shape the analysis deliberately exempts — the finding
must land on the cross-object reach-through in ``Journal.record``.)

This file is a lint fixture, not production code: it lives outside the
tree gate's paths (gofr_tpu/, tools/, bench.py) and is linted only by
its own test.
"""

import os
import threading


class WalWriter:
    """Minimal segmented-WAL stand-in: append then durability barrier."""

    def __init__(self, path):
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT, 0o600)
        self._lock = threading.Lock()

    def append_tokens(self, payload):
        with self._lock:
            self._write(payload)
            self._sync()

    def _write(self, payload):
        os.write(self._fd, payload)

    def _sync(self):
        os.fsync(self._fd)


class Journal:
    """Minimal generation-journal stand-in with the hazardous shape."""

    def __init__(self, path):
        self._lock = threading.Lock()
        self._entries = {}
        self._wal = WalWriter(path)

    def record(self, request_id, payload):
        with self._lock:
            self._entries[request_id] = payload
            # HAZARD (intentional): a device-speed durability barrier
            # runs while every other journal operation is locked out
            self._wal.append_tokens(payload)
