"""Process death as a routine event, tier-1: the supervisor
(restart-on-exit, bounded backoff, crash-loop verdict), the prober's
``restarting`` passage for connection-refused-then-reborn replicas
(boot_id change → probation, counted), and THE acceptance e2e —
``kill -9`` a REAL subprocess replica mid-stream, the supervisor
respawns it, the respawned process rehydrates its journal WAL, and the
client's stream completes token-exact through the router with the
restart visible on metrics and ``/admin/fleet``.

These tests spawn real OS processes; CI runs this module in the serial
``fleet-chaos`` job.
"""

import json
import sys
import time
import urllib.request

from gofr_tpu.devtools.supervise import CRASH_LOOP, STOPPED, Supervisor

PY = sys.executable


def _get(url, headers=None, timeout=10):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers.items())


def _wait(cond, timeout=20.0, interval=0.02, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


def _read_sse_tokens(resp, initial: bytes = b"") -> tuple:
    """Drain one SSE response: returns (token_ids, event_ids, raw)."""
    raw = initial
    while True:
        chunk = resp.read(4096)
        if not chunk:
            break
        raw += chunk
    tokens: list = []
    ids: list = []
    for block in raw.split(b"\n\n"):
        event_id = None
        for line in block.split(b"\n"):
            if line.startswith(b"id:"):
                event_id = int(line[3:].strip())
            elif line.startswith(b"data:"):
                data = line[5:].strip()
                if data == b"[DONE]" or not data.startswith(b"{"):
                    continue
                frame = json.loads(data)
                if "error" in frame:
                    raise AssertionError(f"error frame reached client: {frame}")
                choice = frame["choices"][0]
                if choice.get("tokens"):
                    tokens.extend(choice["tokens"])
                    if event_id is not None:
                        ids.append(event_id)
    return tokens, ids, raw


# -- the supervisor ------------------------------------------------------------

def test_supervisor_restarts_after_kill():
    supervisor = Supervisor(
        [PY, "-c", "import time; time.sleep(60)"],
        backoff_s=0.05, backoff_max_s=0.2,
    ).start()
    try:
        assert supervisor.running
        first_pid = supervisor.pid
        assert supervisor.kill9() == first_pid
        _wait(lambda: supervisor.restarts == 1 and supervisor.running,
              message="respawn")
        assert supervisor.pid != first_pid
        assert supervisor.last_exit_code != 0  # SIGKILL is not clean
        assert supervisor.verdict is None  # still supervising
    finally:
        supervisor.stop()
    assert supervisor.verdict == STOPPED
    assert not supervisor.running


def test_supervisor_crash_loop_verdict_stops_respawning():
    supervisor = Supervisor(
        [PY, "-c", "raise SystemExit(3)"],
        backoff_s=0.01, backoff_max_s=0.02,
        crash_window_s=10.0, max_restarts_in_window=3,
    ).start()
    try:
        _wait(lambda: supervisor.verdict == CRASH_LOOP,
              message="crash-loop verdict")
        assert supervisor.last_exit_code == 3
        restarts_at_verdict = supervisor.restarts
        time.sleep(0.2)  # the verdict is terminal: no further respawns
        assert supervisor.restarts == restarts_at_verdict
        assert not supervisor.running
        snap = supervisor.snapshot()
        assert snap["verdict"] == CRASH_LOOP
    finally:
        supervisor.stop()


def test_supervisor_stop_racing_respawn_leaves_no_orphan():
    """Regression: stop() arriving while the monitor is mid-respawn
    must not leak the just-spawned child (the old code terminated the
    already-dead process and let the fresh one run forever)."""
    import os

    for _ in range(5):  # the race window is narrow: hammer it
        supervisor = Supervisor(
            [PY, "-c", "import time; time.sleep(60)"],
            backoff_s=0.01, backoff_max_s=0.02,
        ).start()
        supervisor.kill9()
        time.sleep(0.012)  # land stop() around the respawn
        supervisor.stop()
        assert not supervisor.running
        pid = supervisor.pid
        if pid is not None:
            try:
                os.kill(pid, 0)
                # the pid exists: it must be a zombie awaiting reap by
                # us (its parent), not a live orphan still sleeping
                with open(f"/proc/{pid}/stat") as f:
                    assert f.read().split()[2] == "Z"
            except OSError:
                pass  # fully gone: the desired outcome


def test_supervisor_clean_stop_terminates_child():
    supervisor = Supervisor(
        [PY, "-c", "import time; time.sleep(60)"], backoff_s=0.05,
    ).start()
    pid = supervisor.pid
    supervisor.stop()
    assert not supervisor.running
    assert supervisor.verdict == STOPPED
    assert pid is not None


# -- the restarting probation path (prober unit) -------------------------------

def test_reborn_boot_id_walks_probation_as_restarting():
    from gofr_tpu.fleet.replica import (
        HEALTHY,
        PROBATION,
        Replica,
        ReplicaSet,
    )
    from gofr_tpu.logging import Level
    from gofr_tpu.testutil import MockLogger

    replica = Replica("r0", "http://127.0.0.1:1", MockLogger(Level.FATAL))
    replica_set = ReplicaSet([replica], MockLogger(Level.FATAL),
                             out_after=2, probation_probes=2)
    restarts_seen = []
    replica_set._on_restart = lambda r: restarts_seen.append(r.name)

    # steady state: same boot id, stays healthy
    replica_set._apply_probe(replica, True, boot_id="boot-a")
    replica_set._apply_probe(replica, True, boot_id="boot-a")
    assert replica.state == HEALTHY and replica.restarts == 0

    # killed and respawned INSIDE one probe interval: no probe ever
    # failed, but the new process must still walk probation
    replica_set._apply_probe(replica, True, boot_id="boot-b")
    assert replica.state == PROBATION
    assert replica.restarting and replica.restarts == 1
    assert restarts_seen == ["r0"]
    # the reboot probe opened the streak (exactly like OUT->PROBATION);
    # one more OK probe completes the 2-probe window
    replica_set._apply_probe(replica, True, boot_id="boot-b")
    assert replica.state == HEALTHY and not replica.restarting

    # the usual shape: connection refused (probe fails) then reborn
    replica_set._apply_probe(replica, False)
    replica_set._apply_probe(replica, False)
    assert replica.state == "out"
    replica_set._apply_probe(replica, True, boot_id="boot-c")
    assert replica.state == PROBATION
    assert replica.restarts == 2 and replica.restarting
    snap = replica.snapshot()
    assert snap["restarts"] == 2 and snap["restarting"] is True
    assert snap["boot_id"] == "boot-c"

    # replicas that predate boot_id (None): detection stays off
    replica_set._apply_probe(replica, True, boot_id=None)
    replica_set._apply_probe(replica, True, boot_id=None)
    assert replica.restarts == 2


# -- THE acceptance e2e --------------------------------------------------------

def test_sigkill_mid_stream_resumes_token_exact_through_router(
        tmp_path, monkeypatch):
    """SIGKILL a subprocess replica mid-stream → the supervisor
    respawns it → the respawned process rehydrates its journal WAL →
    the router's stream relay resumes against the reborn replica — and
    the client sees one unbroken, token-exact stream. The restart is
    visible on gofr_tpu_router_replica_restarts_total and
    /admin/fleet; the rehydration on the replica's /admin/engine."""
    from gofr_tpu.devtools.chaos import chaos_router, subprocess_replica

    monkeypatch.chdir(tmp_path)
    prompt, n_tokens = [5, 6, 7], 40
    expected = [prompt[i % 3] for i in range(n_tokens)]  # echo's contract
    with subprocess_replica(
        name="sp0",
        env={
            "JOURNAL_DIR": str(tmp_path / "journal"),
            "ECHO_STEP_MS": "40",
        },
        backoff_s=0.2, backoff_max_s=0.5,
    ) as replica, chaos_router(
        [replica],
        env={"FLEET_PROBE_INTERVAL_S": "0.05", "FLEET_OUT_AFTER": "2",
             "FLEET_PROBATION_PROBES": "2", "FLEET_READ_TIMEOUT_S": "5",
             "FLEET_DEADLINE_S": "30", "FLEET_MAX_RESUMES": "8"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")

        req = urllib.request.Request(
            base + "/v1/completions",
            data=json.dumps({
                "model": "echo", "prompt": prompt, "max_tokens": n_tokens,
                "stream": True, "seed": 7,
            }).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        resp = urllib.request.urlopen(req, timeout=60)
        assert resp.status == 200
        first = resp.read(1)  # at least one byte of the stream arrived
        assert first
        time.sleep(0.2)  # a few tokens flow (and land in the WAL)

        killed_pid = replica.kill9()
        assert killed_pid is not None

        # the client keeps reading straight through process death,
        # supervisor respawn, WAL rehydration, and the relay's resume
        tokens, ids, raw = _read_sse_tokens(resp, initial=first)
        assert raw and b"data: [DONE]" in raw  # completed, not truncated
        assert tokens == expected  # ZERO missing, ZERO duplicated
        assert ids == sorted(set(ids))  # strictly monotonic event ids

        # a NEW process serves now, and its WAL rehydrated the stream
        assert replica.supervisor.restarts >= 1
        assert replica.pid != killed_pid
        _, body, _ = _get(replica.address + "/admin/engine")
        engine = json.loads(body)["data"]
        assert engine["journal"]["rehydrated"] >= 1
        assert engine["journal"]["wal"]["segments"] >= 1
        _, replica_metrics, _ = _get(replica.address + "/metrics")
        assert ('gofr_tpu_journal_resumes_total{mode="teacher_forced"}'
                in replica_metrics.decode())

        # the router observed the restart AND the resume
        _wait(lambda: fleet.replica_set.replicas[0].restarts >= 1,
              message="prober counts the restart")
        snap = fleet.snapshot()
        rep_snap = snap["replica_set"]["replicas"][0]
        assert rep_snap["restarts"] >= 1
        _, router_metrics, _ = _get(base + "/metrics")
        text = router_metrics.decode()
        assert "gofr_tpu_router_replica_restarts_total" in text
        assert ('gofr_tpu_router_stream_resumes_total{outcome="resumed"}'
                in text)


def test_sigkilled_replica_serves_x_resume_from_directly(
        tmp_path, monkeypatch):
    """The replica-side half without a router: kill a subprocess
    replica mid-stream, wait for the supervisor respawn, and ask the
    REBORN process for the rest via X-Resume-From — the WAL-rehydrated
    journal serves the continuation bit-identically."""
    from gofr_tpu.devtools.chaos import subprocess_replica

    monkeypatch.chdir(tmp_path)
    prompt, n_tokens = [11, 12, 13], 30
    expected = [prompt[i % 3] for i in range(n_tokens)]
    with subprocess_replica(
        name="sp1",
        env={
            "JOURNAL_DIR": str(tmp_path / "journal"),
            "ECHO_STEP_MS": "40",
        },
        backoff_s=0.2, backoff_max_s=0.5,
    ) as replica:
        payload = json.dumps({
            "model": "echo", "prompt": prompt, "max_tokens": n_tokens,
            "stream": True, "seed": 3,
        }).encode()
        req = urllib.request.Request(
            replica.address + "/v1/completions", data=payload,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        resp = urllib.request.urlopen(req, timeout=30)
        # read a couple of complete events off the wire, then the
        # process dies under the client
        buf = b""
        while buf.count(b"\n\n") < 2:
            chunk = resp.read(1)
            assert chunk, "stream ended before two events"
            buf += chunk
        delivered, _, _ = _read_sse_partial(buf)
        replica.kill9()
        try:
            resp.read()
        except Exception:
            pass  # the kill severs the socket mid-body; expected
        replica.wait_ready(30)

        # the REBORN process continues from the delivered offset
        resume_req = urllib.request.Request(
            replica.address + "/v1/completions", data=payload,
            headers={"Content-Type": "application/json",
                     "X-Resume-From": str(len(delivered))},
            method="POST",
        )
        with urllib.request.urlopen(resume_req, timeout=30) as r2:
            rest, _, raw2 = _read_sse_tokens(r2)
        assert b"data: [DONE]" in raw2
        assert delivered + rest == expected
        _, body, _ = _get(replica.address + "/admin/engine")
        engine = json.loads(body)["data"]
        assert engine["journal"]["rehydrated"] >= 1


def _read_sse_partial(buf: bytes) -> tuple:
    """Tokens from the COMPLETE events inside a partial SSE buffer."""
    complete = buf.rsplit(b"\n\n", 1)[0] + b"\n\n"
    return _sse_blocks(complete)


def _sse_blocks(raw: bytes) -> tuple:
    tokens: list = []
    ids: list = []
    for block in raw.split(b"\n\n"):
        for line in block.split(b"\n"):
            if line.startswith(b"id:"):
                ids.append(int(line[3:].strip()))
            elif line.startswith(b"data:"):
                data = line[5:].strip()
                if data == b"[DONE]" or not data.startswith(b"{"):
                    continue
                frame = json.loads(data)
                choice = (frame.get("choices") or [{}])[0]
                if choice.get("tokens"):
                    tokens.extend(choice["tokens"])
    return tokens, ids, raw
