"""Container wiring tests.

Parity model: container_test.go:18-48 — config-driven wiring; invalid hosts
leave members None and the app degrades instead of dying (SURVEY.md §3.1)."""

from gofr_tpu.config import EnvConfig
from gofr_tpu.container import Container, new_container


def test_no_datasources_by_default(monkeypatch):
    for key in ("REDIS_HOST", "DB_NAME", "DB_HOST", "TPU_ENABLED", "MODEL_NAME"):
        monkeypatch.delenv(key, raising=False)
    c = new_container(EnvConfig())
    assert c.redis is None and c.db is None and c.tpu is None
    health = c.health()
    assert health["status"] == "UP"
    assert health["details"] == {}


def test_invalid_redis_host_degrades(monkeypatch):
    monkeypatch.setenv("REDIS_HOST", "256.0.0.1")
    monkeypatch.setenv("REDIS_PORT", "1")
    monkeypatch.delenv("DB_NAME", raising=False)
    monkeypatch.delenv("DB_HOST", raising=False)
    monkeypatch.delenv("TPU_ENABLED", raising=False)
    monkeypatch.delenv("MODEL_NAME", raising=False)
    c = Container(EnvConfig())  # must not raise
    assert c.redis is None


def test_get_http_service_nil_safe():
    c = Container(EnvConfig(), wire=False)
    assert c.get_http_service("missing") is None
    sentinel = object()
    c.services["x"] = sentinel
    assert c.get_http_service("x") is sentinel


def test_health_aggregates_down(monkeypatch):
    c = Container(EnvConfig(), wire=False)

    class FakeSource:
        def health_check(self):
            from gofr_tpu.datasource.health import DOWN, Health

            return Health(DOWN, {"err": "x"})

    c.redis = FakeSource()
    health = c.health()
    assert health["status"] == "DOWN"
    assert health["details"]["redis"]["status"] == "DOWN"
