"""Logging tests. Parity model: reference logger tests asserting leveled
output, sink split, and JSON structure via stdout/stderr capture."""

import json

from gofr_tpu.logging import Level, Logger, level_from_string, new_logger, new_silent_logger
from gofr_tpu.testutil import MockLogger, stderr_output_for, stdout_output_for


def test_level_from_string():
    assert level_from_string("DEBUG") == Level.DEBUG
    assert level_from_string("warn") == Level.WARN
    assert level_from_string("bogus") == Level.INFO
    assert level_from_string("") == Level.INFO


def test_level_filtering():
    logger = MockLogger(Level.WARN)
    logger.debug("nope")
    logger.info("nope")
    logger.warn("yes-warn")
    logger.error("yes-error")
    assert "nope" not in logger.output
    assert "yes-warn" in logger.output
    assert "yes-error" in logger.output


def test_stdout_stderr_split():
    logger = Logger(Level.DEBUG, terminal=False)
    out = stdout_output_for(lambda: (logger.info("to-stdout"), logger.error("to-stderr")))
    assert "to-stdout" in out
    assert "to-stderr" not in out
    err = stderr_output_for(lambda: (logger.info("to-stdout"), logger.error("to-stderr")))
    assert "to-stderr" in err
    assert "to-stdout" not in err


def test_json_entry_shape():
    logger = Logger(Level.DEBUG, terminal=False)
    out = stdout_output_for(lambda: logger.infof("hello %s", "world"))
    entry = json.loads(out)
    assert entry["level"] == "INFO"
    assert entry["message"] == "hello world"
    assert "time" in entry


def test_typed_log_entry():
    class FakeLog:
        def pretty_terminal(self):
            return "PRETTY"

        def log_fields(self):
            return {"method": "GET", "duration_us": 12}

    logger = Logger(Level.DEBUG, terminal=False)
    out = stdout_output_for(lambda: logger.info(FakeLog()))
    entry = json.loads(out)
    assert entry["message"] == {"method": "GET", "duration_us": 12}
    pretty = Logger(Level.DEBUG, terminal=True)
    out2 = stdout_output_for(lambda: pretty.info(FakeLog()))
    assert "PRETTY" in out2


def test_silent_logger():
    logger = new_silent_logger()
    out = stdout_output_for(lambda: logger.info("x"))
    err = stderr_output_for(lambda: logger.fatal("y"))
    assert out == "" and err == ""


def test_variadic_join():
    logger = MockLogger()
    logger.info("a", 1, True)
    assert "a 1 True" in logger.output


def test_new_logger_from_string():
    assert new_logger("ERROR").level == Level.ERROR
