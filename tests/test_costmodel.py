"""Dispatch cost model + residual watchtower (gofr_tpu/tpu/costmodel.py):
roofline prediction units, calibration provenance, residual EMA
accounting, the anomaly verdicts and their false-positive floor, the
AnomalyRing, the costcal fit/check tooling — plus the compile-free
end-to-end acceptance spine on the echo model: a healthy run serves
predicted_ms on every dispatch and ZERO anomalies; an injected stall
(below the watchdog threshold, so the engine never wedges) raises a
counted ``slow_dispatch`` anomaly visible on ``/admin/anomalies``,
``/metrics``, the rider's flight record, and a forced postmortem
bundle."""

import importlib.util
import json
import os
import pathlib
import socket
import time
import urllib.request

import pytest

from gofr_tpu.metrics import Registry
from gofr_tpu.tpu.costmodel import (
    ANOMALY_CAUSES,
    EMA_MIN_SAMPLES,
    AnomalyRing,
    CostModel,
    CostSheet,
)
from gofr_tpu.tpu.introspect import DispatchRecord, DispatchTimeline

REPO = pathlib.Path(__file__).resolve().parents[1]
_spec = importlib.util.spec_from_file_location(
    "costcal", REPO / "tools" / "costcal.py"
)
costcal = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(costcal)


def _model(**kw) -> CostModel:
    return CostModel(metrics=Registry(), **kw)


def _backdated(record: DispatchRecord, observed_ms: float) -> DispatchRecord:
    """Fabricate a dispatch duration by backdating ``t_running``:
    ``finish()`` is set-once on ``t_done``, so the only way to control
    the observed duration through the real timeline path is to move the
    start. The few microseconds between backdating and ``finish()`` are
    noise at the millisecond scales these tests assert with approx."""
    record.t_running = time.perf_counter() - observed_ms / 1e3
    return record


# -- prediction units ---------------------------------------------------------

def test_roofline_prediction_math():
    cm = _model()
    cm.eff_flops = 1e12   # 1 TFLOP/s effective
    cm.eff_bw = 1e11      # 100 GB/s effective
    cm.overhead_ms = 0.5
    cm.install(CostSheet("prefill", bucket=64, batch=8, flops=2e9,
                         bytes_accessed=1e6, source="hlo"))
    ms, source = cm.predict_ms("prefill", bucket=64, batch=8)
    # compute-bound: 2e9/1e12 s = 2ms >> 1e6/1e11 s = 0.01ms
    assert ms == pytest.approx(2.0 + 0.5)
    assert source == "hlo"
    # flip to bandwidth-bound
    cm.install(CostSheet("decode_chunk", bucket=0, batch=8, flops=1e6,
                         bytes_accessed=5e9, source="hlo"))
    ms, _ = cm.predict_ms("decode_chunk", bucket=0, batch=8)
    assert ms == pytest.approx(5e9 / 1e11 * 1e3 + 0.5)  # 50ms + overhead


def test_synthetic_sheet_and_unpriced_kinds():
    cm = _model()
    cm.overhead_ms = 0.2
    cm.install_synthetic("prefill", 5.0)
    ms, source = cm.predict_ms("prefill", bucket=64, batch=3)
    assert ms == pytest.approx(5.2) and source == "synthetic"
    # boot-time kinds have no steady-state cost truth — never priced,
    # even with a wildcard sheet installed for them
    cm.install_synthetic("warmup_compile", 5.0)
    assert cm.predict_ms("warmup_compile") == (None, None)
    assert cm.predict_ms("device_probe") == (None, None)
    # no sheet at all -> no prediction (never a made-up number)
    assert cm.predict_ms("decode_chunk", bucket=0, batch=1) == (None, None)


def test_sheet_lookup_fallback_chain():
    cm = _model()
    exact = CostSheet("prefill", bucket=64, batch=8, flops=1.0, source="hlo")
    cm.install(exact)
    # exact key wins
    assert cm.sheet_for("prefill", bucket=64, batch=8) is exact
    # same bucket, different batch: the compiled shape pads every batch
    # to the bucket's warm shape, so the bucket sheet is the cost truth
    assert cm.sheet_for("prefill", bucket=64, batch=3) is exact
    # different bucket, no sheet, no wildcard -> None
    assert cm.sheet_for("prefill", bucket=128, batch=3) is None
    cm.install_synthetic("prefill", 1.0)
    assert cm.sheet_for("prefill", bucket=128, batch=3).source == "synthetic"
    # hlo_* accessors never serve synthetic numbers
    assert cm.hlo_flops("prefill", bucket=64, batch=8) == 1.0
    assert cm.hlo_flops("prefill", bucket=128, batch=1) is None
    assert cm.hlo_bytes("prefill", bucket=64, batch=8) is None  # no bytes


def test_harvest_defensive_against_backend_quirks():
    cm = _model()

    class _Compiled:
        def cost_analysis(self):
            return [{"flops": 3e9, "bytes accessed": 2e6}]  # list form

        def memory_analysis(self):
            class _M:
                temp_size_in_bytes = 10
                argument_size_in_bytes = 20
                output_size_in_bytes = 30
            return _M()

    sheet = cm.harvest("prefill", 64, 8, _Compiled())
    assert sheet.flops == 3e9 and sheet.bytes_accessed == 2e6
    assert sheet.peak_memory_bytes == 60 and sheet.source == "hlo"

    class _Broken:
        def cost_analysis(self):
            raise RuntimeError("backend says no")

        def memory_analysis(self):
            raise RuntimeError("backend says no")

    assert cm.harvest("prefill", 128, 8, _Broken()) is None


def test_calibration_provenance_profile_vs_nominal(tmp_path):
    # the committed profile: cpu row matches the echo/tier-1 platform
    cm = _model()
    cm.calibrate("cpu", "cpu")
    assert cm.calibration["source"] == "profile"
    assert cm.calibration["matched"] == "cpu"
    assert cm.eff_flops and cm.eff_bw
    # unknown kind + missing profile: labeled nominal fallback, never a
    # silent zero or a boot failure
    cm2 = _model(profile_path=str(tmp_path / "missing.json"))
    cm2.calibrate("warp drive", "tpu")
    assert cm2.calibration["source"] == "nominal"
    assert cm2.eff_flops and cm2.eff_bw
    # corrupt profile degrades the same way
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    cm3 = _model(profile_path=str(bad))
    cm3.calibrate("v5e", "tpu")
    assert cm3.calibration["source"] == "nominal"


def test_ctor_validates_thresholds():
    for kw in ({"anomaly_factor": 1.0}, {"min_anomaly_ms": -1},
               {"ema_alpha": 0.0}, {"ema_alpha": 1.5}, {"ema_band": 1.0}):
        with pytest.raises(ValueError):
            CostModel(**kw)


# -- residual accounting + anomaly verdicts -----------------------------------

def test_residual_ratio_and_family_ema():
    registry = Registry()
    cm = CostModel(metrics=registry, ema_alpha=0.5)
    cm.overhead_ms = 0.0
    cm.install_synthetic("prefill", 10.0)
    timeline = DispatchTimeline(metrics=registry, costmodel=cm)
    drec = timeline.begin("prefill", bucket=64, batch_size=2)
    assert drec.predicted_ms == pytest.approx(10.0)
    timeline.finish(_backdated(drec, observed_ms=20.0))
    assert drec.residual_ratio == pytest.approx(2.0, rel=0.05)
    fam = cm.residuals()["prefill/64"]
    assert fam["ema"] == pytest.approx(2.0, rel=0.05) and fam["n"] == 1
    # second observation at 1x moves the EMA halfway (alpha 0.5)
    drec2 = timeline.begin("prefill", bucket=64, batch_size=2)
    timeline.finish(_backdated(drec2, observed_ms=10.0))
    assert cm.residuals()["prefill/64"]["ema"] == pytest.approx(1.5, rel=0.05)
    # the gauge tracks the family EMA
    gauge = registry.gauge(
        "gofr_tpu_dispatch_residual_ratio", labels=("kind", "bucket")
    )
    assert gauge.data()[("prefill", "64")] == pytest.approx(1.5, rel=0.05)
    # an errored dispatch never poisons the EMA
    drec3 = timeline.begin("prefill", bucket=64, batch_size=2)
    timeline.finish(_backdated(drec3, observed_ms=9999.0), status="error")
    assert cm.residuals()["prefill/64"]["n"] == 2


def test_slow_dispatch_needs_factor_and_absolute_floor():
    cm = _model(anomaly_factor=4.0, min_anomaly_ms=50.0)
    cm.overhead_ms = 0.0
    cm.install_synthetic("prefill", 0.01)
    timeline = DispatchTimeline(costmodel=cm)
    # 100x the prediction but only ~1ms of excess: a noisy-ratio
    # microsecond dispatch must NOT page anyone
    drec = timeline.begin("prefill", bucket=64)
    timeline.finish(_backdated(drec, observed_ms=1.0))
    assert drec.anomaly is None and cm.ring.total() == 0
    # both the factor and the floor breached -> slow_dispatch
    drec2 = timeline.begin("prefill", bucket=64)
    timeline.finish(_backdated(drec2, observed_ms=80.0))
    assert drec2.anomaly == "slow_dispatch"
    events = cm.ring.events()
    assert events[0]["cause"] == "slow_dispatch"
    assert events[0]["dispatch_id"] == drec2.dispatch_id
    assert events[0]["predicted_ms"] == pytest.approx(0.01)


def test_ema_drift_latches_once_per_excursion():
    cm = _model(anomaly_factor=1000.0, min_anomaly_ms=1.0,
                ema_alpha=0.5, ema_band=2.0)
    cm.overhead_ms = 0.0
    cm.install_synthetic("decode_chunk", 10.0)
    timeline = DispatchTimeline(costmodel=cm)

    def dispatch(observed_ms):
        drec = timeline.begin("decode_chunk", bucket=0)
        timeline.finish(_backdated(drec, observed_ms=observed_ms))
        return drec

    # drift every dispatch to 3x: the EMA crosses the band only after
    # EMA_MIN_SAMPLES, and the verdict fires ONCE (latched)
    for _ in range(EMA_MIN_SAMPLES + 4):
        dispatch(30.0)
    drift_events = cm.ring.events(cause="ema_drift")
    assert len(drift_events) == 1
    assert cm.residuals()["decode_chunk/0"]["drift_latched"] is True
    # recover: enough 1x dispatches pull the EMA back inside the band
    # and unlatch; a second excursion then fires a SECOND event
    for _ in range(8):
        dispatch(10.0)
    assert cm.residuals()["decode_chunk/0"]["drift_latched"] is False
    for _ in range(8):
        dispatch(30.0)
    assert len(cm.ring.events(cause="ema_drift", limit=10)) == 2


def test_observe_skips_unpredicted_and_running_records():
    cm = _model()
    timeline = DispatchTimeline(costmodel=cm)
    # no sheet -> no prediction -> observe is a no-op
    drec = timeline.begin("prefill", bucket=64)
    assert drec.predicted_ms is None
    timeline.finish(_backdated(drec, observed_ms=500.0))
    assert drec.residual_ratio is None and cm.ring.total() == 0


# -- the anomaly ring ---------------------------------------------------------

def test_anomaly_ring_bounds_filters_and_stats():
    ring = AnomalyRing(capacity=4)
    for i in range(10):
        ring.record(kind="prefill" if i % 2 else "decode_chunk",
                    cause="slow_dispatch", dispatch_id=i)
    assert ring.total() == 10
    events = ring.events(limit=100)
    assert len(events) == 4  # bounded retention
    assert [e["dispatch_id"] for e in events] == [9, 8, 7, 6]  # newest first
    assert all(e["kind"] == "prefill"
               for e in ring.events(kind="prefill"))
    assert ring.events(cause="ema_drift") == []
    stats = ring.stats()
    assert stats["total"] == 10 and stats["retained"] == 4
    assert stats["capacity"] == 4 and ring.capacity == 4
    assert stats["by"]["prefill/slow_dispatch"] == 5
    assert stats["last_ts"] == events[0]["ts"]


def test_snapshot_and_overview_shapes():
    cm = _model()
    cm.calibrate("cpu", "cpu")
    cm.install_synthetic("prefill", 1.0)
    snap = cm.snapshot()
    assert snap["calibration"]["source"] == "profile"
    assert snap["thresholds"]["anomaly_factor"] == 4.0
    assert len(snap["sheets"]) == 1
    assert snap["anomalies"]["total"] == 0
    over = cm.overview()
    assert over["calibration"] == "profile" and over["sheets"] == 1
    assert over["anomalies_total"] == 0
    assert over["worst_residual_ema"] is None  # needs EMA_MIN_SAMPLES


# -- timebase: labeled rate_total (the rollup's filter) -----------------------

def test_rate_total_labels_filter():
    from gofr_tpu.timebase import TimebaseSampler

    registry = Registry()
    counter = registry.counter("gofr_x_total", "x", labels=("cause",))
    sampler = TimebaseSampler(registry, interval_s=1.0, window_s=60.0,
                              start=False)
    counter.inc(10, cause="a")
    counter.inc(100, cause="b")
    sampler.sample_now()
    counter.inc(10, cause="a")
    sampler.sample_now()
    all_rates = sampler.rate_total("gofr_x_total")
    only_a = sampler.rate_total("gofr_x_total", labels={"cause": "a"})
    only_b = sampler.rate_total("gofr_x_total", labels={"cause": "b"})
    assert all_rates[0][1] == only_a[0][1]  # only `a` moved
    assert only_b[0][1] == 0.0


# -- costcal: the fit/check tooling -------------------------------------------

def test_costcal_fit_reproduces_synthesis_truth(tmp_path):
    out = tmp_path / "records.json"
    costcal.synth(str(out))
    row = costcal.fit([str(out)])
    assert row["device_kind"] == costcal.SYNTH_DEVICE_KIND
    assert row["n_compute_bound"] and row["n_bandwidth_bound"]
    assert row["eff_flops"] == pytest.approx(
        costcal.SYNTH_EFF_FLOPS, rel=0.05
    )
    assert row["eff_bw"] == pytest.approx(costcal.SYNTH_EFF_BW, rel=0.05)
    assert row["overhead_ms"] == pytest.approx(
        costcal.SYNTH_OVERHEAD_MS, rel=0.25
    )


def test_costcal_check_passes_on_committed_artifacts(capsys):
    """The CI smoke: the committed records artifact must reproduce the
    committed cost_profile.json coefficients — editing one side without
    refitting the other is exactly the drift --check exists to catch."""
    rc = costcal.check(
        str(REPO / "gofr_tpu" / "tpu" / "cost_profile.json"),
        [str(REPO / "hw" / "r02" / "dispatch_records.json")],
        tolerance=0.1,
    )
    assert rc == 0, capsys.readouterr().out
    # and a drifted profile fails
    drifted = dict(json.loads(
        (REPO / "gofr_tpu" / "tpu" / "cost_profile.json").read_text()
    ))
    for row in drifted["device_kinds"].values():
        row["eff_flops"] = row["eff_flops"] * 3
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fh:
        json.dump(drifted, fh)
        path = fh.name
    try:
        assert costcal.check(
            path, [str(REPO / "hw" / "r02" / "dispatch_records.json")],
            tolerance=0.1,
        ) == 1
    finally:
        os.unlink(path)


def test_costcal_synth_is_deterministic(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    costcal.synth(str(a))
    costcal.synth(str(b))
    assert a.read_text() == b.read_text()


# -- end-to-end: the compile-free acceptance spine ----------------------------

@pytest.fixture(scope="module")
def echo_app(tmp_path_factory):
    """Echo app with the cost model on defaults and the watchdog
    threshold ABOVE the injected stall — the anomaly path must fire
    without the engine ever wedging."""
    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    pm_dir = str(tmp_path_factory.mktemp("postmortems"))
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
           "MODEL_NAME": "echo", "TOKENIZER": "byte",
           "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "1",
           "TIMEBASE_INTERVAL_S": "0.05", "TIMEBASE_WINDOW_S": "60",
           "POSTMORTEM_DIR": pm_dir,
           # the 0.25s injected stall stays FAR below this: an anomaly
           # is a latency regression verdict, not a wedge
           "WATCHDOG_DISPATCH_TIMEOUT_S": "5"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    os.chdir(tmp_path_factory.mktemp("costmodel_e2e"))
    try:
        app = gofr_tpu.new()
    finally:
        os.chdir(cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    register_openai_routes(app)
    app.start()
    yield app, f"http://127.0.0.1:{port}", pm_dir
    app.shutdown()


def _post(base, payload, path="/v1/chat/completions"):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read()), dict(resp.headers.items())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())["data"]


def test_healthy_dispatches_are_predicted_with_zero_anomalies(echo_app):
    app, base, _ = echo_app
    _post(base, {"messages": [{"role": "user", "content": "hi"}],
                 "max_tokens": 2, "temperature": 0})
    tpu = app.container.tpu
    recs = tpu.timeline.records(limit=20, kind="prefill")
    assert recs, "no prefill dispatch recorded"
    assert recs[0]["predicted_ms"] is not None
    assert recs[0]["cost_source"] == "synthetic"
    assert recs[0]["residual_ratio"] is not None
    assert recs[0]["anomaly"] is None
    # the acceptance contract: a healthy run produces ZERO anomalies
    out = _get(base, "/admin/anomalies")
    assert out["anomalies"] == [] and out["count"] == 0
    assert out["stats"]["total"] == 0


def test_costmodel_admin_page_serves_calibration_and_sheets(echo_app):
    app, base, _ = echo_app
    out = _get(base, "/admin/costmodel")
    assert out["calibration"]["source"] == "profile"
    assert out["calibration"]["matched"] == "cpu"
    sources = {s["source"] for s in out["sheets"]}
    assert sources == {"synthetic"}  # echo: no HLO harvest on CPU
    kinds = {s["kind"] for s in out["sheets"]}
    assert {"prefill", "decode_chunk"} <= kinds
    assert out["thresholds"]["anomaly_factor"] == 4.0
    assert "residuals" in out and "anomalies_per_sec" in out
    # the engine snapshot carries the small overview block
    engine = _get(base, "/admin/engine")
    assert engine["costmodel"]["calibration"] == "profile"
    assert engine["costmodel"]["sheets"] >= 2


def test_anomalies_endpoint_validates_params(echo_app):
    app, base, _ = echo_app
    import urllib.error

    for path in ("/admin/anomalies?limit=0",
                 "/admin/anomalies?limit=x",
                 "/admin/anomalies?cause=nope"):
        try:
            _get(base, path)
            raise AssertionError(f"expected 400 for {path}")
        except urllib.error.HTTPError as e:
            assert e.code == 400, path


def test_injected_stall_raises_counted_anomaly_everywhere(echo_app):
    """The tentpole's e2e: one dispatch stalls 0.25s (>=4x the echo
    prediction AND past the 50ms absolute floor, but far below the 5s
    watchdog threshold) -> a slow_dispatch anomaly lands in the ring,
    on the counter, on the rider's flight record, and in a forced
    postmortem bundle — while the engine stays serving throughout."""
    app, base, pm_dir = echo_app
    tpu = app.container.tpu
    tpu.runner.stall_hook = lambda: time.sleep(0.25)
    try:
        _post(base, {"messages": [{"role": "user", "content": "slowpoke"}],
                     "max_tokens": 2, "temperature": 0})
    finally:
        tpu.runner.stall_hook = None
    assert tpu.engine.state == "serving"  # an anomaly is NOT a wedge
    out = _get(base, "/admin/anomalies?cause=slow_dispatch")
    assert out["count"] >= 1
    event = out["anomalies"][0]
    assert event["cause"] == "slow_dispatch"
    assert event["observed_ms"] >= 250.0
    assert event["observed_ms"] >= event["predicted_ms"] * 4
    anomalous_id = event["dispatch_id"]
    # the dispatch record itself carries the verdict
    rec = [r for r in tpu.timeline.records(limit=50)
           if r["dispatch_id"] == anomalous_id]
    assert rec and rec[0]["anomaly"] == "slow_dispatch"
    # the flight record that rode the stalled dispatch is marked
    reqs = _get(base, "/admin/requests?limit=50")["requests"]
    marked = [r for r in reqs if r.get("anomalous_dispatches")]
    assert any(anomalous_id in r["anomalous_dispatches"] for r in marked)
    # the counter is on /metrics with the kind/cause labels
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        om = resp.read().decode()
    assert "gofr_tpu_dispatch_anomalies_total" in om
    counted = [ln for ln in om.splitlines()
               if ln.startswith("gofr_tpu_dispatch_anomalies_total{")
               and 'cause="slow_dispatch"' in ln]
    assert counted and float(counted[0].rsplit(" ", 1)[1]) >= 1
    # overview + fleet-facing engine snapshot headline the anomaly
    over = _get(base, "/admin/overview")
    assert over["costmodel"]["anomalies_total"] >= 1
    assert over["costmodel"]["last_anomaly_ts"]
    # forced postmortem: the bundle snapshots the watchtower state
    req = urllib.request.Request(
        base + "/admin/postmortem",
        data=json.dumps({"detail": "costmodel drill"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        bundle_path = json.loads(resp.read())["data"]["path"]
    bundle = json.load(open(bundle_path))
    assert bundle["costmodel"]["calibration"]["source"] == "profile"
    assert bundle["costmodel"]["anomalies"]["total"] >= 1
    assert any(e["dispatch_id"] == anomalous_id
               for e in bundle["anomalies"])
    # COSTMODEL_* / ANOMALY_* keys are postmortem config fingerprints
    from gofr_tpu.postmortem import CONFIG_PREFIXES
    assert "COSTMODEL_" in CONFIG_PREFIXES and "ANOMALY_" in CONFIG_PREFIXES


def test_costmodel_off_disables_the_surface(tmp_path, monkeypatch):
    """COSTMODEL=off removes the whole layer: no predictions, no ring,
    503 on the admin pages (same contract as an unconfigured tpu)."""
    monkeypatch.setenv("MODEL_NAME", "echo")
    monkeypatch.setenv("TOKENIZER", "byte")
    monkeypatch.setenv("COSTMODEL", "off")
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    monkeypatch.chdir(tmp_path)
    import gofr_tpu

    app = gofr_tpu.new()
    tpu = app.container.tpu
    try:
        deadline = time.monotonic() + 30.0
        while tpu.engine.state != "serving" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert tpu.costmodel is None
        assert tpu.timeline.costmodel is None
        out = tpu.generate([1, 2, 3], max_new_tokens=2)
        recs = tpu.timeline.records(limit=5, kind="prefill")
        assert recs and recs[0]["predicted_ms"] is None
        assert tpu.engine_snapshot()["costmodel"] is None
    finally:
        tpu.close()
