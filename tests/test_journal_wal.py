"""Crash-durable generation journal (gofr_tpu/journal_wal.py), tier-1:
frame integrity, lifecycle persistence, rotation/retention with
checkpoint carry-over, the truncation fuzz (a segment cut at EVERY byte
must never install a corrupt entry and never lose an intact earlier
one), bit-flip refusal, and the process-death resume e2e — a second
echo device over the same ``JOURNAL_DIR`` rehydrates the first one's
interrupted stream and serves ``resume_from`` bit-identically.
"""

import os
import struct

import pytest

from gofr_tpu.journal_wal import (
    FSYNC_POLICIES,
    K_OPEN,
    K_TOKENS,
    MAGIC,
    WALError,
    JournalWAL,
    _frame,
    _iter_frames,
)
from gofr_tpu.telemetry import GenerationJournal

PROMPT = [5, 6, 7]


def _wal(tmp_path, name="wal", **kw):
    kw.setdefault("segment_bytes", 1 << 20)
    return JournalWAL(str(tmp_path / name), **kw)


def _segment_paths(wal):
    return [
        os.path.join(wal.directory, f)
        for f in sorted(os.listdir(wal.directory))
        if f.startswith("wal-")
    ]


# -- framing -------------------------------------------------------------------

def test_frame_roundtrip_and_refusals():
    header = MAGIC + struct.pack("<I", 1)
    body = _frame(K_OPEN, b'{"x":1}') + _frame(K_TOKENS, b"\x01\x00\x00\x00")
    frames = list(_iter_frames(header + body))
    assert [k for k, _ in frames] == [K_OPEN, K_TOKENS]
    with pytest.raises(WALError):
        list(_iter_frames(b"XXXX" + struct.pack("<I", 1) + body))  # bad magic
    with pytest.raises(WALError):
        list(_iter_frames(MAGIC + struct.pack("<I", 9) + body))  # bad version
    # a flipped KIND byte is a CRC failure (the CRC covers the kind),
    # never a reinterpretation of the payload under the wrong schema
    mutated = bytearray(header + body)
    mutated[len(header)] = K_TOKENS
    with pytest.raises(WALError):
        list(_iter_frames(bytes(mutated)))


def test_fsync_policy_validation(tmp_path):
    for policy in FSYNC_POLICIES:
        _wal(tmp_path, f"p-{policy}", fsync=policy).close()
    with pytest.raises(ValueError):
        _wal(tmp_path, "p-bad", fsync="sometimes")


# -- lifecycle persistence -----------------------------------------------------

def test_lifecycle_persists_and_rehydrates(tmp_path):
    wal = _wal(tmp_path)
    journal = GenerationJournal(capacity=8, max_tokens=64, wal=wal)
    done = journal.start("k-done", "echo", 16, seeded=False,
                         deterministic=True)
    for t in range(5):
        done.append(t)
    journal.finish(done)
    hurt = journal.start("k-hurt", "echo", 16, seeded=True,
                         deterministic=True)
    for t in (100, 101, 102):
        hurt.append(t)
    journal.interrupt(hurt, "pool failure")
    live = journal.start("k-live", "echo", 16, seeded=False,
                         deterministic=True)
    live.append(200)
    live.append(201)
    # `live` gets NO terminal record: the SIGKILL signature. The WAL is
    # deliberately not closed either — flushed frames must be enough.

    wal2 = _wal(tmp_path)
    j2 = GenerationJournal(capacity=8, max_tokens=64, wal=wal2)
    assert j2.rehydrate() == 2
    assert j2.stats()["rehydrated"] == 2
    assert j2.stats()["wal"]["recovered_entries"] == 2
    c = j2.claim("k-hurt", 0)
    assert c is not None and c.tokens == [100, 101, 102]
    assert c.reason == "pool failure"
    c = j2.claim("k-live", 0)
    assert c is not None and c.tokens == [200, 201]
    assert "process death" in c.reason
    assert j2.claim("k-done", 0) is None  # finished: not resumable

    # the claims above were WAL-recorded: a THIRD boot finds nothing
    j3 = GenerationJournal(capacity=8, max_tokens=64, wal=_wal(tmp_path))
    assert j3.rehydrate() == 0


def test_truncated_entry_retires_on_disk_too(tmp_path):
    journal = GenerationJournal(capacity=8, max_tokens=4,
                                wal=_wal(tmp_path))
    entry = journal.start("k-trunc", "echo", 16, seeded=False,
                          deterministic=True)
    for t in range(6):
        entry.append(t)
    assert entry.truncated
    journal.interrupt(entry, "wedge")
    j2 = GenerationJournal(capacity=8, max_tokens=4, wal=_wal(tmp_path))
    assert j2.rehydrate() == 0  # an unprovable record never rehydrates


def test_capacity_eviction_retires_on_disk(tmp_path):
    journal = GenerationJournal(capacity=2, max_tokens=64,
                                wal=_wal(tmp_path))
    for i in range(4):
        e = journal.start(f"k{i}", "echo", 8, seeded=True,
                          deterministic=True)
        e.append(i)
        journal.interrupt(e, "wedge")
    j2 = GenerationJournal(capacity=8, max_tokens=64, wal=_wal(tmp_path))
    assert j2.rehydrate() == 2
    assert j2.claim("k0", 0) is None and j2.claim("k1", 0) is None
    assert j2.claim("k2", 0) is not None and j2.claim("k3", 0) is not None


# -- rotation + retention ------------------------------------------------------

def test_rotation_checkpoint_carries_live_entries(tmp_path):
    wal = _wal(tmp_path, segment_bytes=4096, retain=2)
    journal = GenerationJournal(capacity=8, max_tokens=4096, wal=wal)
    keeper = journal.start("k-keeper", "echo", 4096, seeded=True,
                           deterministic=True)
    keeper.append(7)
    journal.interrupt(keeper, "early wedge")
    # churn enough finished traffic to rotate several times: the
    # keeper's records live only in segments retention has DELETED —
    # rotation checkpoints must carry it across
    for i in range(40):
        e = journal.start(f"churn{i}", "echo", 4096, seeded=False,
                          deterministic=True)
        for t in range(64):
            e.append(t)
        journal.finish(e)
    assert len(_segment_paths(wal)) <= 2
    assert wal.stats()["segments"] <= 2
    j2 = GenerationJournal(capacity=8, max_tokens=4096, wal=_wal(tmp_path))
    assert j2.rehydrate() == 1
    c = j2.claim("k-keeper", 0)
    assert c is not None and c.tokens == [7] and c.reason == "early wedge"


def test_rotation_mid_entry_never_duplicates_tokens(tmp_path):
    """Regression: a rotation triggered BY a token append must not
    replay that batch twice (the checkpoint written at rotation must
    snapshot the mirror from BEFORE the triggering frame). One live
    entry, enough single-token appends to force several rotations:
    recovery returns exactly the appended sequence."""
    wal = _wal(tmp_path, segment_bytes=4096, retain=8)
    journal = GenerationJournal(capacity=8, max_tokens=4096, wal=wal)
    entry = journal.start("k-rot", "echo", 4096, seeded=False,
                          deterministic=True)
    n = 600  # several 4 KiB rotations of ~13B token frames
    for t in range(n):
        entry.append(t)
    assert len(_segment_paths(wal)) > 1  # rotation actually happened
    j2 = GenerationJournal(capacity=8, max_tokens=4096, wal=_wal(tmp_path))
    assert j2.rehydrate() == 1
    c = j2.claim("k-rot", 0)
    assert c is not None
    assert c.tokens == list(range(n))  # exact: no loss, no duplication


# -- the truncation fuzz (satellite) -------------------------------------------

def _build_fuzz_segment(tmp_path, name="fuzz"):
    """One small segment with interleaved entries and recorded byte
    offsets: (wal_dir, truth, completion_offsets). ``truth`` maps key ->
    (final tokens, resumable); ``completion_offsets`` maps key -> the
    segment size after its LAST record landed (an entry is 'intact' for
    cuts at/after that offset)."""
    wal = JournalWAL(str(tmp_path / name), segment_bytes=1 << 20)
    journal = GenerationJournal(capacity=16, max_tokens=256, wal=wal)
    offsets = {}

    def size():
        return os.path.getsize(_segment_paths(wal)[0])

    a = journal.start("ka", "echo", 32, seeded=False, deterministic=True)
    b = journal.start("kb", "echo", 32, seeded=True, deterministic=True)
    for t in range(4):
        a.append(10 + t)
        b.append(20 + t)
    journal.interrupt(a, "wedge-a")
    offsets["ka"] = size()
    c = journal.start("kc", "echo", 32, seeded=False, deterministic=True)
    c.append(30)
    journal.finish(b)
    offsets["kb"] = size()
    c.append(31)
    offsets["kc"] = size()  # c stays open: resumable via process death
    truth = {
        "ka": ([10, 11, 12, 13], True),
        "kb": ([20, 21, 22, 23], False),
        "kc": ([30, 31], True),
    }
    return wal, truth, offsets


def test_truncation_fuzz_every_cut_point(tmp_path):
    """Cut the segment at EVERY byte offset (frame boundaries and
    mid-frame alike): recovery must never raise, never install tokens
    that are not a true prefix, and never lose an entry whose records
    all landed before the cut."""
    wal, truth, offsets = _build_fuzz_segment(tmp_path)
    seg = _segment_paths(wal)[0]
    with open(seg, "rb") as f:
        data = f.read()
    cut_dir = tmp_path / "cut"
    os.makedirs(cut_dir, exist_ok=True)
    cut_seg = os.path.join(str(cut_dir), os.path.basename(seg))
    for cut in range(len(data) + 1):
        with open(cut_seg, "wb") as f:
            f.write(data[:cut])
        recovered = JournalWAL(str(cut_dir)).recover()
        by_key = {}
        for state in recovered:
            assert state["key"] not in by_key, f"duplicate entry at cut {cut}"
            by_key[state["key"]] = state
        for key, state in by_key.items():
            tokens, _ = truth[key]
            got = state["tokens"]
            assert got == tokens[:len(got)], (
                f"cut {cut}: {key} recovered non-prefix tokens {got}"
            )
        for key, (tokens, resumable) in truth.items():
            if cut < offsets[key]:
                continue  # records partially lost: absence is legal
            if resumable:
                assert key in by_key, f"cut {cut}: intact entry {key} lost"
                assert by_key[key]["tokens"] == tokens, (
                    f"cut {cut}: intact entry {key} lost tokens"
                )
            else:
                assert key not in by_key, (
                    f"cut {cut}: finished entry {key} resurrected"
                )
    # the full-length 'cut' is the clean recovery
    with open(cut_seg, "wb") as f:
        f.write(data)
    full = JournalWAL(str(cut_dir))
    assert {s["key"] for s in full.recover()} == {"ka", "kc"}
    assert full.torn_segments == 0


def test_bitflip_fuzz_never_installs_corrupt_tokens(tmp_path):
    """Flip every byte of the segment (one at a time): recovery must
    never raise and never install a token list that is not a true
    prefix of the entry's real stream — a flipped byte is refused at
    its frame, not absorbed."""
    wal, truth, _ = _build_fuzz_segment(tmp_path, name="flip")
    seg = _segment_paths(wal)[0]
    with open(seg, "rb") as f:
        data = bytearray(f.read())
    flip_dir = tmp_path / "flip-out"
    os.makedirs(flip_dir, exist_ok=True)
    flip_seg = os.path.join(str(flip_dir), os.path.basename(seg))
    for i in range(len(data)):
        mutated = bytearray(data)
        mutated[i] ^= 0x40
        with open(flip_seg, "wb") as f:
            f.write(bytes(mutated))
        for state in JournalWAL(str(flip_dir)).recover():
            tokens, _ = truth.get(state["key"], ([], True))
            got = state["tokens"]
            assert got == tokens[:len(got)], (
                f"flip at {i}: corrupt tokens installed for {state['key']}"
            )


# -- process-death resume e2e (echo device) ------------------------------------

def _echo_device(tmp_path, registry=None, **env):
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.logging import Level
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger
    from gofr_tpu.tpu.device import new_device

    cfg = {
        "MODEL_NAME": "echo",
        "JOURNAL_DIR": str(tmp_path / "journal"),
        "WATCHDOG_DISPATCH_TIMEOUT_S": "0.2",
        "RECOVERY_BACKOFF_S": "0.05",
    }
    cfg.update(env)
    old = {k: os.environ.get(k) for k in cfg}
    os.environ.update(cfg)
    try:
        return new_device(
            EnvConfig(), MockLogger(Level.FATAL), registry or Registry()
        )
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_process_death_resume_rehydrates_bit_identical(tmp_path):
    """The tentpole invariant, device-level: an interrupted stream's
    WAL records survive the first device's death (close() writes no
    terminal record for interrupted entries), a SECOND device over the
    same JOURNAL_DIR rehydrates them at construction, and
    ``resume_from`` continues teacher-forced and bit-identical."""
    from gofr_tpu.metrics import Registry

    device = _echo_device(tmp_path)
    try:
        full = device.generate(PROMPT, max_new_tokens=12)
        key = device._journal_key(PROMPT, 12, None, device.default_stop_ids,
                                  None)
        entry = device.journal.start(key, "echo", 12, seeded=False,
                                     deterministic=True)
        for token in full[:7]:
            entry.append(token)
        device.journal.interrupt(entry, "injected wedge")
        assert device.engine_snapshot()["journal"]["wal"]["segments"] >= 1
    finally:
        device.close()

    registry = Registry()
    reborn = _echo_device(tmp_path, registry)
    try:
        stats = reborn.journal.stats()
        assert stats["rehydrated"] == 1
        assert stats["interrupted"] == 1
        resumed = list(reborn.generate_stream(PROMPT, max_new_tokens=12,
                                              resume_from=5))
        assert full[:5] + resumed == full  # zero missing, zero duplicated
        modes = registry.counter(
            "gofr_tpu_journal_resumes_total", labels=("mode",)
        ).data()
        assert modes.get(("teacher_forced",)) == 1.0
        # the claim was durably recorded: a THIRD boot has nothing left
        assert reborn.engine_snapshot()["journal"]["wal"]["live_entries"] == 0
    finally:
        reborn.close()

    third = _echo_device(tmp_path)
    try:
        assert third.journal.stats()["rehydrated"] == 0
    finally:
        third.close()


def test_wal_disabled_without_journal_dir(tmp_path):
    device = _echo_device(tmp_path, JOURNAL_DIR="")
    try:
        assert device.journal_wal is None
        assert device.journal.stats()["wal"] is None
        device.generate(PROMPT, max_new_tokens=4)
    finally:
        device.close()
