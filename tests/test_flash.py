"""Pallas flash-attention kernel vs the XLA reference (interpret mode on
the CPU mesh — same kernel logic that compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import _xla_attention, attention
from gofr_tpu.ops.flash import flash_attention

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def _rand(key, shape):
    return jax.random.normal(jax.random.key(key), shape, jnp.float32)


def _assert_close(got, want, atol=2e-5):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=atol)


def test_flash_matches_xla_causal():
    b, s, h, d = 2, 64, 2, 32
    q, k, v = _rand(0, (b, s, h, d)), _rand(1, (b, s, h, d)), _rand(2, (b, s, h, d))
    got = flash_attention(q, k, v, causal=True, block_q=16, block_kv=16)
    want = _xla_attention(q, k, v, True, 0, None, None)
    _assert_close(got, want)


def test_flash_non_causal():
    b, s, h, d = 1, 32, 2, 16
    q, k, v = _rand(3, (b, s, h, d)), _rand(4, (b, s, h, d)), _rand(5, (b, s, h, d))
    got = flash_attention(q, k, v, causal=False, block_q=8, block_kv=8)
    want = _xla_attention(q, k, v, False, 0, None, None)
    _assert_close(got, want)


def test_flash_gqa():
    b, s, hq, hkv, d = 2, 32, 4, 2, 16
    q = _rand(6, (b, s, hq, d))
    k, v = _rand(7, (b, s, hkv, d)), _rand(8, (b, s, hkv, d))
    got = flash_attention(q, k, v, causal=True, block_q=8, block_kv=8)
    want = _xla_attention(q, k, v, True, 0, None, None)
    _assert_close(got, want)


def test_flash_unaligned_seq_pads():
    # seq not a multiple of the block: wrapper pads, output sliced back
    b, s, h, d = 1, 23, 1, 8
    q, k, v = _rand(9, (b, s, h, d)), _rand(10, (b, s, h, d)), _rand(11, (b, s, h, d))
    got = flash_attention(q, k, v, causal=True, block_q=8, block_kv=8)
    want = _xla_attention(q, k, v, True, 0, None, None)
    _assert_close(got, want)


def test_flash_ragged_offsets_and_kv_lens():
    # decode-shaped: queries at different absolute positions per batch row,
    # cache valid only up to kv_lens
    b, sq, skv, h, d = 2, 8, 64, 2, 16
    q = _rand(12, (b, sq, h, d))
    k, v = _rand(13, (b, skv, h, d)), _rand(14, (b, skv, h, d))
    offsets = jnp.array([5, 17], jnp.int32)
    kv_lens = offsets + sq
    got = flash_attention(
        q, k, v, causal=True, q_offset=offsets, kv_lens=kv_lens, block_q=8, block_kv=8
    )
    mask = jnp.arange(skv)[None, :] < kv_lens[:, None]
    want = _xla_attention(q, k, v, True, offsets, mask, None)
    _assert_close(got, want)
    # keys beyond kv_lens must be invisible
    k2 = k.at[:, 40:].set(99.0)
    v2 = v.at[:, 40:].set(-99.0)
    got2 = flash_attention(
        q, k2, v2, causal=True, q_offset=offsets, kv_lens=kv_lens, block_q=8, block_kv=8
    )
    row0 = np.asarray(got)[0]
    np.testing.assert_allclose(np.asarray(got2)[0], row0, atol=1e-6)


def test_flash_scale_override():
    b, s, h, d = 1, 16, 1, 8
    q, k, v = _rand(15, (b, s, h, d)), _rand(16, (b, s, h, d)), _rand(17, (b, s, h, d))
    got = flash_attention(q, k, v, causal=True, scale=0.1, block_q=8, block_kv=8)
    want = _xla_attention(q, k, v, True, 0, None, 0.1)
    _assert_close(got, want)


def test_flash_bf16_close_to_f32_reference():
    b, s, h, d = 1, 32, 2, 16
    q, k, v = _rand(18, (b, s, h, d)), _rand(19, (b, s, h, d)), _rand(20, (b, s, h, d))
    got = flash_attention(
        q.astype(jnp.bfloat16),
        k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16),
        causal=True,
        block_q=8,
        block_kv=8,
    )
    want = _xla_attention(q, k, v, True, 0, None, None)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), rtol=2e-2, atol=2e-2
    )


def test_flash_gradients_match_xla():
    b, s, h, d = 1, 16, 2, 8
    q, k, v = _rand(21, (b, s, h, d)), _rand(22, (b, s, h, d)), _rand(23, (b, s, h, d))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=8, block_kv=8) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, True, 0, None, None) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        _assert_close(a, b_, atol=1e-4)


def test_attention_auto_rejects_mask_on_pallas():
    b, s, h, d = 1, 16, 1, 8
    q, k, v = _rand(24, (b, s, h, d)), _rand(25, (b, s, h, d)), _rand(26, (b, s, h, d))
    with pytest.raises(NotImplementedError):
        attention(q, k, v, mask=jnp.ones((b, s), bool), impl="pallas")


def test_attention_kv_lens_xla_path_equals_mask():
    b, s, h, d = 2, 12, 1, 8
    q, k, v = _rand(27, (b, s, h, d)), _rand(28, (b, s, h, d)), _rand(29, (b, s, h, d))
    kv_lens = jnp.array([5, 9], jnp.int32)
    got = attention(q, k, v, causal=False, kv_lens=kv_lens, impl="xla")
    mask = jnp.arange(s)[None, :] < kv_lens[:, None]
    want = attention(q, k, v, causal=False, mask=mask, impl="xla")
    _assert_close(got, want)


def test_flash_pallas_impl_via_attention():
    b, s, h, d = 1, 32, 2, 16
    q, k, v = _rand(30, (b, s, h, d)), _rand(31, (b, s, h, d)), _rand(32, (b, s, h, d))
    got = attention(q, k, v, causal=True, impl="pallas")
    want = attention(q, k, v, causal=True, impl="xla")
    _assert_close(got, want)


def test_flash_decode_sq1():
    # sq=1 decode shape: padded q block, KV loop bounded by kv_lens
    b, skv, h, d = 2, 64, 2, 16
    q = _rand(33, (b, 1, h, d))
    k, v = _rand(34, (b, skv, h, d)), _rand(35, (b, skv, h, d))
    offsets = jnp.array([10, 30], jnp.int32)
    got = flash_attention(
        q, k, v, causal=True, q_offset=offsets, kv_lens=offsets + 1,
        block_q=16, block_kv=16,
    )
    want = attention(
        q, k, v, causal=True, q_offset=offsets, kv_lens=offsets + 1, impl="xla"
    )
    _assert_close(got, want)


def test_fully_masked_rows_zero_on_both_paths():
    # kv_lens == 0 slot: both impls emit zeros (not uniform mean(v))
    b, s, h, d = 2, 8, 1, 8
    q, k, v = _rand(36, (b, s, h, d)), _rand(37, (b, s, h, d)), _rand(38, (b, s, h, d))
    kv_lens = jnp.array([0, s], jnp.int32)
    xla = attention(q, k, v, causal=False, kv_lens=kv_lens, impl="xla")
    fl = flash_attention(q, k, v, causal=False, kv_lens=kv_lens, block_q=8, block_kv=8)
    np.testing.assert_allclose(np.asarray(xla)[0], 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(fl)[0], 0.0, atol=1e-7)
    _assert_close(fl[1], xla[1])


def test_blockwise_backward_matches_full(monkeypatch):
    """The O(block_q*S) checkpointed backward (round-2 verdict weak #7)
    must produce the same grads as differentiating the full recompute —
    including ragged offsets, kv_lens, and a non-multiple sequence."""
    from gofr_tpu.ops.flash import _blockwise_reference, _reference

    b, s, h, d = 2, 37, 2, 8
    q, k, v = _rand(31, (b, s, h, d)), _rand(32, (b, s, h, d)), _rand(33, (b, s, h, d))
    offsets = jnp.asarray([0, 3], jnp.int32)
    kv_lens = jnp.asarray([s, s - 5], jnp.int32)

    out_full = _reference(q, k, v, offsets, kv_lens, True, d ** -0.5)
    out_blk = _blockwise_reference(q, k, v, offsets, kv_lens, True, d ** -0.5,
                                   block_q=8)
    _assert_close(out_blk, out_full, atol=1e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, offsets, kv_lens, True, d ** -0.5) ** 2
        )

    gf = jax.grad(loss(lambda *a: _blockwise_reference(*a, block_q=8)),
                  argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        _assert_close(a, b_, atol=1e-4)


def test_flash_grad_routes_through_blockwise(monkeypatch):
    """With FUSED_BWD off, jax.grad(flash_attention) takes the SPLIT
    blockwise recompute backward (not the small-sequence fast path) and
    still matches full-recompute grads: the fallback custom_vjp path with
    real residual shapes."""
    import gofr_tpu.ops.flash as flash_mod

    monkeypatch.setattr(flash_mod, "FUSED_BWD", False)
    monkeypatch.setattr(flash_mod, "BWD_BLOCK_Q", 8)  # 32 > 8: must split
    b, s, h, d = 1, 32, 1, 8
    q, k, v = _rand(41, (b, s, h, d)), _rand(42, (b, s, h, d)), _rand(43, (b, s, h, d))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=8, block_kv=8) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, True, 0, None, None) ** 2)

    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        _assert_close(a, b_, atol=1e-4)


def _flash_grads(q, k, v, **kw):
    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, **kw) ** 2)

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


def test_fused_backward_gqa_matches_xla():
    # GQA: dk/dv sum over the query-head group via output-block revisiting
    b, s, hq, hkv, d = 2, 32, 4, 2, 16
    q = _rand(44, (b, s, hq, d))
    k, v = _rand(45, (b, s, hkv, d)), _rand(46, (b, s, hkv, d))

    def loss_xla(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, True, 0, None, None) ** 2)

    gf = _flash_grads(q, k, v, causal=True, block_q=8, block_kv=8)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        _assert_close(a, b_, atol=1e-4)


def test_fused_backward_ragged_matches_oracle():
    # ragged offsets + kv_lens + non-multiple seq: the fused kernels must
    # agree with the checkpointed-recompute oracle on the exact same call
    from gofr_tpu.ops.flash import _blockwise_reference

    b, sq, skv, h, d = 2, 19, 40, 2, 8
    q = _rand(47, (b, sq, h, d))
    k, v = _rand(48, (b, skv, h, d)), _rand(49, (b, skv, h, d))
    offsets = jnp.array([2, 11], jnp.int32)
    kv_lens = offsets + sq

    gf = _flash_grads(
        q, k, v, causal=True, q_offset=offsets, kv_lens=kv_lens,
        block_q=8, block_kv=8,
    )

    def loss_oracle(q, k, v):
        return jnp.sum(
            _blockwise_reference(q, k, v, offsets, kv_lens, True, d ** -0.5,
                                 block_q=8) ** 2
        )

    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, go):
        _assert_close(a, b_, atol=1e-4)


def test_fused_backward_non_causal():
    b, s, h, d = 1, 24, 2, 8
    q, k, v = _rand(50, (b, s, h, d)), _rand(51, (b, s, h, d)), _rand(52, (b, s, h, d))

    def loss_xla(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, False, 0, None, None) ** 2)

    gf = _flash_grads(q, k, v, causal=False, block_q=8, block_kv=8)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        _assert_close(a, b_, atol=1e-4)


def test_fused_backward_zero_kv_lens_row():
    # a fully-masked row (kv_lens == 0): forward emits zeros, backward must
    # emit zero grads for that row instead of NaN (lse == +inf there)
    b, s, h, d = 2, 8, 1, 8
    q, k, v = _rand(53, (b, s, h, d)), _rand(54, (b, s, h, d)), _rand(55, (b, s, h, d))
    kv_lens = jnp.array([0, s], jnp.int32)
    gq, gk, gv = _flash_grads(
        q, k, v, causal=False, kv_lens=kv_lens, block_q=8, block_kv=8
    )
    assert np.isfinite(np.asarray(gq)).all()
    np.testing.assert_allclose(np.asarray(gq)[0], 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(gk)[0], 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(gv)[0], 0.0, atol=1e-7)
