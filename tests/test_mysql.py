"""MySQL wire-protocol client against the in-process fake server
(minimysql), mirroring the reference's sqlmock strategy (SURVEY.md §4) but
through a real socket: framing, auth, text resultsets, errors."""

import dataclasses
import threading

import pytest

from gofr_tpu.datasource.minimysql import MiniMySQL
from gofr_tpu.datasource.mysql import (
    MySQLDB,
    MySQLError,
    escape_literal,
    interpolate,
    native_password_token,
)


@pytest.fixture(scope="module")
def server():
    with MiniMySQL(user="gofr", password="s3cret") as srv:
        yield srv


@pytest.fixture()
def db(server):
    d = MySQLDB("127.0.0.1", server.port, "gofr", "s3cret", "test")
    yield d
    d.close()


def test_handshake_and_ping(db):
    h = db.health_check()
    assert h.status == "UP"
    assert h.details["dialect"] == "mysql"
    assert "minimysql" in h.details["server_version"]


def test_wrong_password_denied(server):
    with pytest.raises(MySQLError, match="Access denied"):
        MySQLDB("127.0.0.1", server.port, "gofr", "wrong", "test")


def test_wrong_user_denied(server):
    with pytest.raises(MySQLError, match="Access denied"):
        MySQLDB("127.0.0.1", server.port, "intruder", "s3cret", "test")


def test_ddl_dml_and_text_resultset(db):
    db.execute("DROP TABLE IF EXISTS users")
    db.execute("CREATE TABLE users (id INTEGER, full_name TEXT, score REAL)")
    n = db.execute("INSERT INTO users VALUES (?, ?, ?)", 1, "Ada Lovelace", 9.5)
    assert n == 1
    db.execute_many("INSERT INTO users VALUES (?, ?, ?)",
                    [(2, "Grace Hopper", 8.25), (3, None, None)])
    rows = db.query("SELECT id, full_name, score FROM users ORDER BY id")
    assert [tuple(r) for r in rows] == [
        (1, "Ada Lovelace", 9.5), (2, "Grace Hopper", 8.25), (3, None, None),
    ]
    assert rows[0]["full_name"] == "Ada Lovelace"
    assert rows[0].keys() == ["id", "full_name", "score"]


def test_escaping_survives_round_trip(db):
    db.execute("DROP TABLE IF EXISTS notes")
    db.execute("CREATE TABLE notes (body TEXT)")
    evil = "Robert'); DROP TABLE notes;-- \" \\ \n über 🎉"
    db.execute("INSERT INTO notes VALUES (?)", evil)
    assert db.select_value("SELECT body FROM notes") == evil
    assert db.select_value("SELECT COUNT(*) FROM notes") == 1  # not dropped


def test_blob_bytes_vs_text_str(db):
    """BLOB (charset 63) round-trips as bytes; TEXT shares the wire type
    but decodes to str."""
    db.execute("DROP TABLE IF EXISTS b_t")
    db.execute("CREATE TABLE b_t (data BLOB)")
    blob = bytes(range(256))
    db.execute("INSERT INTO b_t VALUES (?)", blob)
    assert db.select_value("SELECT data FROM b_t") == blob


def test_connection_recovers_after_io_error(db, server):
    """An I/O error discards the desynced connection; the next call
    reconnects instead of reading stale packets."""
    db.execute("DROP TABLE IF EXISTS r_t")
    db.execute("CREATE TABLE r_t (v INTEGER)")
    db.execute("INSERT INTO r_t VALUES (1)")
    db._get_conn().sock.close()  # simulate a dropped connection
    with pytest.raises(Exception):
        db.query("SELECT v FROM r_t")
    assert db.select_value("SELECT v FROM r_t") == 1  # fresh connection


def test_connections_are_per_thread(db):
    """Transactions are connection-scoped in MySQL; per-thread connections
    keep one handler's BEGIN from swallowing another handler's statements
    (the sqlite DB uses the same strategy)."""
    conns = {}

    def grab(i):
        conns[i] = db._get_conn()
        db.select_value("SELECT 1")

    threads = [threading.Thread(target=grab, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert conns[0] is not conns[1]
    assert conns[0] is not db._get_conn()  # main thread has its own too


def test_select_into_dataclass(db):
    @dataclasses.dataclass
    class User:
        id: int = 0
        full_name: str = ""
        ignored: str = dataclasses.field(default="", metadata={"db": "nope"})

    db.execute("DROP TABLE IF EXISTS users2")
    db.execute("CREATE TABLE users2 (id INTEGER, full_name TEXT, extra TEXT)")
    db.execute("INSERT INTO users2 VALUES (?, ?, ?)", 7, "Katherine", "x")
    users = db.select(User, "SELECT * FROM users2")
    assert users == [User(id=7, full_name="Katherine")]
    one = db.select_one(User, "SELECT * FROM users2 WHERE id = ?", 7)
    assert one.full_name == "Katherine"
    assert db.select_one(User, "SELECT * FROM users2 WHERE id = ?", 404) is None


def test_transaction_commit_and_rollback(db):
    db.execute("DROP TABLE IF EXISTS tx_t")
    db.execute("CREATE TABLE tx_t (v INTEGER)")
    with db.begin() as tx:
        tx.execute("INSERT INTO tx_t VALUES (1)")
    assert db.select_value("SELECT COUNT(*) FROM tx_t") == 1
    with pytest.raises(RuntimeError, match="boom"):
        with db.begin() as tx:
            tx.execute("INSERT INTO tx_t VALUES (2)")
            raise RuntimeError("boom")
    assert db.select_value("SELECT COUNT(*) FROM tx_t") == 1  # rolled back


def test_sql_error_propagates(db):
    with pytest.raises(MySQLError, match="1064"):
        db.query("SELEKT broken")


def test_concurrent_queries_serialize_safely(db):
    db.execute("DROP TABLE IF EXISTS c_t")
    db.execute("CREATE TABLE c_t (v INTEGER)")
    errors = []

    def worker(i):
        try:
            db.execute("INSERT INTO c_t VALUES (?)", i)
            db.query("SELECT * FROM c_t")
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert db.select_value("SELECT COUNT(*) FROM c_t") == 8


def test_interpolation_and_escaping_units():
    assert interpolate("SELECT ?", [1]) == "SELECT 1"
    assert interpolate("SELECT '?', ?", ["x"]) == "SELECT '?', 'x'"
    assert escape_literal(None) == "NULL"
    assert escape_literal(True) == "1"
    assert escape_literal(b"\x01\xff") == "x'01ff'"
    assert escape_literal("a'b") == r"'a\'b'"
    with pytest.raises(MySQLError, match="not enough"):
        interpolate("? ?", [1])


def test_native_password_token_shape():
    tok = native_password_token("pw", b"\x01" * 20)
    assert len(tok) == 20
    assert native_password_token("", b"\x01" * 20) == b""


def test_container_wires_mysql(server, monkeypatch):
    """DB_DIALECT=mysql end-to-end through config+container (verdict #5's
    done-criterion)."""
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.container import Container

    monkeypatch.setenv("DB_DIALECT", "mysql")
    monkeypatch.setenv("DB_HOST", "127.0.0.1")
    monkeypatch.setenv("DB_PORT", str(server.port))
    monkeypatch.setenv("DB_USER", "gofr")
    monkeypatch.setenv("DB_PASSWORD", "s3cret")
    monkeypatch.setenv("DB_NAME", "test")
    monkeypatch.delenv("REDIS_HOST", raising=False)
    monkeypatch.delenv("MODEL_NAME", raising=False)
    monkeypatch.delenv("TPU_ENABLED", raising=False)
    c = Container(EnvConfig())
    assert c.db is not None
    assert c.db.execute("SELECT 1") == 0  # resultset path exercised below
    assert c.db.select_value("SELECT 41 + 1") == 42
    health = c.health()
    assert health["details"]["sql"]["status"] == "UP"
    c.close()


def test_container_degrades_on_bad_mysql(monkeypatch):
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.container import Container

    monkeypatch.setenv("DB_DIALECT", "mysql")
    monkeypatch.setenv("DB_HOST", "127.0.0.1")
    monkeypatch.setenv("DB_PORT", "1")  # nothing listens
    monkeypatch.setenv("DB_NAME", "test")
    monkeypatch.delenv("REDIS_HOST", raising=False)
    monkeypatch.delenv("MODEL_NAME", raising=False)
    monkeypatch.delenv("TPU_ENABLED", raising=False)
    c = Container(EnvConfig())
    assert c.db is None  # logged, not fatal (container.go:80-85 parity)
    c.close()


# -- caching_sha2_password (MySQL 8 default; VERDICT r03 item 4) -------------

def test_sha2_fast_auth_is_the_default():
    """The fixture server advertises caching_sha2_password (stock MySQL 8),
    so the happy path above already runs the sha2 scramble; this pins it."""
    with MiniMySQL(user="u", password="pw") as srv:
        assert srv.auth_plugin == "caching_sha2_password"
        db = MySQLDB("127.0.0.1", srv.port, "u", "pw", "")
        assert db.health_check().status == "UP"
        db.close()


def test_sha2_full_auth_rsa_exchange():
    """Cache-miss path: server demands perform_full_authentication; the
    client fetches the RSA key and sends the nonce-whitened password
    encrypted — over plain TCP, as go-sql-driver does without TLS."""
    with MiniMySQL(user="u", password="hunter2", full_auth=True) as srv:
        db = MySQLDB("127.0.0.1", srv.port, "u", "hunter2", "")
        assert db.select_value("select 41 + 1") == 42
        db.close()


def test_sha2_full_auth_wrong_password_denied():
    with MiniMySQL(user="u", password="right", full_auth=True) as srv:
        with pytest.raises(MySQLError) as exc:
            MySQLDB("127.0.0.1", srv.port, "u", "wrong", "")
        assert exc.value.code == 1045


def test_auth_switch_to_native_password():
    """Server advertises caching_sha2 but switches the account to
    mysql_native_password — the client must check the plugin NAME in the
    AuthSwitchRequest, not resend the old plugin's token."""
    with MiniMySQL(user="u", password="pw",
                   switch_to="mysql_native_password") as srv:
        db = MySQLDB("127.0.0.1", srv.port, "u", "pw", "")
        assert db.select_value("select 7") == 7
        db.close()


def test_auth_switch_to_sha2():
    with MiniMySQL(user="u", password="pw",
                   auth_plugin="mysql_native_password",
                   switch_to="caching_sha2_password") as srv:
        db = MySQLDB("127.0.0.1", srv.port, "u", "pw", "")
        assert db.select_value("select 7") == 7
        db.close()


def test_unknown_plugin_rejected_with_clear_error():
    with MiniMySQL(user="u", password="pw",
                   auth_plugin="sha256_password") as srv:
        with pytest.raises(MySQLError) as exc:
            MySQLDB("127.0.0.1", srv.port, "u", "pw", "")
        assert exc.value.code == 2059
        assert "sha256_password" in str(exc.value)


def test_sha2_empty_password():
    with MiniMySQL(user="u", password="") as srv:
        db = MySQLDB("127.0.0.1", srv.port, "u", "", "")
        assert db.select_value("select 1") == 1
        db.close()
