"""Engine introspection (gofr_tpu/tpu/introspect.py): dispatch timeline,
engine state machine, and the device stall watchdog — unit semantics plus
the end-to-end spine over the in-process server on the no-JAX ``echo``
model (no XLA compiles; the fast tier covers the whole layer): an
injected device stall must flip the state machine to degraded/wedged,
turn ``/.well-known/ready`` into a diagnosed 503, increment the stall
counter, and recover when the dispatch completes."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from gofr_tpu.metrics import Registry
from gofr_tpu.tpu.introspect import (
    DISPATCH_KINDS,
    DispatchTimeline,
    EngineState,
    StallWatchdog,
)


# -- unit: dispatch timeline --------------------------------------------------

def test_timeline_ids_monotonic_and_ring_bounded():
    timeline = DispatchTimeline(capacity=3)
    records = [timeline.begin("prefill", bucket=64) for _ in range(5)]
    assert [r.dispatch_id for r in records] == [1, 2, 3, 4, 5]
    for r in records:
        timeline.finish(r)
    page = timeline.records()
    assert [r["dispatch_id"] for r in page] == [5, 4, 3]  # newest first
    stats = timeline.stats()
    assert stats["total"] == 5
    assert stats["by_kind"] == {"prefill": 5}
    assert stats["in_flight"] == 0


def test_timeline_in_flight_visible_and_finish_idempotent():
    timeline = DispatchTimeline(capacity=8)
    rec = timeline.begin("decode_chunk", batch_size=4)
    assert timeline.stats()["in_flight"] == 1
    # a running (possibly wedged) dispatch is already on the ring
    assert timeline.records()[0]["status"] == "running"
    assert timeline.records()[0]["duration_s"] is None
    timeline.finish(rec, status="error")
    timeline.finish(rec)  # idempotent: first finish wins
    assert timeline.records()[0]["status"] == "error"
    assert timeline.stats()["in_flight"] == 0


def test_timeline_kind_filter_and_limit():
    timeline = DispatchTimeline(capacity=16)
    for _ in range(3):
        timeline.finish(timeline.begin("prefill"))
    timeline.finish(timeline.begin("warmup_compile", detail="bucket 64"))
    assert all(
        r["kind"] == "prefill" for r in timeline.records(kind="prefill")
    )
    assert len(timeline.records(kind="prefill")) == 3
    assert len(timeline.records(limit=2)) == 2
    assert timeline.records(kind="warmup_compile")[0]["detail"] == "bucket 64"


def test_dispatch_record_queue_vs_running_split():
    timeline = DispatchTimeline(capacity=4)
    queued = time.perf_counter()
    time.sleep(0.02)
    rec = timeline.begin("prefill", queued_at=queued)
    rec.mark_running()
    timeline.finish(rec)
    out = rec.to_dict()
    assert out["queue_wait_s"] >= 0.02
    assert out["duration_s"] < 0.02


# -- unit: engine state machine ----------------------------------------------

def test_engine_state_transitions_history_and_gauge():
    registry = Registry()
    engine = EngineState(metrics=registry)
    assert engine.state == "booting"
    engine.transition("warming", "compiling")
    engine.transition("serving")
    engine.transition("serving")  # same-state: no duplicate history entry
    snap = engine.snapshot()
    assert snap["state"] == "serving"
    assert [h["state"] for h in snap["history"]] == [
        "booting", "warming", "serving",
    ]
    gauge = registry.gauge("gofr_tpu_engine_state", labels=("state",))
    assert gauge.value(state="serving") == 1.0
    assert gauge.value(state="booting") == 0.0


def test_engine_state_rejects_unknown_state():
    engine = EngineState()
    with pytest.raises(ValueError, match="unknown"):
        engine.transition("confused")


# -- unit: stall watchdog -----------------------------------------------------

def test_watchdog_flags_stall_wedges_and_recovers():
    registry = Registry()
    engine = EngineState(metrics=registry)
    engine.transition("serving")
    watchdog = StallWatchdog(
        engine, metrics=registry, timeout_s=0.05, wedge_factor=3.0
    )
    seen = set()

    def stalled_dispatch():
        with watchdog.watch("prefill", 7):
            time.sleep(0.4)

    worker = threading.Thread(target=stalled_dispatch)
    worker.start()
    deadline = time.time() + 2.0
    while worker.is_alive() and time.time() < deadline:
        seen.add(engine.state)
        time.sleep(0.01)
    worker.join()
    watchdog.close()
    assert "degraded" in seen
    assert "wedged" in seen  # 0.4s stall > 3x the 0.05s deadline
    assert watchdog.stall_counts == {"prefill": 1}
    counter = registry.counter("gofr_tpu_device_stalls_total", labels=("kind",))
    assert counter.value(kind="prefill") == 1
    # the dispatch completing flips the engine back to pre-stall state
    assert engine.state == "serving"
    assert "recovered" in (engine.snapshot()["detail"] or "")


def test_watchdog_disabled_is_noop():
    engine = EngineState()
    watchdog = StallWatchdog(engine, timeout_s=0.0)
    assert not watchdog.enabled
    with watchdog.watch("prefill", 1):
        time.sleep(0.05)
    assert watchdog.stall_counts == {}
    assert watchdog.snapshot()["enabled"] is False


def test_watchdog_fast_dispatches_never_flag():
    engine = EngineState()
    engine.transition("serving")
    watchdog = StallWatchdog(engine, timeout_s=0.2)
    for _ in range(5):
        with watchdog.watch("decode_chunk", 1):
            time.sleep(0.005)
    time.sleep(0.1)
    watchdog.close()
    assert watchdog.stall_counts == {}
    assert engine.state == "serving"


# -- end-to-end: the echo app ------------------------------------------------

@pytest.fixture(scope="module")
def echo_app(tmp_path_factory):
    """Echo-model app with the OpenAI routes and an ARMED watchdog —
    the full engine-introspection spine, no XLA compiles."""
    import os

    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
           "MODEL_NAME": "echo", "TOKENIZER": "byte",
           "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "1",
           "FLIGHT_SLOW_MS": "60000",
           # armed deadline small enough that an injected 0.7s stall
           # walks the whole machine: degraded at 0.15s, wedged at 0.45s
           "WATCHDOG_DISPATCH_TIMEOUT_S": "0.15"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    os.chdir(tmp_path_factory.mktemp("engine_obs"))
    try:
        app = gofr_tpu.new()
    finally:
        os.chdir(cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    register_openai_routes(app)
    app.start()
    yield app, f"http://127.0.0.1:{port}"
    app.shutdown()


def _post(base, payload, path="/v1/chat/completions"):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read()), dict(resp.headers.items())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())["data"]


def test_admin_engine_snapshot_is_populated(echo_app):
    app, base = echo_app
    _post(base, {"messages": [{"role": "user", "content": "warm"}],
                 "max_tokens": 2, "temperature": 0})
    snap = _get(base, "/admin/engine")
    assert snap["engine"]["state"] == "serving"
    assert [h["state"] for h in snap["engine"]["history"]][0] == "booting"
    assert snap["model"] == "echo"
    # the boot timeline captured real stages (probe + runner build)
    stages = [s["stage"] for s in snap["boot_timeline"]]
    assert any("probing device runtime" in s for s in stages)
    assert all(s["seconds"] >= 0 for s in snap["boot_timeline"])
    assert snap["watchdog"]["enabled"] is True
    assert snap["watchdog"]["timeout_s"] == pytest.approx(0.15)
    assert snap["dispatches"]["total"] >= 1
    assert "prefill" in snap["dispatches"]["by_kind"]
    assert snap["queue_depth"] == 0
    assert snap["scheduler"]["policy"] == "fair"
    assert "executable" in snap["caches"]


def test_admin_dispatches_schema_filter_and_400(echo_app):
    app, base = echo_app
    _post(base, {"messages": [{"role": "user", "content": "dispatch me"}],
                 "max_tokens": 2, "temperature": 0})
    page = _get(base, "/admin/dispatches")
    assert page["count"] == len(page["dispatches"]) >= 1
    newest = page["dispatches"][0]
    for field in ("dispatch_id", "kind", "status", "batch_size",
                  "padded_tokens", "tokens", "queue_wait_s", "duration_s"):
        assert field in newest
    assert newest["kind"] in DISPATCH_KINDS
    prefills = _get(base, "/admin/dispatches?kind=prefill")["dispatches"]
    assert prefills and all(r["kind"] == "prefill" for r in prefills)
    assert prefills[0]["status"] == "ok"
    assert prefills[0]["bucket"] >= prefills[0]["tokens"]
    assert prefills[0]["duration_s"] > 0
    assert len(_get(base, "/admin/dispatches?limit=1")["dispatches"]) == 1
    # the boot-time device probe rode the timeline too
    assert _get(base, "/admin/dispatches?kind=device_probe")["dispatches"]
    try:
        _get(base, "/admin/dispatches?kind=warp")
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_flight_record_dispatch_ids_resolve(echo_app):
    """The request->dispatch join: a FlightRecord's dispatch_ids must
    resolve to matching DispatchRecords on /admin/dispatches."""
    app, base = echo_app
    _, headers = _post(base, {
        "messages": [{"role": "user", "content": "link me up"}],
        "max_tokens": 3, "temperature": 0,
    })
    corr = headers["X-Correlation-ID"]
    mine = [r for r in _get(base, "/admin/requests")["requests"]
            if r["trace_id"] == corr]
    assert len(mine) == 1
    ids = mine[0]["dispatch_ids"]
    assert ids, "request carried no dispatch ids"
    dispatches = {
        r["dispatch_id"]: r
        for r in _get(base, "/admin/dispatches?limit=500")["dispatches"]
    }
    for did in ids:
        assert did in dispatches, (did, sorted(dispatches))
        assert dispatches[did]["kind"] == "prefill"
        assert dispatches[did]["status"] == "ok"


def test_injected_stall_walks_the_state_machine(echo_app):
    """The acceptance spine: injected stall -> degraded -> wedged ->
    ready 503 with the state -> stall counter -> recovery -> ready 200.
    The recovery SUPERVISOR is disabled for the duration: this test pins
    the watchdog's own stall-resolution walk (the supervisor's rebuild
    path has its own suite, tests/test_recovery.py)."""
    app, base = echo_app
    tpu = app.container.tpu
    counter_before = tpu.metrics.counter(
        "gofr_tpu_device_stalls_total", labels=("kind",)
    ).value(kind="prefill")
    tpu.recovery.enabled = False
    tpu.runner.stall_hook = lambda: time.sleep(0.7)
    try:
        worker = threading.Thread(
            target=lambda: _post(
                base,
                {"messages": [{"role": "user", "content": "stall"}],
                 "max_tokens": 1, "temperature": 0},
            ),
        )
        worker.start()
        states = set()
        ready_bodies = []
        deadline = time.time() + 5.0
        while worker.is_alive() and time.time() < deadline:
            states.add(tpu.engine.state)
            try:
                urllib.request.urlopen(
                    base + "/.well-known/ready", timeout=5
                ).close()
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    ready_bodies.append(json.loads(e.read() or b"{}"))
            time.sleep(0.02)
        worker.join()
    finally:
        tpu.runner.stall_hook = None
        tpu.recovery.enabled = True
    assert "degraded" in states
    assert "wedged" in states  # 0.7s stall > 3x the 0.15s deadline
    # ready told the truth while stalled: 503 with the engine state
    assert ready_bodies, "ready never returned 503 during the stall"
    assert {b["state"] for b in ready_bodies} <= {"degraded", "wedged"}
    assert any("stalled" in (b.get("detail") or "") for b in ready_bodies)
    counter_after = tpu.metrics.counter(
        "gofr_tpu_device_stalls_total", labels=("kind",)
    ).value(kind="prefill")
    assert counter_after >= counter_before + 1
    # recovery: the dispatch completed, the engine serves again
    deadline = time.time() + 2.0
    while tpu.engine.state != "serving" and time.time() < deadline:
        time.sleep(0.02)
    assert tpu.engine.state == "serving"
    with urllib.request.urlopen(base + "/.well-known/ready", timeout=5) as r:
        assert r.status == 200
    # and the stalled dispatch shows on the timeline as completed
    snap = _get(base, "/admin/engine")
    assert snap["watchdog"]["stalls"].get("prefill", 0) >= 1
    assert snap["engine"]["state"] == "serving"


def test_stall_metrics_visible_on_metrics_endpoint(echo_app):
    """The Prometheus view: engine state gauge + stall counter exposed."""
    app, base = echo_app
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert 'gofr_tpu_engine_state{state="serving"} 1' in text
    assert "gofr_tpu_dispatches_total" in text
    assert "gofr_tpu_dispatch_seconds" in text
