"""Real-model ingestion: safetensors parsing + HF-Llama weight mapping.

The writer oracle is the `safetensors` library (independent implementation:
our reader is a from-scratch mmap parser), the tree oracle is
init_transformer + export_llama_hf round-trips."""

import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from gofr_tpu.models.ingest import (
    Checkpoint,
    SafetensorsFile,
    export_llama_hf,
    is_safetensors_path,
    iter_hf_llama_tensors,
    load_llama_params,
)
from gofr_tpu.models.llama import TINY
from gofr_tpu.models.transformer import init_transformer, transformer_forward

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

TOKENS = jnp.asarray([[5, 3, 8, 1, 9, 2]], jnp.int32)


@pytest.fixture(scope="module")
def tiny_params():
    return init_transformer(jax.random.key(7), TINY)


@pytest.fixture(scope="module")
def hf_dict(tiny_params):
    return export_llama_hf(tiny_params, TINY)


def _save(path, tensors):
    from safetensors.numpy import save_file

    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()}, path)


def test_safetensors_file_reader(tmp_path, hf_dict):
    path = str(tmp_path / "model.safetensors")
    _save(path, hf_dict)
    sf = SafetensorsFile(path)
    assert set(sf.names()) == set(hf_dict)
    for name, ref in hf_dict.items():
        got = sf.tensor(name)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        np.testing.assert_array_equal(got, ref)
    with pytest.raises(KeyError, match="nope"):
        sf.tensor("nope")
    sf.close()


def test_load_llama_roundtrip_single_file(tmp_path, tiny_params, hf_dict):
    path = str(tmp_path / "model.safetensors")
    _save(path, hf_dict)
    loaded = load_llama_params(path, TINY)
    ref = transformer_forward(tiny_params, TOKENS, TINY)
    got = transformer_forward(loaded, TOKENS, TINY)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_load_llama_sharded_with_index(tmp_path, tiny_params, hf_dict):
    names = sorted(hf_dict)
    half = len(names) // 2
    shard_of = {}
    for shard, chunk in (("model-00001-of-00002.safetensors", names[:half]),
                         ("model-00002-of-00002.safetensors", names[half:])):
        _save(str(tmp_path / shard), {n: hf_dict[n] for n in chunk})
        for n in chunk:
            shard_of[n] = shard
    with open(tmp_path / "model.safetensors.index.json", "w") as f:
        json.dump({"weight_map": shard_of}, f)
    loaded = load_llama_params(str(tmp_path), TINY)
    ref = transformer_forward(tiny_params, TOKENS, TINY)
    got = transformer_forward(loaded, TOKENS, TINY)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_missing_tensor_named(tmp_path, hf_dict):
    broken = {k: v for k, v in hf_dict.items()
              if k != "model.layers.1.mlp.down_proj.weight"}
    path = str(tmp_path / "model.safetensors")
    _save(path, broken)
    with pytest.raises(KeyError, match="model.layers.1.mlp.down_proj.weight"):
        load_llama_params(path, TINY)


def test_shape_mismatch_named(tmp_path, hf_dict):
    import dataclasses

    wrong = dataclasses.replace(TINY, hidden_dim=96)
    path = str(tmp_path / "model.safetensors")
    _save(path, hf_dict)
    with pytest.raises(ValueError, match="gate_proj"):
        load_llama_params(path, wrong)


def test_tied_embeddings_fallback(tmp_path, tiny_params, hf_dict):
    tied = {k: v for k, v in hf_dict.items() if k != "lm_head.weight"}
    path = str(tmp_path / "model.safetensors")
    _save(path, tied)
    loaded = load_llama_params(path, TINY)
    np.testing.assert_array_equal(
        np.asarray(loaded["lm_head"]), np.asarray(loaded["embed"]).T
    )


def test_quantize_during_load(tmp_path, tiny_params, hf_dict):
    from gofr_tpu.models.quant import quantize_params

    path = str(tmp_path / "model.safetensors")
    _save(path, hf_dict)
    loaded = load_llama_params(path, TINY, quantize=True)
    assert set(loaded["layers"]["wq"]) == {"q", "scale"}
    ref = transformer_forward(quantize_params(tiny_params), TOKENS, TINY)
    got = transformer_forward(loaded, TOKENS, TINY)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_iter_covers_full_tree(tmp_path, tiny_params, hf_dict):
    path = str(tmp_path / "model.safetensors")
    _save(path, hf_dict)
    ckpt = Checkpoint(path)
    paths = {p for p, _ in iter_hf_llama_tensors(ckpt, TINY)}
    ckpt.close()
    expected = {("embed",), ("norm_f",), ("lm_head",)}
    for key in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                "attn_norm", "mlp_norm"):
        for i in range(TINY.n_layers):
            expected.add(("layers", key, i))
    assert paths == expected


def test_is_safetensors_path(tmp_path, hf_dict):
    f = str(tmp_path / "model.safetensors")
    _save(f, hf_dict)
    assert is_safetensors_path(f)
    assert is_safetensors_path(str(tmp_path))  # dir containing shards
    assert not is_safetensors_path(None)
    orbax_dir = tmp_path / "orbax"
    orbax_dir.mkdir()
    assert not is_safetensors_path(str(orbax_dir))


def test_device_boots_from_safetensors(tmp_path, hf_dict, tiny_params):
    """The verdict's done-criterion: MODEL_PATH=*.safetensors boots and
    serves (device routes to the HF loader)."""
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.logging import Level
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger
    from gofr_tpu.tpu.device import new_device

    path = str(tmp_path / "model.safetensors")
    _save(path, hf_dict)
    env = {"MODEL_NAME": "tiny", "MODEL_PATH": path, "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "DECODE_POOL": "off"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            out = device.infer({"tokens": [5, 3, 8, 1, 9, 2]})
            ref = transformer_forward(tiny_params, TOKENS, TINY)
            np.testing.assert_allclose(
                np.asarray(out["logits"]), np.asarray(ref)[0, -1], rtol=1e-4, atol=1e-4
            )
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
