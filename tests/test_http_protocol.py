"""Wire-level HTTP/1.1 protocol tests against the real server: raw
sockets drive the parse/limit/framing paths urllib can't reach —
malformed heads, bad content-length, oversized headers, HTTP/1.0
connection handling, HEAD framing, and chunked request bodies."""

import json
import socket

import pytest


@pytest.fixture
def app(make_plain_app):
    application = make_plain_app()
    application.post("/echo", lambda ctx: ctx.bind())
    application.get("/hello", lambda ctx: "hi")
    application.start()
    return application


def _raw(app, payload: bytes) -> bytes:
    with socket.create_connection(("127.0.0.1", app.http_port), timeout=10) as s:
        s.sendall(payload)
        s.settimeout(10)
        out = b""
        try:
            while True:
                data = s.recv(65536)
                if not data:
                    break
                out += data
        except socket.timeout:
            pass
        return out


def test_malformed_request_head_400(app):
    out = _raw(app, b"NOT A REQUEST\r\n\r\n")
    assert out.startswith(b"HTTP/1.1 400")
    assert b"malformed" in out


def test_bad_content_length_400(app):
    out = _raw(app, b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: banana\r\n\r\n")
    assert out.startswith(b"HTTP/1.1 400")
    assert b"content-length" in out


def test_oversized_headers_431(app):
    big = b"X-Pad: " + b"a" * (70 * 1024) + b"\r\n"
    out = _raw(app, b"GET /hello HTTP/1.1\r\nHost: x\r\n" + big + b"\r\n")
    assert out.startswith(b"HTTP/1.1 431")


def test_oversized_body_413_without_upload(app):
    # the limit must reject on the DECLARED length — before any body
    # bytes are read (a slow client must not upload 64MB to get a 413)
    out = _raw(app, b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: 999999999\r\n\r\n")
    assert out.startswith(b"HTTP/1.1 413")


def test_http10_connection_closes(app):
    out = _raw(app, b"GET /hello HTTP/1.0\r\nHost: x\r\n\r\n")
    assert out.startswith(b"HTTP/1.0 200") or out.startswith(b"HTTP/1.1 200")
    assert b"Connection: close" in out
    # the server closed after the response (recv drained to EOF above)


def test_head_advertises_length_without_body(app):
    out = _raw(app, b"HEAD /hello HTTP/1.1\r\nHost: x\r\n"
                    b"Connection: close\r\n\r\n")
    head, _, body = out.partition(b"\r\n\r\n")
    assert head.startswith(b"HTTP/1.1 200")
    # Content-Length advertises what GET would return; body itself empty
    length = [ln for ln in head.split(b"\r\n")
              if ln.lower().startswith(b"content-length")]
    assert length and int(length[0].split(b":")[1]) > 0
    assert body == b""


def test_chunked_request_body(app):
    payload = json.dumps({"a": 1}).encode()
    chunked = (b"%x\r\n" % len(payload)) + payload + b"\r\n0\r\n\r\n"
    out = _raw(app, b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                    b"Transfer-Encoding: chunked\r\n"
                    b"Connection: close\r\n\r\n" + chunked)
    assert out.startswith(b"HTTP/1.1 200")
    assert b'{"a": 1}' in out or b'{"a":1}' in out


def test_pipelined_keepalive_requests(app):
    # two requests written back-to-back on one connection: both answered
    two = (b"GET /hello HTTP/1.1\r\nHost: x\r\n\r\n"
           b"GET /hello HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
    out = _raw(app, two)
    assert out.count(b"HTTP/1.1 200") == 2
