"""Prefill/decode interference scheduler + bucket-cohort batch formation
(gofr_tpu/tpu/scheduler.py, tpu/batcher.py) — all JAX-free, so the fast
tier covers the scheduling machinery end to end.

The regression the interleaver guards: a long prefill admitted while a
pooled stream is decoding must not delay pooled decode chunks by more
than ~one chunk budget. Decode NEVER blocks on the scheduler (it only
notes its cadence); prefill chunks are admitted at most one per
decode-chunk interval, and a single device executes its stream in
dispatch order — so the inter-admit invariant asserted here (every
admitted prefill chunk saw a fresh decode turn) IS the bounded-gap
property, without timing-flaky sleeps.
"""

import threading
import time

import numpy as np
import pytest

from gofr_tpu.metrics import Registry
from gofr_tpu.telemetry import FlightRecorder, activate_record
from gofr_tpu.tpu.batcher import DynamicBatcher, pack_token_rows
from gofr_tpu.tpu.scheduler import InterferenceScheduler


# -- scheduler unit ----------------------------------------------------------

def test_bad_policy_rejected():
    with pytest.raises(ValueError):
        InterferenceScheduler(policy="yolo")
    with pytest.raises(ValueError):
        InterferenceScheduler(max_defer_ms=0)


def test_idle_decode_never_defers():
    sched = InterferenceScheduler(policy="fair")
    for _ in range(5):
        assert sched.admit_prefill(64) < 0.01
    assert sched.stats["prefill_chunks"] == 5
    assert sched.stats["deferred_chunks"] == 0


def test_prefill_first_never_defers_even_under_load():
    sched = InterferenceScheduler(policy="prefill-first")
    sched.note_decode_chunk(active=4)
    sched.note_decode_chunk(active=4)
    for _ in range(4):
        assert sched.admit_prefill(64) < 0.01


def test_fair_admits_one_chunk_per_decode_interval():
    sched = InterferenceScheduler(policy="fair", max_defer_ms=2000)
    sched.note_decode_chunk(active=2)
    sched.note_decode_chunk(active=2)
    # first chunk: a decode turn already elapsed since the last admit
    assert sched.admit_prefill(64) < 0.05
    # second chunk in the SAME interval must wait for the next decode note
    release = threading.Timer(0.15, sched.note_decode_chunk, args=(2,))
    release.start()
    deferred = sched.admit_prefill(64)
    release.join()
    assert deferred >= 0.1  # waited for the decode turn, then proceeded
    assert sched.stats["deferred_chunks"] >= 1


def test_decode_first_needs_two_intervals():
    sched = InterferenceScheduler(policy="decode-first", max_defer_ms=2000)
    sched.note_decode_chunk(active=1)
    sched.note_decode_chunk(active=1)
    assert sched.admit_prefill(64) < 0.05
    # ONE decode note is not enough under decode-first; the second
    # releases the waiter
    t1 = threading.Timer(0.1, sched.note_decode_chunk, args=(1,))
    t2 = threading.Timer(0.25, sched.note_decode_chunk, args=(1,))
    t1.start(), t2.start()
    deferred = sched.admit_prefill(64)
    t1.join(), t2.join()
    assert deferred >= 0.2


def test_defer_is_bounded_when_decode_stalls():
    # active slots but no cadence within the bound: prefill must keep
    # progressing (the defer cap), never deadlock behind a wedged pool
    sched = InterferenceScheduler(policy="fair", max_defer_ms=120)
    sched.note_decode_chunk(active=4)
    sched.admit_prefill(64)  # consumes the decode turn
    start = time.perf_counter()
    sched.admit_prefill(64)  # nothing left to wait for -> capped wait
    elapsed = time.perf_counter() - start
    assert 0.05 <= elapsed < 1.0


def test_decode_idle_releases_waiting_prefill():
    sched = InterferenceScheduler(policy="fair", max_defer_ms=5000)
    sched.note_decode_chunk(active=4)
    sched.admit_prefill(64)
    release = threading.Timer(0.1, sched.note_decode_idle)
    release.start()
    start = time.perf_counter()
    sched.admit_prefill(64)
    release.join()
    assert time.perf_counter() - start < 1.0  # released, not capped out


def test_long_prefill_interleaves_with_decode_cadence():
    """The regression guard (ISSUE satellite): a long prefill — many
    bounded chunks — admitted mid-stream interleaves one chunk per
    decode turn, so pooled chunks are never delayed by more than ~one
    chunk budget. Asserted via the inter-admit invariant: under load,
    every admitted chunk observed a decode seq advance since the
    previous admit."""
    sched = InterferenceScheduler(policy="fair", max_defer_ms=3000)
    stop = threading.Event()

    def decode_loop():
        while not stop.is_set():
            sched.note_decode_chunk(active=3)
            time.sleep(0.01)

    worker = threading.Thread(target=decode_loop, daemon=True)
    worker.start()
    try:
        # decode is established and busy: poll, don't trust a fixed
        # sleep (a slow-starting worker thread would flake the
        # strict-advance invariant below)
        deadline = time.monotonic() + 5.0
        while sched._decode_seq < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sched._decode_seq >= 2, "decode loop never started"
        seqs = []
        for _ in range(8):  # the "long prefill": 8 bounded chunks
            sched.admit_prefill(512)
            seqs.append(sched._decode_seq)
    finally:
        stop.set()
        worker.join(timeout=2)
    # every chunk rode its own decode interval: seq strictly advanced
    # between consecutive admits (one prefill chunk per decode turn)
    assert all(b > a for a, b in zip(seqs, seqs[1:])), seqs
    assert sched.stats["prefill_chunks"] == 8


def test_metrics_registered_and_counted():
    registry = Registry()
    sched = InterferenceScheduler(policy="fair", metrics=registry, model="m")
    sched.admit_prefill(64)
    counter = registry.counter(
        "gofr_tpu_prefill_chunks_total", labels=("model",)
    )
    assert counter.value(model="m") == 1


# -- bucket-cohort batch formation -------------------------------------------

LADDER = (16, 32, 64, 128)


def _bucket_of(ids) -> int:
    n = int(ids.size)
    for b in LADDER:
        if n <= b:
            return b
    return LADDER[-1]


def _run_mixed_cohort(cohort: bool):
    """Feed one mixed-length 8-request burst through a batcher; returns
    (dispatched batches as bucket lists, padded-token counter value)."""
    registry = Registry()
    batches: list[list[int]] = []
    done = threading.Event()

    def run(payloads):
        batches.append([_bucket_of(p) for p in payloads])
        return [int(p[0]) for p in payloads]

    b = DynamicBatcher(
        run, max_batch=8, timeout_ms=60, metrics=registry,
        name="m", bucket_fn=_bucket_of, cohort=cohort,
    )
    try:
        lengths = [4, 120, 8, 100, 12, 90, 6, 110]  # 16-bucket vs 128-bucket
        futures = [
            b.submit(np.arange(1, n + 1, dtype=np.int32)) for n in lengths
        ]
        results = [f.result(timeout=10) for f in futures]
        assert results == [1] * 8  # every request answered
        done.set()
    finally:
        b.close()
    counter = registry.counter(
        "gofr_tpu_prefill_padded_tokens_total", labels=("model",)
    )
    return batches, counter.value(model="m")


def test_mixed_cohort_dispatches_bucket_homogeneous_batches():
    """Acceptance (a): a mixed-length 8-request burst forms per-bucket
    cohorts, and the padded-token total is STRICTLY lower than the FIFO
    mixed batch's."""
    cohort_batches, cohort_padded = _run_mixed_cohort(cohort=True)
    fifo_batches, fifo_padded = _run_mixed_cohort(cohort=False)
    # cohort mode: every dispatched batch is one bucket
    assert all(len(set(batch)) == 1 for batch in cohort_batches), cohort_batches
    # FIFO mode co-batched 16-bucket prompts with 128-bucket prompts
    assert any(len(set(batch)) > 1 for batch in fifo_batches), fifo_batches
    assert cohort_padded < fifo_padded
    # exactness: cohorts pay only their own bucket's padding
    assert cohort_padded == sum(
        _bucket_of(np.zeros(n)) - n for n in (4, 120, 8, 100, 12, 90, 6, 110)
    )


def test_cohort_off_keeps_fifo_single_batch():
    fifo_batches, _ = _run_mixed_cohort(cohort=False)
    assert len(fifo_batches) == 1 and len(fifo_batches[0]) == 8


def test_displaced_items_survive_close():
    """Items displaced into the worker's pending buffer during cohort
    formation must complete (or fail loudly) on close — never hang."""
    registry = Registry()

    def run(payloads):
        time.sleep(0.01)
        return [0] * len(payloads)

    b = DynamicBatcher(
        run, max_batch=4, timeout_ms=30, metrics=registry,
        name="m", bucket_fn=_bucket_of, cohort=True,
    )
    futures = [
        b.submit(np.arange(1, n + 1, dtype=np.int32))
        for n in (4, 100, 4, 100)
    ]
    b.close()
    for f in futures:
        try:
            f.result(timeout=5)  # resolved either way — no strand
        except RuntimeError:
            pass


def test_dispatch_stamps_prefill_shape_and_chunk_on_records():
    recorder = FlightRecorder(capacity=8)
    record = recorder.start(model="m", endpoint="/t")
    try:
        b = DynamicBatcher(
            lambda ps: [0] * len(ps), max_batch=2, timeout_ms=5,
            bucket_fn=_bucket_of, cohort=True,
        )
        try:
            b.submit(np.arange(1, 7, dtype=np.int32)).result(timeout=5)
        finally:
            b.close()
    finally:
        activate_record(None)
    assert record.prefill_chunks == 1
    assert record.prefill_bucket == 16  # 6 tokens -> the 16 bucket
    recorder.finish(record)
    assert recorder.records()[0]["prefill_bucket"] == 16


# -- decode-pool reject accounting (the JAX-free half) -----------------------

def test_pool_reject_accounting_increments_counter_and_record():
    import queue as queue_mod
    from types import SimpleNamespace

    from gofr_tpu.tpu.decode_pool import DecodePool

    registry = Registry()
    counter = registry.counter(
        "gofr_tpu_pool_reject_total", labels=("reason",)
    )
    fake = SimpleNamespace(_reject_counter=counter)
    recorder = FlightRecorder(capacity=4)
    record = recorder.start(model="m", endpoint="/t")
    try:
        with pytest.raises(queue_mod.Full):
            DecodePool._reject(fake, "no_free_slots", "no free decode slots")
        DecodePool._reject(fake, "closed", count_only=True)
    finally:
        activate_record(None)
    assert counter.value(reason="no_free_slots") == 1
    assert counter.value(reason="closed") == 1
    assert record.pool_reject_reason == "no_free_slots"  # FIRST reason kept
    recorder.finish(record)
    assert recorder.records()[0]["pool_reject_reason"] == "no_free_slots"


# -- pack_token_rows edge cases: native vs Python parity ---------------------

def _pack_via_python(monkeypatch, rows, n_rows, width, pad_id=0):
    from gofr_tpu import native

    monkeypatch.setattr(native, "load", lambda: None)
    return pack_token_rows(rows, n_rows, width, pad_id)


PACK_CASES = [
    ("empty_rows", [], 4, 8),
    ("zero_length_row", [np.array([], np.int32), np.array([5, 6], np.int32)], 2, 4),
    ("overlong_keeps_last", [np.arange(1, 11, dtype=np.int32)], 1, 4),
    ("pad_rows_beyond_inputs", [np.array([9], np.int32)], 4, 4),
    ("all_zero_length", [np.array([], np.int32)], 2, 4),
]


@pytest.mark.parametrize("name,rows,n_rows,width", PACK_CASES)
def test_pack_token_rows_python_semantics(monkeypatch, name, rows, n_rows, width):
    out, lens = _pack_via_python(monkeypatch, rows, n_rows, width, pad_id=0)
    assert out.shape == (n_rows, width) and lens.shape == (n_rows,)
    for i in range(n_rows):
        if i < len(rows):
            kept = np.asarray(rows[i], np.int32).reshape(-1)[-width:]
            assert lens[i] == kept.size
            assert (out[i, : kept.size] == kept).all()
            assert (out[i, kept.size:] == 0).all()
        else:
            assert lens[i] == 0 and (out[i] == 0).all()
    if name == "overlong_keeps_last":
        assert list(out[0]) == [7, 8, 9, 10]  # LAST tokens, not first


@pytest.mark.parametrize("name,rows,n_rows,width", PACK_CASES)
def test_pack_token_rows_native_matches_python(monkeypatch, name, rows, n_rows, width):
    from gofr_tpu import native

    if native.load() is None:
        pytest.skip("no C++ toolchain — native path unavailable")
    native_out, native_lens = pack_token_rows(rows, n_rows, width, pad_id=3)
    py_out, py_lens = _pack_via_python(monkeypatch, rows, n_rows, width, pad_id=3)
    assert (native_out == py_out).all(), name
    assert (native_lens == py_lens).all(), name
