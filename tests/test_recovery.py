"""Self-healing engine, tier-1: the recovery supervisor's incident
loop (quarantine → rebuild → serving, bounded attempts, terminal
verdicts), the durable generation journal (prompt-hash keying,
interrupt/claim, bounded retention), and journal-backed stream resume
asserted BIT-IDENTICAL to an uninterrupted run — on the compile-free
echo runner AND the tiny transformer (teacher-forced prefill over
prompt+emitted through the paged-KV path).

The fleet-level half — wedge a replica mid-stream, resume through the
router with zero missing/duplicated tokens — lives in
tests/test_fleet.py::test_wedge_mid_stream_recovers_and_resumes_bit_identical.
"""

import os
import threading
import time

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.telemetry import GenerationJournal, request_key
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import new_device
from gofr_tpu.tpu.introspect import ENGINE_STATES, EngineState, StallWatchdog
from gofr_tpu.tpu.recovery import HUNG_DETAIL, RecoverySupervisor

PROMPT = [5, 6, 7]


def _echo_device(registry=None, **env):
    cfg = {
        "MODEL_NAME": "echo",
        "WATCHDOG_DISPATCH_TIMEOUT_S": "0.2",
        "RECOVERY_BACKOFF_S": "0.05",
    }
    cfg.update(env)
    old = {k: os.environ.get(k) for k in cfg}
    os.environ.update(cfg)
    try:
        return new_device(
            EnvConfig(), MockLogger(Level.FATAL), registry or Registry()
        )
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def _wait(cond, timeout=15.0, message="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {message}")
        time.sleep(0.02)


def _wedge(device, release):
    """Arm a latch stall and kick a sacrificial request into it."""
    device.runner.stall_hook = lambda: release.wait(30)

    def kick():
        try:
            device.generate([9], max_new_tokens=2)
        except Exception:
            pass  # the wedged dispatch fails by design

    thread = threading.Thread(target=kick, name="test-wedge-kick")
    thread.start()
    return thread


# -- the incident loop ---------------------------------------------------------

def test_wedge_recovers_to_serving_without_restart():
    registry = Registry()
    device = _echo_device(registry)
    try:
        assert "recovering" in ENGINE_STATES
        # the postmortem hook fires BEFORE quarantine: the bundle must
        # still see the stalled watchdog entries (evidence order)
        hook_evidence: list = []
        device.recovery.postmortem = lambda detail: hook_evidence.append(
            device.watchdog.snapshot()
        )
        release = threading.Event()
        kicker = _wedge(device, release)
        # wedged/recovering can flash by in milliseconds (the echo
        # rebuild is nearly instant): wait on the incident counter, and
        # read the transition history for the state walk below
        _wait(lambda: device.engine.state == "serving"
              and device.recovery.snapshot()["recoveries"].get("recovered"),
              message="recovery")
        release.set()
        kicker.join(10)
        snap = device.recovery.snapshot()
        assert snap["recoveries"] == {"recovered": 1}
        assert snap["last_outcome"] == "recovered"
        assert snap["last_mttr_s"] is not None and snap["last_mttr_s"] >= 0
        counter = registry.counter(
            "gofr_tpu_engine_recoveries_total", labels=("outcome",)
        )
        assert counter.value(outcome="recovered") == 1.0
        # the state history reads like the contract: wedged ->
        # recovering -> warming -> serving
        states = [h["state"] for h in device.engine.snapshot()["history"]]
        wedge_at = states.index("wedged")
        assert states[wedge_at:] == ["wedged", "recovering", "warming",
                                     "serving"]
        # the quarantined ghost no longer poisons the watchdog: the
        # rebuilt stack serves and a fresh request flows
        assert device.watchdog.snapshot()["watching"] == []
        # ...but its evidence survives (snapshot + postmortem order)
        assert device.watchdog.snapshot()["quarantined"]
        assert hook_evidence and any(
            w["stalled"] for w in hook_evidence[0]["watching"]
        )
        assert device.generate(PROMPT, max_new_tokens=6) == [5, 6, 7, 5, 6, 7]
        # /admin/engine carries the incident
        snapshot = device.engine_snapshot()
        assert snapshot["recovery"]["recoveries"]["recovered"] == 1
        assert snapshot["journal"]["interruptions"] >= 1
    finally:
        device.close()


def test_recovery_disabled_keeps_wedged_terminal():
    device = _echo_device(RECOVERY_ENABLED="off")
    try:
        release = threading.Event()
        kicker = _wedge(device, release)
        _wait(lambda: device.engine.state == "wedged", message="wedge")
        time.sleep(0.3)  # recovery must NOT kick in
        assert device.engine.state == "wedged"
        assert device.recovery.snapshot()["recoveries"] == {}
        release.set()  # the stall resolves -> the watchdog recovers it
        kicker.join(10)
        _wait(lambda: device.engine.state == "serving",
              message="legacy stall-resolution recovery")
    finally:
        device.close()


class _FakeDevice:
    """Engine + watchdog real; recover() scripted — the unit harness
    for attempt/backoff/terminal bookkeeping."""

    def __init__(self, fail_times=0, hang=False):
        self.engine = EngineState()
        self.watchdog = StallWatchdog(self.engine)
        self._closed = False
        self.fail_times = fail_times
        self.hang = hang
        self.calls = 0

    def recover(self, detail=""):
        self.calls += 1
        if self.hang:
            time.sleep(60)
        if self.calls <= self.fail_times:
            raise RuntimeError(f"rebuild {self.calls} failed")
        self.engine.transition("serving", detail)


def test_bounded_attempts_with_backoff_then_recovered():
    device = _FakeDevice(fail_times=2)
    supervisor = RecoverySupervisor(
        device, max_attempts=3, backoff_s=0.02, backoff_max_s=0.1,
    )
    device.engine.transition("serving")
    device.engine.transition("wedged", "test")
    _wait(lambda: supervisor.snapshot()["state"] == "idle"
          and supervisor.snapshot()["recoveries"].get("recovered") == 1,
          message="third attempt recovers")
    snap = supervisor.snapshot()
    assert device.calls == 3
    assert snap["attempts"] == 3
    assert snap["recoveries"]["failed_attempt"] == 2
    supervisor.close()


def test_exhausted_attempts_fail_terminally():
    device = _FakeDevice(fail_times=99)
    supervisor = RecoverySupervisor(
        device, max_attempts=2, backoff_s=0.02, backoff_max_s=0.05,
    )
    device.engine.transition("serving")
    device.engine.transition("wedged", "test")
    _wait(lambda: supervisor.snapshot()["state"] == "exhausted",
          message="exhaustion")
    assert device.engine.state == "failed"
    assert device.calls == 2
    assert supervisor.snapshot()["recoveries"]["exhausted"] == 1
    # a later wedge does NOT restart the loop: terminal means terminal
    device.engine.transition("wedged", "again")
    time.sleep(0.1)
    assert device.calls == 2
    # ...until the operator resets the verdict
    supervisor.reset()
    device.engine.transition("serving")
    device.fail_times = 0
    device.engine.transition("wedged", "after reset")
    _wait(lambda: supervisor.snapshot()["recoveries"].get("recovered") == 1,
          message="post-reset recovery")
    supervisor.close()


def test_hung_rebuild_is_terminal_with_restart_verdict():
    device = _FakeDevice(hang=True)
    supervisor = RecoverySupervisor(
        device, max_attempts=3, backoff_s=0.01, attempt_timeout_s=0.1,
    )
    device.engine.transition("serving")
    device.engine.transition("wedged", "test")
    _wait(lambda: supervisor.snapshot()["state"] == "hung", message="hang")
    assert device.engine.state == "failed"
    assert HUNG_DETAIL in (device.engine.snapshot()["detail"] or "")
    assert supervisor.snapshot()["recoveries"]["timeout"] == 1
    supervisor.close()


def test_watchdog_quarantine_forgets_flagged_entries():
    engine = EngineState()
    watchdog = StallWatchdog(engine, timeout_s=0.05)
    engine.transition("serving")
    release = threading.Event()

    def stuck():
        with watchdog.watch("decode_chunk", 7):
            release.wait(10)

    thread = threading.Thread(target=stuck, name="test-stuck")
    thread.start()
    _wait(lambda: engine.state == "degraded", message="stall flag")
    quarantined = watchdog.quarantine()
    assert [q["dispatch_id"] for q in quarantined] == [7]
    assert watchdog.snapshot()["watching"] == []
    # the ghost finishing later must not flip a recovered engine
    engine.transition("serving", "rebuilt")
    release.set()
    thread.join(5)
    assert engine.state == "serving"
    watchdog.close()


# -- the generation journal ----------------------------------------------------

def test_request_key_separates_seeds_prompts_and_budgets():
    from gofr_tpu.ops.sampling import Sampler

    base = request_key("m", [1, 2, 3], 8, Sampler(seed=7))
    assert base == request_key("m", [1, 2, 3], 8, Sampler(seed=7))
    assert base != request_key("m", [1, 2, 3], 8, Sampler(seed=8))
    assert base != request_key("m", [1, 2, 4], 8, Sampler(seed=7))
    assert base != request_key("m", [1, 2, 3], 9, Sampler(seed=7))
    assert base != request_key("m2", [1, 2, 3], 8, Sampler(seed=7))
    assert base != request_key("m", [1, 2, 3], 8, Sampler(seed=7),
                               stop_tokens={5})


def test_journal_interrupt_claim_and_bounds():
    journal = GenerationJournal(capacity=2, max_tokens=4)
    entry = journal.start("k1", "echo", 8, seeded=True, deterministic=True)
    entry.append(11)
    entry.append(12)
    journal.interrupt(entry, "pool died")
    assert journal.stats()["interrupted"] == 1
    # a claim needs enough journaled tokens to cover the offset
    assert journal.claim("k1", min_tokens=3) is None
    claimed = journal.claim("k1", min_tokens=2)
    assert claimed is entry and claimed.status == "resumed"
    assert journal.claim("k1") is None  # single-use

    # token cap: a truncated entry refuses resume (it cannot prove
    # bit-identity past its cap) but keeps forensics
    full = journal.start("k2", "echo", 8, seeded=True, deterministic=True)
    for token in range(6):
        full.append(token)
    assert full.truncated and len(full.tokens) == 4
    journal.interrupt(full, "wedge")
    assert journal.claim("k2") is None

    # capacity bound: oldest interrupted entries evict first
    for i in range(3, 6):
        e = journal.start(f"k{i}", "echo", 8, seeded=True, deterministic=True)
        journal.interrupt(e, "wedge")
    assert journal.stats()["interrupted"] == 2
    assert journal.claim("k3") is None  # evicted
    assert journal.claim("k5") is not None


def test_clean_completion_and_client_abort_leave_no_interrupted_entry():
    device = _echo_device()
    try:
        device.generate(PROMPT, max_new_tokens=4)
        assert device.journal.stats()["interrupted"] == 0
        it = device.generate_stream(PROMPT, max_new_tokens=8)
        next(it)
        it.close()  # client walks away: a CANCELLED request, not an incident
        _wait(lambda: device.journal.stats()["active"] == 0,
              message="stream settles")
        assert device.journal.stats()["interrupted"] == 0
    finally:
        device.close()


# -- resume bit-identity: echo -------------------------------------------------

def test_echo_resume_teacher_forced_bit_identical():
    registry = Registry()
    device = _echo_device(registry, ECHO_STEP_MS="5")
    try:
        full = device.generate(PROMPT, max_new_tokens=12)
        # manufacture a mid-stream interruption at token 7
        key = device._journal_key(PROMPT, 12, None, device.default_stop_ids,
                                  None)
        entry = device.journal.start(key, "echo", 12, seeded=False,
                                     deterministic=True)
        for token in full[:7]:
            entry.append(token)
        device.journal.interrupt(entry, "injected wedge")
        # the client saw 5 of the 7 journaled tokens
        resumed = list(device.generate_stream(PROMPT, max_new_tokens=12,
                                              resume_from=5))
        assert full[:5] + resumed == full
        modes = registry.counter(
            "gofr_tpu_journal_resumes_total", labels=("mode",)
        ).data()
        assert modes.get(("teacher_forced",)) == 1.0
    finally:
        device.close()


def test_echo_resume_replay_without_journal_entry():
    registry = Registry()
    device = _echo_device(registry)
    try:
        full = device.generate(PROMPT, max_new_tokens=10)
        # no interrupted entry (another replica's journal): full replay
        # with suppression still resumes bit-identically
        resumed = list(device.generate_stream(PROMPT, max_new_tokens=10,
                                              resume_from=4))
        assert full[:4] + resumed == full
        modes = registry.counter(
            "gofr_tpu_journal_resumes_total", labels=("mode",)
        ).data()
        assert modes.get(("replayed",)) == 1.0
    finally:
        device.close()


def test_resume_refuses_nondeterministic_and_logprobs():
    from gofr_tpu.errors import InvalidParamError
    from gofr_tpu.ops.sampling import Sampler

    device = _echo_device()
    try:
        with pytest.raises(InvalidParamError):
            device.generate_stream(
                PROMPT, 8, sampler=Sampler(temperature=0.9), resume_from=2
            )
        with pytest.raises(InvalidParamError):
            device.generate_stream(PROMPT, 8, logprobs=True, resume_from=2)
        # seeded sampled IS deterministic: allowed
        it = device.generate_stream(
            PROMPT, 8, sampler=Sampler(temperature=0.9, seed=3), resume_from=2
        )
        assert len(list(it)) == 6
    finally:
        device.close()


# -- resume bit-identity: tiny transformer (the real teacher-forced path) ------

@pytest.fixture(scope="module")
def tiny_device():
    device = _echo_device(
        MODEL_NAME="tiny", MODEL_BUCKETS="64", DECODE_SLOTS="2",
        PREFIX_CACHE="2", BATCH_MAX_SIZE="2", BATCH_TIMEOUT_MS="1",
        WATCHDOG_DISPATCH_TIMEOUT_S="off",
    )
    yield device
    device.close()


def test_tiny_model_teacher_forced_resume_bit_identical(tiny_device):
    """The real thing: a greedy tiny-transformer generation interrupted
    at token 6 resumes via teacher-forced prefill over prompt+emitted —
    THROUGH the paged-KV path (block aliasing makes the re-prefill
    nearly copy-free) — and the resumed stream is bit-identical to the
    uninterrupted run."""
    device = tiny_device
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    full = device.generate(prompt, max_new_tokens=10)
    assert len(full) == 10
    key = device._journal_key(prompt, 10, None, device.default_stop_ids, None)
    entry = device.journal.start(key, "tiny", 10, seeded=False,
                                 deterministic=True, prior=full[:6])
    device.journal.interrupt(entry, "injected wedge")

    resumed = list(device.generate_stream(prompt, max_new_tokens=10,
                                          resume_from=4))
    assert full[:4] + resumed == full  # zero missing, zero duplicated


def test_tiny_model_seeded_sampled_resume_replays_bit_identical(tiny_device):
    """Seeded SAMPLED requests cannot teacher-force (the per-chunk RNG
    schedule is position-aligned to the original decode) — they resume
    by full deterministic replay with the delivered prefix suppressed,
    still bit-identical."""
    from gofr_tpu.ops.sampling import Sampler

    device = tiny_device
    prompt = [2, 7, 1, 8, 2, 8]
    full = device.generate(prompt, max_new_tokens=8,
                           sampler=Sampler(temperature=0.8, seed=11))
    resumed = list(device.generate_stream(
        prompt, max_new_tokens=8,
        sampler=Sampler(temperature=0.8, seed=11), resume_from=3,
    ))
    assert full[:3] + resumed == full


# -- readiness evidence + probation (satellites) -------------------------------

def test_ready_body_carries_recovery_evidence():
    from gofr_tpu.handler import _attach_recovery_evidence

    device = _FakeDevice(fail_times=99)
    supervisor = RecoverySupervisor(
        device, max_attempts=2, backoff_s=5.0, backoff_max_s=5.0,
    )
    device.recovery = supervisor
    state: dict = {}
    _attach_recovery_evidence(device, state)
    assert state == {}  # never wedged: ready body unchanged
    device.engine.transition("serving")
    device.engine.transition("wedged", "test")
    _wait(lambda: supervisor.snapshot()["state"] == "waiting_backoff",
          message="backoff window")
    _attach_recovery_evidence(device, state)
    assert state["recovery"]["state"] == "waiting_backoff"
    assert state["recovery"]["attempts"] == 1
    assert state["recovery"]["max_attempts"] == 2
    assert state["recovery"]["backoff_in_s"] > 0
    assert state["recovery"]["last_outcome"] == "failed_attempt"
    supervisor.close()


def test_probation_treats_recovering_as_coming_back():
    from gofr_tpu.fleet.replica import (
        HEALTHY,
        OUT,
        PROBATION,
        Replica,
        ReplicaSet,
    )

    replica = Replica("r0", "http://127.0.0.1:1", MockLogger(Level.FATAL))
    replica_set = ReplicaSet([replica], MockLogger(Level.FATAL),
                             out_after=2, probation_probes=2)
    # a recovering 503 parks a HEALTHY replica in probation, never
    # hard-out; plain failures still drop it to OUT
    replica_set._apply_probe(replica, False, recovering=True)
    assert replica.state == HEALTHY  # first fail: below out_after
    replica_set._apply_probe(replica, False, recovering=True)
    assert replica.state == PROBATION
    replica_set._apply_probe(replica, False, recovering=True)
    assert replica.state == PROBATION  # holds, not OUT
    replica_set._apply_probe(replica, False, recovering=False)
    assert replica.state == OUT  # hard failure while out: hard-out
    replica_set._apply_probe(replica, False, recovering=True)
    assert replica.state == PROBATION  # coming back again
    replica_set._apply_probe(replica, True)
    replica_set._apply_probe(replica, True)
    assert replica.state == HEALTHY

    # the verdict parser: engine state or active recovery block
    verdict = ReplicaSet._recovering_verdict
    assert verdict(b'{"state": "recovering", "detail": "attempt 1/3"}')
    assert verdict(b'{"state": "warming", "recovery": {"state": "recovering"}}')
    assert verdict(
        b'{"state": "wedged", "recovery": {"state": "waiting_backoff"}}'
    )
    assert not verdict(
        b'{"state": "failed", "recovery": {"state": "exhausted"}}'
    )
    assert not verdict(b'{"state": "wedged", "detail": "x"}')
    assert not verdict(b"not json")
