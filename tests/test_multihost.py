"""Multi-host runtime without a cluster: two REAL processes join a local
coordinator on the CPU backend and run one cross-host collective — the
reference's fake-backend test strategy (SURVEY.md §4) applied to the
distributed bootstrap (the framework's NCCL/MPI-equivalent)."""

import os
import socket
import subprocess
import sys
import textwrap
import pytest

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # one device per process
    sys.path.insert(0, "@@REPO@@")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from gofr_tpu.config import EnvConfig
    from gofr_tpu.parallel import multihost

    cfg = EnvConfig()
    assert multihost.init_from_config(cfg) is True
    assert multihost.init_from_config(cfg) is True  # idempotent
    info = multihost.process_info()
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 2 * info["local_devices"], info
    total = multihost.global_psum_check()
    assert total == info["global_devices"], (total, info)
    print(f"rank {info['process_id']} OK total={total}", flush=True)
    multihost.shutdown()
    """
)


def test_two_process_runtime_and_collective(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(WORKER.replace("@@REPO@@", REPO))
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            TPU_COORDINATOR=f"127.0.0.1:{port}",
            TPU_NUM_PROCESSES="2",
            TPU_PROCESS_ID=str(rank),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outputs.append(out)
    finally:
        # a rank that died pre-join leaves its peer blocked in
        # initialize() forever — never leak it into the CI runner
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=10)
    for rank, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert "OK total=2" in out, out


def test_single_host_is_noop():
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.parallel import multihost

    assert "TPU_COORDINATOR" not in os.environ
    assert multihost.init_from_config(EnvConfig()) is False
