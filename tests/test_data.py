"""Training data pipeline: dataset crops, determinism, device prefetch."""

import numpy as np
import pytest

from gofr_tpu.tokenizer import Tokenizer
from gofr_tpu.training.data import TokenDataset, corpus_to_bin, prefetch_to_device


def test_corpus_to_bin_and_memmap(tmp_path):
    path = str(tmp_path / "tokens.bin")
    n = corpus_to_bin("hello world, " * 50, Tokenizer.byte_level(), path)
    ds = TokenDataset(path, seq_len=16, batch_size=4)
    assert len(ds) == n
    b = ds.batch(0)
    assert b.shape == (4, 16)
    assert b.dtype == np.int32
    assert (b >= 0).all() and (b < 256).all()


def test_batches_deterministic_by_seed_and_step():
    tokens = np.arange(1000) % 250
    a = TokenDataset(tokens, seq_len=8, batch_size=2, seed=5)
    b = TokenDataset(tokens, seq_len=8, batch_size=2, seed=5)
    c = TokenDataset(tokens, seq_len=8, batch_size=2, seed=6)
    np.testing.assert_array_equal(a.batch(3), b.batch(3))
    assert not np.array_equal(a.batch(3), a.batch(4))
    assert not np.array_equal(a.batch(3), c.batch(3))
    # crops are contiguous windows of the stream
    row = a.batch(0)[0]
    np.testing.assert_array_equal(np.diff(row) % 250, np.ones(7))


def test_dataset_validation():
    with pytest.raises(ValueError, match="1-D"):
        TokenDataset(np.zeros((3, 3), np.int32), seq_len=2, batch_size=1)
    with pytest.raises(ValueError, match="seq_len"):
        TokenDataset(np.zeros(4, np.int32), seq_len=8, batch_size=1)


def test_prefetch_to_device_preserves_order_and_values():
    ds = TokenDataset(np.arange(500) % 200, seq_len=4, batch_size=2, seed=1)
    want = [ds.batch(i) for i in range(5)]
    it = prefetch_to_device(iter(want), size=2)
    got = [np.asarray(x) for x in it]
    assert len(got) == 5
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


def test_prefetch_applies_sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for

    mesh = make_mesh(mesh_shape_for(8, fsdp=4))  # dp=2 x fsdp=4
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    ds = TokenDataset(np.arange(500) % 200, seq_len=4, batch_size=8)
    it = prefetch_to_device(ds.batches(0), size=1, sharding=sharding)
    arr = next(it)
    assert arr.sharding == sharding
    it.close()


def test_prefetch_propagates_errors():
    def bad():
        yield np.zeros((2, 2), np.int32)
        raise RuntimeError("disk on fire")

    it = prefetch_to_device(bad(), size=1)
    next(it)
    with pytest.raises(RuntimeError, match="disk on fire"):
        list(it)


def test_prefetch_close_stops_producer():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield np.full((1, 1), i, np.int32)

    it = prefetch_to_device(gen(), size=1)
    next(it)
    it.close()
    import time

    # poll until the count stabilizes (a slow-to-park producer thread
    # must not flake a fixed-sleep snapshot), then require it stays put
    deadline = time.monotonic() + 5.0
    n = len(produced)
    streak = 0
    while streak < 4 and time.monotonic() < deadline:
        time.sleep(0.05)
        m = len(produced)
        streak = streak + 1 if m == n else 0
        n = m
    assert streak >= 4, "producer kept running after close"
    assert n < 100


def test_end_to_end_train_step_with_loader():
    import jax

    from gofr_tpu.models.transformer import TransformerConfig
    from gofr_tpu.training.trainer import (
        default_optimizer,
        init_train_state,
        make_train_step,
    )

    cfg = TransformerConfig(
        vocab_size=256, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
        hidden_dim=64, max_seq=32, dtype="float32", attn_impl="xla",
    )
    opt = default_optimizer(lr=1e-2)
    state = init_train_state(jax.random.key(0), cfg, opt)
    step_fn = make_train_step(cfg, opt)
    ds = TokenDataset(np.arange(2000) % 256, seq_len=16, batch_size=4)
    losses = []
    for i, batch in zip(range(3), prefetch_to_device(ds.batches(0), size=2)):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(x) for x in losses)
    assert int(state["step"]) == 3


def test_warmup_cosine_schedule_trains():
    import jax

    from gofr_tpu.models.transformer import TransformerConfig
    from gofr_tpu.training.trainer import (
        init_train_state,
        make_train_step,
        warmup_cosine_optimizer,
    )

    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
        hidden_dim=64, max_seq=32, dtype="float32", attn_impl="xla",
    )
    opt = warmup_cosine_optimizer(peak_lr=1e-2, total_steps=50, warmup_steps=5)
    state = init_train_state(jax.random.key(0), cfg, opt)
    step_fn = make_train_step(cfg, opt)
    tokens = np.random.RandomState(0).randint(1, 64, size=(4, 16))
    losses = []
    for _ in range(6):
        state, metrics = step_fn(state, np.asarray(tokens))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(x) for x in losses)
    assert losses[-1] < losses[0]  # warmup ramp still makes progress
    # the schedule is a pure function of step: mid-warmup LR is peak * 3/5
    import optax

    sched = optax.warmup_cosine_decay_schedule(0.0, 1e-2, 5, 50, 1e-3)
    assert float(sched(3)) == pytest.approx(1e-2 * 3 / 5)


def test_corpus_to_bin_large_vocab_dtype(tmp_path):
    from gofr_tpu.training.data import dtype_for_vocab

    class BigVocabTok:
        vocab_size = 100_000

        def encode(self, text):
            return [70_000, 99_999, 5]

    path = str(tmp_path / "big.bin")
    n = corpus_to_bin("x", BigVocabTok(), path)  # auto uint32
    assert n == 3
    ds = TokenDataset(path, seq_len=2, batch_size=1, dtype=np.uint32)
    assert int(ds.tokens[1]) == 99_999
    assert dtype_for_vocab(65536) == np.uint16
    assert dtype_for_vocab(65537) == np.uint32
    with pytest.raises(ValueError, match="uint32"):
        corpus_to_bin("x", BigVocabTok(), path, dtype=np.uint16)


def test_sidecar_dtype_auto_detected(tmp_path):
    class BigVocabTok:
        vocab_size = 100_000

        def encode(self, text):
            return [70_000, 2, 99_999, 5, 1, 2, 3, 4]

    path = str(tmp_path / "auto.bin")
    corpus_to_bin("x", BigVocabTok(), path)
    # NO dtype arg: the sidecar must prevent uint16 misinterpretation
    ds = TokenDataset(path, seq_len=4, batch_size=1)
    assert int(ds.tokens[0]) == 70_000
    assert int(ds.tokens[2]) == 99_999
