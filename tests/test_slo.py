"""SLO engine (gofr_tpu/slo.py) + bounded tenant metering
(telemetry.TenantLedger): unit semantics for target parsing, record
judging, the multi-window burn-rate latch, and the space-saving sketch,
plus the end-to-end spine on the no-JAX ``echo`` model — a deadline-miss
fault burst must trip the fast-burn page on ``/admin/slo/budget``,
``/admin/anomalies``, ``/metrics``, and the postmortem bundle, while a
healthy run raises ZERO alerts; and 5000 distinct tenants through the
serving surface must leave ``/metrics`` cardinality bounded while the
ledger's heavy hitters stay exact."""

import concurrent.futures
import hashlib
import json
import socket
import time
import urllib.error
import urllib.request

import pytest

from gofr_tpu.metrics import Registry
from gofr_tpu.slo import (
    DEFAULT_TARGETS,
    Objective,
    SloEngine,
    parse_targets,
)
from gofr_tpu.telemetry import FlightRecorder, TenantLedger, activate_tenant


# -- unit: SLO_TARGETS parsing ------------------------------------------------

def test_parse_default_targets():
    objectives = {o.id: o for o in parse_targets(DEFAULT_TARGETS)}
    assert set(objectives) == {
        "availability", "shed_rate", "tier9.availability",
    }
    assert objectives["availability"].budget == pytest.approx(0.001)
    assert objectives["shed_rate"].budget == pytest.approx(0.05)
    assert objectives["tier9.availability"].tier == 9
    assert objectives["tier9.availability"].budget == pytest.approx(0.0005)


def test_parse_scoped_and_latency_targets():
    objectives = {o.id: o for o in parse_targets(
        "model=echo:ttft_p95_ms=500; tier>=5:availability=0.99;"
        "tpot_p99_ms=40"
    )}
    assert set(objectives) == {
        "echo.ttft_p95_ms", "tier_ge5.availability", "tpot_p99_ms",
    }
    ttft = objectives["echo.ttft_p95_ms"]
    assert ttft.model == "echo"
    assert ttft.threshold_s == pytest.approx(0.5)
    assert ttft.budget == pytest.approx(0.05)  # p95 -> 5% may exceed
    assert objectives["tpot_p99_ms"].budget == pytest.approx(0.01)
    assert objectives["tier_ge5.availability"].tier_ge == 5


@pytest.mark.parametrize("spec", [
    "bogus=1",                      # unknown metric
    "availability",                 # no target
    "availability=lots",            # non-numeric target
    "availability=1.5",             # out of (0, 1)
    "ttft_p95_ms=-3",               # negative latency bound
    "tier=11:availability=0.9",     # tier out of 0..9
    "planet=mars:availability=0.9",  # unknown scope
    "model=:availability=0.9",      # empty model scope
    "tier=9:shed_rate=0.1",         # shed counters carry no scope
    "availability=0.9;availability=0.99",  # duplicate objective
])
def test_parse_rejects_malformed(spec):
    with pytest.raises(ValueError):
        parse_targets(spec)


# -- unit: Objective.judge ----------------------------------------------------

def _finished(recorder, status="ok", model="echo", priority=None,
              ttft_s=None, tokens_out=0):
    rec = recorder.start(model, "/test")
    if priority is not None:
        rec.priority = priority
    if ttft_s is not None:
        rec.t_first_token = rec.t_start + ttft_s
    rec.tokens_out = tokens_out
    recorder.finish(
        rec, status=status,
        error=RuntimeError("boom") if status == "error" else None,
    )
    return rec


def test_judge_availability_statuses_and_scopes():
    recorder = FlightRecorder(capacity=16)
    availability = Objective("availability", 0.999)
    assert availability.judge(_finished(recorder)) is False
    assert availability.judge(_finished(recorder, status="error")) is True
    assert availability.judge(
        _finished(recorder, status="deadline_exceeded")
    ) is True
    # a client hanging up is its verdict, not ours
    assert availability.judge(_finished(recorder, status="cancelled")) is None
    scoped = Objective("availability", 0.999, model="llama")
    assert scoped.judge(_finished(recorder, status="error")) is None
    tiered = Objective("availability", 0.999, tier=9)
    assert tiered.judge(_finished(recorder, status="error")) is None
    assert tiered.judge(
        _finished(recorder, status="error", priority=9)
    ) is True
    ge = Objective("availability", 0.999, tier_ge=5)
    assert ge.judge(_finished(recorder, status="error", priority=7)) is True
    assert ge.judge(_finished(recorder, status="error", priority=3)) is None


def test_judge_latency_bound_and_missing_measurement():
    recorder = FlightRecorder(capacity=16)
    bound = Objective("ttft_p95_ms", 200.0)
    assert bound.judge(_finished(recorder, ttft_s=0.05)) is False
    assert bound.judge(_finished(recorder, ttft_s=0.5)) is True
    # no first token + ok (e.g. an embeddings hit) = no sample
    assert bound.judge(_finished(recorder)) is None
    # no first token + deadline_exceeded IS a latency violation
    assert bound.judge(
        _finished(recorder, status="deadline_exceeded")
    ) is True


# -- unit: TenantLedger (space-saving sketch) ---------------------------------

def test_ledger_tracks_and_pages():
    ledger = TenantLedger(size=8)
    ledger.observe("t-a", requests=1, tokens_in=10, tokens_out=20)
    ledger.observe("t-a", requests=1, tokens_in=5, tokens_out=5)
    ledger.observe("t-b", requests=1, errors=1)
    ledger.shed("t-c")
    assert ledger.get("t-a")["tokens_out"] == 25
    assert ledger.get("t-b")["errors"] == 1
    assert ledger.get("t-c")["sheds"] == 1
    assert ledger.get("t-nope") is None
    top = ledger.top(2)
    assert top[0]["tenant"] == "t-a"  # most tokens
    totals = ledger.totals()
    assert totals["requests"] == 3
    assert totals["sheds"] == 1
    assert totals["tokens_in"] == 15


def test_ledger_eviction_conserves_sums_and_bounds_error():
    registry = Registry()
    ledger = TenantLedger(size=2, metrics=registry)
    ledger.observe("heavy", requests=5, tokens_in=50)
    ledger.observe("light", requests=1, tokens_in=2)
    ledger.observe("newcomer", requests=1)  # full table: evicts "light"
    assert ledger.get("light") is None
    assert ledger.get("heavy")["requests"] == 5  # heavy hitter untouched
    newcomer = ledger.get("newcomer")
    assert newcomer["requests"] == 1
    # classic space-saving bound: up to the evicted weight may belong
    # to ~other instead of this slot
    assert newcomer["err"] == 1
    stats = ledger.stats()
    assert stats["tracked"] == 2
    assert stats["evictions"] == 1
    assert stats["other"]["requests"] == 1
    assert stats["other"]["tokens_in"] == 2
    # sum conservation: totals never lose the evicted tenant's counts
    totals = ledger.totals()
    assert totals["requests"] == 7
    assert totals["tokens_in"] == 52
    assert registry.counter(
        "gofr_tpu_tenant_overflow_total"
    ).value() == 1.0
    assert registry.gauge(
        "gofr_tpu_tenants_tracked_entries"
    ).value() == 2.0


def test_ledger_heavy_hitters_exact_under_singleton_flood():
    """5000 distinct one-shot tenants churn a 64-slot table; the heavy
    hitters' counters must match a brute-force dict exactly (once their
    weight clears the churn floor they are never the eviction minimum)."""
    ledger = TenantLedger(size=64)
    brute: dict[str, int] = {}
    heavies = [f"heavy-{i}" for i in range(4)]
    for i in range(5000):
        if i % 10 == 0:
            tenant = heavies[(i // 10) % len(heavies)]
        else:
            tenant = f"one-shot-{i}"
        ledger.observe(tenant, requests=1, tokens_in=4, tokens_out=8)
        brute[tenant] = brute.get(tenant, 0) + 1
    stats = ledger.stats()
    assert stats["tracked"] == 64  # hard cardinality bound
    assert stats["evictions"] > 0
    top = {row["tenant"]: row for row in ledger.top(len(heavies))}
    assert set(top) == set(heavies)
    for tenant in heavies:
        assert top[tenant]["requests"] == brute[tenant]
        assert top[tenant]["tokens_out"] == brute[tenant] * 8
    # sum conservation across slots + ~other
    assert ledger.totals()["requests"] == 5000


def test_ledger_feeds_from_flight_recorder():
    ledger = TenantLedger(size=8)
    recorder = FlightRecorder(capacity=8, tenants=ledger)
    activate_tenant("key-abc")
    try:
        rec = recorder.start("echo", "/v1/completions", tokens_in=7)
        rec.tokens_out = 3
        recorder.finish(rec)
        bad = recorder.start("echo", "/v1/completions")
        recorder.finish(bad, status="deadline_exceeded")
    finally:
        activate_tenant(None)
    slot = ledger.get("key-abc")
    assert slot["requests"] == 2
    assert slot["tokens_in"] == 7
    assert slot["tokens_out"] == 3
    assert slot["deadline_misses"] == 1


# -- unit: SloEngine burn windows + latch -------------------------------------

def _engine(recorder, targets="availability=0.999", **kwargs):
    """Tiny distinct windows (1s/2s/3s/4s) so one test-local burst sits
    inside every window; alerts stay assertable without sleeps."""
    kwargs.setdefault("fast_s", 1.0)
    kwargs.setdefault("fast_long_s", 2.0)
    kwargs.setdefault("slow_s", 3.0)
    kwargs.setdefault("slow_long_s", 4.0)
    return SloEngine(recorder, targets=targets, **kwargs)


def test_engine_healthy_run_raises_zero_alerts():
    recorder = FlightRecorder(capacity=32)
    for _ in range(10):
        _finished(recorder)
    engine = _engine(recorder)
    report = engine.evaluate()
    row = report["objectives"][0]
    assert row["windows"]["1s"]["total"] == 10
    assert row["windows"]["1s"]["bad"] == 0
    assert row["windows"]["1s"]["burn"] == 0.0
    assert row["budget_remaining"] == 1.0
    assert row["alerting"] == {"fast": False, "slow": False}
    assert report["alerts_total"] == 0
    assert engine.ring.events(kind="slo") == []


def test_engine_burst_latches_one_alert_per_excursion():
    registry = Registry()
    recorder = FlightRecorder(capacity=64)
    for _ in range(5):
        _finished(recorder)
    bad = [_finished(recorder, status="error") for _ in range(5)]
    engine = _engine(recorder, metrics=registry)
    report = engine.evaluate()
    row = report["objectives"][0]
    # 5 bad of 10 against a 0.001 budget: burning 500x on every window
    assert row["windows"]["1s"]["bad_fraction"] == pytest.approx(0.5)
    assert row["windows"]["1s"]["burn"] == pytest.approx(500.0)
    assert row["alerting"] == {"fast": True, "slow": True}
    assert row["budget_remaining"] == pytest.approx(1.0 - 500.0)
    events = engine.ring.events(kind="slo")
    assert {e["cause"] for e in events} == {"slo_fast_burn", "slo_slow_burn"}
    assert all(e["objective"] == "availability" for e in events)
    assert report["alerts_total"] == 2
    counter = registry.counter(
        "gofr_tpu_slo_burn_alerts_total", labels=("objective", "window")
    )
    assert counter.value(objective="availability", window="fast") == 1.0
    # still burning: the latch holds, no duplicate page
    engine.evaluate()
    assert engine.evaluate()["alerts_total"] == 2
    assert len(engine.ring.events(kind="slo")) == 2
    # the burst ages out of every window: burn clears, latch re-arms
    for rec in bad:
        rec.t_done -= 60.0
    cleared = engine.evaluate()["objectives"][0]
    assert cleared["alerting"] == {"fast": False, "slow": False}
    # a second excursion pages again
    for _ in range(5):
        _finished(recorder, status="error")
    assert engine.evaluate()["alerts_total"] == 4
    assert counter.value(objective="availability", window="fast") == 2.0
    # the gauges tracked the whole ride
    burn_gauge = registry.gauge(
        "gofr_tpu_slo_burn_rate", labels=("objective", "window")
    )
    assert burn_gauge.value(objective="availability", window="1s") > 100.0


def test_engine_no_traffic_spends_no_budget():
    engine = _engine(FlightRecorder(capacity=8))
    row = engine.evaluate()["objectives"][0]
    assert row["windows"]["4s"]["total"] == 0
    assert row["budget_remaining"] == 1.0
    assert row["alerting"] == {"fast": False, "slow": False}


def test_engine_shed_rate_from_timebase_counters():
    from gofr_tpu.timebase import TimebaseSampler

    registry = Registry()
    shed = registry.counter(
        "gofr_tpu_brownout_shed_total", labels=("priority",)
    )
    sampler = TimebaseSampler(
        registry, interval_s=0.05, window_s=30, start=False
    )
    sampler.sample_now()
    shed.inc(30, priority="0")
    sampler.sample_now()
    recorder = FlightRecorder(capacity=256)
    engine = _engine(
        recorder, targets="shed_rate=0.05", timebase=sampler,
    )
    row = engine.evaluate()["objectives"][0]
    # 30 sheds, 0 completions: shed fraction 1.0 -> burning 20x budget
    stats = row["windows"]["1s"]
    assert stats["bad"] == 30
    assert stats["total"] == 30
    assert stats["bad_fraction"] == pytest.approx(1.0)
    assert stats["burn"] == pytest.approx(20.0)
    assert row["alerting"] == {"fast": True, "slow": True}
    # completions dilute the rate: 30 sheds / (30 + 90) demand = 25%
    for _ in range(90):
        _finished(recorder)
    diluted = engine.evaluate()["objectives"][0]["windows"]["1s"]
    assert diluted["bad_fraction"] == pytest.approx(0.25)


def test_engine_headline_compacts_the_report():
    recorder = FlightRecorder(capacity=32)
    for _ in range(5):
        _finished(recorder)
    for _ in range(5):
        _finished(recorder, status="error")
    # shed_rate with no timebase wired never burns — the quiet second
    # objective the headline must NOT list as alerting
    engine = _engine(
        recorder, targets="availability=0.999;shed_rate=0.5",
    )
    engine.evaluate()
    headline = engine.headline()
    assert headline["objectives"] == 2
    assert headline["worst_objective"] == "availability"
    assert headline["worst_burn"] == pytest.approx(500.0)
    assert headline["alerting"] == ["availability"]
    assert headline["budget_remaining_min"] == pytest.approx(-499.0)
    assert headline["alerts_total"] == 2


def test_engine_rejects_bad_window_config():
    recorder = FlightRecorder(capacity=4)
    with pytest.raises(ValueError, match="windows"):
        SloEngine(recorder, fast_s=10, fast_long_s=5)
    with pytest.raises(ValueError, match="threshold"):
        SloEngine(recorder, fast_rate=0)
    with pytest.raises(ValueError, match="INTERVAL"):
        SloEngine(recorder, interval_s=0)


def test_shed_verdict_echoes_hashed_tenant():
    """A 429's error body quotes the hashed tenant id the admission
    gate derived — the key a shed client uses to find itself on
    /admin/tenants and /admin/requests?tenant=."""
    from gofr_tpu.http.responder import respond

    class Shed(Exception):
        status_code = 429
        retry_after_s = 1.0
        tenant = "key-0123456789abcdef"

    response = respond(None, Shed("brownout shed"))
    assert response.status == 429
    payload = json.loads(response.body)["error"]
    assert payload["tenant"] == "key-0123456789abcdef"
    assert "brownout" in payload["message"]
    # and an untenanted error body stays exactly as before
    class Plain(Exception):
        status_code = 400

    bare = json.loads(respond(None, Plain("nope")).body)["error"]
    assert "tenant" not in bare


# -- e2e: the echo app --------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def slo_app(tmp_path_factory):
    """Echo-model app with the OpenAI routes, a small tenant table (64
    slots — the 5k-tenant flood must churn it), and a lazy SLO thread
    (evaluation happens on every /admin/slo/budget read)."""
    import os

    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    port = _free_port()
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
           "MODEL_NAME": "echo", "TOKENIZER": "byte",
           "BATCH_MAX_SIZE": "8", "BATCH_TIMEOUT_MS": "1",
           "ECHO_STEP_MS": "1", "FLIGHT_SLOW_MS": "60000",
           "FLIGHT_RECORDER_SIZE": "8192",
           "TENANT_LEDGER_SIZE": "64",
           "SLO_EVAL_INTERVAL_S": "3600",
           "GRPC_PORT": str(_free_port())}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    os.chdir(tmp_path_factory.mktemp("slo_e2e"))
    try:
        app = gofr_tpu.new()
    finally:
        os.chdir(cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    register_openai_routes(app)
    app.start()
    yield app, f"http://127.0.0.1:{port}"
    app.shutdown()


def _post(base, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        base + "/v1/completions", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())["data"]


def _metrics(base):
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        return resp.read().decode()


def _hashed(authorization):
    digest = hashlib.sha256(authorization.encode("utf-8")).hexdigest()
    return "key-" + digest[:16]


def test_e2e_healthy_run_zero_alerts(slo_app):
    app, base = slo_app
    for _ in range(6):
        status, _ = _post(
            base, {"prompt": [1, 2, 3], "max_tokens": 2, "temperature": 0},
            headers={"Authorization": "Bearer healthy-key"},
        )
        assert status == 200
    budget = _get(base, "/admin/slo/budget")
    assert budget["targets"] == DEFAULT_TARGETS
    assert {r["objective"] for r in budget["objectives"]} == {
        "availability", "shed_rate", "tier9.availability",
    }
    for row in budget["objectives"]:
        assert row["alerting"] == {"fast": False, "slow": False}
        assert row["budget_remaining"] == 1.0
    assert budget["alerts_total"] == 0
    assert budget["recent_alerts"] == []
    # the default window labels are the gauge's stable label values
    avail = next(r for r in budget["objectives"]
                 if r["objective"] == "availability")
    assert set(avail["windows"]) == {"5m", "1h", "6h", "3d"}
    assert avail["windows"]["5m"]["total"] >= 6
    # headline surfaces: /admin/overview + the fleet-facing snapshot
    over = _get(base, "/admin/overview")
    assert over["slo_budget"]["alerting"] == []
    assert over["slo_budget"]["objectives"] == 3
    assert over["tenants"]["tracked"] >= 1
    engine = _get(base, "/admin/engine")
    assert engine["slo"]["alerts_total"] == 0
    assert engine["tenants"]["tracked"] >= 1


def test_e2e_tenant_metering_and_request_filter(slo_app):
    app, base = slo_app
    auth = "Bearer metered-key"
    tenant = _hashed(auth)
    for _ in range(3):
        _post(base, {"prompt": [1, 2, 3, 4], "max_tokens": 2,
                     "temperature": 0},
              headers={"Authorization": auth})
    page = _get(base, "/admin/tenants")
    assert page["size"] == 64
    mine = [r for r in page["tenants"] if r["tenant"] == tenant]
    assert mine and mine[0]["requests"] >= 3
    assert mine[0]["tokens_in"] >= 12
    assert mine[0]["tokens_out"] >= 6
    # single-tenant lookup + the hashed id never echoes the raw key
    one = _get(base, f"/admin/tenants?tenant={tenant}")["tenant"]
    assert one["requests"] >= 3
    assert "metered-key" not in json.dumps(page)
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(base, "/admin/tenants?tenant=key-ffffffffffffffff")
    assert err.value.code == 404
    # /admin/requests?tenant= ranks only this tenant's flights
    records = _get(base, f"/admin/requests?tenant={tenant}")["requests"]
    assert len(records) >= 3
    assert all(r["tenant"] == tenant for r in records)
    assert _get(
        base, "/admin/requests?tenant=key-ffffffffffffffff"
    )["requests"] == []


def test_e2e_fault_burst_pages_on_every_surface(slo_app):
    """Acceptance: one deadline-miss burst -> slo_fast_burn visible on
    /admin/slo/budget, /admin/anomalies, /metrics, and in a postmortem
    bundle, with the misses metered to the offending tenant."""
    app, base = slo_app
    auth = "Bearer bursty-key"
    tenant = _hashed(auth)
    for _ in range(10):
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base, {"prompt": [1, 2], "max_tokens": 2,
                         "temperature": 0},
                  headers={"Authorization": auth,
                           "X-Request-Deadline-Ms": "1"})
        assert err.value.code == 504
    budget = _get(base, "/admin/slo/budget")
    avail = next(r for r in budget["objectives"]
                 if r["objective"] == "availability")
    assert avail["windows"]["5m"]["bad"] >= 10
    assert avail["alerting"]["fast"] is True
    assert budget["alerts_total"] >= 2  # fast page + slow ticket
    causes = {e["cause"] for e in budget["recent_alerts"]}
    assert "slo_fast_burn" in causes
    # same ring the dispatch watchtower uses
    anomalies = _get(base, "/admin/anomalies")
    assert "slo_fast_burn" in {a["cause"] for a in anomalies["anomalies"]}
    # exposition: the latched excursion counter
    text = _metrics(base)
    line = next(
        ln for ln in text.splitlines()
        if ln.startswith("gofr_tpu_slo_burn_alerts_total{")
        and 'objective="availability"' in ln and 'window="fast"' in ln
    )
    assert float(line.rsplit(" ", 1)[1]) >= 1
    # the tenant wore its deadline misses
    slot = _get(base, f"/admin/tenants?tenant={tenant}")["tenant"]
    assert slot["deadline_misses"] >= 10
    # the black-box bundle carries the whole ledger
    req = urllib.request.Request(
        base + "/admin/postmortem",
        data=json.dumps({"detail": "slo burn drill"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        bundle_path = json.loads(resp.read())["data"]["path"]
    bundle = json.load(open(bundle_path))
    assert bundle["slo_budget"]["alerts_total"] >= 2
    assert any(r["tenant"] == tenant
               for r in bundle["tenants"]["tenants"])
    assert "slo_fast_burn" in {a["cause"] for a in bundle["anomalies"]}
    # the overview headline flips too
    over = _get(base, "/admin/overview")
    assert "availability" in over["slo_budget"]["alerting"]


def test_e2e_5k_tenants_bounded_cardinality(slo_app):
    """5000 distinct API keys through the serving surface: /metrics
    must stay bounded (no per-tenant series, no dropped-series
    pressure) while the ledger keeps the heavy hitters exact."""
    app, base = slo_app
    heavies = [f"Bearer vip-{i}" for i in range(3)]
    payload = json.dumps(
        {"prompt": [1], "max_tokens": 1, "temperature": 0}
    ).encode()

    def fire(auth):
        req = urllib.request.Request(
            base + "/v1/completions", data=payload,
            headers={"Content-Type": "application/json",
                     "Authorization": auth},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()
        return auth

    brute: dict[str, int] = {}
    plan = []
    for i in range(5000):
        auth = heavies[i % 3] if i % 10 == 0 else f"Bearer scan-{i}"
        plan.append(auth)
        key = _hashed(auth)
        brute[key] = brute.get(key, 0) + 1
    with concurrent.futures.ThreadPoolExecutor(max_workers=16) as pool:
        for _ in pool.map(fire, plan):
            pass
    ledger = app.container.tenants
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if ledger.totals()["requests"] >= 5000:
            break
        time.sleep(0.05)
    stats = ledger.stats()
    assert stats["tracked"] == 64  # TENANT_LEDGER_SIZE holds
    assert stats["evictions"] > 0
    top = {r["tenant"]: r for r in ledger.top(3)}
    for auth in heavies:
        key = _hashed(auth)
        assert key in top, (key, sorted(top))
        assert top[key]["requests"] == brute[key]  # exact, not approximate
    # bounded exposition: no per-tenant series ever minted, and the
    # cardinality guard never had to drop one
    text = _metrics(base)
    assert "key-" not in text
    dropped = [
        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
        if ln.startswith("gofr_tpu_metrics_dropped_series_total")
        and not ln.startswith("#")
    ]
    assert sum(dropped) == 0
    assert _get(base, "/admin/tenants")["tracked"] == 64
