"""OpenAI-compatible completions surface (gofr_tpu/openai_compat.py):
request/response shape, SSE streaming with [DONE], stop handling, usage
accounting, and validation errors — through the real HTTP transport."""

import json
import urllib.error
import urllib.request

import pytest

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def _make_app(tmp_path_factory, label, extra_env=None):
    """Boot an app with the OpenAI routes under temporary env; shared by
    the tokenizer-less and byte-tokenizer fixtures so the bootstrap dance
    (port pick, env save/restore, chdir) exists once."""
    import os
    import socket

    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL", "MODEL_NAME": "tiny",
           "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1", "DECODE_CHUNK": "4"}
    env.update(extra_env or {})
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    os.chdir(tmp_path_factory.mktemp(label))
    try:
        app = gofr_tpu.new()
    finally:
        os.chdir(cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    register_openai_routes(app)
    app.start()
    return app


@pytest.fixture(scope="module")
def base(tmp_path_factory):
    app = _make_app(tmp_path_factory, "openai")
    yield f"http://127.0.0.1:{app.http_port}"
    app.shutdown()


def _post(base_url, payload, path="/v1/completions"):
    req = urllib.request.Request(
        base_url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, json.loads(resp.read())


def test_completions_response_shape_and_usage(base):
    status, body = _post(base, {"prompt": [3, 1, 4, 1, 5], "max_tokens": 6,
                                "temperature": 0})
    assert status == 200
    # OpenAI object at top level — NOT the framework envelope
    assert "data" not in body or body.get("object") == "text_completion"
    assert body["object"] == "text_completion"
    assert body["id"].startswith("cmpl-")
    choice = body["choices"][0]
    assert choice["finish_reason"] == "length"
    assert body["usage"] == {
        "prompt_tokens": 5, "completion_tokens": 6, "total_tokens": 11,
    }
    # no tokenizer configured for 'tiny': ids carried alongside empty text
    assert len(choice["tokens"]) == 6


def test_completions_greedy_matches_native_generate(base):
    status, body = _post(base, {"prompt": [2, 7, 2], "max_tokens": 5,
                                "temperature": 0})
    ids = body["choices"][0]["tokens"]
    status, body2 = _post(base, {"prompt": [2, 7, 2], "max_tokens": 5,
                                 "temperature": 0})
    assert body2["choices"][0]["tokens"] == ids  # deterministic greedy


def test_completions_stop_token_ids(base):
    # generate once to learn the greedy continuation, then stop on its
    # first token: the completion must end immediately with reason "stop"
    _, free = _post(base, {"prompt": [5, 5, 5], "max_tokens": 4,
                           "temperature": 0})
    first = free["choices"][0]["tokens"][0]
    _, stopped = _post(base, {"prompt": [5, 5, 5], "max_tokens": 4,
                              "temperature": 0, "stop_token_ids": [first]})
    assert stopped["choices"][0]["tokens"] == []
    assert stopped["choices"][0]["finish_reason"] == "stop"
    assert stopped["usage"]["completion_tokens"] == 0


def test_completions_logprobs(base):
    _, body = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                           "temperature": 0, "logprobs": 1})
    lps = body["choices"][0]["logprobs"]["token_logprobs"]
    assert len(lps) == 4
    assert all(lp <= 0.0 for lp in lps)


def test_completions_stream_sse_with_done(base):
    req = urllib.request.Request(
        base + "/v1/completions",
        data=json.dumps({"prompt": [4, 4], "max_tokens": 3,
                         "temperature": 0, "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.status == 200
        raw = resp.read().decode()
    events = [ln[len("data: "):] for ln in raw.splitlines()
              if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert all(p["object"] == "text_completion" for p in parsed)
    assert parsed[-1]["choices"][0]["finish_reason"] == "length"
    assert all(p["choices"][0]["finish_reason"] is None for p in parsed[:-1])


def test_models_endpoint(base):
    with urllib.request.urlopen(base + "/v1/models", timeout=30) as resp:
        body = json.loads(resp.read())
    assert body["object"] == "list"
    assert body["data"][0]["id"] == "tiny"


@pytest.mark.parametrize("payload,needle", [
    ({"prompt": "text prompt", "max_tokens": 2}, "tokenizer"),
    ({"prompt": [], "max_tokens": 2}, "prompt"),
    ({"prompt": [1, 2], "max_tokens": 0}, "max_tokens"),
    ({"prompt": [1, 2], "max_tokens": 2, "stop": "word"}, "tokenizer"),
    ({"prompt": [1, 2], "max_tokens": 2, "stop_token_ids": ["x"]}, "stop_token_ids"),
    ({"prompt": [1, 2], "max_tokens": 2, "temperature": -1}, "sampling"),
])
def test_completions_validation_errors(base, payload, needle):
    try:
        _post(base, payload)
        raise AssertionError(f"expected 400 for {payload}")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert needle in e.read(400).decode()


# -- chat completions (needs a tokenizer: byte-level over tiny's 256 vocab) --

@pytest.fixture(scope="module")
def chat_base(tmp_path_factory):
    app = _make_app(tmp_path_factory, "openai-chat", {"TOKENIZER": "byte"})
    yield f"http://127.0.0.1:{app.http_port}"
    app.shutdown()


def test_chat_completion_shape(chat_base):
    status, body = _post(chat_base, {
        "messages": [{"role": "system", "content": "be brief"},
                     {"role": "user", "content": "hi"}],
        "max_tokens": 6, "temperature": 0,
    }, path="/v1/chat/completions")
    assert status == 200
    assert body["object"] == "chat.completion"
    assert body["id"].startswith("chatcmpl-")
    msg = body["choices"][0]["message"]
    assert msg["role"] == "assistant"
    assert isinstance(msg["content"], str)
    assert body["choices"][0]["finish_reason"] == "length"
    # prompt = rendered template bytes: usage must count them exactly
    rendered = "[system]: be brief\n[user]: hi\n[assistant]: "
    assert body["usage"]["prompt_tokens"] == len(rendered.encode())
    assert body["usage"]["completion_tokens"] == 6


def test_chat_completion_stream_deltas(chat_base):
    req = urllib.request.Request(
        chat_base + "/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": "go"}],
                         "max_tokens": 4, "temperature": 0,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        raw = resp.read().decode()
    events = [ln[len("data: "):] for ln in raw.splitlines()
              if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert all(p["object"] == "chat.completion.chunk" for p in parsed)
    assert parsed[0]["choices"][0]["delta"] == {"role": "assistant"}
    assert parsed[-1]["choices"][0]["finish_reason"] == "length"
    content = "".join(
        p["choices"][0]["delta"].get("content", "") for p in parsed
    )
    # streamed deltas must reassemble to exactly the non-stream content
    # (raw bytes may be invalid UTF-8 from an untrained model — both
    # paths share the replacement-char policy)
    _, blocking = _post(chat_base, {
        "messages": [{"role": "user", "content": "go"}],
        "max_tokens": 4, "temperature": 0,
    }, path="/v1/chat/completions")
    assert content == blocking["choices"][0]["message"]["content"]


def test_chat_without_tokenizer_400(base):
    try:
        _post(base, {"messages": [{"role": "user", "content": "x"}]},
              path="/v1/chat/completions")
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "tokenizer" in e.read(300).decode()


def test_chat_bad_messages_400(chat_base):
    for bad in ([], [{"role": "user"}], "hi", [{"role": 1, "content": "x"}]):
        try:
            _post(chat_base, {"messages": bad}, path="/v1/chat/completions")
            raise AssertionError(f"expected 400 for {bad!r}")
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_completions_missing_prompt_400(base):
    try:
        _post(base, {"max_tokens": 3})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "prompt" in e.read(200).decode()


def test_chat_logprobs(chat_base):
    _, body = _post(chat_base, {
        "messages": [{"role": "user", "content": "x"}],
        "max_tokens": 3, "temperature": 0, "logprobs": True,
    }, path="/v1/chat/completions")
    lp_obj = body["choices"][0]["logprobs"]
    lps = lp_obj["token_logprobs"]
    assert len(lps) == 3 and all(lp <= 0.0 for lp in lps)
    # the CURRENT chat shape stock SDKs parse, alongside the legacy field
    content = lp_obj["content"]
    assert len(content) == 3
    for e, lp in zip(content, lps):
        assert e["logprob"] == lp
        assert isinstance(e["token"], str)
        assert e["bytes"] == list(e["token"].encode("utf-8"))
    # alternatives ride content entries when requested
    _, body2 = _post(chat_base, {
        "messages": [{"role": "user", "content": "x"}],
        "max_tokens": 3, "temperature": 0, "logprobs": True,
        "top_logprobs": 2,
    }, path="/v1/chat/completions")
    c2 = body2["choices"][0]["logprobs"]["content"]
    assert all(len(e["top_logprobs"]) == 2 for e in c2)
    # greedy: the chosen token is the best alternative
    for e in c2:
        assert max(a["logprob"] for a in e["top_logprobs"]) == \
            e["top_logprobs"][0]["logprob"]


# -- embeddings (encoder models: BASELINE config 2's OpenAI face) ------------

@pytest.fixture(scope="module")
def embed_base(tmp_path_factory):
    app = _make_app(tmp_path_factory, "openai-embed",
                    {"MODEL_NAME": "bert-tiny"})
    yield f"http://127.0.0.1:{app.http_port}"
    app.shutdown()


def test_embeddings_single_and_batch(embed_base):
    status, body = _post(embed_base, {"input": [1, 2, 3]},
                         path="/v1/embeddings")
    assert status == 200
    assert body["object"] == "list"
    assert body["data"][0]["object"] == "embedding"
    dim = len(body["data"][0]["embedding"])
    assert dim == 128  # bert-tiny hidden size
    assert body["usage"] == {"prompt_tokens": 3, "total_tokens": 3}
    # multi-item: one embedding per input, indexed
    _, multi = _post(embed_base, {"input": [[1, 2, 3], [4, 5]]},
                     path="/v1/embeddings")
    assert [d["index"] for d in multi["data"]] == [0, 1]
    assert multi["usage"]["prompt_tokens"] == 5
    # same ids => same vector
    assert multi["data"][0]["embedding"] == body["data"][0]["embedding"]


def test_embeddings_decoder_model_400(base):
    try:
        _post(base, {"input": [1, 2, 3]}, path="/v1/embeddings")
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "encoder" in e.read(300).decode()


def test_embeddings_bad_input_400(embed_base):
    for bad in (None, [], "", [[]], [1.5]):
        try:
            _post(embed_base, {"input": bad}, path="/v1/embeddings")
            raise AssertionError(f"expected 400 for {bad!r}")
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_chat_template_opener_derivation(chat_base):
    """ChatML-style markup AFTER {content}: the opener must stop at the
    content slot, not emit a closed empty assistant turn."""
    import os

    os.environ["CHAT_TEMPLATE"] = "<|s|>{role}\n{content}<|e|>\n"
    try:
        _, body = _post(chat_base, {
            "messages": [{"role": "user", "content": "q"}],
            "max_tokens": 2, "temperature": 0,
        }, path="/v1/chat/completions")
        rendered = "<|s|>user\nq<|e|>\n<|s|>assistant\n"
        assert body["usage"]["prompt_tokens"] == len(rendered.encode())
    finally:
        os.environ.pop("CHAT_TEMPLATE", None)


def test_chat_template_invalid_is_clear_error(chat_base):
    import os

    for bad in ("{role}: {contnet}\n", "{role} {content} {", "{role} only\n"):
        os.environ["CHAT_TEMPLATE"] = bad
        try:
            _post(chat_base, {"messages": [{"role": "user", "content": "x"}]},
                  path="/v1/chat/completions")
            raise AssertionError(f"expected error for template {bad!r}")
        except urllib.error.HTTPError as e:
            assert e.code == 500
            assert "CHAT_TEMPLATE" in e.read(300).decode()
        finally:
            os.environ.pop("CHAT_TEMPLATE", None)


def test_embeddings_overlong_input_400(embed_base):
    """Over-long input must 400 (OpenAI behavior) — the encoder would
    silently embed a truncated prefix while usage reported the full
    count."""
    try:
        _post(embed_base, {"input": list(range(1, 200))},
              path="/v1/embeddings")
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        body = e.read(300).decode()
        assert "128" in body and "199" in body


def test_unsupported_openai_knobs_400_not_silent(base):
    """Knobs this server cannot honor must 400 loudly: suffix always;
    best_of-ranking when streaming (candidates cannot be discarded
    mid-stream); echo with logprobs; constraint violations (best_of <
    n, fan-out past the cap). n > 1 streaming itself is SUPPORTED
    (interleaved multi-index SSE — test_completions_stream_fanout)."""
    for payload, expect in (
        ({"suffix": "tail"}, "suffix"),
        ({"best_of": 2, "stream": True, "temperature": 1.0}, "best_of"),
        ({"echo": True, "logprobs": 1, "stream": True}, "echo"),
        ({"n": 3, "best_of": 2, "temperature": 1.0}, "best_of"),
        ({"n": 999, "temperature": 1.0}, "n"),
        ({"n": 0}, "n"),
    ):
        try:
            _post(base, {"prompt": [1, 2], "max_tokens": 2, **payload})
            raise AssertionError(f"expected 400 for {payload}")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert expect in e.read(300).decode()


def test_completions_fanout_n_best_of_echo(base):
    """n parallel samples, best_of ranking, echo prompt replay."""
    # greedy n: deterministic — one generation replicated across choices
    status, body = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                                "temperature": 0, "n": 2})
    assert status == 200
    assert [c["index"] for c in body["choices"]] == [0, 1]
    assert body["choices"][0]["tokens"] == body["choices"][1]["tokens"]
    assert body["usage"]["completion_tokens"] == 8  # summed across choices
    # seeded sampled n: reproducible fan-out (per-choice derived seeds)
    a = _post(base, {"prompt": [1, 2, 3], "max_tokens": 6,
                     "temperature": 1.0, "seed": 11, "n": 3})[1]
    b = _post(base, {"prompt": [1, 2, 3], "max_tokens": 6,
                     "temperature": 1.0, "seed": 11, "n": 3})[1]
    toks_a = [tuple(c["tokens"]) for c in a["choices"]]
    assert toks_a == [tuple(c["tokens"]) for c in b["choices"]]
    assert len(toks_a) == 3 and len(set(toks_a)) >= 2  # distinct streams
    # best_of > n: n survive; logprobs stay internal unless requested;
    # usage counts the DISCARDED candidates too (OpenAI accounting)
    picked = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                          "temperature": 1.0, "seed": 5,
                          "best_of": 4, "n": 2})[1]
    assert len(picked["choices"]) == 2
    assert all(c["logprobs"] is None for c in picked["choices"])
    assert picked["usage"]["completion_tokens"] == 16  # 4 candidates x 4
    # a string seed is coerced, not a 500 (and stays reproducible)
    s1 = _post(base, {"prompt": [1, 2], "max_tokens": 3,
                      "temperature": 1.0, "seed": "7", "n": 2})[1]
    s2 = _post(base, {"prompt": [1, 2], "max_tokens": 3,
                      "temperature": 1.0, "seed": 7, "n": 2})[1]
    assert ([c["tokens"] for c in s1["choices"]]
            == [c["tokens"] for c in s2["choices"]])
    # non-bool echo is a loud 400, not a truthy surprise
    try:
        _post(base, {"prompt": [1, 2], "max_tokens": 2, "echo": "false"})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400 and "echo" in e.read(300).decode()
    # echo replays the prompt ahead of the completion
    echoed = _post(base, {"prompt": [9, 8, 7], "max_tokens": 3,
                          "temperature": 0, "echo": True})[1]
    assert echoed["choices"][0]["tokens"][:3] == [9, 8, 7]
    assert len(echoed["choices"][0]["tokens"]) == 6


def test_jinja_chat_template(tmp_path):
    """CHAT_TEMPLATE_JINJA renders with the HF conventions (messages,
    add_generation_prompt, sandboxed env); tokenizer_config.json
    auto-discovery picks up a checkpoint's own template; render errors
    are clear 500s, not bare crashes."""
    import json as _json

    from gofr_tpu.openai_compat import (
        _jinja_template_source,
        render_chat_prompt,
    )

    class _Cfg:
        def __init__(self, env):
            self.env = env

        def get(self, k):
            return self.env.get(k)

        def get_or_default(self, k, d):
            return self.env.get(k, d)

    class _Ctx:
        tpu = None

        def __init__(self, env):
            self.config = _Cfg(env)

    chatml = (
        "{% for m in messages %}<|im_start|>{{ m.role }}\n"
        "{{ m.content }}<|im_end|>\n{% endfor %}"
        "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
    )
    ctx = _Ctx({"CHAT_TEMPLATE_JINJA": chatml})
    out = render_chat_prompt(ctx, [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": "hi"},
    ])
    assert out == (
        "<|im_start|>system\nbe brief<|im_end|>\n"
        "<|im_start|>user\nhi<|im_end|>\n"
        "<|im_start|>assistant\n"
    )
    # file form
    p = tmp_path / "t.jinja"
    p.write_text(chatml)
    assert render_chat_prompt(_Ctx({"CHAT_TEMPLATE_JINJA": str(p)}), [
        {"role": "user", "content": "hi"},
    ]).endswith("<|im_start|>assistant\n")
    # auto-discovery from the checkpoint's tokenizer_config.json
    (tmp_path / "tokenizer_config.json").write_text(
        _json.dumps({"chat_template": chatml})
    )
    src = _jinja_template_source(
        _Ctx({"TOKENIZER_PATH": str(tmp_path / "tokenizer.json")})
    )
    assert src == chatml
    # explicit simple CHAT_TEMPLATE (or a customized opener) wins over
    # discovery — a tuned opener must never be silently ignored
    assert _jinja_template_source(_Ctx({
        "TOKENIZER_PATH": str(tmp_path / "tokenizer.json"),
        "CHAT_TEMPLATE": "[{role}] {content}",
    })) is None
    assert _jinja_template_source(_Ctx({
        "TOKENIZER_PATH": str(tmp_path / "tokenizer.json"),
        "CHAT_TEMPLATE_OPENER": "<asst>",
    })) is None
    # a corrupt sidecar is a clear 500, never a silent fallback
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    (bad_dir / "tokenizer_config.json").write_text("{truncated")
    try:
        _jinja_template_source(
            _Ctx({"TOKENIZER_PATH": str(bad_dir / "tokenizer.json")})
        )
        raise AssertionError("expected HTTPError")
    except Exception as e:
        from gofr_tpu.errors import HTTPError as _HE

        assert isinstance(e, _HE) and e.status_code == 500
    # a template that raises renders as a clear 500
    from gofr_tpu.errors import HTTPError as _HTTPError

    bad = _Ctx({"CHAT_TEMPLATE_JINJA":
                "{{ raise_exception('only user turns') }}"})
    try:
        render_chat_prompt(bad, [{"role": "user", "content": "x"}])
        raise AssertionError("expected HTTPError")
    except _HTTPError as e:
        assert e.status_code == 500 and "only user turns" in str(e)


def test_jinja_template_end_to_end(chat_base, tmp_path_factory):
    """A live chat completion through a jinja template: the rendered
    prompt reaches the model (deterministic greedy output changes when
    the template changes)."""
    # the chat_base app has no jinja template; spin a request through the
    # simple path first, then compare against a jinja-rendered call on a
    # fresh app
    plain = _post(chat_base, {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 6, "temperature": 0,
    }, path="/v1/chat/completions")[1]
    # EnvConfig reads the live environment per get(): CHAT_TEMPLATE_JINJA
    # must stay set while requests run, so this test manages env itself
    import os
    import socket

    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL", "MODEL_NAME": "tiny",
           "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
           "DECODE_CHUNK": "4", "TOKENIZER": "byte",
           "CHAT_TEMPLATE_JINJA":
               "{% for m in messages %}<{{ m.role }}>{{ m.content }}"
               "{% endfor %}{% if add_generation_prompt %}<assistant>{% endif %}"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    app = None
    try:
        os.chdir(tmp_path_factory.mktemp("openai-jinja"))
        try:
            app = gofr_tpu.new()
        finally:
            os.chdir(cwd)
        register_openai_routes(app)
        app.start()
        jinja = _post(
            f"http://127.0.0.1:{app.http_port}",
            {"messages": [{"role": "user", "content": "hi"}],
             "max_tokens": 6, "temperature": 0},
            path="/v1/chat/completions",
        )[1]
        assert jinja["choices"][0]["message"]["role"] == "assistant"
        # different rendered prompt -> different greedy continuation
        assert (jinja["choices"][0]["message"]["content"]
                != plain["choices"][0]["message"]["content"])
    finally:
        # shutdown in the FINALLY: an assertion failure must not leak
        # the running server into the rest of the session
        if app is not None:
            app.shutdown()
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_multitoken_stop_strings(chat_base):
    """Multi-token "stop" strings match host-side against the decoded
    text: truncation before the match, finish_reason stop, early decode
    cancel; streaming holds back text that could still grow into a stop
    so a partial stop never leaks."""
    full = _post(chat_base, {"prompt": "ab", "max_tokens": 12,
                             "temperature": 0})[1]
    text = full["choices"][0]["text"]
    assert len(text) >= 5
    stop = text[2:4]  # two byte-tokens under the byte tokenizer
    cut = _post(chat_base, {"prompt": "ab", "max_tokens": 12,
                            "temperature": 0, "stop": stop})[1]
    c = cut["choices"][0]
    assert c["finish_reason"] == "stop"
    assert c["text"] == text[: text.find(stop)]
    assert stop not in c["text"]
    # usage still counts what was actually generated (may exceed the
    # truncated text, never the untruncated run)
    assert 1 <= cut["usage"]["completion_tokens"] <= len(text) + 2
    # streaming: same final text, no partial-stop leak, finish stop
    req = urllib.request.Request(
        chat_base + "/v1/completions",
        data=json.dumps({"prompt": "ab", "max_tokens": 12,
                         "temperature": 0, "stop": stop,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        raw = resp.read().decode()
    events = [ln[len("data: "):] for ln in raw.splitlines()
              if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    streamed = "".join(p["choices"][0]["text"] for p in parsed)
    assert streamed == c["text"]
    assert parsed[-1]["choices"][0]["finish_reason"] == "stop"
    # chat: the same stop semantics through the chat shape
    chat_cut = _post(chat_base, {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 12, "temperature": 0, "stop": stop,
    }, path="/v1/chat/completions")[1]
    assert stop not in chat_cut["choices"][0]["message"]["content"]
    # single-token stop strings stop on-device AND are host-matched —
    # truncation lands before the first text occurrence either way
    ch = text[3]
    cut1 = _post(chat_base, {"prompt": "ab", "max_tokens": 12,
                             "temperature": 0, "stop": ch})[1]
    assert ch not in cut1["choices"][0]["text"]
    assert cut1["choices"][0]["text"] == text[: text.find(ch)]
    # logprobs align with the truncated text, not the full generation
    lp_cut = _post(chat_base, {"prompt": "ab", "max_tokens": 12,
                               "temperature": 0, "stop": stop,
                               "logprobs": 1})[1]["choices"][0]
    assert len(lp_cut["logprobs"]["token_logprobs"]) <= len(lp_cut["text"]) + 1
    # the OpenAI 4-sequence limit stays loud
    try:
        _post(chat_base, {"prompt": "ab", "max_tokens": 2,
                          "stop": ["aa", "bb", "cc", "dd", "ee"]})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400 and "4" in e.read(300).decode()


def test_echo_logprobs_prompt_scoring(base):
    """echo+logprobs returns teacher-forcing prompt logprobs (first
    null, the OpenAI convention) ahead of the completion's; max_tokens=0
    with echo is pure scoring — the eval-harness loglikelihood pattern."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    prompt = [3, 1, 4, 1, 5]
    status, body = _post(base, {"prompt": prompt, "max_tokens": 0,
                                "temperature": 0, "echo": True,
                                "logprobs": 1})
    assert status == 200
    choice = body["choices"][0]
    lps = choice["logprobs"]["token_logprobs"]
    assert lps[0] is None and len(lps) == len(prompt)
    assert body["usage"]["completion_tokens"] == 0
    assert choice["tokens"] == prompt  # echo, nothing generated
    # oracle: the full no-cache forward's log-softmax at each position —
    # the tiny serving device rebuilds exactly init_transformer(key(0))
    # (the same seeded-base convention test_multi_lora relies on)
    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.transformer import init_transformer, transformer_forward

    params = init_transformer(jax.random.key(0), TINY)
    logits = transformer_forward(
        params, jnp.asarray([prompt], jnp.int32), TINY
    )
    ref = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
    for i in range(1, len(prompt)):
        np.testing.assert_allclose(
            lps[i], float(ref[i - 1, prompt[i]]), rtol=1e-4, atol=1e-4
        )
    # echo + logprobs + generation: prompt scores then completion scores
    status, body = _post(base, {"prompt": prompt, "max_tokens": 3,
                                "temperature": 0, "echo": True,
                                "logprobs": 1})
    full = body["choices"][0]["logprobs"]["token_logprobs"]
    assert full[: len(prompt)] == lps and len(full) == len(prompt) + 3
    # max_tokens=0 without echo stays a 400
    try:
        _post(base, {"prompt": prompt, "max_tokens": 0})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400 and "max_tokens" in e.read(300).decode()
    # an unknown adapter 400s even on the pure-scoring path (no
    # generation runs to catch it)
    for payload in ({"logprobs": 1}, {}):
        try:
            _post(base, {"prompt": prompt, "max_tokens": 0, "echo": True,
                         "adapter": "nope", **payload})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400 and "adapter" in e.read(300).decode()
    # an over-long prompt is a loud 400, never a silently clipped score
    try:
        _post(base, {"prompt": list(range(1, 200)) * 4, "max_tokens": 0,
                     "echo": True, "logprobs": 1})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400 and "bucket" in e.read(300).decode()


def test_top_logprobs_alternatives(base):
    """logprobs >= 2 (or the chat-style top_logprobs key) returns the
    N best alternatives per position; greedy's chosen token is the top
    entry; logprobs 1/true stays chosen-only (documented back-compat)."""
    status, body = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                                "temperature": 0, "logprobs": 3})
    assert status == 200
    lp = body["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == 4
    assert len(lp["tokens"]) == 4  # aligned names (stringified ids here)
    assert len(lp["top_logprobs"]) == 4
    out = body["choices"][0]["tokens"]
    for i, alts in enumerate(lp["top_logprobs"]):
        assert len(alts) == 3
        # greedy chosen token is the best alternative
        assert str(out[i]) in alts
        assert max(alts.values()) == alts[str(out[i])]
    # explicit top_logprobs key works too
    via_key = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                           "temperature": 0, "logprobs": 1,
                           "top_logprobs": 3})[1]
    assert via_key["choices"][0]["logprobs"]["top_logprobs"] == \
        lp["top_logprobs"]
    # logprobs: 1 stays chosen-only
    plain = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                         "temperature": 0, "logprobs": 1})[1]
    assert "top_logprobs" not in plain["choices"][0]["logprobs"]
    # echo scoring: prompt positions carry null alternatives
    echoed = _post(base, {"prompt": [1, 2, 3], "max_tokens": 2,
                          "temperature": 0, "echo": True,
                          "logprobs": 2})[1]
    tl = echoed["choices"][0]["logprobs"]["top_logprobs"]
    assert tl[:3] == [None, None, None] and len(tl) == 5
    # bounds + streaming stay loud
    for payload, expect in (
        ({"logprobs": 9}, "maximum"),
        ({"top_logprobs": -1}, "top_logprobs"),
        ({"logprobs": 2, "stream": True, "temperature": 0}, "stream"),
    ):
        try:
            _post(base, {"prompt": [1, 2], "max_tokens": 2, **payload})
            raise AssertionError(f"expected 400 for {payload}")
        except urllib.error.HTTPError as e:
            assert e.code == 400 and expect in e.read(300).decode()


def test_chat_fanout_n(chat_base):
    """chat supports n; best_of and echo are completions-only 400s."""
    status, body = _post(chat_base, {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 4, "temperature": 1.0, "seed": 3, "n": 2,
    }, path="/v1/chat/completions")
    assert status == 200
    assert [c["index"] for c in body["choices"]] == [0, 1]
    assert all(c["message"]["role"] == "assistant" for c in body["choices"])
    for key in ("best_of", "echo"):
        try:
            _post(chat_base, {
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2, key: 2 if key == "best_of" else True,
            }, path="/v1/chat/completions")
            raise AssertionError(f"expected 400 for chat {key}")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "completions-only" in e.read(300).decode()
    # best_of=true must not slip past the completions-only gate via
    # True == 1 — positive() rejects bools on both endpoints
    try:
        _post(chat_base, {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2, "best_of": True,
        }, path="/v1/chat/completions")
        raise AssertionError("expected 400 for chat best_of=true")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "best_of" in e.read(300).decode()


def test_openai_penalties_honored(base):
    """presence/frequency penalties run on-device: an extreme presence
    penalty forbids re-emitting any generated token, and out-of-range
    values 400 per the documented [-2, 2] bound."""
    plain = _post(base, {"prompt": [1, 2, 3], "max_tokens": 8,
                         "temperature": 0})[1]
    pen = _post(base, {"prompt": [1, 2, 3], "max_tokens": 8,
                       "temperature": 0, "presence_penalty": 2.0,
                       "frequency_penalty": 2.0})[1]
    plain_ids = plain["choices"][0]["tokens"]
    pen_ids = pen["choices"][0]["tokens"]
    assert len(plain_ids) == len(pen_ids) == 8
    # greedy tiny repeats; max-strength additive penalties steer away
    assert len(set(plain_ids)) < len(plain_ids)
    assert pen_ids != plain_ids
    # penalties cover GENERATED tokens only: first emission matches
    assert pen_ids[0] == plain_ids[0]
    try:
        _post(base, {"prompt": [1, 2], "max_tokens": 2,
                     "presence_penalty": 3.5})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "presence_penalty" in e.read(300).decode()
    # explicit JSON null = "use the default" (nullable per the OpenAI
    # spec) — must 200, not 400
    status, _ = _post(base, {"prompt": [1, 2], "max_tokens": 2,
                             "temperature": None, "top_p": None,
                             "presence_penalty": None,
                             "frequency_penalty": None,
                             "logit_bias": None})
    assert status == 200


def test_logit_bias_honored(base):
    """logit_bias (string keys, the JSON form OpenAI clients send) bans
    and forces tokens on-device; out-of-range values 400."""
    plain = _post(base, {"prompt": [1, 2, 3], "max_tokens": 6,
                         "temperature": 0})[1]["choices"][0]["tokens"]
    banned = _post(base, {"prompt": [1, 2, 3], "max_tokens": 6,
                          "temperature": 0,
                          "logit_bias": {str(plain[0]): -100}})[1]
    assert plain[0] not in banned["choices"][0]["tokens"]
    forced = _post(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                          "temperature": 0, "logit_bias": {"42": 100}})[1]
    assert forced["choices"][0]["tokens"] == [42] * 4
    try:
        _post(base, {"prompt": [1, 2], "max_tokens": 2,
                     "logit_bias": {"1": 200}})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "logit_bias" in e.read(300).decode()
    # STREAMING with an out-of-vocab id must 400 BEFORE the stream
    # commits — never a 200 followed by an error frame
    try:
        _post(base, {"prompt": [1, 2], "max_tokens": 2, "stream": True,
                     "logit_bias": {"999999999": -1}})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "vocab" in e.read(300).decode()
    # null max_tokens = the default (nullable per the OpenAI spec)
    status, body = _post(base, {"prompt": [1, 2], "max_tokens": None,
                                "temperature": 0})
    assert status == 200 and body["usage"]["completion_tokens"] >= 1


def test_text_offset_in_logprobs(chat_base):
    """Completions logprobs carry text_offset — each token's character
    start within the choice text (eval harnesses locate the prompt/
    continuation boundary with it under echo)."""
    prompt = "hi there"
    status, body = _post(chat_base, {"prompt": prompt, "max_tokens": 4,
                                     "temperature": 0, "echo": True,
                                     "logprobs": 1})
    assert status == 200
    lp = body["choices"][0]["logprobs"]
    toks, offs = lp["tokens"], lp["text_offset"]
    text = body["choices"][0]["text"]
    assert len(offs) == len(toks) == len(lp["token_logprobs"])
    # offsets index into the choice text: start at 0, never decrease,
    # never pass the end (they come from the STREAM decoder, so they stay
    # correct even when generated byte tokens are UTF-8 fragments whose
    # per-token decode would be U+FFFD)
    assert offs[0] == 0
    assert all(a <= b for a, b in zip(offs, offs[1:]))
    assert all(o <= len(text) for o in offs)
    # THE property eval harnesses rely on under echo: the first
    # continuation token's offset is exactly the prompt/continuation
    # boundary (the byte tokenizer maps the ASCII prompt 1:1)
    assert offs[len(prompt)] == len(prompt)
    assert text.startswith(prompt)
    # the echoed-ASCII prefix tiles exactly
    assert [o for o in offs[: len(prompt)]] == list(range(len(prompt)))
    # tokenizer-less deployments still emit the field (stringified ids)
    # — typed clients treat the completions logprobs shape as fixed


def test_unknown_model_404_and_toggle(tmp_path_factory):
    """An unknown "model" is a 404 (the r04 breaking change) unless
    OPENAI_ACCEPT_UNKNOWN_MODEL restores the legacy accept-anything
    routing, which serves the base model."""
    import os

    app = _make_app(tmp_path_factory, "openai-anymodel")
    # EnvConfig reads the LIVE environment per get(), and _make_app
    # restores env right after construction — the toggle must stay set
    # while requests run (the ADMIN_TOKEN tests use the same pattern)
    old = os.environ.get("OPENAI_ACCEPT_UNKNOWN_MODEL")
    os.environ["OPENAI_ACCEPT_UNKNOWN_MODEL"] = "1"
    try:
        url = f"http://127.0.0.1:{app.http_port}"
        status, body = _post(url, {"model": "gpt-4o", "prompt": [1, 2, 3],
                                   "max_tokens": 2, "temperature": 0})
        assert status == 200
        assert body["model"] == "tiny"  # served as the base, honestly named
    finally:
        if old is None:
            os.environ.pop("OPENAI_ACCEPT_UNKNOWN_MODEL", None)
        else:
            os.environ["OPENAI_ACCEPT_UNKNOWN_MODEL"] = old
        app.shutdown()


def test_unknown_model_404_default(base):
    try:
        _post(base, {"model": "gpt-4o", "prompt": [1, 2], "max_tokens": 2})
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404 and "gpt-4o" in e.read(300).decode()


def test_chat_multi_turn_reuses_conversation_kv(tmp_path_factory):
    """The real chat flow: the follow-up request carries the whole
    history (system + user + assistant reply + new user turn) and must
    partial-hit the cached conversation KV instead of re-prefilling it;
    the entries gauge sizes the cache's HBM footprint."""
    app = _make_app(tmp_path_factory, "openai-chat-mt",
                    {"TOKENIZER": "byte", "PREFIX_CACHE": "4",
                     "PREFIX_LCP_MIN": "8", "DECODE_CHUNK": "4"})
    try:
        base = f"http://127.0.0.1:{app.http_port}"
        msgs = [{"role": "system", "content": "be brief"},
                {"role": "user", "content": "hello there"}]
        status, body = _post(base, {"messages": msgs, "max_tokens": 8,
                                    "temperature": 0},
                             "/v1/chat/completions")
        assert status == 200
        reply = body["choices"][0]["message"]["content"]
        msgs2 = msgs + [{"role": "assistant", "content": reply},
                        {"role": "user", "content": "more please"}]
        status, _ = _post(base, {"messages": msgs2, "max_tokens": 4,
                                 "temperature": 0}, "/v1/chat/completions")
        assert status == 200
        stats = app.container.tpu.runner.prefix_stats
        assert stats["partial_hits"] >= 1, stats
        metrics = urllib.request.urlopen(
            base + "/metrics", timeout=30).read().decode()
        assert 'gofr_tpu_prefix_entries{model="tiny"}' in metrics, metrics
    finally:
        app.shutdown()


def _read_sse(base_url, payload, path="/v1/completions"):
    req = urllib.request.Request(
        base_url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=180) as resp:
        assert resp.status == 200
        raw = resp.read().decode()
    return [ln[len("data: "):] for ln in raw.splitlines()
            if ln.startswith("data: ")]


def test_completions_stream_fanout(base):
    """n > 1 streaming: interleaved chunks carry their choice index, and
    a SEEDED fan-out's per-index token sequences reproduce the
    non-stream fan-out's candidates exactly (same seed+i derivation)."""
    req = {"prompt": [3, 1, 4], "max_tokens": 5, "temperature": 1.0,
           "seed": 11, "n": 2}
    _, want = _post(base, req)
    events = _read_sse(base, {**req, "stream": True})
    assert events[-1] == "[DONE]"
    per_index: dict = {0: [], 1: []}
    finishes: dict = {}
    for e in events[:-1]:
        choice = json.loads(e)["choices"][0]
        i = choice["index"]
        if choice.get("tokens"):
            per_index[i].extend(choice["tokens"])
        if choice["finish_reason"] is not None:
            finishes[i] = choice["finish_reason"]
    assert sorted(finishes) == [0, 1]
    for i in (0, 1):
        assert per_index[i] == want["choices"][i]["tokens"], i
    # greedy n>1 replicates one stream across identical indexes
    events = _read_sse(base, {"prompt": [3, 1, 4], "max_tokens": 4,
                              "temperature": 0, "n": 2, "stream": True})
    toks = {0: [], 1: []}
    for e in events[:-1]:
        c = json.loads(e)["choices"][0]
        if c.get("tokens"):
            toks[c["index"]].extend(c["tokens"])
    assert toks[0] == toks[1] and len(toks[0]) == 4


def test_chat_stream_fanout(chat_base):
    """Chat n > 1 streaming: every index opens with its own role chunk
    and closes with its own finish; greedy indexes carry identical
    content."""
    events = _read_sse(chat_base, {
        "messages": [{"role": "user", "content": "go"}],
        "max_tokens": 4, "temperature": 0, "n": 2, "stream": True,
    }, path="/v1/chat/completions")
    assert events[-1] == "[DONE]"
    roles: dict = {}
    content: dict = {0: "", 1: ""}
    finishes: dict = {}
    for e in events[:-1]:
        c = json.loads(e)["choices"][0]
        i = c["index"]
        if c["delta"].get("role"):
            roles[i] = c["delta"]["role"]
        content[i] += c["delta"].get("content", "")
        if c["finish_reason"] is not None:
            finishes[i] = c["finish_reason"]
    assert roles == {0: "assistant", 1: "assistant"}
    assert sorted(finishes) == [0, 1]
    assert content[0] == content[1] != ""


def test_stream_options_include_usage(base, chat_base):
    """stream_options.include_usage: every chunk carries "usage": null
    and ONE final pre-[DONE] chunk has empty choices + the usage object
    (both endpoints, single and fan-out streams); stream_options without
    stream is a 400."""
    # completions, single stream
    ev = _read_sse(base, {"prompt": [1, 2, 3], "max_tokens": 4,
                          "temperature": 0, "stream": True,
                          "stream_options": {"include_usage": True}})
    frames = [json.loads(e) for e in ev[:-1]]
    assert all(f["usage"] is None for f in frames[:-1])
    last = frames[-1]
    assert last["choices"] == []
    assert last["usage"] == {"prompt_tokens": 3, "completion_tokens": 4,
                             "total_tokens": 7}
    # completions, seeded fan-out: usage bills ALL candidates
    ev = _read_sse(base, {"prompt": [1, 2], "max_tokens": 3,
                          "temperature": 1.0, "seed": 3, "n": 2,
                          "stream": True,
                          "stream_options": {"include_usage": True}})
    last = json.loads(ev[-2])
    assert last["choices"] == []
    assert last["usage"]["completion_tokens"] == 6  # 2 candidates x 3
    # chat, single stream
    ev = _read_sse(chat_base, {
        "messages": [{"role": "user", "content": "go"}],
        "max_tokens": 3, "temperature": 0, "stream": True,
        "stream_options": {"include_usage": True},
    }, path="/v1/chat/completions")
    last = json.loads(ev[-2])
    assert last["choices"] == [] and last["usage"]["completion_tokens"] == 3
    # without stream: loud 400 (OpenAI semantics)
    try:
        _post(base, {"prompt": [1, 2], "max_tokens": 2,
                     "stream_options": {"include_usage": True}})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400 and "stream_options" in e.read(300).decode()


def test_tool_and_format_knobs_400_not_silent(base, chat_base):
    """Tool-calling and modality knobs must 400 loudly — a client that
    believes its tools were offered (or its JSON schema enforced) would
    otherwise trust free-text output. response_format type "text" (the
    documented default) is a no-op and passes."""
    for key, value in (
        ("tools", [{"type": "function", "function": {"name": "f"}}]),
        ("tool_choice", "auto"),
        ("functions", [{"name": "f"}]),
        ("function_call", "auto"),
        ("response_format", {"type": "json_object"}),
        ("response_format", {"type": "json_schema", "json_schema": {}}),
        ("modalities", ["text", "audio"]),
    ):
        try:
            _post(chat_base, {
                "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 2, key: value,
            }, path="/v1/chat/completions")
            raise AssertionError(f"expected 400 for {key}")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert key.split("_")[0] in e.read(300).decode()
    # the default-equivalent form passes on both endpoints
    status, _ = _post(base, {"prompt": [1, 2], "max_tokens": 2,
                             "temperature": 0,
                             "response_format": {"type": "text"}})
    assert status == 200
