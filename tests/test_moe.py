"""MoE + expert parallelism: the ep all_to_all dispatch path must equal the
exact dense mixture when capacity is ample, and degrade gracefully (finite,
residual passthrough) when tokens overflow expert capacity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.moe import MoEConfig, init_moe, moe_forward, moe_loss
from gofr_tpu.parallel.expert import (
    make_moe_forward,
    make_moe_loss,
    place_moe_params,
)
from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

# capacity_factor = n_experts/top_k => capacity = T (no token can ever drop)
CFG = MoEConfig(
    vocab_size=89, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
    hidden_dim=32, max_seq=64, n_experts=4, top_k=2, capacity_factor=2.0,
    dtype=jnp.float32, attn_impl="xla",
)


@pytest.fixture(scope="module")
def params():
    return init_moe(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.key(1), (8, 12), 0, CFG.vocab_size)


def test_dense_forward_shapes_and_aux(params, tokens):
    logits, aux = moe_forward(params, tokens, CFG)
    assert logits.shape == (8, 12, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # a perfectly balanced router gives load_balance == 1.0; any router is >= 1
    assert float(aux["load_balance"]) >= 0.99
    assert np.isfinite(float(aux["router_z"]))


def test_ep_forward_matches_dense(params, tokens):
    mesh = make_mesh(mesh_shape_for(8, ep=4, fsdp=2), devices=jax.devices()[:8])
    fwd = make_moe_forward(CFG, mesh)
    got, aux = fwd(place_moe_params(params, mesh), tokens)
    want, _ = moe_forward(params, tokens, CFG)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ep_loss_and_grads_match_dense(params, tokens):
    # aux_weight=0: the load-balance term is the per-device Switch estimator
    # (averages over LOCAL tokens), which legitimately differs from the
    # global-batch dense value; NLL and z-loss are token-linear so they
    # pmean to exactly the dense numbers.
    cfg = MoEConfig(
        vocab_size=89, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=32, max_seq=64, n_experts=4, top_k=2, capacity_factor=2.0,
        aux_weight=0.0, dtype=jnp.float32, attn_impl="xla",
    )
    mesh = make_mesh(mesh_shape_for(8, ep=2, fsdp=2), devices=jax.devices()[:8])
    loss_fn = make_moe_loss(cfg, mesh)
    placed = place_moe_params(params, mesh)

    got_loss, got_grads = jax.value_and_grad(loss_fn)(placed, tokens)
    want_loss, want_grads = jax.value_and_grad(
        lambda p, t: moe_loss(p, t, cfg)
    )(params, tokens)

    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-4)
    for key in ("w_gate", "w_down", "router", "wq"):
        np.testing.assert_allclose(
            np.asarray(got_grads["layers"][key]),
            np.asarray(want_grads["layers"][key]),
            rtol=5e-3, atol=1e-5, err_msg=f"layers.{key}",
        )


def test_ep_full_loss_close_to_dense_with_aux(params, tokens):
    mesh = make_mesh(mesh_shape_for(8, ep=2, fsdp=2), devices=jax.devices()[:8])
    got = float(make_moe_loss(CFG, mesh)(place_moe_params(params, mesh), tokens))
    want = float(moe_loss(params, tokens, CFG))
    assert abs(got - want) / want < 0.02


def test_ep_capacity_overflow_is_finite(params, tokens):
    tight = MoEConfig(
        vocab_size=89, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=32, max_seq=64, n_experts=4, top_k=2, capacity_factor=0.25,
        dtype=jnp.float32, attn_impl="xla",
    )
    mesh = make_mesh(mesh_shape_for(8, ep=4, fsdp=2), devices=jax.devices()[:8])
    fwd = make_moe_forward(tight, mesh)
    logits, _ = fwd(place_moe_params(params, mesh), tokens)
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_quantization_skips_experts_quantizes_attention(params, tokens):
    from gofr_tpu.models.quant import is_quantized, quantize_params

    q = quantize_params(params, "int8")
    layer = q["layers"]
    # expert FFN stacks run through batched einsums, never mm(): dense
    assert not is_quantized(layer["w_gate"])
    assert not is_quantized(layer["w_up"])
    assert not is_quantized(layer["w_down"])
    # attention weights beside them route through mm(): packed
    assert is_quantized(layer["wq"]) and is_quantized(layer["wo"])
    assert is_quantized(q["lm_head"])
    # the quantized tree still runs the full forward
    logits, aux = jax.jit(lambda p, t: moe_forward(p, t, CFG))(q, tokens)
    assert np.isfinite(np.asarray(logits)).all()


def test_ep_rejects_indivisible_experts(params):
    mesh = make_mesh(mesh_shape_for(8, ep=8), devices=jax.devices()[:8])
    bad = MoEConfig(
        vocab_size=89, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=32, max_seq=64, n_experts=6, top_k=2,
        dtype=jnp.float32, attn_impl="xla",
    )
    with pytest.raises(ValueError, match="n_experts"):
        make_moe_forward(bad, mesh)
