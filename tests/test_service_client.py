"""Inter-service HTTP client tests against a live in-process server.

Parity model: service/new_test.go:35-90 — a test server asserts
method/path/query/headers server-side (SURVEY.md §4)."""

import http.server
import json
import threading

import pytest

from gofr_tpu.service import ServiceCallError, new_http_service
from gofr_tpu.testutil import MockLogger


@pytest.fixture
def echo_server(free_port):
    port = free_port()
    seen = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def _handle(self):
            seen["method"] = self.command
            seen["path"] = self.path
            seen["headers"] = dict(self.headers.items())
            length = int(self.headers.get("Content-Length", 0))
            seen["body"] = self.rfile.read(length) if length else b""
            status = 500 if self.path.startswith("/fail") else 200
            payload = json.dumps({"ok": True}).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _handle

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{port}", seen
    srv.shutdown()


def test_get_with_params_and_correlation(echo_server):
    base, seen = echo_server
    logger = MockLogger()
    svc = new_http_service(base, logger, name="downstream")
    resp = svc.get("items", params={"limit": 5, "tag": ["a", "b"]})
    assert resp.status_code == 200
    assert resp.json() == {"ok": True}
    assert seen["method"] == "GET"
    assert seen["path"] == "/items?limit=5&tag=a&tag=b"
    lower_headers = {k.lower(): v for k, v in seen["headers"].items()}
    assert "x-correlation-id" in lower_headers
    assert lower_headers["traceparent"].startswith("00-")
    assert "downstream" in logger.output


def test_post_json_body_and_headers(echo_server):
    base, seen = echo_server
    svc = new_http_service(base, MockLogger())
    svc.post_with_headers("create", None, {"a": 1}, {"X-Api-Key": "k"})
    assert seen["method"] == "POST"
    assert json.loads(seen["body"]) == {"a": 1}
    assert seen["headers"]["Content-Type"] == "application/json"
    assert seen["headers"]["X-Api-Key"] == "k"


def test_5xx_logged_as_error(echo_server):
    base, _ = echo_server
    logger = MockLogger()
    svc = new_http_service(base, logger)
    resp = svc.get("fail")
    assert resp.status_code == 500
    assert '"level": "ERROR"' in logger.output


def test_unreachable_service_raises_502():
    svc = new_http_service("http://127.0.0.1:1", MockLogger(), name="ghost")
    with pytest.raises(ServiceCallError) as exc:
        svc.get("x")
    assert exc.value.status_code == 502
    assert "ghost" in str(exc.value)


def test_health_check(echo_server):
    base, _ = echo_server
    svc = new_http_service(base, MockLogger())
    assert svc.health_check().status == "UP"
    ghost = new_http_service("http://127.0.0.1:1", MockLogger())
    assert ghost.health_check().status == "DOWN"
