"""LoRA adapters: identity at init, adapter-only training, merge
equivalence, quantized (QLoRA) bases, and tp sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.llama import TINY
from gofr_tpu.models.lora import (
    add_lora,
    is_lora,
    lora_mask,
    lora_optimizer,
    merge_lora,
)
from gofr_tpu.models.quant import quantize_params
from gofr_tpu.models.transformer import init_transformer, transformer_forward

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

CFG = TINY


@pytest.fixture(scope="module")
def params():
    return init_transformer(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.key(1), (2, 12), 0, CFG.vocab_size)


_fwd = jax.jit(lambda p, t: transformer_forward(p, t, CFG))


def test_fresh_adapter_is_identity(params, tokens):
    wrapped = add_lora(params, jax.random.key(2), rank=4)
    assert is_lora(wrapped["layers"]["wq"])
    base = _fwd(params, tokens)
    with_lora = _fwd(wrapped, tokens)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(with_lora), rtol=1e-5, atol=1e-5
    )


def test_training_touches_only_adapters(params, tokens):
    import optax

    from gofr_tpu.training.trainer import cross_entropy_loss

    wrapped = add_lora(params, jax.random.key(3), rank=4)
    opt = lora_optimizer(optax.adam(1e-2), wrapped)
    opt_state = opt.init(wrapped)

    @jax.jit
    def step(p, s, t):
        loss, grads = jax.value_and_grad(cross_entropy_loss)(p, t, CFG)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    p = wrapped
    losses = []
    for _ in range(5):
        p, opt_state, loss = step(p, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # base weights bit-identical; adapters moved
    np.testing.assert_array_equal(
        np.asarray(p["layers"]["wq"]["w"]), np.asarray(wrapped["layers"]["wq"]["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(p["embed"]), np.asarray(wrapped["embed"])
    )
    assert not np.array_equal(
        np.asarray(p["layers"]["wq"]["lora_b"]),
        np.asarray(wrapped["layers"]["wq"]["lora_b"]),
    )


def test_merge_matches_unmerged(params, tokens):
    wrapped = add_lora(params, jax.random.key(4), rank=4)
    # give B real values so the merge is non-trivial
    wrapped = jax.tree.map(lambda x: x, wrapped)
    wrapped["layers"]["wq"]["lora_b"] = (
        jax.random.normal(jax.random.key(5), wrapped["layers"]["wq"]["lora_b"].shape)
        * 0.02
    ).astype(jnp.bfloat16)
    merged = merge_lora(wrapped)
    assert not is_lora(merged["layers"]["wq"])
    a = _fwd(wrapped, tokens)
    b = _fwd(merged, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2)


def test_qlora_int8_base(params, tokens):
    qparams = quantize_params(params, "int8")
    wrapped = add_lora(qparams, jax.random.key(6), rank=4)
    leaf = wrapped["layers"]["wq"]
    assert is_lora(leaf) and set(leaf["w"]) == {"q", "scale"}
    out = _fwd(wrapped, tokens)
    base = _fwd(qparams, tokens)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(out), rtol=1e-5, atol=1e-5
    )
    merged = merge_lora(wrapped)  # dequantizes the base
    assert hasattr(merged["layers"]["wq"], "ndim")


def test_qlora_train_step_on_int8_base(params, tokens):
    # the split train step differentiates ONLY adapters: an int8 packed
    # base is never a grad input, so QLoRA fine-tuning just works
    import optax

    from gofr_tpu.models.lora import (
        combine_lora,
        init_lora_train_state,
        make_lora_train_step,
        split_lora,
    )

    qparams = quantize_params(params, "int8")
    wrapped = add_lora(qparams, jax.random.key(9), rank=4)
    # split/combine round-trips the tree exactly
    a, r = split_lora(wrapped)
    rt = combine_lora(a, r)
    la, lb = jax.tree.leaves(wrapped), jax.tree.leaves(rt)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    opt = optax.adam(5e-3)
    state = init_lora_train_state(wrapped, opt)
    step = make_lora_train_step(CFG, opt)
    losses = []
    for _ in range(6):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    # base stayed packed and untouched
    assert set(state["rest"]["layers"]["wq"]["w"]) == {"q", "scale"}
    np.testing.assert_array_equal(
        np.asarray(state["rest"]["layers"]["wq"]["w"]["q"]),
        np.asarray(wrapped["layers"]["wq"]["w"]["q"]),
    )


def test_lora_mask_shape(params):
    wrapped = add_lora(params, jax.random.key(7), rank=2)
    mask = lora_mask(wrapped)
    assert mask["layers"]["wq"]["lora_a"] is True
    assert mask["layers"]["wq"]["w"] is False
    assert mask["embed"] is False


def test_lora_shards_over_tp(params, tokens):
    from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
    from gofr_tpu.parallel.sharding import param_specs, shard_params

    wrapped = add_lora(params, jax.random.key(8), rank=4)
    mesh = make_mesh(mesh_shape_for(2, tp=2), devices=jax.devices()[:2])
    placed = shard_params(wrapped, mesh, param_specs(wrapped))
    assert len(placed["layers"]["wq"]["lora_b"].sharding.device_set) == 2
    a = _fwd(wrapped, tokens)
    b = _fwd(placed, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_add_lora_leaves_moe_expert_stacks_dense():
    """MoE expert stacks (batched-einsum weights beside a router) must not
    be wrapped — the einsum cannot trace a LoRA dict. Same skip rule as
    quantize_params (quant.moe_skip_keys)."""
    from gofr_tpu.models.lora import is_lora
    from gofr_tpu.models.moe import MoEConfig, init_moe, moe_forward

    cfg = MoEConfig(
        vocab_size=89, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=32, max_seq=64, n_experts=4, top_k=2,
        capacity_factor=2.0, dtype=jnp.float32, attn_impl="xla",
    )
    params = init_moe(jax.random.key(0), cfg)
    wrapped = add_lora(params, jax.random.key(1), rank=2)
    layers = wrapped["layers"]
    for key in ("w_gate", "w_up", "w_down"):
        assert not is_lora(layers[key]), f"{key} must stay a dense stack"
    assert is_lora(layers["wq"]), "attention weights beside the router wrap"
    tokens = jax.random.randint(jax.random.key(2), (2, 8), 0, cfg.vocab_size)
    base_logits, _ = moe_forward(params, tokens, cfg)
    lora_logits, _ = moe_forward(wrapped, tokens, cfg)
    # fresh adapters are identity: the wrapped MoE must trace AND match
    np.testing.assert_allclose(
        np.asarray(base_logits), np.asarray(lora_logits), rtol=1e-5, atol=1e-5
    )


def test_add_lora_rejects_w8a8_base():
    """w8a8 is a serving mode: the activation round has zero gradient, so
    QLoRA over it must fail loudly, not train on silent zeros."""
    from gofr_tpu.models.quant import quantize_params

    base = quantize_params(init_transformer(jax.random.key(4), TINY), "w8a8")
    with pytest.raises(ValueError, match="w8a8"):
        add_lora(base, jax.random.key(5))
