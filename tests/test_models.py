"""Model family tests (CPU, tiny configs)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import (
    BertConfig,
    MLPConfig,
    bert_embed,
    decode_step,
    init_bert,
    init_cache,
    init_mlp,
    init_transformer,
    mlp_forward,
    prefill,
    transformer_forward,
)

from gofr_tpu.models.llama import CONFIGS, TINY
from gofr_tpu.models.quant import (
    dequantize_params,
    quantization_error,
    quantize_params,
)

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

CFG = TINY

# jitted entry points (compiled once per shape; eager JAX on this CPU build
# is far too slow for per-op dispatch in tests)
_fwd = jax.jit(lambda p, t: transformer_forward(p, t, CFG))
_prefill = jax.jit(lambda p, t, c: prefill(p, t, c, CFG))
_decode = jax.jit(lambda p, t, c: decode_step(p, t, c, CFG))


@pytest.fixture(scope="module")
def params():
    return init_transformer(jax.random.key(0), CFG)


def test_mlp_forward_shape_and_jit():
    cfg = MLPConfig(in_dim=8, hidden_dim=16, out_dim=4)
    p = init_mlp(jax.random.key(0), cfg)
    x = jnp.ones((3, 8))
    y = jax.jit(mlp_forward)(p, x)
    assert y.shape == (3, 4)
    assert bool(jnp.isfinite(y).all())


def test_transformer_forward_shape(params):
    tokens = jnp.ones((2, 10), jnp.int32)
    logits = _fwd(params, tokens)
    assert logits.shape == (2, 10, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_transformer_causality(params):
    t1 = jax.random.randint(jax.random.key(1), (1, 8), 0, CFG.vocab_size)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % CFG.vocab_size)
    l1 = _fwd(params, t1)
    l2 = _fwd(params, t2)
    # logits strictly before the changed position are identical
    np.testing.assert_allclose(np.asarray(l1[:, :-1]), np.asarray(l2[:, :-1]), atol=1e-5)


def test_prefill_matches_full_forward(params):
    tokens = jax.random.randint(jax.random.key(2), (2, 12), 0, CFG.vocab_size)
    full = _fwd(params, tokens)[:, -1]
    cache = init_cache(CFG, batch=2, max_seq=32)
    logits, cache = _prefill(params, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full), rtol=1e-4, atol=1e-4)
    assert cache["lengths"].tolist() == [12, 12]


def test_decode_matches_prefill(params):
    """Greedy decode step-by-step must reproduce full-sequence logits."""
    tokens = jax.random.randint(jax.random.key(3), (1, 9), 0, CFG.vocab_size)
    prompt, tail = tokens[:, :5], tokens[:, 5:]

    cache = init_cache(CFG, batch=1, max_seq=32)
    logits, cache = _prefill(params, prompt, cache)
    stepwise = [logits]
    for i in range(tail.shape[1]):
        logits, cache = _decode(params, tail[:, i : i + 1], cache)
        stepwise.append(logits)

    for i in range(len(stepwise)):
        full = _fwd(params, tokens[:, : 5 + i])[:, -1]
        np.testing.assert_allclose(
            np.asarray(stepwise[i]), np.asarray(full), rtol=2e-4, atol=2e-4
        )


def test_bert_embedding_shape_and_mask():
    cfg = BertConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, hidden_dim=64, max_seq=16,
        dtype=jnp.float32, attn_impl="xla",
    )
    p = init_bert(jax.random.key(4), cfg)
    tokens = jax.random.randint(jax.random.key(5), (2, 10), 0, 128)
    mask = jnp.ones((2, 10), jnp.int32).at[:, 7:].set(0)
    embed_fn = jax.jit(lambda p, t, m: bert_embed(p, t, m, cfg))
    emb = embed_fn(p, tokens, mask)
    assert emb.shape == (2, 32)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(emb), axis=-1), 1.0, rtol=1e-5)
    # padding content must not affect the embedding
    tokens2 = tokens.at[:, 7:].set(0)
    emb2 = embed_fn(p, tokens2, mask)
    np.testing.assert_allclose(np.asarray(emb), np.asarray(emb2), atol=1e-5)


def test_quantization_roundtrip_error_small():
    w = jax.random.normal(jax.random.key(6), (64, 32))
    assert quantization_error(w) < 0.02


def test_quantized_forward_close(params):
    tokens = jax.random.randint(jax.random.key(7), (1, 6), 0, CFG.vocab_size)
    base = _fwd(params, tokens)
    qparams = quantize_params(params)
    # int8 leaves present
    assert qparams["layers"]["wq"]["q"].dtype == jnp.int8
    quant = jax.jit(lambda p, t: transformer_forward(p, t, CFG))(qparams, tokens)
    base_probs = jax.nn.softmax(base[:, -1])
    quant_probs = jax.nn.softmax(quant[:, -1])
    # distributions stay close under weight-only int8
    assert float(jnp.abs(base_probs - quant_probs).sum()) < 0.15

    # dequantize restores plain arrays usable by the same forward
    deq = dequantize_params(qparams, jnp.float32)
    deq_logits = jax.jit(lambda p, t: transformer_forward(p, t, CFG))(deq, tokens)
    np.testing.assert_allclose(np.asarray(quant), np.asarray(deq_logits), rtol=1e-3, atol=1e-3)


def test_int4_roundtrip_and_mm():
    from gofr_tpu.models.quant import (
        dequantize_array_int4,
        mm,
        quantize_array_int4,
    )

    w = jax.random.normal(jax.random.key(8), (256, 32), jnp.float32)
    packed = quantize_array_int4(w)
    assert packed["q4"].dtype == jnp.int4
    assert packed["scale"].shape == (2, 32)  # 256 / 128 groups
    back = dequantize_array_int4(packed, jnp.float32)
    rel = float(jnp.sqrt(jnp.mean((w - back) ** 2)) / jnp.sqrt(jnp.mean(w ** 2)))
    assert rel < 0.2  # 4-bit grid, group-wise scales
    # group-wise scales must beat one per-channel scale over the same grid
    per_channel = w / jnp.maximum(jnp.max(jnp.abs(w), axis=0, keepdims=True) / 7.0, 1e-8)
    coarse = jnp.round(jnp.clip(per_channel, -7, 7)) * jnp.maximum(
        jnp.max(jnp.abs(w), axis=0, keepdims=True) / 7.0, 1e-8
    )
    rel_coarse = float(
        jnp.sqrt(jnp.mean((w - coarse) ** 2)) / jnp.sqrt(jnp.mean(w ** 2))
    )
    assert rel < rel_coarse
    # mm against the packed dict == matmul against the dequantized weight
    x = jax.random.normal(jax.random.key(9), (3, 256), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(mm(x, packed)), np.asarray(x @ back), rtol=1e-4, atol=1e-4
    )


def test_int4_forward_close(params):
    tokens = jax.random.randint(jax.random.key(10), (1, 6), 0, CFG.vocab_size)
    base = _fwd(params, tokens)
    qparams = quantize_params(params, "int4")
    assert qparams["layers"]["wq"]["q4"].dtype == jnp.int4
    quant = jax.jit(lambda p, t: transformer_forward(p, t, CFG))(qparams, tokens)
    base_probs = jax.nn.softmax(base[:, -1])
    quant_probs = jax.nn.softmax(quant[:, -1])
    assert float(jnp.abs(base_probs - quant_probs).sum()) < 0.35
    # dequantize restores plain arrays usable by the same forward
    deq = dequantize_params(qparams, jnp.float32)
    deq_logits = jax.jit(lambda p, t: transformer_forward(p, t, CFG))(deq, tokens)
    np.testing.assert_allclose(
        np.asarray(quant), np.asarray(deq_logits), rtol=1e-3, atol=1e-3
    )


def test_quantizer_for_rejects_unknown_mode():
    from gofr_tpu.models.quant import quantizer_for

    with pytest.raises(ValueError, match="int8, int4, or w8a8"):
        quantizer_for("fp4")
    assert quantizer_for("") is None and quantizer_for(None) is None


def test_int4_init_matches_quantize_after():
    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.transformer import init_transformer

    a = init_transformer(jax.random.key(3), TINY, quantize="int4")
    b = quantize_params(init_transformer(jax.random.key(3), TINY), "int4")
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_ragged_prefill_ignores_padding(params):
    """A prompt padded to a bucket must yield the same logits and decode
    behavior as the unpadded prompt (per-request lengths)."""
    tokens = jax.random.randint(jax.random.key(8), (1, 5), 0, CFG.vocab_size)
    # unpadded reference
    cache_a = init_cache(CFG, batch=1, max_seq=32)
    ref, cache_a = _prefill(params, tokens, cache_a)
    # padded to bucket 8 with garbage padding
    padded = jnp.concatenate([tokens, jnp.full((1, 3), 7, jnp.int32)], axis=1)
    cache_b = init_cache(CFG, batch=1, max_seq=32)
    got, cache_b = prefill(params, padded, cache_b, CFG, lengths=jnp.array([5], jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)
    assert int(cache_b["lengths"][0]) == 5
    # decode after padded prefill matches decode after exact prefill
    nxt = jnp.argmax(got, axis=-1)[:, None].astype(jnp.int32)
    la, _ = _decode(params, nxt, cache_a)
    lb, _ = _decode(params, nxt, cache_b)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=2e-4, atol=2e-4)


def test_ragged_batch_mixed_lengths(params):
    """Two requests with different prompt lengths in one bucket."""
    t_a = jax.random.randint(jax.random.key(9), (1, 7), 0, CFG.vocab_size)
    t_b = jax.random.randint(jax.random.key(10), (1, 4), 0, CFG.vocab_size)
    # individual references
    ra, _ = prefill(params, t_a, init_cache(CFG, 1, 32), CFG)
    rb, _ = prefill(params, t_b, init_cache(CFG, 1, 32), CFG)
    # batched: pad b to 7
    batch_tokens = jnp.concatenate(
        [t_a, jnp.concatenate([t_b, jnp.zeros((1, 3), jnp.int32)], axis=1)]
    )
    lengths = jnp.array([7, 4], jnp.int32)
    logits, cache = prefill(params, batch_tokens, init_cache(CFG, 2, 32), CFG, lengths=lengths)
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(ra[0]), rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(np.asarray(logits[1]), np.asarray(rb[0]), rtol=3e-4, atol=3e-4)
    assert cache["lengths"].tolist() == [7, 4]


def test_cache_max_seq_bound():
    with pytest.raises(ValueError, match="RoPE"):
        init_cache(CFG, 1, CFG.max_seq + 1)


def test_quantized_bert_forward():
    cfg = BertConfig(
        vocab_size=64, dim=16, n_layers=1, n_heads=2, hidden_dim=32, max_seq=8,
        dtype=jnp.float32, attn_impl="xla",
    )
    p = init_bert(jax.random.key(11), cfg)
    qp = quantize_params(p)
    tokens = jnp.ones((1, 4), jnp.int32)
    mask = jnp.ones((1, 4), jnp.int32)
    base = bert_embed(p, tokens, mask, cfg)
    quant = bert_embed(qp, tokens, mask, cfg)
    assert float(jnp.abs(base - quant).max()) < 0.05


def test_named_configs_have_llama_shapes():
    cfg = CONFIGS["llama3-8b"]
    assert cfg.dim == 4096 and cfg.n_layers == 32 and cfg.n_kv_heads == 8
    assert CONFIGS["llama3-70b"].hidden_dim == 28672


def test_quantized_init_matches_quantize_after():
    import jax
    import numpy as np

    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.quant import quantize_params
    from gofr_tpu.models.transformer import init_transformer

    a = init_transformer(jax.random.key(3), TINY, quantize=True)
    b = quantize_params(init_transformer(jax.random.key(3), TINY))
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- W8A8 (int8 weights AND activations: the MXU int8 serving mode) ----------

def test_w8a8_mm_matches_manual_oracle():
    """mm on a {"q8","scale"} pack == explicit per-token quant + int8 dot
    + two-scale rescale, computed by hand."""
    from gofr_tpu.models.quant import mm, quantize_array_w8a8

    w = jax.random.normal(jax.random.key(20), (64, 48), jnp.float32)
    x = jax.random.normal(jax.random.key(21), (5, 64), jnp.float32)
    packed = quantize_array_w8a8(w)
    assert packed["q8"].dtype == jnp.int8

    sx = jnp.maximum(jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0, 1e-8)
    qx = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int32)
    oracle = (
        (qx @ packed["q8"].astype(jnp.int32)).astype(jnp.float32)
        * sx * packed["scale"].reshape(1, -1)
    )
    np.testing.assert_allclose(
        np.asarray(mm(x, packed)), np.asarray(oracle), rtol=1e-6, atol=1e-6
    )


def test_w8a8_quantize_params_keeps_lm_head_weight_only(params):
    from gofr_tpu.models.quant import is_quantized, is_quantized_w8a8

    qparams = quantize_params(params, "w8a8")
    assert is_quantized_w8a8(qparams["layers"]["wq"])
    # logits matmul stays weight-only: activation noise must not flip argmax
    assert is_quantized(qparams["lm_head"])


def test_w8a8_forward_close(params):
    tokens = jax.random.randint(jax.random.key(22), (1, 6), 0, CFG.vocab_size)
    base = _fwd(params, tokens)
    qparams = quantize_params(params, "w8a8")
    quant = jax.jit(lambda p, t: transformer_forward(p, t, CFG))(qparams, tokens)
    base_probs = jax.nn.softmax(base[:, -1])
    quant_probs = jax.nn.softmax(quant[:, -1])
    # per-token activation quant adds noise on top of weight-only int8:
    # distributions stay close, bound looser than the 0.15 weight-only one
    assert float(jnp.abs(base_probs - quant_probs).sum()) < 0.25
    # dequantize restores plain arrays usable by the same forward
    deq = dequantize_params(qparams, jnp.float32)
    deq_logits = jax.jit(lambda p, t: transformer_forward(p, t, CFG))(deq, tokens)
    assert np.isfinite(np.asarray(deq_logits)).all()


def test_w8a8_moe_experts_stay_dense():
    from gofr_tpu.models.moe import MoEConfig, init_moe, moe_forward
    from gofr_tpu.models.quant import is_quantized_w8a8

    cfg = MoEConfig(
        vocab_size=89, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=32, max_seq=64, n_experts=4, top_k=2,
        capacity_factor=2.0, dtype=jnp.float32, attn_impl="xla",
    )
    qparams = quantize_params(init_moe(jax.random.key(23), cfg), "w8a8")
    layers = qparams["layers"]
    for key in ("w_gate", "w_up", "w_down"):
        assert not is_quantized_w8a8(layers[key])
    assert is_quantized_w8a8(layers["wq"])
    tokens = jax.random.randint(jax.random.key(24), (2, 8), 0, cfg.vocab_size)
    logits, _ = moe_forward(qparams, tokens, cfg)
    assert np.isfinite(np.asarray(logits)).all()


def test_w8a8_param_specs_shard_like_int8():
    from gofr_tpu.parallel.sharding import param_specs

    qparams = quantize_params(init_transformer(jax.random.key(25), TINY), "w8a8")
    specs = param_specs(qparams)
    wq = specs["layers"]["wq"]
    assert set(wq) == {"q8", "scale"}
    # the q8 spec matches what the int8 pack of the same tree gets
    int8_specs = param_specs(
        quantize_params(init_transformer(jax.random.key(25), TINY), "int8")
    )
    assert wq["q8"] == int8_specs["layers"]["wq"]["q"]


def test_w8a8_init_matches_quantize_after():
    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.transformer import init_transformer

    a = init_transformer(jax.random.key(3), TINY, quantize="w8a8")
    b = quantize_params(init_transformer(jax.random.key(3), TINY), "w8a8")
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
