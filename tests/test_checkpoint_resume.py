"""Checkpoint/resume (SURVEY §5 A4): a training run interrupted at step
k and resumed from its saved state must continue EXACTLY like the
uninterrupted run — params, optimizer moments, and step all round-trip
through orbax."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.transformer import TransformerConfig
from gofr_tpu.training.checkpoint import (
    latest_step,
    restore_params,
    restore_train_state,
    save_params,
    save_train_state,
)
from gofr_tpu.training.trainer import (
    default_optimizer,
    init_train_state,
    make_train_step,
)

# XLA-compile-dominated module: deselect with -m 'not slow'
pytestmark = pytest.mark.slow

CFG = TransformerConfig(
    vocab_size=64, dim=16, n_layers=2, n_heads=2, n_kv_heads=2,
    hidden_dim=32, max_seq=32, dtype=jnp.float32, attn_impl="xla",
)


def _batches(n):
    rng = np.random.RandomState(7)
    return [jnp.asarray(rng.randint(1, 60, (2, 16)), jnp.int32)
            for _ in range(n)]


def test_resume_matches_uninterrupted_run(tmp_path):
    opt = default_optimizer(lr=1e-2)
    step_fn = make_train_step(CFG, opt)
    batches = _batches(4)

    # uninterrupted: 4 steps
    s = init_train_state(jax.random.key(0), CFG, opt)
    for b in batches:
        s, ref_metrics = step_fn(s, b)

    # interrupted: 2 steps, save, RESTORE, 2 more
    s2 = init_train_state(jax.random.key(0), CFG, opt)
    for b in batches[:2]:
        s2, _ = step_fn(s2, b)
    save_train_state(str(tmp_path), s2["params"], s2["opt_state"],
                     int(s2["step"]))
    assert latest_step(str(tmp_path)) == 2
    # an interrupted save leaves orbax tmp dirs beside good checkpoints:
    # resume must skip them, not crash (latest_step parsed them once)
    (tmp_path / "state_3.orbax-checkpoint-tmp-1712345").mkdir()
    assert latest_step(str(tmp_path)) == 2
    # ``like`` carries the optax namedtuple structure the checkpoint
    # cannot describe — restoring without it yields raw dicts the
    # optimizer cannot consume (the bug this test originally caught).
    # Built ABSTRACTLY: a concrete init just for structure would double
    # peak memory at restore time
    like = jax.eval_shape(
        lambda: init_train_state(jax.random.key(9), CFG, opt)
    )
    restored = restore_train_state(str(tmp_path), like=like)
    assert int(restored["step"]) == 2
    s3 = {
        "params": restored["params"],
        "opt_state": restored["opt_state"],
        "step": jnp.asarray(restored["step"], jnp.int32),
    }
    for b in batches[2:]:
        s3, metrics = step_fn(s3, b)
    assert int(s3["step"]) == 4
    # bit-for-bit continuation: loss and every param leaf agree
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        s3["params"], s["params"],
    )


def test_latest_step_and_missing_state(tmp_path):
    assert latest_step(str(tmp_path)) is None            # empty dir
    assert latest_step(str(tmp_path / "nope")) is None   # missing dir
    with pytest.raises(FileNotFoundError, match="no training state"):
        restore_train_state(str(tmp_path))


def test_params_roundtrip_with_target(tmp_path):
    from gofr_tpu.models.transformer import init_transformer

    params = init_transformer(jax.random.key(3), CFG)
    save_params(str(tmp_path / "ckpt"), params)
    # typed restore (like=) places onto the target's structure/dtypes
    like = jax.tree.map(lambda a: jnp.zeros_like(a), params)
    back = restore_params(str(tmp_path / "ckpt"), like=like)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        back, params,
    )
