"""Pooled speculative decoding, compile-free (tier-1): the whole
control flow — zero-weight n-gram drafting, the deterministic
SPEC_FAKE_ACCEPT schedule, batched-verify accounting, paged-KV
rollback, the adaptive-k controller with its brownout/deadline clamps,
and journal resume — driven through the echo runner, plus the unit
surface of tpu/spec_pool.py. Output bit-identity with the plain decode
loop is the anchor invariant: speculation may only move
tokens-per-dispatch, never a single emitted token."""

import os
import threading

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.deadline import Deadline, activate_deadline, clamp_spec_k
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.batcher import verify_width, verify_width_ladder
from gofr_tpu.tpu.device import new_device
from gofr_tpu.tpu.spec_pool import (
    AdaptiveK,
    FakeDraft,
    NgramDraft,
    PoolSpecConfig,
    SpecRequestState,
    parse_fake_accept,
)


def _device(**env):
    defaults = {"MODEL_NAME": "echo", "BATCH_MAX_SIZE": "4",
                "BATCH_TIMEOUT_MS": "1"}
    defaults.update(env)
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry()), old
    except BaseException:
        _restore(old)
        raise


def _restore(old):
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


# -- n-gram drafting -----------------------------------------------------------

def test_ngram_proposes_continuation_of_most_recent_match():
    d = NgramDraft([1, 2, 3, 9, 1, 2, 3], n_max=3)
    # trailing [1,2,3] matched at the start; continuation there was [9,...]
    assert d.propose(2) == [9, 1]


def test_ngram_prefers_longer_grams():
    # trailing [5, 6]: the 2-gram match (-> 7) must win over the more
    # recent 1-gram match of [6] (-> 8)
    d = NgramDraft([5, 6, 7, 6, 8, 5, 6], n_max=3)
    assert d.propose(1) == [7]


def test_ngram_miss_returns_empty_and_extend_learns():
    d = NgramDraft([1, 2, 3, 4], n_max=3)
    assert d.propose(3) == []
    d.extend([1, 2])  # now the tail [1, 2] has an earlier occurrence
    assert d.propose(2) == [3, 4]


def test_ngram_k_zero_and_tiny_context():
    assert NgramDraft([1, 2, 3]).propose(0) == []
    assert NgramDraft([7]).propose(4) == []


def test_ngram_validates_bounds():
    with pytest.raises(ValueError):
        NgramDraft([1], n_max=0)
    with pytest.raises(ValueError):
        NgramDraft([1], n_max=1, n_min=2)


# -- fake-accept schedule ------------------------------------------------------

def test_parse_fake_accept():
    assert parse_fake_accept("3,1,0") == (3, 1, 0)
    assert parse_fake_accept(" 2 ") == (2,)
    with pytest.raises(ValueError):
        parse_fake_accept("-1")
    with pytest.raises(ValueError):
        parse_fake_accept(",")


def test_fake_draft_follows_schedule():
    f = FakeDraft((2, 0))
    truth = [10, 11, 12]
    assert f.propose_against(truth, 3) == [10, 11, 13]  # 2 right, 1 wrong
    assert f.propose_against(truth, 3) == [11, 12, 13]  # 0 right
    assert f.propose_against(truth, 3) == [10, 11, 13]  # schedule cycles


# -- adaptive k ----------------------------------------------------------------

def test_adaptive_k_starts_optimistic_and_tracks_acceptance():
    a = AdaptiveK(4)
    assert a.current() == 4  # optimistic first cycle
    for _ in range(20):
        a.observe(4, 4)
    assert a.current() == 4
    for _ in range(20):
        a.observe(4, 2)  # 50% acceptance settles around k=2
    assert 1 <= a.current() <= 2


def test_adaptive_k_degrades_to_plain_and_probes():
    a = AdaptiveK(4)
    for _ in range(30):
        a.observe(4, 0)
    ks = [a.current() for _ in range(16)]
    assert ks.count(0) >= 12  # degraded: mostly plain decode
    assert 1 in ks  # ...with a periodic probe so recovery is possible


def test_adaptive_k_recovers_after_probe_success():
    a = AdaptiveK(4)
    for _ in range(30):
        a.observe(4, 0)
    for _ in range(20):
        a.observe(1, 1)  # probes start accepting
    assert a.current() >= 1


def test_adaptive_k_validates():
    with pytest.raises(ValueError):
        AdaptiveK(0)


# -- serving clamps ------------------------------------------------------------

def test_clamp_spec_k_brownout_levels():
    assert clamp_spec_k(4, brownout_level=0) == 4
    assert clamp_spec_k(4, brownout_level=1) == 1
    assert clamp_spec_k(4, brownout_level=2) == 0
    assert clamp_spec_k(0, brownout_level=0) == 0


def test_clamp_spec_k_deadline_budget():
    generous = Deadline(10.0)
    assert clamp_spec_k(4, deadline=generous, cadence_s=0.1) == 4
    tight = Deadline(0.25)  # ~2 chunks of budget -> at most 1 draft
    assert clamp_spec_k(4, deadline=tight, cadence_s=0.1) <= 1
    spent = Deadline(0.0)
    assert clamp_spec_k(4, deadline=spent, cadence_s=0.1) == 0
    # no cadence sample yet: the clamp stays out of the way
    assert clamp_spec_k(4, deadline=tight, cadence_s=0.0) == 4


# -- verify width cohorts ------------------------------------------------------

def test_verify_width_ladder():
    assert verify_width_ladder(4) == (2, 4, 5)
    assert verify_width_ladder(1) == (2,)
    assert verify_width(0, 4) == 1
    assert verify_width(1, 4) == 2
    assert verify_width(3, 4) == 4
    assert verify_width(4, 4) == 5  # clamped at k_max + 1
    with pytest.raises(ValueError):
        verify_width(-1, 4)


def test_widths_cover_every_dispatched_k():
    # the worker never dispatches a zero-draft cycle, so the ladder
    # covers k >= 1 (width 1 would be a dead boot-time compile)
    ladder = verify_width_ladder(7)
    for k in range(1, 8):
        assert verify_width(k, 7) in ladder
        assert verify_width(k, 7) >= k + 1


# -- spec request state --------------------------------------------------------

def test_spec_state_commit_and_tokens_per_dispatch():
    s = SpecRequestState([1, 2, 3], pending=4, k_max=4)
    s.commit([5, 6, 7], drafted=4, accepted=2)
    assert s.pending == 7
    assert s.draft.context == [1, 2, 3, 4, 5, 6, 7]
    s.note_plain([8])
    assert s.pending == 8
    assert s.tokens_per_dispatch == 2.0  # 4 tokens over 2 dispatches
    assert s.drafted == 4 and s.accepted == 2


# -- echo runner: bit-identity -------------------------------------------------

PROMPTS = ([5, 6, 7, 8], [9], [3, 1, 4, 1, 5, 9, 2, 6], list(range(40)))
LENS = (17, 6, 1, 33)


def _outputs(dev):
    return [
        dev.generate(p, max_new_tokens=n) for p, n in zip(PROMPTS, LENS)
    ]


def test_spec_ngram_bit_identical_to_plain():
    plain_dev, old = _device(SPEC_POOLED="off")
    try:
        want = _outputs(plain_dev)
    finally:
        plain_dev.close()
        _restore(old)
    spec_dev, old = _device(SPEC_POOLED="on", SPEC_K_MAX="4")
    try:
        assert _outputs(spec_dev) == want
        stats = spec_dev.runner.spec_stats
        assert stats["cycles"] > 0
        assert stats["drafted"] >= stats["accepted"] > 0
    finally:
        spec_dev.close()
        _restore(old)


@pytest.mark.parametrize("schedule", ["0", "3,1,0,2", "1", "0,0,4"])
def test_spec_fake_schedule_bit_identical(schedule):
    """Every accept/reject mix — full rollback included — emits exactly
    the plain stream."""
    plain_dev, old = _device(SPEC_POOLED="off")
    try:
        want = _outputs(plain_dev)
    finally:
        plain_dev.close()
        _restore(old)
    spec_dev, old = _device(SPEC_POOLED="on", SPEC_FAKE_ACCEPT=schedule)
    try:
        assert _outputs(spec_dev) == want
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_seeded_sampler_bit_identical():
    """Seeded sampling rides the same spec cycles on echo (the runner
    is sampler-agnostic) — output must still match the plain path."""
    from gofr_tpu.ops.sampling import Sampler

    plain_dev, old = _device(SPEC_POOLED="off")
    try:
        want = plain_dev.generate(
            [5, 6, 7], max_new_tokens=12,
            sampler=Sampler(temperature=0.7, seed=42),
        )
    finally:
        plain_dev.close()
        _restore(old)
    spec_dev, old = _device(SPEC_POOLED="on")
    try:
        got = spec_dev.generate(
            [5, 6, 7], max_new_tokens=12,
            sampler=Sampler(temperature=0.7, seed=42),
        )
        assert got == want
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_respects_stop_tokens_mid_burst():
    plain_dev, old = _device(SPEC_POOLED="off")
    try:
        full = plain_dev.generate([5, 6, 7, 8], max_new_tokens=12)
        stop_tok = full[6]
        want = plain_dev.generate([5, 6, 7, 8], max_new_tokens=12,
                                  stop_tokens=[stop_tok])
    finally:
        plain_dev.close()
        _restore(old)
    spec_dev, old = _device(SPEC_POOLED="on")
    try:
        got = spec_dev.generate([5, 6, 7, 8], max_new_tokens=12,
                                stop_tokens=[stop_tok])
        assert got == want == full[: full.index(stop_tok)]
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_cancellation_stops_emission():
    spec_dev, old = _device(SPEC_POOLED="on")
    try:
        stop = threading.Event()
        seen = []

        def on_token(t):
            seen.append(t)
            if len(seen) >= 3:
                stop.set()

        out = spec_dev.generate([1, 2, 3, 4], max_new_tokens=64,
                                on_token=on_token, stop=stop)
        assert 3 <= len(out) < 64
    finally:
        spec_dev.close()
        _restore(old)


# -- journal resume ------------------------------------------------------------

def test_spec_resume_from_matches_uninterrupted_tail():
    spec_dev, old = _device(SPEC_POOLED="on")
    try:
        full = spec_dev.generate([4, 5, 6], max_new_tokens=15)
        tail = spec_dev.generate([4, 5, 6], max_new_tokens=15,
                                 resume_from=7)
        assert tail == full[7:]
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_resume_under_fake_full_reject():
    spec_dev, old = _device(SPEC_POOLED="on", SPEC_FAKE_ACCEPT="0")
    try:
        full = spec_dev.generate([4, 5, 6], max_new_tokens=10)
        assert spec_dev.generate(
            [4, 5, 6], max_new_tokens=10, resume_from=4
        ) == full[4:]
    finally:
        spec_dev.close()
        _restore(old)


# -- paged-KV rollback ---------------------------------------------------------

def test_spec_rollback_releases_all_blocks_at_finish():
    """Full-reject schedule + tiny blocks: every cycle writes drafts
    into the paged KV and rolls them back; at finish the pool must
    balance — nothing active, no refcount drift (the leak invariant
    extended to the rollback path)."""
    spec_dev, old = _device(SPEC_POOLED="on", SPEC_FAKE_ACCEPT="0,2,1",
                            KV_BLOCKS="64", KV_BLOCK_TOKENS="4")
    try:
        pool = spec_dev.runner.kv_pool
        out = spec_dev.generate([5, 6, 7, 8], max_new_tokens=21)
        assert len(out) == 21
        st = pool.stats()
        assert st["active"] == 0  # only cache entries hold blocks
        assert st["free"] + st["cached"] == st["total"]
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_rollback_abort_returns_to_baseline():
    spec_dev, old = _device(SPEC_POOLED="on", KV_BLOCKS="64",
                            KV_BLOCK_TOKENS="4", PREFIX_CACHE="0")
    try:
        pool = spec_dev.runner.kv_pool
        spec_dev.runner.paged.pool.cache_clear()
        baseline = pool.stats()["free"]
        stop = threading.Event()

        def on_token(t, _n=[0]):
            _n[0] += 1
            if _n[0] >= 5:
                stop.set()

        spec_dev.generate([1, 2, 3, 4, 5], max_new_tokens=64,
                          on_token=on_token, stop=stop)
        # cancelled: the aborted sequence releases EVERYTHING it held
        # beyond the prompt's cache entry — speculative writes included
        # (the admission path cached the prompt itself; live refs = 0)
        st = pool.stats()
        assert st["active"] == 0
        assert st["free"] + st["cached"] == st["total"]
        assert st["free"] >= baseline - st["cached"]
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_rollback_exercises_cow_on_shared_boundary():
    """A cached conversation shares blocks with the next admission;
    speculative appends must COW the shared boundary before writing
    drafts — and a full reject must leave the donor entry intact."""
    spec_dev, old = _device(SPEC_POOLED="on", SPEC_FAKE_ACCEPT="0",
                            KV_BLOCKS="64", KV_BLOCK_TOKENS="8")
    try:
        pool = spec_dev.runner.kv_pool
        first = spec_dev.generate([5, 6, 7], max_new_tokens=6)
        cows = pool.stats()["cow_copies"]
        second = spec_dev.generate([5, 6, 7], max_new_tokens=6)
        assert second == first  # exact repeat, through aliased blocks
        assert pool.stats()["cow_copies"] > cows
        assert pool.stats()["active"] == 0
    finally:
        spec_dev.close()
        _restore(old)


def test_hostpagedkv_rollback_contract():
    import numpy as np

    from gofr_tpu.tpu.kv_blocks import (
        BlockPool,
        HostPagedKV,
        HostTokenArena,
    )

    arena = HostTokenArena(32, 4)
    pool = BlockPool(32, 4, arena=arena)
    eng = HostPagedKV(pool, arena, lcp_min=4)
    seq = eng.admit(np.arange(1, 7, dtype=np.int32), 8)
    for t in (10, 11, 12):
        eng.append(seq, t)
    blocks_before = list(seq.table.blocks)
    eng.rollback(seq, 7)  # reject 11, 12
    # length rolled back, capacity kept (an admitted request must never
    # re-allocate mid-decode)
    assert seq.table.length == 7
    assert seq.table.blocks == blocks_before
    eng.append(seq, 13)
    assert list(arena.read(seq.table)) == [1, 2, 3, 4, 5, 6, 10, 13]
    with pytest.raises(ValueError):
        eng.rollback(seq, 3)  # below the prompt
    with pytest.raises(ValueError):
        eng.rollback(seq, 99)  # past the length
    eng.abort(seq)
    assert pool.stats()["active"] == 0


# -- observability -------------------------------------------------------------

def test_spec_metrics_and_flight_record():
    from gofr_tpu.telemetry import FlightRecord, activate_record

    spec_dev, old = _device(SPEC_POOLED="on")
    try:
        record = FlightRecord("echo", "test")
        activate_record(record)
        try:
            spec_dev.generate([5, 6, 7, 8], max_new_tokens=17)
        finally:
            activate_record(None)
        assert record.spec_dispatches > 0
        assert record.spec_drafted >= record.spec_accepted > 0
        assert record.tokens_per_dispatch > 1.0
        d = record.to_dict()
        assert d["spec_drafted"] == record.spec_drafted
        assert d["tokens_per_dispatch"] == record.tokens_per_dispatch
        text = spec_dev.metrics.expose()
        assert 'gofr_tpu_spec_accept_ratio{model="echo"}' in text
        assert 'gofr_tpu_spec_tokens_per_dispatch{model="echo"}' in text
        # the solo-path acceptance gauge reads the shared spec_stats too
        assert 'gofr_tpu_spec_acceptance{model="echo"}' in text
    finally:
        spec_dev.close()
        _restore(old)


def test_slo_reports_tokens_per_dispatch_percentiles():
    from gofr_tpu.telemetry import FlightRecorder, activate_record

    recorder = FlightRecorder()
    for tpd_tokens in (2, 4, 6):
        record = recorder.start("echo", "generate")
        record.note_spec(4, tpd_tokens - 1, tpd_tokens)
        recorder.finish(record)
    activate_record(None)  # start() binds the contextvar — don't leak it
    slo = recorder.slo(window_s=60.0)
    tpd = slo["models"]["echo"]["tokens_per_dispatch"]
    assert tpd["p50"] == 4.0
    assert tpd["p99"] >= tpd["p50"] >= 2.0


# -- brownout + deadline interaction ------------------------------------------

def test_brownout_level_disables_speculation():
    spec_dev, old = _device(SPEC_POOLED="on")
    try:
        cfg = spec_dev.runner.spec_pooled
        cfg.brownout_level = lambda: 2  # force hard brownout
        stats = spec_dev.runner.spec_stats
        before = dict(stats)
        out = spec_dev.generate([5, 6, 7, 8], max_new_tokens=9)
        assert len(out) == 9
        with spec_dev.runner._spec_lock:
            drafted = stats["drafted"] - before["drafted"]
            cycles = stats["cycles"] - before["cycles"]
        assert drafted == 0  # level 2: plain decode, one token per cycle
        assert cycles == 9
    finally:
        spec_dev.close()
        _restore(old)


def test_spec_deadline_expires_mid_decode():
    from gofr_tpu.errors import DeadlineExceeded

    spec_dev, old = _device(SPEC_POOLED="on", ECHO_STEP_MS="20")
    try:
        token = activate_deadline(Deadline(0.12))
        try:
            with pytest.raises(DeadlineExceeded) as err:
                spec_dev.generate([1, 2, 3], max_new_tokens=512)
            assert err.value.stage in ("decode", "admission")
        finally:
            activate_deadline(None)
            del token
    finally:
        spec_dev.close()
        _restore(old)


def test_fake_schedule_never_reaches_the_real_pool():
    """SPEC_FAKE_ACCEPT is echo scaffolding: the fake source drafts
    against a known TRUE continuation, which the real pool does not
    have — handed to the pool it would draft nothing forever while
    still clamping pipeline depth. The device must strip it from the
    pool's config (and a state without any source must draft nothing
    rather than fall through to a half-armed one)."""
    spec_dev, old = _device(SPEC_POOLED="on", SPEC_FAKE_ACCEPT="2,0")
    try:
        # the echo runner keeps the schedule...
        assert spec_dev.runner.spec_pooled.fake_schedule == (2, 0)
        # ...and the pool-facing build strips it
        pool_cfg = spec_dev._build_spec_cfg(include_fake=False)
        assert pool_cfg.fake_schedule is None
        state = pool_cfg.new_state([1, 2, 3, 1, 2], 3)
        assert state.propose(3) != []  # n-gram still drafts
    finally:
        spec_dev.close()
        _restore(old)


def test_state_without_a_draft_source_drafts_nothing():
    s = SpecRequestState([1, 2, 3, 1, 2], pending=3, k_max=4,
                         ngram=False)
    assert s.propose(4) == []


def test_spec_config_validation():
    with pytest.raises(ValueError):
        PoolSpecConfig(k_max=0)
    # a typo must fail at construction (_device restores the env when
    # the boot raises)
    with pytest.raises(ValueError):
        _device(SPEC_POOLED="on", SPEC_K_MAX="0")
    # SPEC_POOLED without any draft source is a config error
    with pytest.raises(ValueError):
        _device(SPEC_POOLED="on", SPEC_NGRAM="off")
