"""Paged KV allocator (tpu/kv_blocks.py): BlockPool invariants under
unit and fuzzed workloads (no double-free, no leak, refcounts never
negative), copy-on-write, LRU eviction under budget, the admission
ledger, and the host paged engine's aliasing fidelity — all
compile-free (the device arena's scatter/gather roundtrip is the one
small-jit exception)."""

import random

import numpy as np
import pytest

from gofr_tpu.metrics import Registry
from gofr_tpu.tpu.kv_blocks import (
    BlockPool,
    BlockTable,
    HostPagedKV,
    HostTokenArena,
    KVExhausted,
    blocks_for,
)


def _pool(n=16, bt=4, **kw):
    arena = HostTokenArena(n, bt)
    return BlockPool(n, bt, arena=arena, **kw), arena


# -- allocator invariants -----------------------------------------------------

def test_alloc_release_roundtrip():
    pool, _ = _pool()
    a = pool.alloc(5)
    assert len(a) == 5 and len(set(a)) == 5
    st = pool.stats()
    assert st["free"] == 11 and st["active"] == 5
    pool.release_blocks(a)
    assert pool.stats()["free"] == 16


def test_exhaustion_raises_and_counts():
    pool, _ = _pool(n=4)
    pool.alloc(4)
    with pytest.raises(KVExhausted):
        pool.alloc(1)
    assert pool.stats()["kv_exhausted_rejects"] == 1


def test_double_free_raises():
    pool, _ = _pool()
    (b,) = pool.alloc(1)
    pool.release_blocks([b])
    with pytest.raises(RuntimeError, match="double free"):
        pool.release_blocks([b])


def test_incref_of_free_block_raises():
    pool, _ = _pool()
    (b,) = pool.alloc(1)
    pool.release_blocks([b])
    with pytest.raises(RuntimeError, match="use-after-free"):
        pool.incref([b])


def test_scratch_block_never_allocated():
    pool = BlockPool(8, 4, scratch=True)
    got = pool.alloc(7)  # everything allocatable
    assert 0 not in got
    assert pool.total_blocks == 7
    with pytest.raises(KVExhausted):
        pool.alloc(1)


def test_reserve_ensure_trim():
    pool, _ = _pool(n=16, bt=4)
    t = pool.reserve(10)  # 3 blocks of capacity, length 0
    assert len(t.blocks) == 3 and t.length == 0
    pool.ensure(t, 22)  # grow to 6 blocks
    assert len(t.blocks) == 6
    t.length = 9  # only 3 blocks actually used
    assert pool.trim(t) == 3
    assert len(t.blocks) == 3
    pool.release(t)
    assert pool.stats()["free"] == 16 and t.blocks == []


# -- aliasing + copy-on-write -------------------------------------------------

def test_rollback_then_finish_releases_every_rejected_block():
    """The spec reject path end to end at the allocator level: drafts
    appended across block boundaries, rolled back, the sequence
    finished — blocks for rejected tokens must all come back (at
    finish, via trim: live sequences keep their reserved capacity so
    an admitted request can never starve mid-decode)."""
    arena = HostTokenArena(16, 2)
    pool = BlockPool(16, 2, arena=arena)
    engine = HostPagedKV(pool, arena, lcp_min=4)
    seq = engine.admit(np.asarray([1, 2, 3], np.int32), 8)
    capacity = len(seq.table.blocks)
    for t in (10, 11, 12, 13, 14):  # spec drafts across 3 boundaries
        engine.append(seq, t)
    engine.rollback(seq, 4)  # keep one draft, reject four
    # live rollback retains capacity (reserved at admission)...
    assert len(seq.table.blocks) == capacity
    assert seq.table.length == 4
    engine.finish(seq, store=False)
    # ...and finish returns EVERYTHING the request held, rejected-draft
    # blocks included (admission cached the prompt entry by design —
    # purging it must balance the pool to empty)
    assert pool.stats()["active"] == 0
    pool.cache_clear()
    assert pool.stats()["free"] == pool.stats()["total"]


def test_alias_shares_blocks_and_survives_donor_release():
    pool, arena = _pool(bt=4)
    donor = pool.reserve(8)
    arena.write(donor, 0, np.arange(8))
    donor.length = 8
    al = pool.alias(donor, 8)
    assert al.blocks == donor.blocks
    pool.release(donor)
    # aliased blocks still alive (refcounted), content intact
    assert list(arena.read(al)) == list(range(8))
    pool.release(al)
    assert pool.stats()["free"] == 16


def test_cow_boundary_copies_shared_partial_block():
    pool, arena = _pool(bt=4)
    donor = pool.reserve(6)
    arena.write(donor, 0, np.arange(6))
    donor.length = 6
    al = pool.alias(donor, 6)  # boundary block (tokens 4-5) shared
    pool.cow_boundary(al)
    assert al.blocks[-1] != donor.blocks[-1]  # private copy now
    assert pool.stats()["cow_copies"] == 1
    pool.ensure(al, 7)
    arena.write(al, 6, [99])
    al.length = 7
    # the donor's view is untouched by the alias's append
    assert list(arena.read(donor)) == list(range(6))
    assert list(arena.read(al)) == list(range(6)) + [99]


def test_cow_noop_when_private_or_aligned():
    pool, arena = _pool(bt=4)
    t = pool.reserve(6)
    arena.write(t, 0, np.arange(6))
    t.length = 6
    assert pool.cow_boundary(t) is None  # private
    t.length = 4
    al = pool.alias(t, 4)
    assert pool.cow_boundary(al) is None  # block-aligned boundary


# -- cache registry + eviction ------------------------------------------------

def _cached_seq(pool, arena, tokens):
    t = pool.reserve(len(tokens))
    arena.write(t, 0, np.asarray(tokens, np.int32))
    t.length = len(tokens)
    pool.cache_put(np.asarray(tokens, np.int32).tobytes(), t, {"length": len(tokens)})
    return t


def test_cache_put_lookup_lru_bound():
    pool, arena = _pool(n=32, bt=4, cache_entries=2)
    for i in range(4):
        _cached_seq(pool, arena, [i] * 5)
    st = pool.stats()
    assert st["cached_entries"] == 2
    assert st["evictions"] == 2
    # oldest evicted, newest present
    assert pool.cache_lookup(np.asarray([0] * 5, np.int32).tobytes()) is None
    assert pool.cache_lookup(np.asarray([3] * 5, np.int32).tobytes()) is not None


def test_allocation_pressure_evicts_lru_cache():
    pool, arena = _pool(n=8, bt=4)
    _cached_seq(pool, arena, [1] * 8)   # 2 blocks
    _cached_seq(pool, arena, [2] * 8)   # 2 blocks
    live = pool.alloc(4)                # remaining free blocks
    assert pool.stats()["free"] == 0
    got = pool.alloc(2)                 # must evict the LRU entry
    assert pool.stats()["evictions"] == 1
    assert pool.cache_lookup(np.asarray([1] * 8, np.int32).tobytes()) is None
    assert pool.cache_lookup(np.asarray([2] * 8, np.int32).tobytes()) is not None
    pool.release_blocks(live + got)


def test_eviction_spares_blocks_shared_with_live_requests():
    pool, arena = _pool(n=8, bt=4)
    t = _cached_seq(pool, arena, list(range(16)))  # 4 blocks cached
    al = pool.alias(t, 16)  # a live request shares the entry's blocks
    pool.alloc(4)  # the other half of the arena
    with pytest.raises(KVExhausted):
        # the entry's blocks are pinned by the live alias, so eviction
        # could free NOTHING: the doomed alloc must fail upfront, not
        # wipe the cache as collateral
        pool.alloc(2)
    assert pool.stats()["evictions"] == 0
    key = np.asarray(list(range(16)), np.int32).tobytes()
    assert pool.cache_lookup(key) is not None  # entry survived
    assert list(arena.read(al)) == list(range(16))  # content intact
    pool.release(al)  # the live alias drops: blocks become reclaimable
    got = pool.alloc(2)  # NOW eviction frees them and the alloc lands
    assert pool.stats()["evictions"] == 1
    assert pool.cache_lookup(key) is None
    pool.release_blocks(got)


def test_cache_clear_releases_everything():
    pool, arena = _pool(n=16, bt=4)
    for i in range(3):
        _cached_seq(pool, arena, [i] * 6)
    pool.cache_clear()
    st = pool.stats()
    assert st["free"] == 16 and st["cached_entries"] == 0
    assert st["evictions"] == 0  # administrative purge, not pressure


# -- admission ledger ---------------------------------------------------------

def test_ledger_reserve_release_and_exhaustion():
    pool, _ = _pool(n=8, bt=4)
    r1 = pool.reserve_ledger(20)  # 5 blocks of an 8-block ledger
    assert r1 == 5
    with pytest.raises(KVExhausted):
        pool.reserve_ledger(16)  # 4 more don't fit
    r2 = pool.reserve_ledger(12)  # 3 do
    assert pool.stats()["reserved"] == 8
    pool.release_ledger(r1)
    # freed budget admits the next request immediately
    assert pool.reserve_ledger(20) == 5
    pool.release_ledger(r2)


def test_ledger_treats_cached_blocks_as_reclaimable():
    pool, arena = _pool(n=8, bt=4)
    _cached_seq(pool, arena, [7] * 32)  # cache fills the whole arena
    assert pool.stats()["cached"] == 8
    # admission still succeeds: cached blocks evict on demand
    r = pool.reserve_ledger(32)
    assert r == 8
    pool.release_ledger(r)


def test_separate_ledger_budget():
    pool = BlockPool(4, 4, ledger_blocks=10)
    r = pool.reserve_ledger(40)  # 10 blocks, beyond the 4 physical
    assert r == 10
    with pytest.raises(KVExhausted):
        pool.reserve_ledger(4)
    pool.release_ledger(r)


# -- metrics ------------------------------------------------------------------

def test_block_state_gauge_and_eviction_counter():
    registry = Registry()
    arena = HostTokenArena(8, 4)
    pool = BlockPool(8, 4, arena=arena, cache_entries=1, metrics=registry)
    _cached_seq(pool, arena, [1] * 8)
    _cached_seq(pool, arena, [2] * 8)  # evicts the first (entry bound)
    text = registry.expose()
    assert 'gofr_tpu_kv_blocks{state="total"} 8' in text
    assert 'gofr_tpu_kv_blocks{state="cached"} 2' in text
    assert "gofr_tpu_kv_evictions_total 1" in text


# -- fuzz: allocator invariants under random workloads ------------------------

def test_fuzzed_alloc_alias_cow_evict_invariants():
    """Randomized sequences of reserve/ensure/alias/COW/append/finish/
    release against live invariant checks: refcounts consistent, no
    leaks (everything released -> all free), cached accounting exact,
    and every table reads back exactly the tokens written through it."""
    rng = random.Random(1234)
    for round_ in range(20):
        n_blocks, bt = rng.choice([(12, 2), (24, 4), (48, 3)])
        arena = HostTokenArena(n_blocks, bt)
        pool = BlockPool(
            n_blocks, bt, arena=arena,
            cache_entries=rng.choice([0, 2, 4]),
        )
        engine = HostPagedKV(pool, arena, lcp_min=2)
        live = []  # (seq, expected_tokens, decode_budget_left)
        next_tok = 1
        for _ in range(120):
            op = rng.random()
            if op < 0.45:  # admit a new sequence
                size = rng.randint(1, 2 * bt + 1)
                prompt = np.arange(next_tok, next_tok + size) % 251
                next_tok += size
                if rng.random() < 0.3 and live:
                    # force sharing: reuse an existing prompt's tokens
                    prompt = live[rng.randrange(len(live))][1][:size].copy()
                    if prompt.size == 0:
                        continue
                max_new = rng.randint(0, bt)
                try:
                    seq = engine.admit(prompt, max_new)
                except KVExhausted:
                    continue
                assert list(engine.prompt_tokens(seq)) == list(prompt)
                live.append((seq, np.asarray(prompt, np.int32), max_new))
            elif op < 0.65 and live:  # append (COW path)
                i = rng.randrange(len(live))
                seq, toks, budget = live[i]
                if budget <= 0:  # reservation cap: appends never allocate
                    continue
                t = int(next_tok % 251)
                next_tok += 1
                engine.append(seq, t)
                live[i] = (seq, np.append(toks, t).astype(np.int32),
                           budget - 1)
            elif op < 0.8 and live:  # speculative drafts + rollback
                i = rng.randrange(len(live))
                seq, toks, budget = live[i]
                if budget <= 0:
                    continue
                k = rng.randint(1, budget)
                drafts = [int((next_tok + j) % 251) for j in range(k)]
                next_tok += k
                base = seq.table.length
                for t in drafts:
                    engine.append(seq, t)  # speculative writes (COW too)
                keep = rng.randint(0, k)  # verify keeps a prefix
                engine.rollback(seq, base + keep)
                live[i] = (
                    seq,
                    np.append(toks, drafts[:keep]).astype(np.int32),
                    budget - keep,
                )
            elif live:  # finish (store or abort)
                i = rng.randrange(len(live))
                seq, toks, _ = live.pop(i)
                read = arena.read(seq.table)
                assert list(read) == list(toks), (round_, list(read), list(toks))
                engine.finish(seq, store=rng.random() < 0.7)
            # standing invariants
            st = pool.stats()
            assert st["free"] + st["cached"] + st["active"] == st["total"]
            assert st["free"] >= 0 and st["cached"] >= 0 and st["active"] >= 0
        # drain: every content check then full release
        for seq, toks, _ in live:
            assert list(arena.read(seq.table)) == list(toks)
            engine.abort(seq)
        pool.cache_clear()
        st = pool.stats()
        assert st["free"] == st["total"], (round_, st)  # no leak
        assert st["cached"] == 0 and st["active"] == 0


# -- host engine: aliasing fidelity + continuous admission --------------------

def _engine(n=64, bt=4, lcp_min=4, copy_mode=False, cache_entries=8):
    arena = HostTokenArena(n, bt)
    pool = BlockPool(n, bt, arena=arena, cache_entries=cache_entries)
    return HostPagedKV(pool, arena, lcp_min=lcp_min, copy_mode=copy_mode)


def test_aliased_and_copy_paths_read_identical_tokens():
    """THE bit-identity property: the copy-free aliased path returns
    exactly the tokens the slot-model copy path returns, for exact and
    LCP partial hits."""
    prompts = [
        [5, 6, 7, 8, 9, 10, 11, 12],
        [5, 6, 7, 8, 9, 10, 11, 12],          # exact repeat
        [5, 6, 7, 8, 9, 10, 99, 98, 97],      # LCP partial
        [5, 6, 7, 8, 42],                      # shorter LCP
    ]
    outs = {}
    for mode in (False, True):
        eng = _engine(copy_mode=mode)
        got = []
        for p in prompts:
            seq = eng.admit(np.asarray(p, np.int32), 4)
            got.append(list(eng.prompt_tokens(seq)))
            for t in (71, 72):
                eng.append(seq, t)
            assert list(eng.arena.read(seq.table)) == list(p) + [71, 72]
            eng.finish(seq)
        outs[mode] = got
    assert outs[False] == outs[True]
    # and the paged mode actually aliased: exact repeat cost 0 copies
    eng = _engine()
    a = eng.admit(np.asarray(prompts[0], np.int32), 0)
    eng.finish(a)
    before = eng.pool.stats()["copied_kv_bytes"]
    b = eng.admit(np.asarray(prompts[0], np.int32), 0)
    assert b.kind == "hit" and b.aliased_blocks == len(b.table.blocks)
    assert eng.pool.stats()["copied_kv_bytes"] == before  # copy-free
    eng.finish(b)


def test_partial_hit_aliases_whole_blocks_only():
    eng = _engine(bt=4, lcp_min=4)
    a = eng.admit(np.asarray([1, 2, 3, 4, 5, 6], np.int32), 0)
    eng.finish(a)
    b = eng.admit(np.asarray([1, 2, 3, 4, 5, 9, 9], np.int32), 0)
    assert b.kind == "partial_hit"
    assert b.aliased_blocks == 1  # tokens 1-4 shared; 5 sits mid-block
    assert list(eng.prompt_tokens(b)) == [1, 2, 3, 4, 5, 9, 9]
    eng.finish(b)


def test_admission_exhaustion_rolls_back_cleanly():
    eng = _engine(n=8, bt=4, cache_entries=0)
    seq = eng.admit(np.asarray([1] * 8, np.int32), 8)  # 4 blocks
    free_before = eng.pool.stats()["free"]
    with pytest.raises(KVExhausted):
        eng.admit(np.asarray([2] * 24, np.int32), 8)  # needs > free
    assert eng.pool.stats()["free"] == free_before  # full rollback
    eng.finish(seq, store=False)
    # the prompt entry (2 aliased blocks) survives the finish — the
    # doomed admission above must NOT have wiped it
    assert eng.pool.stats()["free"] == 6
    assert eng.pool.stats()["cached"] == 2
    eng.pool.cache_clear()
    assert eng.pool.stats()["free"] == 8


def test_freed_blocks_admit_waiting_request_mid_flight():
    """Continuous batching at block granularity: B cannot admit while A
    holds the arena; the moment A finishes, B admits — while C (admitted
    small) is still mid-decode."""
    eng = _engine(n=12, bt=4, cache_entries=0)
    a = eng.admit(np.asarray([1] * 16, np.int32), 16)  # 8 blocks
    c = eng.admit(np.asarray([3] * 8, np.int32), 4)    # 3 blocks, mid-decode
    eng.append(c, 30)
    with pytest.raises(KVExhausted):
        eng.admit(np.asarray([2] * 16, np.int32), 0)   # 4 blocks: only 1 free
    eng.finish(a, store=False)                          # A's blocks free NOW
    b = eng.admit(np.asarray([2] * 16, np.int32), 0)   # admitted mid-flight
    eng.append(c, 31)                                   # C still decoding fine
    assert list(eng.arena.read(c.table))[-2:] == [30, 31]
    eng.finish(b, store=False)
    eng.finish(c, store=False)


# -- device arena: block <-> row bridge (small jit, CPU-fast) -----------------

def test_jax_arena_scatter_gather_roundtrip_and_skip():
    import jax.numpy as jnp

    from gofr_tpu.models.llama import CONFIGS
    from gofr_tpu.tpu.kv_blocks import JaxKVArena

    cfg = CONFIGS["tiny"]  # max_seq 128
    bt = 32
    arena = JaxKVArena(cfg, n_blocks=9, block_tokens=bt)
    pool = BlockPool(9, bt, block_bytes=arena.block_bytes, scratch=True)

    def row_of(seed, length):
        import numpy as _np

        rng = _np.random.default_rng(seed)
        shape = (cfg.n_layers, 1, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim)
        r = rng.standard_normal(shape, dtype=_np.float32)
        return {
            "k": jnp.asarray(r, cfg.cache_dtype),
            "v": jnp.asarray(-r, cfg.cache_dtype),
            "lengths": jnp.asarray([length], jnp.int32),
        }

    length = 70  # 3 blocks, boundary mid-block
    row = row_of(1, length)
    t = pool.reserve(length)
    t.length = length
    copied = arena.scatter_row(row, t)
    assert copied == 3 * arena.block_bytes
    back = arena.gather_row(t, length)
    # bit-identical for every valid position; lengths mirrors the request
    assert int(back["lengths"][0]) == length
    for f in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(back[f][:, :, :length]),
            np.asarray(row[f][:, :, :length]),
        )
    # skip_blocks: an aliased prefix keeps its DONOR content even when a
    # different row is scattered over the same table
    other = row_of(2, length)
    copied2 = arena.scatter_row(other, t, skip_blocks=2)
    assert copied2 == 1 * arena.block_bytes
    back2 = arena.gather_row(t, length)
    for f in ("k", "v"):
        np.testing.assert_array_equal(  # first 2 blocks: original content
            np.asarray(back2[f][:, :, : 2 * bt]),
            np.asarray(row[f][:, :, : 2 * bt]),
        )
        np.testing.assert_array_equal(  # third block: the new row's
            np.asarray(back2[f][:, :, 2 * bt : length]),
            np.asarray(other[f][:, :, 2 * bt : length]),
        )


def test_jax_arena_rejects_non_tiling_block_size():
    from gofr_tpu.models.llama import CONFIGS
    from gofr_tpu.tpu.kv_blocks import JaxKVArena

    with pytest.raises(ValueError, match="must divide"):
        JaxKVArena(CONFIGS["tiny"], n_blocks=4, block_tokens=48)


def test_jax_arena_sharded_over_tp_matches_unsharded():
    """JaxKVArena(mesh=tp-only): k/v shard their head axis across the
    tp devices, and scatter/gather through the sharded arena is
    bit-identical to the single-device arena — sharding is placement,
    never numerics."""
    import jax
    import jax.numpy as jnp

    from gofr_tpu.models.llama import CONFIGS
    from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
    from gofr_tpu.tpu.kv_blocks import JaxKVArena

    cfg = CONFIGS["tiny"]  # 2 kv heads: tp=2 puts one head per device
    bt = 32
    mesh = make_mesh(mesh_shape_for(2, tp=2), devices=jax.devices()[:2])
    sharded = JaxKVArena(cfg, n_blocks=9, block_tokens=bt, mesh=mesh)
    plain = JaxKVArena(cfg, n_blocks=9, block_tokens=bt)
    assert len(sharded.k.sharding.device_set) == 2

    rng = np.random.default_rng(3)
    length = 70
    shape = (cfg.n_layers, 1, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim)
    r = rng.standard_normal(shape, dtype=np.float32)
    row = {
        "k": jnp.asarray(r, cfg.cache_dtype),
        "v": jnp.asarray(-r, cfg.cache_dtype),
        "lengths": jnp.asarray([length], jnp.int32),
    }
    for arena in (sharded, plain):
        pool = BlockPool(9, bt, block_bytes=arena.block_bytes, scratch=True)
        t = pool.reserve(length)
        t.length = length
        assert arena.scatter_row(row, t) == 3 * arena.block_bytes
        back = arena.gather_row(t, length)
        for f in ("k", "v"):
            np.testing.assert_array_equal(
                np.asarray(back[f][:, :, :length]),
                np.asarray(row[f][:, :, :length]),
            )


def test_jax_arena_mesh_rejects_indivisible_heads():
    import jax

    from gofr_tpu.models.llama import CONFIGS
    from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
    from gofr_tpu.tpu.kv_blocks import JaxKVArena

    mesh = make_mesh(mesh_shape_for(4, tp=4), devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="n_kv_heads=2 not divisible by tp=4"):
        JaxKVArena(CONFIGS["tiny"], n_blocks=5, block_tokens=32, mesh=mesh)


# -- host arena shards (echo host-mesh mode) ----------------------------------

def test_host_arena_sharded_write_read_fidelity():
    """shards=2: every block's token span splits across two fake
    devices; writes landing across shard boundaries reassemble exactly,
    and COW copies preserve content — checked against the unsharded
    arena on identical traffic."""
    ids = (np.arange(37, dtype=np.int32) * 11) % 127 + 1
    for shards in (1, 2, 4):
        arena = HostTokenArena(16, 8, shards=shards)
        pool = BlockPool(16, 8, arena=arena)
        t = pool.reserve(ids.size)
        t.length = ids.size
        # two writes split mid-shard: offsets 0..20 then 20..37
        arena.write(t, 0, ids[:20])
        arena.write(t, 20, ids[20:])
        np.testing.assert_array_equal(arena.read(t), ids)
        if shards > 1:
            assert sum(arena.shard_writes) > 0
    # COW across shards: partial copy keeps the donor's prefix
    arena = HostTokenArena(16, 8, shards=2)
    pool = BlockPool(16, 8, arena=arena)
    t = pool.reserve(8)
    t.length = 6
    arena.write(t, 0, ids[:6])
    dst = pool.alloc(1)[0]
    arena.copy_partial(dst, t.blocks[0], 6)
    t2 = BlockTable([dst], 6)
    np.testing.assert_array_equal(arena.read(t2), ids[:6])


def test_host_arena_shard_divisibility_enforced():
    with pytest.raises(ValueError, match="tp=3 does not divide"):
        HostTokenArena(8, 8, shards=3)


# -- cross-replica transfer pins (fleet KV handoff) ---------------------------

def test_transfer_pin_release_balances_refcounts():
    """The export path pins an entry's blocks for the wire's lifetime;
    a normal close releases them and the pool balances back to its
    pre-pull state."""
    from gofr_tpu.tpu.kv_blocks import TransferPin

    pool, _ = _pool()
    blocks = pool.alloc(3)
    before = pool.stats()
    pin = TransferPin(pool, blocks, ttl_s=60.0)
    assert not pin.released and not pin.expired
    pin.release()
    assert pin.released
    assert pool.stats() == before
    pool.release_blocks(blocks)
    assert pool.stats()["free"] == 16  # nothing leaked overall


def test_transfer_pin_ttl_guard_covers_a_dead_serving_thread():
    """The refcount-leak regression: a pin whose owner dies mid-send
    (release never called) must NOT leak — the named bounded-lifetime
    timer releases it, and the blocks become evictable again."""
    import time

    from gofr_tpu.tpu.kv_blocks import TransferPin

    pool, _ = _pool()
    blocks = pool.alloc(2)
    before = pool.stats()
    pin = TransferPin(pool, blocks, ttl_s=0.1)
    # the serving thread "dies" here: nobody calls release()
    deadline = time.monotonic() + 5.0
    while not pin.released and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pin.expired and pin.released
    assert pool.stats() == before
    pool.release_blocks(blocks)  # the original refs are still exact


def test_transfer_pin_release_is_idempotent_vs_the_timer():
    """Late releaser after the TTL fired (or double release): a no-op,
    never a double-free."""
    import time

    from gofr_tpu.tpu.kv_blocks import TransferPin

    pool, _ = _pool()
    blocks = pool.alloc(1)
    pin = TransferPin(pool, blocks, ttl_s=0.05)
    deadline = time.monotonic() + 5.0
    while not pin.released and time.monotonic() < deadline:
        time.sleep(0.01)
    pin.release()  # the owner wakes up late
    pin.release()  # and is confused
    st = pool.stats()
    assert st["active"] == 1  # only the caller's own alloc refs remain
    pool.release_blocks(blocks)
    assert pool.stats()["free"] == 16


def test_transfer_pin_keeps_cached_entry_alive_through_eviction():
    """The advertise→pull race the pin exists for: the entry is evicted
    WHILE pinned — its blocks must survive until the pin drops, then
    free."""
    from gofr_tpu.tpu.kv_blocks import TransferPin

    arena = HostTokenArena(8, 4)
    pool = BlockPool(8, 4, arena=arena, cache_entries=4)
    ids = np.arange(1, 9, dtype=np.int32)
    t = pool.reserve(8)
    t.length = 8
    arena.write(t, 0, ids)
    pool.cache_put(ids.tobytes(), t, {"length": 8})
    entry = pool.cache_lookup(ids.tobytes())
    pin = TransferPin(pool, entry.table.blocks, ttl_s=60.0)
    pool.cache_clear()  # eviction mid-transfer
    # the wire can still read the pinned blocks' content
    np.testing.assert_array_equal(
        arena.read(BlockTable(list(pin.blocks), 8)), ids
    )
    pin.release()
    assert pool.stats()["free"] == 8  # eviction completed once unpinned
