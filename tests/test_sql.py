"""SQL datasource tests.

Parity model: db_test.go:19-271 — Select scenarios (tags, snake_case,
unmatched columns), logged queries, tx commit/rollback (SURVEY.md §4)."""

import dataclasses
import threading

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.datasource.sql import DB, new_sql, to_snake_case
from gofr_tpu.logging import Level
from gofr_tpu.testutil import MockLogger


@dataclasses.dataclass
class User:
    id: int = 0
    full_name: str = ""
    email: str = dataclasses.field(default="", metadata={"db": "mail"})


@pytest.fixture
def db():
    logger = MockLogger(Level.DEBUG)
    database = DB(":memory:", logger)
    database.execute("CREATE TABLE users (id INTEGER PRIMARY KEY,"
                     " full_name TEXT, mail TEXT, junk TEXT)")
    database.execute_many(
        "INSERT INTO users (id, full_name, mail, junk) VALUES (?, ?, ?, ?)",
        [(1, "Ada Lovelace", "ada@x.io", "z"), (2, "Alan Turing", "alan@x.io", "z")],
    )
    yield database, logger
    database.close()


def test_to_snake_case():
    assert to_snake_case("FullName") == "full_name"
    assert to_snake_case("ID") == "id"
    assert to_snake_case("HTTPPort") == "http_port"
    assert to_snake_case("simple") == "simple"


def test_select_into_dataclass(db):
    database, _ = db
    users = database.select(User, "SELECT * FROM users ORDER BY id")
    assert len(users) == 2
    assert users[0] == User(1, "Ada Lovelace", "ada@x.io")  # db tag mapped mail->email
    assert users[1].full_name == "Alan Turing"  # snake_case mapping


def test_select_one_and_value(db):
    database, _ = db
    user = database.select_one(User, "SELECT * FROM users WHERE id = ?", 2)
    assert user.email == "alan@x.io"
    assert database.select_one(User, "SELECT * FROM users WHERE id = ?", 99) is None
    assert database.select_value("SELECT COUNT(*) FROM users") == 2
    assert database.select_value("SELECT 2+2") == 4


def test_exec_returns_rowcount_and_logs(db):
    database, logger = db
    n = database.execute("UPDATE users SET junk = ? WHERE id > ?", "y", 0)
    assert n == 2
    assert "UPDATE users SET junk" in logger.output
    assert "duration_us" in logger.output


def test_transaction_commit_and_rollback(db):
    database, logger = db
    with database.begin() as tx:
        tx.execute("INSERT INTO users (id, full_name) VALUES (3, 'Grace')")
    assert database.select_value("SELECT COUNT(*) FROM users") == 3

    with pytest.raises(RuntimeError):
        with database.begin() as tx:
            tx.execute("INSERT INTO users (id, full_name) VALUES (4, 'Nope')")
            raise RuntimeError("abort")
    assert database.select_value("SELECT COUNT(*) FROM users") == 3  # rolled back
    assert "ROLLBACK" in logger.output


def test_memory_db_shared_across_threads(db):
    database, _ = db
    results = []

    def read():
        results.append(database.select_value("SELECT COUNT(*) FROM users"))

    t = threading.Thread(target=read)
    t.start()
    t.join()
    assert results == [2]


def test_select_requires_dataclass(db):
    database, _ = db
    with pytest.raises(TypeError):
        database.select(dict, "SELECT * FROM users")


def test_health_check(db):
    database, _ = db
    h = database.health_check()
    assert h.status == "UP"
    assert "latency_us" in h.details


def test_new_sql_dialect_gating(monkeypatch, tmp_path):
    monkeypatch.setenv("DB_DIALECT", "sqlite")
    monkeypatch.setenv("DB_NAME", str(tmp_path / "t.db"))
    database = new_sql(EnvConfig(), MockLogger())
    database.execute("CREATE TABLE t (x INTEGER)")
    database.close()

    # mysql dialect routes to the wire-protocol client (tests/test_mysql.py
    # covers it against minimysql); a dead port surfaces as a connect error
    monkeypatch.setenv("DB_DIALECT", "mysql")
    monkeypatch.setenv("DB_HOST", "127.0.0.1")
    monkeypatch.setenv("DB_PORT", "1")
    with pytest.raises(OSError):
        new_sql(EnvConfig(), MockLogger())

    monkeypatch.setenv("DB_DIALECT", "cockroach")
    with pytest.raises(RuntimeError, match="unsupported"):
        new_sql(EnvConfig(), MockLogger())
