"""Continuous-batching decode pool: correctness vs solo decode, slot
reuse, saturation fallback, cancellation."""

import os
import threading

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.ops.sampling import Sampler
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import new_device

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def _device(**env):
    defaults = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "1"}
    defaults.update(env)
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry()), old
    except BaseException:
        _restore(old)  # a failed boot must not leak env into later tests
        raise


def _restore(old):
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


@pytest.fixture(scope="module")
def pooled():
    dev, old = _device(DECODE_POOL="on", DECODE_SLOTS="4", DECODE_CHUNK="4")
    yield dev
    dev.close()
    _restore(old)


@pytest.fixture(scope="module")
def solo():
    dev, old = _device(DECODE_POOL="off", DECODE_CHUNK="4")
    yield dev
    dev.close()
    _restore(old)


def test_pool_enabled_by_default():
    dev, old = _device()
    try:
        assert dev.decode_pool is not None
    finally:
        dev.close()
        _restore(old)


def test_submit_rejection_reason_is_counted():
    """A solo-decode fallback must be diagnosable without
    GOFR_POOL_DEBUG: the reject reason lands on
    gofr_tpu_pool_reject_total{reason=...}. DECODE_POOL_PENALTIES=off
    rejects penalized submits deterministically."""
    dev, old = _device(DECODE_POOL_PENALTIES="off")
    try:
        out = dev.generate(
            [3, 1, 4, 1, 5], max_new_tokens=6, sampler=Sampler(presence_penalty=0.5)
        )
        assert len(out) == 6  # the solo fallback still served the request
        counter = dev.metrics.counter(
            "gofr_tpu_pool_reject_total", labels=("reason",)
        )
        assert counter.value(reason="penalties_off") >= 1
    finally:
        dev.close()
        _restore(old)


def test_pooled_greedy_matches_solo(pooled, solo):
    for prompt, n in (([1, 2, 3], 11), ([7] * 30, 6), ([42], 1), ([5, 6], 4)):
        assert pooled.generate(prompt, max_new_tokens=n) == \
            solo.generate(prompt, max_new_tokens=n), (prompt, n)


def test_concurrent_streams_share_the_pool(pooled, solo):
    prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
    want = [solo.generate(p, max_new_tokens=9) for p in prompts]
    got = [None] * 4

    def run(i):
        got[i] = pooled.generate(prompts[i], max_new_tokens=9)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want


def test_slots_recycle_across_many_requests(pooled, solo):
    # 12 sequential requests through 4 slots: reuse must not leak state
    for i in range(12):
        prompt = [(i % 5) + 1, 2, 3]
        assert pooled.generate(prompt, max_new_tokens=5) == \
            solo.generate(prompt, max_new_tokens=5), i


def test_pool_saturation_falls_back_to_solo(pooled, solo):
    # 8 concurrent streams, 4 slots: the overflow must still complete
    prompts = [[i + 1, 9, 9] for i in range(8)]
    want = [solo.generate(p, max_new_tokens=7) for p in prompts]
    got = [None] * 8

    def run(i):
        got[i] = pooled.generate(prompts[i], max_new_tokens=7)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want


def test_seeded_requests_bypass_pool(pooled):
    s = Sampler(temperature=1.0, seed=5)
    s2 = Sampler(temperature=1.0, seed=5)
    a = pooled.generate([1, 2, 3], max_new_tokens=8, sampler=s)
    b = pooled.generate([1, 2, 3], max_new_tokens=8, sampler=s2)
    assert a == b  # exact reproducibility preserved


def test_pooled_sampling_respects_top_k(pooled):
    # temperature>0 unseeded goes through the pool with per-row params;
    # top_k=1 must reduce to greedy
    greedy = pooled.generate([4, 5, 6], max_new_tokens=6)
    via_pool = pooled.generate(
        [4, 5, 6], max_new_tokens=6, sampler=Sampler(temperature=5.0, top_k=1)
    )
    assert via_pool == greedy


def test_pooled_cancellation_frees_slot(pooled):
    stop = threading.Event()
    seen = []

    def on_token(t):
        seen.append(t)
        if len(seen) >= 2:
            stop.set()

    out = pooled.generate([1, 2, 3], max_new_tokens=200, on_token=on_token, stop=stop)
    assert len(out) < 200
    # slot must be free again: another full round completes
    assert len(pooled.generate([1, 2, 3], max_new_tokens=5)) == 5


def test_pool_deadline_admission_reject_accounting(pooled):
    """The submit-time deadline gate: a spent budget rejects with the
    ``deadline`` pool-reject reason stamped on the FlightRecord, the
    ``admission`` stage on the shared counter, and a DeadlineExceeded
    raise (NO solo fallback — solo is slower, not faster)."""
    import time as _time

    from gofr_tpu.deadline import Deadline, activate_deadline
    from gofr_tpu.errors import DeadlineExceeded
    from gofr_tpu.telemetry import FlightRecorder, activate_record

    pool = pooled.decode_pool
    recorder = FlightRecorder()
    record = recorder.start(model="tiny", endpoint="/test")
    expired = Deadline(0.001)
    _time.sleep(0.005)
    try:
        with pool._work:
            with pytest.raises(DeadlineExceeded) as err:
                pool._admit_deadline(expired)
        assert err.value.stage == "admission"
        assert record.pool_reject_reason == "deadline"
        assert record.shed_stage == "admission"
        # a live-but-insufficient budget rejects too once a cadence is
        # observed (cannot cover even one chunk) — but only while rows
        # are DECODING: on an idle pool the cadence is stale (a single
        # anomalous chunk must not wedge the gate into rejecting
        # everything forever) and the chunk runs immediately anyway
        pool._chunk_ema_s = max(pool._chunk_ema_s, 0.05)
        thin = Deadline(0.01)
        with pool._work:
            assert not pool._active
            pool._admit_deadline(thin)  # idle: stale cadence bypassed
            pool._active[0] = pool._slots[0]
            try:
                with pytest.raises(DeadlineExceeded):
                    pool._admit_deadline(Deadline(0.01))
            finally:
                del pool._active[0]
        # a roomy budget admits
        with pool._work:
            pool._admit_deadline(Deadline(30.0))
    finally:
        activate_record(None)
        recorder.finish(record)


def test_pool_deadline_expiry_mid_stream_frees_slot(pooled):
    """Per-chunk row expiry: a deadline that expires mid-generation
    ends the pooled stream with DeadlineExceeded (stage decode), and
    the slot + KV budget are free for the next request."""
    from gofr_tpu.deadline import Deadline, activate_deadline
    from gofr_tpu.errors import DeadlineExceeded

    d = Deadline(30.0)
    seen = []

    def on_token(t):
        seen.append(t)
        if len(seen) == 2:
            # force expiry mid-stream, deterministically (no sleeps):
            # the worker's next per-chunk check sees it
            d.t_deadline = 0.0

    activate_deadline(d)
    try:
        with pytest.raises(DeadlineExceeded) as err:
            pooled.generate([1, 2, 3], max_new_tokens=200,
                            on_token=on_token)
        assert err.value.stage == "decode"
    finally:
        activate_deadline(None)
    assert 0 < len(seen) < 200
    # slot must be free again: another full round completes
    assert len(pooled.generate([1, 2, 3], max_new_tokens=5)) == 5


def test_solo_deadline_expiry_mid_decode(solo):
    """The SOLO path honors the per-chunk decode expiry too: a request
    that fell out of the pool (or a pool-off deployment) must not
    decode unmetered past its budget."""
    from gofr_tpu.deadline import Deadline, activate_deadline
    from gofr_tpu.errors import DeadlineExceeded

    d = Deadline(30.0)
    seen = []

    def on_token(t):
        seen.append(t)
        if len(seen) == 2:
            d.t_deadline = 0.0

    activate_deadline(d)
    try:
        with pytest.raises(DeadlineExceeded) as err:
            solo.generate([1, 2, 3], max_new_tokens=200,
                          on_token=on_token)
        assert err.value.stage == "decode"
    finally:
        activate_deadline(None)
    assert 0 < len(seen) < 200
    # the device serves the next request normally
    assert len(solo.generate([1, 2, 3], max_new_tokens=5)) == 5


def test_cache_bound_in_pool(pooled, solo):
    # tiny max_seq=128; prompt 100 -> at most 28-ish decodes
    out = pooled.generate(list(range(1, 100)), max_new_tokens=300)
    want = solo.generate(list(range(1, 100)), max_new_tokens=300)
    assert out == want
    assert len(out) <= 30


def test_submissions_during_fetch_window_join_next_chunk(pooled, solo):
    # hammer the race: stagger many submissions so some land while the
    # worker is mid-fetch; every stream must still match solo exactly
    import time

    prompts = [[(i % 7) + 1, 3, 9] for i in range(16)]
    want = [solo.generate(p, max_new_tokens=9) for p in prompts]
    got = [None] * len(prompts)

    def run(i):
        time.sleep(0.003 * i)  # staggered arrivals hit fetch windows
        got[i] = pooled.generate(prompts[i], max_new_tokens=9)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want


def test_worker_death_fails_requests_not_hangs():
    dev, old = _device(DECODE_POOL="on", DECODE_SLOTS="2", DECODE_CHUNK="2")
    try:
        pool = dev.decode_pool

        def boom(*a, **k):
            raise RuntimeError("device fell off")

        pool._decode = boom
        with pytest.raises(RuntimeError, match="device fell off"):
            dev.generate([1, 2, 3], max_new_tokens=8)
        # pool is closed; later requests fall back to solo and still work
        out = dev.generate([1, 2, 3], max_new_tokens=4)
        assert len(out) == 4
    finally:
        dev.close()
        _restore(old)


def test_stop_tokens_solo_and_pooled_agree(pooled, solo):
    # pick a token the greedy continuation actually emits, use it as stop
    full = solo.generate([1, 2, 3], max_new_tokens=10)
    assert len(full) == 10
    stop_tok = full[5]
    want = full[: full.index(stop_tok)]
    for dev in (solo, pooled):
        got = dev.generate([1, 2, 3], max_new_tokens=10, stop_tokens=[stop_tok])
        assert got == want, (dev is pooled, got, want)


def test_stop_token_on_first_token(pooled, solo):
    first = solo.generate([1, 2, 3], max_new_tokens=1)[0]
    for dev in (solo, pooled):
        assert dev.generate([1, 2, 3], max_new_tokens=10, stop_tokens=[first]) == []


def test_stop_tokens_in_stream(pooled):
    full = pooled.generate([1, 2, 3], max_new_tokens=10)
    stop_tok = full[4]
    got = list(pooled.generate_stream([1, 2, 3], max_new_tokens=10,
                                      stop_tokens=[stop_tok]))
    assert got == full[: full.index(stop_tok)]


def test_pooled_decode_sets_mbu_gauge(pooled):
    # decode is bandwidth-bound; the pool maintains an MBU gauge (bytes
    # streamed per step / time / peak bw) next to the MFU one
    pooled.generate([1, 2, 3], max_new_tokens=6)
    text = pooled.metrics.expose()
    line = next(
        (ln for ln in text.splitlines()
         if ln.startswith('gofr_tpu_mbu{model="tiny",op="decode"}')),
        None,
    )
    assert line is not None, text
    assert float(line.rsplit(" ", 1)[1]) > 0.0
    assert pooled.decode_pool._bytes_per_step > 0


def test_slot_sampling_knobs_reset_on_free(pooled):
    # a finished sampled request must not leave its temperature on the
    # slot: stale temps defeat the all-greedy lax.cond fast path in
    # sample_logits_rows for every later chunk
    pooled.generate([2, 4, 6], max_new_tokens=4,
                    sampler=Sampler(temperature=0.9, top_k=7, top_p=0.5))
    pool = pooled.decode_pool
    with pool._work:  # settle: delivery runs under this lock
        assert all(t == 0.0 for t in pool._temps), list(pool._temps)
        assert all(k == 0 for k in pool._top_ks), list(pool._top_ks)
        assert all(p == 1.0 for p in pool._top_ps), list(pool._top_ps)


def test_pool_close_mid_stream_raises_not_truncates():
    dev, old = _device(DECODE_POOL="on", DECODE_SLOTS="2", DECODE_CHUNK="2")
    try:
        import time

        results = []

        def run():
            try:
                results.append(("ok", dev.generate([1, 2, 3], max_new_tokens=10_000)))
            except RuntimeError as exc:
                results.append(("err", str(exc)))

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.4)  # mid-stream (tiny max_seq keeps it bounded; chunk=2 is slow)
        dev.decode_pool.close()
        t.join(timeout=10)
        assert results, "generation thread hung"
        kind, value = results[0]
        # either it finished before the close (cache bound) or it errored —
        # never a silently truncated 'ok' shorter than the cache allows
        if kind == "ok":
            assert len(value) >= 100  # ran to the tiny cache bound
        else:
            assert "closed" in value
    finally:
        dev.close()
        _restore(old)


def test_pooled_logprobs_match_solo(pooled, solo):
    """logprobs requests ride the pool (the chosen tokens' log-softmax
    comes back with every chunk): tokens equal the solo path exactly,
    logprobs to float tolerance (the [slots]-batch executable may
    schedule the matmuls differently than the [1]-batch one)."""
    import numpy as np

    for prompt, n in (([1, 2, 3], 11), ([5, 6], 4)):
        pt, plp = pooled.generate(prompt, max_new_tokens=n, logprobs=True)
        st, slp = solo.generate(prompt, max_new_tokens=n, logprobs=True)
        assert pt == st, (prompt, n)
        np.testing.assert_allclose(plp, slp, rtol=1e-4, atol=1e-4)
    # streaming consumers receive (token, logprob) pairs from the pool
    got = []
    out = pooled.generate([1, 2, 3], max_new_tokens=6, logprobs=True,
                          on_token=got.append)
    assert [t for t, _ in got] == out[0]
    assert [lp for _, lp in got] == out[1]


def test_pooled_penalized_logprobs(pooled, solo):
    """Penalties + logprobs pool together; the logprobs stay RAW model
    values (unpenalized log-softmax), matching the solo convention."""
    import numpy as np

    s = dict(presence_penalty=1.5, frequency_penalty=0.5)
    pt, plp = pooled.generate([1, 2, 3], max_new_tokens=8, logprobs=True,
                              sampler=Sampler(**s))
    st, slp = solo.generate([1, 2, 3], max_new_tokens=8, logprobs=True,
                            sampler=Sampler(**s))
    assert pt == st
    np.testing.assert_allclose(plp, slp, rtol=1e-4, atol=1e-4)


def test_top_logprobs_pooled_and_solo(pooled, solo):
    """top_logprobs=True returns the TOP_LOGPROBS alternatives per
    position, best first; greedy's chosen token IS the top-1 entry, and
    the pooled and solo paths agree."""
    import numpy as np

    from gofr_tpu.models.transformer import TOP_LOGPROBS

    for dev in (pooled, solo):
        out, lps, tops = dev.generate([1, 2, 3], max_new_tokens=6,
                                      logprobs=True, top_logprobs=True)
        assert len(out) == len(lps) == len(tops) == 6
        for i, alts in enumerate(tops):
            assert len(alts) == TOP_LOGPROBS
            vals = [v for _, v in alts]
            assert vals == sorted(vals, reverse=True)
            assert alts[0][0] == out[i]  # greedy picks the argmax
            np.testing.assert_allclose(alts[0][1], lps[i], rtol=1e-4,
                                       atol=1e-4)
    p = pooled.generate([1, 2, 3], max_new_tokens=6, logprobs=True,
                        top_logprobs=True)
    s = solo.generate([1, 2, 3], max_new_tokens=6, logprobs=True,
                      top_logprobs=True)
    assert p[0] == s[0]
    assert [[i for i, _ in alts] for alts in p[2]] == \
        [[i for i, _ in alts] for alts in s[2]]


# -- paged KV (KV_PAGED, tpu/kv_blocks.py) ------------------------------------


def _deactivate():
    """Drop the contextvar a recorder.start() activated — a leaked
    active record would bleed into unrelated tests in the same worker."""
    from gofr_tpu.telemetry import activate_record

    activate_record(None)


def test_kv_exhausted_reject_reason_and_solo_fallback():
    """Block starvation is observable at the flight-record level like
    every other reject: with the shared KV ledger pre-claimed, submit
    rejects with reason=kv_exhausted (distinct from slot rejects), the
    request decodes solo and still completes, and releasing the budget
    re-admits pooled requests — continuous admission, no drain wait."""
    from gofr_tpu.telemetry import FlightRecorder

    # tiny max_seq=128, 16-token blocks -> 8 blocks per full sequence
    dev, old = _device(DECODE_POOL="on", DECODE_SLOTS="2", DECODE_CHUNK="2",
                       KV_BLOCKS="8", KV_BLOCK_TOKENS="16")
    try:
        assert dev.kv_pool is not None
        claimed = dev.kv_pool.reserve_ledger(128)  # the whole ledger
        recorder = FlightRecorder()
        rec = recorder.start(model="tiny", endpoint="/t")
        try:
            out = dev.generate([1, 2, 3], max_new_tokens=6)
        finally:
            recorder.finish(rec)
            _deactivate()
        assert len(out) == 6  # solo fallback served it
        assert rec.pool_reject_reason == "kv_exhausted"
        counter = dev.metrics.counter(
            "gofr_tpu_pool_reject_total", labels=("reason",)
        )
        assert counter.value(reason="kv_exhausted") >= 1
        # freed budget admits the next request immediately
        dev.kv_pool.release_ledger(claimed)
        rec2 = recorder.start(model="tiny", endpoint="/t")
        try:
            out2 = dev.generate([1, 2, 3], max_new_tokens=6)
        finally:
            recorder.finish(rec2)
            _deactivate()
        assert out2 == out  # pooled and solo agree (bit-identity)
        assert rec2.pool_reject_reason == ""
        assert rec2.kv_blocks > 0  # pooled admission reserved blocks
        assert dev.kv_pool.stats()["reserved"] == 0  # released at finish
    finally:
        dev.close()
        _restore(old)


def test_paged_pooled_outputs_match_unpaged(pooled, solo):
    """The paged device (block-table prefix cache + ledger admission)
    produces bit-identical pooled output to the unpaged slot model —
    across prefix hits, partial hits, and conversation stores."""
    dev, old = _device(DECODE_POOL="on", DECODE_SLOTS="4", DECODE_CHUNK="4",
                       PREFIX_CACHE="3", PREFIX_LCP_MIN="4",
                       KV_BLOCK_TOKENS="16")
    try:
        assert dev.kv_pool is not None  # paging actually on
        system = [7, 3, 9, 2, 11, 5]
        prompts = [[1, 2, 3], [1, 2, 3], system + [21, 22],
                   system + [31, 32, 33], [5, 6]]
        for p in prompts:
            assert dev.generate(p, max_new_tokens=8) == \
                solo.generate(p, max_new_tokens=8), p
        # multi-turn conversation reuse through the paged store
        reply = dev.generate(system + [41], max_new_tokens=6)
        follow = system + [41] + reply + [42]
        assert dev.generate(follow, max_new_tokens=5) == \
            solo.generate(follow, max_new_tokens=5)
        st = dev.kv_pool.stats()
        assert st["reserved"] == 0  # every reservation released
        assert dev.decode_pool.occupancy()["kv"]["total"] == st["total"]
    finally:
        dev.close()
        _restore(old)
