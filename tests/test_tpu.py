"""TPU datasource tests on the CPU backend (the reference's
sqlmock/miniredis strategy: SURVEY.md §4 — CPU PJRT is the fake)."""

import asyncio
import threading
import time

import numpy as np
import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.errors import TooManyRequestsError
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.batcher import DynamicBatcher, next_pow2, pad_rows
from gofr_tpu.tpu.device import new_device

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


# -- batcher -----------------------------------------------------------------

def test_next_pow2():
    assert [next_pow2(n) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 16]


def test_batcher_coalesces_concurrent_requests():
    batches = []

    def run(payloads):
        batches.append(len(payloads))
        return [p * 2 for p in payloads]

    b = DynamicBatcher(run, max_batch=8, timeout_ms=50)
    futures = [b.submit(i) for i in range(6)]
    results = [f.result(timeout=5) for f in futures]
    assert results == [0, 2, 4, 6, 8, 10]
    assert max(batches) > 1  # actually batched
    b.close()


def test_batcher_deadline_flush_bounds_latency():
    def run(payloads):
        return payloads

    b = DynamicBatcher(run, max_batch=64, timeout_ms=30)
    start = time.perf_counter()
    b.infer("solo", timeout=5)
    elapsed = time.perf_counter() - start
    assert elapsed < 1.0  # flushed by deadline, not stuck waiting for 64
    b.close()


def test_batcher_overflow_sheds_load():
    release = threading.Event()

    def run(payloads):
        release.wait(5)
        return payloads

    b = DynamicBatcher(run, max_batch=1, timeout_ms=1, max_queue=2)
    futures = [b.submit(i) for i in range(2)]
    time.sleep(0.05)
    with pytest.raises(TooManyRequestsError):
        for i in range(8):  # queue of 2 + in-flight; must overflow
            b.submit(i)
    release.set()
    for f in futures:
        f.result(timeout=5)
    b.close()


def test_batcher_propagates_errors():
    def run(payloads):
        raise RuntimeError("device on fire")

    b = DynamicBatcher(run, max_batch=4, timeout_ms=1)
    with pytest.raises(RuntimeError, match="device on fire"):
        b.infer("x", timeout=5)
    b.close()


def test_batcher_async_api():
    def run(payloads):
        return [p + 1 for p in payloads]

    b = DynamicBatcher(run, max_batch=4, timeout_ms=1)

    async def main():
        return await b.infer_async(41)

    assert asyncio.run(main()) == 42
    b.close()


def test_pad_rows():
    rows = [np.ones(3), np.zeros(3)]
    out = pad_rows(rows, 4)
    assert out.shape == (4, 3)
    np.testing.assert_array_equal(out[2], out[1])  # repeats last row


# -- device: MLP -------------------------------------------------------------

@pytest.fixture(scope="module")
def mlp_device(tmp_path_factory):
    import os

    env = {"MODEL_NAME": "mlp", "BATCH_MAX_SIZE": "8", "BATCH_TIMEOUT_MS": "2"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    device = new_device(EnvConfig(), MockLogger(Level.DEBUG), Registry())
    yield device
    device.close()
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_mlp_infer(mlp_device):
    out = mlp_device.infer([0.5] * 64)
    assert out.shape == (16,)
    assert np.isfinite(out).all()


def test_mlp_infer_batched_concurrently(mlp_device):
    results = [None] * 6
    threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(
            i, mlp_device.infer([float(i)] * 64)))
        for i in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r is not None and r.shape == (16,) for r in results)
    # identical inputs give identical outputs regardless of batch packing
    a = mlp_device.infer([1.0] * 64)
    bq = mlp_device.infer([1.0] * 64)
    np.testing.assert_allclose(a, bq, rtol=1e-5)


def test_mlp_invalid_input(mlp_device):
    from gofr_tpu.errors import InvalidParamError

    with pytest.raises(InvalidParamError):
        mlp_device.infer([1.0, 2.0])


def test_device_health_and_metrics(mlp_device):
    h = mlp_device.health_check()
    assert h.status == "UP"
    assert h.details["device_count"] >= 1
    assert "platform" in h.details
    mlp_device.infer([0.0] * 64)
    text = mlp_device.metrics.expose()
    assert "gofr_tpu_requests_total" in text
    assert "gofr_tpu_batch_size" in text
    assert "gofr_tpu_ttft_seconds" in text
    assert "mlp" in mlp_device.describe()


def test_unknown_model_name(monkeypatch):
    monkeypatch.setenv("MODEL_NAME", "gpt-17")
    with pytest.raises(ValueError, match="unknown MODEL_NAME"):
        new_device(EnvConfig(), MockLogger(), Registry())


# -- device: transformer generation ------------------------------------------

@pytest.fixture(scope="module")
def tiny_device():
    import os

    # DECODE_CHUNK=1: token-granular stop/stream semantics for the
    # cancellation tests (chunked decode is covered by
    # test_chunked_decode_matches_stepwise)
    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "2",
           "DECODE_CHUNK": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    device = new_device(EnvConfig(), MockLogger(Level.DEBUG), Registry())
    yield device
    device.close()
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_generate_deterministic_and_streams(tiny_device):
    streamed = []
    out = tiny_device.generate([1, 2, 3], max_new_tokens=5, on_token=streamed.append)
    assert len(out) == 5
    assert out == streamed
    assert all(0 <= t < 256 for t in out)
    again = tiny_device.generate([1, 2, 3], max_new_tokens=5)
    assert again == out  # greedy decode is deterministic


def test_generate_respects_cache_bound(tiny_device):
    # max_seq=128: a long generation stops at the cache bound, no crash
    out = tiny_device.generate(list(range(1, 60)), max_new_tokens=500)
    assert len(out) <= 128


def test_infer_returns_prefill_state(tiny_device):
    state = tiny_device.infer({"tokens": [1, 2, 3, 4]})
    assert state["logits"].shape[-1] == 256
    assert state["length"] == 4


def test_generate_stream_yields_and_completes(tiny_device):
    toks = list(tiny_device.generate_stream([1, 2, 3], max_new_tokens=5))
    assert toks == tiny_device.generate([1, 2, 3], max_new_tokens=5)


def test_generate_stream_close_cancels_decode(tiny_device, monkeypatch):
    # closing the iterator must halt the BACKGROUND decode, observed on the
    # actual closed stream: slow each token down, close after two, then
    # assert production stops (not just that a fresh pre-set event stops)
    import time

    produced = []
    real_generate = tiny_device.generate

    def spy(tokens, max_new_tokens=32, on_token=None, stop=None, **kw):
        def slow_token(t):
            produced.append(t)
            on_token(t)
            time.sleep(0.02)

        return real_generate(
            tokens, max_new_tokens, on_token=slow_token, stop=stop, **kw
        )

    monkeypatch.setattr(tiny_device, "generate", spy)
    it = tiny_device.generate_stream([1, 2, 3], max_new_tokens=100)
    next(it)
    next(it)
    it.close()
    # decode halts at the next step boundary; allow a few in-flight steps
    time.sleep(0.3)
    n_after_close = len(produced)
    assert n_after_close < 20, "decode kept running after the stream closed"
    time.sleep(0.3)
    assert len(produced) == n_after_close, "tokens still being produced after close"


def test_generate_with_preset_stop_event(tiny_device):
    ev = threading.Event()
    ev.set()
    out = tiny_device.generate([1, 2, 3], max_new_tokens=64, stop=ev)
    assert len(out) == 1  # prefill token only; decode loop never entered


def test_stop_event_mid_decode(tiny_device):
    ev = threading.Event()
    seen = []

    def on_token(t):
        seen.append(t)
        if len(seen) == 3:
            ev.set()

    out = tiny_device.generate([1, 2, 3], max_new_tokens=64, on_token=on_token, stop=ev)
    assert len(out) == 3  # stopped at the next step boundary


# -- tokenizer wiring ---------------------------------------------------------

@pytest.fixture(scope="module")
def text_device():
    import os

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "2",
           "TOKENIZER": "byte"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    device = new_device(EnvConfig(), MockLogger(Level.DEBUG), Registry())
    yield device
    device.close()
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_text_payload_infer(text_device):
    state = text_device.infer({"text": "hello"})
    assert state["length"] == 5  # byte-level: one id per byte
    assert "tokenizer=" in text_device.describe()


def test_text_generate_matches_ids(text_device):
    by_text = text_device.generate("hi", max_new_tokens=4)
    by_ids = text_device.generate([ord("h"), ord("i")], max_new_tokens=4)
    assert by_text == by_ids


def test_text_without_tokenizer_rejected(tiny_device):
    from gofr_tpu.errors import InvalidParamError

    with pytest.raises(InvalidParamError, match="tokenizer"):
        tiny_device.infer({"text": "hello"})


def test_out_of_range_ids_rejected(tiny_device):
    from gofr_tpu.errors import InvalidParamError

    with pytest.raises(InvalidParamError, match="token ids"):
        tiny_device.infer({"tokens": [1, 2, 999999]})


def test_chunked_decode_matches_stepwise(tiny_device):
    # the default chunked decode (N steps per dispatch) must emit the same
    # greedy sequence as token-at-a-time decode
    import os

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "2",
           "DECODE_CHUNK": "8"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        chunked = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            for prompt, n in (([1, 2, 3], 13), ([9] * 20, 8), ([4], 1)):
                assert chunked.generate(prompt, max_new_tokens=n) == \
                    tiny_device.generate(prompt, max_new_tokens=n), (prompt, n)
        finally:
            chunked.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_reinit_rebuilds_working_stack(tiny_device):
    before = tiny_device.generate([1, 2, 3], max_new_tokens=5)
    # wedge the stack the way device loss presents: runner calls fail
    tiny_device.runner.run_batch = lambda payloads: (_ for _ in ()).throw(
        RuntimeError("device lost")
    )
    tiny_device.batcher.close()
    tiny_device.reinit()
    after = tiny_device.generate([1, 2, 3], max_new_tokens=5)
    assert after == before  # fresh stack, same params seed
    h = tiny_device.health_check()
    assert h.status == "UP"


def test_auto_reinit_rate_limited(tiny_device):
    import time as time_mod

    tiny_device._last_reinit = time_mod.monotonic()
    assert tiny_device._maybe_auto_reinit() is False  # within the 30s window


def test_model_buckets_limits_warmup_compiles():
    import os

    env = {"MODEL_NAME": "tiny", "MODEL_BUCKETS": "64", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device.runner.buckets == [64]
            out = device.generate([1, 2, 3], max_new_tokens=4)
            assert len(out) == 4
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_mfu_gauge_and_token_counter(tiny_device):
    tiny_device.infer({"tokens": [1, 2, 3, 4, 5]})
    text = tiny_device.metrics.expose()
    assert 'gofr_tpu_mfu{model="tiny",op="prefill"}' in text
    assert 'gofr_tpu_tokens_total{model="tiny",op="prefill"}' in text
    from gofr_tpu.tpu.flops import transformer_param_count

    # analytic count matches the materialized tree
    import jax

    n_leaf = sum(
        int(np.prod(x.shape))
        for x in jax.tree.leaves(tiny_device.runner.params)
    )
    assert transformer_param_count(tiny_device.runner.cfg) == n_leaf


def test_background_boot_and_readiness():
    import os

    env = {"MODEL_NAME": "tiny", "TPU_BOOT": "background", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            # health is UP (alive) even before ready; requests block until
            # warm instead of crashing
            assert device.health_check().status == "UP"
            out = device.generate([1, 2, 3], max_new_tokens=4)
            assert len(out) == 4
            assert device.ready()
            assert device.boot_status["state"] == "ready"
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_failed_background_boot_recovers(monkeypatch):
    """A transient init failure in a background boot is not terminal: the
    health check's rate-limited rebuild path recovers the stack and flips
    readiness back."""
    import os

    import gofr_tpu.tpu.device as device_mod

    calls = {"n": 0}
    orig = device_mod._build_runner

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient init failure")
        return orig(*a, **k)

    monkeypatch.setattr(device_mod, "_build_runner", flaky)
    env = {"MODEL_NAME": "tiny", "TPU_BOOT": "background", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "DECODE_POOL": "off"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device._ready.wait(30)
            assert not device.ready()
            assert device.boot_status["state"] == "failed"
            device._last_reinit = -1e9  # bypass the 30s rate limit for the test
            h = device.health_check()
            assert h.status == "UP" and h.details.get("reinitialized")
            assert device.ready()
            assert len(device.generate([1, 2, 3], max_new_tokens=3)) == 3
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_wedged_device_probe_does_not_block_construction(monkeypatch):
    """jax.devices() can hang on a wedged remote tunnel; with
    TPU_BOOT=background the constructor must return immediately and
    readiness must report the probing stage (the driver-bench postmortem:
    a hang before the server listens emits no diagnostics at all)."""
    import os

    import gofr_tpu.tpu.device as device_mod

    release = threading.Event()
    real_devices = device_mod.jax.devices

    def blocking_devices(*a, **k):
        release.wait(30)
        return real_devices(*a, **k)

    monkeypatch.setattr(device_mod.jax, "devices", blocking_devices)
    env = {"MODEL_NAME": "tiny", "TPU_BOOT": "background", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "DECODE_POOL": "off"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        start = time.perf_counter()
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        construction = time.perf_counter() - start
        try:
            assert construction < 5.0  # not blocked on the wedged probe
            assert not device.ready()
            # poll: the boot thread may not have been scheduled yet
            deadline = time.perf_counter() + 10
            while (
                device.boot_status["detail"] != "probing device runtime"
                and time.perf_counter() < deadline
            ):
                time.sleep(0.01)
            assert device.boot_status["detail"] == "probing device runtime"
            assert device.health_check().status == "UP"  # alive, not ready
            release.set()
            device.wait_ready(60)
            assert len(device.generate([1, 2, 3], max_new_tokens=3)) == 3
        finally:
            release.set()
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_model_max_seq_bounds_cache():
    import os

    env = {"MODEL_NAME": "tiny", "MODEL_MAX_SEQ": "64", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "MODEL_QUANT": "int8"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device.runner.cfg.max_seq == 64
            assert device.runner.buckets[-1] <= 64
            out = device.generate(list(range(1, 50)), max_new_tokens=100)
            assert len(out) <= 64 - 49 + 1  # bounded by the reduced cache
            assert "quant=int8" in device.describe()
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_int4_serving_generates():
    """MODEL_QUANT=int4 boots and serves; packed int4 leaves in the runner
    tree; generation runs through prefill + pooled decode."""
    import os

    import jax.numpy as jnp

    env = {"MODEL_NAME": "tiny", "MODEL_QUANT": "int4", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "DECODE_CHUNK": "4"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device.runner.params["layers"]["wq"]["q4"].dtype == jnp.int4
            out = device.generate([1, 2, 3], max_new_tokens=6)
            assert len(out) == 6
            assert all(0 <= t < device.runner.cfg.vocab_size for t in out)
            assert "quant=int4" in device.describe()
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_chunked_prefill_matches_full_bucket():
    """A prompt longer than the largest compiled bucket prefills through
    bucket-sized chunks into one cache row — generation must equal a
    device whose ladder covers the prompt in a single shot (no
    truncation), and the batched /infer path keeps the recency clip."""
    import os

    base = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1"}
    old = {k: os.environ.get(k) for k in {**base, "MODEL_BUCKETS": None}}
    prompt = [(i % 11) + 1 for i in range(100)]
    try:
        os.environ.update(base)
        os.environ["MODEL_BUCKETS"] = "128"
        full = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            want = full.generate(prompt, max_new_tokens=8)
        finally:
            full.close()
        os.environ["MODEL_BUCKETS"] = "32"
        small = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert small.runner.buckets == [32]
            got = small.generate(prompt, max_new_tokens=8)
            # same tokens from 4 chunked prefills as from one 128-bucket
            assert got == want, (got, want)
            # /infer (batched path) still clips to the top bucket
            clipped = small.infer({"tokens": prompt})
            assert clipped["next_token"] == small.infer(
                {"tokens": prompt[-32:]}
            )["next_token"]
        finally:
            small.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_prefill_chunk_budget_bit_identical_and_bounded():
    """PREFILL_CHUNK_TOKENS: a prompt whose bucket exceeds the budget
    prefills in >= 2 bounded chunks through the warmed budget bucket —
    and the output tokens are BIT-IDENTICAL to the unbudgeted path (the
    chunk-resume contract in models/transformer.py::prefill)."""
    import os

    base = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
            "MODEL_BUCKETS": "16,32,64"}
    old = {k: os.environ.get(k)
           for k in {**base, "PREFILL_CHUNK_TOKENS": None}}
    prompt = [(i % 9) + 1 for i in range(40)]  # the 64 bucket, > 2x budget
    try:
        os.environ.update(base)
        os.environ.pop("PREFILL_CHUNK_TOKENS", None)
        plain = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert plain.runner.prefill_chunk_bucket is None
            want = plain.generate(prompt, max_new_tokens=8)
        finally:
            plain.close()
        os.environ["PREFILL_CHUNK_TOKENS"] = "16"
        registry = Registry()
        budget = new_device(EnvConfig(), MockLogger(Level.INFO), registry)
        try:
            assert budget.runner.prefill_chunk_bucket == 16
            chunks = registry.counter(
                "gofr_tpu_prefill_chunks_total", labels=("model",)
            )
            before = chunks.value(model="tiny")
            got = budget.generate(prompt, max_new_tokens=8)
            assert got == want, (got, want)  # bit-identical to unchunked
            # 40 tokens through a 16-wide budget = 3 bounded dispatches
            assert chunks.value(model="tiny") - before >= 3
        finally:
            budget.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_budgeted_prefill_alongside_pooled_stream():
    """A >1-bucket prompt admitted while a pooled stream decodes: the
    prefill lands in bounded chunks (scheduler-admitted), both requests
    finish with their exact interference-free outputs, and the pool's
    cadence notes flowed through the shared scheduler. (The bounded
    inter-chunk gap itself is asserted deterministically in
    tests/test_scheduler.py — dispatch-order interleaving.)"""
    import os
    import threading

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
           "MODEL_BUCKETS": "16,32,64", "PREFILL_CHUNK_TOKENS": "16",
           "DECODE_CHUNK": "1", "DECODE_SLOTS": "2"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    prompt = [(i % 9) + 1 for i in range(40)]
    try:
        registry = Registry()
        dev = new_device(EnvConfig(), MockLogger(Level.INFO), registry)
        try:
            assert dev.decode_pool is not None
            stream_prompt = [5, 6, 7]
            stream_out: list[int] = []
            first = threading.Event()

            def on_token(t):
                stream_out.append(t)
                first.set()

            worker = threading.Thread(
                target=dev.generate,
                args=(stream_prompt,),
                kwargs={"max_new_tokens": 80, "on_token": on_token},
            )
            worker.start()
            assert first.wait(60)  # the pooled stream is live
            chunks = registry.counter(
                "gofr_tpu_prefill_chunks_total", labels=("model",)
            )
            before = chunks.value(model="tiny")
            got = dev.generate(prompt, max_new_tokens=4)
            worker.join(timeout=120)
            assert not worker.is_alive()
            # the long prefill went through in bounded chunks mid-traffic
            assert chunks.value(model="tiny") - before >= 3
            assert dev.scheduler.stats["decode_chunks"] >= 1
            # neither request perturbed the other: greedy outputs equal
            # their interference-free reruns exactly
            assert got == dev.generate(prompt, max_new_tokens=4)
            assert stream_out == dev.generate(stream_prompt, max_new_tokens=80)
        finally:
            dev.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_attn_impl_override():
    import os

    env = {"MODEL_NAME": "tiny", "MODEL_ATTN_IMPL": "xla", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device.runner.cfg.attn_impl == "xla"
            assert len(device.generate([1, 2, 3], max_new_tokens=4)) == 4
        finally:
            device.close()
        os.environ["MODEL_ATTN_IMPL"] = "nope"
        with pytest.raises(ValueError, match="MODEL_ATTN_IMPL"):
            new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_bad_model_quant_fails_fast():
    import os

    env = {"MODEL_NAME": "tiny", "MODEL_QUANT": "fp4"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with pytest.raises(ValueError, match="int8, int4, or w8a8"):
            new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_flops_helpers():
    from gofr_tpu.tpu.flops import device_peak_flops, mfu, train_mfu

    assert device_peak_flops("TPU v5 lite", "tpu") == 197e12
    assert device_peak_flops("TPU v4", "tpu") == 275e12
    assert device_peak_flops("unknown", "cpu") == 100e9
    assert mfu(100, 10, 0.0, 1e3) == 0.0  # degenerate inputs never divide by 0
    assert train_mfu(100, 10, 1.0, 1e12) == pytest.approx(3 * mfu(100, 10, 1.0, 1e12))
    # int4 leaves count half a byte per element in the decode stream
    from gofr_tpu.tpu.flops import tree_bytes

    import jax.numpy as jnp

    tree = {"a": jnp.zeros((4, 4), jnp.int4), "s": jnp.zeros((4,), jnp.float32)}
    assert tree_bytes(tree) == 16 // 2 + 16


def test_seq_bucket_ladder_covers_full_context():
    """The bucket ladder must reach the model family's max context: a
    ladder capped short silently truncates long prompts to its top
    bucket (prepare keeps the LAST tokens, so the user would see answers
    computed from a suffix with no error)."""
    from gofr_tpu.models.llama import LLAMA3_8B
    from gofr_tpu.tpu.device import _TransformerRunner

    assert _TransformerRunner.SEQ_BUCKETS[-1] >= LLAMA3_8B.max_seq


def test_f8_kv_cache_serving():
    """MODEL_KV_DTYPE=f8 stores the cache in float8 (2x tokens per HBM
    byte): serving and the pooled decode run end-to-end on it."""
    import os

    import jax.numpy as jnp

    env = {"MODEL_NAME": "tiny", "MODEL_KV_DTYPE": "f8", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "DECODE_SLOTS": "2"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device.runner.cfg.cache_dtype == jnp.float8_e4m3fn
            assert device.runner._zero_cache(2)["k"].dtype == jnp.float8_e4m3fn
            assert device.decode_pool is not None  # pool cache is f8 too
            assert device.decode_pool.cache["k"].dtype == jnp.float8_e4m3fn
            out = device.generate([1, 2, 3, 4], max_new_tokens=8)
            assert len(out) == 8 and all(0 <= t < 256 for t in out)
            again = device.generate([1, 2, 3, 4], max_new_tokens=8)
            assert again == out  # still deterministic under greedy
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_bad_kv_dtype_rejected(monkeypatch):
    monkeypatch.setenv("MODEL_NAME", "tiny")
    monkeypatch.setenv("MODEL_KV_DTYPE", "int4")
    with pytest.raises(ValueError, match="MODEL_KV_DTYPE"):
        new_device(EnvConfig(), MockLogger(), Registry())


def test_bert_param_count_matches_tree():
    import jax

    from gofr_tpu.models.bert import BertConfig, init_bert
    from gofr_tpu.tpu.flops import bert_param_count

    cfg = BertConfig(vocab_size=512, dim=64, n_layers=2, n_heads=2,
                     hidden_dim=128, max_seq=64)
    tree = init_bert(jax.random.key(0), cfg)
    n_leaf = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))
    assert bert_param_count(cfg) == n_leaf


def test_bert_serving_reports_mfu(monkeypatch):
    monkeypatch.setenv("MODEL_NAME", "bert-tiny")
    monkeypatch.setenv("BATCH_MAX_SIZE", "2")
    monkeypatch.setenv("BATCH_TIMEOUT_MS", "1")
    device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    try:
        out = device.infer({"tokens": [1, 2, 3]})
        assert np.isfinite(np.asarray(out)).all()
        text = device.metrics.expose()
        assert 'gofr_tpu_mfu{model="bert-tiny",op="prefill"}' in text
    finally:
        device.close()


def test_w8a8_serving_generates():
    """MODEL_QUANT=w8a8 boots and serves: q8 packs in the runner tree
    (lm_head weight-only), generation through prefill + pooled decode."""
    import os

    import jax.numpy as jnp

    env = {"MODEL_NAME": "tiny", "MODEL_QUANT": "w8a8", "BATCH_MAX_SIZE": "2",
           "BATCH_TIMEOUT_MS": "1", "DECODE_CHUNK": "4"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            assert device.runner.params["layers"]["wq"]["q8"].dtype == jnp.int8
            assert set(device.runner.params["lm_head"]) == {"q", "scale"}
            out = device.generate([1, 2, 3], max_new_tokens=6)
            assert len(out) == 6
            assert all(0 <= t < device.runner.cfg.vocab_size for t in out)
            assert "quant=w8a8" in device.describe()
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_default_eos_stop(tmp_path):
    """Generation stops at the checkpoint's EOS by default: the ids come
    from generation_config.json (int or list) next to MODEL_PATH, else
    the tokenizer's eos; GEN_STOP_TOKENS overrides; GEN_STOP_EOS=off
    disables. (OpenAI semantics — a real instruct model must never run
    past <|eot_id|> to max_tokens.)"""
    import json

    from gofr_tpu.testutil import serving_device
    from gofr_tpu.tpu.device import _checkpoint_eos_ids

    # unit: generation_config parsing
    (tmp_path / "generation_config.json").write_text(
        json.dumps({"eos_token_id": [128001, 128009]})
    )
    assert _checkpoint_eos_ids(str(tmp_path / "model.safetensors"), None) \
        == {128001, 128009}
    (tmp_path / "generation_config.json").write_text(
        json.dumps({"eos_token_id": 7})
    )
    assert _checkpoint_eos_ids(str(tmp_path), None) == {7}
    assert _checkpoint_eos_ids(None, None) == set()

    # e2e: pick the plain greedy continuation's second token as the
    # "eos" via GEN_STOP_TOKENS — generation must end before emitting it
    with serving_device(DECODE_CHUNK="4", TOKENIZER="") as dev:
        free = dev.generate([1, 2, 3], max_new_tokens=6)
        assert dev.default_stop_ids == frozenset()  # no tokenizer/ckpt
    with serving_device(DECODE_CHUNK="4",
                        GEN_STOP_TOKENS=str(free[1])) as dev:
        assert dev.default_stop_ids == {free[1]}
        out = dev.generate([1, 2, 3], max_new_tokens=6)
        assert out == free[:1]  # stopped before the configured id
        # request stops COMPOSE with the default
        out2 = dev.generate([1, 2, 3], max_new_tokens=6,
                            stop_tokens=[free[0]])
        assert out2 == []
    with serving_device(DECODE_CHUNK="4", GEN_STOP_TOKENS=str(free[1]),
                        GEN_STOP_EOS="off") as dev:
        assert dev.default_stop_ids == frozenset()
        assert dev.generate([1, 2, 3], max_new_tokens=6) == free
