"""Prefix cache (PREFIX_CACHE=n): exact-prompt repeats skip prefill and
must produce identical generations; entries are private copies, LRU-bound,
and safe under the decode pool and sampling."""

import threading

import pytest

from gofr_tpu.ops.sampling import Sampler
from gofr_tpu.testutil import serving_device

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def cached():
    with serving_device(PREFIX_CACHE="2", DECODE_CHUNK="4") as dev:
        yield dev


@pytest.fixture(scope="module")
def plain():
    # PREFIX_CACHE pinned OFF so this baseline stays a real no-cache
    # device even while 'cached' has the env var set
    with serving_device(PREFIX_CACHE="0", DECODE_CHUNK="4") as dev:
        yield dev


def test_repeat_prompt_hits_and_matches(cached, plain):
    prompt = [1, 2, 3, 4]
    want = plain.generate(prompt, max_new_tokens=8)
    first = cached.generate(prompt, max_new_tokens=8)
    stats_after_first = dict(cached.runner.prefix_stats)
    second = cached.generate(prompt, max_new_tokens=8)
    assert first == want and second == want
    assert cached.runner.prefix_stats["hits"] == stats_after_first["hits"] + 1
    # hit-ratio gauge exposed
    text = cached.metrics.expose()
    assert any(
        ln.startswith('gofr_tpu_prefix_hit_ratio{model="tiny"}')
        for ln in text.splitlines()
    ), text


def test_hit_entry_survives_reuse(cached):
    # three generations off one stored entry, interleaved with another
    # prompt: stored rows must not be corrupted by earlier decodes
    a = cached.generate([9, 8, 7], max_new_tokens=6)
    cached.generate([5, 5, 5], max_new_tokens=6)
    b = cached.generate([9, 8, 7], max_new_tokens=6)
    c = cached.generate([9, 8, 7], max_new_tokens=6)
    assert a == b == c


def test_lru_eviction_bounds_entries(cached):
    for i in range(5):
        cached.generate([i + 1, i + 2], max_new_tokens=2)
    assert len(cached.runner._prefix_cache) <= 2


def test_sampled_requests_use_cached_logits(cached):
    # seeded sampling works off a cache hit (the stored logits row)
    prompt = [3, 1, 4, 1, 5]
    a = cached.generate(prompt, max_new_tokens=6,
                        sampler=Sampler(temperature=1.0, seed=7))
    b = cached.generate(prompt, max_new_tokens=6,
                        sampler=Sampler(temperature=1.0, seed=7))
    assert a == b


def test_concurrent_hits_are_safe(cached, plain):
    prompt = [2, 7, 1, 8]
    want = plain.generate(prompt, max_new_tokens=6)
    cached.generate(prompt, max_new_tokens=6)  # seed the entry
    got = [None] * 4

    def run(i):
        got[i] = cached.generate(prompt, max_new_tokens=6)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(g == want for g in got)


def test_negative_size_rejected():
    with pytest.raises(ValueError, match="PREFIX_CACHE"):
        with serving_device(PREFIX_CACHE="-1"):
            pass


# -- longest-common-prefix (partial) reuse -----------------------------------
# Two prompts sharing a system prefix: the second resumes from the first's
# cached KV and prefills only its tail. PREFIX_LCP_MIN=4 lowers the
# worthwhileness bar (default = smallest bucket = 64) to test scale.

SYSTEM = [7, 3, 9, 2, 11, 5]  # the shared "system prompt"


@pytest.fixture(scope="module")
def lcp():
    with serving_device(
        PREFIX_CACHE="4", PREFIX_LCP_MIN="4", DECODE_CHUNK="4"
    ) as dev:
        yield dev


def test_shared_prefix_partial_hit_matches(lcp, plain):
    a = SYSTEM + [21, 22, 23]
    b = SYSTEM + [31, 32]  # same system prompt, different user turn
    want_a = plain.generate(a, max_new_tokens=8)
    want_b = plain.generate(b, max_new_tokens=8)
    got_a = lcp.generate(a, max_new_tokens=8)  # miss; stores entry
    before = dict(lcp.runner.prefix_stats)
    got_b = lcp.generate(b, max_new_tokens=8)  # partial hit off a's KV
    after = lcp.runner.prefix_stats
    assert got_a == want_a
    assert got_b == want_b
    assert after["partial_hits"] == before["partial_hits"] + 1
    assert after["misses"] == before["misses"]


def test_partial_hit_stores_full_prompt_for_exact_reuse(lcp):
    b = SYSTEM + [41, 42, 43, 44]
    first = lcp.generate(b, max_new_tokens=6)
    before = dict(lcp.runner.prefix_stats)
    second = lcp.generate(b, max_new_tokens=6)  # exact hit on the stored tail state
    assert second == first
    assert lcp.runner.prefix_stats["hits"] == before["hits"] + 1


def test_short_shared_prefix_stays_a_miss(lcp):
    lcp.generate([1, 2, 3, 50, 51, 52], max_new_tokens=4)
    before = dict(lcp.runner.prefix_stats)
    lcp.generate([1, 2, 3, 60, 61, 62], max_new_tokens=4)  # LCP=3 < min 4
    after = lcp.runner.prefix_stats
    assert after["partial_hits"] == before["partial_hits"]
    assert after["misses"] == before["misses"] + 1


def test_query_shorter_than_cached_entry(lcp, plain):
    long = SYSTEM + [71, 72, 73, 74, 75]
    short = SYSTEM + [71]  # strict prefix of the cached prompt
    lcp.generate(long, max_new_tokens=4)
    want = plain.generate(short, max_new_tokens=6)
    before = dict(lcp.runner.prefix_stats)
    got = lcp.generate(short, max_new_tokens=6)
    assert got == want
    assert lcp.runner.prefix_stats["partial_hits"] == before["partial_hits"] + 1


def test_partial_hit_ratio_exposed(lcp):
    # self-sufficient: labeled gauges emit no sample until set, so drive
    # one partial hit here rather than depending on module test order
    lcp.generate(SYSTEM + [91, 92, 93], max_new_tokens=2)
    lcp.generate(SYSTEM + [94, 95], max_new_tokens=2)
    text = lcp.metrics.expose()
    assert any(
        ln.startswith('gofr_tpu_prefix_partial_hit_ratio{model="tiny"}')
        for ln in text.splitlines()
    ), text


# -- multi-turn conversation reuse -------------------------------------------
# After a generation, the WHOLE conversation's KV (prompt + reply) is
# stored; the follow-up turn (prompt + reply + new message) partial-hits
# it and prefills only the new message.


def test_multi_turn_conversation_reuse_pooled(lcp, plain):
    turn1 = SYSTEM + [101, 102, 103]
    reply = lcp.generate(turn1, max_new_tokens=8)
    assert reply == plain.generate(turn1, max_new_tokens=8)
    followup = turn1 + reply + [111, 112]
    want = plain.generate(followup, max_new_tokens=6)
    before = dict(lcp.runner.prefix_stats)
    got = lcp.generate(followup, max_new_tokens=6)
    assert got == want
    # the conversation entry (len(turn1)+len(reply)-1 shared) was used
    assert lcp.runner.prefix_stats["partial_hits"] == before["partial_hits"] + 1


def test_multi_turn_conversation_reuse_solo():
    with serving_device(
        PREFIX_CACHE="4", PREFIX_LCP_MIN="4", DECODE_CHUNK="4",
        DECODE_POOL="off",
    ) as solo, serving_device(
        PREFIX_CACHE="0", DECODE_CHUNK="4", DECODE_POOL="off"
    ) as plain_solo:
        turn1 = SYSTEM + [121, 122]
        reply = solo.generate(turn1, max_new_tokens=6)
        assert reply == plain_solo.generate(turn1, max_new_tokens=6)
        followup = turn1 + reply + [131]
        want = plain_solo.generate(followup, max_new_tokens=4)
        before = dict(solo.runner.prefix_stats)
        got = solo.generate(followup, max_new_tokens=4)
        assert got == want
        assert (
            solo.runner.prefix_stats["partial_hits"]
            == before["partial_hits"] + 1
        )


def test_generation_entry_exact_hit_greedy_and_sampled_divert(lcp, plain):
    turn1 = SYSTEM + [141, 142, 143]
    reply = lcp.generate(turn1, max_new_tokens=6)
    conv_key = turn1 + reply[:-1]  # the stored generation entry's tokens
    want = plain.generate(conv_key, max_new_tokens=4)
    before = dict(lcp.runner.prefix_stats)
    got = lcp.generate(conv_key, max_new_tokens=4)  # greedy: exact hit ok
    assert got == want
    assert lcp.runner.prefix_stats["hits"] == before["hits"] + 1
    # a logprobs request needs final-position logits the stored
    # generation lacks: it must DIVERT to the tail-prefill (partial hit)
    # and still match a no-cache device
    want_lp = plain.generate(conv_key, max_new_tokens=4, logprobs=True)
    before = dict(lcp.runner.prefix_stats)
    got_lp = lcp.generate(conv_key, max_new_tokens=4, logprobs=True)
    assert got_lp[0] == want_lp[0]  # tokens bit-exact
    # logprobs to float noise: the tail-prefill runs a [1, bucket] shape,
    # the no-cache oracle a batched one — XLA reduces them differently
    import numpy as np

    np.testing.assert_allclose(got_lp[1], want_lp[1], rtol=1e-4, atol=1e-5)
    after = lcp.runner.prefix_stats
    assert after["hits"] == before["hits"]
    assert after["partial_hits"] == before["partial_hits"] + 1


def test_sampled_generation_entry_never_exact_serves_greedy(lcp, plain):
    """A SAMPLED generation seeds the cache too (KV is token-content-
    determined), but its next_token must never exact-serve a later
    greedy request — that would emit a random token where the model's
    argmax belongs. Such entries divert to the tail-prefill."""
    turn1 = SYSTEM + [151, 152]
    reply = lcp.generate(
        turn1, max_new_tokens=6, sampler=Sampler(temperature=1.0)
    )
    conv_key = turn1 + reply[:-1]
    want = plain.generate(conv_key, max_new_tokens=4)
    before = dict(lcp.runner.prefix_stats)
    got = lcp.generate(conv_key, max_new_tokens=4)
    assert got == want  # greedy bit-exact despite the sampled-source entry
    assert lcp.runner.prefix_stats["hits"] == before["hits"]  # diverted


def test_below_off_lcp_min_rejected():
    # -1 is the documented off switch; anything below is a config error
    with pytest.raises(ValueError, match="PREFIX_LCP_MIN"):
        with serving_device(PREFIX_CACHE="2", PREFIX_LCP_MIN="-2"):
            pass


def test_lcp_off_restores_exact_only():
    with serving_device(
        PREFIX_CACHE="2", PREFIX_LCP_MIN="-1", DECODE_CHUNK="4"
    ) as dev:
        dev.generate(SYSTEM + [21, 22, 23], max_new_tokens=2)
        dev.generate(SYSTEM + [31, 32], max_new_tokens=2)  # would LCP-hit
        stats = dev.runner.prefix_stats
        assert stats["partial_hits"] == 0
        assert stats["misses"] == 2
