"""Prefix cache (PREFIX_CACHE=n): exact-prompt repeats skip prefill and
must produce identical generations; entries are private copies, LRU-bound,
and safe under the decode pool and sampling."""

import threading

import pytest

from gofr_tpu.ops.sampling import Sampler
from gofr_tpu.testutil import serving_device

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def cached():
    with serving_device(PREFIX_CACHE="2", DECODE_CHUNK="4") as dev:
        yield dev


@pytest.fixture(scope="module")
def plain():
    # PREFIX_CACHE pinned OFF so this baseline stays a real no-cache
    # device even while 'cached' has the env var set
    with serving_device(PREFIX_CACHE="0", DECODE_CHUNK="4") as dev:
        yield dev


def test_repeat_prompt_hits_and_matches(cached, plain):
    prompt = [1, 2, 3, 4]
    want = plain.generate(prompt, max_new_tokens=8)
    first = cached.generate(prompt, max_new_tokens=8)
    stats_after_first = dict(cached.runner.prefix_stats)
    second = cached.generate(prompt, max_new_tokens=8)
    assert first == want and second == want
    assert cached.runner.prefix_stats["hits"] == stats_after_first["hits"] + 1
    # hit-ratio gauge exposed
    text = cached.metrics.expose()
    assert any(
        ln.startswith('gofr_tpu_prefix_hit_ratio{model="tiny"}')
        for ln in text.splitlines()
    ), text


def test_hit_entry_survives_reuse(cached):
    # three generations off one stored entry, interleaved with another
    # prompt: stored rows must not be corrupted by earlier decodes
    a = cached.generate([9, 8, 7], max_new_tokens=6)
    cached.generate([5, 5, 5], max_new_tokens=6)
    b = cached.generate([9, 8, 7], max_new_tokens=6)
    c = cached.generate([9, 8, 7], max_new_tokens=6)
    assert a == b == c


def test_lru_eviction_bounds_entries(cached):
    for i in range(5):
        cached.generate([i + 1, i + 2], max_new_tokens=2)
    assert len(cached.runner._prefix_cache) <= 2


def test_sampled_requests_use_cached_logits(cached):
    # seeded sampling works off a cache hit (the stored logits row)
    prompt = [3, 1, 4, 1, 5]
    a = cached.generate(prompt, max_new_tokens=6,
                        sampler=Sampler(temperature=1.0, seed=7))
    b = cached.generate(prompt, max_new_tokens=6,
                        sampler=Sampler(temperature=1.0, seed=7))
    assert a == b


def test_concurrent_hits_are_safe(cached, plain):
    prompt = [2, 7, 1, 8]
    want = plain.generate(prompt, max_new_tokens=6)
    cached.generate(prompt, max_new_tokens=6)  # seed the entry
    got = [None] * 4

    def run(i):
        got[i] = cached.generate(prompt, max_new_tokens=6)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(g == want for g in got)


def test_negative_size_rejected():
    with pytest.raises(ValueError, match="PREFIX_CACHE"):
        with serving_device(PREFIX_CACHE="-1"):
            pass
