"""CMD transport tests.

Parity model: cmd_test.go:15-29 and examples/sample-cmd/main_test.go:21-45
(os.Args injection + stdout/stderr capture)."""

import pytest

import gofr_tpu
from gofr_tpu.cmd import CMDRequest, command_string, run_cmd
from gofr_tpu.testutil import stderr_output_for, stdout_output_for


@pytest.fixture
def cmd_app(monkeypatch, tmp_path):
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    monkeypatch.chdir(tmp_path)
    return gofr_tpu.new_cmd()


def test_flag_parsing():
    req = CMDRequest(["hello", "-verbose", "--name=ada", "-n=3"])
    assert req.param("verbose") == "true"
    assert req.param("name") == "ada"
    assert req.param("n") == "3"
    assert req.param("missing") == ""


def test_command_string_skips_flags():
    assert command_string(["hello", "-a", "--b=c", "world"]) == "hello world"


def test_bind_types():
    class Opts:
        name: str = ""
        count: int = 0
        fast: bool = False

    req = CMDRequest(["run", "--name=x", "--count=5", "-fast"])
    opts = req.bind(Opts)
    assert opts.name == "x" and opts.count == 5 and opts.fast is True


def test_route_match_and_output(cmd_app):
    cmd_app.sub_command("hello", lambda ctx: f"Hello {ctx.param('name')}!")
    out = stdout_output_for(lambda: run_cmd(cmd_app, ["hello", "--name=ada"]))
    assert out == "Hello ada!\n"


def test_regex_route(cmd_app):
    cmd_app.sub_command(r"greet \w+", lambda ctx: "matched")
    out = stdout_output_for(lambda: run_cmd(cmd_app, ["greet", "bob"]))
    assert "matched" in out


def test_no_command_found(cmd_app):
    cmd_app.sub_command("hello", lambda ctx: "hi")
    err = stderr_output_for(lambda: run_cmd(cmd_app, ["bogus"]))
    assert "No Command Found!" in err


def test_handler_error_to_stderr(cmd_app):
    def fails(ctx):
        raise ValueError("broken pipe dream")

    cmd_app.sub_command("fail", fails)
    err = stderr_output_for(lambda: run_cmd(cmd_app, ["fail"]))
    assert "broken pipe dream" in err
    assert run_cmd(cmd_app, ["fail"]) == 1


def test_dict_result_prints_json(cmd_app):
    cmd_app.sub_command("info", lambda ctx: {"version": 1})
    out = stdout_output_for(lambda: run_cmd(cmd_app, ["info"]))
    assert '"version": 1' in out
