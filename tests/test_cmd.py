"""CMD transport tests.

Parity model: cmd_test.go:15-29 and examples/sample-cmd/main_test.go:21-45
(os.Args injection + stdout/stderr capture)."""

import pytest

import gofr_tpu
from gofr_tpu.cmd import CMDRequest, command_string, run_cmd
from gofr_tpu.testutil import stderr_output_for, stdout_output_for


@pytest.fixture
def cmd_app(monkeypatch, tmp_path):
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    monkeypatch.chdir(tmp_path)
    return gofr_tpu.new_cmd()


def test_flag_parsing():
    req = CMDRequest(["hello", "-verbose", "--name=ada", "-n=3"])
    assert req.param("verbose") == "true"
    assert req.param("name") == "ada"
    assert req.param("n") == "3"
    assert req.param("missing") == ""


def test_command_string_skips_flags():
    assert command_string(["hello", "-a", "--b=c", "world"]) == "hello world"


def test_bind_types():
    class Opts:
        name: str = ""
        count: int = 0
        fast: bool = False

    req = CMDRequest(["run", "--name=x", "--count=5", "-fast"])
    opts = req.bind(Opts)
    assert opts.name == "x" and opts.count == 5 and opts.fast is True


def test_route_match_and_output(cmd_app):
    cmd_app.sub_command("hello", lambda ctx: f"Hello {ctx.param('name')}!")
    out = stdout_output_for(lambda: run_cmd(cmd_app, ["hello", "--name=ada"]))
    assert out == "Hello ada!\n"


def test_regex_route(cmd_app):
    cmd_app.sub_command(r"greet \w+", lambda ctx: "matched")
    out = stdout_output_for(lambda: run_cmd(cmd_app, ["greet", "bob"]))
    assert "matched" in out


def test_no_command_found(cmd_app):
    cmd_app.sub_command("hello", lambda ctx: "hi")
    err = stderr_output_for(lambda: run_cmd(cmd_app, ["bogus"]))
    assert "No Command Found!" in err


def test_handler_error_to_stderr(cmd_app):
    def fails(ctx):
        raise ValueError("broken pipe dream")

    cmd_app.sub_command("fail", fails)
    err = stderr_output_for(lambda: run_cmd(cmd_app, ["fail"]))
    assert "broken pipe dream" in err
    assert run_cmd(cmd_app, ["fail"]) == 1


def test_dict_result_prints_json(cmd_app):
    cmd_app.sub_command("info", lambda ctx: {"version": 1})
    out = stdout_output_for(lambda: run_cmd(cmd_app, ["info"]))
    assert '"version": 1' in out


def test_lora_finetune_example(monkeypatch, tmp_path):
    """The lora-finetune example CLI trains adapters and writes a merged
    checkpoint that the serving path can load (MODEL_PATH round trip)."""
    import os
    import runpy
    import sys

    out = str(tmp_path / "lora_ckpt")
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    monkeypatch.setattr(
        sys, "argv",
        ["main.py", "finetune", "--model=tiny", "--steps=4", "--rank=2",
         f"--out={out}"],
    )
    text = stdout_output_for(
        lambda: runpy.run_path(
            os.path.join(os.path.dirname(__file__), "..", "examples",
                         "lora-finetune", "main.py"),
            run_name="__main__",
        )
    )
    assert "merged checkpoint" in text

    from gofr_tpu.training.checkpoint import restore_params

    params = restore_params(out)
    assert hasattr(params["layers"]["wq"], "ndim")  # merged: plain weights
