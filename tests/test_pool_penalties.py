"""Penalized requests in the continuous-batching pool.

Before r04, any request with a repetition/presence/frequency penalty or
logit_bias decoded solo ([1, 1] dispatches) — common OpenAI traffic
would have collapsed pool throughput. These tests pin the per-slot
penalty state: pooled output must equal the solo path's (greedy
determinism), co-tenants must not contaminate each other, and a freed
penalized slot must serve the next plain request exactly like a fresh
one (the bias row is zeroed on release).
"""

import queue
import threading

import pytest

from gofr_tpu.ops.sampling import Sampler
from gofr_tpu.testutil import serving_device

pytestmark = pytest.mark.slow

PROMPT = [1, 2, 3]
PEN = dict(presence_penalty=2.0, frequency_penalty=2.0)


def _spy_submit(dev):
    """Wrap pool.submit to record whether each call pooled a penalty."""
    pool = dev.decode_pool
    seen = []
    orig = pool.submit

    def submit(*args, **kwargs):
        out = orig(*args, **kwargs)  # raises queue.Full on fallback
        seen.append(kwargs.get("penalty") is not None)
        return out

    pool.submit = submit
    return seen


def test_penalized_pooled_equals_solo():
    # solo reference: penalties machinery off
    with serving_device(DECODE_CHUNK="4",
                        DECODE_POOL_PENALTIES="off") as dev:
        solo = dev.generate(PROMPT, max_new_tokens=10, sampler=Sampler(**PEN))
        plain = dev.generate(PROMPT, max_new_tokens=10)
    with serving_device(DECODE_CHUNK="4",
                        DECODE_POOL_PENALTIES="eager") as dev:
        seen = _spy_submit(dev)
        pooled = dev.generate(PROMPT, max_new_tokens=10,
                              sampler=Sampler(**PEN))
        assert seen == [True], "request did not take the pooled path"
        assert pooled == solo
        assert pooled != plain  # penalties actually did something
        # logit_bias rides the same slot state
        forced_solo_ref = [42] * 6
        forced = dev.generate(PROMPT, max_new_tokens=6,
                              sampler=Sampler(logit_bias={42: 100.0}))
        assert forced == forced_solo_ref
        assert seen == [True, True]


def test_bias_row_zeroed_on_slot_reuse():
    with serving_device(DECODE_CHUNK="4", BATCH_MAX_SIZE="2",
                        DECODE_POOL_PENALTIES="eager") as dev:
        plain_before = dev.generate(PROMPT, max_new_tokens=8)
        # occupy-and-free every slot with a +100 forced-token bias
        for _ in range(int(dev.decode_pool.n_slots)):
            assert dev.generate(
                PROMPT, max_new_tokens=4,
                sampler=Sampler(logit_bias={7: 100.0}),
            ) == [7, 7, 7, 7]
        # a plain request reusing those slots must be bias-free
        assert dev.generate(PROMPT, max_new_tokens=8) == plain_before
        assert dev.decode_pool._pen_slots == set()


def test_mixed_penalized_and_plain_cotenants():
    with serving_device(DECODE_CHUNK="4", BATCH_MAX_SIZE="2",
                        DECODE_POOL_PENALTIES="eager") as dev:
        plain_alone = dev.generate(PROMPT, max_new_tokens=12)
        pen_alone = dev.generate(PROMPT, max_new_tokens=12,
                                 sampler=Sampler(**PEN))
        results: dict = {}

        def run(name, sampler):
            results[name] = dev.generate(PROMPT, max_new_tokens=12,
                                         sampler=sampler)

        threads = [
            threading.Thread(target=run, args=("plain", None)),
            threading.Thread(target=run, args=("pen", Sampler(**PEN))),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # identity knobs on the plain slot: co-tenancy with a penalized
        # slot must not change its greedy output, and vice versa
        assert results["plain"] == plain_alone
        assert results["pen"] == pen_alone


def test_lazy_mode_solos_then_pools():
    with serving_device(DECODE_CHUNK="4",
                        DECODE_POOL_PENALTIES="lazy") as dev:
        pool = dev.decode_pool
        assert not pool._pen_ready
        # first penalized request: correct output via the solo fallback,
        # and it kicks the background build
        first = dev.generate(PROMPT, max_new_tokens=8, sampler=Sampler(**PEN))
        for _ in range(600):  # the tiny-model build takes a few seconds
            if pool._pen_ready:
                break
            import time

            time.sleep(0.1)
        assert pool._pen_ready
        seen = _spy_submit(dev)
        second = dev.generate(PROMPT, max_new_tokens=8,
                              sampler=Sampler(**PEN))
        assert seen == [True]
        assert second == first  # greedy: pooled == solo


def test_off_mode_always_solos():
    with serving_device(DECODE_CHUNK="4",
                        DECODE_POOL_PENALTIES="off") as dev:
        pool = dev.decode_pool
        orig = pool.submit

        def submit(*args, **kwargs):
            if kwargs.get("penalty") is not None:
                submit.rejected = True  # type: ignore[attr-defined]
            return orig(*args, **kwargs)

        submit.rejected = False  # type: ignore[attr-defined]
        pool.submit = submit
        out = dev.generate(PROMPT, max_new_tokens=6, sampler=Sampler(**PEN))
        assert len(out) == 6
        assert not pool._pen_ready
        # the penalty submit was refused (queue.Full) and the request
        # soloed — prove the refusal is what happened
        with pytest.raises(queue.Full):
            orig(None, 0, 0, 0, Sampler(), penalty=(None,) * 6)
