"""gRPC transport tests over a real in-process server + channel.

Parity model: grpc_test.go:24-52 (server lifecycle incl. error paths) and
examples/grpc-server tests (SURVEY.md §4)."""

import json

import grpc
import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.container import Container
from gofr_tpu.errors import EntityNotFoundError
from gofr_tpu.grpcx import GRPCServer
from gofr_tpu.testutil import MockLogger


@pytest.fixture
def server(free_port):
    port = free_port()
    container = Container(EnvConfig(), wire=False)
    container.logger = MockLogger()

    def say_hello(ctx):
        name = ctx.param("name") or "World"
        return f"Hello {name}!"

    def not_found(ctx):
        raise EntityNotFoundError("user", ctx.param("id"))

    def panics(ctx):
        raise RuntimeError("secret internals")

    srv = GRPCServer(
        port,
        container,
        json_services={
            "HelloService": {"SayHello": say_hello, "Lookup": not_found, "Panic": panics}
        },
    )
    srv.start()
    yield port, container
    srv.stop()


def _call(port, method, payload, metadata=None):
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_unary(
            f"/HelloService/{method}",
            request_serializer=None,
            response_deserializer=None,
        )
        return stub(json.dumps(payload).encode(), metadata=metadata, timeout=5)


def test_json_unary_call(server):
    port, _ = server
    resp = json.loads(_call(port, "SayHello", {"name": "ada"}))
    assert resp == {"data": "Hello ada!"}


def test_typed_error_maps_to_grpc_status(server):
    port, _ = server
    with pytest.raises(grpc.RpcError) as exc:
        _call(port, "Lookup", {"id": "9"})
    assert exc.value.code() == grpc.StatusCode.NOT_FOUND
    assert "No 'user' found" in exc.value.details()


def test_unknown_error_hides_internals(server):
    port, container = server
    with pytest.raises(grpc.RpcError) as exc:
        _call(port, "Panic", {})
    assert exc.value.code() == grpc.StatusCode.INTERNAL
    assert "secret internals" not in exc.value.details()
    assert "secret internals" in container.logger.output  # logged server-side


def test_unknown_method_unimplemented(server):
    port, _ = server
    with pytest.raises(grpc.RpcError) as exc:
        _call(port, "Nope", {})
    assert exc.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_rpc_log_emitted(server):
    port, container = server
    _call(port, "SayHello", {"name": "x"})
    assert "/HelloService/SayHello" in container.logger.output


def test_invalid_json_payload(server):
    port, _ = server
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_unary("/HelloService/SayHello")
        with pytest.raises(grpc.RpcError) as exc:
            stub(b"\xff\xfe not json", timeout=5)
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
