"""gRPC transport tests over a real in-process server + channel.

Parity model: grpc_test.go:24-52 (server lifecycle incl. error paths) and
examples/grpc-server tests (SURVEY.md §4)."""

import json

import grpc
import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.container import Container
from gofr_tpu.errors import EntityNotFoundError
from gofr_tpu.grpcx import GRPCServer
from gofr_tpu.testutil import MockLogger


@pytest.fixture
def server(free_port):
    port = free_port()
    container = Container(EnvConfig(), wire=False)
    container.logger = MockLogger()

    def say_hello(ctx):
        name = ctx.param("name") or "World"
        return f"Hello {name}!"

    def not_found(ctx):
        raise EntityNotFoundError("user", ctx.param("id"))

    def panics(ctx):
        raise RuntimeError("secret internals")

    srv = GRPCServer(
        port,
        container,
        json_services={
            "HelloService": {"SayHello": say_hello, "Lookup": not_found, "Panic": panics}
        },
    )
    srv.start()
    yield port, container
    srv.stop()


def _call(port, method, payload, metadata=None):
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_unary(
            f"/HelloService/{method}",
            request_serializer=None,
            response_deserializer=None,
        )
        return stub(json.dumps(payload).encode(), metadata=metadata, timeout=5)


def test_json_unary_call(server):
    port, _ = server
    resp = json.loads(_call(port, "SayHello", {"name": "ada"}))
    assert resp == {"data": "Hello ada!"}


def test_typed_error_maps_to_grpc_status(server):
    port, _ = server
    with pytest.raises(grpc.RpcError) as exc:
        _call(port, "Lookup", {"id": "9"})
    assert exc.value.code() == grpc.StatusCode.NOT_FOUND
    assert "No 'user' found" in exc.value.details()


def test_unknown_error_hides_internals(server):
    port, container = server
    with pytest.raises(grpc.RpcError) as exc:
        _call(port, "Panic", {})
    assert exc.value.code() == grpc.StatusCode.INTERNAL
    assert "secret internals" not in exc.value.details()
    assert "secret internals" in container.logger.output  # logged server-side


def test_unknown_method_unimplemented(server):
    port, _ = server
    with pytest.raises(grpc.RpcError) as exc:
        _call(port, "Nope", {})
    assert exc.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_rpc_log_emitted(server):
    port, container = server
    _call(port, "SayHello", {"name": "x"})
    assert "/HelloService/SayHello" in container.logger.output


def test_invalid_json_payload(server):
    port, _ = server
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_unary("/HelloService/SayHello")
        with pytest.raises(grpc.RpcError) as exc:
            stub(b"\xff\xfe not json", timeout=5)
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT


# -- server-streaming JSON services (token decode transport) ------------------

@pytest.fixture
def stream_server(free_port):
    port = free_port()
    container = Container(EnvConfig(), wire=False)
    container.logger = MockLogger()

    def countdown(ctx):
        n = int(ctx.param("n") or 3)
        for i in range(n, 0, -1):
            yield {"tick": i}

    def stream_fails(ctx):
        yield {"tick": 1}
        raise RuntimeError("decode blew up")

    def bad_request(ctx):
        from gofr_tpu.errors import InvalidParamError

        raise InvalidParamError("n")
        yield  # makes it a generator-shaped handler

    srv = GRPCServer(
        port,
        container,
        json_services={"Clock": {"Now": lambda ctx: "now"}},
        json_stream_services={
            "Clock": {"Countdown": countdown, "Broken": stream_fails, "Bad": bad_request}
        },
    )
    srv.start()
    yield port, container
    srv.stop()


def _stream(port, method, payload):
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_stream(f"/Clock/{method}")
        return [json.loads(m) for m in stub(json.dumps(payload).encode(), timeout=10)]


def test_json_stream_messages(stream_server):
    port, _ = stream_server
    assert _stream(port, "Countdown", {"n": 3}) == [
        {"tick": 3}, {"tick": 2}, {"tick": 1},
    ]


def test_unary_and_stream_share_service_name(stream_server):
    port, _ = stream_server
    assert json.loads(_call_service(port, "Clock", "Now", {})) == {"data": "now"}


def test_stream_midstream_error_aborts(stream_server):
    port, container = stream_server
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_stream("/Clock/Broken")
        it = stub(b"{}", timeout=10)
        assert json.loads(next(it)) == {"tick": 1}
        with pytest.raises(grpc.RpcError) as exc:
            list(it)
    assert exc.value.code() == grpc.StatusCode.INTERNAL
    assert "decode blew up" not in exc.value.details()
    assert "decode blew up" in container.logger.output


def test_stream_typed_error_maps_status(stream_server):
    port, _ = stream_server
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_stream("/Clock/Bad")
        with pytest.raises(grpc.RpcError) as exc:
            list(stub(b"{}", timeout=10))
    assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def _call_service(port, service, method, payload):
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = channel.unary_unary(f"/{service}/{method}")
        return stub(json.dumps(payload).encode(), timeout=5)


def test_duplicate_unary_and_stream_method_rejected(free_port):
    container = Container(EnvConfig(), wire=False)
    container.logger = MockLogger()
    with pytest.raises(ValueError, match="both"):
        GRPCServer(
            free_port(),
            container,
            json_services={"S": {"Gen": lambda ctx: 1}},
            json_stream_services={"S": {"Gen": lambda ctx: iter(())}},
        )


# -- generated-stub path (parity: examples/grpc-server committed .pb.go) -----

def _load_hello_stubs():
    """Import the example's vendored protoc-generated modules (checked-in
    codegen, like the reference's hello{,_grpc}.pb.go)."""
    import os
    import sys

    pb_dir = os.path.join(
        os.path.dirname(__file__), "..", "examples", "grpc-server", "pb"
    )
    sys.path.insert(0, pb_dir)
    try:
        import hello_pb2
        import hello_pb2_grpc
    finally:
        sys.path.remove(pb_dir)
    return hello_pb2, hello_pb2_grpc


def test_generated_stub_service(free_port):
    """app.register_service wiring: a protoc-generated servicer served and
    called through the generated client stub — real proto bytes on the
    wire, not JSON."""
    hello_pb2, hello_pb2_grpc = _load_hello_stubs()

    class Servicer(hello_pb2_grpc.HelloServicer):
        def SayHello(self, request, context):
            return hello_pb2.HelloResponse(
                message=f"Hello {request.name or 'World'}!"
            )

    port = free_port()
    container = Container(EnvConfig(), wire=False)
    container.logger = MockLogger()
    srv = GRPCServer(
        port, container,
        registrations=[(hello_pb2_grpc.add_HelloServicer_to_server, Servicer())],
    )
    srv.start()
    try:
        with grpc.insecure_channel(f"localhost:{port}") as channel:
            stub = hello_pb2_grpc.HelloStub(channel)
            reply = stub.SayHello(
                hello_pb2.HelloRequest(name="ada"), timeout=5
            )
            assert reply.message == "Hello ada!"
            reply = stub.SayHello(hello_pb2.HelloRequest(), timeout=5)
            assert reply.message == "Hello World!"
    finally:
        srv.stop()


def test_generated_stub_rpc_is_logged(free_port):
    """The interceptor chain (recovery -> RPCLog) wraps generated-stub
    services exactly as JSON ones (parity: grpc/log.go:27-50)."""
    hello_pb2, hello_pb2_grpc = _load_hello_stubs()

    class Servicer(hello_pb2_grpc.HelloServicer):
        def SayHello(self, request, context):
            return hello_pb2.HelloResponse(message="hi")

    port = free_port()
    container = Container(EnvConfig(), wire=False)
    container.logger = MockLogger()
    srv = GRPCServer(
        port, container,
        registrations=[(hello_pb2_grpc.add_HelloServicer_to_server, Servicer())],
    )
    srv.start()
    try:
        with grpc.insecure_channel(f"localhost:{port}") as channel:
            hello_pb2_grpc.HelloStub(channel).SayHello(
                hello_pb2.HelloRequest(name="x"), timeout=5
            )
    finally:
        srv.stop()
    assert container.logger.contains("/hello.Hello/SayHello")
    assert container.logger.contains('"status": "OK"')
