"""Speculative SAMPLING (temperature > 0): the canonical accept/residual
scheme must emit tokens distributed exactly as sampling from the target's
warped distribution — whatever the draft proposes. The kernel-level test
checks that law directly against teacher-forcing probabilities; the
serving tests pin the routing (unseeded sampled requests draft, seeded
ones stay on the exact solo path)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.ops.sampling import Sampler, warped_probs
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import new_device

pytestmark = pytest.mark.slow

TEMP = 0.25  # concentrates the tiny model's near-uniform logits


def _setup_kernel():
    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.transformer import (
        init_cache,
        init_transformer,
        transformer_forward,
        verify_chunk_sampled,
    )

    params = init_transformer(jax.random.key(0), TINY)
    verify = jax.jit(
        lambda t, c, d, q, key: verify_chunk_sampled(
            params, t, c, TINY, d, q, key, TEMP
        )
    )
    cache = init_cache(TINY, 1)
    t0, drafts = 7, [3, 11, 200]
    tokens = jnp.asarray([[t0] + drafts], jnp.int32)
    draft_toks = jnp.asarray([drafts], jnp.int32)
    # exact warped target distribution at the first position (predicts
    # the token after t0), via teacher forcing
    logits = transformer_forward(params, tokens, TINY)
    p0 = np.asarray(warped_probs(logits[:, 0, :], TEMP)[0])
    return verify, cache, tokens, draft_toks, p0, TINY.vocab_size


def _empirical(verify, cache, tokens, draft_toks, q, n=2000):
    counts: dict[int, int] = {}
    accs = []
    for i in range(n):
        emitted, n_acc, _, _ = verify(
            tokens, cache, draft_toks, q, jax.random.key(i)
        )
        first = int(emitted[0, 0])
        counts[first] = counts.get(first, 0) + 1
        accs.append(int(n_acc[0]))
    return counts, accs


def _tv(counts, p, n):
    """Total variation between the empirical law and exact p, over p's
    effective support plus a lumped tail."""
    support = [i for i in range(len(p)) if p[i] > 0.01]
    tv = sum(abs(counts.get(i, 0) / n - p[i]) for i in support)
    tail_p = 1.0 - sum(p[i] for i in support)
    tail_e = sum(c for i, c in counts.items() if i not in support) / n
    return 0.5 * (tv + abs(tail_e - tail_p))


def test_sampled_spec_marginal_is_exactly_target():
    verify, cache, tokens, draft_toks, p0, vocab = _setup_kernel()
    n = 2000
    # ADVERSARIAL draft: q concentrated on the first draft token (which
    # was chosen arbitrarily, not by p) — rejections dominate and the
    # residual path does the work; the emitted marginal must still be p0
    q_row = np.full(vocab, 0.1 / vocab, np.float32)
    q_row[int(draft_toks[0, 0])] += 0.9
    q = jnp.asarray(np.tile(q_row, (1, 3, 1)).reshape(1, 3, vocab))
    counts, accs = _empirical(verify, cache, tokens, draft_toks, q, n)
    assert _tv(counts, p0, n) < 0.08
    assert max(accs) <= 3  # never beyond the tested drafts


def _teacher_warped(tokens):
    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.transformer import init_transformer, transformer_forward

    params = init_transformer(jax.random.key(0), TINY)
    logits = transformer_forward(params, tokens, TINY)
    b, s, v = logits.shape
    return warped_probs(logits.reshape(b * s, v), TEMP).reshape(b, s, v)


def test_sampled_spec_full_accept_when_draft_equals_target():
    """q == warped p AND drafts drawn as p's top tokens: u < p/q = 1
    accepts every draft deterministically; emitted = drafts + bonus."""
    verify, cache, tokens, _, _, _ = _setup_kernel()
    full = _teacher_warped(tokens)
    # re-issue the verify with drafts that match what q says (q(d) > 0
    # required; top-1 tokens make the fixture deterministic to build)
    drafts = jnp.argmax(full[:, :3, :], axis=-1).astype(jnp.int32)
    tokens2 = jnp.concatenate([tokens[:, :1], drafts], axis=1)
    full2 = _teacher_warped(tokens2)
    q = full2[:, :3, :]
    for i in range(25):
        emitted, n_acc, _, _ = verify(
            tokens2, cache, drafts, q, jax.random.key(i)
        )
        assert int(n_acc[0]) == 3
        assert [int(x) for x in emitted[0, :3]] == [int(x) for x in drafts[0]]


def _device(**env):
    defaults = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2",
                "BATCH_TIMEOUT_MS": "1"}
    defaults.update(env)
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry()), old
    except BaseException:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
        raise


@pytest.fixture(scope="module")
def spec_dev():
    dev, old = _device(DRAFT_MODEL_NAME="tiny", DRAFT_TOKENS="4",
                       DECODE_POOL="off", DECODE_CHUNK="4")
    yield dev
    dev.close()
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_unseeded_sampled_requests_draft(spec_dev):
    before = dict(spec_dev.runner.spec_stats)
    out = spec_dev.generate([1, 2, 3], max_new_tokens=9,
                            sampler=Sampler(temperature=1.0))
    assert len(out) == 9
    assert all(0 <= t < spec_dev.runner.cfg.vocab_size for t in out)
    after = spec_dev.runner.spec_stats
    assert after["cycles"] > before["cycles"]
    assert after["drafted"] > before["drafted"]


def test_sampled_spec_respects_stop_tokens(spec_dev):
    # a stop token can only end the stream early, never be emitted
    outs = [
        spec_dev.generate([1, 2, 3], max_new_tokens=12,
                          sampler=Sampler(temperature=1.0, top_k=8),
                          stop_tokens=[5])
        for _ in range(6)
    ]
    assert all(5 not in o for o in outs)
    assert all(len(o) <= 12 for o in outs)


def test_seeded_sampled_stays_on_exact_solo_path(spec_dev):
    plain, old = _device(DECODE_POOL="off", DECODE_CHUNK="4")
    try:
        before = dict(spec_dev.runner.spec_stats)
        a = spec_dev.generate([1, 2, 3], max_new_tokens=7,
                              sampler=Sampler(temperature=1.0, seed=11))
        b = plain.generate([1, 2, 3], max_new_tokens=7,
                           sampler=Sampler(temperature=1.0, seed=11))
        # seeded requests bypass the draft entirely and reproduce the
        # plain device's exact seeded sequence
        assert a == b
        assert spec_dev.runner.spec_stats == before
    finally:
        plain.close()
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
