"""Request flight recorder (gofr_tpu/telemetry.py): ring/side-buffer
semantics, SLO percentiles, and the end-to-end spine — a request through
the OpenAI surface produces a retrievable FlightRecord with real queue/
TTFT/TPOT timings, a single connected Zipkin trace, and per-model SLO
percentiles — driven through the in-process server on the no-JAX
``echo`` model (no XLA compiles; the fast suite covers the whole path)."""

import json
import socket
import urllib.request

import pytest

from gofr_tpu.telemetry import FlightRecord, FlightRecorder, current_record


# -- unit: recorder buffers and math -----------------------------------------

def _finished(recorder, model="m", status="ok", ttft=None, tpot_marks=None):
    rec = recorder.start(model=model, endpoint="/t", activate=False)
    if ttft is not None:
        rec.t_first_token = rec.t_start + ttft
    if tpot_marks is not None:
        first, last, n = tpot_marks
        rec.t_first_token = rec.t_start + first
        rec.t_last_token = rec.t_start + last
        rec.tokens_out = n
    error = RuntimeError("boom") if status == "error" else None
    recorder.finish(rec, error=error)
    return rec


def test_ring_bounded_and_newest_first():
    recorder = FlightRecorder(capacity=3, keep=2)
    for i in range(5):
        rec = recorder.start(model=f"m{i}", endpoint="/t", activate=False)
        recorder.finish(rec)
    records = recorder.records()
    assert [r["model"] for r in records] == ["m4", "m3", "m2"]


def test_side_buffer_keeps_errored_after_ring_eviction():
    recorder = FlightRecorder(capacity=2, keep=4)
    _finished(recorder, model="bad", status="error")
    for i in range(4):  # evicts "bad" from the ring
        _finished(recorder, model=f"ok{i}")
    errored = recorder.records(errored=True)
    assert [r["model"] for r in errored] == ["bad"]
    assert errored[0]["status"] == "error"
    assert "boom" in errored[0]["error"]
    # and the ok filter excludes it
    assert all(r["status"] == "ok" for r in recorder.records(errored=False))


def test_slow_classification_and_filter():
    recorder = FlightRecorder(capacity=8, slow_threshold_s=0.5)
    _finished(recorder, model="fast", ttft=0.01)
    slow = recorder.start(model="slow", endpoint="/t", activate=False)
    slow.t_first_token = slow.t_start + 0.9  # ttft past the threshold
    recorder.finish(slow)
    assert [r["model"] for r in recorder.records(slow=True)] == ["slow"]
    assert "fast" in [r["model"] for r in recorder.records(slow=False)]


def test_slo_percentiles_are_exact_samples():
    recorder = FlightRecorder(capacity=256)
    for ms in range(1, 101):  # TTFTs 0.001..0.100
        _finished(recorder, model="m", ttft=ms / 1000.0)
    slo = recorder.slo(window_s=60.0)["models"]["m"]
    assert slo["count"] == 100
    assert slo["ttft_s"]["p50"] == pytest.approx(0.050)
    assert slo["ttft_s"]["p95"] == pytest.approx(0.095)
    assert slo["ttft_s"]["p99"] == pytest.approx(0.099)


def test_slo_window_excludes_old_requests():
    recorder = FlightRecorder(capacity=8)
    rec = _finished(recorder, model="m", ttft=0.01)
    rec.t_done -= 3600  # finished an hour ago (monotonic mark drives the window)
    assert recorder.slo(window_s=60.0)["models"] == {}


def test_tpot_needs_two_tokens():
    recorder = FlightRecorder()
    rec = recorder.start(model="m", endpoint="/t", activate=False)
    rec.t_first_token = rec.t_start + 0.1
    rec.t_last_token = rec.t_start + 0.1
    rec.tokens_out = 1
    assert rec.tpot is None
    rec.tokens_out = 5
    rec.t_last_token = rec.t_start + 0.5
    assert rec.tpot == pytest.approx(0.1)


def test_finish_is_idempotent_and_logs_wide_event():
    from gofr_tpu.logging import Level
    from gofr_tpu.testutil import MockLogger

    logger = MockLogger(Level.INFO)
    recorder = FlightRecorder(capacity=4, logger=logger)
    rec = recorder.start(model="m", endpoint="/t", trace_id="t" * 32,
                         activate=False)
    recorder.finish(rec)
    recorder.finish(rec, error=RuntimeError("late"))  # first finish wins
    assert len(recorder.records()) == 1
    assert recorder.records()[0]["status"] == "ok"
    wide = [ln for ln in logger.lines if "request_flight" in ln]
    assert len(wide) == 1
    payload = json.loads(wide[0])["message"]
    assert payload["trace_id"] == "t" * 32
    assert payload["status"] == "ok"


def test_contextvar_activation():
    recorder = FlightRecorder()
    assert current_record() is None
    rec = recorder.start(model="m", endpoint="/t")
    assert current_record() is rec
    from gofr_tpu.telemetry import activate_record

    activate_record(None)
    assert current_record() is None


def test_marks_set_once():
    rec = FlightRecord(model="m", endpoint="/t")
    rec.mark_enqueue()
    first = rec.t_enqueue
    rec.mark_enqueue()
    assert rec.t_enqueue == first
    rec.mark_dispatch(4)
    assert rec.batch_size == 4
    rec.mark_dispatch(8)  # chunked prefill: the FIRST cohort stays
    assert rec.batch_size == 4
    rec.mark_pooled(2)
    rec.mark_pooled(1)
    assert rec.pool_cohort == 2  # max across fan-out candidates


# -- end-to-end: the full spine over the in-process server -------------------

class _ListExporter:
    def __init__(self):
        self.spans = []

    def export(self, span):
        self.spans.append(span)

    def shutdown(self):
        pass


@pytest.fixture(scope="module")
def echo_app(tmp_path_factory):
    """Echo-model app with the OpenAI routes and a span-collecting
    tracer — the whole serving stack, no XLA compiles."""
    import os

    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes
    from gofr_tpu.tracing import Tracer, get_tracer, set_global_tracer

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
           "MODEL_NAME": "echo", "TOKENIZER": "byte",
           "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "1",
           "ECHO_STEP_MS": "1", "FLIGHT_SLOW_MS": "60000"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    os.chdir(tmp_path_factory.mktemp("telemetry"))
    prev_tracer = get_tracer()
    try:
        app = gofr_tpu.new()
    finally:
        os.chdir(cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    exporter = _ListExporter()
    set_global_tracer(Tracer(exporter))
    register_openai_routes(app)
    app.start()
    yield app, exporter, f"http://127.0.0.1:{port}"
    app.shutdown()
    set_global_tracer(prev_tracer)


def _post(base, payload, path="/v1/chat/completions"):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read()), dict(resp.headers.items())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())["data"]


def test_chat_request_produces_flight_record(echo_app):
    app, _, base = echo_app
    body, headers = _post(base, {
        "messages": [{"role": "user", "content": "flight check"}],
        "max_tokens": 6, "temperature": 0,
    })
    assert body["usage"]["completion_tokens"] == 6
    corr = headers["X-Correlation-ID"]
    records = _get(base, "/admin/requests")["requests"]
    mine = [r for r in records if r["trace_id"] == corr]
    assert len(mine) == 1, records
    rec = mine[0]
    assert rec["endpoint"] == "/v1/chat/completions"
    assert rec["model"] == "echo"
    assert rec["status"] == "ok"
    assert rec["tokens_in"] == body["usage"]["prompt_tokens"]
    assert rec["tokens_out"] == 6
    assert rec["batch_size"] >= 1
    # interference-scheduler accounting rode the echo prefill dispatch:
    # one bounded chunk through the (synthetic) echo bucket ladder
    assert rec["prefill_chunks"] == 1
    assert rec["prefill_bucket"] >= rec["tokens_in"]
    assert rec["pool_reject_reason"] is None  # echo has no decode pool
    # the spine timings are real, not defaults
    assert rec["queue_wait_s"] > 0
    assert rec["ttft_s"] > 0
    assert rec["tpot_s"] > 0
    assert rec["ttft_s"] < rec["duration_s"]
    # marks are ordered: enqueue <= dispatch <= first token <= done
    assert (rec["enqueue_ts"] <= rec["dispatch_ts"]
            <= rec["first_token_ts"] <= rec["done_ts"])


def test_chat_trace_is_one_connected_tree(echo_app):
    app, exporter, base = echo_app
    del exporter.spans[:]
    _, headers = _post(base, {
        "messages": [{"role": "user", "content": "trace me"}],
        "max_tokens": 4, "temperature": 0,
    })
    corr = headers["X-Correlation-ID"]
    spans = [s for s in exporter.spans if s.trace_id == corr]
    by_name = {s.name: s for s in spans}
    server = by_name["POST /v1/chat/completions"]
    batch = by_name["tpu-batch"]
    assert server.kind == "SERVER" and server.parent_id is None
    # tpu-batch is a DESCENDANT of the server span: walk the parent chain
    by_id = {s.span_id: s for s in spans}
    hops, cursor = 0, batch
    while cursor.parent_id is not None and hops < 10:
        cursor = by_id[cursor.parent_id]
        hops += 1
    assert cursor is server
    assert batch.tags["tpu.model"] == "echo"
    assert int(batch.tags["tpu.device_time_us"]) > 0


def test_streaming_chat_records_flight(echo_app):
    app, _, base = echo_app
    req = urllib.request.Request(
        base + "/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": "go"}],
                         "max_tokens": 5, "temperature": 0,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        corr = resp.headers["X-Correlation-ID"]
        raw = resp.read().decode()
    assert raw.rstrip().endswith("data: [DONE]")
    records = _get(base, "/admin/requests")["requests"]
    mine = [r for r in records if r["trace_id"] == corr]
    assert len(mine) == 1
    assert mine[0]["stream"] is True
    assert mine[0]["status"] == "ok"
    assert mine[0]["tokens_out"] == 5
    assert mine[0]["ttft_s"] > 0 and mine[0]["tpot_s"] > 0


def test_completions_endpoint_records_flight(echo_app):
    app, _, base = echo_app
    _, headers = _post(base, {"prompt": [7, 8, 9], "max_tokens": 3,
                              "temperature": 0}, path="/v1/completions")
    corr = headers["X-Correlation-ID"]
    mine = [r for r in _get(base, "/admin/requests")["requests"]
            if r["trace_id"] == corr]
    assert len(mine) == 1
    assert mine[0]["endpoint"] == "/v1/completions"
    assert mine[0]["tokens_in"] == 3 and mine[0]["tokens_out"] == 3


def test_slo_endpoint_reports_percentiles(echo_app):
    app, _, base = echo_app
    for _ in range(3):
        _post(base, {"messages": [{"role": "user", "content": "slo"}],
                     "max_tokens": 4, "temperature": 0})
    slo = _get(base, "/admin/slo?window=300")
    echo = slo["models"]["echo"]
    assert echo["count"] >= 3
    ttft = echo["ttft_s"]
    tpot = echo["tpot_s"]
    assert 0 < ttft["p50"] <= ttft["p95"] <= ttft["p99"]
    assert 0 < tpot["p50"] <= tpot["p95"] <= tpot["p99"]


def test_requests_endpoint_filters_and_limit(echo_app):
    app, _, base = echo_app
    _post(base, {"messages": [{"role": "user", "content": "x"}],
                 "max_tokens": 2, "temperature": 0})
    page = _get(base, "/admin/requests?limit=1")
    assert page["count"] == 1
    # nothing errored on this app (slow threshold is 60s, nothing slow)
    assert _get(base, "/admin/requests?errored=")["requests"] == []
    assert _get(base, "/admin/requests?slow=true")["requests"] == []
    # explicit false keeps the healthy ones
    assert _get(base, "/admin/requests?errored=false")["count"] >= 1


def test_sampled_fanout_candidates_share_one_record(echo_app):
    """n>1 sampled candidates run on pool threads; the copied contexts
    must carry the flight record there — tokens from EVERY candidate
    accumulate on the one record (and the trace stays connected)."""
    app, exporter, base = echo_app
    del exporter.spans[:]
    body, headers = _post(base, {
        "messages": [{"role": "user", "content": "fan out"}],
        "max_tokens": 3, "temperature": 1.0, "n": 2,
    })
    corr = headers["X-Correlation-ID"]
    assert len(body["choices"]) == 2
    mine = [r for r in _get(base, "/admin/requests")["requests"]
            if r["trace_id"] == corr]
    assert len(mine) == 1
    assert mine[0]["tokens_out"] == 6  # 2 candidates x 3 tokens, no losses
    # every candidate's device span joined the request trace
    gen_spans = [s for s in exporter.spans
                 if s.trace_id == corr and s.name == "tpu-echo-generate"]
    assert len(gen_spans) == 2


def test_pre_inference_400_is_not_recorded(echo_app):
    """A parameter rejection AFTER record start but BEFORE any device
    work (stream + top_logprobs 400s inside the stream constructor) must
    not pollute the recorder: no errored record, no SLO error count."""
    import urllib.error

    app, _, base = echo_app
    before = len(_get(base, "/admin/requests?limit=500")["requests"])
    try:
        _post(base, {"messages": [{"role": "user", "content": "x"}],
                     "max_tokens": 2, "temperature": 0, "stream": True,
                     "logprobs": True, "top_logprobs": 2})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    after = _get(base, "/admin/requests?limit=500")["requests"]
    assert len(after) == before  # dropped, not recorded


def test_generation_error_lands_in_errored_filter(echo_app):
    import urllib.error

    app, _, base = echo_app
    # the echo runner serves no adapters: the request parses fine (an
    # adapter key skips the model-name routing) but generation 400s —
    # a real inference attempt, so it must be recorded as errored
    try:
        _post(base, {"messages": [{"role": "user", "content": "x"}],
                     "max_tokens": 2, "temperature": 0, "adapter": "nope"})
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    errored = _get(base, "/admin/requests?errored=true")["requests"]
    assert errored and errored[0]["status"] == "error"
    assert "adapter" in errored[0]["error"]
