"""Mesh request validation: every malformed/unsatisfiable ``TPU_MESH``
fails AT BOOT with a ``ValueError`` that names the offending axis —
never a GSPMD shape error (or a wedge) at first dispatch. Tier-1: the
failing boots never reach a compile (mesh-fit validation runs before
params load), so each case costs milliseconds."""

import os

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import _parse_mesh_request, new_device


def _boot(**env):
    defaults = {"MODEL_NAME": "echo", "BATCH_MAX_SIZE": "4",
                "BATCH_TIMEOUT_MS": "1"}
    defaults.update(env)
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_malformed_entry_fails_at_construction():
    # the parse is device-free and runs in __init__ — before any probe
    with pytest.raises(ValueError, match="tp=abc"):
        _boot(TPU_MESH="tp=abc")


def test_unsupported_axis_names_the_axis():
    with pytest.raises(ValueError, match="'pp' not supported"):
        _boot(TPU_MESH="pp=2")


def test_mesh_larger_than_visible_devices():
    # the 8-device virtual mesh cannot host tp=64: the device-count
    # check fires at the probe, naming the request and the counts
    with pytest.raises(ValueError, match="needs 64 devices"):
        _boot(TPU_MESH="tp=64")


def test_tp_not_dividing_kv_heads_fails_before_params_load():
    # tiny has 2 kv heads; tp=4 cannot shard them — ValueError names tp
    # and fires from _validate_mesh_fit, before any checkpoint/init work
    with pytest.raises(ValueError, match=r"n_kv_heads=2 not divisible by tp=4"):
        _boot(MODEL_NAME="tiny", TPU_MESH="tp=4,dp=2")


def test_dp_not_dividing_batch_fails_at_boot():
    with pytest.raises(ValueError, match=r"dp\*fsdp=4"):
        _boot(MODEL_NAME="tiny", BATCH_MAX_SIZE="2", TPU_MESH="dp=4")


def test_tp_not_dividing_block_tokens_fails_echo_boot():
    # the echo host-mesh arena splits each block's tokens over tp:
    # KV_BLOCK_TOKENS=6 cannot split 4 ways — boot fails naming tp
    with pytest.raises(ValueError, match="tp=4 does not divide KV_BLOCK_TOKENS=6"):
        _boot(TPU_MESH="tp=4", KV_BLOCK_TOKENS="6", KV_BLOCKS="16")


def test_parse_is_the_single_grammar():
    assert _parse_mesh_request("tp=2,dp=2") == {"tp": 2, "dp": 2}
    assert _parse_mesh_request("") is None
    assert _parse_mesh_request("2x4") is None  # TPU VM physical grid form
    with pytest.raises(ValueError, match="malformed"):
        _parse_mesh_request("tp=")
