"""End-to-end App tests over real sockets.

Parity model: reference gofr_test.go:109-132 (boot the app, hit real
routes), handler_test.go, middleware tests (SURVEY.md §4)."""

import json
import urllib.error
import urllib.request

import pytest

import gofr_tpu
from gofr_tpu.errors import InvalidParamError
from gofr_tpu.http.response import Raw, Stream


@pytest.fixture
def app(make_plain_app):
    # shared conftest builder: ONE env-scrub list for every transport suite
    return make_plain_app()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read(), dict(r.headers.items())


def test_hello_route_envelope(app):
    app.get("/hello", lambda ctx: "Hello World!")
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    status, body, headers = _get(base + "/hello")
    assert status == 200
    assert json.loads(body) == {"data": "Hello World!"}
    assert headers["Content-Type"] == "application/json"
    assert "X-Correlation-ID" in headers


def test_path_and_query_params(app):
    app.get("/greet/{name}", lambda ctx: f"hi {ctx.path_param('name')} x{ctx.param('times')}")
    app.start()
    status, body, _ = _get(f"http://127.0.0.1:{app.http_port}/greet/ada?times=3")
    assert json.loads(body) == {"data": "hi ada x3"}


def test_error_handler_status(app):
    def boom(ctx):
        raise InvalidParamError("id")

    app.get("/err", boom)
    app.start()
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{app.http_port}/err", timeout=5)
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "invalid" in json.loads(e.read())["error"]["message"]


def test_panic_recovery_returns_500(app):
    def panics(ctx):
        raise RuntimeError("kaboom")

    app.get("/panic", panics)
    app.start()
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{app.http_port}/panic", timeout=5)
        raise AssertionError("expected 500")
    except urllib.error.HTTPError as e:
        assert e.code == 500
        assert e.read() == b'{"error":{"message":"some unexpected error has occurred"}}'


def test_default_routes(app):
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    status, body, _ = _get(base + "/.well-known/health")
    assert status == 200
    assert json.loads(body)["data"]["status"] == "UP"

    status, body, headers = _get(base + "/favicon.ico")
    assert status == 200
    assert headers["Content-Type"] == "image/x-icon"
    assert body[:4] == b"\x00\x00\x01\x00"  # ICO magic

    status, body, headers = _get(base + "/metrics")
    assert status == 200
    assert b"gofr_http_requests_total" in body

    try:
        urllib.request.urlopen(base + "/nope", timeout=5)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_blocking_sync_handlers_run_concurrently(app):
    """Sync handlers use the container's dedicated pool, not asyncio's
    cpu_count+4 default executor: 10 handlers blocking simultaneously must
    all be IN their handler body at once, even on a 1-CPU host (where the
    default executor would cap concurrency at 5 and queue the rest)."""
    import threading

    entered = threading.Semaphore(0)
    release = threading.Event()

    def blocker(ctx):
        entered.release()
        release.wait(10)
        return "ok"

    app.get("/block", blocker)
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    threads = [
        threading.Thread(target=lambda: _get(base + "/block")) for _ in range(10)
    ]
    for t in threads:
        t.start()
    try:
        all_entered = all(entered.acquire(timeout=10) for _ in range(10))
    finally:
        release.set()
        for t in threads:
            t.join(15)
    assert all_entered, "blocking handlers serialized by an undersized executor"


def test_many_concurrent_sse_streams_progress(app):
    """SSE pulls run on the container's I/O-sized pool: 8 streams that
    each BLOCK between events must all deliver their first event
    concurrently, even on a 1-CPU host where asyncio's default executor
    (cpu_count+4 threads) would starve streams 6+."""
    import http.client
    import threading

    release = threading.Event()

    def stream_handler(ctx):
        def events():
            yield "first"
            release.wait(10)  # hold the stream (and its pull thread) open
            yield "last"

        return Stream(events())

    app.get("/events", stream_handler)
    app.start()
    n = 8
    got_first = threading.Semaphore(0)
    failures = []

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=15)
        try:
            conn.request("GET", "/events")
            resp = conn.getresponse()
            line = resp.fp.readline()
            while line and not line.startswith(b"data:"):
                line = resp.fp.readline()
            if b"first" in line:
                got_first.release()
            else:
                failures.append(line)
        except Exception as exc:  # pragma: no cover
            failures.append(exc)
        finally:
            conn.close()

    threads = [threading.Thread(target=client) for _ in range(n)]
    for t in threads:
        t.start()
    try:
        all_first = all(got_first.acquire(timeout=10) for _ in range(n))
    finally:
        release.set()
        for t in threads:
            t.join(15)
    assert all_first and not failures, failures


def test_readiness_route(app):
    """/.well-known/ready is distinct from health: 200 once serving, 503
    with the current boot stage while the TPU stack warms up."""
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    status, body, _ = _get(base + "/.well-known/ready")
    assert status == 200
    ready = json.loads(body)  # no TPU: ready at listen
    assert ready["state"] == "ready"
    # process identity rides every ready 200 (the fleet prober's
    # restart detection keys on it changing across respawns)
    assert ready["boot_id"]

    class Warming:
        boot_status = {"state": "warming", "detail": "compiling prefill bucket 64"}

        def ready(self):
            return False

    app.container.tpu = Warming()
    try:
        urllib.request.urlopen(base + "/.well-known/ready", timeout=5)
        raise AssertionError("expected 503")
    except urllib.error.HTTPError as e:
        assert e.code == 503
        payload = json.loads(e.read())
        assert payload["state"] == "warming"
        assert "bucket 64" in payload["detail"]
    finally:
        app.container.tpu = None


def test_post_bind_and_raw(app):
    def create(ctx):
        data = ctx.bind()
        return Raw({"echo": data["v"] * 2})

    app.post("/double", create)
    app.start()
    req = urllib.request.Request(
        f"http://127.0.0.1:{app.http_port}/double",
        data=b'{"v": 21}',
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        assert json.loads(r.read()) == {"echo": 42}


def test_async_handler_and_sse_stream(app):
    async def stream(ctx):
        async def gen():
            for i in range(3):
                yield f"tok{i}"

        return Stream(gen())

    app.get("/stream", stream)
    app.start()
    with urllib.request.urlopen(f"http://127.0.0.1:{app.http_port}/stream", timeout=5) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        payload = r.read()
    assert payload == b"data: tok0\n\ndata: tok1\n\ndata: tok2\n\n"


def test_cors_preflight(app):
    app.start()
    req = urllib.request.Request(
        f"http://127.0.0.1:{app.http_port}/anything", method="OPTIONS"
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        assert r.status == 200
        assert r.headers["Access-Control-Allow-Origin"] == "*"


def test_keep_alive_multiple_requests(app):
    app.get("/ping", lambda ctx: "pong")
    app.start()
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", app.http_port, timeout=5)
    for _ in range(3):
        conn.request("GET", "/ping")
        resp = conn.getresponse()
        assert json.loads(resp.read()) == {"data": "pong"}
    conn.close()


def test_trace_context_propagation(app):
    seen = {}

    def echo_trace(ctx):
        seen["trace_id"] = ctx.trace_id
        return "ok"

    app.get("/t", echo_trace)
    app.start()
    req = urllib.request.Request(
        f"http://127.0.0.1:{app.http_port}/t",
        headers={"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"},
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        assert r.headers["X-Correlation-ID"] == "ab" * 16
    assert seen["trace_id"] == "ab" * 16


def test_correlation_id_generated_without_traceparent(app):
    """Every response carries X-Correlation-ID even when the caller sent
    no traceparent: the server's own trace id (32 hex chars) — the
    middleware-stack propagation contract end to end."""
    app.get("/cid", lambda ctx: "ok")
    app.start()
    _, _, headers = _get(f"http://127.0.0.1:{app.http_port}/cid")
    cid = headers["X-Correlation-ID"]
    assert len(cid) == 32
    int(cid, 16)  # hex


def test_metrics_path_label_is_route_pattern(app):
    """The metrics path label must be the MATCHED ROUTE PATTERN (bounded
    cardinality), never the raw URL; unrouted requests share one
    'unmatched' series."""
    import urllib.error

    app.get("/greet/{name}", lambda ctx: "hi")
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    _get(base + "/greet/ada")
    _get(base + "/greet/bob")
    try:
        urllib.request.urlopen(base + "/definitely/not/routed", timeout=5)
    except urllib.error.HTTPError:
        pass
    _, body, _ = _get(base + "/metrics")
    text = body.decode()
    assert 'path="/greet/{name}"' in text
    assert "/greet/ada" not in text and "/greet/bob" not in text
    assert 'path="unmatched"' in text
    # duration histogram carries the same label
    assert ('gofr_http_request_duration_seconds_count{path="/greet/{name}"} 2'
            in text)


def test_metrics_middleware_counts_escaping_exceptions():
    """An exception that escapes the inner chain must count as a 500
    instead of silently bypassing the metrics (try/finally), and still
    propagate to the outer recovery middleware."""
    import asyncio

    from gofr_tpu.http.middleware import metrics_middleware
    from gofr_tpu.http.request import Request
    from gofr_tpu.metrics import Registry

    registry = Registry()

    async def exploding(request):
        raise RuntimeError("middleware-level failure")

    endpoint = metrics_middleware(registry)(exploding)
    request = Request("GET", "/boom", {})
    with pytest.raises(RuntimeError):
        asyncio.run(endpoint(request))
    counter = registry.counter("gofr_http_requests_total")
    assert counter.value(method="GET", path="unmatched", status="500") == 1


def test_put_patch_delete_routes(app):
    """The full method-helper surface (parity: gofr.go:152-169) through
    real sockets — PUT/PATCH/DELETE were registered but never driven."""
    app.put("/thing/{id}", lambda ctx: {"put": ctx.request.path_param("id")})
    app.patch("/thing/{id}", lambda ctx: {"patch": ctx.request.path_param("id")})
    app.delete("/thing/{id}", lambda ctx: {"del": ctx.request.path_param("id")})
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    for method, key in (("PUT", "put"), ("PATCH", "patch"), ("DELETE", "del")):
        req = urllib.request.Request(base + "/thing/7", method=method,
                                     data=b"{}" if method != "DELETE" else None)
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read()) == {"data": {key: "7"}}


def test_register_json_service_overlap_rejected(app):
    """A method registered as both unary and streaming is a registration-
    time ValueError, never a runtime surprise."""
    with pytest.raises(ValueError, match="both"):
        app.register_json_service(
            "svc", {"M": lambda ctx: 1}, stream_methods={"M": lambda ctx: iter(())}
        )


def test_run_drains_on_sigterm(app, monkeypatch):
    """app.run() on the main thread installs a SIGTERM handler and drains
    cleanly (the graceful-shutdown behavior the reference lacks —
    SURVEY §5 notes its servers just ListenAndServe)."""
    import os
    import signal
    import threading
    import time

    app.get("/ping", lambda ctx: "pong")
    # the prober must never fire before run() installs its handler (a
    # SIGTERM under the default disposition would kill pytest itself):
    # record the installation by wrapping signal.signal, and restore the
    # process's SIGTERM disposition afterwards — run() never does
    installed = threading.Event()
    orig_handler = signal.getsignal(signal.SIGTERM)
    orig_signal = signal.signal

    def recording_signal(num, handler):
        out = orig_signal(num, handler)
        if num == signal.SIGTERM:
            installed.set()
        return out

    monkeypatch.setattr(signal, "signal", recording_signal)

    def fire():
        if not installed.wait(timeout=10):
            # run() never installed the handler: interrupt the main
            # thread (run() handles KeyboardInterrupt and drains) so the
            # test FAILS on the assert below instead of hanging the
            # whole suite in stop.wait()
            import _thread

            _thread.interrupt_main()
            return
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                _get(f"http://127.0.0.1:{app.http_port}/ping")
                break
            except Exception:
                time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGTERM)

    t = threading.Thread(target=fire)
    t.start()
    try:
        app.run()  # blocks until the SIGTERM handler fires, then drains
        t.join(timeout=10)
        assert installed.is_set()
        with pytest.raises(Exception):
            _get(f"http://127.0.0.1:{app.http_port}/ping")
    finally:
        orig_signal(signal.SIGTERM, orig_handler)
