"""Property/fuzz tests for serving invariants.

1. Pooled continuous-batching decode must equal solo decode for ANY
   (prompt, length, chunk) combination — seeded fuzz over the config
   space, not just the hand-picked cases in test_decode_pool.py.
2. The on-device sampler must match a straightforward numpy oracle of
   the documented composition (temperature → top-k → top-p → min-p)
   for random logits and knob combinations, including ties.
3. Malformed HTTP bodies must map to 4xx — never a 5xx — across a zoo
   of broken payloads.
"""

import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.testutil import serving_device
import pytest

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def test_fuzz_pooled_equals_solo():
    rng = np.random.RandomState(7)
    with serving_device(DECODE_POOL="on", DECODE_SLOTS="3", DECODE_CHUNK="3") \
            as pooled, serving_device(DECODE_POOL="off", DECODE_CHUNK="5") as solo:
        vocab = pooled.runner.cfg.vocab_size
        for trial in range(12):
            plen = int(rng.randint(1, 60))
            prompt = [int(t) for t in rng.randint(1, vocab, size=plen)]
            n = int(rng.randint(1, 20))
            a = pooled.generate(prompt, max_new_tokens=n)
            b = solo.generate(prompt, max_new_tokens=n)
            assert a == b, (trial, plen, n)


def _oracle_filter(logits, temperature, top_k, top_p, min_p):
    """Numpy oracle of the documented composition; returns the allowed
    token set."""
    scaled = logits / max(temperature, 1e-6)
    v = scaled.shape[-1]
    order = np.argsort(-scaled, kind="stable")
    sorted_desc = scaled[order]
    k = top_k if top_k > 0 else v
    kth = sorted_desc[min(k, v) - 1]
    keep = scaled >= kth  # value threshold: ties at kth survive
    masked = np.where(keep[order], sorted_desc, -1e30)
    probs = np.exp(masked - masked.max())
    probs = probs / probs.sum()
    cum = np.cumsum(probs) - probs  # exclusive
    nucleus_keep = cum < top_p
    cutoff = np.min(np.where(nucleus_keep, masked, np.inf))
    keep &= scaled >= cutoff
    mp_keep = probs >= min_p * probs.max()
    cutoff_mp = np.min(np.where(mp_keep, masked, np.inf))
    keep &= scaled >= cutoff_mp
    return {int(i) for i in np.nonzero(keep)[0]}


def test_fuzz_sampler_matches_oracle():
    from gofr_tpu.ops.sampling import sample_logits

    rng = np.random.RandomState(3)
    for trial in range(25):
        v = int(rng.randint(4, 40))
        logits = rng.randn(v).astype(np.float32)
        if trial % 3 == 0:  # inject ties
            logits[: v // 2] = logits[0]
        temperature = float(rng.uniform(0.2, 3.0))
        top_k = int(rng.randint(0, v + 2))
        top_p = float(rng.uniform(0.3, 1.0))
        min_p = float(rng.uniform(0.0, 0.6))
        allowed = _oracle_filter(logits, temperature, top_k, top_p, min_p)
        assert allowed, (trial, "oracle must keep at least the argmax")
        picks = {
            int(sample_logits(jnp.asarray(logits)[None], jax.random.key(s),
                              temperature, top_k, top_p, min_p)[0])
            for s in range(30)
        }
        assert picks <= allowed, (trial, picks - allowed, allowed,
                                  temperature, top_k, top_p, min_p)


BROKEN_BODIES = [
    b"",  # empty
    b"not json",
    b"[1, 2",  # truncated
    b"null",
    b'{"tokens": "abc"}',  # wrong type
    b'{"tokens": []}',  # empty prompt
    b'{"tokens": [1.5]}',  # float ids
    b'{"tokens": [999999999]}',  # out of vocab
    b'{"tokens": [-4]}',  # negative id
    b'{"tokens": [1, 2], "max": "lots"}',
    b'{"tokens": [1, 2], "temperature": -3}',
    b'{"tokens": [1, 2], "top_p": 0}',
    b'{"tokens": [1, 2], "min_p": 2}',
    b'{"tokens": [1, 2], "repetition_penalty": 0}',
    b'{"tokens": [1, 2], "stop_tokens": "x"}',
    b'{"tokens": [1, 2], "seed": "abc"}',
]


def test_fuzz_malformed_bodies_never_500(free_port, monkeypatch, tmp_path):
    import gofr_tpu
    from gofr_tpu.errors import InvalidParamError
    from gofr_tpu.ops.sampling import Sampler, stop_tokens_from_body

    monkeypatch.setenv("HTTP_PORT", str(free_port()))
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    monkeypatch.setenv("MODEL_NAME", "tiny")
    monkeypatch.setenv("BATCH_MAX_SIZE", "2")
    monkeypatch.setenv("BATCH_TIMEOUT_MS", "1")
    monkeypatch.chdir(tmp_path)
    app = gofr_tpu.new()

    def generate(ctx):
        body = ctx.bind()
        if not isinstance(body, dict):
            raise InvalidParamError("body (expected a JSON object)")
        try:
            sampler = Sampler.from_body(body)
            stops = stop_tokens_from_body(body)
            max_new = int(body.get("max", 8))
        except (TypeError, ValueError) as exc:
            raise InvalidParamError(f"sampling params ({exc})") from exc
        toks = ctx.tpu.generate(body.get("tokens"), max_new_tokens=max_new,
                                sampler=sampler, stop_tokens=stops)
        return {"tokens": toks}

    app.post("/generate", generate)
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    try:
        for raw in BROKEN_BODIES:
            req = urllib.request.Request(
                base + "/generate", data=raw,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=60):
                    pass  # some payloads may legitimately succeed
            except urllib.error.HTTPError as e:
                assert 400 <= e.code < 500, (raw, e.code, e.read(300))
    finally:
        app.shutdown()
