"""Lint-style guard on metric naming: every metric registered through
the container's registry must follow the framework convention —
``gofr_`` prefix, snake_case, and a recognized unit/dimension suffix —
so dashboard and alert queries stay stable as metrics grow. Scans the
package source for registration calls (the registry API takes literal
names), the same way a linter would."""

import pathlib
import re

import gofr_tpu

PKG_DIR = pathlib.Path(gofr_tpu.__file__).parent

# registry.counter("name", ...) / metrics.gauge(\n    "name", ... — the
# name literal is the first argument, possibly on the next line
_REGISTRATION = re.compile(
    r'\.(counter|gauge|histogram)\(\s*\n?\s*"([^"]+)"', re.MULTILINE
)

# unit suffixes (prometheus convention) plus the framework's recognized
# dimensionless suffixes (counts of things whose unit IS the thing)
_COUNTER_SUFFIXES = ("_total",)
_HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_size")
_GAUGE_SUFFIXES = (
    "_seconds", "_bytes", "_total", "_depth", "_ratio", "_entries",
    "_active", "_acceptance", "_state",
)
# roofline utilization gauges: the suffix IS the (well-known) metric name
_GAUGE_ALLOWLIST = {"gofr_tpu_mfu", "gofr_tpu_mbu"}


def _registrations():
    found = []
    for path in sorted(PKG_DIR.rglob("*.py")):
        source = path.read_text(encoding="utf-8")
        for kind, name in _REGISTRATION.findall(source):
            found.append((str(path.relative_to(PKG_DIR)), kind, name))
    return found


def test_scanner_sees_the_known_registrations():
    names = {name for _, _, name in _registrations()}
    # sanity that the regex actually matches the codebase's idiom — a
    # refactor that breaks the scan must fail here, not silently pass
    assert {"gofr_http_requests_total", "gofr_tpu_ttft_seconds",
            "gofr_tpu_batch_size", "gofr_tpu_queue_depth"} <= names
    # the interference-scheduler suite (tpu/scheduler.py, batcher
    # padded-FLOP accounting, pool reject reasons) stays scan-visible
    assert {"gofr_tpu_prefill_chunks_total", "gofr_tpu_sched_defer_seconds",
            "gofr_tpu_prefill_padded_tokens_total",
            "gofr_tpu_pool_reject_total"} <= names
    # the engine-introspection suite (tpu/introspect.py + device compile/
    # cache observability + the profiler-activity gauge) stays visible too
    assert {"gofr_tpu_engine_state", "gofr_tpu_device_stalls_total",
            "gofr_tpu_dispatches_total", "gofr_tpu_dispatch_seconds",
            "gofr_tpu_compile_seconds", "gofr_tpu_compiles_total",
            "gofr_tpu_cache_events_total",
            "gofr_tpu_profiler_active"} <= names
    assert len(names) >= 24


def test_every_metric_follows_the_naming_convention():
    problems = []
    for where, kind, name in _registrations():
        if not name.startswith("gofr_"):
            problems.append(f"{where}: {name} missing gofr_ prefix")
            continue
        if not re.fullmatch(r"[a-z][a-z0-9_]*", name) or "__" in name:
            problems.append(f"{where}: {name} is not snake_case")
            continue
        if kind == "counter" and not name.endswith(_COUNTER_SUFFIXES):
            problems.append(f"{where}: counter {name} must end in _total")
        elif kind == "histogram" and not name.endswith(_HISTOGRAM_SUFFIXES):
            problems.append(
                f"{where}: histogram {name} needs a unit suffix "
                f"{_HISTOGRAM_SUFFIXES}"
            )
        elif kind == "gauge" and name not in _GAUGE_ALLOWLIST and \
                not name.endswith(_GAUGE_SUFFIXES):
            problems.append(
                f"{where}: gauge {name} needs a unit/dimension suffix "
                f"{_GAUGE_SUFFIXES} (or an explicit allowlist entry)"
            )
    assert not problems, "\n".join(problems)


def test_registered_names_at_runtime_match_convention():
    """Belt and braces: metrics actually registered by a wired container
    (middleware + batcher instantiation) pass the same check — catches
    dynamically composed names the source scan cannot see."""
    from gofr_tpu.http.middleware import metrics_middleware
    from gofr_tpu.metrics import Registry
    from gofr_tpu.tpu.batcher import DynamicBatcher

    registry = Registry()
    metrics_middleware(registry)
    batcher = DynamicBatcher(lambda batch: batch, metrics=registry, name="t")
    try:
        for name in registry._metrics:
            assert name.startswith("gofr_"), name
            assert re.fullmatch(r"[a-z][a-z0-9_]*", name), name
    finally:
        batcher.close()
