"""Lint-style guard on metric naming: every metric registered through
the container's registry must follow the framework convention —
``gofr_`` prefix, snake_case, and a recognized unit/dimension suffix —
so dashboard and alert queries stay stable as metrics grow. Scans the
package source for registration calls (the registry API takes literal
names), the same way a linter would."""

import pathlib
import re

import gofr_tpu

PKG_DIR = pathlib.Path(gofr_tpu.__file__).parent

# registry.counter("name", ...) / metrics.gauge(\n    "name", ... — the
# name literal is the first argument, possibly on the next line
_REGISTRATION = re.compile(
    r'\.(counter|gauge|histogram)\(\s*\n?\s*"([^"]+)"', re.MULTILINE
)

# unit suffixes (prometheus convention) plus the framework's recognized
# dimensionless suffixes (counts of things whose unit IS the thing)
_COUNTER_SUFFIXES = ("_total",)
_HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_size")
_GAUGE_SUFFIXES = (
    "_seconds", "_bytes", "_total", "_depth", "_ratio", "_entries",
    "_active", "_acceptance", "_state", "_blocks", "_size", "_level",
    "_per_dispatch", "_rate", "_remaining",
)
# roofline utilization gauges: the suffix IS the (well-known) metric name
_GAUGE_ALLOWLIST = {"gofr_tpu_mfu", "gofr_tpu_mbu"}


def _registrations():
    found = []
    for path in sorted(PKG_DIR.rglob("*.py")):
        source = path.read_text(encoding="utf-8")
        for kind, name in _REGISTRATION.findall(source):
            found.append((str(path.relative_to(PKG_DIR)), kind, name))
    return found


def test_scanner_sees_the_known_registrations():
    names = {name for _, _, name in _registrations()}
    # sanity that the regex actually matches the codebase's idiom — a
    # refactor that breaks the scan must fail here, not silently pass
    assert {"gofr_http_requests_total", "gofr_tpu_ttft_seconds",
            "gofr_tpu_batch_size", "gofr_tpu_queue_depth"} <= names
    # the interference-scheduler suite (tpu/scheduler.py, batcher
    # padded-FLOP accounting, pool reject reasons) stays scan-visible
    assert {"gofr_tpu_prefill_chunks_total", "gofr_tpu_sched_defer_seconds",
            "gofr_tpu_prefill_padded_tokens_total",
            "gofr_tpu_pool_reject_total"} <= names
    # the engine-introspection suite (tpu/introspect.py + device compile/
    # cache observability + the profiler-activity gauge) stays visible too
    assert {"gofr_tpu_engine_state", "gofr_tpu_device_stalls_total",
            "gofr_tpu_dispatches_total", "gofr_tpu_dispatch_seconds",
            "gofr_tpu_compile_seconds", "gofr_tpu_compiles_total",
            "gofr_tpu_cache_events_total",
            "gofr_tpu_profiler_active"} <= names
    # the paged-KV block accounting (tpu/kv_blocks.py BlockPool)
    assert {"gofr_tpu_kv_blocks", "gofr_tpu_kv_evictions_total"} <= names
    # the sharded-serving suite (TPU_MESH): live mesh shape + the
    # features a mesh shape degraded (tpu/device.py)
    assert {"gofr_tpu_mesh_axis_size",
            "gofr_tpu_mesh_degrade_total"} <= names
    # the cardinality guard's overflow ledger (metrics.py Registry)
    assert "gofr_tpu_metrics_dropped_series_total" in names
    # deadline-aware serving + overload brownout (PR 10)
    assert {"gofr_tpu_deadline_exceeded_total",
            "gofr_tpu_cancellations_total",
            "gofr_tpu_brownout_level",
            "gofr_tpu_brownout_shed_total"} <= names
    # the fleet front door (fleet/router.py FleetRouter._init_metrics):
    # every routing/retry/shed/breaker decision must stay scan-visible
    assert {"gofr_tpu_router_requests_total",
            "gofr_tpu_router_retries_total",
            "gofr_tpu_router_shed_total",
            "gofr_tpu_router_breaker_transitions_total",
            "gofr_tpu_router_breaker_state",
            "gofr_tpu_router_replica_state",
            "gofr_tpu_router_outstanding_depth",
            "gofr_tpu_router_inflight_depth",
            "gofr_tpu_router_upstream_seconds"} <= names
    # disaggregated prefill/decode (PR 11): the cross-replica KV
    # transfer ledger + the quota redis fail-open counter
    assert {"gofr_tpu_kv_transfer_total",
            "gofr_tpu_router_quota_fallback_total"} <= names
    # pooled speculative decoding (tpu/spec_pool.py): the accept-ratio
    # EMA and tokens-per-dispatch gauges stay scan-visible
    assert {"gofr_tpu_spec_accept_ratio",
            "gofr_tpu_spec_tokens_per_dispatch"} <= names
    # fleet-wide tracing (PR 16): the per-hop latency decomposition
    # histogram (router.py) and the zipkin exporter drop counter
    # (tracing.py attach_metrics)
    assert {"gofr_tpu_router_hop_seconds",
            "gofr_tpu_trace_export_failures_total"} <= names
    # dispatch cost model (tpu/costmodel.py): the per-family residual
    # EMA gauge and the anomaly counter stay scan-visible
    assert {"gofr_tpu_dispatch_residual_ratio",
            "gofr_tpu_dispatch_anomalies_total"} <= names
    # SLO engine (slo.py) + bounded tenant metering (telemetry.py
    # TenantLedger): burn/budget surfaces and the sketch's OWN
    # cardinality ledger — per-tenant series are forbidden by design
    assert {"gofr_tpu_slo_burn_rate",
            "gofr_tpu_slo_budget_remaining",
            "gofr_tpu_slo_burn_alerts_total",
            "gofr_tpu_tenants_tracked_entries",
            "gofr_tpu_tenant_overflow_total"} <= names
    # the device serving core (tpu/device.py _init_metrics is the one
    # registration home — GFL007 — for request/token/memory accounting,
    # speculative acceptance and the prefix-cache surfaces)
    assert {"gofr_tpu_requests_total",
            "gofr_tpu_tokens_total",
            "gofr_tpu_device_memory_bytes",
            "gofr_tpu_spec_acceptance",
            "gofr_tpu_prefix_hit_ratio",
            "gofr_tpu_prefix_partial_hit_ratio",
            "gofr_tpu_prefix_entries"} <= names
    # continuous batching internals: queue-wait histogram (batcher.py)
    # and the live decode-slot gauge (decode_pool.py)
    assert {"gofr_tpu_queue_wait_seconds",
            "gofr_tpu_decode_slots_active"} <= names
    # crash-recovery surfaces: engine recovery outcomes (tpu/recovery.py),
    # journal resume modes (telemetry.py), and the fleet's replica
    # restart / stream-resume ledgers (fleet/router.py)
    assert {"gofr_tpu_engine_recoveries_total",
            "gofr_tpu_journal_resumes_total",
            "gofr_tpu_router_replica_restarts_total",
            "gofr_tpu_router_stream_resumes_total"} <= names
    assert len(names) >= 35


def test_suffix_tables_match_gofrlint():
    """GFL005 (tools/gofrlint.py) is the static half of this exact
    convention: the two suffix tables must stay in LOCKSTEP or a new
    metric family passes one gate and fails the other with a split
    verdict."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gofrlint_naming", PKG_DIR.parent / "tools" / "gofrlint.py"
    )
    gofrlint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gofrlint)
    assert gofrlint._COUNTER_SUFFIXES == _COUNTER_SUFFIXES
    assert gofrlint._HISTOGRAM_SUFFIXES == _HISTOGRAM_SUFFIXES
    assert gofrlint._GAUGE_SUFFIXES == _GAUGE_SUFFIXES
    assert gofrlint._GAUGE_ALLOWLIST == _GAUGE_ALLOWLIST


def test_every_metric_follows_the_naming_convention():
    problems = []
    for where, kind, name in _registrations():
        if not name.startswith("gofr_"):
            problems.append(f"{where}: {name} missing gofr_ prefix")
            continue
        if not re.fullmatch(r"[a-z][a-z0-9_]*", name) or "__" in name:
            problems.append(f"{where}: {name} is not snake_case")
            continue
        if kind == "counter" and not name.endswith(_COUNTER_SUFFIXES):
            problems.append(f"{where}: counter {name} must end in _total")
        elif kind == "histogram" and not name.endswith(_HISTOGRAM_SUFFIXES):
            problems.append(
                f"{where}: histogram {name} needs a unit suffix "
                f"{_HISTOGRAM_SUFFIXES}"
            )
        elif kind == "gauge" and name not in _GAUGE_ALLOWLIST and \
                not name.endswith(_GAUGE_SUFFIXES):
            problems.append(
                f"{where}: gauge {name} needs a unit/dimension suffix "
                f"{_GAUGE_SUFFIXES} (or an explicit allowlist entry)"
            )
    assert not problems, "\n".join(problems)


def test_registered_names_at_runtime_match_convention():
    """Belt and braces: metrics actually registered by a wired container
    (middleware + batcher instantiation) pass the same check — catches
    dynamically composed names the source scan cannot see."""
    from gofr_tpu.http.middleware import metrics_middleware
    from gofr_tpu.metrics import Registry
    from gofr_tpu.tpu.batcher import DynamicBatcher

    registry = Registry()
    metrics_middleware(registry)
    batcher = DynamicBatcher(lambda batch: batch, metrics=registry, name="t")
    try:
        for name in registry._metrics:
            assert name.startswith("gofr_"), name
            assert re.fullmatch(r"[a-z][a-z0-9_]*", name), name
    finally:
        batcher.close()


# -- exposition validity: strict parser over the full /metrics output ---------
#
# The naming checks above guard the NAMES; these guard the WIRE FORMAT.
# A hand-rolled expositor can drift in ways Prometheus silently
# tolerates and OpenMetrics parsers reject (repr() floats, integer `le`
# values, missing # EOF, broken escaping) — so both formats are parsed
# with a STRICT reader and every structural rule is asserted.

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?"
    r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:e-?[0-9]+)?)|\+Inf|-Inf|NaN)$"
)
_EXEMPLAR_RE = re.compile(
    r"^\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\"\\n])*\",?)*)\}"
    r" -?[0-9]+(?:\.[0-9]+)?(?:e-?[0-9]+)?( [0-9]+\.[0-9]+)?$"
)


def _parse_labels(raw):
    """Parse `{a="b",c="d"}` strictly: every byte must be consumed by
    well-formed, correctly escaped label pairs."""
    if not raw:
        return {}
    assert raw.startswith("{") and raw.endswith("}"), raw
    inner = raw[1:-1]
    labels = {}
    pos = 0
    while pos < len(inner):
        m = _LABEL_RE.match(inner, pos)
        assert m, f"malformed label at {inner[pos:]!r} in {raw!r}"
        assert m.group(1) not in labels, f"duplicate label in {raw!r}"
        labels[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(inner):
            assert inner[pos] == ",", f"bad label separator in {raw!r}"
            pos += 1
    return labels


def parse_exposition(text, openmetrics=False):
    """Strict structural parse of a Prometheus/OpenMetrics text body.
    Returns {family: {"kind", "help", "samples": [(name, labels, value,
    exemplar)]}} and asserts every format rule on the way."""
    assert text.endswith("\n"), "exposition must end with a newline"
    lines = text.split("\n")[:-1]
    if openmetrics:
        assert lines and lines[-1] == "# EOF", "OpenMetrics must end with # EOF"
        lines = lines[:-1]
        assert "# EOF" not in lines, "# EOF before the end of the body"
    else:
        assert "# EOF" not in lines, "# EOF is OpenMetrics-only"
    families = {}
    current = None
    for line in lines:
        assert line == line.rstrip(), f"trailing whitespace: {line!r}"
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            assert name not in families, f"duplicate family {name}"
            families[name] = {"kind": None, "help": help_, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            assert name == current, "# TYPE must directly follow its # HELP"
            assert kind in ("counter", "gauge", "histogram"), kind
            assert families[name]["kind"] is None, f"duplicate TYPE {name}"
            families[name]["kind"] = kind
            continue
        assert not line.startswith("#"), f"unexpected comment {line!r}"
        assert current is not None, f"sample before any family: {line!r}"
        kind = families[current]["kind"]
        assert kind is not None, f"sample before # TYPE: {line!r}"
        sample, sep, exemplar = line.partition(" # ")
        if sep:
            assert openmetrics and kind == "histogram", (
                f"exemplar outside an OpenMetrics histogram: {line!r}"
            )
            assert _EXEMPLAR_RE.match("# " + exemplar) or _EXEMPLAR_RE.match(
                exemplar
            ), f"malformed exemplar {exemplar!r}"
        m = _SAMPLE_RE.match(sample)
        assert m, f"malformed sample line {sample!r}"
        name, raw_labels, value = m.groups()
        labels = _parse_labels(raw_labels)
        if kind == "histogram":
            assert name in (
                current + "_bucket", current + "_sum", current + "_count"
            ), f"sample {name} not of histogram family {current}"
            if name.endswith("_bucket"):
                assert "le" in labels, f"bucket without le: {line!r}"
                if openmetrics:
                    le = labels["le"]
                    assert le == "+Inf" or "." in le, (
                        f"OpenMetrics le must be a canonical float: {line!r}"
                    )
        elif kind == "counter" and openmetrics:
            assert name == current + "_total", (
                f"OpenMetrics counter sample {name} must be "
                f"{current}_total"
            )
        else:
            assert name == current, f"sample {name} outside family {current}"
        families[current]["samples"].append(
            (name, labels, value, exemplar if sep else None)
        )
    return families


def _assert_histogram_invariants(family, data):
    """Cumulative bucket monotonicity, +Inf == _count, sum/count pairing
    — per label-set."""
    series = {}
    for name, labels, value, _ in data["samples"]:
        key = tuple(sorted(
            (k, v) for k, v in labels.items() if k != "le"
        ))
        entry = series.setdefault(key, {"buckets": [], "sum": None, "count": None})
        if name.endswith("_bucket"):
            entry["buckets"].append((labels["le"], float(value)))
        elif name.endswith("_sum"):
            entry["sum"] = float(value)
        elif name.endswith("_count"):
            entry["count"] = float(value)
    for key, entry in series.items():
        assert entry["sum"] is not None and entry["count"] is not None, (
            f"{family}{key}: missing _sum/_count"
        )
        les = [le for le, _ in entry["buckets"]]
        assert les[-1] == "+Inf", f"{family}{key}: last bucket must be +Inf"
        bounds = [float("inf") if le == "+Inf" else float(le) for le in les]
        assert bounds == sorted(bounds), f"{family}{key}: le out of order"
        counts = [c for _, c in entry["buckets"]]
        assert counts == sorted(counts), (
            f"{family}{key}: cumulative bucket counts must be monotonic"
        )
        assert counts[-1] == entry["count"], (
            f"{family}{key}: +Inf bucket != _count"
        )


def _tricky_registry():
    """A registry wired the way the container wires it (middleware +
    batcher + device-shaped metrics), then poked with the values that
    historically break expositions: label escaping, float formatting,
    exemplars, +Inf overflow."""
    from gofr_tpu.http.middleware import metrics_middleware
    from gofr_tpu.metrics import Registry
    from gofr_tpu.tpu.batcher import DynamicBatcher

    registry = Registry(
        exemplar_provider=lambda: {"trace_id": "abc123", "dispatch_id": "7"}
    )
    metrics_middleware(registry)
    batcher = DynamicBatcher(lambda batch: batch, metrics=registry, name="t")
    batcher.close()
    counter = registry.counter(
        "gofr_http_requests_total", labels=("method", "path", "status")
    )
    counter.inc(method="GET", path='/esc"ape\\me\nnow', status="200")
    counter.inc(3, method="POST", path="/v1/chat/completions", status="500")
    registry.gauge("gofr_tpu_queue_depth").set(2.5)
    hist = registry.histogram(
        "gofr_tpu_ttft_seconds", "ttft", labels=("model", "op"),
        buckets=(0.1, 1.0, 2.5),
    )
    hist.observe(0.05, model="echo", op="generate")
    hist.observe(0.7, exemplar={"trace_id": "def456"}, model="echo", op="generate")
    hist.observe(99.0, model="echo", op="generate")  # +Inf overflow
    hist.observe(0.3, model='quo"te', op="infer")  # escaped label + exemplar
    return registry


def test_prometheus_exposition_parses_strictly():
    registry = _tricky_registry()
    families = parse_exposition(registry.expose(), openmetrics=False)
    assert families["gofr_http_requests_total"]["kind"] == "counter"
    # escaping round-trips: the parsed label equals the escaped form
    paths = {
        labels["path"]
        for _, labels, _, _ in families["gofr_http_requests_total"]["samples"]
    }
    assert '/esc\\"ape\\\\me\\nnow' in paths
    for family, data in families.items():
        if data["kind"] == "histogram":
            _assert_histogram_invariants(family, data)
    # no exemplars ever leak into the classic format
    assert all(
        ex is None
        for data in families.values()
        for _, _, _, ex in data["samples"]
    )


def test_openmetrics_exposition_parses_strictly():
    registry = _tricky_registry()
    families = parse_exposition(
        registry.expose(openmetrics=True), openmetrics=True
    )
    # counter families dropped their _total suffix; samples kept it
    assert families["gofr_http_requests"]["kind"] == "counter"
    assert all(
        name == "gofr_http_requests_total"
        for name, _, _, _ in families["gofr_http_requests"]["samples"]
    )
    for family, data in families.items():
        if data["kind"] == "histogram":
            _assert_histogram_invariants(family, data)
    # exemplars present, only on buckets, correctly formed (the regex
    # asserted syntax during parsing; here: the content arrived)
    ttft = families["gofr_tpu_ttft_seconds"]["samples"]
    exemplars = [ex for name, _, _, ex in ttft if ex is not None]
    assert exemplars, "ttft histogram lost its exemplars"
    assert any('trace_id="def456"' in ex for ex in exemplars)
    assert any('trace_id="abc123"' in ex for ex in exemplars)
    assert all(name.endswith("_bucket") for name, _, _, ex in ttft if ex)


def test_full_app_metrics_output_is_openmetrics_valid():
    """The tree-wide sweep, live: a wired container's ACTUAL registry —
    every default metric the container, middleware, and recorder
    register — must expose a strictly parseable body in both formats."""
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.container import Container

    container = Container(EnvConfig(), wire=False)
    try:
        container.metrics.histogram(
            "gofr_http_request_duration_seconds", labels=("path",)
        ).observe(0.2, path="/v1/x")
        parse_exposition(container.metrics.expose(), openmetrics=False)
        families = parse_exposition(
            container.metrics.expose(openmetrics=True), openmetrics=True
        )
        assert "gofr_tpu_metrics_dropped_series" in families
    finally:
        container.close()
