"""Fleet front door, tier-1: unit semantics for the breaker / quota /
affinity pieces, then chaos e2e over real sockets — multi-replica echo
fleets in ONE process (``gofr_tpu/devtools/chaos.py``), every failure
injected deterministically:

- a force-wedged replica mid-request: the client request still
  completes, retried to a healthy replica (non-stream AND not-yet-
  streamed SSE), and the wedged replica's breaker opens within its
  configured threshold;
- connection refused (listener gone): retries land elsewhere, the
  breaker opens, half-open probes, closes on recovery;
- a device-level wedge (echo ``stall_hook`` + watchdog): the replica
  leaves rotation on its OWN readiness 503 — whose body now carries the
  watchdog evidence — and re-enters through probation;
- induced ``kv_exhausted``: admission sheds 429 + Retry-After while
  every in-rotation replica is starved, never queueing unboundedly;
- graceful drain: in-flight requests finish, new ones shed, readiness
  flips 503.

These tests spawn several HTTP servers each; CI also runs this module
serially in the ``fleet-chaos`` step.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from gofr_tpu.fleet import parse_replicas
from gofr_tpu.fleet.admission import QuotaTable, TokenBucket, tenant_of
from gofr_tpu.fleet.breaker import CLOSED, HALF_OPEN, OPEN, PROBE, CircuitBreaker
from gofr_tpu.fleet.replica import affinity_order


# -- helpers -------------------------------------------------------------------

def _get(url, headers=None, timeout=10):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers.items())


def _post(url, payload, headers=None, timeout=10):
    send = {"Content-Type": "application/json"}
    send.update(headers or {})
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=send, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers.items())


def _wait(cond, timeout=10.0, interval=0.02, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


def _fleet_snapshot(app):
    return app.container.fleet.snapshot()


def _key_for(target: str, names: list) -> str:
    """An affinity key that rendezvous-routes to ``target``."""
    for i in range(1000):
        key = f"conv-{i}"
        if affinity_order(key, list(names))[0] == target:
            return key
    raise AssertionError(f"no key found mapping to {target}")


def _tokens_for(target: str, names: list) -> list:
    """A token-id prompt whose KV-hash rendezvous routes to ``target``
    (token prompts route by prompt hash, not by the session-key
    heuristics, unless the client pins a session explicitly)."""
    from gofr_tpu.fleet.kvwire import prompt_hash

    for i in range(1000):
        tokens = [i + 1, i + 2, i + 3]
        if affinity_order(prompt_hash(tokens), list(names))[0] == target:
            return tokens
    raise AssertionError(f"no tokens found mapping to {target}")


# -- unit: circuit breaker -----------------------------------------------------

def test_breaker_opens_half_opens_and_closes():
    transitions = []
    breaker = CircuitBreaker(
        failure_threshold=2, cooldown_s=0.1,
        on_transition=lambda was, to: transitions.append((was, to)),
    )
    assert breaker.state == CLOSED and breaker.try_acquire()
    breaker.record_failure()
    assert breaker.state == CLOSED  # one failure is not a trip
    breaker.record_failure()
    assert breaker.state == OPEN
    assert not breaker.try_acquire()  # cooldown running
    time.sleep(0.12)
    assert breaker.try_acquire() == PROBE  # the half-open probe slot
    assert breaker.state == HALF_OPEN
    assert not breaker.try_acquire()  # ONE probe at a time
    breaker.record_success(probe=True)
    assert breaker.state == CLOSED
    assert transitions == [
        (CLOSED, OPEN), (OPEN, HALF_OPEN), (HALF_OPEN, CLOSED)
    ]
    snap = breaker.snapshot()
    assert snap["state"] == CLOSED and snap["transitions"] == 3


def test_breaker_half_open_failure_reopens():
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=0.05)
    breaker.record_failure()
    assert breaker.state == OPEN
    time.sleep(0.06)
    assert breaker.try_acquire()
    breaker.record_failure()  # the probe failed
    assert breaker.state == OPEN
    assert not breaker.try_acquire()  # cooldown restarted
    assert "cooldown_remaining_s" in breaker.snapshot()


def test_breaker_half_open_race_admits_exactly_one_probe():
    """Two (here: eight) threads contending for the single half-open
    probe slot must admit EXACTLY one — the race is real: try_acquire
    reads the cooldown clock and claims the slot in one critical
    section, and a double grant would double-probe a replica that
    earned exactly one trial request."""
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=0.05)
    breaker.record_failure()
    assert breaker.state == OPEN
    time.sleep(0.06)  # cooldown passed: next acquire flips half-open

    n = 8
    barrier = threading.Barrier(n)
    grants: list = [None] * n

    def contend(i: int) -> None:
        barrier.wait()
        grants[i] = breaker.try_acquire()

    threads = [
        threading.Thread(target=contend, args=(i,), name=f"probe-race-{i}")
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
    assert sum(1 for g in grants if g == PROBE) == 1
    assert all(g is False for g in grants if g != PROBE)
    assert breaker.state == HALF_OPEN
    # the single winner reports success -> the breaker closes; the
    # losers' (refused) outcomes never touched the probe slot
    breaker.record_success(probe=True)
    assert breaker.state == CLOSED


def test_breaker_success_resets_failure_streak():
    breaker = CircuitBreaker(failure_threshold=2, cooldown_s=1)
    breaker.record_failure()
    breaker.record_success()
    breaker.record_failure()
    assert breaker.state == CLOSED  # streak broken: 1+1 is not 2


def test_breaker_open_ignores_stale_success():
    """A success from a request dispatched BEFORE the trip (or a long
    stream finishing minutes later) must not close an OPEN breaker —
    recovery goes through the half-open probe, always."""
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=10)
    breaker.record_failure()
    assert breaker.state == OPEN
    breaker.record_success()  # stale: pre-trip dispatch completing
    assert breaker.state == OPEN
    assert not breaker.try_acquire()  # cooldown still holds


def test_breaker_half_open_stale_success_does_not_preempt_probe():
    """While probe P runs, a stale non-probe success must not close the
    breaker on P's behalf — only the probe's own verdict counts."""
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=0.05)
    breaker.record_failure()
    time.sleep(0.06)
    assert breaker.try_acquire() == PROBE  # probe P in flight
    breaker.record_success()  # stale success from a pre-trip request
    assert breaker.state == HALF_OPEN  # P's outcome still pending
    breaker.record_success(probe=True)  # P reports
    assert breaker.state == CLOSED


# -- unit: admission -----------------------------------------------------------

def test_token_bucket_denies_with_refill_hint():
    bucket = TokenBucket(rate=10.0, capacity=2.0)
    assert bucket.take() == (True, 0.0)
    assert bucket.take()[0]
    ok, retry_after = bucket.take()
    assert not ok and 0 < retry_after <= 0.11
    time.sleep(retry_after + 0.02)
    assert bucket.take()[0]  # the hint was honest


def test_quota_table_per_tenant_and_disabled():
    # near-zero refill: at high rates the bucket regains a token within
    # the microseconds between takes and the deny assertion flakes
    table = QuotaTable(rate_rps=0.001, burst=1.0)
    assert table.take("a")[0]
    assert not table.take("a")[0]  # a's burst spent
    assert table.take("b")[0]     # b unaffected
    stats = table.stats()
    assert stats["tenants"] == 2 and stats["denied"] == 1
    off = QuotaTable(rate_rps=0.0, burst=0.0)
    assert all(off.take("x")[0] for _ in range(100))


def test_quota_table_redis_backend_and_fail_open():
    """The redis backing runs against the REAL client + miniredis —
    the pipelined HGET/HSET/EXPIRE path, fleet-wide bucket sharing
    across two QuotaTables (two router processes), and fail-open."""
    from gofr_tpu.datasource.miniredis import MiniRedis
    from gofr_tpu.datasource.redis import new_client
    from gofr_tpu.testutil import MockLogger

    mini = MiniRedis().run()
    client = new_client("127.0.0.1", mini.port, MockLogger())
    try:
        table = QuotaTable(rate_rps=0.001, burst=1.0, redis=client)
        assert table.take("t")[0]
        # a SECOND router process sees the same spent bucket
        sibling = QuotaTable(rate_rps=0.001, burst=1.0, redis=client)
        ok, retry_after = sibling.take("t")
        assert not ok and retry_after > 0
        assert table.stats()["backend"] == "redis"
        assert client.ttl("fleet:quota:t") > 0  # idle tenants expire
    finally:
        client.close()
        mini.close()

    class BrokenRedis:
        def pipeline(self):
            raise ConnectionError("redis down")

    logger = MockLogger()
    failing = QuotaTable(rate_rps=0.001, burst=1.0, redis=BrokenRedis(),
                         logger=logger)
    assert failing.take("t")[0]  # fail OPEN to the memory bucket
    assert not failing.take("t")[0]  # which still enforces
    assert "failing open" in logger.output


def test_tenant_of_header_then_auth_then_anonymous():
    from gofr_tpu.http.request import Request

    # X-Tenant is honored only when the operator opted in (a gateway
    # stamps it); trusted from arbitrary clients it would let anyone
    # mint a fresh quota bucket per request by randomizing the header
    trusted = Request("GET", "/", {"x-tenant": "acme"})
    assert tenant_of(trusted, trust_tenant_header=True) == "acme"
    assert tenant_of(trusted) == "anonymous"
    both = Request("GET", "/", {"x-tenant": "spoof",
                                "authorization": "Bearer sk-123"})
    assert tenant_of(both).startswith("key-")  # the KEY pays, not the header
    key_a = tenant_of(Request("GET", "/", {"authorization": "Bearer sk-123"}))
    key_b = tenant_of(Request("GET", "/", {"authorization": "Bearer sk-456"}))
    # API keys bucket stably but the tenant string (which lands in
    # route records, /admin/fleet, and redis keys) is a HASH — no
    # secret material may leak through the admin surface
    assert key_a.startswith("key-") and key_b.startswith("key-")
    assert key_a != key_b
    assert "sk-123" not in key_a
    assert key_a == tenant_of(
        Request("GET", "/", {"authorization": "Bearer sk-123"})
    )
    assert tenant_of(Request("GET", "/", {})) == "anonymous"


def test_router_sheds_do_not_charge_quota_tokens():
    """Router-side rejections (no replicas, draining, in-flight cap)
    must not burn the tenant's tokens — a tenant retrying politely
    through a saturation episode would otherwise arrive quota-blocked
    when capacity returns. A QUOTA shed in turn must release the
    in-flight slot it briefly held."""
    from gofr_tpu.fleet.replica import Replica, ReplicaSet
    from gofr_tpu.fleet.router import FleetRouter
    from gofr_tpu.http.request import Request
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger

    logger = MockLogger()
    quota = QuotaTable(rate_rps=1.0, burst=1.0)
    router = FleetRouter(
        logger, Registry(), ReplicaSet([], logger), quota,
    )
    request = Request("POST", "/generate", {"x-tenant": "acme"})
    verdict = router._admit(request, "acme")
    assert verdict is not None and verdict.status == 503  # no replicas
    router._draining = True
    verdict = router._admit(request, "acme")
    assert verdict is not None and verdict.status == 503  # draining
    stats = quota.stats()
    assert stats["admitted"] == 0 and stats["denied"] == 0  # untouched
    assert router.in_flight == 0  # no slot held across sheds

    # with a rotation: admitted requests HOLD the slot, quota denials
    # release it
    with_replica = FleetRouter(
        logger, Registry(),
        ReplicaSet([Replica("r0", "http://127.0.0.1:1", logger)], logger),
        QuotaTable(rate_rps=0.001, burst=1.0),
    )
    assert with_replica._admit(request, "acme") is None
    assert with_replica.in_flight == 1  # held for the forward
    with_replica._release()
    verdict = with_replica._admit(request, "acme")  # burst of 1 spent
    assert verdict is not None and verdict.status == 429
    assert with_replica.in_flight == 0  # quota shed released the slot

    # the in-flight cap itself is atomic check-and-increment
    capped = FleetRouter(
        logger, Registry(),
        ReplicaSet([Replica("r0", "http://127.0.0.1:1", logger)], logger),
        QuotaTable(rate_rps=0.0, burst=0.0),
        max_inflight=1,
    )
    assert capped._admit(request, "t") is None
    verdict = capped._admit(request, "t")
    assert verdict is not None and verdict.status == 429
    assert json.loads(verdict.body)["error"]["reason"] == "inflight"
    capped._release()
    assert capped._admit(request, "t") is None  # slot freed, admits again


# -- unit: affinity + replica spec ---------------------------------------------

def test_affinity_order_is_stable_under_membership_churn():
    names = ["r0", "r1", "r2", "r3"]
    for key in ("alice", "bob", "conv-42"):
        full = affinity_order(key, names)
        survivor_order = [n for n in full if n != full[0]]
        assert affinity_order(key, [n for n in names if n != full[0]]) == \
            survivor_order  # removing the holder only remaps ITS keys
    # keys spread: not everything lands on one replica
    firsts = {affinity_order(f"k{i}", names)[0] for i in range(32)}
    assert len(firsts) > 1


def test_parse_replicas_names_and_errors():
    assert parse_replicas("http://a:1,http://b:2") == [
        ("r0", "http://a:1"), ("r1", "http://b:2")
    ]
    assert parse_replicas("x=http://a:1, y=http://b:2 ,") == [
        ("x", "http://a:1"), ("y", "http://b:2")
    ]
    with pytest.raises(ValueError, match="twice"):
        parse_replicas("x=http://a:1,x=http://b:2")
    with pytest.raises(ValueError, match="no URL"):
        parse_replicas("x=")


# -- unit: resilient service client -------------------------------------------

def test_service_call_error_carries_elapsed_and_attempts():
    from gofr_tpu.service import HTTPService, ServiceCallError
    from gofr_tpu.testutil import MockLogger

    svc = HTTPService("http://127.0.0.1:1", MockLogger(), name="ghost",
                      connect_timeout=0.2, read_timeout=0.2)
    with pytest.raises(ServiceCallError) as excinfo:
        svc.request("GET", "/x", retries=2)
    err = excinfo.value
    assert err.attempts == 3
    assert err.elapsed_s > 0
    assert "3 attempt(s)" in str(err)


def test_service_client_retries_5xx_for_idempotent_only(free_port):
    import http.server

    port = free_port()
    hits = {"n": 0}

    class Flaky(http.server.BaseHTTPRequestHandler):
        def _serve(self):
            hits["n"] += 1
            status = 503 if hits["n"] < 3 else 200
            payload = b'{"ok": true}'
            self.send_response(status)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        do_GET = do_POST = _serve

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", port), Flaky)
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="test-flaky-http")
    thread.start()
    try:
        from gofr_tpu.service import HTTPService
        from gofr_tpu.testutil import MockLogger

        svc = HTTPService(f"http://127.0.0.1:{port}", MockLogger())
        resp = svc.request("GET", "/x", retries=3)
        assert resp.status_code == 200 and hits["n"] == 3
        hits["n"] = 0
        resp = svc.request("POST", "/x", retries=3)  # NOT idempotent
        assert resp.status_code == 503 and hits["n"] == 1
        hits["n"] = 0
        resp = svc.request("POST", "/x", retries=3, retryable=True)
        assert resp.status_code == 200 and hits["n"] == 3
    finally:
        srv.shutdown()
        thread.join(5)


def test_redirects_followed_for_safe_methods_only(free_port):
    """urlopen parity: GET follows Location hops; POST gets the 3xx
    raw (replaying a body across a redirect is the caller's call)."""
    import http.server

    port = free_port()

    class Redirecting(http.server.BaseHTTPRequestHandler):
        def _serve(self):
            if self.path == "/old":
                self.send_response(302)
                self.send_header("Location", "/new")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            payload = b'{"moved": true}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        do_GET = do_POST = _serve

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", port), Redirecting)
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="test-redirect-http")
    thread.start()
    try:
        from gofr_tpu.service import HTTPService
        from gofr_tpu.testutil import MockLogger

        svc = HTTPService(f"http://127.0.0.1:{port}", MockLogger())
        resp = svc.get("/old")
        assert resp.status_code == 200 and resp.json() == {"moved": True}
        resp = svc.post("/old", body={"x": 1})
        assert resp.status_code == 302  # returned raw, not replayed
    finally:
        srv.shutdown()
        thread.join(5)


def test_drip_fed_body_cannot_outlive_the_read_budget(free_port):
    """Socket timeouts are per-recv: an upstream dripping one byte per
    interval would reset the clock forever and pin a router handler
    thread. The buffered read is bounded by a TOTAL read_timeout."""
    import socket as socket_mod

    port = free_port()
    stop = threading.Event()
    server = socket_mod.socket()
    server.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", port))
    server.listen(1)

    def drip():
        conn, _ = server.accept()
        try:
            conn.recv(65536)
            conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 1000000\r\n\r\n")
            while not stop.wait(0.05):  # one byte per 50ms, forever
                conn.sendall(b"x")
        except OSError:
            pass
        finally:
            conn.close()

    thread = threading.Thread(target=drip, name="test-drip-server")
    thread.start()
    try:
        from gofr_tpu.service import HTTPService, ServiceCallError
        from gofr_tpu.testutil import MockLogger

        svc = HTTPService(f"http://127.0.0.1:{port}", MockLogger())
        start = time.monotonic()
        with pytest.raises(ServiceCallError):
            svc.request("GET", "/x", read_timeout=0.4, retries=0)
        assert time.monotonic() - start < 3.0  # bounded, not forever
    finally:
        stop.set()
        server.close()
        thread.join(5)


def test_backoff_delays_decorrelate_and_cap():
    from gofr_tpu.service import backoff_delays

    delays = list(backoff_delays(50, base=0.01, cap=0.2))
    assert len(delays) == 50
    assert all(0.01 <= d <= 0.2 for d in delays)
    assert len(set(delays)) > 10  # jittered, not a fixed ladder


# -- e2e: routing, retry, breaker ---------------------------------------------

def _completion(base, prompt, headers=None, stream=False, max_tokens=4,
                timeout=15):
    payload = {"model": "echo", "prompt": prompt, "max_tokens": max_tokens}
    if stream:
        payload["stream"] = True
    return _post(base + "/v1/completions", payload, headers=headers,
                 timeout=timeout)


def test_wedged_replica_mid_request_retries_to_healthy(tmp_path, monkeypatch):
    """The acceptance spine: one of three replicas force-wedged while
    serving; the client request still completes, and the wedged
    replica's breaker opens within its threshold."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(3) as replicas, chaos_router(
        replicas,
        # read timeout must be comfortably above a healthy echo
        # completion on a LOADED runner (0.4s raced real decode work
        # and flaked) while staying far below the 30s chaos stall
        env={"FLEET_READ_TIMEOUT_S": "2", "FLEET_BREAKER_THRESHOLD": "1",
             "FLEET_BREAKER_COOLDOWN_S": "30", "FLEET_PROBE_INTERVAL_S": "30"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 3,
              message="3 replicas in rotation")
        names = [r.name for r in fleet.replica_set.replicas]
        target = "r1"
        key = _key_for(target, names)
        victim = next(r for r in replicas if r.name == target)
        victim.chaos.stall(30.0)  # wedged: accepts, never answers

        # non-stream: first attempt times out on r1, retry completes
        status, body, _ = _completion(
            base, [5, 6, 7], headers={"X-Session-ID": key}
        )
        assert status == 200
        payload = json.loads(body)
        assert payload["choices"]  # a full completion from a HEALTHY replica
        assert payload["usage"]["completion_tokens"] == 4
        snap = _fleet_snapshot(app)
        route = snap["routes"][0]
        assert route["retries"] >= 1
        assert route["attempts"][0]["replica"] == target
        assert route["attempts"][0]["error"]
        assert route["attempts"][-1]["status"] == 200
        assert route["attempts"][-1]["replica"] != target
        by_name = {r["name"]: r for r in snap["replica_set"]["replicas"]}
        assert by_name[target]["breaker"]["state"] == "open"  # threshold 1

        # streaming, not-yet-streamed: r1 would stall before the response
        # head, so the router may still fail over; the SSE completes
        victim2 = next(r for r in replicas if r.name != target)
        key2 = _key_for(victim2.name, names)
        victim2.chaos.stall(30.0)
        status, body, headers = _completion(
            base, [1, 2, 3], headers={"X-Session-ID": key2}, stream=True
        )
        assert status == 200
        assert headers["Content-Type"].startswith("text/event-stream")
        assert b"data: [DONE]" in body  # the stream COMPLETED elsewhere

        # router metrics observed the retries
        _, metrics_body, _ = _get(base + "/metrics")
        text = metrics_body.decode()
        assert "gofr_tpu_router_retries_total" in text
        assert 'gofr_tpu_router_breaker_state{replica="' + target + '"} 2' \
            in text


def test_connection_refused_breaker_cycle(tmp_path, monkeypatch):
    """Listener killed: requests retry elsewhere (clients never see the
    failure), the breaker opens after its threshold, half-opens after
    the cooldown, and closes once the listener returns."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(3) as replicas, chaos_router(
        replicas,
        env={"FLEET_BREAKER_THRESHOLD": "2",
             "FLEET_BREAKER_COOLDOWN_S": "0.2",
             "FLEET_PROBE_INTERVAL_S": "30"},  # rotation state frozen
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 3,
              message="3 replicas in rotation")
        names = [r.name for r in fleet.replica_set.replicas]
        dead = replicas[0]
        dead.stop_listener()  # connection refused from here on

        def breaker_state():
            return fleet.replica_set.by_name(dead.name).breaker.state

        # drive requests until the breaker trips; the prompt's KV-hash
        # rendezvous pins the dead replica first in every pick, so each
        # request charges its breaker deterministically
        tokens = _tokens_for(dead.name, names)
        for _ in range(8):
            status, _, _ = _post(base + "/generate", {"tokens": tokens})
            assert status == 200  # the CLIENT never sees the dead replica
            if breaker_state() == "open":
                break
        assert breaker_state() == "open"

        dead.start_listener()
        time.sleep(0.25)  # past the cooldown: next pick half-opens
        for _ in range(8):
            status, _, _ = _post(base + "/generate", {"tokens": tokens})
            assert status == 200
            if breaker_state() == "closed":
                break
        assert breaker_state() == "closed", \
            "breaker must close after recovery probe"
        snap = _fleet_snapshot(app)
        by_name = {r["name"]: r for r in snap["replica_set"]["replicas"]}
        assert by_name[dead.name]["breaker"]["transitions"] >= 3
        _, metrics_body, _ = _get(base + "/metrics")
        assert 'gofr_tpu_router_breaker_transitions_total{replica="' \
            + dead.name + '",to="open"}' in metrics_body.decode()


def test_device_wedge_leaves_rotation_and_probation_reentry(
        tmp_path, monkeypatch):
    """A REAL engine wedge (echo stall_hook + watchdog): the replica's
    own readiness 503s — with the watchdog evidence in the body — the
    prober takes it out of rotation, and recovery walks probation
    before traffic returns. RECOVERY_ENABLED=off on purpose: this test
    pins the legacy stall-resolves-itself path (the watchdog's own
    recovery transition); the supervisor-driven rebuild has its own
    e2e (test_recovery.py + the resume e2e below)."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2, env={"RECOVERY_ENABLED": "off"}) as replicas, chaos_router(
        replicas,
        env={"FLEET_PROBE_INTERVAL_S": "0.05", "FLEET_OUT_AFTER": "1",
             "FLEET_PROBATION_PROBES": "3"},
    ) as app:
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="both replicas in rotation")
        victim = replicas[0]
        victim.wedge(1.2)  # next dispatch stalls 1.2s; watchdog is 0.2s

        def kick():
            try:
                _post(victim.address + "/generate",
                      {"tokens": [9], "max_new_tokens": 2}, timeout=15)
            except Exception:
                pass

        kicker = threading.Thread(target=kick, name="test-wedge-kick")
        kicker.start()
        try:
            _wait(lambda: fleet.replica_set.by_name(victim.name).state
                  == "out", timeout=15, message="wedged replica out")
            # the replica's OWN ready body explains why (satellite:
            # engine state + watchdog reason in the 503 body) — read it
            # BEFORE recover(): releasing the latch un-stalls the
            # dispatch and the watchdog flips the engine back instantly
            try:
                _get(victim.address + "/.well-known/ready", timeout=5)
                raise AssertionError("expected 503 while wedged")
            except urllib.error.HTTPError as exc:
                payload = json.loads(exc.read())
                assert payload["state"] in ("degraded", "wedged")
                assert payload["detail"]
                assert "watchdog" in payload
                assert payload["watchdog"]["stalls"]
            victim.recover()  # the paired heal control: stall releases NOW
            # traffic avoids the wedged replica meanwhile
            base = f"http://127.0.0.1:{app.http_port}"
            status, _, _ = _post(base + "/generate", {"tokens": [1]})
            assert status == 200
            snap = _fleet_snapshot(app)
            served = {a["replica"] for r in snap["routes"]
                      for a in r["attempts"] if a.get("status") == 200}
            assert victim.name not in served
        finally:
            kicker.join(20)
        # recovery: the stall ends, the engine recovers, and the replica
        # must string together FLEET_PROBATION_PROBES ok probes
        _wait(lambda: fleet.replica_set.by_name(victim.name).state
              == "healthy", timeout=20, message="probation re-entry")
        assert fleet.replica_set.by_name(victim.name).probes >= 3


# -- e2e: admission control ----------------------------------------------------

def test_kv_exhausted_sheds_429_with_retry_after(tmp_path, monkeypatch):
    """Induced kv_exhausted: a long generation pins EVERY paged-KV
    block of the only replica, the next request is rejected by the pool
    (it still completes via the solo fallback — the reject is a signal,
    not a failure), the prober picks the rising reject counter up, and
    the router sheds subsequent work with 429 + Retry-After instead of
    queueing unboundedly."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(
        1,
        env={"KV_BLOCKS": "64", "KV_BLOCK_TOKENS": "2",
             "ECHO_STEP_MS": "30", "WATCHDOG_DISPATCH_TIMEOUT_S": "off"},
    ) as replicas, chaos_router(
        replicas, env={"FLEET_PROBE_INTERVAL_S": "0.05"}
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        replica = fleet.replica_set.replicas[0]
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")
        _wait(lambda: replica.engine is not None, message="engine scraped")
        # 28 prompt + 100 new tokens = 128 tokens = ALL 64 blocks of 2,
        # held for ~3s of step-delayed decoding
        hog = threading.Thread(
            target=lambda: _post(
                base + "/generate",
                {"tokens": list(range(1, 29)), "max_new_tokens": 100},
                timeout=30,
            ),
            name="test-kv-hog",
        )
        hog.start()
        try:
            _wait(
                lambda: (replica.engine or {}).get("kv_free") == 0,
                timeout=10, message="hog pinned every block",
            )
            # the canary is REJECTED by the pool (kv_exhausted) but the
            # request itself still completes — solo fallback
            status, _, _ = _post(base + "/generate",
                                 {"tokens": [1, 2], "max_new_tokens": 2},
                                 timeout=10)
            assert status == 200
            _wait(lambda: replica.saturated, timeout=10,
                  message="prober sees the kv_exhausted rejects")
            assert (replica.engine or {}).get("kv_exhausted_rejects", 0) >= 1
            try:
                _post(base + "/generate", {"tokens": [1, 2]}, timeout=5)
                raise AssertionError("expected 429 while saturated")
            except urllib.error.HTTPError as exc:
                assert exc.code == 429
                assert int(exc.headers["Retry-After"]) >= 1
                payload = json.loads(exc.read())
                assert payload["error"]["reason"] == "kv_exhausted"
            counter = app.container.metrics.counter(
                "gofr_tpu_router_shed_total", labels=("reason",)
            )
            assert counter.value(reason="kv_exhausted") >= 1
            snap = _fleet_snapshot(app)
            assert any(r["outcome"] == "shed:kv_exhausted"
                       for r in snap["routes"])
        finally:
            hog.join(30)
        # blocks free as the hog finishes: admission recovers
        _wait(lambda: not replica.saturated,
              timeout=10, message="saturation clears")
        status, _, _ = _post(base + "/generate", {"tokens": [1, 2]})
        assert status == 200


def test_quota_sheds_429_per_tenant(tmp_path, monkeypatch):
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(1) as replicas, chaos_router(
        replicas,
        env={"FLEET_QUOTA_RPS": "0.5", "FLEET_QUOTA_BURST": "2",
             "FLEET_TRUST_TENANT_HEADER": "on"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        _wait(lambda: len(app.container.fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")
        acme = {"X-Tenant": "acme"}
        for _ in range(2):
            status, _, _ = _post(base + "/generate", {"tokens": [1]},
                                 headers=acme)
            assert status == 200
        try:
            _post(base + "/generate", {"tokens": [1]}, headers=acme)
            raise AssertionError("expected 429 over quota")
        except urllib.error.HTTPError as exc:
            assert exc.code == 429
            assert "Retry-After" in exc.headers
            assert json.loads(exc.read())["error"]["reason"] == "quota"
        # another tenant is unaffected
        status, _, _ = _post(base + "/generate", {"tokens": [1]},
                             headers={"X-Tenant": "other"})
        assert status == 200


def test_upstream_429_burst_echoes_with_retry_after(tmp_path, monkeypatch):
    """A replica answering 429 (its own admission) is echoed upstream
    verbatim with a Retry-After — the router never retry-storms an
    overloaded replica."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(1) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        _wait(lambda: len(app.container.fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")
        replicas[0].chaos.error_burst(1, status=429,
                                      paths=("/generate",))
        try:
            _post(base + "/generate", {"tokens": [1]})
            raise AssertionError("expected 429 echoed")
        except urllib.error.HTTPError as exc:
            assert exc.code == 429
            assert "Retry-After" in exc.headers
        assert replicas[0].chaos.injected.get("error_burst") == 1  # ONE try


def test_5xx_burst_retries_and_mid_stream_disconnect_aborts(
        tmp_path, monkeypatch):
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="replicas in rotation")
        # 5xx burst on both replicas: first two attempts eat the bursts,
        # retry completes on whichever recovered first
        for replica in replicas:
            replica.chaos.error_burst(1, status=503, paths=("/generate",))
        status, _, _ = _post(base + "/generate", {"tokens": [4, 5]})
        assert status == 200
        snap = _fleet_snapshot(app)
        assert snap["routes"][0]["retries"] >= 1

        # mid-stream disconnect: chunks flowed, so NO replay — the
        # router aborts the client connection (truncated body)
        names = [r.name for r in fleet.replica_set.replicas]
        key = _key_for(replicas[0].name, names)
        replicas[0].chaos.disconnect_after(1, paths=("/v1/",))
        with pytest.raises(Exception) as excinfo:
            _completion(base, [1, 2, 3], headers={"X-Session-ID": key},
                        stream=True, max_tokens=8)
        assert not isinstance(excinfo.value, urllib.error.HTTPError) or \
            excinfo.value.code >= 500
        _wait(
            lambda: any(r["outcome"] == "aborted"
                        for r in _fleet_snapshot(app)["routes"]),
            timeout=5, message="aborted route record",
        )


# -- e2e: self-healing engine + resumable streams (ISSUE 9 acceptance) ---------

def _read_sse_tokens(resp, initial: bytes = b"") -> tuple:
    """Drain one SSE response: returns (token_ids, event_ids, raw)."""
    raw = initial
    while True:
        chunk = resp.read(4096)
        if not chunk:
            break
        raw += chunk
    tokens: list = []
    ids: list = []
    for block in raw.split(b"\n\n"):
        event_id = None
        for line in block.split(b"\n"):
            if line.startswith(b"id:"):
                event_id = int(line[3:].strip())
            elif line.startswith(b"data:"):
                data = line[5:].strip()
                if data == b"[DONE]" or not data.startswith(b"{"):
                    continue
                frame = json.loads(data)
                if "error" in frame:
                    raise AssertionError(f"error frame reached client: {frame}")
                choice = frame["choices"][0]
                if choice.get("tokens"):
                    tokens.extend(choice["tokens"])
                    if event_id is not None:
                        ids.append(event_id)
    return tokens, ids, raw


def test_wedge_mid_stream_recovers_and_resumes_bit_identical(
        tmp_path, monkeypatch):
    """THE acceptance spine of the self-healing engine: a seeded SSE
    stream is interrupted by a REAL device wedge (echo stall_hook +
    watchdog); the recovery supervisor rebuilds the engine back to
    serving WITHOUT a process restart; the router's stream relay
    resumes from the journaled offset — and the client's stream
    completes with zero missing and zero duplicated tokens, asserted
    bit-identical against the uninterrupted expectation. Recovery is
    visible on gofr_tpu_engine_recoveries_total and /admin/engine."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    prompt, n_tokens = [5, 6, 7], 40
    expected = [prompt[i % 3] for i in range(n_tokens)]  # echo's contract
    with chaos_fleet(1, env={"ECHO_STEP_MS": "40"}) as replicas, chaos_router(
        replicas,
        env={"FLEET_PROBE_INTERVAL_S": "0.05", "FLEET_OUT_AFTER": "2",
             "FLEET_PROBATION_PROBES": "2", "FLEET_READ_TIMEOUT_S": "5",
             "FLEET_DEADLINE_S": "30"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        victim = replicas[0]
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")

        req = urllib.request.Request(
            base + "/v1/completions",
            data=json.dumps({
                "model": "echo", "prompt": prompt, "max_tokens": n_tokens,
                "stream": True, "seed": 7,
            }).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200

        # let a few tokens flow, then wedge the device mid-stream: the
        # latch holds until recover(); a sacrificial direct request
        # carries the stall into a watched dispatch
        first = resp.read(1)  # at least one byte of the stream arrived
        assert first
        victim.wedge()

        def kick():
            try:
                _post(victim.address + "/generate",
                      {"tokens": [9], "max_new_tokens": 2}, timeout=30)
            except Exception:
                pass  # the wedged dispatch fails by design

        kicker = threading.Thread(target=kick, name="test-wedge-kick")
        kicker.start()
        try:
            # the client keeps reading through wedge -> recovery ->
            # resume; the relay splices the continuation in
            tokens, ids, raw = _read_sse_tokens(resp, initial=first)
        finally:
            victim.recover()
            kicker.join(20)
        assert raw  # the stream carried data after the wedge
        assert b"data: [DONE]" in raw  # completed, not truncated

        # ZERO missing, ZERO duplicated: bit-identical to uninterrupted
        assert tokens == expected
        assert ids == sorted(set(ids))  # strictly monotonic event ids

        # the engine RECOVERED (no process restart): counter + admin
        status, body, _ = _get(victim.address + "/admin/engine")
        engine = json.loads(body)["data"]
        assert engine["engine"]["state"] == "serving"
        assert engine["recovery"]["recoveries"].get("recovered", 0) >= 1
        assert engine["recovery"]["last_mttr_s"] is not None
        states = [h["state"] for h in engine["engine"]["history"]]
        assert "recovering" in states and "wedged" in states
        _, metrics_body, _ = _get(victim.address + "/metrics")
        assert ('gofr_tpu_engine_recoveries_total{outcome="recovered"}'
                in metrics_body.decode())

        # the router saw (and journaled) the resume
        snap = _fleet_snapshot(app)
        resumed_routes = [r for r in snap["routes"] if r.get("resumes")]
        assert resumed_routes, snap["routes"]
        _, router_metrics, _ = _get(base + "/metrics")
        assert ('gofr_tpu_router_stream_resumes_total{outcome="resumed"}'
                in router_metrics.decode())


# -- e2e: graceful drain -------------------------------------------------------

def test_sigterm_drain_finishes_inflight_then_sheds(tmp_path, monkeypatch):
    """App.shutdown (the SIGTERM path) drains: the in-flight request
    completes through the still-open listener, new requests shed, and
    readiness flips to a draining 503."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    fleet_ctx = chaos_fleet(2, env={"ECHO_STEP_MS": "25"})
    replicas = fleet_ctx.__enter__()
    try:
        router_ctx = chaos_router(
            replicas, env={"FLEET_DRAIN_TIMEOUT_S": "20"}
        )
        app = router_ctx.__enter__()
        shutdown_done = False
        try:
            base = f"http://127.0.0.1:{app.http_port}"
            fleet = app.container.fleet
            _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
                  message="replicas in rotation")
            slow_result = {}

            def slow():
                # ~100 tokens x 25ms ≈ 2.5s of decoding
                slow_result["resp"] = _post(
                    base + "/generate",
                    {"tokens": [1, 2, 3], "max_new_tokens": 100},
                    timeout=30,
                )

            worker = threading.Thread(target=slow, name="test-drain-slow")
            worker.start()
            _wait(lambda: fleet.in_flight >= 1, message="request in flight")

            shutdown_thread = threading.Thread(
                target=app.shutdown, name="test-drain-shutdown"
            )
            shutdown_thread.start()
            _wait(lambda: fleet.draining, message="drain began")
            # while draining with work in flight the listener is still
            # up: new work is SHED and readiness says why
            assert fleet.in_flight >= 1
            try:
                _post(base + "/generate", {"tokens": [7]}, timeout=5)
                raise AssertionError("expected 503 while draining")
            except urllib.error.HTTPError as exc:
                assert exc.code == 503
                assert json.loads(exc.read())["error"]["reason"] == "draining"
            try:
                _get(base + "/.well-known/ready", timeout=5)
                raise AssertionError("expected ready 503 while draining")
            except urllib.error.HTTPError as exc:
                assert exc.code == 503
                assert json.loads(exc.read())["state"] == "draining"

            shutdown_thread.join(30)
            assert not shutdown_thread.is_alive()
            shutdown_done = True
            worker.join(10)
            # the in-flight request FINISHED before the listener died
            status, body, _ = slow_result["resp"]
            assert status == 200
            assert json.loads(body)["data"]["count"] == 100
            counter = app.container.metrics.counter(
                "gofr_tpu_router_shed_total", labels=("reason",)
            )
            assert counter.value(reason="draining") >= 1
        finally:
            if not shutdown_done:
                router_ctx.__exit__(None, None, None)
            else:
                # already shut down; just unwind the contextmanager
                try:
                    router_ctx.__exit__(None, None, None)
                except Exception:
                    pass
    finally:
        fleet_ctx.__exit__(None, None, None)


# -- e2e: affinity -------------------------------------------------------------

def test_affinity_pins_conversation_to_one_replica(tmp_path, monkeypatch):
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(3) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 3,
              message="replicas in rotation")
        headers = {"X-Session-ID": "conversation-7"}
        for _ in range(5):
            status, _, _ = _post(base + "/generate", {"tokens": [1, 2]},
                                 headers=headers)
            assert status == 200
        from gofr_tpu.fleet.router import hash_affinity

        snap = _fleet_snapshot(app)
        # records carry the HASHED key (raw keys can be prompt text)
        pinned = {r["attempts"][0]["replica"] for r in snap["routes"]
                  if r.get("affinity_key") == hash_affinity("conversation-7")}
        assert len(pinned) == 1  # every turn landed on ONE replica
        assert not any(r.get("affinity_key") == "conversation-7"
                       for r in snap["routes"])
        # and the SAME prompt routes by its own prefix without a header
        for _ in range(3):
            _completion(base, [9, 9, 9, 9])
        snap = _fleet_snapshot(app)
        by_prompt = {r["attempts"][0]["replica"] for r in snap["routes"]
                     if r["path"] == "/v1/completions"}
        assert len(by_prompt) == 1


def test_fleet_replicas_on_host_mesh(tmp_path, monkeypatch):
    """Echo replicas booted on TPU_MESH=tp=2 (host-mesh mode: paged
    block tables sharded over 2 fake devices) serve through the router
    exactly like unsharded ones, and each replica's /admin/engine
    exposes the mesh it runs on — fleet and mesh compose compile-free."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    mesh_env = {"TPU_MESH": "tp=2", "KV_BLOCKS": "64",
                "KV_BLOCK_TOKENS": "4"}
    with chaos_fleet(2, env=mesh_env) as replicas, chaos_router(
        replicas, env={"FLEET_PROBE_INTERVAL_S": "0.1"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="2 mesh replicas in rotation")
        status, body, _ = _completion(base, [5, 6, 7, 8])
        assert status == 200
        # id-prompt on the tokenizer-less echo runner: tokens came back
        # (text stays empty without a tokenizer — the count is the proof)
        assert json.loads(body)["usage"]["completion_tokens"] == 4
        for r in replicas:
            rstatus, engine, _ = _get(
                f"http://127.0.0.1:{r.port}/admin/engine"
            )
            assert rstatus == 200
            data = json.loads(engine)["data"]
            assert data["mesh"] == {"axes": {"tp": 2}, "devices": 2}
            assert data["kv_blocks"]["total"] == 64


# -- unit: disaggregated role routing (ISSUE 11) -------------------------------

def _role_set(roles, logger=None):
    """A ReplicaSet of named replicas with fixed roles, all healthy, no
    prober traffic (probe thread is started by start(), never called)."""
    from gofr_tpu.fleet.replica import Replica, ReplicaSet
    from gofr_tpu.testutil import MockLogger

    logger = logger or MockLogger()
    replicas = []
    for i, role in enumerate(roles):
        replica = Replica(f"r{i}", f"http://127.0.0.1:{20000 + i}", logger)
        replica.role = role
        replicas.append(replica)
    return ReplicaSet(replicas, logger)


def test_candidates_role_tier_includes_mixed_and_empty_tier_is_empty():
    rs = _role_set(["prefill", "decode", "mixed"])
    assert {r.name for r in rs.candidates(role="decode")} == {"r1", "r2"}
    assert {r.name for r in rs.candidates(role="prefill")} == {"r0", "r2"}
    assert {r.name for r in rs.candidates()} == {"r0", "r1", "r2"}
    # an empty tier returns [] — the CALLER degrades, candidates never
    # invents capacity
    only_prefill = _role_set(["prefill", "prefill"])
    assert only_prefill.candidates(role="decode") == []
    # roles compose with exclusion
    assert {r.name for r in rs.candidates(role="decode", exclude={"r1"})} \
        == {"r2"}


def test_classify_role_and_kv_hash_of():
    from gofr_tpu.fleet.kvwire import prompt_hash
    from gofr_tpu.fleet.router import FleetRouter

    classify = FleetRouter._classify_role
    assert classify("/v1/completions") == "decode"
    assert classify("/v1/chat/completions") == "decode"
    assert classify("/generate") == "decode"
    assert classify("/v1/embeddings") == "prefill"
    assert classify("/infer") == "prefill"
    assert classify("/v1/models") is None

    kv_hash = FleetRouter._kv_hash_of
    assert kv_hash({"tokens": [1, 2, 3]}) == prompt_hash([1, 2, 3])
    assert kv_hash({"prompt": [4, 5]}) == prompt_hash([4, 5])
    # text prompts tokenize replica-side: no router-side identity
    assert kv_hash({"prompt": "hello"}) == ""
    assert kv_hash({"prompt": [1, True, 3]}) == ""  # bools are not ids
    assert kv_hash({"prompt": []}) == ""
    assert kv_hash(None) == ""


def test_pick_degrades_from_empty_and_vetoed_tiers():
    """Role config can never make the fleet serve less: an empty tier
    AND a tier whose breakers all veto both fall through to role-free
    selection; only a fleet with nothing admittable returns None."""
    from gofr_tpu.fleet.router import FleetRouter
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger

    logger = MockLogger()
    rs = _role_set(["prefill", "decode", "decode"], logger=logger)
    router = FleetRouter(logger, Registry(), rs, QuotaTable(0.0, 0.0))

    picked, _ = router._pick("", set(), role="decode")
    assert picked.role == "decode"  # the tier is preferred when alive

    # every decode breaker open: the prefill replica must still serve
    for name in ("r1", "r2"):
        breaker = rs.by_name(name).breaker
        for _ in range(breaker.failure_threshold):
            breaker.record_failure()
    picked, _ = router._pick("", set(), role="decode")
    assert picked.name == "r0"  # degraded to role-free, not to a 502

    # empty tier (no decode/mixed at all): same degradation
    prefill_only = _role_set(["prefill", "prefill"], logger=logger)
    router2 = FleetRouter(logger, Registry(), prefill_only,
                          QuotaTable(0.0, 0.0))
    picked, _ = router2._pick("", set(), role="decode")
    assert picked.role == "prefill"

    # nothing admittable anywhere: None (the caller 502s/retries)
    for replica in prefill_only.replicas:
        for _ in range(replica.breaker.failure_threshold):
            replica.breaker.record_failure()
    assert router2._pick("", set(), role="decode") is None


def test_kv_donor_picks_the_prefill_replica_by_rendezvous():
    from gofr_tpu.fleet.kvwire import prompt_hash
    from gofr_tpu.fleet.router import FleetRouter
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger

    logger = MockLogger()
    rs = _role_set(["prefill", "prefill", "decode"], logger=logger)
    router = FleetRouter(logger, Registry(), rs, QuotaTable(0.0, 0.0))
    kv_hash = prompt_hash([7, 8, 9])
    donor = router._kv_donor(kv_hash)
    assert donor is not None and donor.role == "prefill"
    # deterministic: rendezvous on the hash over the prefill tier only
    expected = affinity_order(kv_hash, ["r0", "r1"])[0]
    assert donor.name == expected
    assert router._kv_donor("") is None
    # a mixed/decode-only fleet has no dedicated donors
    no_prefill = _role_set(["mixed", "decode"], logger=logger)
    router2 = FleetRouter(logger, Registry(), no_prefill,
                          QuotaTable(0.0, 0.0))
    assert router2._kv_donor(kv_hash) is None


def test_explicit_session_key_outranks_kv_hash_affinity():
    """KV-hash rendezvous replaces the prompt-head HEURISTIC only; a
    client that pinned a session keeps its pin."""
    from gofr_tpu.fleet.router import FleetRouter
    from gofr_tpu.http.request import Request

    body = {"tokens": [1, 2, 3]}
    assert not FleetRouter._explicit_affinity(
        Request("POST", "/generate", {}), body)
    assert FleetRouter._explicit_affinity(
        Request("POST", "/generate", {"x-session-id": "conv"}), body)
    assert FleetRouter._explicit_affinity(
        Request("POST", "/generate", {"x-affinity-key": "k"}), body)
    assert FleetRouter._explicit_affinity(
        Request("POST", "/generate", {}), {"user": "alice"})


# -- unit: quota redis outage-window observability -----------------------------

class _FlakyRedis:
    """A chainable pipeline stub with a kill switch — deterministic
    outage windows without racing a real miniredis teardown."""

    def __init__(self):
        self.down = False

    def pipeline(self):
        if self.down:
            raise ConnectionError("redis down")
        return self

    def hget(self, *a):
        return self

    def hset(self, *a):
        return self

    def expire(self, *a):
        return self

    def execute(self):
        if self.down:
            raise ConnectionError("redis down")
        return [None, None]


def test_quota_fail_open_counts_fallbacks_and_logs_once_per_outage():
    """A silent redis outage must be VISIBLE: every fail-open take
    counts on gofr_tpu_router_quota_fallback_total (and the stats
    block), while the log gets ONE line per outage window — not one per
    request — and recovery re-arms the next window's line."""
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger

    logger = MockLogger()
    registry = Registry()
    redis = _FlakyRedis()
    table = QuotaTable(rate_rps=100.0, burst=10.0, redis=redis,
                       logger=logger, metrics=registry)
    counter = registry.counter("gofr_tpu_router_quota_fallback_total")
    assert table.take("t")[0] and counter.value() == 0
    assert not table.stats()["redis_down"]

    redis.down = True
    for _ in range(5):
        assert table.take("t")[0]  # fail-open: still admitted
    assert counter.value() == 5
    stats = table.stats()
    assert stats["redis_down"] and stats["fallbacks"] == 5
    assert stats["backend"] == "redis"
    failed_lines = [ln for ln in logger.lines if "failed" in ln]
    assert len(failed_lines) == 1  # once per window, not per request

    redis.down = False
    assert table.take("t")[0]
    assert counter.value() == 5  # recovery takes are not fallbacks
    assert not table.stats()["redis_down"]
    assert any("recovered" in ln for ln in logger.lines)

    # a SECOND outage logs its own first-failure line
    redis.down = True
    assert table.take("t")[0]
    failed_lines = [ln for ln in logger.lines if "failed" in ln]
    assert len(failed_lines) == 2


# -- e2e: disaggregated prefill/decode (ISSUE 11 acceptance) -------------------

def test_disagg_fleet_corrupt_and_dead_donor_streams_bit_identical(
    tmp_path, monkeypatch
):
    """The acceptance spine: a 1-prefill/2-decode echo fleet behind the
    router. Decode-bound streams carry an X-KV-Donor stamp naming the
    prefill replica; corrupting a KV payload mid-pull AND killing the
    donor mid-pull both yield a COMPLETED, bit-identical client stream
    via local-prefill fallback, every outcome lands on
    gofr_tpu_kv_transfer_total and /admin/fleet, and no BlockPool
    refcount leaks anywhere (all pools balance back to idle)."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(3, per_replica_env=[
        {"FLEET_ROLE": "prefill"},
        {"FLEET_ROLE": "decode"},
        {"FLEET_ROLE": "decode"},
    ], env={"KV_TRANSFER_TIMEOUT_S": "1"}) as replicas, chaos_router(
        replicas,
        # rotation state frozen after the initial probe: the donor must
        # stay "healthy" in the router's view even once its listener is
        # killed, so the hint keeps getting stamped and the RECEIVER's
        # pull (not the prober) discovers the death
        env={"FLEET_PROBE_INTERVAL_S": "30"},
    ) as app:
        donor = replicas[0]
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 3,
              message="3 replicas in rotation")
        # roles ride the /admin/engine scrape, which lands AFTER the
        # rotation entry the _wait above observed (same probe thread,
        # separate HTTP request) — so wait for every replica's role
        _wait(lambda: [fleet.replica_set.by_name(n).role
                       for n in ("r0", "r1", "r2")]
              == ["prefill", "decode", "decode"],
              message="advertised roles scraped")

        def stream_tokens(prompt, base_url=None):
            payload = {"model": "echo", "prompt": prompt, "max_tokens": 6,
                       "stream": True}
            req = urllib.request.Request(
                (base_url or base) + "/v1/completions",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=20) as resp:
                assert resp.status == 200
                tokens, _, raw = _read_sse_tokens(resp)
            assert b"data: [DONE]" in raw  # completed, never truncated
            assert len(tokens) == 6
            return tokens

        def donor_stream(prompt):
            """Clean reference + donor warm-up in one: the donor serves
            (and caches) the prompt itself; echo decoding is
            deterministic across replicas, so its token stream is the
            bit-identity baseline for the fallback streams below."""
            return stream_tokens(prompt, base_url=donor.address)

        def xfer_totals():
            out: dict = {}
            for r in replicas[1:]:
                _, body, _ = _get(f"{r.address}/admin/engine")
                for k, v in json.loads(body)["data"]["kv_transfer"].items():
                    if isinstance(v, int):
                        out[k] = out.get(k, 0) + v
            return out

        # scenario 0, the happy path: a donor-warmed prompt streamed
        # through the router is pulled from the donor (outcome ok)
        prompt0 = list(range(1, 40))
        clean0 = donor_stream(prompt0)
        assert stream_tokens(prompt0) == clean0
        assert xfer_totals()["ok"] >= 1  # the donor stamp was honored

        # scenario 1: payload corrupted mid-pull -> per-block CRC
        # catches it, local-prefill fallback, bit-identical stream.
        # Fresh prompt: the serving decode replica must actually PULL
        # (a locally-warm prompt skips the transfer entirely).
        prompt1 = list(range(100, 140))
        clean1 = donor_stream(prompt1)
        donor.chaos.corrupting_proxy(mode="flip", n=1, after_bytes=280)
        assert stream_tokens(prompt1) == clean1
        assert xfer_totals()["corrupt"] == 1
        assert xfer_totals()["fallback"] == 1

        # scenario 2: donor killed mid-pull — the body ends with no
        # trailer frame, exactly what a dying donor process leaves on
        # the wire -> detected, fallback, bit-identical
        prompt2 = list(range(200, 250))
        clean2 = donor_stream(prompt2)
        donor.chaos.corrupting_proxy(mode="truncate", n=1, after_bytes=80)
        assert stream_tokens(prompt2) == clean2
        assert xfer_totals()["corrupt"] == 2

        # scenario 3: donor wedged mid-pull (drip-feeding past the
        # budget) -> timeout, fallback, bit-identical
        prompt3 = list(range(300, 340))
        clean3 = donor_stream(prompt3)
        donor.chaos.corrupting_proxy(mode="stall", n=1, after_bytes=50,
                                     stall_s=4.0)
        assert stream_tokens(prompt3) == clean3
        assert xfer_totals()["timeout"] == 1

        # scenario 4: the donor is GONE entirely (listener down, the
        # router still believes in it) -> refused pull, fallback
        donor.stop_listener()
        prompt4 = list(range(400, 440))
        stream_tokens(prompt4)
        assert xfer_totals()["timeout"] == 2
        donor.start_listener()

        # route records carry the disagg evidence
        snap = _fleet_snapshot(app)
        routes = [r for r in snap["routes"]
                  if r["path"] == "/v1/completions"]
        assert routes and all(r["role"] == "decode" for r in routes)
        assert any(r["kv_donor"] == "r0" for r in routes)
        # decode work landed on the decode tier while it was healthy
        for r in routes:
            assert r["attempts"][-1]["replica"] in ("r1", "r2")
        # /admin/fleet surfaces each replica's role + transfer ledger
        by_name = {r["name"]: r for r in snap["replica_set"]["replicas"]}
        assert by_name["r0"]["role"] == "prefill"
        _wait(lambda: (
            (_fleet_snapshot(app)["replica_set"]["replicas"][1].get("engine")
             or {}).get("kv_transfer") is not None
        ), timeout=5, message="kv_transfer ledger scraped onto /admin/fleet")

        # every outcome visible, fleet-wide
        merged = xfer_totals()
        assert merged["corrupt"] == 2 and merged["timeout"] == 2
        assert merged["fallback"] == 4 and merged["ok"] >= 1

        # zero refcount leaks fleet-wide: every pool balances to idle
        for r in replicas:
            _, body, _ = _get(f"{r.address}/admin/engine")
            kv = json.loads(body)["data"]["kv_blocks"]
            assert kv["active"] == 0 and kv["reserved"] == 0, r.name


def test_role_routing_off_restores_mixed_behavior(tmp_path, monkeypatch):
    """FLEET_ROLE_ROUTING=off: advertised roles are ignored, no donor
    stamps, routing is exactly the pre-disaggregation fleet."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2, per_replica_env=[
        {"FLEET_ROLE": "prefill"}, {"FLEET_ROLE": "decode"},
    ]) as replicas, chaos_router(
        replicas, env={"FLEET_ROLE_ROUTING": "off"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        assert fleet.role_routing is False
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="2 replicas in rotation")
        status, _, _ = _completion(base, [1, 2, 3])
        assert status == 200
        snap = _fleet_snapshot(app)
        route = snap["routes"][0]
        assert route["role"] is None and route["kv_donor"] is None
        assert snap["role_routing"] is False


# -- fleet-scale hardening (ISSUE 12) ------------------------------------------

def test_token_bucket_exact_accounting_under_concurrency():
    """Many threads, ONE tenant: the memory-mode bucket admits EXACTLY
    its capacity — no over-admission from racing read-modify-writes, no
    lost tokens from double refills. Refill is negligible over the test
    window (0.001 rps), so capacity is the whole supply and the count
    is exact, not approximate."""
    table = QuotaTable(rate_rps=0.001, burst=48.0)
    n_threads, per_thread = 16, 25
    barrier = threading.Barrier(n_threads)
    admitted = [0] * n_threads
    denied = [0] * n_threads

    def worker(w):
        barrier.wait()
        for _ in range(per_thread):
            ok, retry_after = table.take("hot")
            if ok:
                admitted[w] += 1
            else:
                assert retry_after > 0
                denied[w] += 1

    threads = [
        threading.Thread(target=worker, args=(w,), name=f"test-quota-{w}")
        for w in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert sum(admitted) == 48
    assert sum(admitted) + sum(denied) == n_threads * per_thread
    stats = table.stats()
    assert stats["admitted"] == 48
    assert stats["denied"] == n_threads * per_thread - 48


def test_quota_lease_cache_serves_hot_tenant_locally():
    """The hot-key fix: with ``cache_ttl_s`` > 0 a hot tenant's takes
    are served from a local token lease, not one redis sync each —
    every take is either a cache hit or a sync, and syncs are rare."""
    from gofr_tpu.devtools.fleetsim import SimRedis

    redis = SimRedis()
    table = QuotaTable(rate_rps=200.0, burst=400.0, redis=redis,
                       cache_ttl_s=0.5)
    for _ in range(100):
        assert table.take("hot")[0]
    stats = table.stats()
    assert stats["cache_hits"] + stats["redis_syncs"] == 100
    assert stats["redis_syncs"] <= 5  # ~1 sync per lease of ~100 tokens
    # each sync is one read + one write pipeline round trip
    assert redis.execs == 2 * stats["redis_syncs"]


def test_quota_lease_leases_at_least_one_token_at_low_rates():
    """``rate * ttl`` < 1 must still lease a WHOLE token: a fractional
    lease can never admit, which silently disables the cache at
    realistic per-tenant rates (the first live fleetsim runs measured
    zero cache hits for exactly this reason)."""
    from gofr_tpu.devtools.fleetsim import SimRedis

    redis = SimRedis()
    table = QuotaTable(rate_rps=2.0, burst=8.0, redis=redis,
                       cache_ttl_s=0.05)
    assert table._lease_target() == 1.0  # max(1, 2*0.05), capped burst/2
    assert table.take("t")[0]  # sync: admits AND leases one token
    assert table.take("t")[0]  # served from the lease, no redis trip
    stats = table.stats()
    assert stats["cache_hits"] == 1 and stats["redis_syncs"] == 1
    # the hoard cap gets the SAME ≥1 floor: a sub-2.0 burst must not
    # clamp the lease back under one token and silently re-disable the
    # cache (min(1.0, burst/2) with burst 1.0 was exactly that hole)
    tiny = QuotaTable(rate_rps=0.5, burst=0.0, redis=SimRedis(),
                      cache_ttl_s=0.05)
    assert tiny.burst == 1.0  # the burst<=0 default: max(1, 2*rate)
    assert tiny._lease_target() == 1.0


def test_quota_lease_concurrent_sync_merges_instead_of_stranding():
    """Two syncs for the same tenant can race (lease expired, many
    workers): each debits a lease batch from the shared redis bucket,
    so the second install must MERGE the first's unused tokens, not
    overwrite them — an overwritten lease's tokens were debited in
    redis, never admitted, never credited: gone. Conservation is the
    assertion: bucket + live lease + admitted == burst, exactly."""
    from gofr_tpu.devtools.fleetsim import SimRedis

    redis = SimRedis()
    table = QuotaTable(rate_rps=100.0, burst=200.0, redis=redis,
                       cache_ttl_s=0.5)
    assert table.take("t")[0]   # sync 1: debits 1 + leases 50
    first_lease = table._leases["t"].tokens
    assert table._take_redis("t")[0]  # a racing sync: debits 1 + 50 more
    merged = table._leases["t"].tokens
    assert merged >= first_lease + 1.0  # both batches live, none stranded
    stored = float(redis.hashes["fleet:quota:t"]["tokens"])
    # refill over the test's microseconds is < 1 token
    assert stored + merged + 2.0 == pytest.approx(200.0, abs=1.0)


def test_quota_lease_caches_denial_with_counted_down_hint():
    """A denied sync caches the DENIAL for the TTL window too (a
    hammering tenant must not buy a redis trip per rejected request),
    and the cached Retry-After counts down as the window ages instead
    of re-serving the sync-time value."""
    from gofr_tpu.devtools.fleetsim import SimRedis

    redis = SimRedis()
    table = QuotaTable(rate_rps=0.5, burst=1.0, redis=redis,
                       cache_ttl_s=5.0)
    assert table.take("t")[0]  # burns the only token
    ok2, retry2 = table.take("t")  # sync: denied, denial cached
    assert not ok2 and retry2 > 0
    execs_after_denial = redis.execs
    ok3, retry3 = table.take("t")  # cached denial: no redis trip
    assert not ok3 and 0 < retry3 <= retry2
    assert redis.execs == execs_after_denial
    assert table.stats()["cache_hits"] >= 1


def test_quota_no_phantom_lease_when_redis_dies_mid_sync():
    """Redis failing BETWEEN the read and the write pipeline (exactly
    what the fleetsim redis-outage scenario injects mid-run) must not
    leave a local lease behind: its tokens were never debited
    fleet-wide, so a whole TTL window would admit from tokens every
    other router can also spend. The verdict must be fail-open (memory
    bucket), with no lease and the popped credit restored."""
    from gofr_tpu.devtools.fleetsim import SimRedis

    class _DiesOnWrite(SimRedis):
        def __init__(self):
            super().__init__()
            self.fail_after = None

        def pipeline(self):
            if self.fail_after is not None:
                if self.fail_after <= 0:
                    raise ConnectionError("injected mid-sync outage")
                self.fail_after -= 1
            return super().pipeline()

    redis = _DiesOnWrite()
    table = QuotaTable(rate_rps=100.0, burst=200.0, redis=redis,
                       cache_ttl_s=0.5)
    table._credit["t"] = 7.0  # pending give-back from an expired lease
    redis.fail_after = 1  # the read pipeline builds; the write raises
    ok, _ = table.take("t")
    assert ok  # failed open to the memory bucket
    assert "t" not in table._leases  # no phantom tokens
    assert table._credit["t"] == 7.0  # the give-back survived, once
    redis.fail_after = None
    assert table.take("t")[0]  # recovery: a real sync with a real lease
    assert table._leases["t"].tokens > 0
    assert "t" not in table._credit  # credit consumed exactly once


def test_quota_lease_expiry_credits_unused_tokens_back():
    """Leased-but-unused tokens return to the fleet-wide bucket on the
    tenant's next sync: the accounting error is bounded by one lease
    per router per TTL window, never cumulative."""
    from gofr_tpu.devtools.fleetsim import SimRedis

    redis = SimRedis()
    table = QuotaTable(rate_rps=100.0, burst=200.0, redis=redis,
                       cache_ttl_s=0.5)
    assert table.take("t")[0]  # sync: debits 1, leases 50 (rate*ttl)
    lease = table._leases["t"]
    assert lease.tokens >= 1.0
    lease.expires = 0.0  # force expiry (monotonic 0 = the distant past)
    assert table.take("t")[0]  # expiry -> credit -> sync gives it back
    stored = float(redis.hashes["fleet:quota:t"]["tokens"])
    new_lease = table._leases["t"].tokens
    # bucket contents = burst - 2 takes - the live lease; the expired
    # lease's 50 unused tokens came BACK (without the credit this would
    # sit ~50 lower). Refill noise over the test's microseconds < 1.
    assert stored == pytest.approx(200.0 - 2.0 - new_lease, abs=1.0)


def test_route_records_and_outstanding_survive_concurrent_load(
        tmp_path, monkeypatch):
    """Satellite: the route-record ring and the outstanding/in-flight
    bookkeeping under genuinely concurrent traffic, with a concurrent
    snapshot reader hammering the ring the whole time. The fleet-chaos
    CI job runs this module with GOFR_SANITIZE=1, so a lock-order
    inversion or an over-held lock inside the selection/record path is
    a FAILURE here, not a warning. Exactness: every request leaves
    exactly one intact record, and every depth counter drains to 0."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="2 replicas in rotation")
        n_threads, per_thread = 12, 6
        errors: list = []
        snap_stop = threading.Event()

        def snapshotter():
            while not snap_stop.is_set():
                snap = fleet.snapshot()
                assert isinstance(snap["routes"], list)
                time.sleep(0.002)

        def client(w):
            for i in range(per_thread):
                try:
                    status, body, _ = _post(
                        base + "/generate",
                        {"tokens": [w + 1, i + 1], "max_new_tokens": 3},
                        headers={"X-Session-ID": f"s{w}-{i}"}, timeout=20,
                    )
                    assert status == 200
                except Exception as exc:  # collected, asserted below
                    errors.append(exc)

        snap_thread = threading.Thread(
            target=snapshotter, name="test-fleet-snap")
        threads = [
            threading.Thread(target=client, args=(w,), name=f"test-load-{w}")
            for w in range(n_threads)
        ]
        snap_thread.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        snap_stop.set()
        snap_thread.join(10)
        assert not errors
        _wait(lambda: fleet.in_flight == 0, message="in-flight drained")
        for r in fleet.replica_set.replicas:
            assert r.outstanding == 0, r.name
        records = fleet.records(limit=1024)  # the admin page shows 50
        oks = [r for r in records if r["outcome"] == "ok"]
        assert len(oks) == n_threads * per_thread
        for rec in oks:
            assert rec["attempts"] and rec["attempts"][-1]["status"] == 200


def test_stream_dead_before_first_frame_resumes_from_zero(
        tmp_path, monkeypatch):
    """A stream that dies before ANY event reaches the client used to
    get its resume REFUSED (the relay required seen event ids), so
    every wedge-before-first-token became a truncated client stream —
    the fleetsim harness surfaced the whole cohort. Resuming from 0 is
    trivially safe when nothing was delivered: the relay now hunts, and
    the client sees one complete, token-exact stream."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    prompt, n_tokens = [3, 5, 7], 12
    expected = [prompt[i % 3] for i in range(n_tokens)]
    with chaos_fleet(2) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="2 replicas in rotation")
        victim = replicas[0]
        key = _key_for(victim.name, [r.name for r in replicas])
        # one-shot: the next streamed response dies after ZERO chunks
        # (headers sent, zero SSE frames — the pre-first-token wedge)
        victim.chaos.arm("disconnect_after", chunks=0, remaining=1,
                         paths=("/v1/",))
        req = urllib.request.Request(
            base + "/v1/completions",
            data=json.dumps({
                "model": "echo", "prompt": prompt, "max_tokens": n_tokens,
                "stream": True, "seed": 5,
            }).encode(),
            headers={"Content-Type": "application/json",
                     "X-Session-ID": key},
            method="POST",
        )
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200
        tokens, ids, raw = _read_sse_tokens(resp)
        assert b"data: [DONE]" in raw  # completed, not truncated
        assert tokens == expected  # zero missing, zero duplicated
        assert ids and ids[0] == 0  # the splice really started at zero
        snap = _fleet_snapshot(app)
        resumed = [r for r in snap["routes"] if r.get("resumes")]
        assert resumed, snap["routes"]
        assert resumed[0]["attempts"][-1]["resume_from"] == 0
        _, metrics_body, _ = _get(base + "/metrics")
        assert ('gofr_tpu_router_stream_resumes_total{outcome="resumed"}'
                in metrics_body.decode())
