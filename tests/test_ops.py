"""Numeric tests for compute ops (CPU, f32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.norms import layer_norm, rms_norm
from gofr_tpu.ops.rope import apply_rope, rope_frequencies

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def test_rms_norm_matches_manual():
    x = jax.random.normal(jax.random.key(0), (2, 5, 8))
    w = jnp.linspace(0.5, 1.5, 8)
    got = rms_norm(x, w)
    want = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-5) * np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_layer_norm_zero_mean_unit_var():
    x = jax.random.normal(jax.random.key(1), (3, 16)) * 5 + 2
    y = layer_norm(x, jnp.ones(16), jnp.zeros(16))
    np.testing.assert_allclose(np.asarray(y).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(-1), 1.0, atol=1e-3)


def test_rope_preserves_norm_and_identity_at_zero():
    q = jax.random.normal(jax.random.key(2), (1, 4, 2, 8))
    freqs = rope_frequencies(8, 32)
    positions = jnp.arange(4)
    rotated = apply_rope(q, freqs, positions)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rotated), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1),
        rtol=1e-5,
    )
    # position 0 is the identity rotation
    np.testing.assert_allclose(np.asarray(rotated[:, 0]), np.asarray(q[:, 0]), rtol=1e-6)


def test_rope_relative_property():
    # dot(q_m, k_n) depends only on m-n: shift both positions, dots unchanged
    q = jax.random.normal(jax.random.key(3), (1, 1, 1, 16))
    k = jax.random.normal(jax.random.key(4), (1, 1, 1, 16))
    freqs = rope_frequencies(16, 64)

    def dot_at(m, n):
        qm = apply_rope(q, freqs, jnp.array([m]))
        kn = apply_rope(k, freqs, jnp.array([n]))
        return float(jnp.sum(qm * kn))

    assert abs(dot_at(5, 3) - dot_at(25, 23)) < 1e-4


def test_attention_causality():
    key = jax.random.key(5)
    q = jax.random.normal(key, (1, 6, 2, 4))
    k = jax.random.normal(jax.random.key(6), (1, 6, 2, 4))
    v = jax.random.normal(jax.random.key(7), (1, 6, 2, 4))
    out1 = attention(q, k, v, causal=True, impl="xla")
    # perturb the LAST key/value; outputs at earlier positions must not move
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(-99.0)
    out2 = attention(q, k2, v2, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-6)
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]))


def test_attention_gqa_matches_repeated_mha():
    b, s, hq, hkv, d = 2, 5, 4, 2, 8
    q = jax.random.normal(jax.random.key(8), (b, s, hq, d))
    k = jax.random.normal(jax.random.key(9), (b, s, hkv, d))
    v = jax.random.normal(jax.random.key(10), (b, s, hkv, d))
    gqa = attention(q, k, v, causal=True, impl="xla")
    k_rep = jnp.repeat(k, hq // hkv, axis=2)
    v_rep = jnp.repeat(v, hq // hkv, axis=2)
    mha = attention(q, k_rep, v_rep, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(gqa), np.asarray(mha), rtol=2e-5, atol=2e-5)


def test_attention_padding_mask():
    b, s, h, d = 1, 4, 1, 4
    q = jax.random.normal(jax.random.key(11), (b, s, h, d))
    k = jax.random.normal(jax.random.key(12), (b, s, h, d))
    v = jax.random.normal(jax.random.key(13), (b, s, h, d))
    mask = jnp.array([[True, True, False, False]])
    out = attention(q, k, v, causal=False, mask=mask, impl="xla")
    # masked keys changed -> output unchanged
    k2 = k.at[:, 2:].set(7.0)
    v2 = v.at[:, 2:].set(-7.0)
    out2 = attention(q, k2, v2, causal=False, mask=mask, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


def test_attention_decode_offset():
    # decode: 1 query at absolute position 3 sees keys 0..3 only
    q = jax.random.normal(jax.random.key(14), (1, 1, 1, 4))
    k = jax.random.normal(jax.random.key(15), (1, 8, 1, 4))
    v = jax.random.normal(jax.random.key(16), (1, 8, 1, 4))
    out = attention(q, k, v, causal=True, q_offset=3, impl="xla")
    k2 = k.at[:, 4:].set(55.0)
    v2 = v.at[:, 4:].set(55.0)
    out2 = attention(q, k2, v2, causal=True, q_offset=3, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


def test_attention_low_precision_kv_close_to_full():
    """float8 KV upcasts at the attention boundary; outputs stay within
    float8's quantization error of the full-precision result."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gofr_tpu.ops.attention import attention

    key = jax.random.key(11)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (2, 6, 4, 8), jnp.float32)
    k = jax.random.normal(kk, (2, 6, 2, 8), jnp.float32)
    v = jax.random.normal(kv, (2, 6, 2, 8), jnp.float32)
    full = attention(q, k, v, causal=True, impl="xla")
    low = attention(
        q, k.astype(jnp.float8_e4m3fn), v.astype(jnp.float8_e4m3fn),
        causal=True, impl="xla",
    )
    assert low.dtype == q.dtype
    np.testing.assert_allclose(
        np.asarray(low), np.asarray(full), atol=0.2, rtol=0.2
    )
