"""Fleet-scale chaos simulation harness, tier-1: deterministic
generators (same seed ⇒ byte-identical trace AND fault schedule — the
replay contract), the probe-jitter de-synchronization that is
load-bearing at N=16, the quota lease cache A/B, the SLO gate's
absolute/relative failure matrix, and a small end-to-end smoke run
(5 replicas; the CI ``fleet-sim`` job runs the real N=16 topology via
``tools/fleetsim.py`` and gates it against ``fleetsim_baseline.json``)."""

import importlib.util
import json
import pathlib
import random

import pytest

from gofr_tpu.devtools import fleetsim
from gofr_tpu.devtools.fleetsim import (
    FleetSim,
    SimRedis,
    TraceSpec,
    build_scenario,
    build_trace,
)

REPO = pathlib.Path(__file__).resolve().parents[1]
_spec = importlib.util.spec_from_file_location(
    "fleetsim_gate", REPO / "tools" / "fleetsim_gate.py"
)
fleetsim_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(fleetsim_gate)


# -- replayability: the seed IS the run ----------------------------------------

def test_trace_same_seed_is_byte_identical():
    """The replay contract: the trace is a pure function of its spec.
    Byte-identity is asserted on the canonical JSON itself, not just
    the digest — the digest is merely the witness the artifact
    records."""
    a_events, a_digest = build_trace(TraceSpec(requests=120, seed=42))
    b_events, b_digest = build_trace(TraceSpec(requests=120, seed=42))
    assert a_digest == b_digest
    assert (json.dumps(a_events, sort_keys=True)
            == json.dumps(b_events, sort_keys=True))
    c_events, c_digest = build_trace(TraceSpec(requests=120, seed=43))
    assert c_digest != a_digest


def test_scenario_same_seed_is_byte_identical():
    a_events, a_digest = build_scenario(7, n_replicas=16, n_prefill=2,
                                        duration_s=20.0)
    b_events, b_digest = build_scenario(7, n_replicas=16, n_prefill=2,
                                        duration_s=20.0)
    assert a_digest == b_digest
    assert (json.dumps(a_events, sort_keys=True)
            == json.dumps(b_events, sort_keys=True))
    _, c_digest = build_scenario(8, n_replicas=16, n_prefill=2,
                                 duration_s=20.0)
    assert c_digest != a_digest


def test_trace_structure_and_protected_cohort():
    """Structural invariants the SLOs depend on: timestamps
    non-decreasing, phases in spec order, priority tiers drawn from the
    mix, and the priority-9 cohort riding its OWN low-volume tenant —
    'tier 9 is never shed' must be a property of the system, not of a
    lucky tenant draw."""
    spec = TraceSpec(requests=200, seed=3)
    events, _ = build_trace(spec)
    assert len(events) >= spec.requests
    tiers = {tier for tier, _ in spec.priority_mix}
    phase_order = [name for name, _, _ in spec.phases]
    last_at, last_phase_idx = 0.0, 0
    for ev in events:
        assert ev["at_s"] >= last_at
        last_at = ev["at_s"]
        idx = phase_order.index(ev["phase"])
        assert idx >= last_phase_idx  # phases never rewind
        last_phase_idx = idx
        assert ev["priority"] in tiers
        assert ev["kind"] in ("unary", "stream", "abort_stream")
        assert (ev["abort_after"] is not None) == (
            ev["kind"] == "abort_stream")
        if ev["priority"] == 9:
            assert ev["tenant"] == "t-platinum"
        else:
            assert ev["tenant"].startswith("t") and ev["tenant"] != (
                "t-platinum")
    p9 = [ev for ev in events if ev["priority"] == 9]
    assert 0 < len(p9) < len(events) / 4  # present AND low-volume


def test_scenario_events_are_paired_and_ordered():
    """Every injected fault carries its own cure in the schedule: a
    wedge has a recover, a drain a restart, an armed stream-mangler a
    clear, the redis outage an end — the digest captures the WHOLE
    incident timeline, so convergence is part of the replayed run."""
    events, _ = build_scenario(11, n_replicas=16, n_prefill=2,
                               duration_s=20.0)
    assert events == sorted(events, key=lambda e: e["at_s"])
    ops = [e["op"] for e in events]
    assert "redis_down" in ops and "redis_up" in ops

    def targets(op):
        return sorted(e["replica"] for e in events if e["op"] == op)

    assert targets("wedge") == targets("recover")
    assert targets("drain") == targets("restart")
    cleared = {(e["replica"], e["mode"]) for e in events if e["op"] == "clear"}
    for e in events:
        if e["op"] == "slow_loris":
            assert (e["replica"], "slow_loris") in cleared
        if e["op"] == "disconnect":
            assert (e["replica"], "disconnect_after") in cleared
    # faults target the decode tier; the prefill tier only ever gets
    # the KV corruption (its serving plane must stay healthy so the
    # local-prefill fallback has somewhere to run)
    for e in events:
        if e["op"] == "kv_corrupt":
            assert e["replica"] < 2
        elif "replica" in e:
            assert e["replica"] >= 2


# -- probe jitter: the thundering-herd fix -------------------------------------

def _fire_times(jitter, n=16, rounds=40, interval=1.0):
    """Simulate the prober's schedule without threads: accumulate each
    replica's per-round delays exactly as ``_probe_loop`` draws them
    (same per-replica RNG seeding), returning all fire times sorted."""
    from gofr_tpu.fleet.replica import Replica, ReplicaSet

    logger = fleetsim._NullLogger()
    replicas = [
        Replica(f"m{i}", "http://127.0.0.1:9", logger) for i in range(n)
    ]
    rset = ReplicaSet(replicas, logger, probe_interval_s=interval,
                      probe_jitter=jitter)
    times = []
    for r in replicas:
        rng = random.Random(f"gofr-probe-jitter|{r.name}")
        t = rset.next_probe_delays(rng, initial=True)
        for _ in range(rounds):
            times.append(t)
            t += rset.next_probe_delays(rng)
    return sorted(times)


def _max_burst(times, window):
    best = 0
    for i, t0 in enumerate(times):
        n = 0
        for t in times[i:]:
            if t - t0 > window:
                break
            n += 1
        best = max(best, n)
    return best


def test_probe_jitter_desynchronizes_schedule():
    """The satellite's unit: with jitter off, every round of a
    16-replica fleet fires as ONE instantaneous burst, forever; with
    decorrelated jitter the phases drift apart and stay apart. Fully
    deterministic — the per-replica RNGs are seeded off replica names,
    exactly as the live prober seeds them."""
    sync = [t for t in _fire_times(jitter=0.0) if t > 20.0]
    jit = [t for t in _fire_times(jitter=0.3) if t > 20.0]
    assert _max_burst(sync, 0.05) == 16  # the whole round, one instant
    assert _max_burst(jit, 0.05) <= 8  # uniform expectation is ~0.8


def test_next_probe_delays_bounds():
    from gofr_tpu.fleet.replica import Replica, ReplicaSet

    logger = fleetsim._NullLogger()
    replicas = [Replica("m0", "http://127.0.0.1:9", logger)]
    rset = ReplicaSet(replicas, logger, probe_interval_s=2.0,
                      probe_jitter=0.25)
    rng = random.Random(1)
    for _ in range(200):
        initial = rset.next_probe_delays(rng, initial=True)
        assert 0.0 <= initial < 0.5  # spread over the jitter window only
        steady = rset.next_probe_delays(rng)
        assert 1.5 <= steady <= 2.5  # interval * (1 +/- jitter)
    # jitter 0 restores the synchronized sweep exactly
    plain = ReplicaSet(replicas, logger, probe_interval_s=2.0,
                       probe_jitter=0.0)
    assert plain.next_probe_delays(rng, initial=True) == 0.0
    assert plain.next_probe_delays(rng) == 2.0
    # the constructor clamps runaway jitter below 1 so the schedule
    # can never stall (delay can never reach 0 at steady state)
    wild = ReplicaSet(replicas, logger, probe_interval_s=2.0,
                      probe_jitter=5.0)
    assert wild.probe_jitter == 0.9


def test_quota_lease_cache_ab_measure():
    """The hardening A/B the artifact records: TTL 0 is exactly one
    sync per request; the lease cache cuts it by an order of
    magnitude on a hot tenant."""
    before = fleetsim.measure_quota_trips(cache_ttl_s=0.0)
    after = fleetsim.measure_quota_trips(cache_ttl_s=0.05)
    assert before["syncs_per_request"] == 1.0
    assert before["cache_hits"] == 0
    assert after["syncs_per_request"] < 0.5
    assert after["cache_hits"] > 0


# -- the SLO gate --------------------------------------------------------------

def _artifact(**overrides):
    art = {
        "kind": "FLEETSIM",
        "schema": 1,
        "seed": 1,
        "replicas": 16,
        "scenario": {
            "injected": {"error_burst": 5, "slow_loris": 3,
                         "disconnect_after": 2},
        },
        "slo": {
            "requests": 240, "ok": 200, "client_aborted": 10, "errors": 2,
            "ttft_p50_ms": 30.0, "ttft_p99_ms": 120.0,
            "shed": {"total": 28, "rate": 0.1167,
                     "by_priority": {"0": 20, "3": 8}, "p9": 0},
            "streams": {"verified": 90, "token_exact": 90,
                        "duplicated_tokens": 0, "missing_tokens": 0},
            "resume": {"resumed": 3, "exhausted": 0, "refused": 0,
                       "failures": 0},
            "tenants": [
                {"tenant": "t-platinum", "requests": 24, "ok": 24,
                 "sheds": 0, "errors": 0, "client_aborted": 0,
                 "availability": 1.0, "target": 0.9995,
                 "budget_remaining": 1.0},
                {"tenant": "t00", "requests": 70, "ok": 60, "sheds": 9,
                 "errors": 1, "client_aborted": 0,
                 "availability": 0.9833, "target": 0.999,
                 "budget_remaining": -15.7},
            ],
            "breaker_flaps": 6,
            "pools_idle": True,
            "converged": {"rotation": True, "pools_idle": True},
        },
        "hardening": {
            "probe_spread": {"before": {"max_probes_in_window": 16},
                             "after": {"max_probes_in_window": 4}},
            "quota": {"before": {"syncs_per_request": 1.0},
                      "after": {"syncs_per_request": 0.02}},
        },
    }
    for path, value in overrides.items():
        cursor, keys = art, path.split(".")
        for key in keys[:-1]:
            cursor = cursor[key]
        cursor[keys[-1]] = value
    return art


def test_gate_passes_a_healthy_artifact():
    assert fleetsim_gate.gate(_artifact(), _artifact()) == []


def test_gate_absolute_invariants():
    baseline = _artifact()
    cases = [
        ({"slo.streams.missing_tokens": 3}, "lost/duplicated"),
        ({"slo.streams.duplicated_tokens": 1}, "lost/duplicated"),
        ({"slo.streams.token_exact": 88}, "token-exact"),
        ({"slo.resume.failures": 1, "slo.resume.refused": 1},
         "resume success must be 100%"),
        ({"slo.shed.p9": 2}, "never shed"),
        ({"slo.pools_idle": False}, "idle"),
        ({"hardening.probe_spread.after": {"max_probes_in_window": 16}},
         "probe jitter"),
        ({"hardening.quota.after": {"syncs_per_request": 1.0}},
         "lease cache"),
        # anti-vacuity: invariants only count when their chaos fired
        ({"scenario.injected": {"error_burst": 5, "slow_loris": 3}},
         "'disconnect_after' never fired"),
        ({"scenario.injected": {"error_burst": 5, "disconnect_after": 2}},
         "'slow_loris' never fired"),
        ({"slo.resume.resumed": 0}, "vacuously true"),
        # the protected tenant: its SLO lines must exist and hold
        ({"slo.tenants": []}, "no per-tenant SLO lines"),
        ({"slo.tenants": [{"tenant": "t00", "budget_remaining": 1.0}]},
         "t-platinum"),
        ({"slo.tenants": [{"tenant": "t-platinum", "availability": 0.5,
                           "target": 0.9995, "budget_remaining": -999.0}]},
         "exhausted its availability budget"),
    ]
    for overrides, needle in cases:
        failures = fleetsim_gate.gate(_artifact(**overrides), baseline)
        assert failures, overrides
        assert any(needle in f for f in failures), (overrides, failures)


def test_gate_relative_tolerances():
    baseline = _artifact()
    # inside tolerance: loose-first factors absorb CI noise
    assert fleetsim_gate.gate(
        _artifact(**{"slo.ttft_p99_ms": 400.0, "slo.errors": 6,
                     "slo.breaker_flaps": 14}),
        baseline,
    ) == []
    cases = [
        # above BOTH the factor allowance and the 15s absolute floor
        ({"slo.ttft_p99_ms": 16000.0}, "p99 TTFT"),
        ({"slo.errors": 8}, "error count"),
        ({"slo.shed.rate": 0.4}, "shed rate"),
        ({"slo.breaker_flaps": 30}, "breaker flap"),
        ({"replicas": 8}, "fleet shrank"),
    ]
    # the shed-rate floor keeps the check alive against a ZERO-shed
    # baseline (0 * factor would disable it entirely)
    zero_base = _artifact(**{"slo.shed.rate": 0.0, "slo.shed.total": 0})
    assert fleetsim_gate.gate(
        _artifact(**{"slo.shed.rate": 0.08}), zero_base) == []
    floor_failures = fleetsim_gate.gate(
        _artifact(**{"slo.shed.rate": 0.4}), zero_base)
    assert floor_failures and any(
        "shed rate" in f for f in floor_failures)
    for overrides, needle in cases:
        failures = fleetsim_gate.gate(_artifact(**overrides), baseline)
        assert failures and any(needle in f for f in failures), (
            overrides, failures)


def test_gate_rejects_foreign_artifacts():
    failures = fleetsim_gate.gate({"kind": "BENCH"}, _artifact())
    assert failures and "not a FLEETSIM artifact" in failures[0]


def test_sim_redis_speaks_the_quota_pipeline():
    """The in-sim redis honors the exact pipelined chains
    ``QuotaTable._take_redis`` issues, counts round trips, and raises
    while down (the redis-outage scenario's switch)."""
    redis = SimRedis()
    tokens, ts = redis.pipeline().hget("k", "tokens").hget("k", "ts").execute()
    assert tokens is None and ts is None
    redis.pipeline().hset("k", "tokens", "3.5").hset(
        "k", "ts", "99.0").expire("k", 60).execute()
    assert redis.pipeline().hget("k", "tokens").execute() == ["3.5"]
    assert redis.execs == 3
    redis.down = True
    with pytest.raises(ConnectionError):
        redis.pipeline().hget("k", "tokens").execute()
    assert redis.execs == 3  # a down backend serves nothing


# -- end-to-end smoke ----------------------------------------------------------

def test_fleetsim_smoke_small_fleet(tmp_path, monkeypatch):
    """One real run at tier-1 scale: 5 echo replicas (1 prefill)
    behind the real router, the full seeded trace + fault schedule,
    and the gate's ABSOLUTE invariants asserted on the artifact. The
    N=16 topology runs in the CI ``fleet-sim`` job — this smoke keeps
    the harness itself honest inside plain pytest."""
    monkeypatch.chdir(tmp_path)
    spec = TraceSpec(requests=50, base_rps=25.0, seed=11)
    sim = FleetSim(
        n_replicas=5, n_prefill=1, seed=11, spec=spec,
        quota_rps=30.0, quota_burst=60.0, workers=8,
        measure_hardening=False,
    )
    artifact = sim.run()
    # the artifact's digests ARE the replay contract
    _, trace_digest = build_trace(TraceSpec(requests=50, base_rps=25.0,
                                            seed=11))
    assert artifact["trace"]["digest"] == trace_digest
    assert artifact["seed"] == 11
    slo = artifact["slo"]
    assert slo["requests"] == len(build_trace(spec)[0])
    assert slo["ok"] > 0 and slo["ttft_p99_ms"] is not None
    # the gate's absolute chaos-correctness invariants, at tier-1 scale
    assert slo["shed"]["p9"] == 0
    assert slo["streams"]["duplicated_tokens"] == 0
    assert slo["streams"]["missing_tokens"] == 0
    assert slo["streams"]["token_exact"] == slo["streams"]["verified"]
    assert slo["resume"]["failures"] == 0, slo["resume"]
    assert slo["pools_idle"], artifact["scenario"]["applied"]
    assert slo["converged"]["rotation"]
    assert slo["errors"] <= 3, slo["error_detail"]
    # chaos actually fired: the schedule was applied, not skipped
    assert all(e["applied"] for e in artifact["scenario"]["applied"])
    assert artifact["scenario"]["injected"]


# -- process death (router HA + supervised subprocess victim) ------------------

def test_process_kill_scenario_is_deterministic_and_layered():
    """process_kill layers SIGKILL + router-death events ON TOP of the
    default schedule (so the existing chaos anti-vacuity checks stay
    armed), deterministically: same seed ⇒ identical digest, and the
    router events appear only when a second router exists to fail over
    to."""
    base_events, base_digest = build_scenario(
        7, n_replicas=16, n_prefill=2, duration_s=20.0)
    a_events, a_digest = build_scenario(
        7, n_replicas=16, n_prefill=2, duration_s=20.0,
        process_kill=True, n_routers=2)
    b_events, b_digest = build_scenario(
        7, n_replicas=16, n_prefill=2, duration_s=20.0,
        process_kill=True, n_routers=2)
    assert a_digest == b_digest
    assert (json.dumps(a_events, sort_keys=True)
            == json.dumps(b_events, sort_keys=True))
    assert a_digest != base_digest
    ops = [e["op"] for e in a_events]
    assert ops.count("process_kill") == 2
    assert ops.count("router_kill") == 1 and ops.count("router_restart") == 1
    kill_at = next(e["at_s"] for e in a_events if e["op"] == "router_kill")
    restart_at = next(
        e["at_s"] for e in a_events if e["op"] == "router_restart")
    assert restart_at > kill_at  # the dead router comes back for converge
    # every default-schedule fault survives the layering
    base_ops = [e["op"] for e in base_events]
    for op in set(base_ops):
        assert ops.count(op) >= base_ops.count(op)
    # a single-router fleet schedules no router death (nothing to fail
    # over to — the kill would just truncate the whole trace)
    solo_events, _ = build_scenario(
        7, n_replicas=16, n_prefill=2, duration_s=20.0,
        process_kill=True, n_routers=1)
    solo_ops = [e["op"] for e in solo_events]
    assert "router_kill" not in solo_ops
    assert solo_ops.count("process_kill") == 2


def test_gate_process_kill_invariants():
    healthy_block = {
        "victim": "r16", "replica_kills": 2, "router_kills": 1,
        "supervisor_restarts": 2, "victim_rehydrated": 1,
    }
    healthy = _artifact(**{
        "scenario_mode": "process_kill",
        "routers": 2,
        "process_kill": healthy_block,
        "slo.router_failovers": 5,
    })
    baseline = _artifact()
    assert fleetsim_gate.gate(healthy, baseline) == []
    cases = [
        ({"process_kill": None}, "no process_kill evidence"),
        ({"process_kill": dict(healthy_block, replica_kills=0)},
         "no replica SIGKILL landed"),
        ({"process_kill": dict(healthy_block, supervisor_restarts=0)},
         "never respawned"),
        ({"process_kill": dict(healthy_block, victim_rehydrated=None)},
         "rehydration cannot be verified"),
        ({"process_kill": dict(healthy_block, router_kills=0)},
         "router kill never applied"),
        ({"slo.router_failovers": 0}, "no-single-point-of-failure"),
    ]
    for overrides, needle in cases:
        broken = _artifact(**{
            "scenario_mode": "process_kill", "routers": 2,
            "process_kill": dict(healthy_block),
            "slo.router_failovers": 5,
        })
        for path, value in overrides.items():
            cursor, keys = broken, path.split(".")
            for key in keys[:-1]:
                cursor = cursor[key]
            cursor[keys[-1]] = value
        failures = fleetsim_gate.gate(broken, baseline)
        assert failures and any(needle in f for f in failures), (
            overrides, failures)
    # a default-scenario artifact is never held to process-kill checks
    assert fleetsim_gate.gate(_artifact(), baseline) == []


def test_fleetsim_smoke_process_kill_two_routers(tmp_path, monkeypatch):
    """The router-HA acceptance at tier-1 scale: 5 in-process replicas
    + 1 SUPERVISED SUBPROCESS replica behind TWO router instances; the
    schedule SIGKILLs the subprocess victim twice and hard-kills router
    0 mid-trace — and the absolute SLOs hold: zero token loss, 100%
    resume success, pools idle, clients failed over between routers,
    the supervisor respawned the victim. The CI ``fleet-sim`` job runs
    the same scenario at N=16."""
    monkeypatch.chdir(tmp_path)
    spec = TraceSpec(requests=60, base_rps=12.0, seed=13)
    sim = FleetSim(
        n_replicas=5, n_prefill=1, seed=13, spec=spec,
        quota_rps=30.0, quota_burst=60.0, workers=8,
        n_routers=2, scenario="process_kill",
        measure_hardening=False,
    )
    artifact = sim.run()
    assert artifact["routers"] == 2
    assert artifact["scenario_mode"] == "process_kill"
    slo = artifact["slo"]
    block = artifact["process_kill"]
    assert block["replica_kills"] >= 1
    assert block["supervisor_restarts"] >= 1
    assert block["victim_rehydrated"] is not None
    assert block["router_kills"] == 1
    assert slo["router_failovers"] >= 1  # clients rode the sibling router
    # the existing correctness SLOs hold THROUGH process death
    assert slo["streams"]["duplicated_tokens"] == 0
    assert slo["streams"]["missing_tokens"] == 0
    assert slo["streams"]["token_exact"] == slo["streams"]["verified"]
    assert slo["resume"]["failures"] == 0, slo["resume"]
    assert slo["shed"]["p9"] == 0
    assert slo["pools_idle"], artifact["scenario"]["applied"]
    assert slo["errors"] <= 3, slo["error_detail"]


# -- capture -> replay round trip ---------------------------------------------

def test_fleetsim_capture_then_replay_round_trip(tmp_path, monkeypatch):
    """Production traffic becomes a regression suite: a small live run
    scrapes its OWN route + flight records into a TRACE_CAPTURE
    artifact (seeded anonymization), and a second run driven by
    ``replay=`` replays those exact events — trace digest equal to the
    capture's, ``replay_of`` stamped, and the replay itself digest-
    stable (the determinism the CI fleet-sim smoke leans on)."""
    from gofr_tpu.devtools.trace_capture import load_capture

    monkeypatch.chdir(tmp_path)
    cap_path = tmp_path / "capture.json"
    spec = TraceSpec(requests=30, base_rps=25.0, seed=21)
    sim = FleetSim(
        n_replicas=3, n_prefill=1, seed=21, spec=spec,
        quota_rps=30.0, quota_burst=60.0, workers=6,
        measure_hardening=False, capture_out=str(cap_path),
    )
    artifact = sim.run()
    block = artifact["capture"]
    assert block["path"] == str(cap_path)
    assert block["requests"] > 0
    capture = load_capture(str(cap_path))  # digest verified on load
    assert capture["digest"] == block["digest"]
    # raw tenant names never leak into the capture (t0/t1... are the
    # sim's real tenant ids; captured events carry seeded hashes)
    blob = json.dumps(capture["events"])
    assert '"t0"' not in blob and '"t1"' not in blob

    replay_sim = FleetSim(
        n_replicas=3, n_prefill=1, seed=21,
        quota_rps=30.0, quota_burst=60.0, workers=6,
        measure_hardening=False, replay=capture,
    )
    replayed = replay_sim.run()
    assert replayed["trace"]["digest"] == capture["digest"]
    assert replayed["trace"]["replay_of"] == capture["digest"]
    assert replayed["slo"]["requests"] == len(capture["events"])
    assert replayed["slo"]["ok"] > 0
