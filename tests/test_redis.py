"""Redis datasource tests against in-process miniredis.

Parity model: redis_test.go:23-51 — miniredis.Run(), command round-trips,
logged command assertions (SURVEY.md §4)."""

import threading
import time

import pytest

from gofr_tpu.datasource.miniredis import MiniRedis
from gofr_tpu.datasource.redis import RedisClient, RedisError, RedisServerError, new_client
from gofr_tpu.logging import Level
from gofr_tpu.testutil import MockLogger


@pytest.fixture(scope="module")
def mini():
    server = MiniRedis().run()
    yield server
    server.close()


@pytest.fixture
def client(mini):
    logger = MockLogger(Level.DEBUG)
    c = new_client("127.0.0.1", mini.port, logger)
    c.flushdb()
    yield c, logger
    c.close()


def test_set_get_roundtrip(client):
    c, logger = client
    assert c.set("greeting", "hello") == "OK"
    assert c.get("greeting") == "hello"
    assert c.get("missing") is None
    # logged command with duration (parity: redis_test.go:49-51)
    assert "SET greeting hello" in logger.output
    assert "duration_us" in logger.output


def test_set_with_expiry(client):
    c, _ = client
    c.set("temp", "x", ex=100)
    assert 0 < c.ttl("temp") <= 100
    assert c.ttl("no-such-key") == -2


def test_incr_del_exists(client):
    c, _ = client
    assert c.incr("counter") == 1
    assert c.incr("counter") == 2
    assert c.exists("counter") == 1
    assert c.delete("counter") == 1
    assert c.exists("counter") == 0


def test_hash_and_list_ops(client):
    c, _ = client
    assert c.hset("h", "field", "v") == 1
    assert c.hget("h", "field") == "v"
    c.lpush("l", "a", "b")
    assert c.rpop("l") == "a"


def test_keys_pattern(client):
    c, _ = client
    c.set("user:1", "a")
    c.set("user:2", "b")
    c.set("other", "c")
    assert sorted(c.keys("user:*")) == ["user:1", "user:2"]


def test_server_error_keeps_connection(client):
    c, _ = client
    c.lpush("alist", "x")
    with pytest.raises(RedisServerError):
        c.get("alist")  # WRONGTYPE
    assert c.ping()  # connection still usable


def test_health_check(client, mini):
    c, _ = client
    h = c.health_check()
    assert h.status == "UP"
    assert h.details["redis_version"] == "7.0.0-mini"
    assert "latency_us" in h.details


def test_connect_failure_raises():
    with pytest.raises(OSError):
        RedisClient("127.0.0.1", 1, timeout=0.2)


def test_concurrent_clients(client):
    c, _ = client
    errors = []

    def work(i):
        try:
            c.set(f"k{i}", str(i))
            assert c.get(f"k{i}") == str(i)
        except Exception as exc:
            errors.append(exc)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


# -- pipelining (parity: redis/hook.go:38-58 pipeline logging) ----------------

def test_pipeline_one_round_trip_in_order(client):
    c, logger = client
    p = c.pipeline()
    p.set("pa", "1").incr("pc").get("pa").command("ECHO", "hi")
    assert len(p) == 4
    results = p.execute()
    assert results == ["OK", 1, "1", "hi"]
    assert p.results == results
    assert len(p) == 0  # queue drained
    assert "pipeline[4]" in logger.output  # batched RedisLog entry


def test_pipeline_context_manager(client):
    c, _ = client
    with c.pipeline() as p:
        p.set("cm", "x")
        p.get("cm")
    assert p.results == ["OK", "x"]


def test_pipeline_error_drains_and_raises(client):
    c, _ = client
    p = c.pipeline()
    p.set("pe", "v").command("INCR", "pe").get("pe")
    with pytest.raises(RedisServerError):
        p.execute()
    # all replies were drained: the connection stays usable
    assert c.get("pe") == "v"


def test_pipeline_errors_returned_when_not_raising(client):
    c, _ = client
    p = c.pipeline()
    p.set("pr", "v").command("INCR", "pr").get("pr")
    results = p.execute(raise_on_error=False)
    assert results[0] == "OK"
    assert isinstance(results[1], RedisServerError)
    assert results[2] == "v"


def test_empty_pipeline(client):
    c, _ = client
    assert c.pipeline().execute() == []
