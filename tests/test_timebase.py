"""Telemetry timebase + postmortem black box (gofr_tpu/timebase.py,
gofr_tpu/postmortem.py, metrics exemplars/cardinality): unit semantics
plus the end-to-end acceptance spine over the in-process server on the
no-JAX ``echo`` model — an injected device stall must wedge the engine
AND leave a postmortem bundle on disk containing the stalling
dispatch_id, the flight records that rode it, timebase snapshots, and
every thread's stack; ``/admin/timeseries`` must serve a counter rate
series spanning the incident; the OpenMetrics exposition must carry an
exemplar resolving to a ``/admin/requests`` row."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from gofr_tpu.metrics import Histogram, Registry
from gofr_tpu.timebase import TimebaseSampler


# -- unit: timebase ring ------------------------------------------------------

def _sampler(registry):
    return TimebaseSampler(
        registry, interval_s=0.5, window_s=60.0, start=False
    )


def test_sampler_series_and_rate_derivation():
    registry = Registry()
    counter = registry.counter("gofr_t_total", "t", labels=("k",))
    sampler = _sampler(registry)
    counter.inc(10, k="a")
    sampler.sample_now()
    counter.inc(30, k="a")
    counter.inc(5, k="b")
    sampler.sample_now()
    out = sampler.series("gofr_t_total")
    assert out["kind"] == "counter"
    by_labels = {tuple(s["labels"].items()): s for s in out["series"]}
    a = by_labels[(("k", "a"),)]
    assert [p[1] for p in a["points"]] == [10.0, 40.0]
    assert len(a["rate"]) == 1
    snaps = sampler.snapshots()
    dt = snaps[1]["mono"] - snaps[0]["mono"]  # rate dt is monotonic
    assert a["rate"][0][1] == pytest.approx(30.0 / dt)
    # label-set b only exists in the second snapshot: one point, no rate
    b = by_labels[(("k", "b"),)]
    assert len(b["points"]) == 1 and b["rate"] == []
    # labels filter is a subset match
    only_a = sampler.series("gofr_t_total", labels={"k": "a"})
    assert len(only_a["series"]) == 1
    assert sampler.series("gofr_unknown_total") is None


def test_sampler_counter_reset_clamps_to_zero():
    registry = Registry()
    counter = registry.counter("gofr_r_total", "r")
    sampler = _sampler(registry)
    counter.inc(100)
    sampler.sample_now()
    counter._values[()] = 3.0  # simulate a process restart's fresh counter
    sampler.sample_now()
    out = sampler.series("gofr_r_total")
    assert out["series"][0]["rate"][0][1] == 0.0  # never a negative spike


def test_sampler_ring_is_bounded_and_windowed():
    registry = Registry()
    sampler = TimebaseSampler(
        registry, interval_s=1.0, window_s=3.0, start=False
    )
    for _ in range(10):
        sampler.sample_now()
    stats = sampler.stats()
    assert stats["snapshots"] <= 4  # window/interval + 1
    assert len(sampler.snapshots(last=2)) == 2
    assert sampler.snapshots(window=0.0) in ([], sampler.snapshots(window=0.0))


def test_sampler_hist_quantile_trend_is_interval_local():
    registry = Registry()
    hist = registry.histogram(
        "gofr_q_seconds", "q", buckets=(0.1, 1.0, 10.0)
    )
    sampler = _sampler(registry)
    sampler.sample_now()
    for _ in range(10):
        hist.observe(0.05)  # interval 1: everything fast
    sampler.sample_now()
    for _ in range(10):
        hist.observe(5.0)  # interval 2: everything slow
    sampler.sample_now()
    trend = sampler.hist_quantile_trend("gofr_q_seconds", 0.95)
    assert [v for _, v in trend] == [0.1, 10.0]
    # the cumulative histogram would have reported a blended p95 —
    # interval-locality is the whole point of the trend


def test_sampler_quantile_trend_survives_bucket_overflow():
    """An incident where every observation blows past the top bucket —
    exactly when the trend matters — must still produce points (clamped
    to the top bound), not go blank: overflow lives only in the series
    count, never in the finite bucket counts."""
    registry = Registry()
    hist = registry.histogram("gofr_o_seconds", "o", buckets=(0.1, 1.0))
    sampler = _sampler(registry)
    sampler.sample_now()
    for _ in range(10):
        hist.observe(50.0)  # all +Inf overflow
    sampler.sample_now()
    trend = sampler.hist_quantile_trend("gofr_o_seconds", 0.95)
    assert [v for _, v in trend] == [1.0]


def test_rate_total_sums_across_label_sets():
    registry = Registry()
    counter = registry.counter("gofr_s_total", "s", labels=("k",))
    sampler = _sampler(registry)
    counter.inc(1, k="a")
    sampler.sample_now()
    counter.inc(1, k="a")
    counter.inc(2, k="b")
    sampler.sample_now()
    rate = sampler.rate_total("gofr_s_total")
    snaps = sampler.snapshots()
    dt = snaps[1]["mono"] - snaps[0]["mono"]  # rate dt is monotonic
    assert rate[0][1] == pytest.approx(3.0 / dt)


def test_sampler_validates_intervals():
    with pytest.raises(ValueError):
        TimebaseSampler(Registry(), interval_s=0, start=False)
    with pytest.raises(ValueError):
        TimebaseSampler(
            Registry(), interval_s=10.0, window_s=5.0, start=False
        )


# -- unit: metrics cardinality guard -----------------------------------------

def test_cardinality_guard_drops_overflow_series():
    registry = Registry(max_series=2)
    counter = registry.counter("gofr_c_total", "c", labels=("k",))
    counter.inc(k="a")
    counter.inc(k="b")
    counter.inc(k="c")  # third label-set: dropped
    counter.inc(5, k="a")  # existing series still updates
    assert counter.value(k="a") == 6
    assert counter.value(k="c") == 0.0
    dropped = registry.counter(
        "gofr_tpu_metrics_dropped_series_total", labels=("metric",)
    )
    assert dropped.value(metric="gofr_c_total") == 1
    gauge = registry.gauge("gofr_g_depth", "g", labels=("k",))
    gauge.set(1, k="a")
    gauge.set(1, k="b")
    gauge.set(1, k="c")
    assert dropped.value(metric="gofr_g_depth") == 1
    hist = registry.histogram("gofr_h_seconds", "h", labels=("k",))
    hist.observe(0.1, k="a")
    hist.observe(0.1, k="b")
    hist.observe(0.1, k="c")
    assert dropped.value(metric="gofr_h_seconds") == 1
    assert "gofr_tpu_metrics_dropped_series_total" in registry.expose()


# -- unit: exemplars + OpenMetrics exposition ---------------------------------

def test_histogram_exemplar_explicit_and_provider():
    provided = {"trace_id": "feedface"}
    hist = Histogram(
        "gofr_e_seconds", "e", buckets=(0.1, 1.0),
        exemplar_provider=lambda: provided,
    )
    hist.observe(0.05)  # provider exemplar
    hist.observe(0.5, exemplar={"trace_id": "cafebabe"})  # explicit wins
    hist.observe(5.0)  # +Inf overflow bucket keeps exemplars too
    text = "\n".join(hist.expose(openmetrics=True))
    assert '# {trace_id="feedface"} 0.05' in text
    assert '# {trace_id="cafebabe"} 0.5' in text
    inf_line = next(
        line for line in text.splitlines() if 'le="+Inf"' in line
    )
    assert 'trace_id="feedface"' in inf_line
    # classic Prometheus text never carries exemplars
    assert "# {" not in "\n".join(hist.expose())


def test_exemplar_label_budget_is_enforced():
    huge = {"trace_id": "a" * 200}
    hist = Histogram("gofr_b_seconds", "b", buckets=(1.0,))
    hist.observe(0.5, exemplar=huge)
    assert "# {" not in "\n".join(hist.expose(openmetrics=True))
    both = {"trace_id": "b" * 60, "dispatch_id": "c" * 100}
    hist.observe(0.5, exemplar=both)
    text = "\n".join(hist.expose(openmetrics=True))
    assert "b" * 60 in text  # first label fits
    assert "c" * 100 not in text  # second would blow the 128-rune budget


def test_openmetrics_counter_family_and_eof():
    registry = Registry()
    registry.counter("gofr_x_total", "xs", labels=("k",)).inc(k="v")
    om = registry.expose(openmetrics=True)
    assert "# TYPE gofr_x counter" in om
    assert "# HELP gofr_x xs" in om
    assert 'gofr_x_total{k="v"} 1' in om
    assert om.rstrip().endswith("# EOF")
    prom = registry.expose()
    assert "# TYPE gofr_x_total counter" in prom
    assert "# EOF" not in prom


def test_openmetrics_le_is_canonical_float():
    registry = Registry()
    registry.histogram("gofr_f_seconds", "f", buckets=(1.0, 2.5)).observe(0.5)
    om = registry.expose(openmetrics=True)
    assert 'le="1.0"' in om
    assert 'le="2.5"' in om
    prom = registry.expose()
    assert 'le="1"' in prom  # classic text keeps the terse form


def test_histogram_percentile_interpolation():
    hist = Histogram("gofr_p_seconds", "p", buckets=(1.0, 2.0, 4.0))
    for v in (0.5,) * 5 + (1.5,) * 5:
        hist.observe(v)
    assert hist.percentile(0.5) == 1.0  # upper-bound default
    # interpolated: rank 5 of 10 sits at the very top of bucket (0, 1]
    assert hist.percentile(0.5, interpolate=True) == pytest.approx(1.0)
    assert hist.percentile(0.75, interpolate=True) == pytest.approx(1.5)
    assert hist.percentile(0.25, interpolate=True) == pytest.approx(0.5)


# -- unit: postmortem store ---------------------------------------------------

class _StubContainer:
    def __init__(self, registry):
        from gofr_tpu.telemetry import FlightRecorder

        self.metrics = registry
        self.telemetry = FlightRecorder(capacity=8, keep=4)
        self.timebase = TimebaseSampler(
            registry, interval_s=0.5, window_s=60.0, start=False
        )
        self.tpu = None


def _store(tmp_path, **kw):
    from gofr_tpu.postmortem import PostmortemStore

    registry = Registry()
    container = _StubContainer(registry)
    kw.setdefault("directory", str(tmp_path / "pm"))
    return PostmortemStore(container, **kw), container


def test_postmortem_bundle_contents_and_atomic_write(tmp_path):
    store, container = _store(tmp_path)
    container.timebase.sample_now()
    container.timebase.sample_now()
    record = container.telemetry.start("m", "/v1/x", trace_id="t1", activate=False)
    container.telemetry.finish(record)
    in_flight = container.telemetry.start(  # noqa: F841 - must stay referenced
        "m", "/v1/y", trace_id="t2", activate=False
    )
    path = store.write(reason="manual", force=True)
    assert path and os.path.exists(path)
    assert not [n for n in os.listdir(store.directory) if n.endswith(".tmp")]
    bundle = json.load(open(path))
    assert bundle["schema"] == "gofr-postmortem/1"
    assert bundle["reason"] == "manual"
    assert bundle["versions"]["gofr_tpu"]
    assert len(bundle["timebase"]) == 2
    assert [r["trace_id"] for r in bundle["requests"]] == ["t1"]
    assert [r["trace_id"] for r in bundle["requests_in_flight"]] == ["t2"]
    assert any(t["stack"] for t in bundle["threads"])


def test_postmortem_rate_limit_and_retention(tmp_path):
    store, _ = _store(tmp_path, keep=2, min_interval_s=3600.0)
    # a forced (operator) write never consumes the automatic budget: a
    # drill at t=0 must not suppress the wedge bundle at t=10
    assert store.write(reason="manual", force=True) is not None
    time.sleep(0.002)  # distinct filename timestamps (ms resolution)
    first = store.write(reason="wedged")
    assert first is not None
    assert store.write(reason="wedged") is None  # rate-limited
    for _ in range(3):
        time.sleep(0.002)
        assert store.write(reason="manual", force=True) is not None
    bundles = store.list()
    assert len(bundles) == 2  # retention pruned the oldest
    assert all(b["bytes"] > 0 for b in bundles)


def test_postmortem_failed_write_refunds_the_rate_limit(tmp_path):
    store, container = _store(tmp_path, min_interval_s=3600.0)
    container.timebase = object()  # snapshots() missing -> bundle raises
    assert store.write(reason="wedged") is None
    container.timebase = TimebaseSampler(
        container.metrics, interval_s=0.5, window_s=60.0, start=False
    )
    # the failure did not burn the hour-long budget
    assert store.write(reason="wedged") is not None


def test_postmortem_config_redacts_secrets(tmp_path, monkeypatch):
    from gofr_tpu.postmortem import _config_fingerprint

    monkeypatch.setenv("ADMIN_TOKEN", "hunter2")
    monkeypatch.setenv("MODEL_NAME", "echo")
    monkeypatch.setenv("GEN_STOP_TOKENS", "1,2")  # NOT a secret
    fp = _config_fingerprint()
    assert fp["keys"]["ADMIN_TOKEN"] == "<redacted>"
    assert fp["keys"]["MODEL_NAME"] == "echo"
    assert fp["keys"]["GEN_STOP_TOKENS"] == "1,2"
    assert "hunter2" not in json.dumps(fp)
    assert len(fp["fingerprint"]) == 16


def test_postmortem_wedge_listener_writes_async(tmp_path):
    from gofr_tpu.tpu.introspect import EngineState

    store, _ = _store(tmp_path)
    engine = EngineState()
    store.watch_engine(engine)
    engine.transition("serving")
    assert store.list() == []  # only wedged/failed trigger
    engine.transition("wedged", "dispatch 7 stalled")
    deadline = time.time() + 5.0
    while not store.list() and time.time() < deadline:
        time.sleep(0.01)
    bundles = store.list()
    assert len(bundles) == 1
    bundle = json.load(
        open(os.path.join(store.directory, bundles[0]["file"]))
    )
    assert bundle["reason"] == "wedged"
    assert bundle["detail"] == "dispatch 7 stalled"


# -- end-to-end: the acceptance spine over the echo app -----------------------

@pytest.fixture(scope="module")
def echo_app(tmp_path_factory):
    """Echo-model app with an armed watchdog, a fast timebase, and a
    postmortem dir — the full timebase/postmortem spine, no XLA."""
    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    pm_dir = str(tmp_path_factory.mktemp("postmortems"))
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
           "MODEL_NAME": "echo", "TOKENIZER": "byte",
           "BATCH_MAX_SIZE": "4", "BATCH_TIMEOUT_MS": "1",
           "FLIGHT_SLOW_MS": "60000",
           "TIMEBASE_INTERVAL_S": "0.05", "TIMEBASE_WINDOW_S": "60",
           "POSTMORTEM_DIR": pm_dir,
           # 0.7s injected stall: degraded at 0.15s, wedged at 0.45s
           "WATCHDOG_DISPATCH_TIMEOUT_S": "0.15"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cwd = os.getcwd()
    os.chdir(tmp_path_factory.mktemp("timebase_e2e"))
    try:
        app = gofr_tpu.new()
    finally:
        os.chdir(cwd)
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    register_openai_routes(app)
    app.start()
    yield app, f"http://127.0.0.1:{port}", pm_dir
    app.shutdown()


def _post(base, payload, path="/v1/chat/completions"):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read()), dict(resp.headers.items())


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["data"]


def _wait_snapshots(app, n=2, timeout=10.0):
    """Deterministic deflake: the sampler thread's first ticks can land
    arbitrarily late on a loaded CI host, so a fixed sleep of a few
    intervals flakes — poll until the ring actually holds ``n``
    snapshots (generous ceiling, returns the moment it's true)."""
    timebase = app.container.timebase
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if timebase.stats()["snapshots"] >= n:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"timebase never reached {n} snapshots within {timeout}s "
        f"(stats: {timebase.stats()})"
    )


def test_timeseries_endpoint_serves_series_and_rates(echo_app):
    app, base, _ = echo_app
    _post(base, {"messages": [{"role": "user", "content": "hi"}],
                 "max_tokens": 2, "temperature": 0})
    _wait_snapshots(app, n=2)
    out = _get(base, "/admin/timeseries?metric=gofr_http_requests_total")
    assert out["kind"] == "counter"
    assert out["series"], "no series for a counter that was incremented"
    assert all(len(s["points"]) >= 1 for s in out["series"])
    assert out["timebase"]["snapshots"] >= 2
    # labels filter narrows to the chat route
    filtered = _get(
        base,
        "/admin/timeseries?metric=gofr_http_requests_total"
        "&labels=path:/v1/chat/completions",
    )
    assert filtered["series"]
    assert all(
        s["labels"]["path"] == "/v1/chat/completions"
        for s in filtered["series"]
    )


def test_timeseries_endpoint_validates_params(echo_app):
    app, base, _ = echo_app
    for path in ("/admin/timeseries",
                 "/admin/timeseries?metric=gofr_nope_total",
                 "/admin/timeseries?metric=gofr_http_requests_total&window=-1",
                 "/admin/timeseries?metric=gofr_http_requests_total&labels=xx"):
        try:
            _get(base, path)
            raise AssertionError(f"expected 400 for {path}")
        except urllib.error.HTTPError as e:
            assert e.code == 400, path


def test_overview_is_one_page_ops_rollup(echo_app):
    app, base, _ = echo_app
    _post(base, {"messages": [{"role": "user", "content": "roll"}],
                 "max_tokens": 2, "temperature": 0})
    _wait_snapshots(app, n=2)
    out = _get(base, "/admin/overview")
    assert out["engine"]["state"] == "serving"
    assert out["model"] == "echo"
    assert out["timebase"]["snapshots"] >= 2
    assert "now" in out["req_per_sec"] and "trend" in out["req_per_sec"]
    assert "slo" in out and "models" in out["slo"]
    assert out["dispatches"]["total"] >= 1
    assert "watchdog" in out and "postmortems" in out


def test_stall_leaves_black_box_bundle_and_history(echo_app):
    """The acceptance spine: injected stall -> wedged -> a postmortem
    bundle on disk with the stalling dispatch_id, the in-flight flight
    record that rode it, >=2 timebase snapshots, and thread stacks;
    /admin/timeseries then serves a rate series spanning the incident;
    the OpenMetrics exposition carries an exemplar resolving to an
    /admin/requests row."""
    app, base, pm_dir = echo_app
    # warm traffic before the incident anchors the rate series: wait
    # for two MORE snapshots so the warm request's counter bump is
    # bracketed in the ring (same deflake discipline as _wait_snapshots)
    before = app.container.timebase.stats()["snapshots"]
    _post(base, {"messages": [{"role": "user", "content": "warm"}],
                 "max_tokens": 2, "temperature": 0})
    _wait_snapshots(app, n=before + 2)
    tpu = app.container.tpu
    stall_start = time.time()
    # supervisor off for the duration: this test pins the postmortem
    # layer's own evidence capture against a LIVE wedge (the recovery
    # rebuild path — including its bundle-before-quarantine order —
    # is covered by tests/test_recovery.py)
    tpu.recovery.enabled = False
    tpu.runner.stall_hook = lambda: time.sleep(0.7)
    try:
        worker = threading.Thread(
            target=lambda: _post(
                base,
                {"messages": [{"role": "user", "content": "stall"}],
                 "max_tokens": 1, "temperature": 0},
            ),
        )
        worker.start()
        bundle_path = None
        deadline = time.time() + 10.0
        while time.time() < deadline and bundle_path is None:
            names = [n for n in os.listdir(pm_dir)
                     if n.startswith("postmortem-") and n.endswith(".json")]
            if names:
                bundle_path = os.path.join(pm_dir, sorted(names)[0])
                break
            time.sleep(0.02)
        worker.join()
    finally:
        tpu.runner.stall_hook = None
        tpu.recovery.enabled = True
    stall_end = time.time()
    assert bundle_path, "wedge never produced a postmortem bundle"
    bundle = json.load(open(bundle_path))
    assert bundle["schema"] == "gofr-postmortem/1"
    assert bundle["reason"] == "wedged"
    # the stalling dispatch: flagged by the watchdog AND visible as
    # running on the timeline snapshot inside the bundle
    stalled = [w for w in bundle["engine"]["watchdog"]["watching"]
               if w["stalled"]]
    assert stalled, "bundle carries no stalled watchdog entry"
    stalled_ids = {w["dispatch_id"] for w in stalled}
    running = {d["dispatch_id"] for d in bundle["dispatches"]
               if d["status"] == "running"}
    assert stalled_ids & running
    # the flight record riding the wedge is in the bundle — with the
    # stalling dispatch_id already linked
    in_flight = bundle["requests_in_flight"]
    assert in_flight, "the wedged request's flight record is missing"
    assert any(
        set(r["dispatch_ids"]) & stalled_ids for r in in_flight
    ), (in_flight, stalled_ids)
    assert len(bundle["timebase"]) >= 2
    stacks = {t["name"]: t["stack"] for t in bundle["threads"]}
    assert len(stacks) >= 2
    assert any("stall_hook" in s for s in stacks.values()), (
        "no thread stack shows the stalled call"
    )
    # recovery, then: the timeseries ring spans the incident
    deadline = time.time() + 3.0
    while tpu.engine.state != "serving" and time.time() < deadline:
        time.sleep(0.02)
    assert tpu.engine.state == "serving"
    _wait_snapshots(
        app, n=app.container.timebase.stats()["snapshots"] + 2
    )
    out = _get(base, "/admin/timeseries?metric=gofr_http_requests_total")
    rates = [p for s in out["series"] for p in s["rate"]]
    assert rates, "no rate points derived"
    assert min(ts for ts, _ in rates) < stall_end
    assert max(ts for ts, _ in rates) > stall_start
    # OpenMetrics exemplar -> flight record join
    _, headers = _post(base, {
        "messages": [{"role": "user", "content": "exemplar"}],
        "max_tokens": 2, "temperature": 0,
    })
    req = urllib.request.Request(
        base + "/metrics",
        headers={"Accept": "application/openmetrics-text"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert "openmetrics-text" in resp.headers["Content-Type"]
        om = resp.read().decode()
    assert om.rstrip().endswith("# EOF")
    corr = headers["X-Correlation-ID"]
    exemplar_lines = [ln for ln in om.splitlines() if "# {" in ln]
    assert any(corr in ln for ln in exemplar_lines), (corr, exemplar_lines[:5])
    trace_ids = {r["trace_id"]
                 for r in _get(base, "/admin/requests?limit=500")["requests"]}
    assert corr in trace_ids


def test_manual_postmortem_trigger_and_listing(echo_app):
    app, base, pm_dir = echo_app
    req = urllib.request.Request(
        base + "/admin/postmortem",
        data=json.dumps({"detail": "operator drill"}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        out = json.loads(resp.read())["data"]
    assert out["reason"] == "manual"
    bundle = json.load(open(out["path"]))
    assert bundle["detail"] == "operator drill"
    listing = _get(base, "/admin/postmortem")
    assert listing["dir"] == pm_dir
    assert any(
        os.path.join(pm_dir, b["file"]) == out["path"]
        for b in listing["bundles"]
    )
