"""Golden-logits checkpoint fidelity (VERDICT r04 item 10).

A REAL HF checkpoint — a tiny random-weight ``LlamaForCausalLM`` written
by ``transformers.save_pretrained``, the actual ecosystem writer, NOT
this repo's own exporter (tests/test_ingest.py's round-trips are
circular by construction) — ingested through ``models/ingest.py`` must
teacher-force the same logits/logprobs the HF model computes with torch.
One test proves safetensors parsing, the weight mapping + transposes +
layer stacking, the RoPE split-half convention, GQA head grouping, RMS
norm semantics, and the SiLU MLP all agree with the HF ecosystem end to
end. A second proves the tokenizer against the ``tokenizers`` library on
a real tokenizer.json.

No network: the checkpoint and tokenizer are BUILT locally by the HF
libraries baked into the image — real formats, real writers, no
downloads.
"""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

# XLA-compile-dominated module: deselect with -m 'not slow'
pytestmark = pytest.mark.slow

PROMPT = [1, 5, 9, 33, 77, 2, 64, 100, 42, 7]


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """(checkpoint dir, HF logits [S, V] f32): a random HF Llama shaped
    EXACTLY like this repo's registered ``tiny`` config, so the serving
    device can load it by MODEL_NAME=tiny + MODEL_PATH."""
    from transformers import LlamaConfig, LlamaForCausalLM

    from gofr_tpu.models.llama import TINY

    hf_cfg = LlamaConfig(
        vocab_size=TINY.vocab_size, hidden_size=TINY.dim,
        intermediate_size=TINY.hidden_dim,
        num_hidden_layers=TINY.n_layers,
        num_attention_heads=TINY.n_heads,
        num_key_value_heads=TINY.n_kv_heads,
        max_position_embeddings=TINY.max_seq, rope_theta=TINY.rope_theta,
        rms_norm_eps=TINY.norm_eps, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    path = tmp_path_factory.mktemp("hf_ckpt")
    model.save_pretrained(str(path), safe_serialization=True)
    with torch.no_grad():
        logits = model(torch.tensor([PROMPT])).logits[0].float().numpy()
    return str(path), logits


def _gofr_cfg():
    from gofr_tpu.models.llama import TINY

    return TINY


def test_hf_checkpoint_golden_logits(hf_checkpoint):
    import jax.numpy as jnp

    from gofr_tpu.models.ingest import load_llama_params
    from gofr_tpu.models.transformer import transformer_forward

    path, hf_logits = hf_checkpoint
    cfg = _gofr_cfg()
    params = load_llama_params(path, cfg)
    ours = np.asarray(
        transformer_forward(params, jnp.asarray([PROMPT], jnp.int32), cfg)
    )[0]
    # absolute logits agree to f32 numerics (conftest pins highest matmul
    # precision); any convention mismatch — rope layout, norm order, GQA
    # grouping, transpose — diverges by O(1), not O(1e-3)
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-3, atol=2e-3)


def test_hf_checkpoint_golden_teacher_forced_logprobs(hf_checkpoint):
    """The serving-surface form of the same proof: device.score() (the
    completions echo+logprobs primitive) must reproduce HF's
    log p(t_i | t_<i) on the real checkpoint."""
    import os

    import torch.nn.functional as F

    path, hf_logits = hf_checkpoint
    want = F.log_softmax(torch.tensor(hf_logits), dim=-1).numpy()
    golden = [float(want[i - 1, PROMPT[i]]) for i in range(1, len(PROMPT))]

    from gofr_tpu.testutil import serving_device

    ckpt_file = os.path.join(path, "model.safetensors")
    with serving_device(MODEL_NAME="tiny", MODEL_PATH=ckpt_file) as dev:
        got = dev.score(PROMPT)
    np.testing.assert_allclose(got, golden, rtol=2e-3, atol=2e-3)


def test_tokenizer_matches_hf_tokenizers_library(tmp_path):
    """gofr's from_hf_json must encode EXACTLY like the ``tokenizers``
    library on a real byte-level-BPE tokenizer.json built BY that
    library (trained in-process on a tiny corpus — a real artifact, not
    a hand-written fixture)."""
    tokenizers = pytest.importorskip("tokenizers")

    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=300, special_tokens=["<s>", "</s>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "hello world, hello TPU serving",
        "pack my box with five dozen liquor jugs",
    ]
    tok.train_from_iterator(corpus, trainer)
    path = str(tmp_path / "tokenizer.json")
    tok.save(path)

    from gofr_tpu.tokenizer import Tokenizer as GofrTokenizer

    ours = GofrTokenizer.from_hf_json(path)
    for text in corpus + ["unseen zebra text!", "  spaces  and\ttabs"]:
        want = tok.encode(text).ids
        got = ours.encode(text)
        assert got == want, (text, got, want)
        assert ours.decode(got) == tok.decode(want, skip_special_tokens=False)
