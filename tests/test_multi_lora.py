"""Multi-LoRA serving: named adapter artifacts over one shared base,
selected per request; outputs must equal the merged-weights equivalent."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gofr_tpu.errors import InvalidParamError
from gofr_tpu.models.llama import TINY
from gofr_tpu.models.lora import (
    add_lora,
    apply_adapter,
    combine_lora,
    export_adapter,
    init_lora_train_state,
    make_lora_train_step,
    merge_lora,
)
from gofr_tpu.models.transformer import init_transformer
from gofr_tpu.testutil import serving_device
from gofr_tpu.training.checkpoint import save_params

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def adapter_paths(tmp_path_factory):
    """Two adapters trained differently over the SAME seeded base the
    serving device will rebuild (MODEL_NAME=tiny, key(0))."""
    root = tmp_path_factory.mktemp("adapters")
    base = init_transformer(jax.random.key(0), TINY)
    paths = {}
    for name, seed, steps in (("calm", 5, 6), ("wild", 9, 3)):
        wrapped = add_lora(base, jax.random.key(seed), rank=4)
        opt = optax.adam(5e-2)
        state = init_lora_train_state(wrapped, opt)
        step = make_lora_train_step(TINY, opt)
        tokens = jnp.asarray(
            np.random.RandomState(seed).randint(1, 200, (2, 16)), jnp.int32
        )
        for _ in range(steps):
            state, _ = step(state, tokens)
        path = str(root / name)
        save_params(path, export_adapter(state))
        paths[name] = (path, state)
    return base, paths


def test_adapter_requests_match_merged_weights(adapter_paths):
    base, paths = adapter_paths
    spec = ",".join(f"{n}={p}" for n, (p, _) in paths.items())
    with serving_device(LORA_ADAPTERS=spec, DECODE_CHUNK="4") as dev:
        prompt = [1, 2, 3]
        base_out = dev.generate(prompt, max_new_tokens=8)
        outs = {}
        for name, (_, state) in paths.items():
            got = dev.generate(prompt, max_new_tokens=8, adapter=name)
            outs[name] = got
            # oracle: merge the trained adapters into plain weights and
            # serve THOSE as the model
            merged = merge_lora(combine_lora(state["adapters"], state["rest"]))
            want = _greedy_reference(merged, prompt, 8)
            assert got == want, name
        # adapters actually change behavior vs base and vs each other
        assert outs["calm"] != base_out or outs["wild"] != base_out
        # base path still serves unadapted
        assert dev.generate(prompt, max_new_tokens=8) == base_out


def _greedy_reference(params, prompt, n):
    """Teacher-forcing greedy rollout via the full no-cache forward."""
    from gofr_tpu.models.transformer import transformer_forward

    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = transformer_forward(params, jnp.asarray([toks], jnp.int32), TINY)
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


def test_adapters_and_base_share_pool_chunk(adapter_paths):
    """Two adapters + the base decode CONCURRENTLY in one continuous-
    batching pool via the stacked adapter bank, and every stream matches
    its solo (pool-off) output token-for-token."""
    import threading

    _, paths = adapter_paths
    spec = ",".join(f"{n}={p}" for n, (p, _) in paths.items())
    prompt = [1, 2, 3]
    with serving_device(
        LORA_ADAPTERS=spec, DECODE_CHUNK="4", DECODE_POOL="off"
    ) as dev:
        want = {
            name: dev.generate(prompt, max_new_tokens=12, adapter=name)
            for name in (None, "calm", "wild")
        }
    with serving_device(
        LORA_ADAPTERS=spec, DECODE_CHUNK="4", DECODE_SLOTS="4",
        BATCH_MAX_SIZE="4",
    ) as dev:
        got: dict = {}
        errs: list = []
        barrier = threading.Barrier(3)

        def run(name):
            try:
                barrier.wait(timeout=60)
                got[name] = dev.generate(
                    prompt, max_new_tokens=12, adapter=name
                )
            except Exception as exc:  # surfaced below — threads must not hide it
                errs.append((name, exc))

        threads = [
            threading.Thread(target=run, args=(n,))
            for n in (None, "calm", "wild")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errs, errs
        assert got == want
        # the adapter executable actually carried chunks (no solo fallback)
        assert dev.decode_pool.lora_chunks > 0


def test_runtime_loads_rebuild_pool_bank(adapter_paths):
    """Runtime-loaded adapters join the pool bank; loads and unloads
    rebuild it and pooled outputs stay stable across rebuilds."""
    _, paths = adapter_paths
    (n1, (p1, _)), (n2, (p2, _)) = list(paths.items())
    with serving_device(DECODE_CHUNK="4", DECODE_SLOTS="4") as dev:
        dev.load_adapter(n1, p1)
        before = dev.decode_pool.lora_chunks
        out1 = dev.generate([1, 2, 3], max_new_tokens=8, adapter=n1)
        assert dev.decode_pool.lora_chunks > before  # pooled, not solo
        dev.load_adapter(n2, p2)  # bank rebuild (2 adapters)
        out2 = dev.generate([1, 2, 3], max_new_tokens=8, adapter=n2)
        assert len(out1) == len(out2) == 8
        dev.unload_adapter(n1)
        # n2 still pooled after the shrink rebuild
        before = dev.decode_pool.lora_chunks
        again = dev.generate([1, 2, 3], max_new_tokens=8, adapter=n2)
        assert again == out2
        assert dev.decode_pool.lora_chunks > before


def test_rank_mismatched_bank_disables_and_solos(adapter_paths, tmp_path):
    """A rank-mismatched adapter set cannot form one stacked bank: the
    pool bank disables (logged, never an error) and adapter requests
    SOLO with correct outputs — the fallback path the pool's queue.Full
    rejection feeds."""
    base, paths = adapter_paths
    name, (path, state) = next(iter(paths.items()))
    # a second adapter at a DIFFERENT rank over the same base
    wrapped = add_lora(base, jax.random.key(3), rank=2)
    opt = optax.adam(5e-2)
    st = init_lora_train_state(wrapped, opt)
    stepf = make_lora_train_step(TINY, opt)
    toks = jnp.asarray(
        np.random.RandomState(3).randint(1, 200, (2, 16)), jnp.int32
    )
    for _ in range(2):
        st, _ = stepf(st, toks)
    odd_path = str(tmp_path / "odd")
    save_params(odd_path, export_adapter(st))
    with serving_device(
        LORA_ADAPTERS=f"{name}={path},odd={odd_path}", DECODE_CHUNK="4",
        DECODE_SLOTS="4",
    ) as dev:
        assert sorted(dev.list_adapters()) == sorted([name, "odd"])
        # both adapters serve correctly — solo, since no bank exists
        merged = merge_lora(combine_lora(state["adapters"], state["rest"]))
        got = dev.generate([1, 2, 3], max_new_tokens=8, adapter=name)
        assert got == _greedy_reference(merged, [1, 2, 3], 8)
        assert len(dev.generate([1, 2], max_new_tokens=4, adapter="odd")) == 4
        assert dev.decode_pool.lora_chunks == 0  # never pooled
        # unloading the odd-rank adapter restores a uniform bank
        dev.unload_adapter("odd")
        dev.generate([1, 2, 3], max_new_tokens=8, adapter=name)
        assert dev.decode_pool.lora_chunks > 0


def test_unknown_adapter_rejected(adapter_paths):
    _, paths = adapter_paths
    name, (path, _) = next(iter(paths.items()))
    with serving_device(LORA_ADAPTERS=f"{name}={path}") as dev:
        with pytest.raises(InvalidParamError, match="adapter"):
            dev.generate([1, 2, 3], max_new_tokens=4, adapter="nope")


def test_malformed_adapter_spec_fails_fast():
    old = {k: os.environ.get(k) for k in ("MODEL_NAME", "LORA_ADAPTERS")}
    os.environ.update(MODEL_NAME="tiny", LORA_ADAPTERS="justapath")
    try:
        from gofr_tpu.config import EnvConfig
        from gofr_tpu.logging import Level
        from gofr_tpu.metrics import Registry
        from gofr_tpu.testutil import MockLogger
        from gofr_tpu.tpu.device import new_device

        with pytest.raises(ValueError, match="LORA_ADAPTERS"):
            new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_adapter_shares_base_arrays(adapter_paths):
    base, paths = adapter_paths
    name, (path, _) = next(iter(paths.items()))
    with serving_device(LORA_ADAPTERS=f"{name}={path}") as dev:
        wrapped = dev.runner.adapters[name]
        # the wrapped tree's base leaves ARE the served base arrays
        assert wrapped["layers"]["wq"]["w"] is dev.runner.params["layers"]["wq"]
        assert wrapped["embed"] is dev.runner.params["embed"]


def test_adapters_serve_over_w8a8_base(adapter_paths):
    """Multi-LoRA SERVING over a w8a8 base works (forward-only: the
    zero-gradient activation round only matters for training, which
    add_lora rejects). The adapter must still change behavior."""
    _, paths = adapter_paths
    name, (path, _) = next(iter(paths.items()))
    with serving_device(
        LORA_ADAPTERS=f"{name}={path}", MODEL_QUANT="w8a8", DECODE_CHUNK="4"
    ) as dev:
        assert set(dev.runner.params["layers"]["wq"]) == {"q8", "scale"}
        prompt = [1, 2, 3]
        base_t, base_lp = dev.generate(prompt, max_new_tokens=8, logprobs=True)
        ad_t, ad_lp = dev.generate(
            prompt, max_new_tokens=8, adapter=name, logprobs=True
        )
        assert len(ad_t) == 8
        # the adapter must actually reach the forward: token ids need not
        # flip (a few training steps may not move any greedy argmax), but
        # the chosen tokens' logprobs shift whenever the LoRA delta is
        # consumed — a silently-ignored adapter reproduces BOTH exactly
        assert (ad_t, ad_lp) != (base_t, base_lp)
        assert (ad_t, ad_lp) == dev.generate(
            prompt, max_new_tokens=8, adapter=name, logprobs=True
        )


def test_runtime_adapter_management(adapter_paths):
    """Adapters load/unload at RUNTIME (no restart): the swap is one
    dict assignment, new requests see it immediately, and errors are
    parameter errors, never 500s."""
    _, paths = adapter_paths
    (n1, (p1, _)), (n2, (p2, _)) = list(paths.items())
    with serving_device(DECODE_CHUNK="4") as dev:  # boots with NO adapters
        assert dev.list_adapters() == []
        with pytest.raises(InvalidParamError):
            dev.generate([1, 2, 3], max_new_tokens=4, adapter=n1)
        assert dev.load_adapter(n1, p1) == [n1]
        base = dev.generate([1, 2, 3], max_new_tokens=8, logprobs=True)
        a1 = dev.generate([1, 2, 3], max_new_tokens=8, adapter=n1,
                          logprobs=True)
        assert a1 != base  # the runtime-loaded adapter reaches the forward
        assert dev.load_adapter(n2, p2) == sorted([n1, n2])
        assert dev.unload_adapter(n1) == [n2]
        with pytest.raises(InvalidParamError):
            dev.generate([1, 2, 3], max_new_tokens=4, adapter=n1)
        with pytest.raises(InvalidParamError):
            dev.unload_adapter("nope")
        with pytest.raises(InvalidParamError):
            dev.load_adapter(n1, "/no/such/path")
        with pytest.raises(InvalidParamError):
            dev.load_adapter("", p1)


def test_admin_adapter_routes(adapter_paths, tmp_path):
    """The /admin/adapters surface over HTTP: token-gated, loads and
    unloads against a live server."""
    import json as _json
    import socket
    import urllib.error
    import urllib.request

    import gofr_tpu

    _, paths = adapter_paths
    name, (path, _) = next(iter(paths.items()))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {"HTTP_PORT": str(port), "LOG_LEVEL": "FATAL", "MODEL_NAME": "tiny",
           "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
           "ADMIN_TOKEN": "hunter2"}
    old = {k: os.environ.get(k) for k in env}
    # EnvConfig reads the live environment per get(): ADMIN_TOKEN must
    # stay set while requests run — ONE try restores it on every exit
    # path (incl. a failed boot), so nothing leaks into later tests
    os.environ.update(env)
    app = None
    cwd = os.getcwd()

    def call(method, route, payload=None, token="hunter2"):
        req = urllib.request.Request(
            base + route, method=method,
            data=_json.dumps(payload).encode() if payload is not None else None,
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {token}"} if token else {})},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, _json.loads(resp.read())

    try:
        os.chdir(tmp_path)
        try:
            app = gofr_tpu.new()
        finally:
            os.chdir(cwd)
        app.start()
        base = f"http://127.0.0.1:{app.http_port}"
        try:
            call("GET", "/admin/adapters", token=None)
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        status, body = call("GET", "/admin/adapters")
        assert (status, body["data"]["adapters"]) == (200, [])
        status, body = call("POST", "/admin/adapters",
                            {"name": name, "path": path})
        assert body["data"]["adapters"] == [name]
        status, body = call("DELETE", f"/admin/adapters/{name}")
        assert body["data"]["adapters"] == []
        try:
            call("DELETE", f"/admin/adapters/{name}")
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        try:
            call("POST", "/admin/adapters", {"name": "x"})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # OpenAI-conventional routing: /v1/models lists a loaded adapter
        # and "model": <adapter> selects it without the custom key
        from gofr_tpu.openai_compat import register_openai_routes

        register_openai_routes(app)
        status, body = call("POST", "/admin/adapters",
                            {"name": name, "path": path})
        req = urllib.request.Request(
            base + "/v1/models",
            headers={"Authorization": "Bearer hunter2"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            models = _json.loads(resp.read())
        ids = [m["id"] for m in models["data"]]
        assert "tiny" in ids and name in ids
        def openai(payload):
            r = urllib.request.Request(
                base + "/v1/completions", data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(r, timeout=120) as resp:
                return _json.loads(resp.read())
        via_model = openai({"model": name, "prompt": [1, 2, 3],
                            "max_tokens": 6, "temperature": 0,
                            "logprobs": 1})
        via_key = openai({"adapter": name, "prompt": [1, 2, 3],
                          "max_tokens": 6, "temperature": 0,
                          "logprobs": 1})
        base_out = openai({"prompt": [1, 2, 3], "max_tokens": 6,
                           "temperature": 0, "logprobs": 1})
        assert via_model["model"] == name  # served under the adapter name
        assert via_model["choices"][0]["logprobs"]["token_logprobs"] == \
            via_key["choices"][0]["logprobs"]["token_logprobs"]
        assert via_model["choices"][0]["logprobs"] != \
            base_out["choices"][0]["logprobs"]
        # an UNKNOWN model is a 404 like the real API — a gateway routed
        # to an unloaded adapter must never silently get the base model
        try:
            openai({"model": "ghost", "prompt": [1, 2], "max_tokens": 2})
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404 and "ghost" in e.read(300).decode()
        # an adapter named like the base would be unselectable: 400
        try:
            call("POST", "/admin/adapters", {"name": "tiny", "path": path})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400 and "collides" in e.read(300).decode()
    finally:
        if app is not None:
            app.shutdown()
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
