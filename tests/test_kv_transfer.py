"""Cross-replica KV handoff (fleet/kvwire.py + /admin/kv + the device
pull path): wire-format integrity units — every way a transfer stream
can lie is DETECTED, never installed — then compile-free e2e over real
sockets: a donor echo replica serves its cached block tables, a
receiver pulls/verifies/aliases them, and EVERY injected failure
(bit-flip, truncation, stall, eviction, dead donor) degrades to local
chunked prefill with a bit-identical result and the outcome counted on
``gofr_tpu_kv_transfer_total``."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from gofr_tpu.fleet import kvwire
from gofr_tpu.tpu.kv_blocks import (
    BlockPool,
    ForeignKVRejected,
    HostPagedKV,
    HostTokenArena,
)


# -- helpers -------------------------------------------------------------------

def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _post(url, payload, headers=None, timeout=15):
    send = {"Content-Type": "application/json"}
    send.update(headers or {})
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=send, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _entry_bytes(spec, payloads):
    return b"".join(kvwire.encode_entry(spec, payloads))


def _spec(payloads, **extra):
    spec = {"kind": "host-tokens", "block_tokens": 4,
            "prompt_hash": "ab" * 16, "length": 7,
            "n_blocks": len(payloads), "meta": {"length": 7}}
    spec.update(extra)
    return spec


# -- wire format: integrity units ---------------------------------------------

def test_wire_roundtrip_and_chunk_boundary_agnosticism():
    payloads = [b"abcd" * 4, b"wxyz"]
    raw = _entry_bytes(_spec(payloads), payloads)
    # whole-buffer decode
    header, got = kvwire.decode_stream([raw])
    assert got == payloads
    assert header["version"] == kvwire.WIRE_VERSION
    assert header["prompt_hash"] == "ab" * 16
    # byte-by-byte: frame boundaries never align with feed boundaries
    decoder = kvwire.WireDecoder()
    events = []
    for i in range(len(raw)):
        events.extend(decoder.feed(raw[i:i + 1]))
    decoder.finish()
    assert [e[0] for e in events] == ["header", "block", "block", "end"]
    assert [e[2] for e in events if e[0] == "block"] == payloads


def test_wire_bit_flip_fails_the_blocks_own_crc():
    payloads = [b"abcd" * 4, b"wxyz"]
    raw = bytearray(_entry_bytes(_spec(payloads), payloads))
    # flip one bit inside the SECOND block's payload (the last 16 bytes
    # are the trailer frame; the 4-byte payload sits just before it)
    flip_at = len(raw) - 18
    raw[flip_at] ^= 0x01
    with pytest.raises(kvwire.ChecksumMismatch, match="CRC"):
        kvwire.decode_stream([bytes(raw)])


def test_wire_truncation_is_detected_by_the_missing_trailer():
    payloads = [b"abcd" * 4, b"wxyz"]
    raw = _entry_bytes(_spec(payloads), payloads)
    for cut in (len(raw) - 17, len(raw) // 2, 30):
        with pytest.raises(kvwire.Truncated):
            kvwire.decode_stream([raw[:cut]])


def test_wire_trailer_count_mismatch_is_truncation():
    payloads = [b"abcd"]
    frames = [kvwire.encode_header(_spec(payloads)),
              kvwire.encode_block(0, payloads[0]),
              kvwire.encode_trailer(2)]  # promises a block that never came
    with pytest.raises(kvwire.Truncated, match="promises 2"):
        kvwire.decode_stream(frames)


def test_wire_mis_sized_trailer_stays_inside_the_error_contract():
    """A CRC-valid trailer whose payload is not exactly 4 bytes must be
    a KVWireError (corrupt), never a struct.error escaping the decoder
    contract."""
    import struct
    import zlib

    payloads = [b"abcd"]
    frames = list(kvwire.encode_entry(_spec(payloads), payloads))
    bad_payload = b"\x01\x00\x00"  # 3 bytes, CRC freshly computed
    frames[-1] = struct.pack(
        "<III", kvwire.END_INDEX, len(bad_payload), zlib.crc32(bad_payload)
    ) + bad_payload
    with pytest.raises(kvwire.ChecksumMismatch):
        kvwire.decode_stream([b"".join(frames)])


def test_wire_out_of_order_and_post_trailer_bytes_rejected():
    payloads = [b"abcd", b"efgh"]
    frames = [kvwire.encode_header(_spec(payloads)),
              kvwire.encode_block(1, payloads[1])]  # skipped index 0
    with pytest.raises(kvwire.ChecksumMismatch, match="out of order"):
        kvwire.decode_stream(frames)
    good = _entry_bytes(_spec(payloads), payloads)
    with pytest.raises(kvwire.ChecksumMismatch, match="after the trailer"):
        kvwire.decode_stream([good + b"x"])


def test_wire_version_skew_refused_before_any_payload():
    # bad magic
    with pytest.raises(kvwire.VersionSkew, match="magic"):
        kvwire.WireDecoder().feed(b"NOPE" + b"\x00" * 8)
    # wrong version number
    raw = kvwire.MAGIC + _u32(b'{"version":99}')
    with pytest.raises(kvwire.VersionSkew, match="99"):
        kvwire.WireDecoder().feed(raw)
    # unparseable / non-object headers
    for body in (b"not json", b"[1,2]"):
        with pytest.raises(kvwire.VersionSkew):
            kvwire.WireDecoder().feed(kvwire.MAGIC + _u32(body))
    # arena spec divergence
    header = {"kind": "host-tokens", "block_tokens": 8}
    with pytest.raises(kvwire.VersionSkew, match="block_tokens"):
        kvwire.check_spec(header, {"kind": "host-tokens", "block_tokens": 4})


def _u32(body: bytes) -> bytes:
    import struct

    return struct.pack("<I", len(body)) + body


def test_wire_oversized_claims_rejected():
    import struct

    head = struct.pack("<III", 0, kvwire.MAX_BLOCK_BYTES + 1, 0)
    decoder = kvwire.WireDecoder()
    decoder.feed(_entry_bytes(_spec([]), [])[: len(kvwire.MAGIC)])
    with pytest.raises(kvwire.KVWireError):
        # a frame claiming more than any block can hold is a framing
        # error the receiver must not buffer toward
        full = kvwire.WireDecoder()
        full.feed(kvwire.encode_header(_spec([])))
        full.feed(head)
    with pytest.raises(ValueError, match="bound"):
        kvwire.encode_block(0, b"x" * (kvwire.MAX_BLOCK_BYTES + 1))


def test_wire_frames_beyond_header_claim_rejected_before_buffering():
    """A donor streaming more frames than its header claims must be
    cut off at the first excess frame — NOT buffered until a post-hoc
    count check (that gap was an unbounded-memory hole)."""
    payloads = [b"abcd"]
    frames = [kvwire.encode_header(_spec(payloads)),
              kvwire.encode_block(0, payloads[0]),
              kvwire.encode_block(1, b"excess")]
    with pytest.raises(kvwire.ChecksumMismatch, match="claim"):
        kvwire.decode_stream(frames)
    # fewer blocks than claimed (consistent trailer) is truncation
    short = [kvwire.encode_header(_spec([b"abcd", b"efgh"])),
             kvwire.encode_block(0, b"abcd"),
             kvwire.encode_trailer(1)]
    with pytest.raises(kvwire.Truncated, match="short of the header"):
        kvwire.decode_stream(short)


def test_wire_header_claim_bounded_by_receiver_expectation():
    """The receiver knows how many blocks the prompt can need; a donor
    claiming more is refused at the header."""
    payloads = [b"abcd", b"efgh"]
    raw = _entry_bytes(_spec(payloads), payloads)
    with pytest.raises(kvwire.VersionSkew, match="at most 1"):
        kvwire.decode_stream([raw], max_blocks=1)
    header, got = kvwire.decode_stream([raw], max_blocks=2)
    assert got == payloads
    for bad in (None, -1, "2", 1.5, True):
        with pytest.raises(kvwire.VersionSkew, match="n_blocks"):
            kvwire.decode_stream(
                [_entry_bytes(_spec(payloads, n_blocks=bad), payloads)]
            )


def test_untrusting_replica_never_pulls(tmp_path, monkeypatch):
    """X-KV-Donor names a URL the replica will FETCH into its shared
    prefix cache: client-minted it is an SSRF/cache-poisoning
    primitive, so the device acts on it only under
    KV_TRANSFER_TRUST_HINT=on (the FLEET_TRUST_TENANT_HEADER
    contract). With the flag off (the production default for a
    client-facing replica), a request carrying X-KV-Donor completes
    normally via local prefill and NO pull ever leaves the replica —
    zero transfer outcomes, donor serves nothing."""
    from gofr_tpu.devtools.chaos import chaos_fleet

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2, per_replica_env=[
        {"FLEET_ROLE": "prefill"},
        {"FLEET_ROLE": "decode", "KV_TRANSFER_TRUST_HINT": "off"},
    ]) as (donor, recv):
        prompt = list(range(1, 40))
        _, clean = _post(donor.address + "/generate",
                         {"tokens": prompt, "max_new_tokens": 6})
        status, body = _post(
            recv.address + "/generate",
            {"tokens": prompt, "max_new_tokens": 6},
            headers={"X-KV-Donor": donor.address},
        )
        assert status == 200 and body == clean
        stats, _ = _xfer(recv)
        assert all(stats.get(k, 0) == 0 for k in kvwire.TRANSFER_OUTCOMES)
        donor_stats, _ = _xfer(donor)
        assert donor_stats["served"] == 0


def test_parse_kv_hint_accepts_only_peer_base_urls():
    ok = kvwire.parse_kv_hint
    assert ok("http://10.0.0.5:8000") == "http://10.0.0.5:8000"
    assert ok("https://replica-3.fleet.local") == "https://replica-3.fleet.local"
    assert ok(" http://r1:9000/ ") == "http://r1:9000"
    for bad in (
        None, "", "r1:8000", "ftp://r1", "http://", "http://r1/admin/kv",
        "http://user:pw@r1:8000", "http://r1:8000?x=1", "http://r1:8000#f",
        "http://r1:abc", "http://" + "a" * 300,
    ):
        assert ok(bad) is None, bad


def test_prompt_hash_matches_cache_key_hash():
    ids = np.asarray([5, 6, 7, 8], np.int32)
    assert kvwire.prompt_hash([5, 6, 7, 8]) == kvwire.hash_of_key(ids.tobytes())


# -- arena codec + install units ----------------------------------------------

def test_host_arena_export_ingest_roundtrip():
    arena = HostTokenArena(8, 4)
    pool = BlockPool(8, 4, arena=arena)
    ids = np.asarray([3, 1, 4, 1, 5, 9, 2], np.int32)  # boundary block short
    t = pool.reserve(ids.size)
    t.length = ids.size
    arena.write(t, 0, ids)
    payloads = [arena.export_block_payload(t, j) for j in range(2)]
    assert len(payloads[0]) == 16 and len(payloads[1]) == 12  # 4 + 3 tokens
    t2 = pool.reserve(ids.size)
    t2.length = ids.size
    for j, p in enumerate(payloads):
        arena.ingest_block_payload(t2, j, p)
    np.testing.assert_array_equal(arena.read(t2), ids)


def test_host_arena_ingest_rejects_malformed_payloads():
    arena = HostTokenArena(8, 4)
    pool = BlockPool(8, 4, arena=arena)
    t = pool.reserve(4)
    t.length = 4
    with pytest.raises(ForeignKVRejected, match="whole number"):
        arena.ingest_block_payload(t, 0, b"xyz")
    with pytest.raises(ForeignKVRejected, match="0 tokens"):
        arena.ingest_block_payload(t, 0, b"")
    with pytest.raises(ForeignKVRejected, match="5 tokens"):
        arena.ingest_block_payload(t, 0, b"\x01\x00\x00\x00" * 5)


def test_install_remote_verifies_readback_and_rolls_back():
    """Checksums guard the wire; the readback guards the CONTENT — a
    payload that decodes to different tokens than the prompt being
    admitted must be rejected AND leave no trace in the pool."""
    arena = HostTokenArena(8, 4)
    pool = BlockPool(8, 4, arena=arena)
    engine = HostPagedKV(pool, arena)
    ids = np.arange(1, 8, dtype=np.int32)
    wrong = np.asarray([9, 9, 9, 9], np.int32).tobytes()
    before = pool.stats()
    with pytest.raises(ForeignKVRejected, match="different token"):
        engine.install_remote(ids, [wrong, wrong[:12]], {})
    assert pool.stats() == before  # full rollback
    with pytest.raises(ForeignKVRejected, match="block payloads"):
        engine.install_remote(ids, [wrong], {})  # count mismatch
    assert pool.stats() == before


def test_install_remote_exhaustion_is_local_not_corrupt():
    arena = HostTokenArena(4, 4)
    pool = BlockPool(4, 4, arena=arena)
    engine = HostPagedKV(pool, arena)
    pool.alloc(4)  # nothing left
    ids = np.arange(1, 5, dtype=np.int32)
    assert engine.install_remote(ids, [ids.tobytes()], {}) is False


def test_install_remote_aliases_into_the_next_admit():
    """The point of the pull: after install, admitting the same prompt
    is a copy-free HIT."""
    arena = HostTokenArena(16, 4)
    pool = BlockPool(16, 4, arena=arena)
    engine = HostPagedKV(pool, arena)
    ids = np.arange(10, 21, dtype=np.int32)
    payloads = [
        np.ascontiguousarray(ids[j * 4:(j + 1) * 4]).tobytes()
        for j in range(3)
    ]
    assert engine.install_remote(ids, payloads, {}) is True
    seq = engine.admit(ids, max_new=2)
    assert seq.kind == "hit" and seq.aliased_blocks == 3
    np.testing.assert_array_equal(engine.prompt_tokens(seq), ids)
    engine.abort(seq)
    assert engine.install_remote(ids, payloads, {}) is True  # already warm


# -- e2e: pull, verify, ingest, fall back -------------------------------------

def _xfer(rep):
    snap = json.loads(_get(rep.address + "/admin/engine")[1])["data"]
    return snap["kv_transfer"], snap["kv_blocks"]


def test_transfer_ok_aliases_the_donor_prefix(tmp_path, monkeypatch):
    """Happy path over real sockets: the receiver pulls the donor's
    cached prompt blocks, installs them, and the request admits as a
    prefix HIT — outcome ``ok``, donor ``served`` counted, both pools
    balanced back to idle."""
    from gofr_tpu.devtools.chaos import chaos_fleet

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2, per_replica_env=[
        {"FLEET_ROLE": "prefill"}, {"FLEET_ROLE": "decode"},
    ]) as (donor, recv):
        prompt = list(range(1, 40))
        _, clean = _post(donor.address + "/generate",
                         {"tokens": prompt, "max_new_tokens": 6})
        hits_before = recv.app.container.tpu.runner.paged.prefix_stats["hits"]
        status, body = _post(
            recv.address + "/generate",
            {"tokens": prompt, "max_new_tokens": 6},
            headers={"X-KV-Donor": donor.address},
        )
        assert status == 200 and body == clean  # bit-identical
        stats, kv = _xfer(recv)
        assert stats["ok"] == 1 and stats["fallback"] == 0
        paged = recv.app.container.tpu.runner.paged
        assert paged.prefix_stats["hits"] == hits_before + 1  # aliased, not re-prefilled
        assert kv["active"] == 0 and kv["reserved"] == 0
        donor_stats, donor_kv = _xfer(donor)
        assert donor_stats["served"] == 1
        assert donor_kv["active"] == 0 and donor_kv["reserved"] == 0
        # the raw export decodes cleanly too (wire-format sanity on a
        # REAL http body, not a synthetic frame list)
        _, raw = _get(
            donor.address + "/admin/kv/" + kvwire.prompt_hash(prompt)
        )
        header, payloads = kvwire.decode_stream([raw])
        assert header["length"] == len(prompt)
        got = np.concatenate([
            np.frombuffer(p, np.int32) for p in payloads
        ])
        np.testing.assert_array_equal(got, np.asarray(prompt, np.int32))


def test_tokened_admin_plane_still_transfers(tmp_path, monkeypatch):
    """ADMIN_TOKEN gates /admin/kv on the donor; the receiver forwards
    the fleet-shared token on its pull, so a tokened fleet keeps
    transferring instead of silently 401ing every pull into
    ``timeout`` fallbacks (while the raw un-tokened curl stays 401)."""
    from gofr_tpu.devtools.chaos import chaos_fleet

    monkeypatch.chdir(tmp_path)
    # setenv, not chaos env=: _check_admin reads config LIVE at request
    # time, while chaos replicas swap env only at construction — the
    # process-wide var is what a tokened fleet actually looks like
    monkeypatch.setenv("ADMIN_TOKEN", "fleet-secret")
    with chaos_fleet(2, per_replica_env=[
        {"FLEET_ROLE": "prefill"}, {"FLEET_ROLE": "decode"},
    ]) as (donor, recv):
        prompt = list(range(1, 40))
        _, clean = _post(donor.address + "/generate",
                         {"tokens": prompt, "max_new_tokens": 6})
        status, body = _post(
            recv.address + "/generate",
            {"tokens": prompt, "max_new_tokens": 6},
            headers={"X-KV-Donor": donor.address},
        )
        assert status == 200 and body == clean  # pulled, bit-identical
        stats = recv.app.container.tpu.kv_transfer_stats
        assert stats["ok"] == 1 and stats["fallback"] == 0
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(donor.address + "/admin/kv/" + kvwire.prompt_hash(prompt))
        assert err.value.code == 401


def test_transfer_failures_all_fall_back_bit_identical(tmp_path, monkeypatch):
    """The robustness matrix on one fleet: bit-flip → ``corrupt``,
    truncation → ``corrupt``, donor stall → ``timeout``, evicted/never-
    seen → ``evicted``, donor listener dead → ``timeout`` — EVERY case
    completes via local prefill with output identical to a clean run,
    and the receiver's pool balances to idle (no leaked blocks)."""
    from gofr_tpu.devtools.chaos import chaos_fleet

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2, per_replica_env=[
        {"FLEET_ROLE": "prefill"}, {"FLEET_ROLE": "decode"},
    ], env={"KV_TRANSFER_TIMEOUT_S": "1"}) as (donor, recv):
        def run(prompt, expect, warm=True, **chaos):
            if warm:
                _, clean = _post(donor.address + "/generate",
                                 {"tokens": prompt, "max_new_tokens": 6})
            else:
                # clean reference from the receiver itself (dedup vs
                # warm: the donor may be unreachable in this case)
                clean = None
            if chaos:
                donor.chaos.corrupting_proxy(**chaos)
            status, body = _post(
                recv.address + "/generate",
                {"tokens": prompt, "max_new_tokens": 6},
                headers={"X-KV-Donor": donor.address}, timeout=20,
            )
            assert status == 200
            if clean is not None:
                assert body == clean, f"{expect}: fallback not bit-identical"
            return body

        base = 0
        stats = lambda: _xfer(recv)[0]  # noqa: E731

        run(list(range(1, 40)), "corrupt",
            mode="flip", n=1, after_bytes=280)
        assert stats()["corrupt"] == 1 and stats()["fallback"] == 1

        run(list(range(100, 140)), "corrupt",
            mode="truncate", n=1, after_bytes=100)
        assert stats()["corrupt"] == 2 and stats()["fallback"] == 2

        run(list(range(200, 260)), "timeout",
            mode="stall", n=1, after_bytes=50, stall_s=4.0)
        assert stats()["timeout"] == 1 and stats()["fallback"] == 3

        # never cached on the donor: 404 → evicted
        run(list(range(500, 540)), "evicted", warm=False)
        assert stats()["evicted"] == 1 and stats()["fallback"] == 4

        donor.stop_listener()
        run(list(range(600, 640)), "timeout", warm=False)
        assert stats()["timeout"] == 2 and stats()["fallback"] == 5
        assert stats()["ok"] == 0

        # zero refcount leaks: the receiver's pool is idle again
        _, kv = _xfer(recv)
        assert kv["active"] == 0 and kv["reserved"] == 0
        # and the counter is on /metrics with every outcome label
        _, metrics = _get(recv.address + "/metrics")
        text = metrics.decode()
        for outcome, value in (("corrupt", 2), ("timeout", 2),
                               ("evicted", 1), ("fallback", 5)):
            assert (f'gofr_tpu_kv_transfer_total{{outcome="{outcome}"}} '
                    f"{value}") in text


def test_transfer_export_respects_deadline_and_disable(tmp_path, monkeypatch):
    """The donor side honors the PR 10 deadline budget (an expired
    budget truncates the stream — which the receiver's trailer check
    catches), and KV_TRANSFER=off 404s both directions."""
    from gofr_tpu.devtools.chaos import chaos_fleet

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(2, per_replica_env=[
        {}, {"KV_TRANSFER": "off"},
    ]) as (donor, off):
        prompt = list(range(1, 40))
        _post(donor.address + "/generate", {"tokens": prompt, "max_new_tokens": 2})
        phash = kvwire.prompt_hash(prompt)
        # a small budget made DETERMINISTIC by the chaos clock: the
        # slow-loris delays each export chunk 50ms, so the per-block
        # deadline check inside the export generator is guaranteed to
        # see the 5ms budget spent after the header frame — the old
        # shape (1ms budget + a 2ms sleep) raced the server streaming
        # the whole single-block entry inside the budget and flaked
        donor.chaos.slow_loris(0.05, paths=("/admin/kv/",))
        req = urllib.request.Request(
            donor.address + f"/admin/kv/{phash}",
            headers={"X-Request-Deadline-Ms": "5"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            raw = r.read()
        donor.chaos.clear("slow_loris")
        with pytest.raises(kvwire.Truncated):
            kvwire.decode_stream([raw])
        # transfer off: the export surface does not exist
        _post(off.address + "/generate", {"tokens": prompt, "max_new_tokens": 2})
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(off.address + f"/admin/kv/{phash}")
        assert err.value.code == 404
        # and the off replica never pulls despite a hint
        status, _ = _post(
            off.address + "/generate",
            {"tokens": list(range(50, 70)), "max_new_tokens": 2},
            headers={"X-KV-Donor": donor.address},
        )
        assert status == 200
        stats, _ = _xfer(off)
        assert all(
            stats[k] == 0
            for k in ("ok", "timeout", "corrupt", "evicted", "fallback")
        )
        assert stats["enabled"] is False
        # donor-side pins all released (aborted deadline stream included)
        _, donor_kv = _xfer(donor)
        assert donor_kv["active"] == 0 and donor_kv["reserved"] == 0


def test_malformed_donor_hints_degrade_to_local_prefill(tmp_path, monkeypatch):
    """A garbage X-KV-Donor header must never 4xx or stall a request —
    it parses to None and the request serves locally with no transfer
    accounting at all."""
    from gofr_tpu.devtools.chaos import chaos_fleet

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(1) as (rep,):
        for hint in ("not-a-url", "ftp://r1:80", "http://e@vil:80",
                     "http://peer:9/path"):
            status, _ = _post(
                rep.address + "/generate",
                {"tokens": [1, 2, 3], "max_new_tokens": 2},
                headers={"X-KV-Donor": hint},
            )
            assert status == 200
        stats, _ = _xfer(rep)
        assert all(
            stats[k] == 0
            for k in ("ok", "timeout", "corrupt", "evicted", "fallback")
        )
