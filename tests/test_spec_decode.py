"""Greedy speculative decoding: draft-and-verify must be bit-identical to
plain greedy decode whatever the draft proposes — the draft only sets the
acceptance rate, never the output."""

import os
import threading

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import new_device

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def _device(**env):
    defaults = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1"}
    defaults.update(env)
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry()), old
    except BaseException:
        _restore(old)  # a failed boot must not leak env into later tests
        raise


def _restore(old):
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


@pytest.fixture(scope="module")
def plain():
    dev, old = _device(DECODE_POOL="off", DECODE_CHUNK="4")
    yield dev
    dev.close()
    _restore(old)


@pytest.fixture(scope="module")
def spec():
    # draft "tiny" for target "tiny" but seeded differently (the engine
    # inits drafts from key(1)): real accept AND reject traffic
    dev, old = _device(DRAFT_MODEL_NAME="tiny", DRAFT_TOKENS="4",
                       DECODE_POOL="off", DECODE_CHUNK="4")
    yield dev
    dev.close()
    _restore(old)


def test_spec_exactly_matches_plain_greedy(plain, spec):
    for prompt, n in (([1, 2, 3], 12), ([7] * 30, 6), ([42], 1), ([5, 6], 17)):
        assert spec.generate(prompt, max_new_tokens=n) == \
            plain.generate(prompt, max_new_tokens=n), (prompt, n)


def test_spec_engine_actually_ran(spec):
    spec.generate([9, 8, 7], max_new_tokens=10)
    stats = spec.runner.spec_stats
    assert stats["cycles"] > 0 and stats["drafted"] >= stats["accepted"] >= 0
    # acceptance gauge exposed after generate
    text = spec.metrics.expose()
    assert any(
        line.startswith('gofr_tpu_spec_acceptance{model="tiny"}')
        for line in text.splitlines()
    ), text


def test_spec_respects_stop_tokens(plain, spec):
    full = plain.generate([1, 2, 3], max_new_tokens=10)
    stop_tok = full[5]
    want = full[: full.index(stop_tok)]
    assert spec.generate([1, 2, 3], max_new_tokens=10,
                         stop_tokens=[stop_tok]) == want


def test_spec_streams_and_cancels(spec):
    stop = threading.Event()
    seen = []

    def on_token(t):
        seen.append(t)
        if len(seen) >= 3:
            stop.set()

    out = spec.generate([1, 2, 3], max_new_tokens=200, on_token=on_token,
                        stop=stop)
    assert out == seen
    assert 3 <= len(out) < 200


def test_spec_cache_capacity_tail(plain, spec):
    # tiny max_seq=128; a near-full prompt forces the plain-step tail path
    prompt = list(range(1, 120))
    assert spec.generate(prompt, max_new_tokens=50) == \
        plain.generate(prompt, max_new_tokens=50)


def test_seeded_requests_skip_spec(spec):
    # SEEDED sampled requests bypass the draft (exact per-request key
    # sequence); unseeded sampled ones take speculative sampling — see
    # tests/test_spec_sampling.py
    from gofr_tpu.ops.sampling import Sampler

    before = dict(spec.runner.spec_stats)
    s = Sampler(temperature=1.0, seed=3)
    out = spec.generate([1, 2, 3], max_new_tokens=5, sampler=s)
    assert len(out) == 5
    assert spec.runner.spec_stats == before  # seeded path never drafts


def test_spec_overlong_prompt_chunks_like_target():
    # prompt longer than the largest bucket: both target and draft prefill
    # CHUNKED through the top bucket; spec must still match plain exactly
    plain_dev, old1 = _device(DECODE_POOL="off", MODEL_BUCKETS="64")
    spec_dev, old2 = _device(DRAFT_MODEL_NAME="tiny", DECODE_POOL="off",
                             MODEL_BUCKETS="64")
    try:
        prompt = [(i % 9) + 1 for i in range(100)]
        assert spec_dev.generate(prompt, max_new_tokens=8) == \
            plain_dev.generate(prompt, max_new_tokens=8)
    finally:
        plain_dev.close()
        spec_dev.close()
        # reverse order: old2 snapshotted values old1's _device had set
        _restore(old2)
        _restore(old1)


def test_draft_tokens_must_allow_acceptance():
    env = {"MODEL_NAME": "tiny", "DRAFT_MODEL_NAME": "tiny", "DRAFT_TOKENS": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with pytest.raises(ValueError, match=">= 2"):
            new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        _restore(old)


def test_vocab_mismatch_fails_fast():
    # "small" has a different vocab than "tiny": must raise, not mis-verify
    env = {"MODEL_NAME": "tiny", "DRAFT_MODEL_NAME": "small"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with pytest.raises(ValueError, match="vocab"):
            new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        _restore(old)


def test_unknown_draft_name_fails_fast():
    env = {"MODEL_NAME": "tiny", "DRAFT_MODEL_NAME": "nope"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        with pytest.raises(ValueError, match="DRAFT_MODEL_NAME"):
            new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        _restore(old)


def test_spec_generation_seeds_conversation_kv(plain):
    """A speculative generation seeds the prefix cache with the whole
    conversation (DRAFT deployments are the latency-mode chat shape):
    the follow-up turn partial-hits and stays bit-identical to plain
    greedy."""
    dev, old = _device(
        DRAFT_MODEL_NAME="tiny", DRAFT_TOKENS="4", DECODE_POOL="off",
        DECODE_CHUNK="4", PREFIX_CACHE="4", PREFIX_LCP_MIN="4",
    )
    try:
        turn1 = [7, 3, 9, 2, 11, 5, 61, 62]
        reply = dev.generate(turn1, max_new_tokens=8)
        assert reply == plain.generate(turn1, max_new_tokens=8)
        followup = turn1 + reply + [71, 72]
        want = plain.generate(followup, max_new_tokens=6)
        before = dict(dev.runner.prefix_stats)
        got = dev.generate(followup, max_new_tokens=6)
        assert got == want
        assert (
            dev.runner.prefix_stats["partial_hits"]
            == before["partial_hits"] + 1
        )
    finally:
        dev.close()
        _restore(old)


# -- pooled speculative decoding (SPEC_POOLED, tpu/spec_pool.py) ---------------

@pytest.fixture(scope="module")
def pooled_plain():
    dev, old = _device(DECODE_SLOTS="4", DECODE_CHUNK="4")
    yield dev
    dev.close()
    _restore(old)


@pytest.fixture(scope="module")
def pooled_spec():
    dev, old = _device(SPEC_POOLED="on", SPEC_K_MAX="4",
                       DECODE_SLOTS="4", DECODE_CHUNK="4")
    yield dev
    dev.close()
    _restore(old)


def test_pooled_spec_bit_identical_to_plain_pool(pooled_plain, pooled_spec):
    """The tentpole invariant on the real executables: speculation
    through the continuous-batching pool emits exactly the plain pooled
    stream — n-gram drafts only move tokens-per-dispatch."""
    for prompt, n in (([1, 2, 3], 12), ([7] * 30, 24), ([42], 8),
                      ([5, 6], 17)):
        assert pooled_spec.generate(prompt, max_new_tokens=n) == \
            pooled_plain.generate(prompt, max_new_tokens=n), (prompt, n)


def test_pooled_spec_cycles_fire_and_are_observable(pooled_spec):
    from gofr_tpu.telemetry import FlightRecord, activate_record

    record = FlightRecord("tiny", "test")
    activate_record(record)
    try:
        pooled_spec.generate([7] * 30, max_new_tokens=24)
    finally:
        activate_record(None)
    assert record.spec_dispatches > 0
    assert record.tokens_per_dispatch > 1.0
    text = pooled_spec.metrics.expose()
    assert 'gofr_tpu_spec_accept_ratio{model="tiny"}' in text
    assert 'gofr_tpu_spec_tokens_per_dispatch{model="tiny"}' in text
    assert pooled_spec.decode_pool.occupancy()["spec"] == {
        "k_max": 4, "ngram": True,
    }


def test_pooled_spec_concurrent_streams(pooled_plain, pooled_spec):
    """Co-tenant rows share one batched verify; every stream still
    emits its own plain-pool sequence."""
    prompts = ([1, 2, 3], [7] * 30, [42, 9], [5, 6])
    want = [pooled_plain.generate(p, max_new_tokens=14) for p in prompts]
    results = [None] * len(prompts)

    def run(i):
        results[i] = pooled_spec.generate(prompts[i], max_new_tokens=14)

    threads = [
        threading.Thread(target=run, args=(i,))
        for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == want


def test_pooled_spec_mixed_cohort_with_sampled_co_tenant(
    pooled_plain, pooled_spec
):
    """An unseeded sampled co-tenant is pool-eligible but NOT
    spec-eligible: the cohort decodes plain chunks while it is active,
    and the greedy stream's output must not move."""
    from gofr_tpu.ops.sampling import Sampler

    want = pooled_plain.generate([1, 2, 3], max_new_tokens=12)
    results = {}

    def greedy():
        results["g"] = pooled_spec.generate([1, 2, 3], max_new_tokens=12)

    def sampled():
        results["s"] = pooled_spec.generate(
            [9, 8], max_new_tokens=12, sampler=Sampler(temperature=1.0)
        )

    ts = [threading.Thread(target=greedy), threading.Thread(target=sampled)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results["g"] == want
    assert len(results["s"]) == 12


def test_pooled_spec_stop_tokens(pooled_plain, pooled_spec):
    full = pooled_plain.generate([7] * 30, max_new_tokens=16)
    stop_tok = full[7]
    want = full[: full.index(stop_tok)]
    assert pooled_spec.generate([7] * 30, max_new_tokens=16,
                                stop_tokens=[stop_tok]) == want


def test_pooled_spec_stands_down_solo_draft_mode():
    """SPEC_POOLED + DRAFT_MODEL_NAME: pooled speculation wins for
    pool-eligible requests (the solo latency mode would bypass the
    pool), and output still matches plain pooled decode."""
    plain_dev, old1 = _device(DECODE_SLOTS="2", DECODE_CHUNK="4")
    both_dev, old2 = _device(DRAFT_MODEL_NAME="tiny", DRAFT_TOKENS="4",
                             SPEC_POOLED="on", DECODE_SLOTS="2",
                             DECODE_CHUNK="4")
    try:
        want = plain_dev.generate([5, 6], max_new_tokens=10)
        before = dict(both_dev.runner.spec_stats)
        assert both_dev.generate([5, 6], max_new_tokens=10) == want
        # the solo draft engine never ran — the pool speculated instead
        assert both_dev.runner.spec_stats == before
    finally:
        plain_dev.close()
        both_dev.close()
        _restore(old2)
        _restore(old1)
