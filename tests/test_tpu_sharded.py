"""Multi-chip serving (TPU_MESH): the tp/dp-sharded transformer runner
must produce the same logits and the same generated tokens as the
single-chip runner — sharding is a placement decision, not a numerics one.
Runs on the virtual 8-device CPU mesh (conftest)."""

import os

import numpy as np
import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import _mesh_from_topology, new_device

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

PROMPT = {"tokens": [3, 1, 4, 1, 5, 9, 2, 6]}


def _device(**env):
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return new_device(EnvConfig(), MockLogger(Level.DEBUG), Registry())
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


@pytest.fixture(scope="module")
def plain():
    d = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                TPU_MESH="")
    yield d
    d.close()


@pytest.fixture(scope="module")
def sharded():
    d = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                TPU_MESH="tp=2")
    yield d
    d.close()


def test_topology_parsing():
    import jax

    devs = jax.devices()
    mesh = _mesh_from_topology("tp=2,dp=2", devs)
    assert mesh.shape["tp"] == 2 and mesh.shape["dp"] == 2
    assert _mesh_from_topology("", devs) is None
    # TPU VMs export TPU_TOPOLOGY as a physical grid ("1x1"); not a mesh ask
    assert _mesh_from_topology("1x1", devs) is None
    with pytest.raises(ValueError, match="needs"):
        _mesh_from_topology("tp=64", devs)
    with pytest.raises(ValueError, match="not supported"):
        _mesh_from_topology("pp=2", devs)


def test_params_actually_sharded(sharded):
    wq = sharded.runner.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 2
    assert "mesh" in sharded.describe()


def test_int4_multigroup_scale_shards_with_weight():
    # a row-parallel int4 weight with several scale groups: the scale's
    # group axis must shard over tp exactly like the weight's in axis
    import jax
    import jax.numpy as jnp

    from gofr_tpu.models.quant import dequantize_array_int4, quantize_array_int4
    from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
    from gofr_tpu.parallel.sharding import param_specs, shard_params

    w = jax.random.normal(jax.random.key(0), (256, 64), jnp.float32)
    tree = {"w_down": quantize_array_int4(w, group=64)}  # 4 groups
    specs = param_specs(tree)
    assert specs["w_down"]["scale"] == specs["w_down"]["q4"]
    mesh = make_mesh(mesh_shape_for(2, tp=2), devices=jax.devices()[:2])
    placed = shard_params(tree, mesh)
    assert len(placed["w_down"]["q4"].sharding.device_set) == 2
    assert len(placed["w_down"]["scale"].sharding.device_set) == 2
    np.testing.assert_allclose(
        np.asarray(dequantize_array_int4(placed["w_down"], jnp.float32)),
        np.asarray(dequantize_array_int4(tree["w_down"], jnp.float32)),
    )


def test_int4_sharded_matches_plain():
    # int4-packed weights shard (q4 like the weight, scale groups along the
    # in axis) and serve the same tokens as the unsharded int4 runner
    plain4 = _device(MODEL_NAME="tiny", MODEL_QUANT="int4", BATCH_MAX_SIZE="4",
                     BATCH_TIMEOUT_MS="1", TPU_MESH="")
    sharded4 = _device(MODEL_NAME="tiny", MODEL_QUANT="int4", BATCH_MAX_SIZE="4",
                       BATCH_TIMEOUT_MS="1", TPU_MESH="tp=2")
    try:
        wq = sharded4.runner.params["layers"]["wq"]
        assert len(wq["q4"].sharding.device_set) == 2
        want = plain4.generate(PROMPT["tokens"], max_new_tokens=8)
        got = sharded4.generate(PROMPT["tokens"], max_new_tokens=8)
        assert got == want
    finally:
        plain4.close()
        sharded4.close()


def test_sharded_infer_matches_plain(plain, sharded):
    a = plain.infer(PROMPT)
    b = sharded.infer(PROMPT)
    np.testing.assert_allclose(
        np.asarray(a["logits"]), np.asarray(b["logits"]), rtol=1e-4, atol=1e-4
    )


def test_sharded_generate_matches_plain(plain, sharded):
    a = plain.generate(PROMPT["tokens"], max_new_tokens=8)
    b = sharded.generate(PROMPT["tokens"], max_new_tokens=8)
    assert a == b


def test_dp_tp_mesh_infer():
    d = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                TPU_MESH="tp=2,dp=2")
    try:
        out = d.infer(PROMPT)
        assert np.isfinite(np.asarray(out["logits"])).all()
        assert d.health_check().status == "UP"
    finally:
        d.close()


def test_pool_active_under_mesh(sharded):
    # continuous batching no longer disabled by a mesh (round-2 verdict #4)
    assert sharded.decode_pool is not None


def test_pooled_sharded_matches_solo_sharded(sharded):
    solo = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                   TPU_MESH="tp=2", DECODE_POOL="off")
    try:
        assert solo.decode_pool is None
        a = solo.generate(PROMPT["tokens"], max_new_tokens=8)
    finally:
        solo.close()
    b = sharded.generate(PROMPT["tokens"], max_new_tokens=8)
    assert a == b


def test_pooled_generate_under_dp_mesh():
    d = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                TPU_MESH="tp=2,dp=2", DECODE_SLOTS="4")
    try:
        assert d.decode_pool is not None  # 4 slots over dp*fsdp=2
        out = d.generate(PROMPT["tokens"], max_new_tokens=6)
        assert len(out) == 6
    finally:
        d.close()


def test_pool_disabled_on_indivisible_slots():
    d = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                TPU_MESH="tp=2,dp=4", DECODE_SLOTS="3")
    try:
        assert d.decode_pool is None  # 3 slots can't shard over dp=4
    finally:
        d.close()


def test_kv_head_divisibility_enforced():
    with pytest.raises(ValueError, match="n_kv_heads"):
        _device(MODEL_NAME="tiny", TPU_MESH="tp=4")  # tiny has 2 kv heads


def test_batch_divisibility_enforced():
    # next_pow2(2)=2 rows can't shard over dp=4: clear config-time error,
    # not an opaque device_put failure inside warmup
    with pytest.raises(ValueError, match="BATCH_MAX_SIZE"):
        _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="2", TPU_MESH="dp=4")


def test_penalized_pool_under_tp_mesh():
    """The per-slot penalty machinery (presence/counts/bias rows, AOT
    penalized executable) composes with a tensor-parallel serving mesh:
    penalized pooled output equals the solo sharded path's, and logprobs
    still ride the chunks."""
    from gofr_tpu.ops.sampling import Sampler

    pen = dict(presence_penalty=2.0, frequency_penalty=2.0)
    solo = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="2",
                   BATCH_TIMEOUT_MS="1", TPU_MESH="tp=2", DECODE_POOL="off")
    try:
        want = solo.generate(PROMPT["tokens"], max_new_tokens=8,
                             sampler=Sampler(**pen))
    finally:
        solo.close()
    pooled = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="2",
                     BATCH_TIMEOUT_MS="1", TPU_MESH="tp=2",
                     DECODE_POOL_PENALTIES="eager")
    try:
        got = pooled.generate(PROMPT["tokens"], max_new_tokens=8,
                              sampler=Sampler(**pen))
        assert got == want
        toks, lps, tops = pooled.generate(
            PROMPT["tokens"], max_new_tokens=4, logprobs=True,
            top_logprobs=True, sampler=Sampler(**pen),
        )
        assert len(toks) == len(lps) == len(tops) == 4
    finally:
        pooled.close()


def test_penalized_pool_lazy_under_dp_mesh():
    """The LAZY penalty build under a dp mesh: plain pooled traffic runs
    first (GSPMD would otherwise leave the fed-back token row sharded
    over dp), then a penalized request triggers the background build and
    a later one must POOL without a sharding mismatch — the exact crash
    a lazily built executable hit when it trusted live shardings."""
    import time

    from gofr_tpu.ops.sampling import Sampler

    pen = dict(presence_penalty=2.0, frequency_penalty=2.0)
    d = _device(MODEL_NAME="tiny", BATCH_MAX_SIZE="4", BATCH_TIMEOUT_MS="1",
                TPU_MESH="dp=2", DECODE_SLOTS="4")
    try:
        plain = d.generate(PROMPT["tokens"], max_new_tokens=8)
        first = d.generate(PROMPT["tokens"], max_new_tokens=8,
                           sampler=Sampler(**pen))  # solos; kicks the build
        for _ in range(600):
            if d.decode_pool._pen_ready:
                break
            time.sleep(0.1)
        assert d.decode_pool._pen_ready
        pooled = d.generate(PROMPT["tokens"], max_new_tokens=8,
                            sampler=Sampler(**pen))
        assert pooled == first  # greedy: pooled == solo
        # plain traffic still clean after the penalized interlude
        assert d.generate(PROMPT["tokens"], max_new_tokens=8) == plain
    finally:
        d.close()
