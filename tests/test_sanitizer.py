"""Unit tests for the runtime concurrency sanitizer
(gofr_tpu/devtools/sanitizer.py): lock-order cycle detection with both
stacks, reentrancy, Condition compatibility, hold-time warnings,
install/uninstall, and the thread-leak detector + allowlist.

These run in the PLAIN tier-1 suite (no GOFR_SANITIZE needed): the
wrappers are constructed directly. The conftest fixture wires the same
machinery across the whole suite when GOFR_SANITIZE=1."""

import threading
import time

import pytest

from gofr_tpu.devtools import sanitizer


@pytest.fixture(autouse=True)
def _fresh_sanitizer_state():
    """Deliberate violations below must never leak into the suite-wide
    GOFR_SANITIZE verdict (this teardown runs before the conftest
    fixture's drain)."""
    sanitizer.reset()
    yield
    sanitizer.reset()


# -- lock-order graph ---------------------------------------------------------

def test_opposite_order_acquisition_is_a_potential_deadlock():
    a = sanitizer.sanitized_lock("lockA")
    b = sanitizer.sanitized_lock("lockB")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    report = sanitizer.drain()
    assert len(report["violations"]) == 1
    v = report["violations"][0]
    assert v["kind"] == "lock-order-cycle"
    assert "lockA" in v["summary"] and "lockB" in v["summary"]
    # both acquisition stacks ride the report
    assert v["this_edge"]["acquire_stack"]
    assert v["reverse_edge"]["acquire_stack"]
    assert any("test_sanitizer" in f for f in v["this_edge"]["acquire_stack"])


def test_consistent_order_is_clean():
    a = sanitizer.sanitized_lock("A")
    b = sanitizer.sanitized_lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert sanitizer.drain()["violations"] == []


def test_indirect_cycle_through_a_third_lock():
    a = sanitizer.sanitized_lock("A")
    b = sanitizer.sanitized_lock("B")
    c = sanitizer.sanitized_lock("C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass  # closes A -> B -> C -> A
    report = sanitizer.drain()
    assert len(report["violations"]) == 1
    assert report["violations"][0]["reverse_edge"] is None  # indirect


def test_cross_thread_opposite_order_is_detected():
    a = sanitizer.sanitized_lock("A")
    b = sanitizer.sanitized_lock("B")

    def forward():
        with a:
            with b:
                pass

    t = threading.Thread(target=forward, name="san-forward")
    t.start()
    t.join()
    with b:
        with a:
            pass
    assert sanitizer.drain()["violations"], (
        "edge recorded on one thread must trip the cycle check on another"
    )


def test_rlock_reentrancy_adds_no_edges():
    r = sanitizer.sanitized_rlock("R")
    with r:
        with r:
            with r:
                pass
    report = sanitizer.drain()
    assert report["violations"] == []
    assert report["edges"] == 0


def test_drain_clears_violations_but_keeps_the_graph():
    a = sanitizer.sanitized_lock("A")
    b = sanitizer.sanitized_lock("B")
    with a:
        with b:
            pass
    assert sanitizer.drain()["edges"] == 1
    with b:
        with a:
            pass  # the edge from before drain still closes the cycle
    report = sanitizer.drain()
    assert len(report["violations"]) == 1


# -- Condition compatibility --------------------------------------------------

@pytest.mark.parametrize("factory", [
    sanitizer.sanitized_lock, sanitizer.sanitized_rlock,
])
def test_condition_wait_notify_on_sanitized_locks(factory):
    cond = threading.Condition(factory("condlock"))
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter, name="san-cond-wait")
    t.start()
    # keep notifying until the waiter wakes: a single notify fired
    # before the waiter reaches wait() would be lost (flake)
    deadline = time.monotonic() + 5.0
    while not hits and time.monotonic() < deadline:
        with cond:
            cond.notify()
        time.sleep(0.005)
    t.join(timeout=5)
    assert hits == [1]
    assert sanitizer.drain()["violations"] == []


# -- hold-time tracking -------------------------------------------------------

def test_long_hold_records_a_warning(monkeypatch):
    monkeypatch.setenv("GOFR_SANITIZE_HOLD_MS", "20")
    lock = sanitizer.sanitized_lock("slow-lock")
    with lock:
        time.sleep(0.05)
    warnings = sanitizer.drain()["hold_warnings"]
    assert any(w["lock"] == "slow-lock" for w in warnings)
    w = next(w for w in warnings if w["lock"] == "slow-lock")
    assert w["seconds"] >= 0.02 and w["stack"]


def test_fast_hold_is_silent(monkeypatch):
    monkeypatch.setenv("GOFR_SANITIZE_HOLD_MS", "500")
    lock = sanitizer.sanitized_lock("fast-lock")
    with lock:
        pass
    assert sanitizer.drain()["hold_warnings"] == []


# -- install / uninstall ------------------------------------------------------

def test_install_rebinds_threading_lock_factories():
    was_installed = sanitizer.installed()
    try:
        sanitizer.install()
        lk = threading.Lock()
        rlk = threading.RLock()
        assert isinstance(lk, sanitizer.SanitizedLock)
        assert isinstance(rlk, sanitizer.SanitizedRLock)
        with lk:
            pass
        with rlk:
            with rlk:
                pass
        # creation label points at THIS file (project-scoped tracking)
        assert "test_sanitizer" in lk._label
        sanitizer.uninstall()
        assert not isinstance(threading.Lock(), sanitizer.SanitizedLock)
    finally:
        # the suite may be running under GOFR_SANITIZE=1: leave the
        # patch state exactly as found
        if was_installed:
            sanitizer.install()
        else:
            sanitizer.uninstall()
    sanitizer.drain()


# -- thread-leak detection ----------------------------------------------------

def test_leaked_nondaemon_thread_is_reported():
    before = set(threading.enumerate())
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="san-leaky")
    t.start()
    try:
        leaked = sanitizer.leaked_threads(before, grace_s=0.1)
        assert t in leaked
    finally:
        release.set()
        t.join(timeout=5)


def test_joined_and_daemon_threads_are_not_leaks():
    before = set(threading.enumerate())
    t = threading.Thread(target=lambda: None, name="san-quick")
    t.start()
    t.join()
    d = threading.Thread(target=time.sleep, args=(0.5,), name="san-d",
                         daemon=True)
    d.start()
    assert sanitizer.leaked_threads(before, grace_s=0.1) == []


def test_allowlisted_singletons_pass():
    before = set(threading.enumerate())
    release = threading.Event()
    t = threading.Thread(
        target=release.wait, name="gofr-timebase-sampler"
    )
    t.start()
    try:
        assert sanitizer.leaked_threads(before, grace_s=0.0) == []
        assert sanitizer.is_allowlisted(t)
    finally:
        release.set()
        t.join(timeout=5)


def test_grace_period_tolerates_winding_down_threads():
    before = set(threading.enumerate())
    t = threading.Thread(target=time.sleep, args=(0.2,), name="san-slowstop")
    t.start()
    # alive at check time, but exits within the grace window
    assert sanitizer.leaked_threads(before, grace_s=2.0) == []
    t.join()



# -- observed lock-order graph export -----------------------------------------

def test_export_graph_schema_and_determinism(tmp_path):
    """The export is the runtime half of the static∪runtime merge
    (tools/lockgraph_check.py): static-exporter schema, sorted nodes
    and edges, and byte-identical on re-export of an unchanged graph."""
    import json

    a = sanitizer.sanitized_lock("graphA")
    b = sanitizer.sanitized_lock("graphB")
    with a:
        with b:
            pass
    out = tmp_path / "graph.json"
    graph = sanitizer.export_graph(str(out))
    assert graph["version"] == 1 and graph["source"] == "runtime"
    assert [n["id"] for n in graph["nodes"]] == ["graphA", "graphB"]
    assert len(graph["edges"]) == 1
    edge = graph["edges"][0]
    assert edge["from"] == "graphA" and edge["to"] == "graphB"
    assert set(edge) == {"from", "to", "site", "thread"}
    assert "test_sanitizer" in edge["site"]
    # the written file round-trips to the returned document...
    assert json.loads(out.read_text()) == graph
    # ...and re-exporting the unchanged graph is deterministic
    assert sanitizer.export_graph() == graph
    sanitizer.drain()  # consume the edge count bookkeeping


def test_export_graph_survives_drain_and_empties_on_reset():
    a = sanitizer.sanitized_lock("keepA")
    b = sanitizer.sanitized_lock("keepB")
    with a:
        with b:
            pass
    sanitizer.drain()  # findings cleared, edge graph intentionally kept
    assert len(sanitizer.export_graph()["edges"]) == 1
    sanitizer.reset()
    assert sanitizer.export_graph() == {
        "version": 1, "source": "runtime", "nodes": [], "edges": [],
    }
