"""Tokenizer: native C++ backend vs pure-Python oracle, trainer, packing.

The native library is the framework's C++ boundary (native/tokenizer.cpp);
every behavior is asserted equal between backends so the Python fallback
doubles as the correctness oracle."""

import ctypes

import numpy as np
import pytest

from gofr_tpu import native
from gofr_tpu.tokenizer import SPECIAL_TOKENS, Tokenizer, train_bpe

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "the quicker the fox, the lazier the dog — überraschung! "
) * 8


@pytest.fixture(scope="module")
def trained():
    return train_bpe(CORPUS, vocab_size=320)


def test_native_library_builds():
    lib = native.load()
    assert lib is not None, "g++ toolchain present in this image; native must build"


def test_byte_level_roundtrip():
    tok = Tokenizer.byte_level()
    text = "hello wörld ☃"
    ids = tok.encode(text)
    assert all(0 <= i < 256 for i in ids)
    assert tok.decode(ids) == text


def test_trained_roundtrip_and_compression(trained):
    ids = trained.encode(CORPUS)
    assert trained.decode(ids) == CORPUS
    assert len(ids) < len(CORPUS.encode()) * 0.6, "BPE must compress its corpus"


def test_native_matches_python_backend(trained):
    if trained.backend != "native":
        pytest.skip("no native toolchain")
    py = Tokenizer(trained.merges)
    py._native = None  # force the Python path
    for text in ("", "a", CORPUS[:200], "emoji \U0001f680 mixed 123", "\x00\xff binary"):
        assert trained.encode(text) == py._encode_python(
            text.encode("utf-8")
        ), f"backend mismatch on {text!r}"
        ids = trained.encode(text)
        assert trained.decode(ids) == py.decode(ids)


def test_save_load_roundtrip(tmp_path, trained):
    path = str(tmp_path / "merges.txt")
    trained.save(path)
    loaded = Tokenizer.from_file(path)
    assert loaded.merges == trained.merges
    sample = CORPUS[:100]
    assert loaded.encode(sample) == trained.encode(sample)


def test_special_ids_top_of_vocab(trained):
    assert trained.special_id("pad") == 256 + len(trained.merges)
    assert trained.special_id("eos") == trained.vocab_size - 1
    assert trained.vocab_size == 256 + len(trained.merges) + len(SPECIAL_TOKENS)
    # specials never appear in encoded output and decode to nothing
    assert trained.decode([trained.special_id("pad")]) == ""


def test_train_rejects_tiny_vocab():
    with pytest.raises(ValueError, match="vocab_size"):
        train_bpe("abc", vocab_size=10)


def test_pack_rows_native():
    lib = native.load()
    if lib is None:
        pytest.skip("no native toolchain")
    rows = [[1, 2, 3], [4], [5, 6, 7, 8, 9, 10]]
    flat = np.asarray([x for r in rows for x in r], np.int32)
    lens = np.asarray([len(r) for r in rows], np.int64)
    width = 4
    out = np.zeros((len(rows), width), np.int32)
    out_lens = np.zeros(len(rows), np.int32)
    lib.gofr_pack_rows(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(rows), width, 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    np.testing.assert_array_equal(out[0], [1, 2, 3, 0])
    np.testing.assert_array_equal(out[1], [4, 0, 0, 0])
    # overlong row keeps its LAST tokens (prepare() semantics)
    np.testing.assert_array_equal(out[2], [7, 8, 9, 10])
    np.testing.assert_array_equal(out_lens, [3, 1, 4])


def test_backends_agree_on_overlapping_merges():
    # "aa"+"a": greedy leftmost at equal rank; classic overlap pitfalls
    a = ord("a")
    tok = Tokenizer([(a, a), (256, a)])
    py = Tokenizer(tok.merges)
    py._native = None
    for text in ("aaaa", "aaa", "aaaaa", "aabaa", "a" * 37):
        got = tok.encode(text)
        want = py._encode_python(text.encode())
        assert got == want, (text, got, want)
        assert tok.decode(got) == text


def test_backends_agree_on_random_corpus(trained):
    import random

    rng = random.Random(7)
    py = Tokenizer(trained.merges)
    py._native = None
    for _ in range(20):
        n = rng.randrange(0, 200)
        data = bytes(rng.randrange(256) for _ in range(n))
        assert trained.encode(data) == py._encode_python(data), data


def test_merges_file_headers_and_duplicates(tmp_path):
    # header lines and duplicate pairs must not shift ids or desync decode
    path = tmp_path / "merges.txt"
    path.write_text("#version: 0.2\n104 105\n104 105\n99 100\n999999 3\n")
    tok = Tokenizer.from_file(str(path))
    assert tok.merges == [(104, 105), (99, 100)]
    assert tok.encode("hi") == [256]
    assert tok.encode("cd") == [257]
    assert tok.decode([256, 257]) == "hicd"


def test_stream_decoder_multibyte_split():
    tok = Tokenizer.byte_level()
    text = "héllo ☃ é"
    ids = tok.encode(text)
    dec = tok.stream_decoder()
    pieces = [dec.feed(i) for i in ids]
    assert "".join(pieces) + dec.flush() == text
    # no replacement chars mid-stream for valid input
    assert "�" not in "".join(pieces)
    # truncated multi-byte at end of stream surfaces on flush as replacement
    dec2 = tok.stream_decoder()
    partial = "é".encode()[:1]
    out = dec2.feed(partial[0])
    assert out == ""  # buffered, not garbled
    assert dec2.flush() == "�"


def test_encode_large_input_is_fast(trained):
    import time

    big = (CORPUS * 300)[:200_000]
    start = time.perf_counter()
    ids = trained.encode(big)
    elapsed = time.perf_counter() - start
    assert trained.decode(ids) == big
    assert elapsed < 3.0, f"encode of 200KB took {elapsed:.1f}s — not O(n log n)?"


def test_pack_token_rows_matches_python_fallback(monkeypatch):
    from gofr_tpu.tpu.batcher import pack_token_rows

    rows = [np.asarray(r, np.int32) for r in ([1, 2, 3], [4], list(range(20)))]
    got, got_lens = pack_token_rows(rows, 4, 8, pad_id=0)
    import gofr_tpu.native as native_mod

    monkeypatch.setattr(native_mod, "load", lambda: None)
    want, want_lens = pack_token_rows(rows, 4, 8, pad_id=0)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got_lens, want_lens)
    np.testing.assert_array_equal(got[2], list(range(12, 20)))  # last tokens kept


def test_native_parser_robustness_direct_abi():
    # direct C-ABI consumers (GOFR_NATIVE_LIB users) may feed raw merges
    # blobs: headers, duplicates, and special-range ids must all be skipped
    lib = native.load()
    if lib is None:
        pytest.skip("no native toolchain")
    blob = b"#version: 0.2\n104 105\n104 105\n300 3\n99 100\n"
    h = lib.gofr_tok_new(blob, len(blob), 3)
    try:
        assert lib.gofr_tok_vocab_size(h) == 256 + 2 + 3  # hi, cd + specials
        buf = (ctypes.c_int32 * 4)()
        n = lib.gofr_tok_encode(h, b"hicd", 4, buf, 4)
        assert list(buf[:n]) == [256, 257]
    finally:
        lib.gofr_tok_free(h)


# -- HF tokenizer.json interop (real-model ingestion) -------------------------

@pytest.fixture(scope="module")
def hf_json_path(tmp_path_factory):
    """Train a real byte-level BPE with the HF `tokenizers` library (the
    independent oracle) and save its tokenizer.json."""
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import decoders, models, pre_tokenizers, trainers

    hf = tokenizers.Tokenizer(models.BPE())
    hf.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    hf.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400,
        special_tokens=["<|begin_of_text|>", "<|end_of_text|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    hf.train_from_iterator([CORPUS], trainer)
    path = str(tmp_path_factory.mktemp("hf") / "tokenizer.json")
    hf.save(path)
    return path


def test_hf_json_encode_matches_hf_library(hf_json_path):
    import tokenizers

    hf = tokenizers.Tokenizer.from_file(hf_json_path)
    ours = Tokenizer.from_hf_json(hf_json_path)
    for text in (
        "the quick brown fox",
        "überraschung! the lazier dog",
        "  leading spaces and   runs",
        "punctuation, too! (yes?)",
        CORPUS[:200],
    ):
        assert ours.encode(text) == hf.encode(text).ids, text


def test_hf_json_decode_roundtrip(hf_json_path):
    ours = Tokenizer.from_hf_json(hf_json_path)
    text = "the quick brown fox — überraschung!"
    assert ours.decode(ours.encode(text)) == text


def test_hf_json_specials_and_vocab(hf_json_path):
    import tokenizers

    hf = tokenizers.Tokenizer.from_file(hf_json_path)
    ours = Tokenizer.from_hf_json(hf_json_path)
    assert ours.vocab_size == hf.get_vocab_size()
    assert ours.special_id("bos") == hf.token_to_id("<|begin_of_text|>")
    assert ours.special_id("eos") == hf.token_to_id("<|end_of_text|>")
    assert ours.token_id("<|begin_of_text|>") == hf.token_to_id("<|begin_of_text|>")
    with pytest.raises(ValueError, match="no pad"):
        ours.special_id("pad")


def test_hf_json_stream_decoder_skips_specials(hf_json_path):
    ours = Tokenizer.from_hf_json(hf_json_path)
    ids = ours.encode("the fox")
    dec = ours.stream_decoder()
    text = "".join(dec.feed(i) for i in [ours.special_id("bos"), *ids])
    text += dec.flush()
    assert text == "the fox"


def test_hf_json_rejects_non_bpe(tmp_path):
    import json as json_mod

    path = str(tmp_path / "tokenizer.json")
    with open(path, "w") as f:
        json_mod.dump({"model": {"type": "Unigram", "vocab": []}}, f)
    with pytest.raises(ValueError, match="Unigram"):
        Tokenizer.from_hf_json(path)


def test_load_tokenizer_routes_hf_json(hf_json_path, monkeypatch):
    from gofr_tpu.config import EnvConfig
    from gofr_tpu.tokenizer import load_tokenizer

    monkeypatch.setenv("TOKENIZER_PATH", hf_json_path)
    tok = load_tokenizer(EnvConfig())
    assert tok is not None and tok._ext_of is not None
