"""gofrlint's own test suite: positive/negative fixture snippets per
rule, suppression comments, the JSON output schema — and the tree gate
itself (the whole package + tools must lint clean, same contract as
``ruff check .``)."""

import importlib.util
import io
import json
import pathlib
import sys
from contextlib import redirect_stdout

REPO = pathlib.Path(__file__).resolve().parents[1]
_spec = importlib.util.spec_from_file_location(
    "gofrlint", REPO / "tools" / "gofrlint.py"
)
gofrlint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(gofrlint)


def lint(source: str, rel: str = "gofr_tpu/somemod.py") -> list:
    """Lint a snippet as though it lived at ``rel`` (path scoping —
    package vs script vs engine module — is part of the rules)."""
    return gofrlint.FileLinter(pathlib.Path(rel), rel, source).run()


def rules_of(violations) -> list:
    return [v.rule for v in violations]


# -- GFL001: env discipline ---------------------------------------------------

def test_gfl001_flags_raw_reads_in_package_code():
    assert rules_of(lint('import os\nx = os.environ.get("K")\n')) == ["GFL001"]
    assert rules_of(lint('import os\nx = os.getenv("K")\n')) == ["GFL001"]
    assert rules_of(lint('import os\nx = os.environ["K"]\n')) == ["GFL001"]
    assert rules_of(lint(
        "import os\nfor k in sorted(os.environ):\n    pass\n"
    )) == ["GFL001"]


def test_gfl001_allows_writes_scripts_and_config():
    assert lint('import os\nos.environ["K"] = "1"\n') == []
    assert lint('import os\nos.environ.setdefault("K", "1")\n') == []
    assert lint('import os\nos.environ.pop("K", None)\n') == []
    assert lint('import os\nos.environ.update({"K": "1"})\n') == []
    # entry-point scripts configure the process env before boot
    assert lint('import os\nx = os.environ.get("K")\n', rel="tools/x.py") == []
    assert lint('import os\nx = os.getenv("K")\n', rel="bench.py") == []
    # config.py IS the sanctioned reader
    assert lint(
        'import os\nx = os.environ.get("K")\n', rel="gofr_tpu/config.py"
    ) == []


def test_gfl001_suppression_comment():
    src = 'import os\nx = os.environ.get("K")  # gofrlint: disable=GFL001 — bootstrap\n'
    assert lint(src) == []


# -- GFL002: timestamp discipline ---------------------------------------------

def test_gfl002_flags_unannotated_time_time():
    assert rules_of(lint("import time\nt = time.time()\n")) == ["GFL002"]
    # scripts are not exempt — durations there drift the same way
    assert rules_of(
        lint("import time\nt = time.time()\n", rel="tools/x.py")
    ) == ["GFL002"]


def test_gfl002_monotonic_and_annotated_sites_pass():
    assert lint("import time\nt = time.monotonic()\n") == []
    assert lint("import time\nt = time.perf_counter()\n") == []
    assert lint(
        "import time\nt = time.time()  # gofrlint: wall-clock — log ts\n"
    ) == []
    # the annotation may ride a comment-only line directly above
    assert lint(
        "import time\n# gofrlint: wall-clock — api field\nt = time.time()\n"
    ) == []


# -- GFL003: thread hygiene ---------------------------------------------------

def test_gfl003_unnamed_or_unjoined_threads():
    src = "import threading\nthreading.Thread(target=print).start()\n"
    assert rules_of(lint(src)) == ["GFL003", "GFL003"]  # unnamed AND unjoined
    named_daemon = (
        "import threading\n"
        'threading.Thread(target=print, name="t", daemon=True).start()\n'
    )
    assert lint(named_daemon) == []
    named_joined = (
        "import threading\n"
        't = threading.Thread(target=print, name="t")\n'
        "t.start()\nt.join()\n"
    )
    assert lint(named_joined) == []


def test_gfl003_str_and_path_join_do_not_count_as_thread_joins():
    src = (
        "import threading, os\n"
        't = threading.Thread(target=print, name="t")\n'
        'x = ",".join(["a"])\ny = os.path.join("a", "b")\n'
    )
    assert rules_of(lint(src)) == ["GFL003"]  # still unjoined


# -- GFL004: no blocking under a lock -----------------------------------------

def test_gfl004_sleep_and_timeoutless_queue_get_under_lock():
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            time.sleep(1)\n"
    )
    assert rules_of(lint(src)) == ["GFL004"]
    src_q = (
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            item = self.queue.get()\n"
    )
    assert rules_of(lint(src_q)) == ["GFL004"]


def test_gfl004_allows_timeouts_condition_wait_and_unlocked_calls():
    ok = (
        "import time\n"
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            x = self.queue.get(timeout=1)\n"
        "            self._work.wait()\n"  # Condition releases its lock
        "        time.sleep(1)\n"  # outside the critical section
    )
    assert lint(ok) == []


def test_gfl004_acquire_release_tracking():
    src = (
        "import time\n"
        "def f(lock):\n"
        "    lock.acquire()\n"
        "    time.sleep(1)\n"
        "    lock.release()\n"
        "    time.sleep(1)\n"
    )
    assert rules_of(lint(src)) == ["GFL004"]  # only the held sleep


def test_gfl004_thread_join_under_lock():
    src = (
        "class C:\n"
        "    def close(self):\n"
        "        with self._lock:\n"
        "            self._thread.join()\n"
    )
    assert rules_of(lint(src)) == ["GFL004"]


# -- GFL005: metric naming ----------------------------------------------------

def test_gfl005_convention_enforced_statically():
    bad = 'm.counter("gofr_tpu_requests", "r")\n'
    assert rules_of(lint(bad)) == ["GFL005"]
    assert rules_of(lint('m.histogram("gofr_tpu_latency", "l")\n')) == ["GFL005"]
    assert rules_of(lint('m.gauge("gofr_tpu_stuff", "s")\n')) == ["GFL005"]
    assert rules_of(lint('m.counter("tpu_x_total", "x")\n')) == ["GFL005"]
    assert lint('m.counter("gofr_tpu_requests_total", "r")\n') == []
    assert lint('m.histogram("gofr_tpu_latency_seconds", "l")\n') == []
    assert lint('m.gauge("gofr_tpu_mfu", "roofline")\n') == []  # allowlist
    # dynamically composed names are the runtime test's job, not ours
    assert lint("m.counter(name, 'x')\n") == []


def test_gfl005_mesh_family_covered():
    """The sharded-serving family (tpu/device.py): the _size gauge
    suffix (gofr_tpu_mesh_axis_size{axis}) and the degrade counter pass;
    suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_mesh_axis_size", "a")\n') == []
    assert lint('m.counter("gofr_tpu_mesh_degrade_total", "d")\n') == []
    assert rules_of(lint('m.gauge("gofr_tpu_mesh_axes", "a")\n')) == \
        ["GFL005"]


def test_gfl005_deadline_family_covered():
    """The deadline/brownout family (deadline.py, batcher.py,
    decode_pool.py): the _level gauge suffix and the stage/cause
    counters pass; suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_brownout_level", "b")\n') == []
    assert lint('m.counter("gofr_tpu_deadline_exceeded_total", "d")\n') == []
    assert lint('m.counter("gofr_tpu_cancellations_total", "c")\n') == []
    assert lint('m.counter("gofr_tpu_brownout_shed_total", "s")\n') == []
    assert rules_of(lint('m.gauge("gofr_tpu_brownout", "b")\n')) == \
        ["GFL005"]
    assert rules_of(lint('m.counter("gofr_tpu_deadline_exceeded", "d")\n')) \
        == ["GFL005"]


def test_gfl005_spec_family_covered():
    """The pooled-speculative-decoding family (tpu/spec_pool.py): the
    _ratio and _per_dispatch gauge suffixes pass; suffix drift within
    the family still fails."""
    assert lint('m.gauge("gofr_tpu_spec_accept_ratio", "a")\n') == []
    assert lint(
        'm.gauge("gofr_tpu_spec_tokens_per_dispatch", "t")\n'
    ) == []
    assert rules_of(lint('m.gauge("gofr_tpu_spec_accept", "a")\n')) == \
        ["GFL005"]
    assert rules_of(lint('m.gauge("gofr_tpu_spec_tokens", "t")\n')) == \
        ["GFL005"]


def test_gfl005_router_family_covered():
    """The gofr_tpu_router_* family (fleet/router.py) rides the same
    convention: the suffix table must keep accepting its gauges (_state,
    _depth) and rejecting drift within the family."""
    assert lint('m.gauge("gofr_tpu_router_breaker_state", "b")\n') == []
    assert lint('m.gauge("gofr_tpu_router_outstanding_depth", "o")\n') == []
    assert lint('m.counter("gofr_tpu_router_shed_total", "s")\n') == []
    assert lint('m.histogram("gofr_tpu_router_upstream_seconds", "u")\n') == []
    assert rules_of(lint('m.gauge("gofr_tpu_router_breakers", "b")\n')) == \
        ["GFL005"]
    assert rules_of(lint('m.counter("gofr_tpu_router_sheds", "s")\n')) == \
        ["GFL005"]


def test_gfl005_trace_family_covered():
    """The fleet-tracing family (PR 16): the per-hop latency histogram
    (router.py) and the zipkin exporter drop counter (tracing.py) pass;
    suffix drift within the family still fails."""
    assert lint('m.histogram("gofr_tpu_router_hop_seconds", "h")\n') == []
    assert lint(
        'm.counter("gofr_tpu_trace_export_failures_total", "z")\n'
    ) == []
    assert rules_of(lint('m.histogram("gofr_tpu_router_hop", "h")\n')) == \
        ["GFL005"]
    assert rules_of(
        lint('m.counter("gofr_tpu_trace_export_failures", "z")\n')
    ) == ["GFL005"]


def test_gfl005_costmodel_family_covered():
    """The dispatch cost-model family (tpu/costmodel.py): the residual
    EMA gauge (``_ratio``) and the anomaly counter (``_total``) pass;
    suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_dispatch_residual_ratio", "r")\n') == []
    assert lint(
        'm.counter("gofr_tpu_dispatch_anomalies_total", "a")\n'
    ) == []
    assert rules_of(
        lint('m.gauge("gofr_tpu_dispatch_residual", "r")\n')
    ) == ["GFL005"]
    assert rules_of(
        lint('m.counter("gofr_tpu_dispatch_anomalies", "a")\n')
    ) == ["GFL005"]


def test_gfl005_slo_tenant_family_covered():
    """The SLO/tenant-metering family (slo.py + telemetry.TenantLedger):
    the burn-rate and budget gauges (``_rate``, ``_remaining``), the
    alert counter, and the ledger's tracked-entries gauge all pass;
    suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_slo_burn_rate", "b")\n') == []
    assert lint('m.gauge("gofr_tpu_slo_budget_remaining", "b")\n') == []
    assert lint(
        'm.counter("gofr_tpu_slo_burn_alerts_total", "a")\n'
    ) == []
    assert lint(
        'm.gauge("gofr_tpu_tenants_tracked_entries", "t")\n'
    ) == []
    assert lint(
        'm.counter("gofr_tpu_tenant_overflow_total", "o")\n'
    ) == []
    assert rules_of(
        lint('m.gauge("gofr_tpu_slo_burn", "b")\n')
    ) == ["GFL005"]
    assert rules_of(
        lint('m.counter("gofr_tpu_slo_burn_alerts", "a")\n')
    ) == ["GFL005"]


# -- GFL006: swallowed exceptions ---------------------------------------------

def test_gfl006_bare_except_everywhere():
    src = "try:\n    x = 1\nexcept:\n    pass\n"
    assert rules_of(lint(src, rel="tools/x.py")) == ["GFL006"]


def test_gfl006_broad_swallow_only_in_engine_paths():
    src = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    assert rules_of(lint(src, rel="gofr_tpu/tpu/x.py")) == ["GFL006"]
    assert rules_of(lint(src, rel="gofr_tpu/timebase.py")) == ["GFL006"]
    assert lint(src, rel="gofr_tpu/handler.py") == []  # request path
    narrow = "try:\n    x = 1\nexcept ValueError:\n    pass\n"
    assert lint(narrow, rel="gofr_tpu/tpu/x.py") == []
    handled = (
        "try:\n    x = 1\nexcept Exception as exc:\n    log(exc)\n"
    )
    assert lint(handled, rel="gofr_tpu/tpu/x.py") == []


def test_gfl006_suppression_sits_on_the_pass_line():
    src = (
        "try:\n    x = 1\nexcept Exception:\n"
        "    pass  # gofrlint: disable=GFL006 — last-resort guard\n"
    )
    assert lint(src, rel="gofr_tpu/tpu/x.py") == []


# -- suppression / annotation robustness --------------------------------------

def test_directives_inside_strings_are_ignored():
    src = 'x = "# gofrlint: disable=GFL002"\nimport time\nt = time.time()\n'
    assert rules_of(lint(src)) == ["GFL002"]


def test_directive_cascades_through_comment_blocks():
    src = (
        "try:\n    x = 1\nexcept Exception:\n"
        "    # gofrlint: disable=GFL006 — reason line one\n"
        "    # ...reason continued on a second line\n"
        "    pass\n"
    )
    assert lint(src, rel="gofr_tpu/tpu/x.py") == []


def test_multi_rule_suppression():
    src = (
        "import os, time\n"
        't = time.time(); x = os.getenv("K")'
        "  # gofrlint: disable=GFL001,GFL002 — fixture\n"
    )
    assert lint(src) == []


# -- output formats / CLI -----------------------------------------------------

def test_json_output_schema(tmp_path):
    bad = tmp_path / "gofr_tpu" / "mod.py"
    bad.parent.mkdir()
    bad.write_text('import os\nx = os.getenv("K")\nimport time\nt = time.time()\n')
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = gofrlint.main(["--format=json", str(tmp_path)])
    assert rc == 1
    out = json.loads(buf.getvalue())
    assert out["version"] == 1
    assert out["files_scanned"] == 1
    assert out["counts_by_rule"] == {"GFL001": 1, "GFL002": 1}
    for v in out["violations"]:
        assert set(v) == {"file", "line", "col", "rule", "message"}
        assert v["rule"] in gofrlint.RULES


def test_clean_tree_exits_zero(tmp_path):
    good = tmp_path / "ok.py"
    good.write_text("import time\nt = time.monotonic()\n")
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = gofrlint.main([str(tmp_path)])
    assert rc == 0
    assert "clean" in buf.getvalue()


def test_syntax_error_is_reported_not_crashed(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    violations, scanned = gofrlint.lint_paths([str(tmp_path)])
    assert scanned == 1
    assert [v.rule for v in violations] == ["GFL000"]


# -- the tree gate ------------------------------------------------------------

def test_the_real_tree_is_clean():
    """The acceptance contract, runnable as a test: the package, tools,
    and bench.py carry zero unsuppressed violations. Same "only
    shrinks" policy as the ruff debt ledger — fix new violations or
    suppress them IN-FILE with a reason."""
    violations, scanned = gofrlint.lint_paths([
        str(REPO / "gofr_tpu"), str(REPO / "tools"), str(REPO / "bench.py")
    ])
    assert scanned > 50
    assert violations == [], "\n".join(
        f"{v.path}:{v.line}: {v.rule} {v.message}" for v in violations
    )


def test_cli_entrypoint_runs(tmp_path):
    """``python tools/gofrlint.py`` stays invocable as a script (the CI
    lint job calls it exactly that way)."""
    import subprocess

    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "gofrlint.py"), str(ok)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- GFL004 interprocedural (whole-program) -----------------------------------

def interproc(sources: dict) -> list:
    """Run only the whole-program half over a {rel: source} tree."""
    project = gofrlint.Project.from_sources(sources)
    return gofrlint.WholeProgram(project).violations()


def test_interproc_direct_call():
    out = interproc({"gofr_tpu/m.py": (
        "import time, threading\n"
        "_LOCK = threading.Lock()\n"
        "def helper():\n"
        "    time.sleep(1)\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        helper()\n"
    )})
    assert [v.rule for v in out] == ["GFL004"]
    assert "helper" in out[0].message and "time.sleep" in out[0].message


def test_interproc_self_method_under_foreign_lock():
    out = interproc({"gofr_tpu/m.py": (
        "import time, threading\n"
        "_LOCK = threading.Lock()\n"
        "class C:\n"
        "    def run(self):\n"
        "        with _LOCK:\n"
        "            self._drain()\n"
        "    def _drain(self):\n"
        "        time.sleep(1)\n"
    )})
    assert [v.rule for v in out] == ["GFL004"]


def test_interproc_class_typed_attribute_dispatch():
    """``self.attr.method()`` resolves through the attribute type
    inferred from the ``__init__`` assignment — the dispatch shape the
    per-file rule cannot see."""
    out = interproc({"gofr_tpu/m.py": (
        "import time, threading\n"
        "class Worker:\n"
        "    def pump(self):\n"
        "        time.sleep(1)\n"
        "class Owner:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._worker = Worker()\n"
        "    def step(self):\n"
        "        with self._lock:\n"
        "            self._worker.pump()\n"
    )})
    assert [v.rule for v in out] == ["GFL004"]
    assert "pump" in out[0].message


def test_interproc_two_hop_chain_carries_a_witness():
    out = interproc({"gofr_tpu/m.py": (
        "import time, threading\n"
        "_LOCK = threading.Lock()\n"
        "def c():\n"
        "    time.sleep(1)\n"
        "def b():\n"
        "    c()\n"
        "def a():\n"
        "    with _LOCK:\n"
        "        b()\n"
    )})
    assert [v.rule for v in out] == ["GFL004"]
    # the finding names the path, not just the endpoint
    assert "b" in out[0].message and "c" in out[0].message


def test_interproc_suppression_on_the_call_line():
    out = interproc({"gofr_tpu/m.py": (
        "import time, threading\n"
        "_LOCK = threading.Lock()\n"
        "def helper():\n"
        "    time.sleep(1)\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        helper()  # gofrlint: disable=GFL004 — fixture\n"
    )})
    assert out == []


def test_interproc_resource_guard_exemption():
    """A class serializing its OWN blocking resource behind its own
    lock (the JournalWAL fsync shape) is exempt: every may-block path
    stays inside the class. The cross-object variant in the committed
    WAL fixture must still be flagged (next test)."""
    out = interproc({"gofr_tpu/m.py": (
        "import os, threading\n"
        "class Wal:\n"
        "    def __init__(self, fd):\n"
        "        self._lock = threading.Lock()\n"
        "        self._fd = fd\n"
        "    def append(self, b):\n"
        "        with self._lock:\n"
        "            os.write(self._fd, b)\n"
        "            self._sync()\n"
        "    def _sync(self):\n"
        "        os.fsync(self._fd)\n"
    )})
    assert out == []


def test_interproc_bounded_join_is_not_blocking():
    """join(timeout=...) is a bounded teardown wait — the device.py
    recovery path (reinit under _reinit_lock → teardown → pool close
    with a bounded join) must stay clean."""
    out = interproc({"gofr_tpu/m.py": (
        "import threading\n"
        "class C:\n"
        "    def close(self):\n"
        "        with self._lock:\n"
        "            self._teardown()\n"
        "    def _teardown(self):\n"
        "        self._thread.join(timeout=2.0)\n"
    )})
    assert out == []


def test_wal_under_lock_fixture_is_caught():
    """The PR 14 regression contract: the committed cross-object
    WAL-under-journal-lock fixture is flagged by the interprocedural
    pass — at the reach-through call in Journal.record, with the fsync
    chain as witness — while WalWriter's own-lock fsync (the
    resource-guard shape) is not."""
    fixture = REPO / "tests" / "fixtures" / "wal_under_lock.py"
    violations, scanned = gofrlint.lint_paths([str(fixture)])
    assert scanned == 1
    assert [v.rule for v in violations] == ["GFL004"]
    v = violations[0]
    assert "append_tokens" in v.message and "os.fsync" in v.message
    # the finding sits on Journal.record's call, not inside WalWriter
    source = fixture.read_text().splitlines()
    assert "self._wal.append_tokens" in source[v.line - 1]


# -- GFL007: metric contract registries ---------------------------------------

def run_tree(tmp_path, files: dict) -> list:
    """Materialize {rel: source} under tmp_path and run the full lint."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    violations, _ = gofrlint.lint_paths([str(tmp_path)])
    return violations


def test_gfl007_duplicate_registration_home(tmp_path):
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/a.py":
            'm.counter("gofr_tpu_x_total", "things", labels=("op",))\n',
        "gofr_tpu/b.py":
            'm.counter("gofr_tpu_x_total", "things", labels=("op",))\n',
    })
    assert [v.rule for v in out] == ["GFL007"]
    assert "duplicate registration home" in out[0].message


def test_gfl007_kind_flip_and_help_divergence(tmp_path):
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/a.py": 'm.counter("gofr_tpu_x_total", "things")\n',
        "gofr_tpu/b.py": 'm.gauge("gofr_tpu_x_total")\n',
    })
    assert "GFL007" in [v.rule for v in out]
    assert any("kind" in v.message for v in out)


def test_gfl007_label_disagreement(tmp_path):
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/a.py":
            'm.counter("gofr_tpu_x_total", "t", labels=("model",))\n',
        "gofr_tpu/b.py":
            'm.counter("gofr_tpu_x_total", labels=("op",))\n',
    })
    assert any(
        v.rule == "GFL007" and "label" in v.message for v in out
    )


def test_gfl007_lookup_sites_are_fine(tmp_path):
    """One home with help text + N help-less lookups is the sanctioned
    idiom (decode_pool.py looks up device.py's registrations)."""
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/a.py":
            'm.counter("gofr_tpu_x_total", "things", labels=("op",))\n',
        "gofr_tpu/b.py":
            'm.counter("gofr_tpu_x_total", labels=("op",))\n',
    })
    assert out == []


def test_gfl007_requires_a_naming_test_row(tmp_path):
    """With a tests/test_metric_naming.py present, every registered
    family needs a row in it — the drift-proof link between the
    registry and the convention test."""
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/a.py": 'm.counter("gofr_tpu_x_total", "t")\n',
        "tests/test_metric_naming.py": "# no rows here\n",
    })
    assert [v.rule for v in out] == ["GFL007"]
    assert "test_metric_naming" in out[0].message


# -- GFL008: config-key provenance --------------------------------------------

def test_gfl008_undeclared_package_read(tmp_path):
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/config.py": 'DECLARED_KEYS = {"GOOD_KEY": "doc"}\n',
        "gofr_tpu/m.py": (
            "from gofr_tpu.config import get_env\n"
            'x = get_env("MYSTERY_KEY")\n'
            'y = get_env("GOOD_KEY")\n'
        ),
    })
    assert [v.rule for v in out] == ["GFL008"]
    assert "MYSTERY_KEY" in out[0].message


def test_gfl008_inert_declared_knob(tmp_path):
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/config.py": 'DECLARED_KEYS = {"NEVER_READ": "doc"}\n',
    })
    assert [v.rule for v in out] == ["GFL008"]
    assert "NEVER_READ" in out[0].message and "inert" in out[0].message


def test_gfl008_wrapper_and_harness_reads_count(tmp_path):
    """A one-hop wrapper read (the fleet ``_f`` idiom) traces to the
    key; a harness-only read (bench/tools) proves a declared key live
    but is NOT itself held to the package registry."""
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/config.py": 'DECLARED_KEYS = {"WRAPPED_KEY": "doc"}\n',
        "gofr_tpu/m.py": (
            "from gofr_tpu.config import get_env\n"
            "def _f(key, default):\n"
            "    return get_env(key) or default\n"
            'x = _f("WRAPPED_KEY", "1")\n'
        ),
        "bench.py": (
            "import os\n"
            'y = os.getenv("BENCH_ONLY_KEY")\n'
        ),
    })
    assert out == []


# -- GFL009: admin-surface parity ---------------------------------------------

def test_gfl009_code_route_missing_from_readme(tmp_path):
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/app.py": 'app.get("/admin/newthing", handler)\n',
        "README.md": "| `/admin/other` | something |\n",
    })
    rules = [v.rule for v in out]
    assert rules.count("GFL009") == 2  # missing route AND stale row
    assert any("/admin/newthing" in v.message for v in out)
    assert any("stale" in v.message for v in out)


def test_gfl009_param_spelling_does_not_break_parity(tmp_path):
    """Code's ``{hash}`` vs the README's ``{prompt_hash}`` is the same
    route — parity guards the surface's shape, not parameter names."""
    out = run_tree(tmp_path, {
        "gofr_tpu/__init__.py": "",
        "gofr_tpu/app.py": 'app.get("/admin/kv/{hash}", handler)\n',
        "README.md": "| `/admin/kv/{prompt_hash}` | kv export |\n",
    })
    assert out == []


# -- suppression ledger ratchet -----------------------------------------------

def test_ledger_emission_and_ratchet(tmp_path):
    src = tmp_path / "gofr_tpu" / "m.py"
    src.parent.mkdir()
    src.write_text(
        "import time\n"
        "t = time.time()  # gofrlint: disable=GFL002 — fixture\n"
        "u = time.time()  # gofrlint: disable=GFL002 — fixture\n"
    )
    run = gofrlint.LintRun([str(tmp_path)])
    assert run.ledger == {"GFL002": 2}
    baseline = tmp_path / "ledger.json"
    baseline.write_text(json.dumps({"version": 1, "counts": {"GFL002": 2}}))
    assert gofrlint.check_ledger(run.ledger, str(baseline)) == []
    # ratchet: baseline of 1 means the second disable is growth
    baseline.write_text(json.dumps({"version": 1, "counts": {"GFL002": 1}}))
    errors = gofrlint.check_ledger(run.ledger, str(baseline))
    assert len(errors) == 1 and "grew" in errors[0]
    # a rule absent from the baseline is allowed zero
    baseline.write_text(json.dumps({"version": 1, "counts": {}}))
    assert len(gofrlint.check_ledger(run.ledger, str(baseline))) == 1


def test_committed_ledger_matches_the_tree():
    """The baseline in tools/gofrlint_ledger.json IS the current tree's
    ledger — the ratchet starts tight (a stale-but-loose baseline would
    let new suppressions ride in under old headroom)."""
    run = gofrlint.LintRun([
        str(REPO / "gofr_tpu"), str(REPO / "tools"), str(REPO / "bench.py")
    ])
    committed = json.loads(
        (REPO / "tools" / "gofrlint_ledger.json").read_text()
    )["counts"]
    assert run.ledger == committed


# -- lock-order graph (static + merge) ----------------------------------------

def test_static_lock_graph_schema_and_edges():
    project = gofrlint.Project.from_sources({"gofr_tpu/m.py": (
        "import threading\n"
        "_a_lock = threading.Lock()\n"
        "_b_lock = threading.Lock()\n"
        "def f():\n"
        "    with _a_lock:\n"
        "        with _b_lock:\n"
        "            pass\n"
    )})
    graph = gofrlint.WholeProgram(project).lock_graph()
    assert graph["version"] == 1 and graph["source"] == "static"
    ids = {n["id"] for n in graph["nodes"]}
    assert ids == {"gofr_tpu/m.py:2", "gofr_tpu/m.py:3"}
    assert [(e["from"], e["to"]) for e in graph["edges"]] == [
        ("gofr_tpu/m.py:2", "gofr_tpu/m.py:3")
    ]


def _load_lockgraph_check():
    spec = importlib.util.spec_from_file_location(
        "lockgraph_check", REPO / "tools" / "lockgraph_check.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lockgraph_merge_finds_cross_tool_cycle(tmp_path):
    """The point of the union: A→B proved statically, B→A observed at
    runtime — a deadlock neither graph contains alone."""
    lgc = _load_lockgraph_check()
    static = {"version": 1, "source": "static", "nodes": [], "edges": [
        {"from": "gofr_tpu/a.py:10", "to": "gofr_tpu/b.py:20", "site": "s"},
    ]}
    runtime = {"version": 1, "source": "runtime", "nodes": [], "edges": [
        {"from": "/ci/work/repo/gofr_tpu/b.py:20",
         "to": "/ci/work/repo/gofr_tpu/a.py:10", "site": "r"},
    ]}
    for name, doc in (("s.json", static), ("r.json", runtime)):
        (tmp_path / name).write_text(json.dumps(doc))
    assert lgc.main(["lockgraph_check", str(tmp_path / "s.json")]) == 0
    assert lgc.main([
        "lockgraph_check", str(tmp_path / "s.json"), str(tmp_path / "r.json")
    ]) == 1


def test_lockgraph_normalization_and_self_loops():
    lgc = _load_lockgraph_check()
    assert lgc.normalize("/home/ci/repo/gofr_tpu/x.py:12") == \
        "gofr_tpu/x.py:12"
    assert lgc.normalize("gofr_tpu/x.py:12") == "gofr_tpu/x.py:12"
    assert lgc.normalize("gofr_tpu/m.py::C._lock") == "gofr_tpu/m.py::C._lock"
    # two instances created at one site collapse — the resulting
    # self-loop must NOT count as a cycle
    adj = lgc.merge([{"source": "runtime", "edges": [
        {"from": "/r/gofr_tpu/x.py:5", "to": "/r/gofr_tpu/x.py:5",
         "site": "s"},
    ]}])
    assert lgc.find_cycles(adj) == []
