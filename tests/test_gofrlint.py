"""gofrlint's own test suite: positive/negative fixture snippets per
rule, suppression comments, the JSON output schema — and the tree gate
itself (the whole package + tools must lint clean, same contract as
``ruff check .``)."""

import importlib.util
import io
import json
import pathlib
import sys
from contextlib import redirect_stdout

REPO = pathlib.Path(__file__).resolve().parents[1]
_spec = importlib.util.spec_from_file_location(
    "gofrlint", REPO / "tools" / "gofrlint.py"
)
gofrlint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(gofrlint)


def lint(source: str, rel: str = "gofr_tpu/somemod.py") -> list:
    """Lint a snippet as though it lived at ``rel`` (path scoping —
    package vs script vs engine module — is part of the rules)."""
    return gofrlint.FileLinter(pathlib.Path(rel), rel, source).run()


def rules_of(violations) -> list:
    return [v.rule for v in violations]


# -- GFL001: env discipline ---------------------------------------------------

def test_gfl001_flags_raw_reads_in_package_code():
    assert rules_of(lint('import os\nx = os.environ.get("K")\n')) == ["GFL001"]
    assert rules_of(lint('import os\nx = os.getenv("K")\n')) == ["GFL001"]
    assert rules_of(lint('import os\nx = os.environ["K"]\n')) == ["GFL001"]
    assert rules_of(lint(
        "import os\nfor k in sorted(os.environ):\n    pass\n"
    )) == ["GFL001"]


def test_gfl001_allows_writes_scripts_and_config():
    assert lint('import os\nos.environ["K"] = "1"\n') == []
    assert lint('import os\nos.environ.setdefault("K", "1")\n') == []
    assert lint('import os\nos.environ.pop("K", None)\n') == []
    assert lint('import os\nos.environ.update({"K": "1"})\n') == []
    # entry-point scripts configure the process env before boot
    assert lint('import os\nx = os.environ.get("K")\n', rel="tools/x.py") == []
    assert lint('import os\nx = os.getenv("K")\n', rel="bench.py") == []
    # config.py IS the sanctioned reader
    assert lint(
        'import os\nx = os.environ.get("K")\n', rel="gofr_tpu/config.py"
    ) == []


def test_gfl001_suppression_comment():
    src = 'import os\nx = os.environ.get("K")  # gofrlint: disable=GFL001 — bootstrap\n'
    assert lint(src) == []


# -- GFL002: timestamp discipline ---------------------------------------------

def test_gfl002_flags_unannotated_time_time():
    assert rules_of(lint("import time\nt = time.time()\n")) == ["GFL002"]
    # scripts are not exempt — durations there drift the same way
    assert rules_of(
        lint("import time\nt = time.time()\n", rel="tools/x.py")
    ) == ["GFL002"]


def test_gfl002_monotonic_and_annotated_sites_pass():
    assert lint("import time\nt = time.monotonic()\n") == []
    assert lint("import time\nt = time.perf_counter()\n") == []
    assert lint(
        "import time\nt = time.time()  # gofrlint: wall-clock — log ts\n"
    ) == []
    # the annotation may ride a comment-only line directly above
    assert lint(
        "import time\n# gofrlint: wall-clock — api field\nt = time.time()\n"
    ) == []


# -- GFL003: thread hygiene ---------------------------------------------------

def test_gfl003_unnamed_or_unjoined_threads():
    src = "import threading\nthreading.Thread(target=print).start()\n"
    assert rules_of(lint(src)) == ["GFL003", "GFL003"]  # unnamed AND unjoined
    named_daemon = (
        "import threading\n"
        'threading.Thread(target=print, name="t", daemon=True).start()\n'
    )
    assert lint(named_daemon) == []
    named_joined = (
        "import threading\n"
        't = threading.Thread(target=print, name="t")\n'
        "t.start()\nt.join()\n"
    )
    assert lint(named_joined) == []


def test_gfl003_str_and_path_join_do_not_count_as_thread_joins():
    src = (
        "import threading, os\n"
        't = threading.Thread(target=print, name="t")\n'
        'x = ",".join(["a"])\ny = os.path.join("a", "b")\n'
    )
    assert rules_of(lint(src)) == ["GFL003"]  # still unjoined


# -- GFL004: no blocking under a lock -----------------------------------------

def test_gfl004_sleep_and_timeoutless_queue_get_under_lock():
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            time.sleep(1)\n"
    )
    assert rules_of(lint(src)) == ["GFL004"]
    src_q = (
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            item = self.queue.get()\n"
    )
    assert rules_of(lint(src_q)) == ["GFL004"]


def test_gfl004_allows_timeouts_condition_wait_and_unlocked_calls():
    ok = (
        "import time\n"
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            x = self.queue.get(timeout=1)\n"
        "            self._work.wait()\n"  # Condition releases its lock
        "        time.sleep(1)\n"  # outside the critical section
    )
    assert lint(ok) == []


def test_gfl004_acquire_release_tracking():
    src = (
        "import time\n"
        "def f(lock):\n"
        "    lock.acquire()\n"
        "    time.sleep(1)\n"
        "    lock.release()\n"
        "    time.sleep(1)\n"
    )
    assert rules_of(lint(src)) == ["GFL004"]  # only the held sleep


def test_gfl004_thread_join_under_lock():
    src = (
        "class C:\n"
        "    def close(self):\n"
        "        with self._lock:\n"
        "            self._thread.join()\n"
    )
    assert rules_of(lint(src)) == ["GFL004"]


# -- GFL005: metric naming ----------------------------------------------------

def test_gfl005_convention_enforced_statically():
    bad = 'm.counter("gofr_tpu_requests", "r")\n'
    assert rules_of(lint(bad)) == ["GFL005"]
    assert rules_of(lint('m.histogram("gofr_tpu_latency", "l")\n')) == ["GFL005"]
    assert rules_of(lint('m.gauge("gofr_tpu_stuff", "s")\n')) == ["GFL005"]
    assert rules_of(lint('m.counter("tpu_x_total", "x")\n')) == ["GFL005"]
    assert lint('m.counter("gofr_tpu_requests_total", "r")\n') == []
    assert lint('m.histogram("gofr_tpu_latency_seconds", "l")\n') == []
    assert lint('m.gauge("gofr_tpu_mfu", "roofline")\n') == []  # allowlist
    # dynamically composed names are the runtime test's job, not ours
    assert lint("m.counter(name, 'x')\n") == []


def test_gfl005_mesh_family_covered():
    """The sharded-serving family (tpu/device.py): the _size gauge
    suffix (gofr_tpu_mesh_axis_size{axis}) and the degrade counter pass;
    suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_mesh_axis_size", "a")\n') == []
    assert lint('m.counter("gofr_tpu_mesh_degrade_total", "d")\n') == []
    assert rules_of(lint('m.gauge("gofr_tpu_mesh_axes", "a")\n')) == \
        ["GFL005"]


def test_gfl005_deadline_family_covered():
    """The deadline/brownout family (deadline.py, batcher.py,
    decode_pool.py): the _level gauge suffix and the stage/cause
    counters pass; suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_brownout_level", "b")\n') == []
    assert lint('m.counter("gofr_tpu_deadline_exceeded_total", "d")\n') == []
    assert lint('m.counter("gofr_tpu_cancellations_total", "c")\n') == []
    assert lint('m.counter("gofr_tpu_brownout_shed_total", "s")\n') == []
    assert rules_of(lint('m.gauge("gofr_tpu_brownout", "b")\n')) == \
        ["GFL005"]
    assert rules_of(lint('m.counter("gofr_tpu_deadline_exceeded", "d")\n')) \
        == ["GFL005"]


def test_gfl005_spec_family_covered():
    """The pooled-speculative-decoding family (tpu/spec_pool.py): the
    _ratio and _per_dispatch gauge suffixes pass; suffix drift within
    the family still fails."""
    assert lint('m.gauge("gofr_tpu_spec_accept_ratio", "a")\n') == []
    assert lint(
        'm.gauge("gofr_tpu_spec_tokens_per_dispatch", "t")\n'
    ) == []
    assert rules_of(lint('m.gauge("gofr_tpu_spec_accept", "a")\n')) == \
        ["GFL005"]
    assert rules_of(lint('m.gauge("gofr_tpu_spec_tokens", "t")\n')) == \
        ["GFL005"]


def test_gfl005_router_family_covered():
    """The gofr_tpu_router_* family (fleet/router.py) rides the same
    convention: the suffix table must keep accepting its gauges (_state,
    _depth) and rejecting drift within the family."""
    assert lint('m.gauge("gofr_tpu_router_breaker_state", "b")\n') == []
    assert lint('m.gauge("gofr_tpu_router_outstanding_depth", "o")\n') == []
    assert lint('m.counter("gofr_tpu_router_shed_total", "s")\n') == []
    assert lint('m.histogram("gofr_tpu_router_upstream_seconds", "u")\n') == []
    assert rules_of(lint('m.gauge("gofr_tpu_router_breakers", "b")\n')) == \
        ["GFL005"]
    assert rules_of(lint('m.counter("gofr_tpu_router_sheds", "s")\n')) == \
        ["GFL005"]


def test_gfl005_trace_family_covered():
    """The fleet-tracing family (PR 16): the per-hop latency histogram
    (router.py) and the zipkin exporter drop counter (tracing.py) pass;
    suffix drift within the family still fails."""
    assert lint('m.histogram("gofr_tpu_router_hop_seconds", "h")\n') == []
    assert lint(
        'm.counter("gofr_tpu_trace_export_failures_total", "z")\n'
    ) == []
    assert rules_of(lint('m.histogram("gofr_tpu_router_hop", "h")\n')) == \
        ["GFL005"]
    assert rules_of(
        lint('m.counter("gofr_tpu_trace_export_failures", "z")\n')
    ) == ["GFL005"]


def test_gfl005_costmodel_family_covered():
    """The dispatch cost-model family (tpu/costmodel.py): the residual
    EMA gauge (``_ratio``) and the anomaly counter (``_total``) pass;
    suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_dispatch_residual_ratio", "r")\n') == []
    assert lint(
        'm.counter("gofr_tpu_dispatch_anomalies_total", "a")\n'
    ) == []
    assert rules_of(
        lint('m.gauge("gofr_tpu_dispatch_residual", "r")\n')
    ) == ["GFL005"]
    assert rules_of(
        lint('m.counter("gofr_tpu_dispatch_anomalies", "a")\n')
    ) == ["GFL005"]


def test_gfl005_slo_tenant_family_covered():
    """The SLO/tenant-metering family (slo.py + telemetry.TenantLedger):
    the burn-rate and budget gauges (``_rate``, ``_remaining``), the
    alert counter, and the ledger's tracked-entries gauge all pass;
    suffix drift within the family still fails."""
    assert lint('m.gauge("gofr_tpu_slo_burn_rate", "b")\n') == []
    assert lint('m.gauge("gofr_tpu_slo_budget_remaining", "b")\n') == []
    assert lint(
        'm.counter("gofr_tpu_slo_burn_alerts_total", "a")\n'
    ) == []
    assert lint(
        'm.gauge("gofr_tpu_tenants_tracked_entries", "t")\n'
    ) == []
    assert lint(
        'm.counter("gofr_tpu_tenant_overflow_total", "o")\n'
    ) == []
    assert rules_of(
        lint('m.gauge("gofr_tpu_slo_burn", "b")\n')
    ) == ["GFL005"]
    assert rules_of(
        lint('m.counter("gofr_tpu_slo_burn_alerts", "a")\n')
    ) == ["GFL005"]


# -- GFL006: swallowed exceptions ---------------------------------------------

def test_gfl006_bare_except_everywhere():
    src = "try:\n    x = 1\nexcept:\n    pass\n"
    assert rules_of(lint(src, rel="tools/x.py")) == ["GFL006"]


def test_gfl006_broad_swallow_only_in_engine_paths():
    src = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    assert rules_of(lint(src, rel="gofr_tpu/tpu/x.py")) == ["GFL006"]
    assert rules_of(lint(src, rel="gofr_tpu/timebase.py")) == ["GFL006"]
    assert lint(src, rel="gofr_tpu/handler.py") == []  # request path
    narrow = "try:\n    x = 1\nexcept ValueError:\n    pass\n"
    assert lint(narrow, rel="gofr_tpu/tpu/x.py") == []
    handled = (
        "try:\n    x = 1\nexcept Exception as exc:\n    log(exc)\n"
    )
    assert lint(handled, rel="gofr_tpu/tpu/x.py") == []


def test_gfl006_suppression_sits_on_the_pass_line():
    src = (
        "try:\n    x = 1\nexcept Exception:\n"
        "    pass  # gofrlint: disable=GFL006 — last-resort guard\n"
    )
    assert lint(src, rel="gofr_tpu/tpu/x.py") == []


# -- suppression / annotation robustness --------------------------------------

def test_directives_inside_strings_are_ignored():
    src = 'x = "# gofrlint: disable=GFL002"\nimport time\nt = time.time()\n'
    assert rules_of(lint(src)) == ["GFL002"]


def test_directive_cascades_through_comment_blocks():
    src = (
        "try:\n    x = 1\nexcept Exception:\n"
        "    # gofrlint: disable=GFL006 — reason line one\n"
        "    # ...reason continued on a second line\n"
        "    pass\n"
    )
    assert lint(src, rel="gofr_tpu/tpu/x.py") == []


def test_multi_rule_suppression():
    src = (
        "import os, time\n"
        't = time.time(); x = os.getenv("K")'
        "  # gofrlint: disable=GFL001,GFL002 — fixture\n"
    )
    assert lint(src) == []


# -- output formats / CLI -----------------------------------------------------

def test_json_output_schema(tmp_path):
    bad = tmp_path / "gofr_tpu" / "mod.py"
    bad.parent.mkdir()
    bad.write_text('import os\nx = os.getenv("K")\nimport time\nt = time.time()\n')
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = gofrlint.main(["--format=json", str(tmp_path)])
    assert rc == 1
    out = json.loads(buf.getvalue())
    assert out["version"] == 1
    assert out["files_scanned"] == 1
    assert out["counts_by_rule"] == {"GFL001": 1, "GFL002": 1}
    for v in out["violations"]:
        assert set(v) == {"file", "line", "col", "rule", "message"}
        assert v["rule"] in gofrlint.RULES


def test_clean_tree_exits_zero(tmp_path):
    good = tmp_path / "ok.py"
    good.write_text("import time\nt = time.monotonic()\n")
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = gofrlint.main([str(tmp_path)])
    assert rc == 0
    assert "clean" in buf.getvalue()


def test_syntax_error_is_reported_not_crashed(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    violations, scanned = gofrlint.lint_paths([str(tmp_path)])
    assert scanned == 1
    assert [v.rule for v in violations] == ["GFL000"]


# -- the tree gate ------------------------------------------------------------

def test_the_real_tree_is_clean():
    """The acceptance contract, runnable as a test: the package, tools,
    and bench.py carry zero unsuppressed violations. Same "only
    shrinks" policy as the ruff debt ledger — fix new violations or
    suppress them IN-FILE with a reason."""
    violations, scanned = gofrlint.lint_paths([
        str(REPO / "gofr_tpu"), str(REPO / "tools"), str(REPO / "bench.py")
    ])
    assert scanned > 50
    assert violations == [], "\n".join(
        f"{v.path}:{v.line}: {v.rule} {v.message}" for v in violations
    )


def test_cli_entrypoint_runs(tmp_path):
    """``python tools/gofrlint.py`` stays invocable as a script (the CI
    lint job calls it exactly that way)."""
    import subprocess

    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "gofrlint.py"), str(ok)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
