import json
import threading

from gofr_tpu.tracing import (
    Span,
    Tracer,
    ZipkinExporter,
    current_span,
    current_trace_id,
    init_tracer,
    parse_traceparent,
)


class _ListExporter:
    def __init__(self):
        self.spans = []

    def export(self, span):
        self.spans.append(span)

    def shutdown(self):
        pass


def test_span_nesting_and_ids():
    exp = _ListExporter()
    tracer = Tracer(exp)
    with tracer.start_span("parent", kind="SERVER") as parent:
        assert current_span() is parent
        with tracer.start_span("child") as child:
            assert child.trace_id == parent.trace_id
            assert child.parent_id == parent.span_id
        assert current_span() is parent
    assert current_span() is None
    assert [s.name for s in exp.spans] == ["child", "parent"]
    assert exp.spans[0].end_us >= exp.spans[0].start_us


def test_traceparent_roundtrip():
    tracer = Tracer(_ListExporter())
    with tracer.start_span("root") as root:
        header = root.traceparent()
    parsed = parse_traceparent(header)
    assert parsed == (root.trace_id, root.span_id)
    span = tracer.start_span("continuation", traceparent=header, activate=False)
    assert span.trace_id == root.trace_id
    assert span.parent_id == root.span_id
    span.end()


def test_parse_traceparent_invalid():
    assert parse_traceparent("") is None
    assert parse_traceparent("00-bad") is None
    assert parse_traceparent("00-zz-yy-01") is None


def test_trace_id_as_correlation_id():
    tracer = Tracer(_ListExporter())
    with tracer.start_span("req"):
        assert current_trace_id() is not None
        assert len(current_trace_id()) == 32


def test_zipkin_payload_shape():
    exp = _ListExporter()
    tracer = Tracer(exp)
    with tracer.start_span("GET /hello", kind="SERVER") as s:
        s.set_tag("http.status", 200)
    z = exp.spans[0].to_zipkin("svc")
    assert z["name"] == "GET /hello"
    assert z["kind"] == "SERVER"
    assert z["localEndpoint"] == {"serviceName": "svc"}
    assert z["tags"]["http.status"] == "200"
    json.dumps(z)  # serializable


def test_zipkin_exporter_posts_batch(free_port):
    import http.server

    port = free_port()
    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append(json.loads(body))
            self.send_response(202)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        exp = ZipkinExporter(f"http://127.0.0.1:{port}/api/v2/spans", flush_interval=0.05)
        tracer = Tracer(exp)
        with tracer.start_span("exported"):
            pass
        exp.shutdown()
        assert received and received[0][0]["name"] == "exported"
    finally:
        srv.shutdown()


def test_zipkin_exporter_drops_on_overflow(free_port):
    """Export must NEVER block the hot path: once the bounded queue is
    full, further spans are silently dropped, not queued unboundedly and
    not raised into the serving thread."""
    exp = ZipkinExporter(
        f"http://127.0.0.1:{free_port()}/api/v2/spans",  # nothing listens
        flush_interval=30.0, max_queue=2,
    )
    exp.shutdown()  # stop the draining worker; the queue bound is now hard
    tracer = Tracer(exp)
    for i in range(10):  # far past max_queue — must not raise
        with tracer.start_span(f"overflow-{i}"):
            pass
    assert exp._queue.qsize() <= 2


def test_tracer_shutdown_flushes_pending_spans(free_port):
    """Spans exported just before shutdown must still reach the
    collector even when the flush interval has not elapsed — shutdown
    drains the queue instead of dropping it."""
    import http.server

    port = free_port()
    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append(json.loads(body))
            self.send_response(202)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        # flush_interval far past the test horizon: only the shutdown
        # flush can deliver these
        tracer = Tracer(ZipkinExporter(
            f"http://127.0.0.1:{port}/api/v2/spans", flush_interval=300.0
        ))
        with tracer.start_span("pending-a"):
            pass
        with tracer.start_span("pending-b"):
            pass
        tracer.shutdown()
        names = {s["name"] for batch in received for s in batch}
        assert {"pending-a", "pending-b"} <= names
    finally:
        srv.shutdown()


def test_init_tracer_without_host(monkeypatch):
    from gofr_tpu.config import EnvConfig

    monkeypatch.delenv("TRACER_HOST", raising=False)
    tracer = init_tracer(EnvConfig())
    with tracer.start_span("noop"):
        pass  # must not raise
