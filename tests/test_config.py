"""Config tests. Parity model: reference config behavior (godotenv load,
env-var backing, defaults)."""

import os

from gofr_tpu.config import EnvConfig, EnvFileConfig, parse_env_file


def test_parse_env_file(tmp_path):
    p = tmp_path / ".env"
    p.write_text(
        """
# comment
APP_NAME=test-app
export HTTP_PORT=8001
QUOTED="hello world"
SINGLE='single'
INLINE=value # trailing comment
EMPTY=
NOEQ
""".strip()
    )
    env = parse_env_file(str(p))
    assert env["APP_NAME"] == "test-app"
    assert env["HTTP_PORT"] == "8001"
    assert env["QUOTED"] == "hello world"
    assert env["SINGLE"] == "single"
    assert env["INLINE"] == "value"
    assert env["EMPTY"] == ""
    assert "NOEQ" not in env


def test_env_file_does_not_override_existing(tmp_path, monkeypatch):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("KEEP_ME=from_file\nNEW_KEY=fresh\n")
    monkeypatch.setenv("KEEP_ME", "from_env")
    monkeypatch.delenv("NEW_KEY", raising=False)
    cfg = EnvFileConfig(str(configs))
    assert cfg.get("KEEP_ME") == "from_env"
    assert cfg.get("NEW_KEY") == "fresh"
    os.environ.pop("NEW_KEY", None)


def test_get_or_default(monkeypatch):
    cfg = EnvConfig()
    monkeypatch.delenv("DOES_NOT_EXIST", raising=False)
    assert cfg.get("DOES_NOT_EXIST") is None
    assert cfg.get_or_default("DOES_NOT_EXIST", "8000") == "8000"
    monkeypatch.setenv("EXISTS", "42")
    assert cfg.get_or_default("EXISTS", "8000") == "42"
    monkeypatch.setenv("EMPTYVAL", "")
    assert cfg.get_or_default("EMPTYVAL", "dflt") == "dflt"


def test_missing_env_file_is_fine(tmp_path):
    cfg = EnvFileConfig(str(tmp_path / "nope"))
    assert cfg.get_or_default("ANYTHING_AT_ALL", "x") == "x"
