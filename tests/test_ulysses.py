"""Ulysses all-to-all context parallelism on the 8-device CPU mesh.

Equivalence oracle: the single-device attention / forward / loss — the same
strategy the ring tests use (test_ring.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.llama import TINY
from gofr_tpu.models.transformer import init_transformer, transformer_forward
from gofr_tpu.ops.attention import attention
from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
from gofr_tpu.parallel.ring import make_ring_loss
from gofr_tpu.parallel.ulysses import (
    make_ulysses_forward,
    make_ulysses_loss,
    ulysses_attention,
)

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(mesh_shape_for(8, sp=4))  # dp=2, sp=4


def _sharded_attn(mesh, **kw):
    from jax.sharding import PartitionSpec as P

    return jax.jit(
        jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp", **kw),
            mesh=mesh,
            in_specs=(P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        )
    )


def test_ulysses_attention_matches_single_device(sp_mesh):
    b, s, hq, hkv, d = 2, 32, 4, 2, 16  # hkv=2 does NOT divide sp=4: repeat path
    q = jax.random.normal(jax.random.key(0), (b, s, hq, d))
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d))
    got = _sharded_attn(sp_mesh)(q, k, v)
    want = attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ulysses_attention_divisible_kv_heads():
    # dp=2 x sp=2 over the first 4 devices; hkv=2 divides sp=2: no repeat
    mesh = make_mesh(mesh_shape_for(4, sp=2), devices=jax.devices()[:4])
    b, s, hq, hkv, d = 2, 16, 4, 2, 8
    q = jax.random.normal(jax.random.key(3), (b, s, hq, d))
    k = jax.random.normal(jax.random.key(4), (b, s, hkv, d))
    v = jax.random.normal(jax.random.key(5), (b, s, hkv, d))
    got = _sharded_attn(mesh)(q, k, v)
    want = attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ulysses_head_divisibility_enforced():
    mesh = make_mesh(mesh_shape_for(8, sp=8))  # TINY has 4 heads; sp=8 can't
    cfg = TINY
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.ones((2, 64), jnp.int32)
    with pytest.raises(ValueError, match="n_heads"):
        make_ulysses_forward(cfg, mesh, batch_axes=())(params, tokens)


def test_ulysses_forward_matches_unsharded(sp_mesh):
    cfg = TINY
    params = init_transformer(jax.random.key(3), cfg)
    tokens = jax.random.randint(jax.random.key(4), (4, 64), 0, cfg.vocab_size)
    got = make_ulysses_forward(cfg, sp_mesh)(params, tokens)
    want = transformer_forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ulysses_loss_matches_ring_and_grads_flow(sp_mesh):
    cfg = TINY
    params = init_transformer(jax.random.key(5), cfg)
    tokens = jax.random.randint(jax.random.key(6), (4, 64), 0, cfg.vocab_size)
    u_loss = make_ulysses_loss(cfg, sp_mesh)
    r_loss = make_ring_loss(cfg, sp_mesh)
    lu, gu = jax.value_and_grad(u_loss)(params, tokens)
    lr = r_loss(params, tokens)
    np.testing.assert_allclose(float(lu), float(lr), rtol=1e-5)
    leaves = jax.tree.leaves(gu)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    assert any(float(jnp.abs(g).max()) > 0 for g in leaves)
