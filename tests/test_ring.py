"""Ring attention / sequence parallelism on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.llama import TINY
from gofr_tpu.models.transformer import init_transformer, transformer_forward
from gofr_tpu.ops.attention import attention
from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
from gofr_tpu.parallel.ring import make_ring_forward, make_ring_loss, ring_attention
from gofr_tpu.training.trainer import cross_entropy_loss

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(mesh_shape_for(8, sp=4))  # dp=2, sp=4


def test_ring_attention_matches_single_device(sp_mesh):
    from jax.sharding import PartitionSpec as P

    b, s, hq, hkv, d = 2, 32, 4, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, hq, d))
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d))

    ring = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=True),
            mesh=sp_mesh,
            in_specs=(P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
            out_specs=P("dp", "sp"),
            check_vma=False,
        )
    )
    got = ring(q, k, v)
    want = attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_forward_matches_unsharded(sp_mesh):
    cfg = TINY
    params = init_transformer(jax.random.key(3), cfg)
    tokens = jax.random.randint(jax.random.key(4), (4, 64), 0, cfg.vocab_size)

    fwd = make_ring_forward(cfg, sp_mesh)
    got = fwd(params, tokens)
    want = transformer_forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_loss_matches_unsharded(sp_mesh):
    cfg = TINY
    params = init_transformer(jax.random.key(5), cfg)
    tokens = jax.random.randint(jax.random.key(6), (4, 64), 0, cfg.vocab_size)

    loss_fn = make_ring_loss(cfg, sp_mesh)
    got = float(loss_fn(params, tokens))
    want = float(cross_entropy_loss(params, tokens, cfg))
    assert abs(got - want) < 5e-4, (got, want)


def test_ring_loss_grads_flow(sp_mesh):
    cfg = TINY
    params = init_transformer(jax.random.key(7), cfg)
    tokens = jax.random.randint(jax.random.key(8), (2, 32), 0, cfg.vocab_size)

    loss_fn = make_ring_loss(cfg, sp_mesh)
    grads = jax.jit(jax.grad(lambda p: loss_fn(p, tokens)))(params)
    gnorm = float(
        jnp.sqrt(
            sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in jax.tree.leaves(grads))
        )
    )
    assert np.isfinite(gnorm) and gnorm > 0


def test_ring_forward_rejects_overlong_sequence(sp_mesh):
    cfg = TINY  # max_seq=128; sp=4 × 64 local = 256 global > 128
    params = init_transformer(jax.random.key(9), cfg)
    tokens = jnp.ones((2, 256), jnp.int32)
    fwd = make_ring_forward(cfg, sp_mesh)
    with pytest.raises(ValueError, match="max_seq"):
        fwd(params, tokens)
