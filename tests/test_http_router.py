"""Router + request + responder unit tests (no sockets).

Parity model: reference router/request/responder tests using httptest
recorders (SURVEY.md §4)."""

import asyncio
import json

import pytest

from gofr_tpu.errors import EntityNotFoundError
from gofr_tpu.http.request import Request
from gofr_tpu.http.responder import respond
from gofr_tpu.http.response import File, Raw, Response, Stream
from gofr_tpu.http.router import Router


def _req(method="GET", target="/", headers=None, body=b""):
    return Request(method, target, headers or {}, body)


def run(coro):
    # full teardown, not just run_until_complete: responder streams pull
    # sync iterators through the loop's default executor, and a loop
    # abandoned without shutdown_default_executor() leaks its non-daemon
    # "asyncio_N" worker until interpreter exit (found by the
    # GOFR_SANITIZE=1 thread-leak check)
    loop = asyncio.new_event_loop()
    try:
        result = loop.run_until_complete(coro)
        loop.run_until_complete(loop.shutdown_default_executor())
        return result
    finally:
        loop.close()


def test_path_params_and_methods():
    router = Router()

    async def user(req):
        return Response(body=req.path_params["id"].encode())

    router.add("GET", "/user/{id}", user)
    resp = run(router.dispatcher()(_req(target="/user/42")))
    assert resp.body == b"42"


def test_405_with_allow_header():
    router = Router()

    async def ep(req):
        return Response()

    router.add("GET", "/thing", ep)
    resp = run(router.dispatcher()(_req(method="POST", target="/thing")))
    assert resp.status == 405
    assert resp.headers["Allow"] == "GET"


def test_catch_all_404():
    router = Router()

    async def nf(req):
        return Response(status=404, body=b"nope")

    router.set_not_found(nf)
    resp = run(router.dispatcher()(_req(target="/missing")))
    assert resp.status == 404 and resp.body == b"nope"


def test_strict_slash_off():
    router = Router()

    async def ep(req):
        return Response(body=b"hit")

    router.add("GET", "/abc", ep)
    assert run(router.dispatcher()(_req(target="/abc/"))).body == b"hit"


def test_head_matches_get_route():
    router = Router()

    async def ep(req):
        return Response(body=b"payload")

    router.add("GET", "/x", ep)
    assert run(router.dispatcher()(_req(method="HEAD", target="/x"))).body == b"payload"


def test_middleware_order():
    router = Router()
    calls = []

    def mw(tag):
        def middleware(next_ep):
            async def endpoint(req):
                calls.append(tag)
                return await next_ep(req)

            return endpoint

        return middleware

    async def ep(req):
        calls.append("handler")
        return Response()

    router.add("GET", "/", ep)
    router.use(mw("outer"), mw("inner"))
    run(router.dispatcher()(_req(target="/")))
    assert calls == ["outer", "inner", "handler"]


def test_request_facade():
    req = Request(
        "POST",
        "/users/7/posts?limit=10&tag=a&tag=b",
        {"Host": "svc.local", "X-Forwarded-Proto": "https", "Content-Type": "application/json"},
        b'{"title": "hi", "views": 3}',
        remote_addr="1.2.3.4",
        path_params={"uid": "7"},
    )
    assert req.param("limit") == "10"
    assert req.params("tag") == ["a", "b"]
    assert req.param("missing") == ""
    assert req.path_param("uid") == "7"
    assert req.host_name() == "https://svc.local"
    assert req.header("content-TYPE") == "application/json"
    data = req.bind()
    assert data == {"title": "hi", "views": 3}

    import dataclasses

    @dataclasses.dataclass
    class Post:
        title: str = ""
        views: int = 0

    post = req.bind(Post)
    assert post.title == "hi" and post.views == 3


def test_envelope_success_and_error():
    ok = respond({"name": "x"}, None)
    assert ok.status == 200
    assert json.loads(ok.body) == {"data": {"name": "x"}}

    err = respond(None, EntityNotFoundError("user", "9"))
    assert err.status == 404
    assert json.loads(err.body)["error"]["message"] == "No 'user' found for value '9'"

    unknown = respond(None, RuntimeError("boom"))
    assert unknown.status == 500


def test_raw_and_file_responses():
    raw = respond(Raw([1, 2, 3]), None)
    assert json.loads(raw.body) == [1, 2, 3]

    f = respond(File(b"\x00\x01", content_type="image/x-icon"), None)
    assert f.body == b"\x00\x01"
    assert f.headers["Content-Type"] == "image/x-icon"


def test_stream_response_sse_framing():
    async def collect():
        resp = respond(Stream(iter(["tok1", {"t": 2}])), None)
        chunks = []
        async for c in resp.stream:
            chunks.append(c)
        return chunks

    chunks = run(collect())
    assert chunks[0] == b"data: tok1\n\n"
    assert chunks[1] == b'data: {"t": 2}\n\n'
