"""Pipeline parallelism (pp axis): GPipe schedule must be numerically
identical to the plain single-device forward — same params, same tokens,
stages are just a partition of the layer stack."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_forward,
)
from gofr_tpu.parallel.mesh import make_mesh, mesh_shape_for
from gofr_tpu.parallel.pipeline import (
    make_pipeline_forward,
    make_pipeline_loss,
    place_pipeline_params,
)
from gofr_tpu.training.trainer import cross_entropy_loss

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow

CFG = TransformerConfig(
    vocab_size=97, dim=16, n_layers=4, n_heads=4, n_kv_heads=2,
    hidden_dim=32, max_seq=64, dtype=jnp.float32, attn_impl="xla",
)


@pytest.fixture(scope="module")
def params():
    return init_transformer(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.key(1), (8, 12), 0, CFG.vocab_size)


def test_pipeline_forward_matches_plain(params, tokens):
    mesh = make_mesh(mesh_shape_for(8, pp=4), devices=jax.devices()[:8])
    fwd = make_pipeline_forward(CFG, mesh, n_micro=2)
    got = np.asarray(fwd(place_pipeline_params(params, mesh), tokens))
    want = np.asarray(transformer_forward(params, tokens, CFG))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_pipeline_forward_pp2_with_dp(params, tokens):
    mesh = make_mesh(mesh_shape_for(8, pp=2, fsdp=2), devices=jax.devices()[:8])
    fwd = make_pipeline_forward(CFG, mesh, n_micro=2)
    got = np.asarray(fwd(place_pipeline_params(params, mesh), tokens))
    want = np.asarray(transformer_forward(params, tokens, CFG))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_pipeline_loss_and_grads_match_plain(params, tokens):
    mesh = make_mesh(mesh_shape_for(8, pp=4), devices=jax.devices()[:8])
    loss_fn = make_pipeline_loss(CFG, mesh, n_micro=2)
    placed = place_pipeline_params(params, mesh)

    got_loss, got_grads = jax.value_and_grad(loss_fn)(placed, tokens)
    want_loss, want_grads = jax.value_and_grad(
        lambda p, t: cross_entropy_loss(p, t, CFG)
    )(params, tokens)

    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-4)
    for key in ("embed", "lm_head", "norm_f"):
        np.testing.assert_allclose(
            np.asarray(got_grads[key]), np.asarray(want_grads[key]),
            rtol=5e-3, atol=1e-5, err_msg=key,
        )
    for key in ("wq", "w_down", "attn_norm"):
        np.testing.assert_allclose(
            np.asarray(got_grads["layers"][key]),
            np.asarray(want_grads["layers"][key]),
            rtol=5e-3, atol=1e-5, err_msg=f"layers.{key}",
        )


def test_pipeline_rejects_indivisible_microbatch(params):
    mesh = make_mesh(mesh_shape_for(8, pp=4), devices=jax.devices()[:8])
    fwd = make_pipeline_forward(CFG, mesh, n_micro=3)
    bad = jnp.ones((8, 12), jnp.int32)  # 8 % 3 != 0
    with pytest.raises(ValueError, match="n_micro"):
        fwd(place_pipeline_params(params, mesh), bad)
