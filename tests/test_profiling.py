"""Device profiler: jax trace capture via the admin endpoints and the
per-batch device-time span tags (SURVEY.md §5 profiling hooks)."""

import json
import urllib.error
import urllib.request

import pytest

import gofr_tpu
from gofr_tpu.profiling import Profiler

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def test_profiler_lifecycle(tmp_path):
    import jax
    import jax.numpy as jnp

    p = Profiler()
    assert p.status() == {"state": "idle"}
    out = p.start(str(tmp_path / "trace"))
    assert out["state"] == "tracing"
    # some device work so the trace has content
    jnp.ones((8, 8)).sum().block_until_ready()
    jax.effects_barrier()
    assert p.status()["state"] == "tracing"
    stopped = p.stop()
    assert stopped["state"] == "stopped"
    assert stopped["artifacts"], "trace capture produced no artifact files"
    assert p.status() == {"state": "idle"}


def test_profiler_double_start_rejected(tmp_path):
    p = Profiler()
    p.start(str(tmp_path / "t"))
    with pytest.raises(RuntimeError, match="already tracing"):
        p.start(str(tmp_path / "t2"))
    p.stop()
    with pytest.raises(RuntimeError, match="not tracing"):
        p.stop()


@pytest.fixture
def app(free_port, monkeypatch, tmp_path):
    monkeypatch.setenv("HTTP_PORT", str(free_port()))
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    for key in ("REDIS_HOST", "DB_NAME", "DB_HOST", "TPU_ENABLED", "MODEL_NAME"):
        monkeypatch.delenv(key, raising=False)
    monkeypatch.chdir(tmp_path)
    application = gofr_tpu.new()
    yield application
    application.shutdown()


def test_admin_profiler_endpoints(app, tmp_path):
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"

    def call(method, path, body=None):
        req = urllib.request.Request(
            base + path, method=method,
            data=json.dumps(body).encode() if body else None,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())["data"]

    def active_gauge():
        return app.container.metrics.gauge("gofr_tpu_profiler_active").value()

    assert call("GET", "/admin/profiler") == {"state": "idle"}
    assert active_gauge() == 0.0
    trace_dir = str(tmp_path / "prof")
    started = call("POST", "/admin/profiler/start", {"dir": trace_dir})
    assert started["state"] == "tracing" and started["dir"] == trace_dir
    assert active_gauge() == 1.0  # the left-running-trace alert signal
    # duplicate start -> 409 (rejecting beats silently restarting the
    # trace: a restart would discard the in-flight capture)
    try:
        call("POST", "/admin/profiler/start")
        raise AssertionError("expected 409")
    except urllib.error.HTTPError as e:
        assert e.code == 409
    assert active_gauge() == 1.0  # the rejected start did not clear it
    import jax.numpy as jnp

    jnp.ones((4, 4)).sum().block_until_ready()
    stopped = call("POST", "/admin/profiler/stop")
    assert stopped["state"] == "stopped"
    assert stopped["artifacts"]
    assert active_gauge() == 0.0
    assert call("GET", "/admin/profiler") == {"state": "idle"}


def test_batch_span_tags(monkeypatch):
    """Every dispatched batch records device time on a tpu-batch span."""
    import os

    from gofr_tpu.config import EnvConfig
    from gofr_tpu.logging import Level
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger
    from gofr_tpu.tpu.device import new_device
    from gofr_tpu.tracing import get_tracer

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
           "DECODE_POOL": "off"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    spans = []
    unpatch = None
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        tracer = get_tracer()
        orig = tracer.start_span

        def spy(name, **kw):
            span = orig(name, **kw)
            if name == "tpu-batch":
                spans.append(span)
            return span

        tracer.start_span = spy
        unpatch = lambda: setattr(tracer, "start_span", orig)  # noqa: E731
        try:
            device.infer({"tokens": [1, 2, 3]})
            assert spans, "no tpu-batch span recorded"
            tags = spans[-1].tags
            assert tags["tpu.batch_size"] == "1"
            assert int(tags["tpu.device_time_us"]) > 0
            assert tags["tpu.model"] == "tiny"
        finally:
            device.close()
    finally:
        if unpatch:
            unpatch()
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_admin_token_gates_profiler(free_port, monkeypatch, tmp_path):
    import gofr_tpu

    monkeypatch.setenv("HTTP_PORT", str(free_port()))
    monkeypatch.setenv("LOG_LEVEL", "FATAL")
    monkeypatch.setenv("ADMIN_TOKEN", "s3cret")
    for key in ("REDIS_HOST", "DB_NAME", "DB_HOST", "TPU_ENABLED", "MODEL_NAME"):
        monkeypatch.delenv(key, raising=False)
    monkeypatch.chdir(tmp_path)
    application = gofr_tpu.new()
    application.start()
    base = f"http://127.0.0.1:{application.http_port}"
    try:
        try:
            urllib.request.urlopen(base + "/admin/profiler", timeout=30)
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        req = urllib.request.Request(
            base + "/admin/profiler",
            headers={"Authorization": "Bearer s3cret"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["data"] == {"state": "idle"}
        # wrong token also rejected
        req = urllib.request.Request(
            base + "/admin/profiler",
            headers={"Authorization": "Bearer wrong"},
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
    finally:
        application.shutdown()
