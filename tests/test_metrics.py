from gofr_tpu.metrics import Counter, Gauge, Histogram, Registry, Timer


def test_counter_and_labels():
    reg = Registry()
    c = reg.counter("gofr_http_requests_total", "reqs", labels=("method", "status"))
    c.inc(method="GET", status="200")
    c.inc(2, method="GET", status="200")
    c.inc(method="POST", status="500")
    assert c.value(method="GET", status="200") == 3
    text = reg.expose()
    assert 'gofr_http_requests_total{method="GET",status="200"} 3' in text
    assert "# TYPE gofr_http_requests_total counter" in text


def test_gauge():
    g = Gauge("queue_depth", "")
    g.set(5)
    g.dec()
    assert g.value() == 4


def test_histogram_exposition_and_percentile():
    h = Histogram("lat", "latency", buckets=(0.1, 0.5, 1.0))
    for v in (0.05, 0.06, 0.2, 0.7, 2.0):
        h.observe(v)
    text = "\n".join(h.expose())
    assert 'lat_bucket{le="0.1"} 2' in text
    assert 'lat_bucket{le="0.5"} 3' in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text
    assert h.percentile(0.5) == 0.5
    assert h.percentile(0.99) == 1.0


def test_registry_reuse_and_type_conflict():
    reg = Registry()
    a = reg.counter("x", "")
    b = reg.counter("x", "")
    assert a is b
    try:
        reg.gauge("x", "")
        raise AssertionError("expected TypeError")
    except TypeError:
        pass


def test_unlabeled_counter_exposes_zero():
    reg = Registry()
    reg.counter("never_incremented", "")
    assert "never_incremented 0" in reg.expose()


def test_timer():
    h = Histogram("t", "", buckets=(10.0,))
    with Timer(h):
        pass
    assert h.percentile(0.5) == 10.0  # bucketed upper bound


def test_reads_locked_against_concurrent_writes():
    """value()/percentile() take the same lock as the write paths:
    hammering reads during concurrent writes must never raise (dict
    resize during iteration) and the final value must be exact."""
    import threading

    c = Counter("c_total", "", ("k",))
    h = Histogram("h_seconds", "", ("k",), buckets=(0.5, 1.0))
    stop = threading.Event()
    failures = []

    def reader():
        try:
            while not stop.is_set():
                c.value(k="w0")
                h.percentile(0.5, k="w0")
        except Exception as exc:  # pragma: no cover - the failure mode
            failures.append(exc)

    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers:
        t.start()
    writers = []
    for w in range(4):
        def write(w=w):
            for i in range(500):
                c.inc(k=f"w{w}-{i % 50}")
                h.observe(0.2, k=f"w{w}-{i % 50}")

        writers.append(threading.Thread(target=write))
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not failures
    assert sum(c.value(k=f"w0-{i}") for i in range(50)) == 500
