"""On-device sampling: temperature / top-k / top-p semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.sampling import Sampler, sample_logits

# XLA-compile-dominated module: deselect with -m 'not slow' for the
# fast developer loop (CI runs everything; CONTRIBUTING.md)
pytestmark = pytest.mark.slow


def _logits(vals):
    return jnp.asarray([vals], jnp.float32)


def test_temperature_zero_is_argmax():
    logits = _logits([0.1, 3.0, 0.2, 1.0])
    out = sample_logits(logits, jax.random.key(0), temperature=0.0)
    assert int(out[0]) == 1


def test_low_temperature_concentrates():
    logits = _logits([0.0, 5.0, 0.0, 0.0])
    keys = jax.random.split(jax.random.key(1), 64)
    picks = [int(sample_logits(logits, k, temperature=0.1)[0]) for k in keys]
    assert all(p == 1 for p in picks)


def test_high_temperature_spreads():
    logits = _logits([0.0, 2.0, 0.0, 0.0])
    keys = jax.random.split(jax.random.key(2), 200)
    picks = {int(sample_logits(logits, k, temperature=50.0)[0]) for k in keys}
    assert len(picks) >= 3  # near-uniform across the vocab


def test_top_k_masks_tail():
    logits = _logits([5.0, 4.0, 3.0, 2.0, 1.0])
    keys = jax.random.split(jax.random.key(3), 100)
    picks = {int(sample_logits(logits, k, temperature=10.0, top_k=2)[0]) for k in keys}
    assert picks <= {0, 1}
    assert len(picks) == 2


def test_top_p_nucleus_masks_tail():
    # probs ~ [0.67, 0.24, 0.09/2, 0.09/2...]: top_p=0.7 keeps {0, 1}
    logits = _logits([3.0, 2.0, 1.0, 1.0])
    keys = jax.random.split(jax.random.key(4), 200)
    picks = {int(sample_logits(logits, k, temperature=1.0, top_p=0.7)[0]) for k in keys}
    assert picks <= {0, 1}, picks


def test_top_p_always_keeps_argmax():
    logits = _logits([1.0, 1.1, 1.0, 1.0])
    out = sample_logits(logits, jax.random.key(5), temperature=1.0, top_p=1e-9)
    assert int(out[0]) == 1


def test_batched_sampling_shape():
    logits = jnp.tile(_logits([1.0, 2.0, 3.0]), (5, 1))
    out = sample_logits(logits, jax.random.key(6), temperature=1.0)
    assert out.shape == (5,)
    assert out.dtype == jnp.int32


def test_sampler_seed_reproducible():
    logits = np.asarray([0.0, 1.0, 2.0, 1.5], np.float32)
    a = Sampler(temperature=1.0, seed=42)
    b = Sampler(temperature=1.0, seed=42)
    c = Sampler(temperature=1.0, seed=43)
    seq_a = [a.pick(logits) for _ in range(8)]
    seq_b = [b.pick(logits) for _ in range(8)]
    seq_c = [c.pick(logits) for _ in range(8)]
    assert seq_a == seq_b
    assert seq_a != seq_c  # overwhelmingly likely


def test_sampler_validation():
    with pytest.raises(ValueError):
        Sampler(temperature=-1)
    with pytest.raises(ValueError):
        Sampler(top_k=-1)
    with pytest.raises(ValueError):
        Sampler(top_p=0.0)
    with pytest.raises(ValueError):
        Sampler(top_p=1.5)


def test_device_generate_with_sampler():
    import os

    from gofr_tpu.config import EnvConfig
    from gofr_tpu.logging import Level
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger
    from gofr_tpu.tpu.device import new_device

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        device = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            greedy = device.generate([1, 2, 3], max_new_tokens=6)
            seeded = device.generate(
                [1, 2, 3], max_new_tokens=6,
                sampler=Sampler(temperature=1.0, top_k=40, seed=7),
            )
            again = device.generate(
                [1, 2, 3], max_new_tokens=6,
                sampler=Sampler(temperature=1.0, top_k=40, seed=7),
            )
            assert seeded == again  # same seed, same tokens
            assert len(seeded) == 6
            assert greedy == device.generate([1, 2, 3], max_new_tokens=6)
        finally:
            device.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_unseeded_samplers_differ():
    logits = np.asarray([1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0], np.float32)
    seqs = {tuple(Sampler(temperature=5.0).pick(logits) for _ in range(12)) for _ in range(4)}
    assert len(seqs) > 1, "unseeded sampling must not be deterministic across requests"


def test_dynamic_top_k_no_recompile():
    # varying request-supplied top_k must reuse ONE compiled executable
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0, 5.0]])
    base = sample_logits._cache_size() if hasattr(sample_logits, "_cache_size") else None
    for k in (1, 2, 3, 4, 0):
        sample_logits(logits, jax.random.key(k), temperature=1.0, top_k=k)
    if base is not None:
        assert sample_logits._cache_size() <= base + 1
    # semantics: top_k=1 at temperature>0 always picks the argmax
    picks = {int(sample_logits(logits, jax.random.key(i), temperature=5.0, top_k=1)[0])
             for i in range(20)}
    assert picks == {4}


def test_min_p_filters_scale_aware():
    # probs ~ [0.5, 0.3, 0.15, 0.05]; min_p=0.5 keeps tokens with
    # p >= 0.25 -> only ids 0 and 1 can ever sample
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    picks = {
        int(sample_logits(logits, jax.random.key(i), temperature=1.0,
                          min_p=0.5)[0])
        for i in range(60)
    }
    assert picks <= {0, 1} and 0 in picks
    # min_p ~1 degenerates to argmax whatever the temperature
    picks = {
        int(sample_logits(logits, jax.random.key(i), temperature=8.0,
                          min_p=0.99)[0])
        for i in range(20)
    }
    assert picks == {0}
    # min_p=0 is off: the tail stays reachable at high temperature
    picks = {
        int(sample_logits(logits, jax.random.key(i), temperature=8.0)[0])
        for i in range(80)
    }
    assert len(picks) >= 3


def test_min_p_rows_and_sampler_body():
    from gofr_tpu.ops.sampling import sample_logits_rows

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05],
                                  [0.5, 0.3, 0.15, 0.05]]))
    # row 0: min_p strict; row 1: off — one dispatch, different behavior
    got = {0: set(), 1: set()}
    for i in range(60):
        ids = sample_logits_rows(
            logits, jax.random.key(i),
            jnp.asarray([1.0, 8.0]), jnp.asarray([0, 0]),
            jnp.asarray([1.0, 1.0]), jnp.asarray([0.5, 0.0]),
        )
        got[0].add(int(ids[0]))
        got[1].add(int(ids[1]))
    assert got[0] <= {0, 1}
    assert len(got[1]) >= 3
    # request-body parse + validation
    s = Sampler.from_body({"temperature": 1.0, "min_p": 0.3})
    assert s.min_p == 0.3
    import pytest as _pytest

    with _pytest.raises(ValueError, match="min_p"):
        Sampler(min_p=1.5)


def test_apply_repetition_penalty_semantics():
    from gofr_tpu.ops.sampling import apply_repetition_penalty

    logits = jnp.asarray([[2.0, -2.0, 1.0, 3.0]])
    presence = jnp.asarray([[True, True, False, False]])
    out = np.asarray(apply_repetition_penalty(logits, presence, 2.0))
    # present positive logit divided, present negative multiplied,
    # absent logits untouched
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0, 3.0]])
    # penalty 1.0 is the identity
    out1 = np.asarray(apply_repetition_penalty(logits, presence, 1.0))
    np.testing.assert_allclose(out1, np.asarray(logits))


def test_repetition_penalty_blocks_repeats_end_to_end():
    import os

    from gofr_tpu.config import EnvConfig
    from gofr_tpu.logging import Level
    from gofr_tpu.metrics import Registry
    from gofr_tpu.testutil import MockLogger
    from gofr_tpu.tpu.device import new_device

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
           "DECODE_CHUNK": "4"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        dev = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            plain = dev.generate([1, 2, 3], max_new_tokens=10)
            assert len(set(plain)) < len(plain), "tiny greedy should repeat"
            # an extreme penalty forbids every previously seen token
            no_rep = dev.generate(
                [1, 2, 3], max_new_tokens=10,
                sampler=Sampler(repetition_penalty=1e6),
            )
            assert len(set(no_rep)) == len(no_rep), no_rep
            assert not (set(no_rep) & {1, 2, 3})  # prompt tokens banned too
            # penalty 1.0 keeps the plain greedy sequence (pool path)
            assert dev.generate(
                [1, 2, 3], max_new_tokens=10,
                sampler=Sampler(repetition_penalty=1.0),
            ) == plain
            # seeded + penalty reproduces exactly
            a = dev.generate([1, 2, 3], max_new_tokens=8,
                             sampler=Sampler(temperature=1.0, seed=3,
                                             repetition_penalty=1.5))
            b = dev.generate([1, 2, 3], max_new_tokens=8,
                             sampler=Sampler(temperature=1.0, seed=3,
                                             repetition_penalty=1.5))
            assert a == b
        finally:
            dev.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_repetition_penalty_body_parse_and_validation():
    s = Sampler.from_body({"repetition_penalty": 1.3})
    assert s.repetition_penalty == 1.3
    import pytest as _pytest

    with _pytest.raises(ValueError, match="repetition_penalty"):
        Sampler(repetition_penalty=0.0)


def test_apply_penalties_semantics():
    """presence_penalty subtracts once per generated token, frequency
    scales with its count, and the CTRL repetition penalty composes over
    the context mask — all in one fused application."""
    from gofr_tpu.ops.sampling import apply_penalties, update_counts

    logits = jnp.asarray([[2.0, -2.0, 1.0, 3.0]])
    presence = jnp.asarray([[True, True, False, False]])
    counts = jnp.asarray([[1.0, 0.0, 3.0, 0.0]])
    out = np.asarray(apply_penalties(logits, presence, 2.0, counts, 0.5, 0.25))
    # token0: 2/2 (rep) - 0.5 (presence) - 0.25*1 (freq) = 0.25
    # token1: -2*2 (rep), counts 0 -> -4
    # token2: no context presence; 1 - 0.5 - 0.25*3 = -0.25
    # token3: untouched
    np.testing.assert_allclose(out, [[0.25, -4.0, -0.25, 3.0]])
    # zero penalties with zero counts is exactly the repetition-only path
    out0 = np.asarray(
        apply_penalties(logits, presence, 2.0, jnp.zeros_like(counts))
    )
    from gofr_tpu.ops.sampling import apply_repetition_penalty

    np.testing.assert_allclose(
        out0, np.asarray(apply_repetition_penalty(logits, presence, 2.0))
    )
    # update_counts accumulates per occurrence
    c = update_counts(counts, jnp.asarray([2]))
    np.testing.assert_allclose(np.asarray(c), [[1.0, 0.0, 4.0, 0.0]])
    # logit_bias rides the same application, added AFTER the penalties
    from gofr_tpu.ops.sampling import bias_row_from_map

    bias = bias_row_from_map({1: 5.0, 3: -100.0}, 4)
    out_b = np.asarray(
        apply_penalties(logits, presence, 2.0, counts, 0.5, 0.25, bias)
    )
    np.testing.assert_allclose(out_b, [[0.25, 1.0, -0.25, -97.0]])
    import pytest as _pytest

    with _pytest.raises(ValueError, match="vocab"):
        bias_row_from_map({7: 1.0}, 4)


def test_logit_bias_end_to_end():
    from gofr_tpu.testutil import serving_device

    with serving_device(DECODE_CHUNK="4") as dev:
        plain = dev.generate([1, 2, 3], max_new_tokens=8)
        # ban the first greedy pick: generation must route around it
        banned = dev.generate(
            [1, 2, 3], max_new_tokens=8,
            sampler=Sampler(logit_bias={plain[0]: -100.0}),
        )
        assert banned[0] != plain[0]
        assert plain[0] not in banned
        # +100 forces a token at EVERY step (bias applies to the first
        # generated token too, unlike the generated-only penalties)
        forced = dev.generate(
            [1, 2, 3], max_new_tokens=6,
            sampler=Sampler(logit_bias={42: 100.0}),
        )
        assert forced == [42] * 6
        # out-of-vocab ids are a parameter error, not a silent drop
        from gofr_tpu.errors import InvalidParamError

        with pytest.raises(InvalidParamError, match="vocab"):
            dev.generate(
                [1, 2], max_new_tokens=2,
                sampler=Sampler(logit_bias={10 ** 9: -1.0}),
            )
    # parse/validation: string keys (JSON), range check, type check
    s = Sampler.from_body({"logit_bias": {"5": -100, "9": 2.5}})
    assert s.logit_bias == {5: -100.0, 9: 2.5} and s.penalized
    with pytest.raises(ValueError, match="logit_bias"):
        Sampler(logit_bias={"5": 101.0})
    with pytest.raises(ValueError, match="logit_bias"):
        Sampler(logit_bias={"x": 1.0})
    with pytest.raises(ValueError, match="logit_bias"):
        Sampler(logit_bias=[5])
    assert not Sampler(logit_bias={}).penalized


def test_presence_frequency_penalty_end_to_end():
    from gofr_tpu.testutil import serving_device

    with serving_device(DECODE_CHUNK="4") as dev:
        plain = dev.generate([1, 2, 3], max_new_tokens=10)
        assert len(set(plain)) < len(plain), "tiny greedy should repeat"
        # max-strength additive penalties on a tiny model (logits O(1))
        # steer greedy away from the repeating sequence
        pen = dev.generate(
            [1, 2, 3], max_new_tokens=10,
            sampler=Sampler(presence_penalty=2.0, frequency_penalty=2.0),
        )
        assert pen != plain
        # penalties are over GENERATED tokens only: a fresh request's
        # first token is unpenalized, so it matches plain greedy
        assert pen[0] == plain[0]
        # zero-valued penalties stay on the plain path (pool-eligible)
        assert dev.generate(
            [1, 2, 3], max_new_tokens=10,
            sampler=Sampler(presence_penalty=0.0),
        ) == plain
        # seeded + penalties reproduce exactly
        a = dev.generate([1, 2, 3], max_new_tokens=8,
                         sampler=Sampler(temperature=1.0, seed=5,
                                         presence_penalty=1.0,
                                         frequency_penalty=0.5))
        b = dev.generate([1, 2, 3], max_new_tokens=8,
                         sampler=Sampler(temperature=1.0, seed=5,
                                         presence_penalty=1.0,
                                         frequency_penalty=0.5))
        assert a == b
        # range validation per the OpenAI spec
        import pytest as _pytest

        with _pytest.raises(ValueError, match="presence_penalty"):
            Sampler(presence_penalty=2.5)
        with _pytest.raises(ValueError, match="frequency_penalty"):
            Sampler(frequency_penalty=-2.5)
        s = Sampler.from_body({"presence_penalty": 0.5,
                               "frequency_penalty": 0.25})
        assert s.presence_penalty == 0.5 and s.penalized


def test_logprobs_match_teacher_forcing():
    """generate(logprobs=True): returned values must equal the log-softmax
    the full no-cache forward assigns to each emitted token at its
    position — the decode path's logprobs are real model logprobs."""
    import os

    from gofr_tpu.config import EnvConfig
    from gofr_tpu.logging import Level
    from gofr_tpu.metrics import Registry
    from gofr_tpu.models.llama import TINY
    from gofr_tpu.models.transformer import transformer_forward
    from gofr_tpu.testutil import MockLogger
    from gofr_tpu.tpu.device import new_device

    env = {"MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
           "DECODE_CHUNK": "4"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        dev = new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
        try:
            prompt = [1, 2, 3]
            toks, lps = dev.generate(prompt, max_new_tokens=7, logprobs=True)
            assert toks == dev.generate(prompt, max_new_tokens=7)
            assert len(lps) == len(toks) == 7
            assert all(lp <= 0.0 for lp in lps)
            # teacher-forcing recompute over [prompt + toks]
            full = jnp.asarray([prompt + toks], jnp.int32)
            logits = transformer_forward(dev.runner.params, full, TINY)
            ref = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
            for i, (t, lp) in enumerate(zip(toks, lps)):
                pos = len(prompt) - 1 + i  # logits at pos predict token i
                np.testing.assert_allclose(
                    lp, float(ref[pos, t]), rtol=1e-4, atol=1e-4
                )
            # seeded sampled + logprobs reproduces
            a = dev.generate(prompt, max_new_tokens=5, logprobs=True,
                             sampler=Sampler(temperature=1.0, seed=2))
            b = dev.generate(prompt, max_new_tokens=5, logprobs=True,
                             sampler=Sampler(temperature=1.0, seed=2))
            assert a == b
            # penalty + logprobs compose; logprobs stay RAW model values
            pt, pl = dev.generate(prompt, max_new_tokens=5, logprobs=True,
                                  sampler=Sampler(repetition_penalty=1e6))
            assert pt == dev.generate(prompt, max_new_tokens=5,
                                      sampler=Sampler(repetition_penalty=1e6))
            full = jnp.asarray([prompt + pt], jnp.int32)
            ref = jax.nn.log_softmax(
                transformer_forward(dev.runner.params, full, TINY)[0]
                .astype(jnp.float32), axis=-1,
            )
            for i, (t, lp) in enumerate(zip(pt, pl)):
                np.testing.assert_allclose(
                    lp, float(ref[len(prompt) - 1 + i, t]), rtol=1e-4, atol=1e-4
                )
        finally:
            dev.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def test_stream_logprobs_match_blocking():
    from gofr_tpu.testutil import serving_device

    with serving_device(DECODE_CHUNK="4") as dev:
        toks, lps = dev.generate([1, 2, 3], max_new_tokens=6, logprobs=True)
        streamed = list(dev.generate_stream([1, 2, 3], max_new_tokens=6,
                                            logprobs=True))
        assert [t for t, _ in streamed] == toks
        assert [round(lp, 5) for _, lp in streamed] == [round(x, 5) for x in lps]
        # without the flag the stream still yields bare ints
        plain = list(dev.generate_stream([1, 2, 3], max_new_tokens=3))
        assert all(isinstance(t, int) for t in plain)
