"""Deadline-aware serving: end-to-end request deadlines, client-abort
cancellation, and overload brownout (PR 10).

Units: deadline/priority parsing and clamping, the batcher's
pre-dispatch shed, the brownout controller's graded levels, and the
fleet router's remaining-budget forwarding across a retry.

Chaos e2e (echo runner — the full serving stack, compile-free): under
a saturated decode path (a) a 50 ms-deadline request sheds at the
queue/admission stage and never reaches the device, (b) a client that
hard-closes its SSE stream mid-decode has its KV blocks reclaimed
within one chunk, and (c) with brownout armed low-priority requests
429 while high-priority requests keep serving — all asserted through
/admin/engine, /admin/requests, and the new counters.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from gofr_tpu.deadline import (
    BrownoutController,
    Deadline,
    activate_deadline,
    parse_deadline,
    parse_priority,
)
from gofr_tpu.errors import DeadlineExceeded


# -- parsing / clamping units -------------------------------------------------

def test_parse_deadline_header_wins_over_default():
    d = parse_deadline("250", 30.0, priority=7)
    assert d is not None
    assert d.budget_s == pytest.approx(0.25)
    assert d.priority == 7
    assert 0 < d.remaining() <= 0.25


def test_parse_deadline_default_applies_without_header():
    d = parse_deadline(None, 1.5)
    assert d is not None
    assert d.budget_s == pytest.approx(1.5)


def test_parse_deadline_off_preserves_old_behavior():
    assert parse_deadline(None, 0.0) is None
    assert parse_deadline("", 0.0) is None
    # an explicit 0 header opts OUT of a configured default
    assert parse_deadline("0", 30.0) is None


def test_parse_deadline_rejects_garbage():
    from gofr_tpu.errors import HTTPError

    with pytest.raises(HTTPError):
        parse_deadline("soon", 0.0)
    with pytest.raises(HTTPError):
        parse_deadline("-5", 0.0)


def test_parse_priority_clamps_and_rejects():
    from gofr_tpu.errors import HTTPError

    assert parse_priority(None) == 5
    assert parse_priority("", default=3) == 3
    assert parse_priority("7") == 7
    assert parse_priority("99") == 9  # clamped into the tier range
    assert parse_priority("-4") == 0
    with pytest.raises(HTTPError):
        parse_priority("high")


def test_deadline_expiry():
    d = Deadline(0.01, priority=2)
    assert not d.expired()
    time.sleep(0.02)
    assert d.expired()
    assert d.remaining() < 0
    # the 504-mapped error every shed site raises
    err = DeadlineExceeded("spent", stage="queue")
    assert err.status_code == 504
    assert err.stage == "queue"


# -- batcher pre-dispatch shedding --------------------------------------------

def test_batcher_sheds_expired_items_before_dispatch():
    """An item whose deadline expired in the queue fails with a
    504-mapped DeadlineExceeded and NEVER reaches run_batch; fresh
    items dispatch normally."""
    from gofr_tpu.metrics import Registry
    from gofr_tpu.tpu.batcher import DynamicBatcher

    seen: list = []
    gate = threading.Event()

    def run_batch(payloads):
        if payloads == ["blocker"]:
            gate.wait(5.0)
        seen.extend(payloads)
        return payloads

    registry = Registry()
    # ONE dispatch worker: the blocker parks it, so the doomed item
    # expires while waiting for dispatch capacity
    batcher = DynamicBatcher(
        run_batch, max_batch=1, timeout_ms=1, metrics=registry,
        name="t-shed", pipeline_depth=1,
    )
    try:
        blocker = batcher.submit("blocker")
        time.sleep(0.02)  # the blocker is inside run_batch now
        activate_deadline(Deadline(0.03))
        try:
            doomed = batcher.submit("doomed")
        finally:
            activate_deadline(None)
        time.sleep(0.06)  # expire while queued behind the blocker
        gate.set()
        assert blocker.result(timeout=5) == "blocker"
        with pytest.raises(DeadlineExceeded) as err:
            doomed.result(timeout=5)
        assert err.value.stage == "queue"
        assert "doomed" not in seen  # never dispatched
        fresh = batcher.submit("fresh")
        assert fresh.result(timeout=5) == "fresh"
        counter = registry.counter(
            "gofr_tpu_deadline_exceeded_total", labels=("stage",)
        )
        assert counter.value(stage="queue") >= 1
    finally:
        gate.set()
        batcher.close()


def test_batcher_skips_cancelled_items_at_dequeue():
    """A future cancelled while queued is skipped at dequeue — it never
    consumes a cohort slot (satellite of the delivery-time check)."""
    from gofr_tpu.tpu.batcher import DynamicBatcher

    seen: list = []
    gate = threading.Event()

    def run_batch(payloads):
        gate.wait(2.0)
        seen.extend(payloads)
        return payloads

    batcher = DynamicBatcher(run_batch, max_batch=1, timeout_ms=1)
    try:
        blocker = batcher.submit("blocker")
        victim = batcher.submit("victim")
        assert victim.cancel()  # caller walked away while queued
        gate.set()
        assert blocker.result(timeout=5) == "blocker"
        survivor = batcher.submit("survivor")
        assert survivor.result(timeout=5) == "survivor"
        assert "victim" not in seen
    finally:
        batcher.close()


# -- brownout controller units ------------------------------------------------

def test_brownout_levels_and_graded_shedding():
    depth = {"value": 0}
    controller = BrownoutController(
        queue_hi=10, kv_hi=0.8, shed_priority=5, clamp_tokens=16,
        queue_depth_fn=lambda: depth["value"],
        kv_util_fn=lambda: 0.0,
        refresh_s=0.0,
    )
    # normal: everyone admitted, nothing clamped
    ok, tokens, level = controller.admit(0, 512)
    assert (ok, tokens, level) == (True, 512, 0)
    # level 1: queue at threshold — below-floor priorities shed
    depth["value"] = 10
    assert controller.level() == 1
    ok, _, _ = controller.admit(4, 512)
    assert not ok
    ok, tokens, _ = controller.admit(5, 512)
    assert ok and tokens == 512  # no clamp below level 2
    # level 2: queue at 2x — at-or-below-floor sheds, max_tokens clamps
    depth["value"] = 20
    assert controller.level() == 2
    ok, _, _ = controller.admit(5, 512)
    assert not ok
    ok, tokens, _ = controller.admit(6, 512)
    assert ok and tokens == 16
    snap = controller.snapshot()
    assert snap["level"] == 2 and snap["sheds"] == 2
    assert snap["signals"]["queue_depth"] == 20


def test_brownout_kv_signal_and_disarmed_controller():
    util = {"value": 0.0}
    controller = BrownoutController(
        kv_hi=0.8, kv_util_fn=lambda: util["value"], refresh_s=0.0,
    )
    assert controller.level() == 0
    util["value"] = 0.85
    assert controller.level() == 1
    util["value"] = 0.95  # past the (kv_hi + (1-kv_hi)/2) hard mark
    assert controller.level() == 2
    inert = BrownoutController(queue_depth_fn=lambda: 10 ** 6)
    assert not inert.armed
    assert inert.level() == 0
    assert inert.admit(0, 8) == (True, 8, 0)


# -- echo e2e helpers ---------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def echo_app(tmp_path, monkeypatch):
    """A saturatable echo app: 1-wide batches with a real per-token
    cadence (ECHO_STEP_MS), a SMALL paged-KV arena (32 blocks) and the
    brownout controller armed on KV utilization — a handful of
    long-budget streams is 'overload'."""
    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    port = _free_port()
    env = {
        "HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
        "MODEL_NAME": "echo", "TOKENIZER": "byte",
        "BATCH_MAX_SIZE": "1", "BATCH_TIMEOUT_MS": "1",
        "ECHO_STEP_MS": "15", "FLIGHT_SLOW_MS": "60000",
        "KV_BLOCKS": "32",
        "BROWNOUT_KV_UTIL": "0.5", "BROWNOUT_CLAMP_TOKENS": "4",
        "TIMEBASE_ENABLED": "off",
        "GRPC_PORT": str(_free_port()),
    }
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    monkeypatch.chdir(tmp_path)
    app = gofr_tpu.new()
    register_openai_routes(app)
    app.start()
    yield app, f"http://127.0.0.1:{port}"
    app.shutdown()


def _post(base, payload, path="/v1/completions", headers=None,
          timeout=30):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())["data"]


def _counter_value(base, name, **labels):
    """Read one counter series off /metrics (classic exposition)."""
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    want = name + "{" if labels else name
    for line in text.splitlines():
        if not line.startswith(want):
            continue
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _background_streams(base, n, max_tokens=300, prompt_width=3):
    """Open n SSE streams and keep reading them on daemon threads —
    the saturation load the deadline/brownout cases shed against (wide
    prompts + long budgets reserve real KV blocks). Returns a stop
    event."""
    stop = threading.Event()
    started = threading.Event()

    def pump() -> None:
        body = json.dumps({
            "prompt": list(range(1, prompt_width + 1)),
            "max_tokens": max_tokens,
            "stream": True, "temperature": 0,
        }).encode()
        req = urllib.request.Request(
            base + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                started.set()
                while not stop.is_set():
                    if not resp.read(256):
                        break
        except Exception:
            started.set()  # saturated enough that even this one shed

    threads = [
        threading.Thread(target=pump, daemon=True, name=f"gofr-test-load-{i}")
        for i in range(n)
    ]
    for t in threads:
        t.start()
    started.wait(10)
    return stop


# -- e2e: deadline threading (header -> record -> stages) ---------------------

def test_deadline_header_stamps_flight_record(echo_app):
    _, base = echo_app
    status, body, _ = _post(
        base, {"prompt": [1, 2, 3], "max_tokens": 3, "temperature": 0},
        headers={"X-Request-Deadline-Ms": "30000", "X-Priority": "8"},
    )
    assert status == 200
    records = _get(base, "/admin/requests")["requests"]
    mine = [r for r in records if r["deadline_s"] is not None]
    assert mine, records
    rec = mine[0]
    assert rec["deadline_s"] == pytest.approx(30.0)
    assert rec["priority"] == 8
    assert rec["shed_stage"] is None
    assert rec["status"] == "ok"


def test_no_deadline_by_default(echo_app):
    _, base = echo_app
    status, _, _ = _post(
        base, {"prompt": [4], "max_tokens": 2, "temperature": 0},
    )
    assert status == 200
    rec = _get(base, "/admin/requests")["requests"][0]
    assert rec["deadline_s"] is None
    # priority records even without a deadline: it is the tier the
    # brownout controller sheds by (PRIORITY_DEFAULT absent a header)
    assert rec["priority"] == 5


def test_malformed_deadline_header_is_400(echo_app):
    _, base = echo_app
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(base, {"prompt": [1], "max_tokens": 1},
              headers={"X-Request-Deadline-Ms": "whenever"})
    assert err.value.code == 400


def test_expired_request_sheds_before_the_device(echo_app):
    """Acceptance (a): with every prefill-dispatch worker stalled (a
    saturated device), a 50 ms-deadline request 504s at the queue
    stage — its flight record carries the shed stage and NO dispatch
    ids (it never reached the device), and the stage counter moved."""
    app, base = echo_app
    runner = app.container.tpu.runner
    before = _counter_value(
        base, "gofr_tpu_deadline_exceeded_total", stage="queue"
    )
    # stall every run_batch 120 ms: both dispatch-pool workers park,
    # so the doomed item's 50 ms budget expires before any dispatch
    runner.stall_hook = lambda: time.sleep(0.12)
    occupiers = []
    try:
        def occupy() -> None:
            try:
                _post(base, {"prompt": [9], "max_tokens": 1,
                             "temperature": 0})
            except Exception:
                pass  # only there to hold a dispatch worker

        for i in range(2):  # batcher pipeline_depth = 2 workers
            t = threading.Thread(target=occupy, daemon=True,
                                 name=f"gofr-test-occupy-{i}")
            t.start()
            occupiers.append(t)
        time.sleep(0.05)  # both occupiers inside the stalled run_batch
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(
                base, {"prompt": [1, 2, 3], "max_tokens": 50,
                       "temperature": 0},
                headers={"X-Request-Deadline-Ms": "50"},
            )
        assert err.value.code == 504
        payload = json.loads(err.value.read())
        assert "deadline" in payload["error"]["message"]
    finally:
        runner.stall_hook = None
        for t in occupiers:
            t.join(timeout=10)
    after = _counter_value(
        base, "gofr_tpu_deadline_exceeded_total", stage="queue"
    )
    assert after >= before + 1
    records = _get(base, "/admin/requests?errored=true")["requests"]
    shed = [r for r in records if r["status"] == "deadline_exceeded"]
    assert shed, records
    rec = shed[0]
    assert rec["shed_stage"] == "queue"
    assert rec["dispatch_ids"] == []  # never carried by a device dispatch


def test_decode_stage_expiry_mid_generation(echo_app):
    """A deadline generous enough to clear admission but too small for
    the full generation expires mid-decode: 504 (non-stream), shed
    stage decode, cancellations{cause=deadline} counts."""
    _, base = echo_app
    before = _counter_value(
        base, "gofr_tpu_cancellations_total", cause="deadline"
    )
    # ~15 ms/token x 100 tokens >> 150 ms budget; admission passes
    # (budget covers one step) and the loop expires partway
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(
            base, {"prompt": [5, 6], "max_tokens": 100, "temperature": 0},
            headers={"X-Request-Deadline-Ms": "150"},
        )
    assert err.value.code == 504
    after = _counter_value(
        base, "gofr_tpu_cancellations_total", cause="deadline"
    )
    assert after >= before + 1
    records = _get(base, "/admin/requests?errored=true")["requests"]
    mine = [r for r in records if r["shed_stage"] == "decode"]
    assert mine, records


# -- e2e: client-abort cancellation (acceptance b) ----------------------------

def _kv_free(base) -> int:
    return _get(base, "/admin/engine")["kv_blocks"]["free"]


def test_abandoning_client_reclaims_kv_within_one_chunk(echo_app):
    """Acceptance (b): a client that hard-closes its SSE socket
    mid-stream has the stream's KV blocks reclaimed within ~one decode
    step, and the abort is counted and recorded."""
    from gofr_tpu.devtools.chaos import abandoning_client

    _, base = echo_app
    prompt = list(range(1, 200))  # ~4 KV blocks wide
    # warm the prompt into the prefix cache FIRST: admission caches a
    # never-seen prompt by design (copy-free store), and the baseline
    # must not mistake that deliberate entry for a leak
    status, _, _ = _post(
        base, {"prompt": prompt, "max_tokens": 1, "temperature": 0},
        timeout=60,
    )
    assert status == 200
    baseline = _kv_free(base)
    before = _counter_value(
        base, "gofr_tpu_cancellations_total", cause="client_abort"
    )
    body = json.dumps({
        # the warmed prompt aliases its cached blocks; a budget long
        # enough that the abort clearly lands mid-generation
        "prompt": prompt, "max_tokens": 400,
        "stream": True, "temperature": 0,
    }).encode()
    frames = abandoning_client(base, "/v1/completions", body, frames=3)
    assert len(frames) == 3
    # the engine must notice within one chunk: the next write fails,
    # the abort hook trips the stop event, and the paged sequence
    # aborts. Poll briefly (the write failure needs one more token).
    deadline = time.monotonic() + 5.0
    reclaimed = False
    while time.monotonic() < deadline:
        if _kv_free(base) >= baseline:
            reclaimed = True
            break
        time.sleep(0.02)
    assert reclaimed, (
        f"KV blocks leaked: free={_kv_free(base)} baseline={baseline}"
    )
    after = _counter_value(
        base, "gofr_tpu_cancellations_total", cause="client_abort"
    )
    assert after >= before + 1
    records = _get(base, "/admin/requests?errored=true")["requests"]
    assert any(r["status"] == "cancelled" for r in records), records


# -- e2e: brownout (acceptance c) ---------------------------------------------

def test_brownout_sheds_low_priority_serves_high(echo_app):
    """Acceptance (c): with brownout armed (queue threshold 2) and the
    queue saturated, a low-priority request 429s with Retry-After
    while a high-priority request still completes; the level is
    visible on /admin/engine and the gauge."""
    _, base = echo_app
    # wide prompts + long budgets: 4 streams reserve ~28 of the 32 KV
    # blocks, pushing utilization past the 0.5 threshold (and usually
    # past the 0.75 hard mark)
    stop = _background_streams(base, 4, max_tokens=300, prompt_width=99)
    try:
        # wait for the armed signal to cross (prober-style poll)
        level = 0
        poll_deadline = time.monotonic() + 10.0
        while time.monotonic() < poll_deadline:
            level = _get(base, "/admin/engine")["brownout"]["level"]
            if level >= 1:
                break
            time.sleep(0.05)
        assert level >= 1, _get(base, "/admin/engine")["brownout"]
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(
                base, {"prompt": [1], "max_tokens": 1, "temperature": 0},
                headers={"X-Priority": "0"},
            )
        assert err.value.code == 429
        assert err.value.headers.get("Retry-After")
        payload = json.loads(err.value.read())
        assert "brownout" in payload["error"]["message"]
        status, body, _ = _post(
            base, {"prompt": [2, 3], "max_tokens": 2, "temperature": 0},
            headers={"X-Priority": "9"}, timeout=60,
        )
        assert status == 200
        assert body["choices"][0]["text"] is not None
    finally:
        stop.set()
    snap = _get(base, "/admin/engine")["brownout"]
    assert snap["armed"] is True
    assert snap["sheds"] >= 1
    assert _counter_value(
        base, "gofr_tpu_brownout_shed_total", priority="0"
    ) >= 1


def test_brownout_level_metric_exposed(echo_app):
    _, base = echo_app
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "gofr_tpu_brownout_level" in text


# -- fleet router: remaining-budget forwarding --------------------------------

@pytest.fixture()
def budget_fleet(tmp_path, monkeypatch):
    """Two device-free replicas that RECORD the deadline header they
    receive — the first 503s its first request (forcing a retry), the
    second serves. Fronted by a real FleetRouter."""
    import gofr_tpu
    from gofr_tpu.devtools.chaos import _env_overrides, chaos_router
    from gofr_tpu.http.response import Raw

    seen: dict[str, list] = {"r0": [], "r1": []}
    # BOTH replicas fail their first request: whichever the router
    # picks first forces a retry, deterministically
    fail_first = {"r0": True, "r1": True}

    def make_handler(name):
        def handler(ctx):
            seen[name].append(ctx.request.header("X-Request-Deadline-Ms"))
            if fail_first.get(name):
                fail_first[name] = False
                time.sleep(0.2)  # burn visible budget before failing
                from gofr_tpu.errors import HTTPError

                raise HTTPError(503, "warming up")
            return Raw({"served_by": name})
        return handler

    apps = []
    replicas = []
    for name in ("r0", "r1"):
        port = _free_port()
        with _env_overrides({
            "HTTP_PORT": str(port), "LOG_LEVEL": "FATAL",
            "MODEL_NAME": None, "TPU_ENABLED": None,
            "TIMEBASE_ENABLED": "off", "GRPC_PORT": str(_free_port()),
        }):
            app = gofr_tpu.new()
            app.post("/v1/completions", make_handler(name))
            app.start()
        apps.append(app)

        class _Stub:
            def __init__(self, name, port):
                self.name = name
                self.port = port
                self.address = f"http://127.0.0.1:{port}"

        replicas.append(_Stub(name, port))
    with chaos_router(replicas, env={
        "FLEET_RETRIES": "2", "FLEET_DEADLINE_S": "30",
        "FLEET_AFFINITY": "off",
    }) as router_app:
        # both replicas healthy in rotation
        fleet = router_app.container.fleet
        poll_deadline = time.monotonic() + 10.0
        while time.monotonic() < poll_deadline:
            if len(fleet.replica_set.in_rotation()) == 2:
                break
            time.sleep(0.05)
        port = router_app.http_server.port
        yield f"http://127.0.0.1:{port}", seen
    for app in apps:
        app.shutdown()


def test_router_forwards_remaining_budget_across_retry(budget_fleet):
    """The second attempt must see a SMALLER X-Request-Deadline-Ms than
    the first (the failed attempt's elapsed time is subtracted), and
    both must be bounded by the client's own budget."""
    base, seen = budget_fleet
    status, body, _ = _post(
        base, {"prompt": [1], "max_tokens": 1},
        headers={"X-Request-Deadline-Ms": "5000"},
    )
    assert status == 200
    budgets = [int(v) for v in seen["r0"] + seen["r1"] if v]
    assert len(budgets) >= 2, seen
    first, second = budgets[0], budgets[-1]
    assert first <= 5000  # capped at the client's budget
    # the failed attempt slept 200 ms before 503ing: the retry's
    # forwarded budget must be visibly smaller
    assert second <= first - 150, (first, second)
    assert second >= 1  # floored per attempt, never zero/negative


def test_router_never_mints_a_deadline(budget_fleet):
    """A request with no deadline header — and an explicit ``0``
    opt-out — must reach the replica with its header untouched: the
    router caps and re-stamps only budgets the client actually set
    (FLEET_DEADLINE_S bounds the router's own forwarding, it must not
    become an engine-enforced deadline the client never asked for)."""
    base, seen = budget_fleet
    status, _, _ = _post(base, {"prompt": [1], "max_tokens": 1})
    assert status == 200
    # every attempt (failing firsts + the serving retry) saw NO header
    # (absent reads back as "")
    assert seen["r0"] + seen["r1"], seen
    assert all(not v for v in seen["r0"] + seen["r1"]), seen
    status, _, _ = _post(
        base, {"prompt": [1], "max_tokens": 1},
        headers={"X-Request-Deadline-Ms": "0"},
    )
    assert status == 200
    stamped = [v for v in seen["r0"] + seen["r1"] if v]
    assert stamped and all(v == "0" for v in stamped), seen
