"""Paged KV end-to-end on the echo runner (compile-free, tier-1): the
whole allocator/aliasing/admission path driven through the real device —
exact/LCP prefix hits produce bit-identical output to the unpaged
runner, kv_exhausted rejections are observable (counter + FlightRecord)
while the request still completes, freed blocks admit a waiting request
mid-flight (continuous batching), and the block accounting surfaces on
``engine_snapshot()`` and /metrics."""

import os
import threading

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.telemetry import FlightRecorder
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import new_device


def _device(**env):
    defaults = {"MODEL_NAME": "echo", "BATCH_MAX_SIZE": "4",
                "BATCH_TIMEOUT_MS": "1"}
    defaults.update(env)
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry()), old
    except BaseException:
        _restore(old)
        raise


def _restore(old):
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


def _deactivate():
    """Drop the contextvar a recorder.start() activated — a leaked
    active record would bleed into unrelated tests in the same worker."""
    from gofr_tpu.telemetry import activate_record

    activate_record(None)


@pytest.fixture()
def paged():
    dev, old = _device(KV_BLOCKS="64", KV_BLOCK_TOKENS="4",
                       PREFIX_LCP_MIN="4")
    yield dev
    dev.close()
    _restore(old)


def test_echo_paged_enabled_by_default():
    dev, old = _device()
    try:
        assert dev.kv_pool is not None
        assert dev.runner.paged is not None
    finally:
        dev.close()
        _restore(old)


def test_kv_paged_off_restores_plain_echo():
    dev, old = _device(KV_PAGED="off")
    try:
        assert dev.kv_pool is None
        assert dev.generate([1, 2, 3], max_new_tokens=4) == [1, 2, 3, 1]
    finally:
        dev.close()
        _restore(old)


def test_paged_output_bit_identical_to_unpaged(paged):
    plain, old = _device(KV_PAGED="off")
    try:
        prompts = [[1, 2, 3, 4, 5], [1, 2, 3, 4, 5],
                   [1, 2, 3, 4, 5, 9, 8], [7, 7, 7]]
        for p in prompts:
            assert paged.generate(p, max_new_tokens=7) == \
                plain.generate(p, max_new_tokens=7), p
    finally:
        plain.close()
        _restore(old)


def test_exact_and_lcp_hits_count_and_alias(paged):
    p = [11, 12, 13, 14, 15, 16]
    paged.generate(p, max_new_tokens=4)          # miss: stores prompt entry
    before = dict(paged.runner.prefix_stats)
    copied_before = paged.kv_pool.stats()["copied_kv_bytes"]
    paged.generate(p, max_new_tokens=4)          # exact hit: block alias
    after = paged.runner.prefix_stats
    assert after["hits"] == before["hits"] + 1
    # the hit wrote only its own decode tokens + one COW boundary block
    # — never a row copy (4 new tokens + <=1 block of 4 tokens, 4B each)
    assert paged.kv_pool.stats()["copied_kv_bytes"] - copied_before <= 8 * 4
    before = dict(paged.runner.prefix_stats)
    paged.generate([11, 12, 13, 14, 99, 98], max_new_tokens=2)  # LCP 4
    assert paged.runner.prefix_stats["partial_hits"] == \
        before["partial_hits"] + 1
    # hit-ratio gauges maintained off the paged stats
    text = paged.metrics.expose()
    assert any(
        ln.startswith('gofr_tpu_prefix_hit_ratio{model="echo"}')
        for ln in text.splitlines()
    ), text


def test_kv_exhausted_rejects_but_request_completes():
    # 8 blocks x 2 tokens: a 5-token prompt + 16 new tokens cannot admit
    dev, old = _device(KV_BLOCKS="8", KV_BLOCK_TOKENS="2")
    try:
        recorder = FlightRecorder()
        rec = recorder.start(model="echo", endpoint="/t")
        try:
            out = dev.generate([1, 2, 3, 4, 5], max_new_tokens=16)
        finally:
            recorder.finish(rec)
            _deactivate()
        assert len(out) == 16  # the block-free fallback served it fully
        assert rec.pool_reject_reason == "kv_exhausted"
        counter = dev.metrics.counter(
            "gofr_tpu_pool_reject_total", labels=("reason",)
        )
        assert counter.value(reason="kv_exhausted") >= 1
        assert dev.kv_pool.stats()["kv_exhausted_rejects"] >= 1
    finally:
        dev.close()
        _restore(old)


def test_freed_blocks_admit_new_request_mid_flight():
    """Continuous batching e2e: A holds most of the arena; B cannot
    admit (kv_exhausted, solo fallback); A finishes and frees its
    blocks; C then admits INTO THEM while B is still mid-decode."""
    dev, old = _device(KV_BLOCKS="16", KV_BLOCK_TOKENS="2",
                       ECHO_STEP_MS="10")
    try:
        recorder = FlightRecorder()
        release_a = threading.Event()
        results = {}
        reject_counter = dev.metrics.counter(
            "gofr_tpu_pool_reject_total", labels=("reason",)
        )

        def run_a():
            # 4-token prompt + 20 new = 12 blocks of 16
            stop = threading.Event()

            def tick(_):
                if release_a.is_set():
                    stop.set()

            results["a"] = dev.generate(
                [1, 2, 3, 4], max_new_tokens=20, on_token=tick, stop=stop
            )

        def run_b():
            rec = recorder.start(model="echo", endpoint="/b")
            try:
                # needs 15 blocks: fits the 16-block arena alone, but NOT
                # while A holds 12 — rejected (solo fallback), and long
                # enough (28 step-delayed tokens) to still be mid-decode
                # when C admits below
                results["b"] = dev.generate([5, 6], max_new_tokens=28)
            finally:
                recorder.finish(rec)
            _deactivate()
            results["b_rec"] = rec

        ta = threading.Thread(target=run_a)
        ta.start()
        # wait until A actually holds its blocks
        for _ in range(500):
            if dev.kv_pool.stats()["free"] < 7:
                break
            threading.Event().wait(0.01)
        tb = threading.Thread(target=run_b)
        tb.start()
        # DETERMINISTIC ordering: release A only after B's rejection is
        # observable — the counter increments at reject time, before B's
        # solo decode starts emitting
        for _ in range(500):
            if reject_counter.value(reason="kv_exhausted") >= 1:
                break
            threading.Event().wait(0.01)
        assert reject_counter.value(reason="kv_exhausted") >= 1
        release_a.set()  # A finishes -> blocks free immediately
        ta.join(10)
        # C admits into A's freed blocks while B (28 step-delayed
        # tokens) is still mid-decode
        rec_c = recorder.start(model="echo", endpoint="/c")
        try:
            results["c"] = dev.generate([9, 9, 9], max_new_tokens=4)
        finally:
            recorder.finish(rec_c)
            _deactivate()
        assert "b" not in results  # B genuinely mid-decode at C's admit
        tb.join(10)
        assert results["a"] and results["b"] == [5, 6] * 14
        assert results["c"] == [9, 9, 9, 9]
        assert rec_c.kv_blocks > 0  # C was ADMITTED (paged), not solo
        # B hit the exhausted arena (reject observable on its record)
        assert results["b_rec"].pool_reject_reason == "kv_exhausted"
    finally:
        dev.close()
        _restore(old)


def test_engine_snapshot_and_metrics_expose_block_accounting(paged):
    paged.generate([1, 2, 3, 4, 5], max_new_tokens=4)
    snap = paged.engine_snapshot()
    kv = snap["kv_blocks"]
    assert kv is not None
    for key in ("total", "ledger", "free", "cached", "active", "reserved",
                "evictions", "cow_copies", "copied_kv_bytes",
                "kv_exhausted_rejects", "budget_utilization"):
        assert key in kv, key
    assert kv["total"] == 64
    assert kv["free"] + kv["cached"] + kv["active"] == kv["total"]
    assert snap["caches"]["prefix"] == paged.runner.prefix_stats
    text = paged.metrics.expose()
    for state in ("total", "free", "cached", "active", "reserved"):
        assert f'gofr_tpu_kv_blocks{{state="{state}"}}' in text, state
    assert "gofr_tpu_kv_evictions_total" in text


def test_eviction_under_pressure_is_counted():
    dev, old = _device(KV_BLOCKS="12", KV_BLOCK_TOKENS="2")
    try:
        # each round caches entries; later admissions must evict them
        for i in range(6):
            dev.generate([i + 1, i + 2, i + 3], max_new_tokens=6)
        assert dev.kv_pool.stats()["evictions"] > 0
        text = dev.metrics.expose()
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("gofr_tpu_kv_evictions_total")
        )
        assert float(line.rsplit(" ", 1)[1]) > 0
    finally:
        dev.close()
        _restore(old)


def test_flight_record_carries_kv_block_fields(paged):
    recorder = FlightRecorder()
    p = [21, 22, 23, 24]
    paged.generate(p, max_new_tokens=4)  # seed the prompt entry
    rec = recorder.start(model="echo", endpoint="/t")
    try:
        paged.generate(p, max_new_tokens=4)  # exact hit: aliased blocks
    finally:
        recorder.finish(rec)
        _deactivate()
    assert rec.kv_blocks > 0
    assert rec.kv_aliased_blocks > 0  # admitted copy-free
    d = rec.to_dict()
    assert d["kv_blocks"] == rec.kv_blocks
    assert d["kv_aliased_blocks"] == rec.kv_aliased_blocks


# -- host-mesh mode (TPU_MESH on the echo runner) ------------------------------

def test_host_mesh_shards_block_tables_bit_identically():
    """TPU_MESH=tp=2 on the echo runner: block tables span 2 fake
    devices (every block's token span split across shards), every shard
    takes writes, and the decoded output is bit-identical to the
    unsharded paged runner — aliasing/COW fidelity is placement-blind."""
    meshed, old = _device(TPU_MESH="tp=2", KV_BLOCKS="64",
                          KV_BLOCK_TOKENS="4", PREFIX_LCP_MIN="4")
    try:
        plain, old2 = _device(KV_BLOCKS="64", KV_BLOCK_TOKENS="4",
                              PREFIX_LCP_MIN="4")
        try:
            arena = meshed.runner.paged.arena
            assert arena.shards == 2
            assert meshed.runner.mesh_axes == {"tp": 2}
            prompts = [[1, 2, 3, 4, 5], [1, 2, 3, 4, 5],
                       [1, 2, 3, 4, 6, 7, 8, 9]]
            for p in prompts:
                assert (
                    meshed.generate(p, max_new_tokens=6)
                    == plain.generate(p, max_new_tokens=6)
                )
            # both fake devices actually held KV (shard-split writes)
            assert all(n > 0 for n in arena.shard_writes)
        finally:
            plain.close()
            _restore(old2)
    finally:
        meshed.close()
        _restore(old)


def test_host_mesh_observability_surfaces():
    """The mesh shape is visible everywhere the tentpole promises:
    /admin/engine ``mesh``, the ``gofr_tpu_mesh_axis_size{axis}``
    gauge, and the request's FlightRecord ``mesh_axes``."""
    dev, old = _device(TPU_MESH="tp=2", KV_BLOCKS="64", KV_BLOCK_TOKENS="4")
    try:
        snap = dev.engine_snapshot()
        assert snap["mesh"] == {"axes": {"tp": 2}, "devices": 2}
        assert snap["kv_blocks"] is not None and snap["kv_blocks"]["total"] == 64
        text = dev.metrics.expose()
        assert 'gofr_tpu_mesh_axis_size{axis="tp"} 2' in text
        recorder = FlightRecorder()
        rec = recorder.start(model="echo", endpoint="/m")
        try:
            dev.generate([5, 6, 7, 8], max_new_tokens=4)
        finally:
            recorder.finish(rec)
            _deactivate()
        assert rec.mesh_axes == {"tp": 2}
        assert rec.to_dict()["mesh_axes"] == {"tp": 2}
    finally:
        dev.close()
        _restore(old)


def test_host_mesh_kv_exhaustion_still_degrades_cleanly():
    """kv_exhausted admission under the host mesh: the reject is
    counted and the request still completes through the block-free
    fallback — mesh and continuous-batching admission compose."""
    # 4 blocks x 4 tokens: a long generation cannot reserve its budget
    dev, old = _device(TPU_MESH="tp=2", KV_BLOCKS="4", KV_BLOCK_TOKENS="4")
    try:
        out = dev.generate([1, 2, 3, 4, 5, 6, 7, 8], max_new_tokens=32)
        assert len(out) == 32  # served despite the reject
        reject = next(
            ln for ln in dev.metrics.expose().splitlines()
            if ln.startswith('gofr_tpu_pool_reject_total{reason="kv_exhausted"}')
        )
        assert float(reject.rsplit(" ", 1)[1]) >= 1
    finally:
        dev.close()
        _restore(old)
