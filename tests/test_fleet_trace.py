"""Fleet-wide request tracing, tier-1: the hop-correlation layer
(request-id mint/honor/echo, ``X-Gofr-Hop`` provenance), the
``/admin/fleet/trace/<id>`` causal-timeline assembly, and trace
capture→replay determinism.

Unit tier: header sanitization/parsing never crashes on garbage, the
pure ``assemble`` join decomposes latency correctly and degrades to
partial-with-evidence, capture anonymization is seeded-deterministic.

Chaos e2e tier (same in-process echo fleets as test_fleet.py): ids
echo on success AND shed responses, client hop spoofing is overridden
at the router boundary, and THE acceptance spine — a streamed request
that rides a cross-replica KV transfer and survives a forced mid-
stream wedge + resume assembles into ONE timeline via
``GET /admin/fleet/trace/<id>``, span-continuous across the resume.
"""

import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from gofr_tpu.fleet import trace as fleet_trace
from gofr_tpu.telemetry import (
    format_hop,
    origin_from_headers,
    parse_hop,
    sanitize_request_id,
)


# -- helpers -------------------------------------------------------------------

def _get(url, headers=None, timeout=10):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers.items())


def _post(url, payload, headers=None, timeout=10):
    send = {"Content-Type": "application/json"}
    send.update(headers or {})
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=send, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers.items())


def _wait(cond, timeout=10.0, interval=0.02, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


def _read_sse_tokens(resp, initial: bytes = b"") -> tuple:
    """Drain one SSE response: returns (token_ids, raw)."""
    raw = initial
    while True:
        chunk = resp.read(4096)
        if not chunk:
            break
        raw += chunk
    tokens: list = []
    for block in raw.split(b"\n\n"):
        for line in block.split(b"\n"):
            if line.startswith(b"data:"):
                data = line[5:].strip()
                if data == b"[DONE]" or not data.startswith(b"{"):
                    continue
                frame = json.loads(data)
                if "error" in frame:
                    raise AssertionError(f"error frame reached client: {frame}")
                choice = frame["choices"][0]
                if choice.get("tokens"):
                    tokens.extend(choice["tokens"])
    return tokens, raw


# -- unit: header sanitization + hop parsing -----------------------------------

def test_sanitize_request_id_contract():
    assert sanitize_request_id("req-a1B2.x_y-9") == "req-a1B2.x_y-9"
    assert sanitize_request_id("a" * 64) == "a" * 64
    assert sanitize_request_id("a" * 65) is None  # too long
    assert sanitize_request_id("has space") is None
    assert sanitize_request_id("semi;colon") is None
    assert sanitize_request_id("") is None
    assert sanitize_request_id(None) is None
    # header injection attempts die at the charset, not downstream
    assert sanitize_request_id("evil\r\nX-Admin: yes") is None


def test_hop_round_trip_and_garbage_never_raises():
    hop = format_hop("router-0", 2, 7)
    assert hop == "router=router-0;attempt=2;resume=7"
    parsed = parse_hop(hop)
    assert parsed == {"router": "router-0", "attempt": 2, "resume_from": 7}
    for garbage in (
        None, "", ";;;", "router=", "router=a;attempt=x", "attempt=1",
        "router=ok;attempt=-1;resume=0", "router=sp ace;attempt=1;resume=0",
        "=" * 500, "a" * 300,
    ):
        assert parse_hop(garbage) is None, garbage
    # unknown extra fields are tolerated (forward-compat), known ones win
    assert parse_hop("router=a;attempt=1;resume=1;extra=junk") == {
        "router": "a", "attempt": 1, "resume_from": 1,
    }
    rng = random.Random(20260807)
    alphabet = "ra=;0123456789\x00\r\n %züter"
    for _ in range(500):
        fuzz = "".join(
            rng.choice(alphabet) for _ in range(rng.randint(0, 80))
        )
        parse_hop(fuzz)  # must never raise, whatever it returns
        origin_from_headers(fuzz, fuzz)  # nor the combined parser


# -- unit: timeline assembly ---------------------------------------------------

def _route(**over):
    base = {
        "ts": 100.0, "request_id": "req-abc", "router_id": "router-0",
        "method": "POST", "path": "/v1/completions", "tenant": "t0",
        "stream": True, "resumable": True, "resumes": 0, "role": "decode",
        "kv_donor": "r0", "status": 200, "outcome": "ok", "retries": 1,
        "elapsed_ms": 200.0,
        "attempts": [
            {"replica": "r1", "status": 503, "error": "saturated",
             "elapsed_ms": 10.0},
            {"replica": "r2", "status": 200, "error": None,
             "elapsed_ms": 150.0},
        ],
    }
    base.update(over)
    return base


def test_assemble_joins_flights_and_decomposes_latency():
    flights = {
        "r2": [{
            "request_id": "req-abc",
            "origin": {"router": "router-0", "attempt": 1, "resume_from": 0},
            "queue_wait_s": 0.010, "ttft_s": 0.050, "status": 200,
        }],
    }
    transfers = [{"replica": "r2", "side": "receiver", "outcome": "ok",
                  "request_id": "req-abc"}]
    out = fleet_trace.assemble("req-abc", _route(), flights, transfers)
    assert out["request_id"] == "req-abc"
    assert out["partial"] is False and out["evidence_gaps"] == []
    assert out["router"]["elapsed_ms"] == 200.0
    assert out["router"]["kv_donor"] == "r0"
    assert [a["replica"] for a in out["attempts"]] == ["r1", "r2"]
    assert out["attempts"][0]["flight"] is None  # failed hop: no record
    assert out["attempts"][1]["flight"]["status"] == 200
    assert out["transfers"] == transfers
    lat = out["latency"]
    assert lat["total_ms"] == 200.0
    # 200 total - (10 + 150) upstream = 40ms router overhead
    assert lat["router_overhead_ms"] == 40.0
    assert lat["replica_queue_ms"] == 10.0
    assert lat["device_ttft_ms"] == 40.0  # ttft net of queue wait
    # remainder: 200 - 40 - 10 - 40
    assert lat["stream_ms"] == 110.0


def test_assemble_is_partial_with_evidence_when_flights_missing():
    out = fleet_trace.assemble(
        "req-abc", _route(), flights={}, transfers=[],
        evidence_gaps=["r2: flight scrape failed (connection refused)"],
    )
    assert out["partial"] is True
    # the served attempt with no flight record is ITSELF named as a gap
    assert any("attempt 1" in g for g in out["evidence_gaps"])
    assert any("connection refused" in g for g in out["evidence_gaps"])
    lat = out["latency"]
    assert lat["router_overhead_ms"] == 40.0  # route-record-only math
    assert lat["replica_queue_ms"] is None  # no flight: no invention
    assert lat["device_ttft_ms"] is None and lat["stream_ms"] is None


def test_assemble_matches_flight_by_attempt_index_not_order():
    # two flights from the SAME replica (original + a retry that landed
    # back on it): the origin attempt index disambiguates
    flights = {"r2": [
        {"origin": {"router": "router-0", "attempt": 5, "resume_from": 0},
         "status": 200, "marker": "wrong"},
        {"origin": {"router": "router-0", "attempt": 1, "resume_from": 0},
         "status": 200, "marker": "right"},
        {"origin": {"router": "OTHER", "attempt": 1, "resume_from": 0},
         "status": 200, "marker": "foreign"},
    ]}
    out = fleet_trace.assemble("req-abc", _route(), flights, [])
    assert out["attempts"][1]["flight"]["marker"] == "right"


def test_assemble_fuzzed_inputs_never_crash():
    rng = random.Random(7)

    def junk(depth=0):
        pick = rng.randint(0, 5 if depth < 2 else 3)
        if pick == 0:
            return rng.randint(-10, 10)
        if pick == 1:
            return rng.random() * 1e3
        if pick == 2:
            return "".join(chr(rng.randint(32, 126)) for _ in range(8))
        if pick == 3:
            return None
        if pick == 4:
            return [junk(depth + 1) for _ in range(rng.randint(0, 3))]
        return {
            rng.choice(["attempts", "elapsed_ms", "status", "replica",
                        "origin", "ts", "x"]): junk(depth + 1)
            for _ in range(rng.randint(0, 4))
        }

    for _ in range(300):
        route = junk()
        if not isinstance(route, dict):
            route = {"attempts": route}
        flights = {"r1": junk() if rng.random() < 0.5 else [junk()]}
        if not isinstance(flights["r1"], list):
            flights["r1"] = [flights["r1"]]
        out = fleet_trace.assemble("req-fuzz", route, flights, [])
        assert out["request_id"] == "req-fuzz"
        assert isinstance(out["partial"], bool)


# -- unit: zipkin exporter drop counter ----------------------------------------

def test_zipkin_exporter_counts_dropped_batches():
    from gofr_tpu.metrics import Registry
    from gofr_tpu.tracing import Span, Tracer, ZipkinExporter

    exporter = ZipkinExporter("http://127.0.0.1:1/api/v2/spans")
    registry = Registry()
    exporter.attach_metrics(registry)
    try:
        span = Span("t", "ab" * 16, "cd" * 8, None, None, Tracer(exporter))
        span.end_us = span.start_us + 5
        exporter._post([span])  # collector port 1: refused, counted
        assert exporter.post_failures == 1
        counted = sum(
            registry.counter(
                "gofr_tpu_trace_export_failures_total"
            ).data().values()
        )
        assert counted == 1
    finally:
        exporter.shutdown()


# -- unit: capture determinism + anonymization ---------------------------------

def _capture_fixtures():
    routes = [
        {"ts": 50.0, "request_id": "req-b", "tenant": "acme",
         "affinity_key": "aff1234567", "stream": True, "outcome": "ok",
         "status": 200, "attempts": [{"replica": "r0", "status": 200}]},
        {"ts": 49.0, "request_id": "req-a", "tenant": "globex",
         "affinity_key": None, "stream": False, "outcome": "ok",
         "status": 200, "attempts": [{"replica": "r0", "status": 200}]},
        {"ts": 51.0, "request_id": "req-c", "tenant": "acme",
         "outcome": "shed:quota", "status": 429, "attempts": []},
    ]
    flights = [
        {"request_id": "req-a", "tokens_in": 12, "tokens_out": 4,
         "priority": 7},
        {"request_id": "req-b", "tokens_in": 33, "tokens_out": 9,
         "priority": 5},
    ]
    return routes, flights


def test_capture_events_are_deterministic_and_anonymized():
    from gofr_tpu.devtools.trace_capture import build_events, capture_artifact

    routes, flights = _capture_fixtures()
    events, dropped = build_events(routes, flights, seed=99)
    events2, _ = build_events(list(routes), list(flights), seed=99)
    assert events == events2  # seeded: byte-identical
    assert dropped["shed"] == 1  # the 429 had no prompt evidence
    assert len(events) == 2
    # sorted by timestamp: req-a (ts 49) first, offsets rebased to 0
    assert events[0]["at_s"] == 0.0 and events[1]["at_s"] == 1.0
    # anonymization: raw tenant names never appear, hashes are stable
    blob = json.dumps(events)
    assert "acme" not in blob and "globex" not in blob
    assert events[0]["tenant"] != events[1]["tenant"]
    # prompt SHAPES survive (length = tokens_in), content is synthetic
    assert len(events[0]["prompt"]) == 12
    assert len(events[1]["prompt"]) == 33
    assert all(1 <= t <= 997 for t in events[1]["prompt"])
    # stream/unary mix survives; fleetsim schema keys all present
    assert events[1]["kind"] == "stream" and events[0]["kind"] == "unary"
    for ev in events:
        assert set(ev) == {"at_s", "phase", "tenant", "session", "priority",
                           "kind", "abort_after", "prompt", "max_tokens",
                           "seed", "i"}
    # a different seed unlinks tenants AND prompts
    events3, _ = build_events(routes, flights, seed=100)
    assert events3[0]["tenant"] != events[0]["tenant"]
    assert events3[0]["prompt"] != events[0]["prompt"]
    artifact = capture_artifact(routes, flights, seed=99)
    assert artifact["digest"] == capture_artifact(routes, flights, 99)["digest"]
    assert artifact["requests"] == 2 and artifact["dropped"]["shed"] == 1


def test_load_capture_rejects_tampered_files(tmp_path):
    from gofr_tpu.devtools.trace_capture import capture_artifact, load_capture

    routes, flights = _capture_fixtures()
    artifact = capture_artifact(routes, flights, seed=5)
    path = tmp_path / "cap.json"
    path.write_text(json.dumps(artifact))
    loaded = load_capture(str(path))
    assert loaded["digest"] == artifact["digest"]
    artifact["events"][0]["max_tokens"] = 9999  # hand-edit
    path.write_text(json.dumps(artifact))
    with pytest.raises(ValueError, match="digest mismatch"):
        load_capture(str(path))
    path.write_text(json.dumps({"kind": "FLEETSIM"}))
    with pytest.raises(ValueError, match="not a TRACE_CAPTURE"):
        load_capture(str(path))


# -- e2e: request-id mint / honor / echo / spoof-stripping ---------------------

def test_request_id_minted_honored_and_hop_spoof_overridden(
        tmp_path, monkeypatch):
    """The id contract at the front door: the router mints an id when
    the client sends none, honors a sanitized ``X-Request-ID``, mints
    over garbage, and OVERRIDES any client-supplied ``X-Gofr-Hop`` —
    provenance headers are router-asserted, never client-asserted. The
    id is then visible end to end: response header, route record, and
    the replica's flight record (``?request_id=`` filter)."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(1) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")
        # no client id: minted, echoed, recorded
        _, _, headers = _post(base + "/generate", {"tokens": [1, 2]})
        minted = headers.get("X-Gofr-Request-Id")
        assert minted and minted.startswith("req-")
        # sanitized client id: honored verbatim
        _, _, headers = _post(
            base + "/generate", {"tokens": [1, 2]},
            headers={"X-Request-ID": "client-id-42"},
        )
        assert headers.get("X-Gofr-Request-Id") == "client-id-42"
        # garbage client id: minted over, never reflected back raw
        _, _, headers = _post(
            base + "/generate", {"tokens": [1, 2]},
            headers={"X-Request-ID": "evil id\twith junk!"},
        )
        echoed = headers.get("X-Gofr-Request-Id")
        assert echoed and echoed.startswith("req-") and "evil" not in echoed
        # client-minted hop: overridden by the router's own stamp.
        # (/v1/completions, not /generate: flight records ride the
        # OpenAI admission gate, and the replica-side origin is the
        # evidence that the spoof died at the router boundary)
        _, _, headers = _post(
            base + "/v1/completions",
            {"model": "echo", "prompt": [1, 2, 3], "max_tokens": 2},
            headers={"X-Request-ID": "spoof-probe",
                     "X-Gofr-Hop": "router=evil;attempt=9;resume=5"},
        )
        assert headers.get("X-Gofr-Request-Id") == "spoof-probe"
        route = fleet.records(request_id="spoof-probe")[0]
        assert route["router_id"] == fleet.router_id
        # the replica-side flight record carries the ROUTER's provenance
        victim = replicas[0]
        status, body, _ = _get(
            victim.address + "/admin/requests?request_id=spoof-probe"
        )
        flights = json.loads(body)["data"]["requests"]
        assert flights, "flight record not found by request id"
        origin = flights[0]["origin"]
        assert origin["router"] == fleet.router_id  # not "evil"
        assert origin["attempt"] == 0 and origin["resume_from"] == 0
        # ?request_id= on /admin/fleet narrows the route view too
        status, body, _ = _get(base + "/admin/fleet?request_id=spoof-probe")
        routes = json.loads(body)["data"]["routes"]
        assert [r["request_id"] for r in routes] == ["spoof-probe"]
        # garbage hop/id sent DIRECTLY to a replica never crashes it
        for fuzz in (";;;;", "router=;attempt=z", "a" * 300, "\x00\x01"):
            status, _, _ = _post(
                victim.address + "/generate", {"tokens": [3]},
                headers={"X-Gofr-Hop": fuzz},
            )
            assert status == 200


def test_shed_responses_carry_the_request_id(tmp_path, monkeypatch):
    """A 429 the router refused is otherwise untraceable — the id must
    ride the error body AND header so the client can quote it."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(1) as replicas, chaos_router(
        replicas,
        env={"FLEET_QUOTA_RPS": "0.5", "FLEET_QUOTA_BURST": "1",
             "FLEET_TRUST_TENANT_HEADER": "on"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")
        acme = {"X-Tenant": "acme", "X-Request-ID": "shed-evidence-1"}
        _post(base + "/generate", {"tokens": [1]}, headers=acme)
        try:
            _post(base + "/generate", {"tokens": [1]}, headers=acme)
            raise AssertionError("expected 429 over quota")
        except urllib.error.HTTPError as exc:
            assert exc.code == 429
            assert exc.headers.get("X-Gofr-Request-Id") == "shed-evidence-1"
            assert json.loads(exc.read())["error"]["request_id"] == \
                "shed-evidence-1"
        # the shed left a route record findable by the same id
        shed_routes = fleet.records(request_id="shed-evidence-1")
        assert any(
            str(r.get("outcome", "")).startswith("shed:") for r in shed_routes
        )
        # drain 503 carries the id the same way
        fleet.begin_drain()
        try:
            _post(base + "/generate", {"tokens": [1]},
                  headers={"X-Request-ID": "drain-evidence"})
            raise AssertionError("expected 503 while draining")
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            assert exc.headers.get("X-Gofr-Request-Id") == "drain-evidence"
            assert json.loads(exc.read())["error"]["request_id"] == \
                "drain-evidence"


# -- e2e: THE acceptance spine -------------------------------------------------

def test_fleet_trace_assembles_transfer_and_resume_timeline(
        tmp_path, monkeypatch):
    """One request's whole story on one page: a streamed completion on
    a prefill/decode fleet rides a cross-replica KV transfer (donor
    warm, router-stamped ``X-KV-Donor``), survives a REAL mid-stream
    device wedge + recovery + resume — and
    ``GET /admin/fleet/trace/<id>`` assembles the route record, the
    replica flight records (joined on the hop-stamped origin), the
    KV-transfer ledger entries from BOTH ends, and the latency
    decomposition into one causal timeline. The continuation's flight
    record shares the original's trace id (span continuity across
    resume) and names its resume offset. A replica that then goes dark
    degrades the SAME endpoint to partial-with-evidence, never a 500."""
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    prompt = [((7 * i) % 251) + 1 for i in range(48)]
    n_tokens = 24
    expected = [prompt[i % len(prompt)] for i in range(n_tokens)]
    with chaos_fleet(
        2,
        env={"ECHO_STEP_MS": "40", "KV_BLOCK_TOKENS": "16",
             "KV_TRANSFER_TIMEOUT_S": "5"},
        per_replica_env=[{"FLEET_ROLE": "prefill"},
                         {"FLEET_ROLE": "decode"}],
    ) as replicas, chaos_router(
        replicas,
        env={"FLEET_PROBE_INTERVAL_S": "0.05", "FLEET_OUT_AFTER": "2",
             "FLEET_PROBATION_PROBES": "2", "FLEET_READ_TIMEOUT_S": "5",
             "FLEET_DEADLINE_S": "30"},
    ) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        fleet = app.container.fleet
        donor, decoder = replicas
        _wait(lambda: len(fleet.replica_set.in_rotation()) == 2,
              message="replicas in rotation")
        # warm the donor: the decode replica's admission will PULL this
        # prompt's KV instead of prefilling locally
        _post(donor.address + "/generate",
              {"tokens": prompt, "max_new_tokens": 2}, timeout=20)

        req = urllib.request.Request(
            base + "/v1/completions",
            data=json.dumps({
                "model": "echo", "prompt": prompt, "max_tokens": n_tokens,
                "stream": True, "seed": 7,
            }).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-ID": "trace-spine-1"},
            method="POST",
        )
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200
        assert resp.headers.get("X-Gofr-Request-Id") == "trace-spine-1"
        first = resp.read(1)
        assert first
        decoder.wedge()  # REAL device wedge mid-stream

        def kick():
            try:
                _post(decoder.address + "/generate",
                      {"tokens": [9], "max_new_tokens": 2}, timeout=30)
            except Exception:
                pass  # the wedged dispatch fails by design

        kicker = threading.Thread(target=kick, name="trace-wedge-kick")
        kicker.start()
        try:
            tokens, raw = _read_sse_tokens(resp, initial=first)
        finally:
            decoder.recover()
            kicker.join(20)
        assert b"data: [DONE]" in raw
        assert tokens == expected  # resume was bit-identical

        # -- the timeline ----------------------------------------------------
        status, body, _ = _get(base + "/admin/fleet/trace/trace-spine-1")
        assert status == 200
        timeline = json.loads(body)["data"]
        assert timeline["request_id"] == "trace-spine-1"
        router_block = timeline["router"]
        assert router_block["router_id"] == fleet.router_id
        assert router_block["kv_donor"] == donor.name
        assert router_block["resumes"] >= 1  # the forced resume happened
        assert isinstance(router_block["elapsed_ms"], float)
        served = [a for a in timeline["attempts"] if a.get("status") == 200]
        assert served and served[0]["replica"] == decoder.name
        flight = served[0]["flight"]
        assert flight is not None, timeline["evidence_gaps"]
        assert flight["request_id"] == "trace-spine-1"
        assert flight["origin"]["attempt"] == served[0]["index"]
        # KV-transfer evidence from both ends, keyed by the SAME id
        sides = {t["side"] for t in timeline["transfers"]}
        assert "receiver" in sides, timeline["transfers"]
        assert all(
            t["request_id"] == "trace-spine-1" for t in timeline["transfers"]
        )
        lat = timeline["latency"]
        assert lat["total_ms"] == router_block["elapsed_ms"]
        assert lat["router_overhead_ms"] is not None
        assert lat["replica_queue_ms"] is not None
        assert lat["device_ttft_ms"] is not None

        # span continuity across the resume: the continuation's flight
        # record exists SOMEWHERE in the fleet (the router resumes onto
        # whichever replica is healthy — here the wedged decoder is out,
        # so it lands on the other one), shares the original trace id,
        # and names the journal offset it resumed from
        flights = []
        for member in replicas:
            status, body, _ = _get(
                member.address + "/admin/requests?request_id=trace-spine-1"
            )
            flights.extend(json.loads(body)["data"]["requests"])
        assert len(flights) >= 2, "continuation flight record missing"
        trace_ids = {f["trace_id"] for f in flights}
        assert len(trace_ids) == 1, f"trace broke across resume: {trace_ids}"
        resumed = [f for f in flights if f["origin"]["resume_from"] > 0]
        assert resumed, [f["origin"] for f in flights]

        # -- partial-with-evidence when the replica goes dark ---------------
        decoder.stop_listener()
        try:
            status, body, _ = _get(
                base + "/admin/fleet/trace/trace-spine-1", timeout=30
            )
            assert status == 200  # partial, NOT a 500
            degraded = json.loads(body)["data"]
            assert degraded["partial"] is True
            assert any(
                decoder.name in gap for gap in degraded["evidence_gaps"]
            )
            # the router-side half of the story still stands
            assert degraded["router"]["elapsed_ms"] is not None
        finally:
            decoder.start_listener()


def test_fleet_trace_endpoint_rejects_garbage_and_404s_unknown(
        tmp_path, monkeypatch):
    from gofr_tpu.devtools.chaos import chaos_fleet, chaos_router

    monkeypatch.chdir(tmp_path)
    with chaos_fleet(1) as replicas, chaos_router(replicas) as app:
        base = f"http://127.0.0.1:{app.http_port}"
        _wait(lambda: len(app.container.fleet.replica_set.in_rotation()) == 1,
              message="replica in rotation")
        # valid-shaped but unknown: 404 with a reasoned message
        try:
            _get(base + "/admin/fleet/trace/req-never-seen")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
        # fuzzed ids: 4xx verdicts only, the assembler never 500s
        rng = random.Random(1)
        for _ in range(30):
            fuzz = "".join(
                chr(rng.randint(33, 126)) for _ in range(rng.randint(1, 90))
            )
            quoted = urllib.parse.quote(fuzz, safe="")
            try:
                status, _, _ = _get(f"{base}/admin/fleet/trace/{quoted}")
                assert status in (200, 404)
            except urllib.error.HTTPError as exc:
                assert exc.code in (400, 404), (fuzz, exc.code)
