"""End-to-end tests against REAL infrastructure containers.

Parity: /root/reference/.github/workflows/go.yml:17-28 boots redis:7.0.5 +
mysql:8.2.0 service containers and main_test.go:12-41 drives the
http-server example against them. The wire clients in this repo are
otherwise tested only against self-written fakes (minimysql/miniredis) —
a fake cannot catch a misreading of the spec both sides share, so CI runs
this module against software we did not write.

Gated on GOFR_REAL_INFRA=1 (the CI real-infrastructure job sets it after
booting the containers on the reference's ports: redis on 2002, mysql on
2001 with root/password and database "test")."""

import json
import os
import time
import urllib.request

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("GOFR_REAL_INFRA") != "1",
    reason="real redis/mysql containers not available (set GOFR_REAL_INFRA=1)",
)

REDIS_PORT = int(os.environ.get("GOFR_REAL_REDIS_PORT", "2002"))
MYSQL_PORT = int(os.environ.get("GOFR_REAL_MYSQL_PORT", "2001"))
MYSQL_PASSWORD = os.environ.get("GOFR_REAL_MYSQL_PASSWORD", "password")


@pytest.fixture(scope="module")
def app_base():
    """The http-server example's route surface wired to the real
    containers, served over a real socket (main_test.go boots main())."""
    import socket

    import gofr_tpu

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        "APP_NAME": "real-infra-test",
        "HTTP_PORT": str(port),
        "LOG_LEVEL": "ERROR",
        "REDIS_HOST": "127.0.0.1",
        "REDIS_PORT": str(REDIS_PORT),
        "DB_DIALECT": "mysql",
        "DB_HOST": "127.0.0.1",
        "DB_PORT": str(MYSQL_PORT),
        "DB_USER": "root",
        "DB_PASSWORD": MYSQL_PASSWORD,
        "DB_NAME": "test",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        app = gofr_tpu.new()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    from gofr_tpu.errors import HTTPError

    def redis_handler(ctx):
        if ctx.redis is None:
            raise HTTPError(503, "redis not configured")
        ctx.redis.set("test", "real-infra", ex=60)
        return ctx.redis.get("test")

    def mysql_handler(ctx):
        if ctx.db is None:
            raise HTTPError(503, "sql not configured")
        return ctx.db.select_value("SELECT 2+2")

    app.get("/redis", redis_handler)
    app.get("/mysql", mysql_handler)
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(base + "/.well-known/health", timeout=2)
            break
        except Exception:
            time.sleep(0.5)
    yield base
    app.shutdown()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def test_health_reports_real_datasources_up(app_base):
    status, body = _get(app_base, "/.well-known/health")
    assert status == 200
    details = body["data"]["details"]
    assert details["redis"]["status"] == "UP"
    assert details["sql"]["status"] == "UP"


def test_redis_route_round_trips_through_real_server(app_base):
    status, body = _get(app_base, "/redis")
    assert status == 200
    assert body["data"] == "real-infra"


def test_mysql_route_queries_real_server(app_base):
    """Auth against stock mysql:8 exercises caching_sha2_password for
    real — the round-3 partial this module exists to close."""
    status, body = _get(app_base, "/mysql")
    assert status == 200
    assert body["data"] == 4


def test_mysql_ddl_dml_select_cycle(app_base):
    from gofr_tpu.datasource.mysql import MySQLDB

    db = MySQLDB("127.0.0.1", MYSQL_PORT, "root", MYSQL_PASSWORD, "test")
    try:
        db.execute("DROP TABLE IF EXISTS gofr_ci_probe")
        db.execute(
            "CREATE TABLE gofr_ci_probe (id INT PRIMARY KEY, note VARCHAR(64))"
        )
        assert db.execute(
            "INSERT INTO gofr_ci_probe VALUES (?, ?)", 1, "it's \"quoted\"\n"
        ) == 1
        row = db.query_row("SELECT note FROM gofr_ci_probe WHERE id = ?", 1)
        assert row[0] == "it's \"quoted\"\n"
        db.execute("DROP TABLE gofr_ci_probe")
    finally:
        db.close()


def test_redis_pipeline_against_real_server(app_base):
    from gofr_tpu.datasource.redis import new_client

    client = new_client("127.0.0.1", REDIS_PORT, None)
    with client.pipeline() as pipe:
        pipe.set("gofr:ci:a", "1")
        pipe.set("gofr:ci:b", "2")
        pipe.get("gofr:ci:a")
    assert client.get("gofr:ci:a") == "1"
    client.delete("gofr:ci:a", "gofr:ci:b")
    client.close()
