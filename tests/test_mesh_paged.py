"""Sharded serving acceptance (the tentpole contract): with
``TPU_MESH=tp=2`` on the virtual 8-device CPU mesh (conftest), pooled
decode, solo decode, prefix-cache hits, and chunked prefill produce
BIT-IDENTICAL outputs to the single-device path — and ``KV_PAGED`` is
genuinely ACTIVE (block arena sharded over tp, ``/admin/engine``
``kv_blocks`` populated), never a silent fallback to the slot/row
model. Deliberately tier-1 (tiny model, ONE compiled bucket) so the
whole sharded serving path stays compile-cheap without a TPU."""

import os

import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.logging import Level
from gofr_tpu.metrics import Registry
from gofr_tpu.testutil import MockLogger
from gofr_tpu.tpu.device import new_device

PROMPT = [3, 1, 4, 1, 5, 9, 2, 6]
# longer than the single compiled bucket (64) -> the chunked-prefill path
LONG_PROMPT = [(7 * i) % 250 + 1 for i in range(80)]

# ONE compiled bucket + a 2-slot pool keeps the per-device boot to a few
# seconds of small CPU compiles — the price of running the sharded
# acceptance in tier-1 instead of behind the slow marker
_BASE = {
    "MODEL_NAME": "tiny", "BATCH_MAX_SIZE": "2", "BATCH_TIMEOUT_MS": "1",
    "MODEL_BUCKETS": "64", "DECODE_SLOTS": "2", "PREFIX_CACHE": "2",
}


def _device(**env):
    cfg = dict(_BASE)
    cfg.update(env)
    old = {k: os.environ.get(k) for k in cfg}
    os.environ.update(cfg)
    try:
        return new_device(EnvConfig(), MockLogger(Level.INFO), Registry())
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


@pytest.fixture(scope="module")
def plain():
    d = _device(TPU_MESH="")
    yield d
    d.close()


@pytest.fixture(scope="module")
def sharded():
    d = _device(TPU_MESH="tp=2")
    yield d
    d.close()


def test_paged_kv_active_under_tp_mesh(sharded):
    # the acceptance bar: KV_PAGED=on is ACTUALLY on — no silent
    # fallback to the slot/copy model under the mesh
    assert sharded.kv_pool is not None
    assert sharded.runner.kv_paged_disabled == ""
    store = sharded.runner._paged_prefix
    assert store is not None
    # the block arena itself is sharded: k/v span both tp devices
    assert len(store.arena.k.sharding.device_set) == 2
    assert store.arena.mesh is sharded.mesh


def test_pooled_bit_identity(plain, sharded):
    assert sharded.decode_pool is not None
    a = plain.generate(PROMPT, max_new_tokens=8)
    b = sharded.generate(PROMPT, max_new_tokens=8)
    assert a == b


def test_solo_bit_identity(plain, sharded):
    # a SEEDED greedy sampler bypasses the pool (per-request key
    # reproducibility), driving the solo chunked-decode path on both
    from gofr_tpu.ops.sampling import Sampler

    a = plain.generate(PROMPT, max_new_tokens=8, sampler=Sampler(seed=7))
    b = sharded.generate(PROMPT, max_new_tokens=8, sampler=Sampler(seed=7))
    assert a == b


def test_prefix_hit_bit_identity(plain, sharded):
    # same prompt twice: the second serve rides the paged prefix cache
    # (blocks gathered from the SHARDED arena) and must not drift
    prompt = [11, 13, 17, 19, 23, 29, 31, 37]
    a1 = plain.generate(prompt, max_new_tokens=8)
    b1 = sharded.generate(prompt, max_new_tokens=8)
    hits_before = sharded.runner.prefix_stats["hits"]
    a2 = plain.generate(prompt, max_new_tokens=8)
    b2 = sharded.generate(prompt, max_new_tokens=8)
    assert a1 == b1 and a2 == b2 and a1 == a2
    assert sharded.runner.prefix_stats["hits"] > hits_before


def test_chunked_prefill_bit_identity(plain, sharded):
    # 80 tokens through the 64-wide bucket: the chunked-prefill path
    # (lifted for tp-only meshes — dp/fsdp still degrades) slices
    # through the same compiled shape on both topologies
    a = plain.generate(LONG_PROMPT, max_new_tokens=8)
    b = sharded.generate(LONG_PROMPT, max_new_tokens=8)
    assert a == b


def test_admin_engine_mesh_and_kv_blocks(sharded):
    snap = sharded.engine_snapshot()
    assert snap["mesh"] == {"axes": {"tp": 2}, "devices": 2}
    kv = snap["kv_blocks"]
    assert kv is not None and kv["total"] > 0
    assert kv["block_tokens"] == 64
    # the decode pool shares the same ledger and reports its mesh
    assert snap["decode_pool"]["mesh_axes"] == {"tp": 2}
    assert snap["decode_pool"]["kv"]["total"] == kv["total"]


def test_mesh_axis_gauge_and_flight_record(sharded):
    assert sharded._mesh_axis_gauge.value(axis="tp") == 2.0
    assert sharded._mesh_axis_gauge.value(axis="dp") == 1.0
    # flight records stamp the topology they ran on
    from gofr_tpu.telemetry import FlightRecorder, activate_record

    recorder = FlightRecorder()
    rec = recorder.start(model="tiny", endpoint="/t")
    try:
        sharded.generate(PROMPT, max_new_tokens=2)
    finally:
        recorder.finish(rec)
        activate_record(None)
    assert rec.mesh_axes == {"tp": 2}
    assert rec.to_dict()["mesh_axes"] == {"tp": 2}


def test_no_mesh_degrade_counted_for_tp_only(sharded, plain):
    # tp-only composes: nothing should have degraded on either device
    for feature in ("kv_paged", "chunked_prefill", "decode_pool"):
        assert sharded._mesh_degrade.value(feature=feature) == 0
        assert plain._mesh_degrade.value(feature=feature) == 0


def test_dp_mesh_degrades_paged_kv_with_metric():
    """The other half of the contract: a dp mesh CANNOT carry paged KV
    (block gather/scatter needs the cache batch axis unsharded) — it
    must degrade to the row model loudly (reason recorded, feature
    counted), never error and never silently pretend."""
    d = _device(TPU_MESH="dp=2")
    try:
        assert d.kv_pool is None
        assert "dp/fsdp" in d.runner.kv_paged_disabled
        assert d._mesh_degrade.value(feature="kv_paged") == 1
        # still serves (row-model prefix cache, pooled decode over dp)
        assert len(d.generate(PROMPT, max_new_tokens=4)) == 4
        snap = d.engine_snapshot()
        assert snap["mesh"] == {"axes": {"dp": 2}, "devices": 2}
        assert snap["kv_blocks"] is None
    finally:
        d.close()
