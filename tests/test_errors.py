from gofr_tpu.errors import (
    EntityNotFoundError,
    GofrError,
    HTTPError,
    InvalidParamError,
    MissingParamError,
    TooManyRequestsError,
    status_from_error,
)


def test_status_mapping():
    assert status_from_error(None) == 200
    assert status_from_error(InvalidParamError("id")) == 400
    assert status_from_error(MissingParamError("name")) == 400
    assert status_from_error(EntityNotFoundError("user", "7")) == 404
    assert status_from_error(TooManyRequestsError()) == 429
    assert status_from_error(HTTPError(418, "teapot")) == 418
    assert status_from_error(ValueError("boom")) == 500
    assert status_from_error(GofrError("x")) == 500


def test_messages():
    assert "user" in str(EntityNotFoundError("user", "7"))
    assert "id" in str(InvalidParamError("id"))
