"""Test environment: force JAX onto a virtual 8-device CPU mesh BEFORE jax
is imported anywhere, mirroring the reference CI's strategy of running
against local fakes (SURVEY.md §4: sqlmock/miniredis ↔ CPU PJRT here).
"""

import os

# HARD override: the ambient environment pins JAX_PLATFORMS to the TPU
# plugin; tests must run on the virtual 8-device CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The ambient sitecustomize force-registers the TPU plugin even when
# JAX_PLATFORMS=cpu is in the env; the config update below is the override
# that actually sticks (must run before any backend initialization).
jax.config.update("jax_platforms", "cpu")

# this jax build computes f32 matmuls at reduced precision by default (TPU
# convention); numeric tests need exact f32 accumulation
jax.config.update("jax_default_matmul_precision", "highest")

import socket

import pytest


@pytest.fixture
def free_port():
    def _get():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    return _get
