"""Test environment: force JAX onto a virtual 8-device CPU mesh BEFORE jax
is imported anywhere, mirroring the reference CI's strategy of running
against local fakes (SURVEY.md §4: sqlmock/miniredis ↔ CPU PJRT here).
"""

import os

# HARD override: the ambient environment pins JAX_PLATFORMS to the TPU
# plugin; tests must run on the virtual 8-device CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The ambient sitecustomize force-registers the TPU plugin even when
# JAX_PLATFORMS=cpu is in the env; the config update below is the override
# that actually sticks (must run before any backend initialization).
jax.config.update("jax_platforms", "cpu")

# this jax build computes f32 matmuls at reduced precision by default (TPU
# convention); numeric tests need exact f32 accumulation
jax.config.update("jax_default_matmul_precision", "highest")

import json
import socket
import threading

import pytest

from gofr_tpu.devtools import sanitizer as _sanitizer

# GOFR_SANITIZE=1: rebind threading.Lock/RLock to the instrumented
# wrappers BEFORE any engine object builds its locks — the whole suite
# then runs under lock-order cycle detection, hold-time tracking, and
# the per-test thread-leak check below (CI runs this as the `sanitize`
# tier-1 variant, serial so the graph sees real interleavings).
if _sanitizer.enabled():
    _sanitizer.install()
    # fresh report per session: the per-test writes below append, so a
    # leftover file would misattribute a previous run's findings
    try:
        os.unlink(os.environ.get("GOFR_SANITIZE_REPORT",
                                 "sanitizer-report.jsonl"))
    except OSError:
        pass


def _format_finding(v: dict) -> str:
    lines = [v.get("summary") or v.get("kind", "finding")]
    for key in ("this_edge", "reverse_edge"):
        edge = v.get(key)
        if edge:
            lines.append(f"  {key}: {edge['from']} -> {edge['to']} "
                         f"on thread {edge['thread']}")
            lines.extend(f"    {frame}" for frame in edge["acquire_stack"][:6])
    return "\n".join(lines)


@pytest.fixture(autouse=True)
def gofr_sanitize(request):
    """Per-test concurrency verdict under GOFR_SANITIZE=1: fail the
    test that recorded a lock-order cycle or leaked an unjoined
    non-daemon thread (allowlisted singletons exempt). Findings also
    land in GOFR_SANITIZE_REPORT (default sanitizer-report.jsonl) so CI
    can upload them as an artifact."""
    if not _sanitizer.enabled():
        yield
        return
    before = set(threading.enumerate())
    yield
    leaked = _sanitizer.leaked_threads(before)
    report = _sanitizer.drain()
    problems = [_format_finding(v) for v in report["violations"]]
    if leaked:
        problems.append(
            "leaked non-daemon thread(s): "
            + ", ".join(sorted(t.name for t in leaked))
            + " — join them in close()/shutdown() or daemonize"
        )
    if problems or report["hold_warnings"]:
        path = os.environ.get("GOFR_SANITIZE_REPORT", "sanitizer-report.jsonl")
        try:
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps({
                    "test": request.node.nodeid,
                    "violations": report["violations"],
                    "hold_warnings": report["hold_warnings"],
                    "leaked_threads": sorted(t.name for t in leaked),
                }) + "\n")
        except OSError:
            pass
    if problems:
        pytest.fail(
            "concurrency sanitizer:\n" + "\n".join(problems), pytrace=False
        )


def pytest_sessionfinish(session):
    """GOFR_SANITIZE_GRAPH=<file>: write the whole session's OBSERVED
    lock-order graph (the edge graph survives drain() on purpose) in
    the static exporter's schema, for the static∪runtime cycle check
    in tools/lockgraph_check.py."""
    graph_path = os.environ.get("GOFR_SANITIZE_GRAPH")
    if graph_path and _sanitizer.enabled():
        try:
            _sanitizer.export_graph(graph_path)
        except OSError:
            pass


@pytest.fixture
def free_port():
    def _get():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    return _get


@pytest.fixture
def make_plain_app(free_port, monkeypatch, tmp_path):
    """ONE place that builds a datasource-free App for transport tests
    (http/app/protocol suites shared this setup as drifting copies: the
    env-scrub list must grow in ONE spot when the container gains a new
    datasource host). Returns a builder; the caller registers routes and
    calls start(). Teardown shuts the app down."""
    import gofr_tpu

    built = []

    def _build():
        monkeypatch.setenv("HTTP_PORT", str(free_port()))
        monkeypatch.setenv("LOG_LEVEL", "FATAL")
        for key in ("REDIS_HOST", "DB_NAME", "DB_HOST", "TPU_ENABLED",
                    "MODEL_NAME"):
            monkeypatch.delenv(key, raising=False)
        monkeypatch.chdir(tmp_path)
        application = gofr_tpu.new()
        built.append(application)
        return application

    yield _build
    for application in built:
        application.shutdown()
