"""Test environment: force JAX onto a virtual 8-device CPU mesh BEFORE jax
is imported anywhere, mirroring the reference CI's strategy of running
against local fakes (SURVEY.md §4: sqlmock/miniredis ↔ CPU PJRT here).
"""

import os

# HARD override: the ambient environment pins JAX_PLATFORMS to the TPU
# plugin; tests must run on the virtual 8-device CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The ambient sitecustomize force-registers the TPU plugin even when
# JAX_PLATFORMS=cpu is in the env; the config update below is the override
# that actually sticks (must run before any backend initialization).
jax.config.update("jax_platforms", "cpu")

# this jax build computes f32 matmuls at reduced precision by default (TPU
# convention); numeric tests need exact f32 accumulation
jax.config.update("jax_default_matmul_precision", "highest")

import socket

import pytest


@pytest.fixture
def free_port():
    def _get():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    return _get


@pytest.fixture
def make_plain_app(free_port, monkeypatch, tmp_path):
    """ONE place that builds a datasource-free App for transport tests
    (http/app/protocol suites shared this setup as drifting copies: the
    env-scrub list must grow in ONE spot when the container gains a new
    datasource host). Returns a builder; the caller registers routes and
    calls start(). Teardown shuts the app down."""
    import gofr_tpu

    built = []

    def _build():
        monkeypatch.setenv("HTTP_PORT", str(free_port()))
        monkeypatch.setenv("LOG_LEVEL", "FATAL")
        for key in ("REDIS_HOST", "DB_NAME", "DB_HOST", "TPU_ENABLED",
                    "MODEL_NAME"):
            monkeypatch.delenv(key, raising=False)
        monkeypatch.chdir(tmp_path)
        application = gofr_tpu.new()
        built.append(application)
        return application

    yield _build
    for application in built:
        application.shutdown()
