"""Test environment: force JAX onto a virtual 8-device CPU mesh BEFORE jax
is imported anywhere, mirroring the reference CI's strategy of running
against local fakes (SURVEY.md §4: sqlmock/miniredis ↔ CPU PJRT here).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import socket

import pytest


@pytest.fixture
def free_port():
    def _get():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    return _get
