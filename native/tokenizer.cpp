// Byte-level BPE tokenizer — the framework's native hot-path component.
//
// The reference framework is pure Go (SURVEY.md §2 "Native components:
// none"); the TPU build adds native code where the serving hot path needs
// it (task brief: runtime around the XLA compute path). Tokenization is
// the classic case: per-request, CPU-bound, allocation-heavy in Python,
// and entirely outside XLA's domain.
//
// Algorithm: greedy rank-based BPE over raw bytes (the GPT-2 family's
// merge loop, re-implemented from the published algorithm):
//   1. each input byte starts as its own symbol (ids 0..255);
//   2. repeatedly merge the adjacent pair with the lowest merge rank
//      until no mergeable pair remains;
//   3. emit vocabulary ids (merged symbols get ids 256+rank by default,
//      or explicit ids from the vocab file).
// Model file format (one merge per line): "left right" where left/right
// are previously-defined symbols spelled as byte escapes (see parse_sym).
//
// C ABI (ctypes-friendly): opaque handle, int64 lengths, caller-owned
// buffers. No exceptions cross the boundary.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<int32_t, int32_t>& p) const {
        return (static_cast<size_t>(static_cast<uint32_t>(p.first)) << 32) ^
               static_cast<uint32_t>(p.second);
    }
};

struct Tokenizer {
    // merge rank: (left_id, right_id) -> rank; merged id = 256 + rank
    std::unordered_map<std::pair<int32_t, int32_t>, int32_t, PairHash> ranks;
    // id -> byte string it decodes to
    std::vector<std::string> pieces;
    int32_t n_special = 0;  // special ids occupy the tail of the id space

    Tokenizer() {
        pieces.reserve(256);
        for (int i = 0; i < 256; ++i) {
            pieces.emplace_back(1, static_cast<char>(i));
        }
    }

    int32_t vocab_size() const {
        return static_cast<int32_t>(pieces.size()) + n_special;
    }

    // Returns false (and changes nothing) for a duplicate pair — ranks and
    // pieces must stay in lockstep or later ids decode to the wrong bytes.
    bool add_merge(int32_t left, int32_t right) {
        int32_t rank = static_cast<int32_t>(ranks.size());
        if (!ranks.emplace(std::make_pair(left, right), rank).second) {
            return false;
        }
        pieces.push_back(pieces[left] + pieces[right]);
        return true;
    }

    // O(n log n) merge: doubly-linked list of live symbols + a min-heap of
    // (rank, position) candidates with lazy invalidation. Equal-rank
    // candidates pop leftmost-first, matching the greedy reference scan.
    void encode(const uint8_t* data, int64_t len, std::vector<int32_t>& out) const {
        out.clear();
        if (len <= 0) return;
        std::vector<int32_t> ids(data, data + len);
        std::vector<int64_t> prev(len), next(len);
        for (int64_t i = 0; i < len; ++i) {
            prev[i] = i - 1;
            next[i] = i + 1 < len ? i + 1 : -1;
        }
        struct Cand {
            int32_t rank;
            int64_t pos;       // left symbol's position
            int32_t left, right;  // ids at push time (for lazy validation)
            bool operator>(const Cand& o) const {
                return rank != o.rank ? rank > o.rank : pos > o.pos;
            }
        };
        std::priority_queue<Cand, std::vector<Cand>, std::greater<Cand>> heap;
        auto push_cand = [&](int64_t i) {
            if (i < 0) return;  // leftmost symbol has prev == -1
            int64_t j = next[i];
            if (j < 0) return;
            auto it = ranks.find({ids[i], ids[j]});
            if (it != ranks.end()) heap.push({it->second, i, ids[i], ids[j]});
        };
        for (int64_t i = 0; i + 1 < len; ++i) push_cand(i);
        std::vector<bool> dead(len, false);
        while (!heap.empty()) {
            Cand c = heap.top();
            heap.pop();
            int64_t i = c.pos, j = dead[c.pos] ? -1 : next[c.pos];
            if (j < 0 || dead[i] || dead[j] || ids[i] != c.left || ids[j] != c.right) {
                continue;  // stale candidate
            }
            ids[i] = 256 + c.rank;
            dead[j] = true;
            next[i] = next[j];
            if (next[j] >= 0) prev[next[j]] = i;
            push_cand(prev[i]);
            push_cand(i);
        }
        out.reserve(len);
        for (int64_t i = 0; i >= 0; i = next[i]) out.push_back(ids[i]);
    }

    int64_t decode(const int32_t* ids, int64_t n, std::string& out) const {
        out.clear();
        for (int64_t i = 0; i < n; ++i) {
            int32_t id = ids[i];
            if (id < 0 || id >= static_cast<int32_t>(pieces.size())) continue;  // skip specials/oob
            out += pieces[id];
        }
        return static_cast<int64_t>(out.size());
    }
};

}  // namespace

extern "C" {

// Build from a merges buffer: lines of "left right" (ids, decimal).
// n_special reserves ids at the top of the vocab (pad/bos/eos...).
void* gofr_tok_new(const char* merges, int64_t merges_len, int32_t n_special) {
    auto* t = new Tokenizer();
    t->n_special = n_special;
    const char* p = merges;
    const char* end = merges + merges_len;
    while (p < end) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        // parse "int int"; skip any line that isn't exactly that (headers,
        // comments, blanks) — strtol signals "no digits" via after == p
        char* after = nullptr;
        long left = strtol(p, &after, 10);
        if (after != p && after < line_end) {
            const char* mid = after;
            long right = strtol(mid, &after, 10);
            // operands must name already-defined PIECES (merged symbols or
            // bytes), never special-range ids — pieces[] indexing below
            long defined = static_cast<long>(t->pieces.size());
            if (after != mid && left >= 0 && right >= 0 &&
                left < defined && right < defined) {
                t->add_merge(static_cast<int32_t>(left), static_cast<int32_t>(right));
            }
        }
        p = nl ? nl + 1 : end;
    }
    return t;
}

void gofr_tok_free(void* handle) { delete static_cast<Tokenizer*>(handle); }

int32_t gofr_tok_vocab_size(void* handle) {
    return static_cast<Tokenizer*>(handle)->vocab_size();
}

// Encode utf-8 bytes into out (capacity out_cap); returns the id count
// (which may exceed out_cap — caller re-calls with a larger buffer).
int64_t gofr_tok_encode(void* handle, const uint8_t* text, int64_t text_len,
                        int32_t* out, int64_t out_cap) {
    thread_local std::vector<int32_t> ids;
    static_cast<Tokenizer*>(handle)->encode(text, text_len, ids);
    int64_t n = static_cast<int64_t>(ids.size());
    if (out && out_cap > 0) {
        memcpy(out, ids.data(), sizeof(int32_t) * std::min(n, out_cap));
    }
    return n;
}

// Decode ids into out (capacity out_cap bytes); returns byte count.
int64_t gofr_tok_decode(void* handle, const int32_t* ids, int64_t n,
                        uint8_t* out, int64_t out_cap) {
    thread_local std::string buf;
    int64_t need = static_cast<Tokenizer*>(handle)->decode(ids, n, buf);
    if (out && out_cap > 0) {
        memcpy(out, buf.data(), std::min(need, out_cap));
    }
    return need;
}

// Batch pad/pack: rows of variable-length int32 ids -> a [n_rows, width]
// row-major buffer (pad_id fill) + per-row lengths. The serving batcher's
// per-request Python loop replaced with one native call.
void gofr_pack_rows(const int32_t* flat, const int64_t* row_lens, int64_t n_rows,
                    int64_t width, int32_t pad_id, int32_t* out, int32_t* out_lens) {
    int64_t off = 0;
    for (int64_t r = 0; r < n_rows; ++r) {
        int64_t len = row_lens[r];
        int64_t keep = len < width ? len : width;
        // overlong rows keep their LAST tokens (recency wins for next-token
        // prediction — matches _TransformerRunner.prepare)
        const int32_t* src = flat + off + (len - keep);
        int32_t* dst = out + r * width;
        memcpy(dst, src, sizeof(int32_t) * keep);
        for (int64_t i = keep; i < width; ++i) dst[i] = pad_id;
        out_lens[r] = static_cast<int32_t>(keep);
        off += len;
    }
}

}  // extern "C"
