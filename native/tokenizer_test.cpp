// Threaded sanity/race harness for the native boundary (tokenizer + batch
// packing). Built with -fsanitize=thread in CI (the framework's analogue of
// `go test -race`, which the reference pipeline omits — SURVEY.md §5): a
// shared Tokenizer handle is exercised from many threads exactly as the
// serving process does (one handle, per-request encode/decode on handler
// threads; pack_rows on the batcher thread), with results checked against a
// single-threaded reference.
//
// Build:  g++ -std=c++17 -O1 -g -fsanitize=thread   tokenizer.cpp tokenizer_test.cpp -o tok_test -lpthread
//    or:  g++ -std=c++17 -O1 -g -fsanitize=undefined tokenizer.cpp tokenizer_test.cpp -o tok_test -lpthread
// Run: ./tok_test   (exit 0 = clean; sanitizer reports fail the process)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* gofr_tok_new(const char* merges, int64_t merges_len, int32_t n_special);
void gofr_tok_free(void* handle);
int32_t gofr_tok_vocab_size(void* handle);
int64_t gofr_tok_encode(void* handle, const uint8_t* text, int64_t text_len,
                        int32_t* out, int64_t out_cap);
int64_t gofr_tok_decode(void* handle, const int32_t* ids, int64_t n,
                        uint8_t* out, int64_t out_cap);
void gofr_pack_rows(const int32_t* flat, const int64_t* row_lens, int64_t n_rows,
                    int64_t width, int32_t pad_id, int32_t* out, int32_t* out_lens);
}

namespace {

constexpr int kThreads = 8;
constexpr int kIters = 400;

std::vector<int32_t> encode(void* tok, const std::string& text) {
    std::vector<int32_t> ids(text.size() + 1);
    int64_t n = gofr_tok_encode(tok, reinterpret_cast<const uint8_t*>(text.data()),
                                static_cast<int64_t>(text.size()), ids.data(),
                                static_cast<int64_t>(ids.size()));
    ids.resize(static_cast<size_t>(n));
    return ids;
}

std::string decode(void* tok, const std::vector<int32_t>& ids) {
    std::vector<uint8_t> buf(ids.size() * 8 + 1);
    int64_t n = gofr_tok_decode(tok, ids.data(), static_cast<int64_t>(ids.size()),
                                buf.data(), static_cast<int64_t>(buf.size()));
    return std::string(reinterpret_cast<char*>(buf.data()), static_cast<size_t>(n));
}

}  // namespace

int main() {
    // a few byte-pair merges over ASCII so encode actually merges
    const char* merges = "116 104\n256 101\n32 257\n101 32\n111 110\n";
    void* tok = gofr_tok_new(merges, static_cast<int64_t>(strlen(merges)), 3);
    if (tok == nullptr) {
        fprintf(stderr, "gofr_tok_new failed\n");
        return 1;
    }

    const std::string texts[] = {
        "the quick brown fox jumps over the lazy dog",
        "on the theory of everything, then and now",
        std::string(512, 'a') + " the end",
    };
    // single-threaded reference results
    std::vector<std::vector<int32_t>> ref_ids;
    std::vector<std::string> ref_text;
    for (const auto& t : texts) {
        ref_ids.push_back(encode(tok, t));
        ref_text.push_back(decode(tok, ref_ids.back()));
    }

    int failures = 0;
    std::vector<std::thread> threads;
    threads.reserve(kThreads);
    for (int w = 0; w < kThreads; ++w) {
        threads.emplace_back([&, w] {
            for (int i = 0; i < kIters; ++i) {
                const size_t which = static_cast<size_t>((w + i) % 3);
                auto ids = encode(tok, texts[which]);
                if (ids != ref_ids[which]) {
                    __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);
                }
                if (decode(tok, ids) != ref_text[which]) {
                    __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);
                }
                // pack_rows with thread-local buffers (the batcher calls it
                // with its own arrays; the shared state is only the code)
                int32_t flat[6] = {1, 2, 3, 4, 5, 6};
                int64_t lens[2] = {4, 2};
                int32_t out[2 * 4];
                int32_t out_lens[2];
                gofr_pack_rows(flat, lens, 2, 4, 0, out, out_lens);
                if (out_lens[0] != 4 || out_lens[1] != 2 || out[4] != 5) {
                    __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);
                }
            }
        });
    }
    for (auto& t : threads) t.join();
    gofr_tok_free(tok);
    if (failures != 0) {
        fprintf(stderr, "tokenizer_test: %d mismatches under concurrency\n", failures);
        return 1;
    }
    printf("tokenizer_test: OK (%d threads x %d iters)\n", kThreads, kIters);
    return 0;
}
