"""Serving-config sweep over bench.py: runs the flagship benchmark under a
list of named configurations and prints one JSON line per run plus a
ranked summary. The driver-facing contract stays bench.py's single line;
this tool answers "which knobs move the number" on real hardware.

    python tools/bench_sweep.py                 # default sweep
    python tools/bench_sweep.py slots32 int4    # named subset

Each run is a fresh process (fresh device runtime), sharing the XLA
compile cache, so later runs boot fast.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# name -> env overrides on top of bench.py's flagship defaults (which are
# DECODE_SLOTS=8, DECODE_CHUNK=8, DECODE_PIPELINE=3 — the round-3 sweep
# ranked 8 slots above 16/32 on the tunneled link). The depth/chunk grid
# answers the round-3 finding that fetch-wait ate ~133ms of a ~137ms chunk
# at depth 2: deeper pipeline and/or longer chunks both amortise the link
# round trip, with different latency costs (chunk length delays delivery,
# depth only wastes lockstep steps on freed slots).
SWEEP: dict[str, dict[str, str]] = {
    "base8": {"DECODE_SLOTS": "8"},
    "depth2": {"DECODE_SLOTS": "8", "DECODE_PIPELINE": "2"},
    "depth4": {"DECODE_SLOTS": "8", "DECODE_PIPELINE": "4"},
    "chunk16": {"DECODE_SLOTS": "8", "DECODE_CHUNK": "16"},
    "chunk32": {"DECODE_SLOTS": "8", "DECODE_CHUNK": "32"},
    "chunk16-depth4": {
        "DECODE_SLOTS": "8", "DECODE_CHUNK": "16", "DECODE_PIPELINE": "4",
    },
    "slots16": {"DECODE_SLOTS": "16"},
    "slots16-chunk16": {"DECODE_SLOTS": "16", "DECODE_CHUNK": "16"},
    "slots32": {"DECODE_SLOTS": "32"},
    "slots32-f8kv": {"DECODE_SLOTS": "32", "MODEL_KV_DTYPE": "f8"},
    "int4": {"MODEL_QUANT": "int4"},
    "w8a8": {"MODEL_QUANT": "w8a8"},
    "attn-pallas": {"MODEL_ATTN_IMPL": "pallas"},
}


def main() -> int:
    names = sys.argv[1:] or list(SWEEP)
    unknown = [n for n in names if n not in SWEEP]
    if unknown:
        print(
            f"unknown config(s) {unknown}; available: {', '.join(SWEEP)}",
            file=sys.stderr,
        )
        return 2
    results = []
    failures = 0
    for name in names:
        env = {**os.environ, **SWEEP[name]}
        print(f"=== {name}: {SWEEP[name]}", file=sys.stderr, flush=True)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, capture_output=True, text=True, timeout=1800,
            )
        except subprocess.TimeoutExpired:
            # one hung config must not discard the completed results
            parsed = {"config": name, "errors": ["timeout after 1800s"]}
            results.append(parsed)
            failures += 1
            print(json.dumps(parsed), flush=True)
            continue
        line = (proc.stdout.strip().splitlines() or ["{}"])[-1]
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            parsed = {"errors": [f"unparseable: {line[:200]}"]}
        parsed["config"] = name
        results.append(parsed)
        print(json.dumps(parsed), flush=True)
        if proc.returncode != 0:
            failures += 1
            tail = "\n".join(proc.stderr.strip().splitlines()[-5:])
            print(f"--- {name} rc={proc.returncode}\n{tail}", file=sys.stderr)
    ranked = sorted(
        (r for r in results if r.get("decode_tok_per_sec")),
        key=lambda r: -r["decode_tok_per_sec"],
    )
    print("\n=== decode tok/s ranking", file=sys.stderr)
    for r in ranked:
        print(
            f"  {r['config']:>14}: {r['decode_tok_per_sec']:8.1f} tok/s  "
            f"p50 {r.get('value')}ms  mbu {r.get('mbu_decode')}",
            file=sys.stderr,
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
