"""Capture a live fleet's served traffic as a replayable trace.

Scrapes the router's route records (``/admin/fleet``) and each
replica's flight records (``/admin/requests``), joins them on the
fleet-wide request id, and writes a ``TRACE_CAPTURE`` artifact in the
exact fleetsim event schema — seeded anonymization throughout (tenant
hashes, session hashes, prompt SHAPES only; no prompt content is ever
read, because the fleet never stored any).

Usage::

    python tools/trace_capture.py --router http://127.0.0.1:8000 \
        [--replica http://127.0.0.1:8001 ...] \
        [--seed 20260807] [--limit 1000] [--out capture.json]

Then replay the captured window through the full chaos harness::

    python tools/fleetsim.py --replay capture.json

The artifact's ``digest`` is the determinism witness: the same fleet
state captured twice with the same seed is byte-identical, and the
replay run records the digest it drove (``trace.digest`` in the
FLEETSIM artifact) so CI can assert the round trip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--router", required=True,
                        help="router base URL (route records)")
    parser.add_argument("--replica", action="append", default=[],
                        help="replica base URL (flight records); repeatable."
                        " Omit to capture shapes from route records alone"
                        " (prompt lengths then fall back to a default)")
    parser.add_argument("--seed", type=int, default=20260807,
                        help="anonymization seed (tenant/session hashes and"
                        " synthetic prompt content key off it)")
    parser.add_argument("--limit", type=int, default=1000,
                        help="max records scraped per endpoint")
    parser.add_argument("--out", default="")
    args = parser.parse_args(argv[1:])

    from gofr_tpu.devtools.trace_capture import (
        capture_artifact,
        scrape_flights,
        scrape_routes,
    )

    routes = scrape_routes(args.router, limit=args.limit)
    flights: list = []
    for base in args.replica:
        try:
            flights.extend(scrape_flights(base, limit=args.limit))
        except Exception as exc:
            print(f"trace_capture: {base}: flight scrape failed ({exc}) — "
                  "capturing without its evidence", file=sys.stderr)
    artifact = capture_artifact(
        routes, flights, args.seed,
        source={
            "router": args.router,
            "replicas": args.replica,
            "captured_at": time.time(),  # gofrlint: wall-clock — capture timestamp (display)
        },
    )
    blob = json.dumps(artifact, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    print(
        f"trace_capture: {artifact['requests']} events "
        f"(dropped {artifact['dropped']}), digest {artifact['digest'][:16]}…",
        file=sys.stderr,
    )
    return 0 if artifact["requests"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
