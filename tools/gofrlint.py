#!/usr/bin/env python3
"""gofrlint — project-invariant linter for the gofr_tpu tree.

ruff holds the style/complexity line; this tool holds the PROJECT
invariants that generic linters cannot know — the conventions PRs 1-4
enforced by hand in review (config discipline, timestamp discipline,
thread hygiene, lock-hold discipline, metric naming, exception
swallowing in engine threads). Stdlib only (``ast`` + ``tokenize``), so
it runs anywhere the tests run.

Rules
-----
GFL001  no raw ``os.environ``/``os.getenv`` READS outside ``config.py``.
        Scope: package code (``gofr_tpu/``). Entry-point scripts
        (``tools/``, ``bench.py``, examples) configure the process
        environment before boot and are exempt, as are environment
        WRITES anywhere (``update``/``setdefault``/``pop``/item
        assignment — test scaffolding restores what it changed).
GFL002  timestamp discipline: ``time.time()`` is forbidden for
        durations/ordering — use ``time.monotonic()`` or
        ``time.perf_counter()``. Wall-clock is allowed only at sites
        explicitly annotated ``# gofrlint: wall-clock — <why>``
        (presentation: log lines, API timestamps, filenames).
GFL003  every ``threading.Thread`` must be named (``name=...``) and
        either ``daemon=True`` or joined (a zero-positional-arg
        ``.join()`` call somewhere in the same module).
GFL004  no blocking calls while holding a lock: ``time.sleep``,
        thread ``.join``, timeout-less queue ``get``/``put``, socket
        accept/recv, subprocess, HTTP — inside a ``with <lock>:`` block
        or between ``.acquire()``/``.release()``. ``Condition.wait``
        is exempt (it releases the lock it guards).
GFL005  metric names passed to the ``metrics.py`` constructors
        (``.counter()``/``.gauge()``/``.histogram()``) must follow the
        naming convention statically: ``gofr_`` prefix, snake_case,
        counters end ``_total``, histograms carry a unit suffix,
        gauges carry a unit/dimension suffix or an allowlist entry.
GFL006  a bare ``except:`` is forbidden everywhere; ``except
        Exception/BaseException: pass`` (swallow-and-continue) is
        forbidden in engine modules (``gofr_tpu/tpu/``, telemetry,
        timebase, tracing, postmortem, metrics) — a silently swallowed
        exception on an engine thread is a wedge with no evidence.

Suppression
-----------
``# gofrlint: disable=GFL001[,GFL004] — <reason>`` on the reported
line (or on a comment-only line directly above it) suppresses those
rules there. Suppressions are the violation LEDGER: grep-able, carried
in-file next to the code they excuse, and expected to only shrink.

Usage
-----
    python tools/gofrlint.py [--format=text|json] PATH [PATH...]

Exit status 0 when clean, 1 when violations were reported.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import re
import sys
import tokenize
from pathlib import Path
from typing import Optional

RULES = {
    "GFL001": "raw environment read outside config.py",
    "GFL002": "time.time() without a wall-clock annotation",
    "GFL003": "threading.Thread hygiene (name + daemon-or-joined)",
    "GFL004": "blocking call while holding a lock",
    "GFL005": "metric name violates the naming convention",
    "GFL006": "swallowed exception in an engine path",
}

_DISABLE_RE = re.compile(r"#\s*gofrlint:\s*disable=([A-Z0-9,\s]+)")
_WALL_RE = re.compile(r"#\s*gofrlint:\s*wall-clock")

# GFL001: os.environ methods that WRITE (allowed anywhere — scripts and
# test scaffolding set the process environment; only reads must route
# through config.py accessors)
_ENV_WRITE_METHODS = {"update", "pop", "setdefault", "clear", "__setitem__"}

# GFL005: mirrored from tests/test_metric_naming.py — the static half
# of the same convention
_COUNTER_SUFFIXES = ("_total",)
_HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_size")
_GAUGE_SUFFIXES = (  # keep in lockstep with tests/test_metric_naming.py
    "_seconds", "_bytes", "_total", "_depth", "_ratio", "_entries",
    "_active", "_acceptance", "_state", "_blocks", "_size", "_level",
    "_per_dispatch", "_rate", "_remaining",
)
_GAUGE_ALLOWLIST = {"gofr_tpu_mfu", "gofr_tpu_mbu"}

# GFL006: modules whose code runs on (or under the locks of) engine
# threads — a swallowed exception there is a silent wedge
_ENGINE_MODULES = {
    "telemetry.py", "timebase.py", "tracing.py", "postmortem.py",
    "metrics.py", "profiling.py",
}

# GFL004 heuristics
_LOCKISH_RE = re.compile(r"(lock|mutex|_mu)\b", re.IGNORECASE)
_QUEUEISH_RE = re.compile(r"(queue|(^|\.)q$|_q$)", re.IGNORECASE)
_EVENTISH_RE = re.compile(r"(event|_stop$|_ready$|stopped)", re.IGNORECASE)
_THREADISH_RE = re.compile(r"(thread|worker|proc)", re.IGNORECASE)


class Violation:
    __slots__ = ("rule", "path", "line", "col", "message")

    def __init__(self, rule: str, path: str, line: int, col: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message

    def as_dict(self) -> dict:
        return {
            "file": self.path, "line": self.line, "col": self.col,
            "rule": self.rule, "message": self.message,
        }


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # very old nodes / synthetic trees
        return ""


def _collect_comments(source: str) -> dict[int, str]:
    """line number -> comment text (tokenize-accurate: a ``# gofrlint``
    inside a string literal never counts)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class FileLinter:
    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.comments = _collect_comments(source)
        self.violations: list[Violation] = []
        self.in_package = "gofr_tpu" in Path(rel).parts
        parts = Path(rel).parts
        self.is_engine = (
            ("tpu" in parts and self.in_package)
            or Path(rel).name in _ENGINE_MODULES and self.in_package
        )
        # comment-only lines pass their directives down to the next
        # CODE line (cascading through blank lines and further comment
        # lines, so a multi-line reason block above a statement works)
        self._directive_lines: dict[int, str] = {}
        for lineno, comment in self.comments.items():
            line = self.lines[lineno - 1]
            code = line[: line.index("#")] if "#" in line else line
            target = lineno
            if not code.strip():
                target = lineno + 1
                while target <= len(self.lines):
                    stripped = self.lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
            self._directive_lines.setdefault(target, "")
            self._directive_lines[target] += " " + comment

    # -- directives -----------------------------------------------------------
    def _directives_at(self, lineno: int) -> str:
        return self._directive_lines.get(lineno, "")

    def suppressed(self, rule: str, lineno: int) -> bool:
        m = _DISABLE_RE.search(self._directives_at(lineno))
        if not m:
            return False
        codes = {c.strip() for c in m.group(1).split(",")}
        return rule in codes

    def wall_annotated(self, lineno: int) -> bool:
        return bool(_WALL_RE.search(self._directives_at(lineno)))

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.suppressed(rule, lineno):
            return
        self.violations.append(Violation(rule, self.rel, lineno, col, message))

    # -- entry ----------------------------------------------------------------
    def run(self) -> list[Violation]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError as exc:
            self.violations.append(Violation(
                "GFL000", self.rel, exc.lineno or 1, 0,
                f"syntax error: {exc.msg}",
            ))
            return self.violations
        parents: dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent
        self._parents = parents
        module_joins = self._module_has_thread_join(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_env_read_call(node)
                self._check_wall_clock(node)
                self._check_thread(node, module_joins)
                self._check_metric_name(node)
            elif isinstance(node, ast.Attribute):
                self._check_environ_use(node)
            elif isinstance(node, ast.ExceptHandler):
                self._check_except(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_lock_holds(node)
        return self.violations

    # -- GFL001 ---------------------------------------------------------------
    def _gfl001_active(self) -> bool:
        return self.in_package and Path(self.rel).name != "config.py"

    def _check_env_read_call(self, node: ast.Call) -> None:
        if not self._gfl001_active():
            return
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "getenv" and \
                isinstance(fn.value, ast.Name) and fn.value.id == "os":
            self.report(
                "GFL001", node,
                "os.getenv() outside config.py — use a config.py accessor "
                "(get_env/env_flag)",
            )

    def _check_environ_use(self, node: ast.Attribute) -> None:
        if not self._gfl001_active():
            return
        if node.attr != "environ" or not (
            isinstance(node.value, ast.Name) and node.value.id == "os"
        ):
            return
        parent = self._parents.get(id(node))
        # allowed: write-method calls and item writes/deletes
        if isinstance(parent, ast.Attribute) and \
                parent.attr in _ENV_WRITE_METHODS:
            return
        if isinstance(parent, ast.Subscript) and isinstance(
            parent.ctx, (ast.Store, ast.Del)
        ):
            return
        self.report(
            "GFL001", node,
            "raw os.environ read outside config.py — use a config.py "
            "accessor (get_env/env_flag/environ_snapshot)",
        )

    # -- GFL002 ---------------------------------------------------------------
    def _check_wall_clock(self, node: ast.Call) -> None:
        fn = node.func
        is_time_time = (
            isinstance(fn, ast.Attribute) and fn.attr == "time"
            and isinstance(fn.value, ast.Name) and fn.value.id == "time"
        )
        if not is_time_time:
            return
        if self.wall_annotated(node.lineno):
            return
        self.report(
            "GFL002", node,
            "time.time() — use time.monotonic()/perf_counter() for "
            "durations and ordering; annotate true presentation sites "
            "with '# gofrlint: wall-clock — <why>'",
        )

    # -- GFL003 ---------------------------------------------------------------
    @staticmethod
    def _module_has_thread_join(tree: ast.Module) -> bool:
        """A zero-positional-arg ``.join()`` call anywhere in the module
        (``t.join()``, ``self._thread.join(timeout=5)``). ``str.join``
        and ``os.path.join`` always take positional args."""
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not node.args
            ):
                return True
        return False

    def _check_thread(self, node: ast.Call, module_joins: bool) -> None:
        fn = node.func
        is_thread = (
            isinstance(fn, ast.Attribute) and fn.attr == "Thread"
            and isinstance(fn.value, ast.Name) and fn.value.id == "threading"
        ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if not is_thread:
            return
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        if "name" not in kwargs:
            self.report(
                "GFL003", node,
                "unnamed thread — pass name=... so stacks, the watchdog, "
                "and the leak detector can attribute it",
            )
        daemon = kwargs.get("daemon")
        is_daemon = isinstance(daemon, ast.Constant) and daemon.value is True
        if not is_daemon and not module_joins:
            self.report(
                "GFL003", node,
                "non-daemon thread with no .join() in this module — "
                "daemonize it or join it in close()",
            )

    # -- GFL004 ---------------------------------------------------------------
    def _check_lock_holds(self, func: ast.AST) -> None:
        self._walk_stmts(list(getattr(func, "body", [])), held=[])

    def _walk_stmts(self, stmts: list, held: list) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are visited on their own
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = [
                    _src(item.context_expr)
                    for item in stmt.items
                    if self._lockish(item.context_expr)
                ]
                held.extend(acquired)
                self._walk_stmts(stmt.body, held)
                for _ in acquired:
                    held.pop()
                continue
            lock_op = self._acquire_release(stmt)
            if lock_op is not None:
                op, name = lock_op
                if op == "acquire":
                    held.append(name)
                elif name in held:
                    held.remove(name)
                continue
            if held:
                for call in (
                    n for n in ast.walk(stmt) if isinstance(n, ast.Call)
                ):
                    self._check_blocking(call, held)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    self._walk_stmts(list(getattr(stmt, attr, [])), held)
                for handler in getattr(stmt, "handlers", []):
                    self._walk_stmts(list(handler.body), held)

    @staticmethod
    def _lockish(expr: ast.AST) -> bool:
        return bool(_LOCKISH_RE.search(_src(expr)))

    def _acquire_release(self, stmt: ast.stmt) -> Optional[tuple]:
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        call = stmt.value
        if not isinstance(call.func, ast.Attribute):
            return None
        if call.func.attr not in ("acquire", "release"):
            return None
        receiver = _src(call.func.value)
        if not _LOCKISH_RE.search(receiver):
            return None
        return (call.func.attr, receiver)

    @staticmethod
    def _has_timeout(call: ast.Call) -> bool:
        if any(kw.arg == "timeout" for kw in call.keywords):
            return True
        # Queue.get(block, timeout) positional form
        return len(call.args) >= 2

    def _check_blocking(self, call: ast.Call, held: list) -> None:
        fn = call.func
        label = None
        if isinstance(fn, ast.Attribute):
            receiver = _src(fn.value)
            attr = fn.attr
            if attr == "sleep" and receiver == "time":
                label = "time.sleep()"
            elif attr == "join" and not call.args and \
                    _THREADISH_RE.search(receiver):
                label = f"{receiver}.join()"
            elif attr in ("get", "put") and _QUEUEISH_RE.search(receiver) \
                    and not self._has_timeout(call):
                label = f"timeout-less {receiver}.{attr}()"
            elif attr == "wait" and _EVENTISH_RE.search(receiver) and \
                    not self._has_timeout(call) and not call.args:
                label = f"timeout-less {receiver}.wait()"
            elif attr in ("accept", "recv", "recvfrom") and \
                    _LOCKISH_RE.search(" ".join(held)):
                label = f"socket .{attr}()"
            elif receiver == "subprocess" and attr in (
                "run", "call", "check_call", "check_output"
            ):
                label = f"subprocess.{attr}()"
            elif receiver in ("requests", "urllib.request") or \
                    attr == "urlopen":
                label = f"{receiver}.{attr}()"
        elif isinstance(fn, ast.Name) and fn.id == "sleep":
            label = "sleep()"
        if label is None:
            return
        self.report(
            "GFL004", call,
            f"{label} while holding {held[-1]!r} — blocking under a lock "
            "stalls every contending thread (move it outside the "
            "critical section)",
        )

    # -- GFL005 ---------------------------------------------------------------
    def _check_metric_name(self, node: ast.Call) -> None:
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("counter", "gauge", "histogram")
        ):
            return
        if not node.args or not (
            isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return
        name = node.args[0].value
        kind = fn.attr
        problem = None
        if not name.startswith("gofr_"):
            problem = "missing gofr_ prefix"
        elif not re.fullmatch(r"[a-z][a-z0-9_]*", name) or "__" in name:
            problem = "not snake_case"
        elif kind == "counter" and not name.endswith(_COUNTER_SUFFIXES):
            problem = "counter must end in _total"
        elif kind == "histogram" and not name.endswith(_HISTOGRAM_SUFFIXES):
            problem = f"histogram needs a unit suffix {_HISTOGRAM_SUFFIXES}"
        elif kind == "gauge" and name not in _GAUGE_ALLOWLIST and \
                not name.endswith(_GAUGE_SUFFIXES):
            problem = (
                f"gauge needs a unit/dimension suffix {_GAUGE_SUFFIXES} "
                "(or an allowlist entry)"
            )
        if problem:
            self.report("GFL005", node, f"metric {name!r}: {problem}")

    # -- GFL006 ---------------------------------------------------------------
    def _check_except(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.report(
                "GFL006", node,
                "bare except: — catch a concrete exception type",
            )
            return
        if not self.is_engine:
            return
        broad = isinstance(node.type, ast.Name) and node.type.id in (
            "Exception", "BaseException"
        )
        body_is_pass = all(
            isinstance(s, ast.Pass)
            or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
            for s in node.body
        )
        if broad and body_is_pass:
            # report at the pass statement: the suppression comment (the
            # ledger entry) belongs next to the swallow itself
            self.report(
                "GFL006", node.body[0],
                f"except {node.type.id}: pass in an engine path — a "
                "swallowed exception on an engine thread is a silent "
                "wedge; log it, re-raise, or narrow the type",
            )


def iter_files(paths: list[str]) -> list[Path]:
    out: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out


def lint_paths(paths: list[str]) -> tuple[list[Violation], int]:
    violations: list[Violation] = []
    files = iter_files(paths)
    for path in files:
        try:
            source = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            continue
        rel = str(path)
        violations.extend(FileLinter(path, rel, source).run())
    return violations, len(files)


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="gofrlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        dest="fmt", help="output format",
    )
    args = parser.parse_args(argv)
    violations, scanned = lint_paths(args.paths)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    if args.fmt == "json":
        counts: dict[str, int] = {}
        for v in violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        print(json.dumps({
            "version": 1,
            "files_scanned": scanned,
            "violations": [v.as_dict() for v in violations],
            "counts_by_rule": counts,
        }, indent=2))
    else:
        for v in violations:
            print(f"{v.path}:{v.line}:{v.col + 1}: {v.rule} {v.message}")
        print(
            f"gofrlint: {len(violations)} violation(s) in {scanned} file(s)"
            if violations else f"gofrlint: clean ({scanned} files)"
        )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
