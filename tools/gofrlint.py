#!/usr/bin/env python3
"""gofrlint — project-invariant linter for the gofr_tpu tree.

This file is the stable entry point (CI and the test suite invoke
``python tools/gofrlint.py`` / import it by path); the implementation
lives in the ``tools/gofrlint/`` package. See that package's
``__init__`` docstring for the rule table (GFL001–GFL009), the
suppression-ledger contract, and the whole-program analysis model —
or docs/advanced-guide/static-analysis.md for the prose version.

Usage
-----
    python tools/gofrlint.py [--format=text|json] [--ledger]
        [--ledger-check FILE] [--emit-lock-graph FILE] PATH [PATH...]

Exit status 0 when clean, 1 when violations were reported (or the
suppression ledger grew past the committed baseline).
"""

from __future__ import annotations

import importlib.util
import sys
from pathlib import Path

_PKG_DIR = Path(__file__).resolve().parent / "gofrlint"


def _load_impl():
    cached = sys.modules.get("_gofrlint_impl")
    if cached is not None:
        return cached
    spec = importlib.util.spec_from_file_location(
        "_gofrlint_impl",
        _PKG_DIR / "__init__.py",
        submodule_search_locations=[str(_PKG_DIR)],
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules["_gofrlint_impl"] = module
    try:
        spec.loader.exec_module(module)
    except BaseException:
        sys.modules.pop("_gofrlint_impl", None)
        raise
    return module


_impl = _load_impl()

RULES = _impl.RULES
Violation = _impl.Violation
FileLinter = _impl.FileLinter
LintRun = _impl.LintRun
Project = _impl.Project
WholeProgram = _impl.WholeProgram
check_ledger = _impl.check_ledger
contract_violations = _impl.contract_violations
iter_files = _impl.iter_files
lint_paths = _impl.lint_paths
main = _impl.main
_COUNTER_SUFFIXES = _impl._COUNTER_SUFFIXES
_HISTOGRAM_SUFFIXES = _impl._HISTOGRAM_SUFFIXES
_GAUGE_SUFFIXES = _impl._GAUGE_SUFFIXES
_GAUGE_ALLOWLIST = _impl._GAUGE_ALLOWLIST

if __name__ == "__main__":
    sys.exit(main())
