"""BASELINE.md config 0: hello-world handler, no model — the pure
transport number (router + middleware chain + envelope, no device).

Prints one JSON line: req/s and p50/p99 latency through real sockets.
This is the framework-overhead floor under every other benchmark: a
`/infer` request can never be faster than `/hello`.

    python tools/bench_hello.py             # 8 clients x 2000 requests
    BENCH_CLIENTS=32 python tools/bench_hello.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    clients = int(os.environ.get("BENCH_CLIENTS", "8"))
    n_requests = int(os.environ.get("BENCH_REQUESTS", "2000"))
    os.environ.setdefault("LOG_LEVEL", "ERROR")
    os.environ.setdefault("HTTP_PORT", "18821")
    os.environ.setdefault("APP_NAME", "bench-hello")

    import gofr_tpu

    app = gofr_tpu.new()
    app.get("/hello", lambda ctx: "Hello World!")
    app.start()
    base = f"http://127.0.0.1:{app.http_port}"
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(base + "/hello", timeout=2)
                break
            except Exception:
                time.sleep(0.2)

        latencies: list[float] = []
        failures: list[str] = []
        lock = threading.Lock()
        per_client = max(1, n_requests // clients)

        def worker() -> None:
            local, bad = [], []
            for _ in range(per_client):
                try:
                    start = time.perf_counter()
                    with urllib.request.urlopen(base + "/hello", timeout=10) as r:
                        body = json.loads(r.read())
                    assert body == {"data": "Hello World!"}, body
                    local.append(time.perf_counter() - start)
                except Exception as exc:
                    bad.append(f"{type(exc).__name__}: {exc}")
            with lock:
                latencies.extend(local)
                failures.extend(bad)

        threads = [
            threading.Thread(target=worker, name=f"bench-hello-{i}")
            for i in range(clients)
        ]
        wall_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - wall_start
        if failures or not latencies:
            # a partial sample divides survivors by the full wall time —
            # a silently wrong number; fail loudly instead
            print(json.dumps({
                "metric": "hello_req_per_sec", "value": None,
                "failures": len(failures), "errors": failures[:5],
            }), flush=True)
            return 1
        latencies.sort()
        print(json.dumps({
            "metric": "hello_req_per_sec",
            "value": round(len(latencies) / wall, 1),
            "unit": "req/s",
            "clients": clients,
            "requests": len(latencies),
            "p50_ms": round(latencies[len(latencies) // 2] * 1e3, 3),
            "p99_ms": round(
                latencies[min(len(latencies) - 1, int(len(latencies) * 0.99))] * 1e3,
                3,
            ),
        }), flush=True)
        return 0
    finally:
        app.shutdown()


if __name__ == "__main__":
    sys.exit(main())
