#!/usr/bin/env python
"""Process supervisor CLI: keep a serving command alive across crashes.

    python tools/supervisor.py [options] -- <command> [args...]

Respawns the child when it exits, with bounded exponential backoff;
more than ``--max-restarts`` exits inside ``--crash-window`` seconds is
a CRASH LOOP — the supervisor stops respawning and exits 1 so the
orchestration layer above (systemd, k8s, an operator) sees the page
instead of a silently burning restart treadmill. SIGTERM/SIGINT forward
to the child and stop supervision (clean exit 0).

Pairs with the journal WAL: a replica run as

    JOURNAL_DIR=/var/lib/gofr/journal python tools/supervisor.py -- \\
        python examples/http-server/main.py

survives ``kill -9`` — the respawned process rehydrates its resumable
streams at boot and the fleet router walks it back into rotation
through the ``restarting`` probation path.
See docs/advanced-guide/fleet.md "Process-death recovery".
"""

import argparse
import signal
import sys
import time


def main() -> int:
    parser = argparse.ArgumentParser(
        description="restart-on-exit process supervisor with bounded "
        "backoff and a crash-loop verdict",
    )
    parser.add_argument("--backoff", type=float, default=0.5,
                        help="initial restart backoff seconds (default 0.5)")
    parser.add_argument("--backoff-max", type=float, default=10.0,
                        help="backoff ceiling seconds (default 10)")
    parser.add_argument("--crash-window", type=float, default=30.0,
                        help="crash-loop detection window seconds "
                        "(default 30)")
    parser.add_argument("--max-restarts", type=int, default=5,
                        help="exits tolerated inside the window before the "
                        "crash-loop verdict (default 5)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- command to supervise")
    args = parser.parse_args()
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (use: supervisor.py [options] -- cmd)")

    sys.path.insert(0, ".")
    from gofr_tpu.devtools.supervise import CRASH_LOOP, Supervisor

    class _StderrLogger:
        @staticmethod
        def _emit(fmt, *fmt_args):
            print(fmt % fmt_args, file=sys.stderr, flush=True)

        infof = warnf = errorf = _emit

    supervisor = Supervisor(
        command,
        backoff_s=args.backoff,
        backoff_max_s=args.backoff_max,
        crash_window_s=args.crash_window,
        max_restarts_in_window=args.max_restarts,
        logger=_StderrLogger(),
        stdout=None,  # inherit: the child's output is the operator's
        stderr=None,
    )

    def handle_signal(signum, _frame):
        supervisor.logger.infof(
            "supervisor: signal %s — stopping child", signum
        )
        supervisor.stop()
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, handle_signal)
    signal.signal(signal.SIGINT, handle_signal)
    supervisor.start()
    try:
        while supervisor.verdict is None:
            time.sleep(0.2)
    finally:
        if supervisor.verdict == CRASH_LOOP:
            code = supervisor.last_exit_code
            supervisor.logger.errorf(
                "supervisor: crash-loop verdict (last exit %s)", code
            )
            supervisor.stop()
            return 1
        supervisor.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
