#!/usr/bin/env python3
"""Cycle check over the UNION of lock-order graphs.

Two producers emit the same schema (``{"version": 1, "source": ...,
"nodes": [{"id"}], "edges": [{"from", "to", "site"}]}``):

- static: ``python tools/gofrlint.py --emit-lock-graph static.json ...``
  — acquisition edges the whole-program pass can PROVE from the source
  (including interprocedural ones: a call made under lock A to a
  function that may take B).
- runtime: the concurrency sanitizer's observed graph
  (``GOFR_SANITIZE_GRAPH=<file>`` under the test suite, or
  ``tools/fleetsim.py --emit-graph`` under fleet chaos load) — edges
  that actually happened in some interleaving.

Each alone has blind spots: the static graph can't see lock use behind
dynamic dispatch it can't resolve, the runtime graph only sees
interleavings that ran. A cycle in the MERGED graph — e.g. A→B proved
statically, B→A observed at runtime in a path the linter can't type —
is a deadlock neither tool finds alone, so CI fails on it.

Node identity is the lock CREATION SITE. Runtime labels carry absolute
paths; they are normalized to repo-relative here before the merge.
Self-loops after normalization are dropped: two instances of the same
class taken in sequence collapse to one site, and site granularity
cannot order instances (an address-ordered hierarchy would be the fix,
not a report here).

Usage::

    python tools/lockgraph_check.py static.json [runtime.json ...]

Exit 0 when the merged graph is acyclic, 1 when a cycle exists (each
cycle printed with the edges' provenance), 2 on unreadable input.
"""

from __future__ import annotations

import json
import sys

# path components that anchor a repo-relative spelling inside an
# absolute one — everything before the LAST occurrence is machine-local
_ROOTS = ("gofr_tpu", "tests", "tools", "bench.py")


def normalize(node: str) -> str:
    """``/home/ci/repo/gofr_tpu/x.py:12`` -> ``gofr_tpu/x.py:12``;
    repo-relative and synthetic (``rel::Class.attr``) ids unchanged."""
    if "::" in node:
        return node
    path, sep, line = node.rpartition(":")
    if not sep or not line.isdigit():
        path, line = node, ""
    parts = path.replace("\\", "/").split("/")
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] in _ROOTS:
            path = "/".join(parts[i:])
            break
    return f"{path}:{line}" if line else path


def load_graph(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "edges" not in doc:
        raise ValueError(f"{path}: not a lock-graph document")
    return doc


def merge(graphs: list[dict]) -> dict[str, dict[str, dict]]:
    """adjacency: from -> {to -> provenance edge dict}."""
    adj: dict[str, dict[str, dict]] = {}
    for doc in graphs:
        source = doc.get("source", "?")
        for edge in doc["edges"]:
            a = normalize(edge["from"])
            b = normalize(edge["to"])
            if a == b:
                continue  # site-granularity alias (see module docstring)
            info = dict(edge)
            info["source"] = source
            adj.setdefault(a, {}).setdefault(b, info)
            adj.setdefault(b, {})
    return adj


def find_cycles(adj: dict[str, dict[str, dict]]) -> list[list[str]]:
    """Tarjan SCCs; every SCC with more than one node (or a 2-cycle
    within it) is an ordering violation. Iterative — graph size is
    bounded by lock count, but recursion limits are not a failure mode
    a checker should have."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    scc.append(member)
                    if member == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
    return sccs


def main(argv: list[str] | None = None) -> int:
    args = (argv if argv is not None else sys.argv)[1:]
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    graphs = []
    for path in args:
        try:
            graphs.append(load_graph(path))
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"lockgraph_check: {exc}", file=sys.stderr)
            return 2
    adj = merge(graphs)
    n_edges = sum(len(v) for v in adj.values())
    cycles = find_cycles(adj)
    if not cycles:
        print(
            f"lockgraph_check: OK — {len(adj)} locks, {n_edges} ordered "
            f"edges across {len(graphs)} graph(s), no cycles"
        )
        return 0
    for scc in cycles:
        print(f"lockgraph_check: CYCLE among {len(scc)} lock(s):")
        members = set(scc)
        for a in scc:
            for b, info in sorted(adj.get(a, {}).items()):
                if b in members:
                    print(
                        f"  {a} -> {b}  [{info.get('source', '?')}"
                        f" @ {info.get('site', '?')}]"
                    )
    print(
        "lockgraph_check: a static∪runtime cycle is a deadlock neither "
        "tool proves alone — fix the acquisition order",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
