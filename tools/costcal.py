#!/usr/bin/env python3
"""costcal — fit the dispatch cost model's roofline coefficients.

The cost model (gofr_tpu/tpu/costmodel.py) predicts dispatch latency as
``max(flops/eff_flops, bytes/eff_bw) * 1e3 + overhead_ms`` with
per-device-kind *effective* coefficients shipped in the committed
``gofr_tpu/tpu/cost_profile.json``. This tool owns those numbers:

  fit     fit coefficients from one or more dispatch-records artifacts
          (the shape ``--synth`` writes: a header naming the device kind
          plus DispatchRecord dicts carrying flops/bytes per dispatch)
  check   CI smoke: refit from the committed r02-derived records and
          assert the committed profile row reproduces within tolerance
          (a drifted fit means someone edited one side only)
  synth   regenerate the committed ``hw/r02/dispatch_records.json``
          deterministically from the r02 bench summary (BENCH_r02.json
          kept no raw dispatch timeline, so the committed calibration
          window is derived: roofline-consistent dispatch durations for
          the r02 serving shape, seeded noise — provenance in-band)

Fit procedure (deterministic, no solver): each record is classified
compute- or bandwidth-bound by NOMINAL peaks (tpu/flops.py tables), then
ordinary least squares per class — ``ms`` against ``flops`` (or
``bytes``) — yields ``eff = 1e3 / slope`` and the shared ``overhead_ms``
from the record-weighted intercepts.

Usage:
  python tools/costcal.py --fit hw/r02/dispatch_records.json [more.json]
  python tools/costcal.py --check [--tolerance 0.1]
  python tools/costcal.py --synth hw/r02/dispatch_records.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
from typing import Any

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PROFILE = os.path.join(REPO, "gofr_tpu", "tpu", "cost_profile.json")
DEFAULT_RECORDS = os.path.join(REPO, "hw", "r02", "dispatch_records.json")

# -- r02 synthesis constants --------------------------------------------------
# BENCH_r02.json: model=small, prompt_len=48, clients=8 on a v5e-class
# chip. The "true" efficiencies the synthesized window encodes — chosen
# inside the published envelope (prefill compute-bound at ~0.35 of bf16
# peak, decode streaming at ~0.55 of HBM peak) and reproduced by --fit.
SYNTH_SEED = 20260807
SYNTH_DEVICE_KIND = "v5e"
SYNTH_EFF_FLOPS = 6.9e13   # 0.35 x 197 TFLOP/s
SYNTH_EFF_BW = 4.5e11      # 0.55 x 819 GB/s
SYNTH_OVERHEAD_MS = 0.35
SYNTH_N_PARAMS = 191_382_528  # transformer_param_count(SMALL)
SYNTH_WEIGHT_BYTES = 2 * SYNTH_N_PARAMS  # bf16 weights streamed per step


def _load_records(paths: list[str]) -> tuple[dict[str, Any], list[dict[str, Any]]]:
    header: dict[str, Any] = {}
    records: list[dict[str, Any]] = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as fh:
            artifact = json.load(fh)
        if isinstance(artifact, list):
            records.extend(artifact)
            continue
        if not header:
            header = {k: v for k, v in artifact.items() if k != "records"}
        records.extend(artifact.get("records") or [])
    return header, records


def _observed_ms(record: dict[str, Any]) -> float | None:
    if record.get("observed_ms") is not None:
        return float(record["observed_ms"])
    if record.get("duration_s") is not None:
        return float(record["duration_s"]) * 1e3
    return None


def _ols(points: list[tuple[float, float]]) -> tuple[float, float] | None:
    """Least-squares (slope, intercept) of y on x; None when degenerate."""
    n = len(points)
    if n < 2:
        return None
    sx = sum(x for x, _ in points)
    sy = sum(y for _, y in points)
    sxx = sum(x * x for x, _ in points)
    sxy = sum(x * y for x, y in points)
    denom = n * sxx - sx * sx
    if denom <= 0:
        return None
    slope = (n * sxy - sx * sy) / denom
    return slope, (sy - slope * sx) / n


def fit(paths: list[str]) -> dict[str, Any]:
    """Fit one profile row from dispatch-records artifacts."""
    from gofr_tpu.tpu.flops import device_peak_flops, device_peak_hbm_bw

    header, records = _load_records(paths)
    device_kind = str(header.get("device_kind") or "unknown")
    platform = str(header.get("platform") or "tpu")
    peak_flops = device_peak_flops(device_kind, platform)
    peak_bw = device_peak_hbm_bw(device_kind, platform)
    compute: list[tuple[float, float]] = []
    bandwidth: list[tuple[float, float]] = []
    skipped = 0
    for record in records:
        ms = _observed_ms(record)
        flops = float(record.get("flops") or 0.0)
        nbytes = float(record.get("bytes_accessed") or 0.0)
        if ms is None or ms <= 0 or (flops <= 0 and nbytes <= 0):
            skipped += 1
            continue
        # classify by NOMINAL roofline terms: which side of the roofline
        # this record's shape sits on is a property of the hardware
        # ratio, not of the efficiencies being fitted
        t_flops = flops / peak_flops if peak_flops > 0 else 0.0
        t_bw = nbytes / peak_bw if peak_bw > 0 else 0.0
        if t_flops >= t_bw:
            compute.append((flops, ms))
        else:
            bandwidth.append((nbytes, ms))
    row: dict[str, Any] = {
        "device_kind": device_kind,
        "platform": platform,
        "n_records": len(records) - skipped,
        "n_skipped": skipped,
        "n_compute_bound": len(compute),
        "n_bandwidth_bound": len(bandwidth),
    }
    intercepts: list[tuple[float, int]] = []
    for name, points, nominal in (
        ("eff_flops", compute, peak_flops),
        ("eff_bw", bandwidth, peak_bw),
    ):
        fitted = _ols(points)
        if fitted is None or fitted[0] <= 0:
            # too few (or colinear) records on this side of the roofline:
            # a labeled nominal-efficiency default, never a silent zero
            row[name] = nominal * 0.5
            row[f"{name}_source"] = "default"
            continue
        slope, intercept = fitted
        row[name] = 1e3 / slope
        row[f"{name}_source"] = "fit"
        intercepts.append((max(0.0, intercept), len(points)))
    total = sum(n for _, n in intercepts)
    row["overhead_ms"] = (
        sum(c * n for c, n in intercepts) / total if total else 0.0
    )
    return row


def check(profile_path: str, records_paths: list[str], tolerance: float) -> int:
    """Refit from the committed records and compare against the
    committed profile row for the same device kind. Returns exit code."""
    with open(profile_path, "r", encoding="utf-8") as fh:
        profile = json.load(fh)
    row = fit(records_paths)
    kind = row["device_kind"].lower()
    committed = None
    for needle, candidate in (profile.get("device_kinds") or {}).items():
        if needle.lower() in kind or kind in needle.lower():
            committed = candidate
            break
    if committed is None:
        print(f"costcal check: no committed row matches device kind {kind!r}")
        return 1
    failures = []
    for coeff in ("eff_flops", "eff_bw", "overhead_ms"):
        want = float(committed.get(coeff) or 0.0)
        got = float(row.get(coeff) or 0.0)
        scale = max(abs(want), 1e-12)
        rel = abs(got - want) / scale
        status = "ok" if rel <= tolerance else "DRIFT"
        print(
            f"costcal check: {kind} {coeff}: committed={want:.6g} "
            f"refit={got:.6g} rel_err={rel:.4f} [{status}]"
        )
        if rel > tolerance:
            failures.append(coeff)
    if failures:
        print(
            f"costcal check FAILED: {', '.join(failures)} drifted past "
            f"tolerance {tolerance} — refit with --fit and recommit "
            "cost_profile.json (or restore the records artifact)"
        )
        return 1
    print(
        f"costcal check ok: {row['n_records']} records reproduce the "
        f"committed {kind} coefficients within {tolerance:.0%}"
    )
    return 0


def synth(out_path: str) -> dict[str, Any]:
    """Regenerate the committed r02-derived calibration window: the r02
    serving shape (model=small, prompt 48 -> bucket 64, batch 8) priced
    by the synthesis coefficients, with seeded multiplicative noise."""
    rng = random.Random(SYNTH_SEED)
    records: list[dict[str, Any]] = []

    def price(flops: float, nbytes: float) -> float:
        roofline_s = max(flops / SYNTH_EFF_FLOPS, nbytes / SYNTH_EFF_BW)
        ms = roofline_s * 1e3 + SYNTH_OVERHEAD_MS
        return ms * rng.gauss(1.0, 0.03)

    # prefill dispatches: 2·N·tokens over the padded (bucket x batch)
    # shape; activations add a weight-stream-scale byte term (prefill is
    # firmly compute-bound for every bucket here)
    for bucket in (64, 128, 256):
        for batch in (1, 2, 4, 8):
            for _ in range(8):
                tokens = bucket * batch
                flops = 2.0 * SYNTH_N_PARAMS * tokens
                nbytes = SYNTH_WEIGHT_BYTES + 6_000.0 * tokens
                records.append({
                    "kind": "prefill",
                    "bucket": bucket,
                    "batch_size": batch,
                    "tokens": tokens,
                    "flops": flops,
                    "bytes_accessed": nbytes,
                    "observed_ms": round(price(flops, nbytes), 5),
                })
    # decode chunks: each scan step streams weights + the KV working
    # set once (bandwidth-bound — per-token flops are 2·N·batch)
    kv_bytes_per_slot = 2 * 8 * 4 * 128 * 2048  # layers*kv_heads*hd*seq, bf16
    for steps in (4, 8):
        for slots in (1, 2, 4, 8):
            for _ in range(8):
                flops = 2.0 * SYNTH_N_PARAMS * slots * steps
                nbytes = steps * (
                    SYNTH_WEIGHT_BYTES + slots * kv_bytes_per_slot
                )
                records.append({
                    "kind": "decode_chunk",
                    "bucket": 0,
                    "batch_size": slots,
                    "tokens": slots * steps,
                    "flops": flops,
                    "bytes_accessed": nbytes,
                    "observed_ms": round(price(flops, nbytes), 5),
                })
    artifact = {
        "schema": "gofr-costmodel-records/1",
        "device_kind": SYNTH_DEVICE_KIND,
        "platform": "tpu",
        "derived_from": (
            "BENCH_r02.json summary (model=small, prompt_len=48, "
            "clients=8) — r02 kept no raw dispatch timeline; durations "
            "are roofline-consistent with seeded noise "
            f"(tools/costcal.py --synth, seed {SYNTH_SEED})"
        ),
        "records": records,
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1)
        fh.write("\n")
    print(f"costcal synth: wrote {len(records)} records to {out_path}")
    return artifact


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--fit", nargs="+", metavar="RECORDS",
                        help="fit a profile row from records artifacts")
    parser.add_argument("--check", action="store_true",
                        help="CI smoke: committed records reproduce the "
                             "committed profile")
    parser.add_argument("--synth", metavar="OUT",
                        help="regenerate the r02-derived records artifact")
    parser.add_argument("--profile", default=DEFAULT_PROFILE)
    parser.add_argument("--records", nargs="+", default=[DEFAULT_RECORDS])
    parser.add_argument("--tolerance", type=float, default=0.1)
    args = parser.parse_args(argv)
    sys.path.insert(0, REPO)
    if args.synth:
        synth(args.synth)
        return 0
    if args.fit:
        print(json.dumps(fit(args.fit), indent=1))
        return 0
    if args.check:
        return check(args.profile, args.records, args.tolerance)
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
