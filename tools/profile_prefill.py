"""Prefill MFU profiler: where does the non-MXU time go?

VERDICT r03 item 3: flagship int8 prefill measured MFU 0.194 at bucket 64 /
batch 8 — one fifth of the v5e roofline — and no profile of the serving hot
path had ever been taken. This tool answers the question two ways:

1. **Shape grid**: times the runner's REAL prefill executable (the same
   ``_prefill`` the serving path dispatches) across bucket x batch shapes,
   reporting ms and MFU per shape. Prefill MFU rises with tokens-per-
   dispatch until the MXU saturates; the grid shows where.
2. **Ablations**: re-times the grid under variants that isolate a cost —
   ``bf16`` (no int8 dequant on the weight path), ``pallas`` / ``xla``
   attention — so the gap to roofline decomposes into named causes
   instead of guesses.

Optionally captures a jax.profiler trace (``--trace DIR``) of one hot
dispatch for TensorBoard's trace viewer (gofr_tpu/profiling.py wraps the
same API for live servers).

    python tools/profile_prefill.py                      # flagship grid
    python tools/profile_prefill.py --model small --platform cpu  # smoke
    python tools/profile_prefill.py --ablate             # + bf16/attn runs

Each config prints one JSON line; stderr carries a ranked summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _time_prefill(runner, bucket: int, batch: int, reps: int = 5) -> dict:
    """Times the runner's real prefill at [batch, bucket] two ways.

    ``seconds``: median wall time of one synchronized dispatch — what a
    single request experiences, INCLUDING the host<->device round trip
    (on the tunneled bench link that RTT is ~65-130 ms, and it is why the
    serving gauge's host-timed prefill MFU reads low).

    ``pipelined``: per-dispatch time of ``reps`` back-to-back dispatches
    synchronized once at the end — jax's async dispatch queues them so
    the link latency amortizes away; this is the DEVICE throughput
    number, the one comparable to the MXU roofline."""
    import jax
    import jax.numpy as jnp

    tokens = jnp.ones((batch, bucket), jnp.int32)
    lengths = jnp.full((batch,), bucket, jnp.int32)
    if getattr(runner, "_token_sharding", None) is not None:
        tokens = jax.device_put(tokens, runner._token_sharding)
        lengths = jax.device_put(lengths, runner._row_sharding)
    cache = runner._zero_cache(batch)
    runner._prefill(runner.params, tokens, cache, lengths)[1].block_until_ready()
    times = []
    for _ in range(reps):
        start = time.perf_counter()
        _, next_ids, _ = runner._prefill(runner.params, tokens, cache, lengths)
        next_ids.block_until_ready()
        times.append(time.perf_counter() - start)
    times.sort()
    start = time.perf_counter()
    for _ in range(reps):
        _, next_ids, _ = runner._prefill(runner.params, tokens, cache, lengths)
    next_ids.block_until_ready()
    pipelined = (time.perf_counter() - start) / reps
    return {"seconds": times[len(times) // 2], "best": times[0],
            "pipelined": pipelined}


def run_grid(model: str, quant: str, buckets, batches, attn: str | None,
             max_seq: int, trace_dir: str | None) -> list[dict]:
    import jax

    from gofr_tpu.tpu.device import _build_runner
    from gofr_tpu.tpu.flops import device_peak_flops, mfu

    dev = jax.devices()[0]
    # quant-aware: w8a8 measures against the MXU int8 peak (flops.py owns
    # the factor — the serving gauge uses the same call)
    peak = device_peak_flops(
        getattr(dev, "device_kind", dev.platform), dev.platform, quant=quant
    )
    label = f"{model}/{quant or 'bf16'}/{attn or 'auto'}"
    print(f"=== building {label} (buckets={buckets})", file=sys.stderr, flush=True)
    runner = _build_runner(
        model, quant, None, max(batches),
        buckets=tuple(sorted(set(buckets))), max_seq=max_seq, attn_impl=attn,
    )
    out = []
    eff_max = runner.cfg.max_seq
    for bucket in buckets:
        if bucket > eff_max:
            # the runner clamps its compiled buckets to the model's
            # max_seq; timing an unclamped shape would crash the grid
            print(f"skip bucket {bucket} > max_seq {eff_max}",
                  file=sys.stderr, flush=True)
            continue
        for batch in batches:
            t = _time_prefill(runner, bucket, batch)
            tokens = bucket * batch
            rec = {
                "config": label, "bucket": bucket, "batch": batch,
                "ms": round(t["seconds"] * 1e3, 2),
                "best_ms": round(t["best"] * 1e3, 2),
                "pipelined_ms": round(t["pipelined"] * 1e3, 2),
                "tokens": tokens,
                "mfu": round(mfu(runner.n_params, tokens, t["seconds"], peak), 4),
                "mfu_device": round(
                    mfu(runner.n_params, tokens, t["pipelined"], peak), 4
                ),
                "tok_per_sec": round(tokens / t["seconds"], 1),
            }
            out.append(rec)
            print(json.dumps(rec), flush=True)
    if trace_dir and out:
        # trace a shape that was actually measured, from one record
        bucket, batch = out[-1]["bucket"], out[-1]["batch"]
        print(f"=== tracing one [{batch}, {bucket}] dispatch -> {trace_dir}",
              file=sys.stderr)
        jax.profiler.start_trace(trace_dir)
        _time_prefill(runner, bucket, batch, reps=2)
        jax.profiler.stop_trace()
    return out


def summarize_trace(trace_dir: str, top: int = 15) -> list[dict]:
    """Aggregate device-plane op time from a captured .xplane.pb — the
    'where does the non-MXU time go' answer, printable without TensorBoard.
    Uses the ambient tensorflow's xplane proto (parse-only; no TF runtime)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E501 — env-provided

    paths = []
    for root, _, names in os.walk(trace_dir):
        paths.extend(os.path.join(root, n) for n in names if n.endswith(".xplane.pb"))
    if not paths:
        print(f"no .xplane.pb under {trace_dir}", file=sys.stderr)
        return []
    spaces = []
    for path in paths:
        space = xplane_pb2.XSpace()
        with open(path, "rb") as fh:
            space.ParseFromString(fh.read())
        spaces.append(space)
    # device planes carry the XLA op timeline; host planes carry
    # python/runtime noise. On a CPU smoke there is no device plane —
    # fall back to /host:CPU so the tool is testable without a chip.
    def is_device(name: str) -> bool:
        return "TPU" in name or "/device:" in name
    have_device = any(is_device(p.name) for s in spaces for p in s.planes)
    totals: dict[str, float] = {}
    plane_names = []
    for space in spaces:
        for plane in space.planes:
            if have_device and not is_device(plane.name):
                continue
            if not have_device and plane.name != "/host:CPU":
                continue
            plane_names.append(plane.name)
            meta = plane.event_metadata
            # TPU device planes nest timelines ('XLA Modules' events span
            # their constituent 'XLA Ops' events) — summing every line
            # would double-count, halving each op's reported share. Keep
            # only the op-level line when one exists; host planes (the CPU
            # smoke fallback) have parallel thread lines, not nested ones.
            lines = [ln for ln in plane.lines if ln.name == "XLA Ops"] or plane.lines
            for line in lines:
                for ev in line.events:
                    name = meta[ev.metadata_id].name if ev.metadata_id in meta else "?"
                    totals[name] = totals.get(name, 0.0) + ev.duration_ps / 1e9
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])
    total_ms = sum(totals.values())
    print(f"\n=== device op time ({', '.join(sorted(set(plane_names))) or 'no device plane'}; "
          f"total {total_ms:.1f} ms)", file=sys.stderr)
    out = []
    for name, ms in ranked[:top]:
        pct = 100.0 * ms / total_ms if total_ms else 0.0
        print(f"  {pct:5.1f}%  {ms:9.2f} ms  {name[:90]}", file=sys.stderr)
        out.append({"op": name, "ms": round(ms, 2), "pct": round(pct, 1)})
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=os.environ.get("BENCH_MODEL", "llama3-8b"))
    ap.add_argument("--quant", default="int8")
    ap.add_argument("--buckets", default="64,128,256,512")
    ap.add_argument("--batches", default="1,4,8,16")
    ap.add_argument("--max-seq", type=int, default=512)
    ap.add_argument("--ablate", action="store_true",
                    help="also run bf16 and explicit xla/pallas attention grids")
    ap.add_argument("--trace", default="", help="capture a profiler trace here")
    ap.add_argument("--platform", default="", help="pin jax platform (cpu smoke)")
    ap.add_argument("--summarize", default="",
                    help="just summarize an existing trace dir and exit")
    args = ap.parse_args()

    if args.summarize:
        # exit 1 on an empty/missing trace so automation can't mistake a
        # typo'd dir for a successful summary
        return 0 if summarize_trace(args.summarize) else 1

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/gofr_jax_cache")
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/gofr_jax_cache")
    except Exception:
        pass

    buckets = [int(b) for b in args.buckets.split(",")]
    batches = [int(b) for b in args.batches.split(",")]
    results = run_grid(args.model, args.quant, buckets, batches, None,
                       args.max_seq, args.trace or None)
    if args.trace:
        # best-effort: a missing tensorflow must not kill the ablation
        # grids below (the trace itself is still on disk for TensorBoard;
        # the explicit --summarize path fails loudly instead)
        try:
            summarize_trace(args.trace)
        except Exception as exc:  # missing tf, truncated .xplane.pb, ...
            print(f"trace summary skipped: {exc!r}", file=sys.stderr)
    if args.ablate and not results:
        # the main grid measured nothing: building more runners to skip
        # the same shapes would waste the whole ablation stage
        print("ablations skipped: the main grid measured nothing",
              file=sys.stderr)
    elif args.ablate:
        # quant ablations at the largest shape the main grid actually
        # MEASURED (its skip logic knows the model's effective max_seq;
        # re-filtering on args.max_seq alone would rebuild multi-GB
        # runners to measure nothing)
        top = [max(r["bucket"] for r in results)]
        for mode in ("", "w8a8"):
            if args.quant != mode:
                results += run_grid(args.model, mode, top,
                                    batches[-1:], None, args.max_seq, None)
        # attention impl: pallas flash vs xla at the largest shape
        for attn in ("xla", "pallas"):
            results += run_grid(args.model, args.quant, top,
                                batches[-1:], attn, args.max_seq, None)
    ranked = sorted(results, key=lambda r: -r["mfu_device"])
    print("\n=== MFU ranking (mfu_device = link-amortized; mfu = one synced"
          " dispatch incl. RTT)", file=sys.stderr)
    for r in ranked[:12]:
        print(
            f"  {r['config']:>24} b{r['bucket']:<4}x{r['batch']:<3}: "
            f"mfu_device {r['mfu_device']:.3f}  mfu {r['mfu']:.3f}  "
            f"{r['pipelined_ms']:8.2f} ms/dispatch",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
