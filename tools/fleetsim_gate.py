"""CI gate for the fleet-scale chaos simulation: compare a FLEETSIM
artifact (``tools/fleetsim.py``) against the committed
``fleetsim_baseline.json``.

Two classes of check, in the bench-gate tradition (gate the artifact,
always upload it, loose-first tolerances):

ABSOLUTE invariants — correctness under chaos, no tolerance:

- every verified seeded stream token-exact: ``duplicated_tokens == 0``
  and ``missing_tokens == 0`` and ``token_exact == verified``;
- ``resume.failures == 0`` — every mid-stream failover on a seeded
  stream spliced a continuation (100% resume success);
- ``shed.p9 == 0`` — the protected priority-9 cohort is never shed;
- the tier-9 tenant's error budget never exhausts: the artifact's
  per-tenant SLO lines must carry ``t-platinum`` with
  ``budget_remaining > 0`` against its 0.9995 availability target
  (under default chaos the protected cohort stays INSIDE its SLO,
  not merely un-shed — errors count too);
- ``pools_idle`` — every replica's paged-KV pool balanced back to idle
  (zero leaked blocks after wedges, drains, aborts, corrupt pulls);
- the hardening A/B holds: jittered probe spread strictly below the
  synchronized sweep's full-round burst, and the quota lease cache
  strictly below 1.0 redis syncs/request (a sync = the read + write
  pipeline pair, i.e. two real round trips);
- the scheduled stream-mangling chaos actually FIRED (error burst,
  slow-loris, disconnect each injected > 0 times) and at least one
  stream resume was exercised — a run whose faults missed the traffic
  would otherwise pass the resume/token-exactness invariants
  VACUOUSLY.

RELATIVE tolerances vs baseline (CI runners are noisy; these catch
structural regressions, not jitter — tighten as the trajectory
stabilizes):

- ``slo.ttft_p99_ms <= max(baseline * FLEETSIM_GATE_TTFT_FACTOR,
  FLEETSIM_GATE_TTFT_FLOOR_MS)`` (factor 4.0, floor 15000 — chaos-
  window p99 swings several-x on shared runners, and a lucky-fast
  baseline must not turn jitter into failures; the floor stays under
  the 20s request deadline);
- ``slo.errors <= max(baseline + 2, baseline * 3, 4)`` — transient
  non-shed failures must stay rare;
- ``slo.shed.rate <= max(baseline * FLEETSIM_GATE_SHED_FACTOR, 0.10)``
  (factor 3.0; the floor keeps the check ALIVE against a zero-shed
  baseline) — a shed-rate explosion means admission broke, not the
  trace;
- ``slo.breaker_flaps <= max(baseline * 3, baseline + 8)`` — flapping
  breakers mean the probation/cooldown machinery stopped damping.

Usage::

    python tools/fleetsim_gate.py FLEETSIM.json [fleetsim_baseline.json]

Exit 0 = pass, 1 = gate failure (each printed). Refreshing the
baseline is an explicit act: run ``tools/fleetsim.py`` with the CI
seed/env and commit the new baseline next to the change that moved it.
"""

from __future__ import annotations

import json
import os
import sys


def _num(d: dict, *path: str) -> float:
    cur: object = d
    for key in path:
        if not isinstance(cur, dict):
            return 0.0
        cur = cur.get(key)
    return float(cur) if isinstance(cur, (int, float)) else 0.0


def _absolute_failures(slo: dict, hardening: dict) -> list[str]:
    failures: list[str] = []
    streams = slo.get("streams") or {}
    if streams.get("duplicated_tokens") or streams.get("missing_tokens"):
        failures.append(
            "seeded streams lost/duplicated tokens: "
            f"{streams.get('missing_tokens')} missing, "
            f"{streams.get('duplicated_tokens')} duplicated"
        )
    if streams.get("token_exact") != streams.get("verified"):
        failures.append(
            f"only {streams.get('token_exact')}/{streams.get('verified')} "
            "verified streams were token-exact"
        )
    resume = slo.get("resume") or {}
    if resume.get("failures"):
        failures.append(
            f"{resume['failures']} stream resume(s) failed "
            f"(exhausted={resume.get('exhausted')}, "
            f"refused={resume.get('refused')}) — resume success must be 100%"
        )
    if _num(slo, "shed", "p9") > 0:
        failures.append(
            f"priority-9 requests were shed ({slo['shed']['p9']}) — "
            "the protected cohort must never shed"
        )
    if not slo.get("pools_idle"):
        failures.append(
            "replica pools did not converge to idle (leaked KV blocks "
            "or a replica never returned to serving)"
        )
    if hardening:
        spread = hardening.get("probe_spread") or {}
        before = _num(spread, "before", "max_probes_in_window")
        after = _num(spread, "after", "max_probes_in_window")
        if before and after >= before:
            failures.append(
                f"probe jitter stopped spreading fan-out: {after} probes "
                f"per window jittered vs {before} synchronized"
            )
        quota = hardening.get("quota") or {}
        if _num(quota, "after", "syncs_per_request") >= 1.0:
            failures.append(
                "quota lease cache is not cutting redis syncs "
                f"({_num(quota, 'after', 'syncs_per_request')}/request)"
            )
    return failures


def _tenant_budget_failures(slo: dict) -> list[str]:
    """The protected cohort's SLO, gated: the tier-9 tenant line must
    exist (its traffic share guarantees requests in every trace) and
    its availability budget must not exhaust under default chaos."""
    lines = slo.get("tenants")
    if not isinstance(lines, list) or not lines:
        return ["artifact carries no per-tenant SLO lines (slo.tenants)"]
    platinum = next(
        (row for row in lines if row.get("tenant") == "t-platinum"), None
    )
    if platinum is None:
        return ["no SLO line for the protected tenant 't-platinum' — "
                "the tier-9 cohort never made it into the artifact"]
    remaining = platinum.get("budget_remaining")
    if not isinstance(remaining, (int, float)) or remaining <= 0:
        return [
            "the protected tenant 't-platinum' exhausted its "
            f"availability budget (budget_remaining={remaining}, "
            f"availability={platinum.get('availability')} vs target "
            f"{platinum.get('target')}) — tier 9 must stay inside its "
            "SLO under default chaos"
        ]
    return []


def _chaos_fired_failures(artifact: dict, slo: dict) -> list[str]:
    """Anti-vacuity: the invariants above only mean something if the
    chaos they guard against actually intersected traffic."""
    failures: list[str] = []
    injected = (artifact.get("scenario") or {}).get("injected") or {}
    for mode in ("error_burst", "slow_loris", "disconnect_after"):
        if not injected.get(mode):
            failures.append(
                f"scheduled chaos mode '{mode}' never fired — the run's "
                "correctness invariants are vacuous for that fault "
                "(progress-gated scheduling should make this impossible "
                "unless the trace shrank too far)"
            )
    if not _num(slo, "resume", "resumed"):
        failures.append(
            "no stream resume was exercised (resume.resumed == 0) — "
            "'100% resume success' is vacuously true; the aimed "
            "disconnect burst must cut at least one live stream"
        )
    return failures


def _relative_failures(slo: dict, base_slo: dict) -> list[str]:
    failures: list[str] = []
    ttft_factor = float(os.environ.get("FLEETSIM_GATE_TTFT_FACTOR", "4.0"))
    ttft_floor = float(os.environ.get("FLEETSIM_GATE_TTFT_FLOOR_MS",
                                      "15000"))
    shed_factor = float(os.environ.get("FLEETSIM_GATE_SHED_FACTOR", "3.0"))
    p99, base_p99 = _num(slo, "ttft_p99_ms"), _num(base_slo, "ttft_p99_ms")
    # the floor mirrors the error check: chaos-window p99 on a shared
    # runner swings several-x run to run, and a LUCKY-fast baseline
    # must not turn ordinary jitter into a gate failure — the floor
    # sits under FLEET_DEADLINE_S (20s), so a fleet that makes clients
    # wait out their whole budget still fails
    allowed_p99 = max(base_p99 * ttft_factor, ttft_floor)
    if base_p99 and p99 > allowed_p99:
        failures.append(
            f"fleet p99 TTFT regression: {p99}ms > {allowed_p99:.1f}ms "
            f"(baseline {base_p99}ms * {ttft_factor}, floor "
            f"{ttft_floor:.0f}ms)"
        )
    errors, base_errors = _num(slo, "errors"), _num(base_slo, "errors")
    # floor of 4: a zero-error baseline must not turn two noisy client
    # timeouts on a loaded CI box into a gate failure
    allowed_errors = max(base_errors + 2, base_errors * 3, 4.0)
    if errors > allowed_errors:
        failures.append(
            f"non-shed error count blew up: {errors:.0f} > "
            f"{allowed_errors:.0f} (baseline {base_errors:.0f})"
        )
    rate, base_rate = _num(slo, "shed", "rate"), _num(base_slo, "shed", "rate")
    # floor of 0.10: a zero-shed baseline must not DISABLE the check —
    # a 50%-shed admission regression has to fail even when the
    # baseline never shed at all
    allowed_rate = max(base_rate * shed_factor, 0.10)
    if rate > allowed_rate:
        failures.append(
            f"shed rate regression: {rate} > {allowed_rate:.2f} "
            f"(baseline {base_rate} * {shed_factor}, floor 0.10)"
        )
    flaps = _num(slo, "breaker_flaps")
    base_flaps = _num(base_slo, "breaker_flaps")
    allowed_flaps = max(base_flaps * 3, base_flaps + 8)
    if flaps > allowed_flaps:
        failures.append(
            f"breaker flap count blew up: {flaps:.0f} > "
            f"{allowed_flaps:.0f} (baseline {base_flaps:.0f})"
        )
    return failures


def _process_kill_failures(artifact: dict, slo: dict) -> list[str]:
    """process_kill scenario (routers >= 2, a supervised subprocess
    replica): the run must PROVE process death was survivable, not just
    scheduled — kills fired, the supervisor respawned the victim, the
    reborn process rehydrated its WAL, and at least one client rode the
    router failover when the router-tier instance died."""
    if artifact.get("scenario_mode") != "process_kill":
        return []
    failures: list[str] = []
    block = artifact.get("process_kill") or {}
    if not block:
        return ["process_kill scenario produced no process_kill evidence "
                "block"]
    if _num(block, "replica_kills") < 1:
        failures.append(
            "no replica SIGKILL landed (replica_kills == 0) — the "
            "process-death invariants are vacuous"
        )
    if _num(block, "supervisor_restarts") < 1:
        failures.append(
            "the supervisor never respawned the SIGKILLed replica "
            "(supervisor_restarts == 0)"
        )
    rehydrated = block.get("victim_rehydrated")
    if rehydrated is None:
        failures.append(
            "the reborn victim's journal block was unreadable — WAL "
            "rehydration cannot be verified"
        )
    if artifact.get("routers", 1) >= 2:
        if _num(block, "router_kills") < 1:
            failures.append("the scheduled router kill never applied")
        if _num(slo, "router_failovers") < 1:
            failures.append(
                "no client ever failed over between routers "
                "(router_failovers == 0) — the no-single-point-of-"
                "failure invariant is vacuous"
            )
    return failures


def gate(artifact: dict, baseline: dict) -> list[str]:
    failures: list[str] = []
    if artifact.get("kind") != "FLEETSIM":
        return [f"not a FLEETSIM artifact (kind={artifact.get('kind')!r})"]
    if artifact.get("replicas", 0) < baseline.get("replicas", 0):
        failures.append(
            f"fleet shrank: {artifact.get('replicas')} replicas < "
            f"baseline {baseline.get('replicas')} — scale trace length, "
            "not replica count"
        )
    slo = artifact.get("slo") or {}
    failures += _absolute_failures(slo, artifact.get("hardening") or {})
    failures += _tenant_budget_failures(slo)
    failures += _chaos_fired_failures(artifact, slo)
    failures += _process_kill_failures(artifact, slo)
    failures += _relative_failures(slo, baseline.get("slo") or {})
    return failures


def main(argv: list[str]) -> int:
    if len(argv) < 2:
        print(__doc__)
        return 2
    base_path = argv[2] if len(argv) > 2 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "fleetsim_baseline.json",
    )
    with open(argv[1]) as f:
        artifact = json.load(f)
    with open(base_path) as f:
        baseline = json.load(f)
    failures = gate(artifact, baseline)
    slo = artifact.get("slo") or {}
    print(
        f"fleetsim gate: seed={artifact.get('seed')} "
        f"replicas={artifact.get('replicas')} "
        f"requests={slo.get('requests')} ok={slo.get('ok')} "
        f"errors={slo.get('errors')} p99_ttft={slo.get('ttft_p99_ms')}ms "
        f"shed_rate={_num(slo, 'shed', 'rate')} "
        f"resume={slo.get('resume')} pools_idle={slo.get('pools_idle')}"
    )
    if failures:
        for failure in failures:
            print(f"FAIL: {failure}")
        return 1
    print("fleetsim gate: OK (within tolerance of fleetsim_baseline.json)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
