"""Fleet-scale chaos simulation entrypoint: boot N (default 16) echo
host-mesh replicas behind the real fleet router, drive a seeded
trace (session reuse, Zipf tenant skew, diurnal/burst phases,
priority mix, streaming + mid-stream-abort clients) while a scenario
schedule injects overlapping faults, then emit the ``FLEETSIM`` JSON
artifact with fleet-level SLOs.

Usage::

    python tools/fleetsim.py [--replicas 16] [--seed 20260803]
        [--requests 240] [--out FLEETSIM.json] [--no-hardening]
        [--replay capture.json] [--capture-out capture.json]

``--replay`` drives a ``TRACE_CAPTURE`` artifact (from
``tools/trace_capture.py`` or a prior ``--capture-out``) through the
harness instead of a synthetic trace — captured production traffic
reruns deterministically under the same absolute SLO gate.
``--capture-out`` scrapes THIS run's served traffic into such an
artifact before teardown (the CI round trip chains the two).

The artifact prints on stdout (and writes to ``--out``). Gate it with
``python tools/fleetsim_gate.py FLEETSIM.json fleetsim_baseline.json``.

REPLAYING A FAILING CI RUN: the artifact records its seed — run
``python tools/fleetsim.py --seed <that seed>`` locally and the trace
AND fault schedule reproduce byte-identically (the ``trace.digest`` /
``scenario.digest`` fields are the witness; thread interleaving is the
only nondeterminism left).

CI keeps wall time bounded by scaling ``--requests`` (trace length),
NEVER ``--replicas`` — fleet-scale behavior (probe fan-out, quota hot
keys, router lock contention) is the entire point of the harness.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replicas", type=int, default=16)
    parser.add_argument("--prefill", type=int, default=2,
                        help="replicas advertising the prefill role")
    parser.add_argument("--seed", type=int, default=20260803)
    parser.add_argument("--requests", type=int, default=240)
    parser.add_argument("--base-rps", type=float, default=12.0)
    parser.add_argument("--quota-rps", type=float, default=4.0)
    parser.add_argument("--workers", type=int, default=12)
    parser.add_argument("--routers", type=int, default=1,
                        help="router instances fronting the fleet (>= 2 "
                        "proves the no-single-point-of-failure story: "
                        "clients fail over when one dies)")
    parser.add_argument("--scenario", default="default",
                        choices=("default", "process_kill"),
                        help="process_kill layers SIGKILLed subprocess "
                        "replicas (supervisor respawn + WAL rehydration) "
                        "and a router-tier death onto the default chaos")
    parser.add_argument("--out", default="")
    parser.add_argument("--no-hardening", action="store_true",
                        help="skip the before/after micro-measures")
    parser.add_argument("--replay", default="",
                        help="TRACE_CAPTURE file to drive instead of a "
                        "synthetic trace (see tools/trace_capture.py)")
    parser.add_argument("--capture-out", default="",
                        help="write this run's served traffic as a "
                        "TRACE_CAPTURE file before teardown")
    parser.add_argument("--emit-graph", default="",
                        help="write the sanitizer's OBSERVED lock-order "
                        "graph as JSON (requires GOFR_SANITIZE=1; same "
                        "schema as gofrlint --emit-lock-graph — union "
                        "the two with tools/lockgraph_check.py)")
    args = parser.parse_args(argv[1:])

    # sanitizer-armed when the environment asks (the CI fleet-sim job
    # sets GOFR_SANITIZE=1): rebind threading.Lock/RLock to the
    # instrumented wrappers BEFORE the fleet builds its locks, so a
    # lock-order cycle anywhere in the router/replica/admission path
    # under real 16-replica load fails the run, not just the unit tier
    from gofr_tpu.devtools import sanitizer

    if sanitizer.enabled():
        sanitizer.install()

    from gofr_tpu.devtools.fleetsim import FleetSim, TraceSpec

    replay = None
    if args.replay:
        from gofr_tpu.devtools.trace_capture import load_capture

        replay = load_capture(args.replay)
        print(
            f"fleetsim: replaying {replay['requests']} captured events "
            f"(digest {replay['digest'][:16]}…)",
            file=sys.stderr, flush=True,
        )

    t0 = time.monotonic()
    sim = FleetSim(
        n_replicas=args.replicas,
        n_prefill=args.prefill,
        seed=args.seed,
        spec=TraceSpec(
            requests=args.requests, base_rps=args.base_rps, seed=args.seed,
        ),
        quota_rps=args.quota_rps,
        workers=args.workers,
        n_routers=args.routers,
        scenario=args.scenario,
        measure_hardening=not args.no_hardening,
        progress=lambda msg: print(msg, file=sys.stderr, flush=True),
        replay=replay,
        capture_out=args.capture_out,
    )
    artifact = sim.run()
    artifact["wall_s"] = round(time.monotonic() - t0, 1)
    artifact["generated_at"] = time.time()  # gofrlint: wall-clock — artifact timestamp
    blob = json.dumps(artifact, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    slo = artifact["slo"]
    print(
        f"fleetsim: {slo['requests']} requests, ok={slo['ok']} "
        f"shed={slo['shed']['total']} errors={slo['errors']} "
        f"p99_ttft={slo['ttft_p99_ms']}ms resume={slo['resume']} "
        f"pools_idle={slo['pools_idle']} wall={artifact['wall_s']}s",
        file=sys.stderr,
    )
    if sanitizer.enabled():
        if args.emit_graph:
            graph = sanitizer.export_graph(args.emit_graph)
            print(
                f"fleetsim: observed lock graph: "
                f"{len(graph['nodes'])} locks, {len(graph['edges'])} "
                f"edges -> {args.emit_graph}",
                file=sys.stderr,
            )
        report = sanitizer.drain()
        for finding in report["violations"]:
            print(f"fleetsim: SANITIZER: {finding.get('summary')}",
                  file=sys.stderr)
        if report["violations"]:
            return 1
    elif args.emit_graph:
        print("fleetsim: --emit-graph needs GOFR_SANITIZE=1 (no graph "
              "was recorded)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
