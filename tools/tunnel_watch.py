"""Wait for the device tunnel to recover, then run the round's hardware
agenda unattended: the decode sweep (VERDICT r04 item 2), the prefill
profile grid + trace (item 3), and one flagship bench with the 64,512
bucket ladder (item 7). Everything logs under /tmp/r04_hw/.

    python tools/tunnel_watch.py        # blocks; safe to background

The probe runs in a killable subprocess (a wedged tunnel hangs
jax.devices() forever in-process). Each stage runs even if the previous
failed — partial hardware data beats none — and a stage that itself hangs
is killed at its timeout so the watcher always reaches the later stages.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = "/tmp/r04_hw"


def log(msg: str) -> None:
    print(f"[watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe(timeout: float = 60.0) -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_COMPILATION_CACHE_DIR": "/tmp/gofr_jax_cache"},
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_stage(name: str, cmd: list[str], timeout: float,
              env: dict | None = None) -> None:
    log(f"stage {name}: {' '.join(cmd)}")
    with open(os.path.join(OUT, f"{name}.log"), "w") as fh:
        try:
            proc = subprocess.run(
                cmd, stdout=fh, stderr=subprocess.STDOUT, timeout=timeout,
                cwd=REPO, env=env,
            )
            log(f"stage {name}: rc={proc.returncode}")
        except subprocess.TimeoutExpired:
            log(f"stage {name}: TIMEOUT after {timeout:.0f}s")


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    poll = float(os.environ.get("WATCH_POLL_SECONDS", "120"))
    deadline = time.monotonic() + float(os.environ.get("WATCH_MAX_SECONDS", "28800"))
    n = 0
    while time.monotonic() < deadline:
        n += 1
        if probe():
            log(f"tunnel ALIVE after {n} probes — starting hardware agenda")
            break
        log(f"probe {n}: tunnel wedged; sleeping {poll:.0f}s")
        time.sleep(poll)
    else:
        log("gave up: tunnel never recovered inside the watch window")
        with open(os.path.join(OUT, "verdict.json"), "w") as fh:
            json.dump({"tunnel": "wedged-all-round", "probes": n}, fh)
        return 1

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/gofr_jax_cache")

    # 1. decode sweep around the measured winner (bench JSON lines land in
    #    the stage log; ranking at the end)
    run_stage(
        "sweep",
        [sys.executable, "tools/bench_sweep.py",
         "base8", "depth2", "depth4", "chunk16", "chunk32", "chunk16-depth4",
         "slots16-chunk16"],
        timeout=3.5 * 3600,
    )
    # 2. prefill MFU grid + ablations + device trace
    run_stage(
        "profile",
        [sys.executable, "tools/profile_prefill.py", "--ablate",
         "--trace", os.path.join(OUT, "prefill_trace")],
        timeout=1.5 * 3600,
    )
    # 3. flagship bench with the bucket ladder (per-bucket compile seconds
    #    land in boot_stages)
    run_stage(
        "ladder", [sys.executable, "bench.py"], timeout=1800,
        env={**os.environ, "MODEL_BUCKETS": "64,512", "BENCH_PROMPT_LEN": "48"},
    )
    log("hardware agenda complete — results under " + OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main())
