"""Wait for the device tunnel to recover, then run the round's hardware
agenda unattended — and COMMIT every artifact into the repo as it lands
(r04 verdict Weak #1/#5: hardware evidence under /tmp evaporates between
rounds; a wedge during the driver window must never again leave the repo
number-less).

    python tools/tunnel_watch.py        # blocks; safe to background

Stages (each runs even if the previous failed; each is killed at its
timeout so later stages always get their chance):
  0. bootsmoke — real-TPU pallas flash kernel validation (the r04 lse
     tiling fix has never run on hardware; nothing else runs until this
     writes its verdict).
  1. sweep    — decode MBU grid (depth x chunk x slots).
  2. profile  — prefill MFU grid + ablations + device trace.
  3. ladder   — flagship bench, MODEL_BUCKETS=64,512.
  4. bert     — BASELINE config-2 encoder bench.

After every stage the log + any emitted JSON metric lines are committed
under hw/r05/ (git retry loop: the builder may be committing too).
Every stage also runs with POSTMORTEM_DIR pointed at hw/r05/, so a
server that wedges mid-stage writes its black-box bundle (engine state
history, dispatch timeline, flight records, timebase snapshots, thread
stacks — see gofr_tpu/postmortem.py) straight into the committed
evidence tree: the wedge explains ITSELF even when the stage is
SIGKILLed moments later.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "hw", "r05")


def log(msg: str) -> None:
    print(f"[watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe(timeout: float = 60.0) -> str:
    """'alive' | 'wedged' (probe hung: the tunnel failure mode) |
    'broken' (fast non-zero exit: NOT a tunnel problem — a broken jax
    install must abort the watch, not burn the window as a fake wedge)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_COMPILATION_CACHE_DIR": "/tmp/gofr_jax_cache"},
        )
    except subprocess.TimeoutExpired:
        return "wedged"
    if proc.returncode == 0:
        return "alive"
    log("probe failed FAST (environment, not tunnel): "
        + "\n".join(proc.stderr.strip().splitlines()[-3:]))
    return "broken"


def commit(msg: str) -> None:
    """Commit hw/r05 artifacts; retry around the builder's own commits.
    Artifact-only commits (no product code), so no verification gates."""
    for attempt in range(5):
        try:
            dirty = subprocess.run(
                ["git", "-C", REPO, "status", "--porcelain", "--", "hw"],
                capture_output=True, text=True, timeout=60,
            )
            if dirty.returncode == 0 and not dirty.stdout.strip():
                return  # nothing new under hw/ — not a failure
            subprocess.run(["git", "-C", REPO, "add", "hw"], check=True,
                           capture_output=True, timeout=60)
            r = subprocess.run(
                ["git", "-C", REPO, "commit",
                 "-m", msg + "\n\nNo-Verification-Needed: hardware data artifacts only",
                 "--", "hw"],
                capture_output=True, text=True, timeout=60,
            )
            # pathspec no-op wording differs from plain no-op wording
            if r.returncode == 0 or "no changes added" in r.stdout + r.stderr \
                    or "nothing to commit" in r.stdout + r.stderr:
                return
        except (subprocess.SubprocessError, OSError) as exc:
            log(f"commit attempt {attempt}: {exc}")
        time.sleep(3 + attempt * 5)
    log(f"giving up committing ({msg}) — artifacts remain on disk under hw/r05")


def harvest(name: str) -> None:
    """Pull JSON metric/verdict lines out of a stage log into their own
    artifact files so the numbers are greppable without log spelunking."""
    path = os.path.join(OUT, f"{name}.log")
    if not os.path.exists(path):
        return
    rows = []
    with open(path, errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if any(k in obj for k in ("metric", "ok", "config")):
                    rows.append(obj)
    if rows:
        ts = time.strftime("%Y%m%dT%H%M%S")
        with open(os.path.join(OUT, f"{name}_results_{ts}.json"), "w") as fh:
            json.dump(rows, fh, indent=1)


def run_stage(name: str, cmd: list[str], timeout: float,
              env: dict | None = None) -> int:
    """Run one stage in its OWN process group: a timeout must kill the
    whole tree (a sweep's in-flight bench.py grandchild would otherwise
    survive the kill, keep the exclusive device runtime, and starve every
    later stage)."""
    import signal

    log(f"stage {name}: {' '.join(cmd)}")
    rc = -1
    with open(os.path.join(OUT, f"{name}.log"), "w") as fh:
        proc = subprocess.Popen(
            cmd, stdout=fh, stderr=subprocess.STDOUT, cwd=REPO, env=env,
            start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=timeout)
            log(f"stage {name}: rc={rc}")
        except subprocess.TimeoutExpired:
            log(f"stage {name}: TIMEOUT after {timeout:.0f}s — killing group")
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                log(f"stage {name}: unreaped after SIGKILL; continuing")
    harvest(name)
    commit(f"Hardware artifacts: {name} stage (r05 watch)")
    return rc


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    poll = float(os.environ.get("WATCH_POLL_SECONDS", "120"))
    deadline = time.monotonic() + float(os.environ.get("WATCH_MAX_SECONDS", "28800"))
    probes: list[dict] = []
    n = broken = 0
    while time.monotonic() < deadline:
        n += 1
        state = probe()
        probes.append({"ts": time.strftime("%H:%M:%S"), "state": state})
        if state == "alive":
            log(f"tunnel ALIVE after {n} probes — starting hardware agenda")
            break
        if state == "broken":
            broken += 1
            if broken >= 3:  # consistent fast failure = config, not link
                log("aborting: probe fails instantly — fix the environment")
                _write_verdict("environment-broken", n, probes)
                return 2
        else:
            broken = 0
        if n % 15 == 0:  # the wedge record itself must survive in-repo
            _write_verdict("still-wedged", n, probes)
        log(f"probe {n}: tunnel {state}; sleeping {poll:.0f}s")
        time.sleep(poll)
    else:
        log("gave up: tunnel never recovered inside the watch window")
        _write_verdict("wedged-all-watch", n, probes)
        return 1

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/gofr_jax_cache")
    # black-box bundles land directly in the committed evidence tree:
    # every stage subprocess inherits this, and the post-stage commit
    # sweeps hw/ — a wedged stage leaves its own forensics behind
    os.environ.setdefault("POSTMORTEM_DIR", OUT)

    # hard stop for the whole agenda (epoch seconds): the driver's own
    # end-of-round bench needs the chip — a watcher still holding it past
    # this point would wedge the round's ONE driver artifact.
    try:
        abs_deadline = float(os.environ.get("WATCH_ABS_DEADLINE", "0"))
    except ValueError:
        log("WATCH_ABS_DEADLINE is not epoch seconds — using now+6h")
        abs_deadline = 0.0
    # gofrlint: wall-clock — WATCH_ABS_DEADLINE's contract is epoch seconds
    abs_deadline = abs_deadline or (time.time() + 6 * 3600)

    def remaining() -> float:
        # gofrlint: wall-clock — epoch-seconds deadline contract
        return abs_deadline - time.time()

    # 0. real-TPU pallas kernel validation — cheap, and gates nothing:
    #    even a failure here is the round's most valuable hardware fact
    smoke_rc = run_stage(
        "bootsmoke", [sys.executable, "tools/boot_smoke.py"],
        timeout=min(900, max(remaining(), 60)),
    )
    log(f"bootsmoke verdict: rc={smoke_rc} (0 = kernels good on real lowering)")
    # 1. decode sweep around the measured winner
    if remaining() > 2700:
        run_stage(
            "sweep",
            [sys.executable, "tools/bench_sweep.py",
             "base8", "depth2", "depth4", "chunk16", "chunk32",
             "chunk16-depth4", "slots16-chunk16"],
            timeout=min(4.0 * 3600, remaining() - 900),
        )
    # 2. prefill MFU grid + ablations + device trace
    if remaining() > 1200:
        run_stage(
            "profile",
            [sys.executable, "tools/profile_prefill.py", "--ablate",
             # trace dumps are hundreds of MB of binary protos — keep them
             # OUT of the auto-committed hw/ tree; the stage log records
             # the path for manual inspection within the session
             "--trace", "/tmp/r05_prefill_trace"],
            timeout=min(1.5 * 3600, remaining() - 600),
        )
    # 3. flagship bench with the bucket ladder
    if remaining() > 1320:
        run_stage(
            "ladder", [sys.executable, "bench.py"],
            timeout=min(1800, remaining() - 720),
            env={**os.environ, "MODEL_BUCKETS": "64,512",
                 "BENCH_PROMPT_LEN": "48"},
        )
    # 4. BASELINE config 2: encoder embeddings through the batcher
    if remaining() > 600:
        run_stage(
            "bert", [sys.executable, "bench.py"],
            timeout=min(900, remaining() - 120),
            env={**os.environ, "BENCH_MODEL": "bert-base",
                 "BENCH_PROMPT_LEN": "32", "BENCH_REQUESTS": "64"},
        )
    log("hardware agenda complete — results under " + OUT)
    return 0


def _write_verdict(state: str, n: int, probes: list[dict]) -> None:
    with open(os.path.join(OUT, "verdict.json"), "w") as fh:
        json.dump({"tunnel": state, "probes": n,
                   "history_tail": probes[-30:]}, fh, indent=1)
    commit(f"Hardware watch: tunnel {state} after {n} probes (r05)")


if __name__ == "__main__":
    sys.exit(main())
