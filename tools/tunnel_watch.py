"""Wait for the device tunnel to recover, then run the round's hardware
agenda unattended: the decode sweep (VERDICT r04 item 2), the prefill
profile grid + trace (item 3), and one flagship bench with the 64,512
bucket ladder (item 7). Everything logs under /tmp/r04_hw/.

    python tools/tunnel_watch.py        # blocks; safe to background

The probe runs in a killable subprocess (a wedged tunnel hangs
jax.devices() forever in-process). Each stage runs even if the previous
failed — partial hardware data beats none — and a stage that itself hangs
is killed at its timeout so the watcher always reaches the later stages.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = "/tmp/r04_hw"


def log(msg: str) -> None:
    print(f"[watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe(timeout: float = 60.0) -> str:
    """'alive' | 'wedged' (probe hung: the tunnel failure mode) |
    'broken' (fast non-zero exit: NOT a tunnel problem — a broken jax
    install must abort the watch, not burn the window as a fake wedge)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_COMPILATION_CACHE_DIR": "/tmp/gofr_jax_cache"},
        )
    except subprocess.TimeoutExpired:
        return "wedged"
    if proc.returncode == 0:
        return "alive"
    log("probe failed FAST (environment, not tunnel): "
        + "\n".join(proc.stderr.strip().splitlines()[-3:]))
    return "broken"


def run_stage(name: str, cmd: list[str], timeout: float,
              env: dict | None = None) -> None:
    """Run one stage in its OWN process group: a timeout must kill the
    whole tree (a sweep's in-flight bench.py grandchild would otherwise
    survive the kill, keep the exclusive device runtime, and starve every
    later stage)."""
    import signal

    log(f"stage {name}: {' '.join(cmd)}")
    with open(os.path.join(OUT, f"{name}.log"), "w") as fh:
        proc = subprocess.Popen(
            cmd, stdout=fh, stderr=subprocess.STDOUT, cwd=REPO, env=env,
            start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=timeout)
            log(f"stage {name}: rc={rc}")
        except subprocess.TimeoutExpired:
            log(f"stage {name}: TIMEOUT after {timeout:.0f}s — killing group")
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                # unreapable (e.g. stuck in device I/O) — log and move on;
                # later stages must still get their chance
                log(f"stage {name}: unreaped after SIGKILL; continuing")


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    poll = float(os.environ.get("WATCH_POLL_SECONDS", "120"))
    deadline = time.monotonic() + float(os.environ.get("WATCH_MAX_SECONDS", "28800"))
    n = broken = 0
    while time.monotonic() < deadline:
        n += 1
        state = probe()
        if state == "alive":
            log(f"tunnel ALIVE after {n} probes — starting hardware agenda")
            break
        if state == "broken":
            broken += 1
            if broken >= 3:  # consistent fast failure = config, not link
                log("aborting: probe fails instantly — fix the environment")
                with open(os.path.join(OUT, "verdict.json"), "w") as fh:
                    json.dump({"tunnel": "environment-broken", "probes": n}, fh)
                return 2
        else:
            broken = 0
        log(f"probe {n}: tunnel {state}; sleeping {poll:.0f}s")
        time.sleep(poll)
    else:
        log("gave up: tunnel never recovered inside the watch window")
        with open(os.path.join(OUT, "verdict.json"), "w") as fh:
            json.dump({"tunnel": "wedged-all-round", "probes": n}, fh)
        return 1

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/gofr_jax_cache")

    # hard stop for the whole agenda (epoch seconds): the driver's own
    # end-of-round bench needs the chip — a watcher still holding it past
    # this point would wedge the round's ONE driver artifact. Stages are
    # skipped (not truncated) once past the deadline; a skipped stage's
    # absence in /tmp/r04_hw is the signal it never fit.
    try:
        abs_deadline = float(os.environ.get("WATCH_ABS_DEADLINE", "0"))
    except ValueError:
        log("WATCH_ABS_DEADLINE is not epoch seconds — using now+6h")
        abs_deadline = 0.0
    abs_deadline = abs_deadline or (time.time() + 6 * 3600)

    def remaining() -> float:
        return abs_deadline - time.time()

    # 1. decode sweep around the measured winner (bench JSON lines land in
    #    the stage log; ranking at the end)
    # gate at one full worst-case config (1800s) + margin: launching a
    # sweep that cannot finish even its first config burns deadline the
    # profile/ladder stages could have used
    if remaining() > 2700:
        run_stage(
            "sweep",
            [sys.executable, "tools/bench_sweep.py",
             "base8", "depth2", "depth4", "chunk16", "chunk32",
             "chunk16-depth4", "slots16-chunk16"],
            # 7 configs x up to 1800s each inside bench_sweep, but never
            # past the agenda deadline
            timeout=min(4.0 * 3600, remaining() - 900),
        )
    # 2. prefill MFU grid + ablations + device trace
    if remaining() > 1200:
        run_stage(
            "profile",
            [sys.executable, "tools/profile_prefill.py", "--ablate",
             "--trace", os.path.join(OUT, "prefill_trace")],
            timeout=min(1.5 * 3600, remaining() - 600),
        )
    # 3. flagship bench with the bucket ladder (per-bucket compile seconds
    #    land in boot_stages)
    if remaining() > 1320:
        run_stage(
            "ladder", [sys.executable, "bench.py"],
            # keep a kill+reap margin inside the deadline: the chip must
            # be free when the driver's own bench wants it
            timeout=min(1800, remaining() - 720),
            env={**os.environ, "MODEL_BUCKETS": "64,512",
                 "BENCH_PROMPT_LEN": "48"},
        )
    # 4. BASELINE config 2: encoder embeddings through the batcher on the
    #    real chip (bert-base; cheap boot, short run)
    if remaining() > 600:
        run_stage(
            "bert", [sys.executable, "bench.py"],
            timeout=min(900, remaining() - 120),
            env={**os.environ, "BENCH_MODEL": "bert-base",
                 "BENCH_PROMPT_LEN": "32", "BENCH_REQUESTS": "64"},
        )
    log("hardware agenda complete — results under " + OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main())
