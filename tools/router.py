"""Fleet front-door entrypoint: a thin router process fronting N engine
replicas (ROADMAP item 3, docs/advanced-guide/fleet.md).

    FLEET_REPLICAS=http://10.0.0.1:8000,http://10.0.0.2:8000 \\
    HTTP_PORT=7000 python tools/router.py

The process is a plain gofr app — same middleware stack, ``/metrics``,
admin surface, graceful SIGTERM drain — whose serving routes forward to
the healthiest replica: readiness-aware rotation with probation,
prefix-affinity routing (a conversation returns to the replica holding
its paged-KV blocks), per-replica circuit breakers, bounded retries
under a per-request deadline budget, per-tenant token-bucket quotas
(fleet-wide when REDIS_HOST is set), and 429 + Retry-After load
shedding instead of unbounded queueing. ``GET /admin/fleet`` shows
every decision. All knobs: the ``FLEET_*`` keys in
``gofr_tpu/config.py``.

No model boots here: leave ``MODEL_NAME``/``TPU_ENABLED`` unset — the
router process needs neither jax nor a device.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import gofr_tpu
    from gofr_tpu.fleet import wire_fleet

    app = gofr_tpu.new()
    if app.container.tpu is not None:
        app.logger.errorf(
            "router process booted a TPU datasource — unset MODEL_NAME/"
            "TPU_ENABLED; a front door must stay device-free"
        )
        return 2
    try:
        wire_fleet(app)
    except ValueError as exc:
        app.logger.errorf("fleet wiring failed: %s", exc)
        return 2
    # SIGTERM → App.run's handler → shutdown() → fleet.drain() finishes
    # in-flight requests before the listener stops
    app.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
